"""graftcontract: whole-program stringly-typed contract drift analysis
(design.md §23) gates itself.

Every contract family gets a positive (drifting) and a negative (clean)
snippet; the package-level closure proofs pin the PR-19 RETRYABLE
reason set and the PR-17 POLICY verdict keys closed (producer set ==
consumer set); and the seeded-drift self-test holds both ends — the
sighted gate exits 0, either ``DASK_ML_TPU_CONTRACT_INJECT`` drift
exits 1, a typo'd mode exits 2 (a drift detector that cannot fail can
never gate)."""

import json
import os
import textwrap

import pytest

from dask_ml_tpu.analysis import lint_paths, lint_source, main
from dask_ml_tpu.analysis import baseline as bl
from dask_ml_tpu.analysis import cache as lint_cache
from dask_ml_tpu.analysis import contracts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dask_ml_tpu")
CONTRACT_BASELINE = os.path.join(REPO, "tools", "contract_baseline.json")

CONTRACT_RULES = (
    "contract-orphan-producer",
    "contract-dead-consumer",
    "contract-roster-drift",
    "contract-baseline-drift",
    "contract-undocumented-metric",
)
SEL = ",".join(CONTRACT_RULES)


# a path under a root that does not exist: find_api_md's walk-up must
# not escape into the REAL repo's docs/ and tools/ (lint_source's
# default "<string>" resolves against cwd, which during pytest IS the
# repo — snippets would silently check against the live contracts)
SNIPPET = os.path.join(os.sep, "graftcontract-snippet", "pkg", "mod.py")


def lint(src):
    return lint_source(textwrap.dedent(src), path=SNIPPET,
                       select=CONTRACT_RULES)


def active(findings):
    return [f for f in findings if not f.suppressed]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


@pytest.fixture(scope="module")
def pkg_model():
    """ONE whole-package contract model shared by the closure proofs."""
    from dask_ml_tpu.analysis.core import Context, all_rules, iter_py_files
    from dask_ml_tpu.analysis.graph import Project

    all_rules()
    ctxs = []
    for path in iter_py_files([PKG]):
        with open(path, encoding="utf-8") as fh:
            ctxs.append(Context(fh.read(), path))
    return contracts.model_for(Project(ctxs))


@pytest.fixture(scope="module")
def pkg_contract_lint(tmp_path_factory):
    cache = str(tmp_path_factory.mktemp("graftcontract") / "cache.json")
    return lint_paths([PKG], select=CONTRACT_RULES, cache=cache)


# ---------------------------------------------------------------------------
# the tier-1 self-gate + closure proofs on the real package
# ---------------------------------------------------------------------------

class TestPackageContractGate:
    def test_package_has_zero_unsuppressed_contract_findings(
            self, pkg_contract_lint):
        findings, errors = pkg_contract_lint
        assert not errors, errors
        bad = active(findings)
        assert not bad, "\n".join(f.render() for f in bad)

    def test_committed_contract_baseline_matches(self, pkg_contract_lint):
        findings, _ = pkg_contract_lint
        snap = bl.load(CONTRACT_BASELINE)
        delta = bl.compare(snap, findings, bl.baseline_root([PKG]),
                           rules=sorted(CONTRACT_RULES))
        assert not delta["new"], [f.render() for f in delta["new"]]
        assert not delta["fixed"], delta["fixed"]

    def test_cli_contract_gate_exit_zero(self, capsys):
        assert main([PKG, "--select", SEL,
                     "--baseline", CONTRACT_BASELINE]) == 0
        assert "0 new, 0 stale" in capsys.readouterr().out

    def test_retryable_reason_set_is_closed(self, pkg_model):
        # PR-19's routing contract, proven both ways: every produced
        # RequestRejected reason is classified, and every roster entry
        # is producible — no dropped-request default, no dead entry
        produced = pkg_model.produced_reasons()
        classified = pkg_model.classified_reasons()
        assert produced, "extraction found no reason producers"
        assert produced == classified, (
            f"orphans: {produced - classified}, "
            f"dead: {classified - produced}")

    def test_retryable_reason_set_exact(self, pkg_model):
        # the full vocabulary, pinned: growing it is deliberate (add
        # the producer AND the roster entry AND update this set)
        assert pkg_model.classified_reasons() == {
            "queue_full", "draining", "serve_down", "shutdown",
            "unknown_model", "bad_input", "oversize", "deadline",
            "brownout"}

    def test_policy_verdict_keys_are_closed(self, pkg_model):
        # PR-17's autopilot contract: every POLICY key names a verdict
        # class graftpath can produce and a plane that exists
        classes = {s.value for s in pkg_model.verdict_classes}
        assert classes, "extraction found no BOTTLENECK_CLASSES"
        for (plane, cls), _site in pkg_model.policy_keys:
            assert cls in classes, (plane, cls)
            assert plane in ("fit", "search", "serve"), plane

    def test_every_injection_point_is_wired(self, pkg_model):
        wired = {s.value for s in pkg_model.fault_sites}
        for site in pkg_model.injection_roster:
            assert site.value in wired, site.value

    def test_every_produced_metric_family_documented(self, pkg_model):
        text = pkg_model.api_md_text()
        assert text is not None
        missing = {s.value for s in pkg_model.metric_literals
                   if s.value not in text}
        assert not missing, missing


# ---------------------------------------------------------------------------
# seeded drift: the detector must be able to fail the very gate CI runs
# ---------------------------------------------------------------------------

class TestSeededDrift:
    def test_sighted_gate_exits_zero(self, monkeypatch):
        monkeypatch.delenv(contracts.CONTRACT_INJECT_ENV, raising=False)
        assert main([PKG, "--select", SEL,
                     "--baseline", CONTRACT_BASELINE]) == 0

    def test_orphan_reason_drift_exits_one(self, monkeypatch, capsys):
        monkeypatch.setenv(contracts.CONTRACT_INJECT_ENV, "orphan-reason")
        assert main([PKG, "--select", SEL,
                     "--baseline", CONTRACT_BASELINE]) == 1
        out = capsys.readouterr().out
        assert "seeded drift" in out and "contract-orphan-producer" in out

    def test_dead_policy_drift_exits_one(self, monkeypatch, capsys):
        monkeypatch.setenv(contracts.CONTRACT_INJECT_ENV, "dead-policy")
        assert main([PKG, "--select", SEL,
                     "--baseline", CONTRACT_BASELINE]) == 1
        out = capsys.readouterr().out
        assert "seeded drift" in out and "contract-dead-consumer" in out

    def test_typo_mode_exits_two(self, monkeypatch):
        # graftlock's strict-parse convention: a misspelled injection
        # must crash the analyzer (2), never read as a lint verdict
        monkeypatch.setenv(contracts.CONTRACT_INJECT_ENV, "orfan-reason")
        assert main([PKG, "--select", SEL, "--no-cache"]) == 2

    def test_inject_is_inert_without_a_contract(self, monkeypatch):
        # guard check: a snippet with no rosters has nothing to drift —
        # the injection must not fabricate findings out of thin air
        monkeypatch.setenv(contracts.CONTRACT_INJECT_ENV, "orphan-reason")
        assert not active(lint("x = 1\n"))


# ---------------------------------------------------------------------------
# rejection-reason family
# ---------------------------------------------------------------------------

class TestRejectionReasons:
    CLEAN = """
        class RequestRejected(Exception):
            def __init__(self, reason, detail=""):
                self.reason = reason

        _RETRYABLE = ("queue_full",)
        _NON_RETRYABLE = ("bad_input",)

        def submit(full, bad):
            if full:
                raise RequestRejected("queue_full", "shed")
            if bad:
                raise RequestRejected("bad_input", "nan rows")
    """

    def test_clean_closed_set(self):
        assert not active(lint(self.CLEAN))

    def test_orphan_reason_flagged(self):
        findings = lint(self.CLEAN + """
        def worse():
            raise RequestRejected("mystery", "who classifies this?")
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-orphan-producer"]
        assert "mystery" in fs[0].message

    def test_dead_roster_entry_flagged(self):
        findings = lint(self.CLEAN.replace(
            '_RETRYABLE = ("queue_full",)',
            '_RETRYABLE = ("queue_full", "draining")'))
        fs = active(findings)
        assert rule_ids(fs) == ["contract-dead-consumer"]
        assert "draining" in fs[0].message

    def test_helper_producers_recognized(self):
        # reject(req, reason, ...) and self._fleet_reject(reason, ...)
        # are reason positions too (arg index differs per callable)
        findings = lint("""
            _RETRYABLE = ("queue_full",)

            def reject(req, reason, detail):
                pass

            class Fleet:
                def _fleet_reject(self, reason, detail):
                    pass

                def shed(self, req):
                    reject(req, "queue_full", "full")
                    self._fleet_reject("overheat", "thermals")
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-orphan-producer"]
        assert "overheat" in fs[0].message

    def test_no_roster_means_no_contract(self):
        # without a _RETRYABLE roster in scope there is nothing to
        # classify against — vendored subsets must not light up
        findings = lint("""
            class RequestRejected(Exception):
                pass

            def submit():
                raise RequestRejected("anything_goes", "no roster here")
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# verdict-class / POLICY family
# ---------------------------------------------------------------------------

class TestVerdictPolicy:
    CLEAN = """
        BOTTLENECK_CLASSES = ("unknown", "device-bound", "parse-bound")

        POLICY = {
            ("fit", "parse-bound"): ("data_readers", "up"),
            ("serve", "device-bound"): ("serve_max_batch", "up"),
        }
    """

    def test_clean_policy(self):
        assert not active(lint(self.CLEAN))

    def test_unreachable_policy_key_flagged(self):
        findings = lint(self.CLEAN.replace(
            '("serve", "device-bound")', '("serve", "zebra-bound")'))
        fs = active(findings)
        assert rule_ids(fs) == ["contract-dead-consumer"]
        assert "zebra-bound" in fs[0].message and "POLICY" in fs[0].message


# ---------------------------------------------------------------------------
# metric-family / flight-event family
# ---------------------------------------------------------------------------

class TestMetricFamilies:
    CLEAN = """
        def tick(reg, obs):
            reg.counter("pipeline.blocks", "ok").inc()
            reg.family("pipeline.blocks")
            obs.event("pipeline.fault", label="x")
    """

    def test_clean_produced_and_read(self):
        assert not active(lint(self.CLEAN))

    def test_dead_family_read_flagged(self):
        findings = lint(self.CLEAN + """
        def stale(reg):
            return reg.family("pipeline.gone")
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-dead-consumer"]
        assert "pipeline.gone" in fs[0].message

    def test_event_off_metric_namespace_flagged(self):
        findings = lint(self.CLEAN + """
        def shout(obs):
            obs.event("zebra.fault", label="orphan layer")
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-orphan-producer"]
        assert "zebra.fault" in fs[0].message

    def test_fstring_pattern_producer_matches_consumer(self):
        # serve/runtime.py's f"serve.req_{leg}_s" shape: the consumer
        # of a concrete expansion must resolve against the pattern
        findings = lint("""
            def split(reg, leg):
                reg.histogram(f"serve.req_{leg}_s").observe(0.1)
                reg.counter("serve.requests").inc()

            def read(reg):
                return reg.family("serve.req_queue_s")
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# injection-point family
# ---------------------------------------------------------------------------

class TestInjectionPoints:
    CLEAN = """
        INJECTION_POINTS = ("step", "stage")

        def run(maybe_fault):
            maybe_fault("step")
            maybe_fault("stage")
    """

    def test_clean_roster(self):
        assert not active(lint(self.CLEAN))

    def test_unrostered_fault_site_flagged(self):
        findings = lint(self.CLEAN + """
        def sneak(maybe_fault):
            maybe_fault("rogue-point")
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-orphan-producer"]
        assert "rogue-point" in fs[0].message

    def test_unwired_roster_entry_flagged(self):
        findings = lint(self.CLEAN.replace(
            '("step", "stage")', '("step", "stage", "prefetch")'))
        fs = active(findings)
        assert rule_ids(fs) == ["contract-dead-consumer"]
        assert "prefetch" in fs[0].message


# ---------------------------------------------------------------------------
# thread-name / lock-name roster family
# ---------------------------------------------------------------------------

class TestThreadLockRosters:
    CLEAN = """
        import threading

        KNOWN_THREAD_NAMES = frozenset({"dask-ml-tpu-serve"})

        def start(fn):
            t = threading.Thread(target=fn, name="dask-ml-tpu-serve")
            return t
    """

    def test_clean_rostered_thread(self):
        assert not active(lint(self.CLEAN))

    def test_off_roster_package_thread_flagged(self):
        findings = lint(self.CLEAN + """
        def sneak(fn):
            return threading.Thread(target=fn, name="dask-ml-tpu-rogue")
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-roster-drift"]
        assert "dask-ml-tpu-rogue" in fs[0].message

    def test_unprefixed_thread_is_not_package_namespace(self):
        findings = lint(self.CLEAN + """
        def client(fn):
            return threading.Thread(target=fn, name="client-traffic")
        """)
        assert not active(findings)

    def test_rostered_but_never_constructed_flagged(self):
        findings = lint(self.CLEAN.replace(
            '{"dask-ml-tpu-serve"}',
            '{"dask-ml-tpu-serve", "dask-ml-tpu-ghost"}'))
        fs = active(findings)
        assert rule_ids(fs) == ["contract-roster-drift"]
        assert "dask-ml-tpu-ghost" in fs[0].message

    def test_lock_contract_key_without_lock_flagged(self):
        findings = lint("""
            LOCK_THREAD_CONTRACTS = {
                "serve.server": ("serve-loop",),
                "gone.lock": ("nobody",),
            }

            def build(make_lock):
                return make_lock("serve.server")
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-roster-drift"]
        assert "gone.lock" in fs[0].message

    def test_lock_contract_keys_all_produced_is_clean(self):
        findings = lint("""
            LOCK_THREAD_CONTRACTS = {"serve.server": ("serve-loop",)}

            def build(make_lock):
                return make_lock("serve.server")
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# knob-name family
# ---------------------------------------------------------------------------

class TestKnobNames:
    CLEAN = """
        class Knob:
            def __init__(self, name, env, kind, default, lo, hi):
                self.name = name

        KNOBS = {k.name: k for k in (
            Knob("prefetch_depth", "DASK_ML_TPU_PREFETCH_DEPTH",
                 int, 2, 0, 64),
        )}

        def read(registry):
            return registry.override_or("prefetch_depth", 2)
    """

    def test_clean_declared_knob(self):
        assert not active(lint(self.CLEAN))

    def test_undeclared_knob_reference_flagged(self):
        findings = lint(self.CLEAN + """
        def poke(registry):
            registry.set_knob("ghost_knob", 9)
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-dead-consumer"]
        assert "ghost_knob" in fs[0].message


# ---------------------------------------------------------------------------
# committed-baseline pin family (tools/*_baseline.json)
# ---------------------------------------------------------------------------

class TestCommittedBaselinePins:
    def _tree(self, tmp_path, perf=None, drill=None, lock=None):
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "api.md").write_text(
            "| `pipeline.blocks` | counter | — | blocks |\n")
        tools = tmp_path / "tools"
        tools.mkdir()
        if perf is not None:
            (tools / "perf_baseline.json").write_text(json.dumps(perf))
        if drill is not None:
            (tools / "drill_baseline.json").write_text(json.dumps(drill))
        if lock is not None:
            (tools / "lock_baseline.json").write_text(json.dumps(lock))
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        return pkg

    def _lint(self, pkg):
        return lint_paths([str(pkg)], select=CONTRACT_RULES)[0]

    def test_valid_perf_pin_is_clean(self, tmp_path):
        pkg = self._tree(tmp_path, perf={"workloads": {"w": {
            "bottleneck": {"class": "device-bound", "share": 0.8}}}})
        (pkg / "mod.py").write_text(
            'BOTTLENECK_CLASSES = ("unknown", "device-bound")\n')
        assert not active(self._lint(pkg))

    def test_perf_class_drift_flagged(self, tmp_path):
        pkg = self._tree(tmp_path, perf={"workloads": {"w": {
            "bottleneck": {"class": "zebra-bound", "share": 0.8}}}})
        (pkg / "mod.py").write_text(
            'BOTTLENECK_CLASSES = ("unknown", "device-bound")\n')
        fs = active(self._lint(pkg))
        assert rule_ids(fs) == ["contract-baseline-drift"]
        assert "zebra-bound" in fs[0].message

    def test_perf_trajectory_knob_drift_flagged(self, tmp_path):
        pkg = self._tree(tmp_path, perf={"workloads": {"controller": {
            "bottleneck": {"class": "device-bound", "share": 0.8},
            "knob_trajectory": [
                {"knob": "ghost_knob", "class": "device-bound"}]}}})
        (pkg / "mod.py").write_text(
            'BOTTLENECK_CLASSES = ("unknown", "device-bound")\n'
            'class Knob:\n'
            '    def __init__(self, name, env, kind):\n'
            '        self.name = name\n'
            'KNOBS = {k.name: k for k in ('
            'Knob("real_knob", "DASK_ML_TPU_REAL", int),)}\n')
        fs = active(self._lint(pkg))
        assert rule_ids(fs) == ["contract-baseline-drift"]
        assert "ghost_knob" in fs[0].message

    def test_drill_point_drift_flagged(self, tmp_path):
        pkg = self._tree(tmp_path,
                         drill={"drills": {"d": {"point": "gone-point"}}})
        (pkg / "mod.py").write_text(
            'INJECTION_POINTS = ("step",)\n'
            'def run(maybe_fault):\n'
            '    maybe_fault("step")\n')
        fs = active(self._lint(pkg))
        assert rule_ids(fs) == ["contract-baseline-drift"]
        assert "gone-point" in fs[0].message

    def test_lock_edge_drift_flagged(self, tmp_path):
        pkg = self._tree(tmp_path,
                         lock={"edges": ["serve.server -> gone.lock"]})
        (pkg / "mod.py").write_text(
            'LOCK_THREAD_CONTRACTS = {"serve.server": ("serve-loop",)}\n'
            'def build(make_lock):\n'
            '    return make_lock("serve.server")\n')
        fs = active(self._lint(pkg))
        assert rule_ids(fs) == ["contract-baseline-drift"]
        assert "gone.lock" in fs[0].message

    def test_no_committed_baseline_is_silent(self, tmp_path):
        pkg = self._tree(tmp_path)
        (pkg / "mod.py").write_text(
            'BOTTLENECK_CLASSES = ("unknown", "device-bound")\n')
        assert not active(self._lint(pkg))


# ---------------------------------------------------------------------------
# docs family: contract-undocumented-metric
# ---------------------------------------------------------------------------

class TestUndocumentedMetric:
    def _tree(self, tmp_path, documented, produced):
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "api.md").write_text(
            f"| `{documented}` | counter | — | a family |\n")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            f'def tick(reg):\n'
            f'    reg.counter("{produced}", "t").inc()\n')
        return str(pkg)

    def test_documented_family_is_clean(self, tmp_path):
        pkg = self._tree(tmp_path, "pipeline.blocks", "pipeline.blocks")
        findings, _ = lint_paths([pkg], select=CONTRACT_RULES)
        assert not active(findings)

    def test_undocumented_family_flagged(self, tmp_path):
        pkg = self._tree(tmp_path, "pipeline.blocks", "pipeline.secret")
        findings, _ = lint_paths([pkg], select=CONTRACT_RULES)
        fs = active(findings)
        assert rule_ids(fs) == ["contract-undocumented-metric"]
        assert "pipeline.secret" in fs[0].message

    def test_no_api_md_in_reach_is_silent(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            'def tick(reg):\n'
            '    reg.counter("pipeline.secret", "t").inc()\n')
        findings, _ = lint_paths([str(pkg)], select=CONTRACT_RULES)
        assert not active(findings)


# ---------------------------------------------------------------------------
# ratchet mechanics: round-trip / new / stale / wrong-root refusal
# ---------------------------------------------------------------------------

class TestContractRatchet:
    DRIFTED = textwrap.dedent("""
        _RETRYABLE = ("queue_full",)

        class RequestRejected(Exception):
            pass

        def submit(full):
            if full:
                raise RequestRejected("queue_full", "shed")
            raise RequestRejected("mystery", "unclassified")
    """)

    def _pkg(self, tmp_path, src):
        (tmp_path / "mod.py").write_text(src)
        return str(tmp_path)

    def test_round_trip_and_clean_compare(self, tmp_path):
        pkg = self._pkg(tmp_path, self.DRIFTED)
        findings, errors = lint_paths([pkg], select=CONTRACT_RULES)
        assert rule_ids(active(findings)) == ["contract-orphan-producer"]
        root = bl.baseline_root([pkg])
        path = tmp_path / "contract_baseline.json"
        bl.write(str(path), bl.emit(findings, errors, root,
                                    rules=sorted(CONTRACT_RULES)))
        delta = bl.compare(bl.load(str(path)), findings, root,
                           rules=sorted(CONTRACT_RULES))
        assert not delta["new"] and not delta["fixed"]

    def test_new_drift_detected(self, tmp_path):
        pkg = self._pkg(tmp_path, self.DRIFTED)
        findings, errors = lint_paths([pkg], select=CONTRACT_RULES)
        root = bl.baseline_root([pkg])
        snap = bl.emit(findings, errors, root)
        self._pkg(tmp_path, self.DRIFTED + textwrap.dedent("""
            def worse():
                raise RequestRejected("second_mystery", "more drift")
        """))
        findings2, _ = lint_paths([pkg], select=CONTRACT_RULES)
        delta = bl.compare(snap, findings2, root)
        assert len(delta["new"]) == 1
        assert delta["new"][0].rule == "contract-orphan-producer"

    def test_fixed_drift_reported_stale(self, tmp_path):
        pkg = self._pkg(tmp_path, self.DRIFTED)
        findings, errors = lint_paths([pkg], select=CONTRACT_RULES)
        root = bl.baseline_root([pkg])
        snap = bl.emit(findings, errors, root)
        self._pkg(tmp_path, self.DRIFTED.replace(
            '_RETRYABLE = ("queue_full",)',
            '_RETRYABLE = ("queue_full", "mystery")'))
        findings2, _ = lint_paths([pkg], select=CONTRACT_RULES)
        delta = bl.compare(snap, findings2, root)
        assert not delta["new"]
        assert {e["rule"] for e in delta["fixed"]} == \
            {"contract-orphan-producer"}

    def test_wrong_root_refused(self, tmp_path):
        pkg_a = tmp_path / "repo_a"
        pkg_a.mkdir()
        pkg_b = tmp_path / "repo_b"
        pkg_b.mkdir()
        (pkg_a / "mod.py").write_text(self.DRIFTED)
        (pkg_b / "mod.py").write_text(self.DRIFTED)
        findings, errors = lint_paths([str(pkg_a)], select=CONTRACT_RULES)
        snap = bl.emit(findings, errors, bl.baseline_root([str(pkg_a)]))
        with pytest.raises(ValueError):
            bl.compare(snap, findings, bl.baseline_root([str(pkg_b)]))

    def test_cli_wrong_root_exits_two(self, tmp_path, capsys):
        pkg_a = tmp_path / "repo_a"
        pkg_a.mkdir()
        pkg_b = tmp_path / "repo_b"
        pkg_b.mkdir()
        (pkg_a / "mod.py").write_text(self.DRIFTED)
        (pkg_b / "mod.py").write_text(self.DRIFTED)
        path = str(tmp_path / "bl.json")
        assert main([str(pkg_a), "--select", SEL,
                     "--write-baseline", path]) == 0
        assert main([str(pkg_b), "--select", SEL,
                     "--baseline", path]) == 2
        capsys.readouterr()

    def test_cli_exit_zero_and_one(self, tmp_path, capsys):
        pkg = self._pkg(tmp_path, self.DRIFTED)
        assert main([pkg, "--select", SEL]) == 1
        self._pkg(tmp_path, self.DRIFTED.replace(
            '_RETRYABLE = ("queue_full",)',
            '_RETRYABLE = ("queue_full", "mystery")'))
        assert main([pkg, "--select", SEL]) == 0
        capsys.readouterr()


# ---------------------------------------------------------------------------
# reporter schema for the contract rules
# ---------------------------------------------------------------------------

class TestContractReporters:
    def test_text_reporter(self, tmp_path, capsys):
        (tmp_path / "mod.py").write_text(TestContractRatchet.DRIFTED)
        assert main([str(tmp_path), "--select", SEL]) == 1
        out = capsys.readouterr().out
        assert "[contract-orphan-producer]" in out
        assert "mystery" in out
        assert "graftlint: 1 finding(s)" in out

    def test_json_reporter_schema(self, tmp_path, capsys):
        (tmp_path / "mod.py").write_text(TestContractRatchet.DRIFTED)
        assert main([str(tmp_path), "--select", SEL,
                     "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["version"] == 2
        assert payload["counts"]["contract-orphan-producer"] == {
            "active": 1, "suppressed": 0}
        [f] = payload["findings"]
        assert f["rule"] == "contract-orphan-producer"
        assert set(f) >= {"rule", "path", "line", "col", "message",
                          "suppressed", "justification"}
        assert f["line"] > 0 and not f["suppressed"]
        assert not payload["errors"]
        # the rules block is the full registry (id -> summary): every
        # contract rule must be registered and self-describing
        for rule in CONTRACT_RULES:
            assert payload["rules"][rule]

    def test_json_reporter_ratchet_block(self, tmp_path, capsys):
        # ACTIVE findings still exit 1 even when baselined — the gate
        # demands zero active; the ratchet exists for the suppressed
        # tail — but the delta block itself must read clean
        (tmp_path / "mod.py").write_text(TestContractRatchet.DRIFTED)
        path = str(tmp_path / "bl.json")
        assert main([str(tmp_path), "--select", SEL,
                     "--write-baseline", path]) == 0
        capsys.readouterr()
        assert main([str(tmp_path), "--select", SEL, "--baseline", path,
                     "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["baseline"] == {"new": [], "stale": []}


# ---------------------------------------------------------------------------
# cache digest: analyzer identity + inject knob + committed ratchets
# ---------------------------------------------------------------------------

class TestCacheDigest:
    def _sources(self, tmp_path):
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "api.md").write_text("knobs\n")
        (tmp_path / "tools").mkdir()
        (tmp_path / "tools" / "perf_baseline.json").write_text(
            '{"workloads": {}}')
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        mod = pkg / "mod.py"
        mod.write_text("x = 1\n")
        return [(str(mod), mod.read_text())]

    def test_inject_env_keys_the_digest(self, tmp_path, monkeypatch):
        src = self._sources(tmp_path)
        monkeypatch.delenv(contracts.CONTRACT_INJECT_ENV, raising=False)
        d0 = lint_cache.project_digest(src)
        monkeypatch.setenv(contracts.CONTRACT_INJECT_ENV, "orphan-reason")
        d1 = lint_cache.project_digest(src)
        monkeypatch.setenv(contracts.CONTRACT_INJECT_ENV, "dead-policy")
        d2 = lint_cache.project_digest(src)
        assert len({d0, d1, d2}) == 3

    def test_committed_ratchet_keys_the_digest(self, tmp_path):
        src = self._sources(tmp_path)
        d0 = lint_cache.project_digest(src)
        (tmp_path / "tools" / "perf_baseline.json").write_text(
            '{"workloads": {"w": {}}}')
        assert lint_cache.project_digest(src) != d0

    def test_analyzer_sources_key_the_digest(self, tmp_path, monkeypatch):
        # adding OR editing a rule module must invalidate the warm
        # cache even when the linted tree is unchanged — point the
        # analyzer-identity walk at a scratch package and mutate it
        src = self._sources(tmp_path)
        fake = tmp_path / "analysis"
        (fake / "rules").mkdir(parents=True)
        (fake / "rules" / "a.py").write_text("A = 1\n")
        monkeypatch.setattr(lint_cache, "__file__",
                            str(fake / "cache.py"))
        d0 = lint_cache.project_digest(src)
        (fake / "rules" / "a.py").write_text("A = 2\n")
        d1 = lint_cache.project_digest(src)
        (fake / "rules" / "b.py").write_text("B = 1\n")
        d2 = lint_cache.project_digest(src)
        assert len({d0, d1, d2}) == 3

    def test_warm_cache_does_not_mask_injection(self, tmp_path,
                                                monkeypatch):
        # the end-to-end regression this PR hit: a sighted run warms
        # the cache, then an injected run MUST NOT read its findings
        (tmp_path / "mod.py").write_text(TestContractRatchet.DRIFTED.replace(
            '_RETRYABLE = ("queue_full",)',
            '_RETRYABLE = ("queue_full", "mystery")'))
        cache = str(tmp_path / "cache.json")
        monkeypatch.delenv(contracts.CONTRACT_INJECT_ENV, raising=False)
        findings, _ = lint_paths([str(tmp_path)], select=CONTRACT_RULES,
                                 cache=cache)
        assert not active(findings)
        monkeypatch.setenv(contracts.CONTRACT_INJECT_ENV, "orphan-reason")
        findings2, _ = lint_paths([str(tmp_path)], select=CONTRACT_RULES,
                                  cache=cache)
        assert rule_ids(active(findings2)) == ["contract-orphan-producer"]


# ---------------------------------------------------------------------------
# satellite: Knob(...) declarations and _env_number resolution are
# knob-read sites for undocumented-knob
# ---------------------------------------------------------------------------

class TestKnobRegistryReads:
    def _tree(self, tmp_path, documented, body):
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "api.md").write_text(
            f"| `{documented}` | int | a knob | — |\n")
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(textwrap.dedent(body))
        return str(pkg)

    KNOB_DECL = """
        class Knob:
            def __init__(self, name, env, kind, default, lo, hi):
                self.name = name

        K = Knob("depth", "{env}", int, 2, 0, 64)
    """

    ENV_NUMBER = """
        import os

        def _env_number(env, cast, default):
            return cast(os.environ.get(env, default))

        def depth():
            return _env_number("{env}", int, 2)
    """

    def test_knob_declaration_is_a_read_site(self, tmp_path):
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH",
                         self.KNOB_DECL.format(env="DASK_ML_TPU_SECRET"))
        findings, _ = lint_paths([pkg], select=["undocumented-knob"])
        fs = active(findings)
        assert rule_ids(fs) == ["undocumented-knob"]
        assert "DASK_ML_TPU_SECRET" in fs[0].message

    def test_documented_knob_declaration_is_clean(self, tmp_path):
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH",
                         self.KNOB_DECL.format(env="DASK_ML_TPU_DEPTH"))
        findings, _ = lint_paths([pkg], select=["undocumented-knob"])
        assert not active(findings)

    def test_env_number_is_a_read_site(self, tmp_path):
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH",
                         self.ENV_NUMBER.format(env="DASK_ML_TPU_HIDDEN"))
        findings, _ = lint_paths([pkg], select=["undocumented-knob"])
        fs = active(findings)
        assert rule_ids(fs) == ["undocumented-knob"]
        assert "DASK_ML_TPU_HIDDEN" in fs[0].message

    def test_documented_env_number_is_clean(self, tmp_path):
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH",
                         self.ENV_NUMBER.format(env="DASK_ML_TPU_DEPTH"))
        findings, _ = lint_paths([pkg], select=["undocumented-knob"])
        assert not active(findings)


# ---------------------------------------------------------------------------
# regression pins for the real drift this PR fixed
# ---------------------------------------------------------------------------

class TestFixedDriftStaysFixed:
    def test_non_retryable_roster_exists_and_is_load_bearing(self):
        from dask_ml_tpu.serve import fleet

        assert set(fleet._NON_RETRYABLE) == {
            "bad_input", "oversize", "deadline", "brownout"}
        assert not set(fleet._RETRYABLE) & set(fleet._NON_RETRYABLE)

    def test_rogue_writer_thread_stays_suppressed_not_rostered(self):
        # the sanitize drill thread must stay OFF the roster (rostering
        # it would blind the runtime check it exists to prove) and stay
        # suppressed rather than deleted
        from dask_ml_tpu.analysis.rules import _spmd

        assert "dask-ml-tpu-rogue-writer" not in _spmd.KNOWN_THREAD_NAMES
        with open(os.path.join(PKG, "sanitize", "locks.py"),
                  encoding="utf-8") as fh:
            src = fh.read()
        assert "disable=contract-roster-drift" in src

"""Property-based pins for the algebraically delicate paths: the
two-level roc_auc prefix sum, the weight-folding helper, the quantile
sketch, StandardScaler's Chan moment merge, and the SGD full-batch
collapse (round 3).

Bounded example counts keep the suite fast; the properties (exact sklearn
equality under ties/weights, duplication-equivalence of integer weights)
are the invariants hand-picked examples keep missing."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402


@st.composite
def _labeled_scores(draw):
    n = draw(st.integers(min_value=4, max_value=120))
    t = draw(st.lists(st.integers(0, 1), min_size=n, max_size=n))
    if len(set(t)) < 2:
        t[0], t[1] = 0, 1
    # coarse rounding makes heavy ties likely
    s = draw(st.lists(
        st.integers(min_value=-5, max_value=5), min_size=n, max_size=n
    ))
    w = draw(st.lists(
        st.floats(min_value=0.1, max_value=4.0, allow_nan=False),
        min_size=n, max_size=n,
    ))
    return np.asarray(t), np.asarray(s, np.float32), np.asarray(w, np.float32)


class TestRocAucProperties:
    @settings(max_examples=30, deadline=None)
    @given(_labeled_scores())
    def test_matches_sklearn_under_ties_and_weights(self, tsw):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t, s, w = tsw
        ours = dm.roc_auc_score(t, s, sample_weight=w)
        ref = skm.roc_auc_score(t, s, sample_weight=w)
        assert ours == pytest.approx(ref, abs=1e-5)

    @settings(max_examples=15, deadline=None)
    @given(_labeled_scores())
    def test_multiblock_equals_singleblock(self, tsw):
        from dask_ml_tpu.metrics import classification as cl

        t, s, w = tsw
        one = cl.roc_auc_score(t, s, sample_weight=w)
        saved = cl._AUC_BLOCK
        cl._AUC_BLOCK = 8  # force many blocks (restored below)
        try:
            many = cl.roc_auc_score(t, s, sample_weight=w)
        finally:
            cl._AUC_BLOCK = saved
        assert one == pytest.approx(many, abs=1e-6)


class TestEffectiveMaskProperties:
    @settings(max_examples=25, deadline=None)
    @given(
        # weight 0 included: a zero-weight row must drop out entirely,
        # exactly like a row repeated zero times
        st.lists(st.integers(0, 3), min_size=3, max_size=40),
        st.lists(st.integers(0, 2), min_size=3, max_size=40),
    )
    def test_integer_weights_equal_duplication_in_weighted_mean(
        self, sw, labels
    ):
        # weighted mean with integer sample weights == unweighted mean of
        # the duplicated rows (the invariant behind every weighted fit)
        import jax.numpy as jnp

        from dask_ml_tpu.utils import effective_mask

        from hypothesis import assume

        n = min(len(sw), len(labels))
        sw, labels = np.asarray(sw[:n]), np.asarray(labels[:n], np.float32)
        assume(sw.sum() > 0)
        vals = labels * 2.0 - 1.0
        mask = jnp.ones(n, jnp.float32)
        w = effective_mask(mask, sample_weight=sw, n_samples=n)
        weighted_mean = float((jnp.asarray(vals) * w).sum() / w.sum())
        dup_mean = float(np.repeat(vals, sw).mean())
        assert weighted_mean == pytest.approx(dup_mean, abs=1e-5)

    @settings(max_examples=25, deadline=None)
    @given(st.lists(st.integers(0, 2), min_size=6, max_size=60))
    def test_balanced_classes_get_equal_total_weight(self, labels):
        import jax.numpy as jnp

        from dask_ml_tpu.utils import effective_mask

        labels = np.asarray(labels, np.float32)
        classes = np.unique(labels)
        if len(classes) < 2:
            return
        mask = jnp.ones(len(labels), jnp.float32)
        w = effective_mask(
            mask, jnp.asarray(labels), class_weight="balanced",
            classes=classes,
        )
        w = np.asarray(w)
        # balanced: every class's TOTAL weight equals n/K
        totals = [w[labels == c].sum() for c in classes]
        np.testing.assert_allclose(
            totals, len(labels) / len(classes), rtol=1e-5
        )


class TestQuantileSketchProperties:
    @settings(max_examples=12, deadline=None)
    @given(
        st.integers(min_value=0, max_value=2**31 - 1),
        st.floats(min_value=1.0, max_value=1e6, allow_nan=False),
    )
    def test_sketch_tracks_exact_quantiles(self, seed, scale):
        # FIXED shape (one jit executable across examples); data and
        # scale vary — incl. the outlier-heavy regimes the refinement
        # passes exist for
        import jax.numpy as jnp

        from dask_ml_tpu.preprocessing.data import _hist_quantiles

        rng = np.random.RandomState(seed)
        x = (rng.normal(size=(2048, 2)) * np.array([1.0, scale])).astype(
            np.float32
        )
        x[0, 0] = scale * 1e3  # guaranteed outlier in column 0
        probs = np.asarray([0.0, 0.25, 0.5, 0.75, 1.0], np.float32)
        got = np.asarray(_hist_quantiles(
            jnp.asarray(x), jnp.ones(2048, jnp.float32), jnp.asarray(probs)
        ))
        want = np.quantile(x, probs, axis=0)
        # bound RELATIVE TO THE IQR, not the outlier-bloated span: an
        # unrefined sketch's error is one bin = span/4096, which for the
        # outlier column exceeds this bound ~10x — so the test actually
        # fails if the refinement passes stop working.  (The residual
        # error is dominated by the rank-interpolation definition gap vs
        # np.quantile, ~order-stat spacing, not by bin resolution.)
        iqr = want[3] - want[1]
        err = np.abs(got[1:4] - want[1:4])
        bound = iqr * 2e-2 + (x.max(axis=0) - x.min(axis=0)) * 1e-6
        assert (err <= bound).all(), (err, bound)
        np.testing.assert_allclose(got[0], want[0], rtol=1e-6)
        np.testing.assert_allclose(got[4], want[4], rtol=1e-6)


class TestPackedSolveProperties:
    @settings(max_examples=8, deadline=None)
    @given(st.integers(min_value=0, max_value=2**31 - 1))
    def test_packed_equals_sequential_lbfgs(self, seed):
        # fixed (n, d, K): one compile serves all examples; data varies.
        # Force the PACKED path (try/finally, not monkeypatch: hypothesis
        # rejects function-scoped fixtures): auto resolves to sequential
        # on CPU, which would make this comparison vacuous
        import os

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.solvers import Logistic, lbfgs, packed_solve

        prev = os.environ.get("DASK_ML_TPU_PACK")
        os.environ["DASK_ML_TPU_PACK"] = "packed"
        try:
            self._run_packed_case(seed, shard_rows, Logistic, lbfgs,
                                  packed_solve)
        finally:
            if prev is None:
                os.environ.pop("DASK_ML_TPU_PACK", None)
            else:
                os.environ["DASK_ML_TPU_PACK"] = prev

    def _run_packed_case(self, seed, shard_rows, Logistic, lbfgs,
                         packed_solve):

        rng = np.random.RandomState(seed)
        n, d, K = 256, 4, 3
        X = rng.normal(size=(n, d)).astype(np.float32)
        sX = shard_rows(X)
        Y = np.zeros((K, sX.data.shape[0]), np.float32)
        labels = rng.randint(0, K, n)
        for k in range(K):
            Y[k, :n] = labels == k
        betas, _ = packed_solve(
            "lbfgs", sX, Y, family=Logistic, lamduh=1.0, max_iter=60,
        )
        for k in range(K):
            solo = lbfgs(sX, Y[k], family=Logistic, lamduh=1.0, max_iter=60)
            np.testing.assert_allclose(
                np.asarray(betas[k]), np.asarray(solo), rtol=5e-3, atol=1e-3
            )


@st.composite
def _block_splits(draw):
    n = draw(st.integers(min_value=20, max_value=200))
    k = draw(st.integers(min_value=1, max_value=5))
    cuts = sorted(draw(st.lists(
        st.integers(min_value=1, max_value=n - 1), min_size=k, max_size=k,
    )))
    seed = draw(st.integers(min_value=0, max_value=2**16))
    return n, [0, *dict.fromkeys(cuts), n], seed


@settings(max_examples=25, deadline=None)
@given(_block_splits())
def test_standard_scaler_partial_fit_split_invariant(case):
    """Chan moment merging: ANY block split of a stream produces the same
    mean_/var_ as one whole-array fit (the invariant that makes mid-
    stream checkpoints and ragged chunk streams safe)."""
    from dask_ml_tpu.preprocessing import StandardScaler

    n, cuts, seed = case
    X = np.random.RandomState(seed).normal(size=(n, 3)).astype(np.float32)
    full = StandardScaler().fit(X)
    stream = StandardScaler()
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        stream.partial_fit(X[lo:hi])  # boundaries are strictly increasing
    np.testing.assert_allclose(
        np.asarray(stream.mean_), np.asarray(full.mean_),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(stream.var_), np.asarray(full.var_),
        rtol=1e-3, atol=1e-5,
    )


@settings(max_examples=15, deadline=None)
@given(st.integers(min_value=2, max_value=64),
       st.integers(min_value=0, max_value=2**16))
def test_sgd_minibatch_one_chunk_equals_fullbatch(n_third, seed):
    """batch_size >= n collapses to the full-batch epoch exactly (same
    t_ and same coefficients)."""
    from dask_ml_tpu.linear_model import SGDClassifier

    rng = np.random.RandomState(seed)
    n = n_third * 3
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    if len(np.unique(y)) < 2:
        y[0] = 1 - y[0]
    a = SGDClassifier(max_iter=3, tol=None).fit(X, y)
    b = SGDClassifier(max_iter=3, tol=None, batch_size=n).fit(X, y)
    assert a.t_ == b.t_
    np.testing.assert_allclose(
        np.asarray(a.coef_), np.asarray(b.coef_), rtol=1e-6, atol=1e-7
    )


@settings(max_examples=20, deadline=None)
@given(_block_splits())
def test_gaussian_nb_weighted_stream_split_invariant(case):
    """Per-class Chan merges: ANY weighted block split reproduces the
    whole-array weighted fit (theta_, var_, class_count_)."""
    from dask_ml_tpu.naive_bayes import GaussianNB

    n, cuts, seed = case
    r = np.random.RandomState(seed)
    X = (r.normal(size=(n, 3)) * 2 + 3).astype(np.float32)
    y = r.randint(0, 3, size=n)
    w = r.uniform(0.25, 4.0, size=n)
    full = GaussianNB().fit(X, y, sample_weight=w)
    stream = GaussianNB()
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        stream.partial_fit(X[lo:hi], y[lo:hi], classes=[0, 1, 2],
                           sample_weight=w[lo:hi])
    np.testing.assert_allclose(
        np.asarray(stream.theta_), np.asarray(full.theta_),
        rtol=2e-4, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(stream.var_), np.asarray(full.var_),
        rtol=2e-3, atol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(stream.class_count_), np.asarray(full.class_count_),
        rtol=1e-5,
    )


@settings(max_examples=30, deadline=None)
@given(st.integers(0, 2**16))
def test_chan_merge_associative(seed):
    """(A+B)+C == A+(B+C) for the shared moment-merge helper."""
    import jax.numpy as jnp

    from dask_ml_tpu.utils import chan_merge

    r = np.random.RandomState(seed)

    def summarize(x):
        n = float(x.shape[0])
        m = x.mean(0)
        v = x.var(0)
        return n, jnp.asarray(m, jnp.float32), jnp.asarray(v * n, jnp.float32)

    parts = [r.normal(size=(r.randint(2, 40), 4)).astype(np.float32) + 2
             for _ in range(3)]
    summaries = [summarize(p) for p in parts]

    def merge(a, b):
        na, ma, m2a = a
        nb, mb, vbn = b
        # chan_merge takes (count_b, mean_b, var_b); recover var from M2
        n, m, m2 = chan_merge(na, ma, m2a, nb, mb, vbn / max(nb, 1.0))
        return n, m, m2

    left = merge(merge(summaries[0], summaries[1]), summaries[2])
    right = merge(summaries[0], merge(summaries[1], summaries[2]))
    np.testing.assert_allclose(np.asarray(left[1]), np.asarray(right[1]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(left[2]), np.asarray(right[2]),
                               rtol=1e-4, atol=1e-4)
    # and both equal the direct whole-array summary
    whole = summarize(np.concatenate(parts))
    np.testing.assert_allclose(np.asarray(left[1]), np.asarray(whole[1]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(left[2]), np.asarray(whole[2]),
                               rtol=1e-3, atol=1e-3)


@settings(max_examples=15, deadline=None)
@given(st.integers(2, 90), st.integers(2, 70), st.integers(1, 6),
       st.integers(0, 2**16))
def test_ring_pairwise_any_shapes(n1, n2, d, seed):
    """The ppermute ring must match sklearn for ARBITRARY (odd,
    non-divisible) row counts on both sides — the pad+mask discipline
    under rotation is the delicate part."""
    from sklearn.metrics.pairwise import euclidean_distances as sk_euc

    from dask_ml_tpu.core import shard_rows
    from dask_ml_tpu.metrics import euclidean_distances

    r = np.random.RandomState(seed)
    X = r.normal(size=(n1, d)).astype(np.float32)
    Y = r.normal(size=(n2, d)).astype(np.float32)
    ours = np.asarray(euclidean_distances(shard_rows(X), shard_rows(Y)))
    np.testing.assert_allclose(ours, sk_euc(X, Y), rtol=1e-3, atol=1e-4)


@settings(max_examples=15, deadline=None)
@given(st.integers(10, 200), st.integers(1, 9), st.integers(0, 2**16))
def test_tsqr_orthonormal_reconstructs(n, d, seed):
    """TSQR on ANY tall shape (odd row counts, non-divisible shards):
    Q^T Q = I, X = Q R, R upper-triangular."""
    import jax.numpy as jnp

    from dask_ml_tpu.core import shard_rows, unshard
    from dask_ml_tpu.linalg.tsqr import tsqr

    if n < d:
        n = d + 10
    r = np.random.RandomState(seed)
    X = r.normal(size=(n, d)).astype(np.float32)
    s = shard_rows(X)
    q, rr = tsqr(s)
    qh = np.asarray(q)[: n]  # unpad rows
    rr = np.asarray(rr)
    np.testing.assert_allclose(qh.T @ qh, np.eye(d), atol=5e-4)
    np.testing.assert_allclose(qh @ rr, X, atol=5e-4)
    # upper-triangular up to fp noise
    assert np.abs(np.tril(rr, -1)).max() < 1e-4


class TestAdversarialNumerics:
    """Round-4 adversarial tier (r3 verdict #8): the delicate paths under
    hostile inputs — extreme ranges, tie-heavy columns, huge weight and
    scale imbalance, near-singular conditioning."""

    @settings(max_examples=10, deadline=None)
    @given(st.integers(0, 2**31 - 1),
           st.sampled_from([1e6, 1e9, 1e12]))
    def test_sketch_extreme_ranges_ties_constants(self, seed, scale):
        import jax.numpy as jnp

        from dask_ml_tpu.preprocessing.data import _hist_quantiles

        rng = np.random.RandomState(seed)
        n = 2048
        x = np.empty((n, 3), np.float32)
        x[:, 0] = rng.normal(size=n)
        x[0, 0] = scale          # outliers BOTH signs: the window must
        x[1, 0] = -scale         # refine from a span straddling zero
        x[:, 1] = 3.75           # constant feature: lo == hi
        x[:, 2] = rng.choice(     # 5 distinct values, heavy ties
            np.array([-7.0, -1.0, 0.0, 2.5, 11.0], np.float32), size=n)
        probs = np.asarray([0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0],
                           np.float32)
        got = np.asarray(_hist_quantiles(
            jnp.asarray(x), jnp.ones(n, jnp.float32), jnp.asarray(probs)))
        want = np.quantile(x.astype(np.float64), probs, axis=0)
        # endpoints exact for every column
        np.testing.assert_allclose(got[0], want[0], rtol=1e-6)
        np.testing.assert_allclose(got[-1], want[-1], rtol=1e-6)
        # constant column: every quantile is the constant
        np.testing.assert_allclose(got[:, 1], 3.75, rtol=1e-6)
        # monotone nondecreasing in p (a sketch that inverts quantile
        # order is broken no matter the tolerance)
        assert (np.diff(got, axis=0) >= -1e-5 * np.maximum(
            np.abs(got[:-1]), 1.0)).all()
        # outlier column: interior quantiles resolve to IQR accuracy
        iqr0 = want[4, 0] - want[2, 0]
        err0 = np.abs(got[1:-1, 0] - want[1:-1, 0])
        assert (err0 <= iqr0 * 5e-2 + scale * 2e-6).all(), (err0, iqr0)
        # tie column: within one inter-value gap of the true quantile
        err2 = np.abs(got[1:-1, 2] - want[1:-1, 2])
        assert (err2 <= 18.0 * 5e-2 + 1e-3).all(), err2

    @settings(max_examples=10, deadline=None)
    @given(st.integers(0, 2**31 - 1))
    def test_scaler_partial_fit_huge_offset_chunks(self, seed):
        # Chan merges at offset 1e6 with unit variance: a naive
        # sum-of-squares accumulator loses ALL variance bits in fp32
        # (1e12 + 1 == 1e12); the merge must keep ~3 digits
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.preprocessing import StandardScaler

        rng = np.random.RandomState(seed)
        chunks = [
            (1e6 + rng.normal(size=(400, 3))).astype(np.float32)
            for _ in range(3)
        ]
        sc = StandardScaler()
        for c in chunks:
            sc.partial_fit(shard_rows(c))
        allx = np.concatenate(chunks).astype(np.float64)
        # rtol: anchor-shifted BLOCK moments (core.sharded._masked_anchor)
        # cut the error 10x (2.3% -> 0.24%); the residual is the merge
        # delta between f32-STORED chunk means, quantized to ulp(1e6) =
        # 0.0625 — the honest f32 state floor (delta² enters M2 scaled
        # by ~n), not a computation defect
        np.testing.assert_allclose(
            np.asarray(sc.mean_), allx.mean(0), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sc.var_), allx.var(0), rtol=1e-2)

    @settings(max_examples=10, deadline=None)
    @given(st.integers(0, 2**31 - 1))
    def test_gaussian_nb_partial_fit_huge_offset(self, seed):
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.naive_bayes import GaussianNB

        rng = np.random.RandomState(seed)
        y = (rng.rand(300) > 0.5).astype(np.float32)
        nb = GaussianNB()
        chunks = []
        for i in range(3):
            c = (1e6 + rng.normal(size=(300, 2))).astype(np.float32)
            chunks.append(c)
            nb.partial_fit(shard_rows(c), shard_rows(y),
                           classes=[0.0, 1.0])
        allx = np.concatenate(chunks).astype(np.float64)
        ally = np.concatenate([y, y, y])
        for ci, cls in enumerate([0.0, 1.0]):
            sel = allx[ally == cls]
            np.testing.assert_allclose(
                np.asarray(nb.theta_)[ci], sel.mean(0), rtol=1e-6)
            np.testing.assert_allclose(
                np.asarray(nb.var_)[ci], sel.var(0), rtol=5e-2,
                atol=1e-3)

    @settings(max_examples=8, deadline=None)
    @given(st.integers(0, 2**31 - 1))
    def test_minibatch_kmeans_kahan_mass_extreme_weights(self, seed):
        # k=1 makes assignment trivial, so the single center must equal
        # the GLOBAL weighted mean of everything streamed — including a
        # heavy 1e6-weight block followed by many 1e-6-weight blocks,
        # where a plain f32 mass accumulator freezes (1e6 + 1e-6 == 1e6
        # exactly in f32) and the late blocks would be silently dropped
        from dask_ml_tpu.cluster import MiniBatchKMeans
        from dask_ml_tpu.core import shard_rows

        rng = np.random.RandomState(seed)
        mbk = MiniBatchKMeans(n_clusters=1, init="random", random_state=0)
        xs, ws = [], []
        for i in range(6):
            x = rng.normal(size=(256, 3)).astype(np.float32) + 2.0 * i
            w = np.full(256, 1e6 if i == 0 else 1e-6, np.float32)
            xs.append(x)
            ws.append(w)
            mbk.partial_fit(shard_rows(x), sample_weight=w)
        allx = np.concatenate(xs).astype(np.float64)
        allw = np.concatenate(ws).astype(np.float64)
        want = np.average(allx, axis=0, weights=allw)
        got = np.asarray(mbk.cluster_centers_)[0]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        # sub-ulp mass loss is provably invisible in the f32 centers at
        # this ratio (the tiny blocks shift the mean by ~3e-11), so the
        # REAL assertion is on the Kahan pair: each 2.56e-4 block
        # increment is far below ulp(2.56e8)=16, a plain f32 accumulator
        # freezes and the lo word stays 0 — the pair must carry the full
        # 5*256*1e-6 of tiny mass
        hi, lo = np.asarray(mbk._counts, np.float64)
        total = float(hi.sum() + lo.sum())
        expect = float(allw.sum())
        heavy_only = 256.0 * 1e6
        tiny = expect - heavy_only  # 1.28e-3
        assert abs(total - expect) < 0.25 * tiny, (
            f"Kahan pair lost the sub-ulp mass: {total} vs {expect}"
        )

    @settings(max_examples=8, deadline=None)
    @given(st.integers(0, 2**31 - 1), st.sampled_from([1e4, 1e6]))
    def test_incremental_pca_huge_offset(self, seed, offset):
        # the anchor-shift bug class, fourth member: the Ross rank-update
        # accumulates mean/var and the SVD correction row from OFFSET-
        # scale f32 means; at offset 1e6 that cost 0.33% of var_ and
        # 0.1 deg of component subspace before the anchor fix (the
        # centered-data floor is ~1e-7 / 3e-5 deg).  Oracle: sklearn's
        # f64 IncrementalPCA on the SAME quantized f32 inputs, so input
        # quantization cancels and only computation error is measured.
        from scipy.linalg import subspace_angles
        from sklearn.decomposition import IncrementalPCA as SkIPCA

        from dask_ml_tpu.decomposition import IncrementalPCA

        rng = np.random.RandomState(seed)
        W = rng.normal(size=(4, 6))
        chunks = [
            (offset + rng.normal(size=(300, 4)) @ W
             + 0.1 * rng.normal(size=(300, 6))).astype(np.float32)
            for _ in range(4)
        ]
        ip = IncrementalPCA(n_components=3)
        sk = SkIPCA(n_components=3)
        for c in chunks:
            ip.partial_fit(c)
            sk.partial_fit(c.astype(np.float64))
        allx = np.concatenate(chunks).astype(np.float64)
        np.testing.assert_allclose(
            np.asarray(ip.var_), allx.var(0), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(ip.explained_variance_), sk.explained_variance_,
            rtol=1e-4)
        angle = np.degrees(subspace_angles(
            np.asarray(ip.components_).T, sk.components_.T)).max()
        assert angle < 0.01, f"component subspace drifted {angle} deg"

    @settings(max_examples=10, deadline=None)
    @given(st.integers(0, 2**31 - 1),
           st.sampled_from([1e4, 1e6, 1e8]),
           st.sampled_from(["householder", "cholqr2"]))
    def test_tsqr_adversarial_conditioning(self, seed, cond, strategy):
        # near-collinear + wildly scaled columns: Householder-based TSQR
        # is backward stable, so Q must stay orthonormal REGARDLESS of
        # conditioning, and QR must reconstruct X columnwise.  The
        # cholqr2 strategy must meet the SAME bar at every conditioning —
        # its deviation guard routes these inputs to the Householder body
        # (linalg/tsqr.py), and this property is what holds it to that.
        import jax.numpy as jnp

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linalg.tsqr import tsqr

        rng = np.random.RandomState(seed)
        n, d = 333, 5
        base = rng.normal(size=(n,))
        X = np.stack([
            base,
            base + rng.normal(size=n) / cond,   # collinear to 1/cond
            rng.normal(size=n) * 1e8,           # huge scale
            rng.normal(size=n) * 1e-8,          # tiny scale
            rng.normal(size=n),
        ], axis=1).astype(np.float32)
        q, r = tsqr(shard_rows(X), strategy=strategy)
        qh = np.asarray(q)[:n].astype(np.float64)
        rr = np.asarray(r).astype(np.float64)
        np.testing.assert_allclose(qh.T @ qh, np.eye(d), atol=5e-4)
        # columnwise reconstruction: tolerance scales with column norm
        rec = qh @ rr
        colnorm = np.linalg.norm(X.astype(np.float64), axis=0)
        err = np.abs(rec - X).max(axis=0)
        assert (err <= 5e-6 * colnorm + 1e-10).all(), (err, colnorm)
        assert np.abs(np.tril(rr, -1)).max() < 1e-4 * max(colnorm)


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2**31 - 1), st.sampled_from([1e-8, 1e-4, 1e4, 1e8]))
def test_euclidean_scale_invariance(seed, scale):
    """d(s·X, s·Y) == s·d(X, Y): the cancellation guard's flagging
    threshold is RELATIVE (d² < τ·(‖x‖²+‖y‖²)), so the safe path must
    behave identically at any uniform scale — including scales where the
    absolute cancellation error alone would dwarf the distances."""
    from dask_ml_tpu.core import shard_rows
    from dask_ml_tpu.metrics import euclidean_distances

    r = np.random.RandomState(seed)
    X = r.normal(size=(33, 4)).astype(np.float32)
    Y = np.vstack([X[:11] + 1e-6 * r.normal(size=(11, 4)).astype(np.float32),
                   r.normal(size=(10, 4)).astype(np.float32)])
    base = np.asarray(euclidean_distances(shard_rows(X), shard_rows(Y)))
    scaled = np.asarray(euclidean_distances(
        shard_rows((X * scale).astype(np.float32)),
        shard_rows((Y * scale).astype(np.float32))))
    np.testing.assert_allclose(scaled, base * scale, rtol=2e-3,
                               atol=scale * 1e-6)


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2**31 - 1), st.sampled_from([1e3, 1e5, 1e6]))
def test_ring_pairwise_huge_offsets(seed, offset):
    """Both-sharded (ppermute ring) distances on data whose mean offset
    dwarfs its spread — the anchor-shift bug class (round 4 found it in
    the moment path; round 5's fix centers the gemm expansion).  The
    ring must match float64 sklearn closely AND must not silently
    abandon the gemm fast path (correctness checked here; the fast-path
    retention is the translation-invariance of the centered expansion)."""
    from sklearn.metrics.pairwise import euclidean_distances as sk_euc

    from dask_ml_tpu.core import shard_rows
    from dask_ml_tpu.metrics import euclidean_distances

    r = np.random.RandomState(seed)
    n1, n2, d = 41, 23, 4
    X = (r.normal(size=(n1, d)) + offset).astype(np.float32)
    Y = (r.normal(size=(n2, d)) + offset).astype(np.float32)
    ours = np.asarray(euclidean_distances(shard_rows(X), shard_rows(Y)))
    ref = sk_euc(X.astype(np.float64), Y.astype(np.float64))
    # fp32 inputs at offset 1e6 carry ~0.06 quantization in each
    # coordinate; the comparison tolerance must absorb input rounding,
    # not mask algorithmic cancellation (which would be O(offset))
    tol = 3e-3 * np.sqrt(d) * max(offset * 1.2e-7, 1e-6) * 50 + 5e-3
    assert np.max(np.abs(ours - ref)) < max(tol, 0.05 * ref.mean())


class TestAdversarialSolvers:
    @settings(max_examples=6, deadline=None)
    @given(st.integers(0, 2**31 - 1),
           st.sampled_from([1e-3, 1.0, 1e3]),
           st.sampled_from([0.0, 1e3]))
    def test_admm_converges_under_rho_and_scale_extremes(
            self, seed, rho, offset):
        """ADMM's consensus splitting under adversarial conditioning:
        penalty rho 6 orders of magnitude apart, columns scaled
        1e-2..1e2, and an optional 1e3 mean offset.  The solve must stay
        finite and actually classify (the inner L-BFGS sees a badly
        scaled local subproblem; the Boyd dual update must still
        converge).  Reference: ``dask_glm/algorithms.py :: admm``."""
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import LogisticRegression

        rng = np.random.RandomState(seed % (2**31 - 1))
        n, d = 192, 5
        X0 = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32)
        y = (X0 @ w > 0).astype(np.float32)
        scales = np.logspace(-2, 2, d).astype(np.float32)
        Xs = (X0 * scales + offset).astype(np.float32)

        sX, sy = shard_rows(Xs), shard_rows(y)
        lr = LogisticRegression(
            solver="admm", max_iter=150,
            solver_kwargs={"rho": float(rho), "inner_iter": 40},
        ).fit(sX, sy)
        b_full = np.asarray(lr.betas_[0])
        assert np.all(np.isfinite(b_full)), (rho, offset)
        # the oracle is OBJECTIVE sub-optimality vs the L-BFGS solution
        # of the same regularized problem — accuracy is a discontinuous
        # proxy that can move 4 points inside ADMM's documented
        # "moderate accuracy" band (Boyd reltol=1e-2; measured: at
        # rho=1e3 the converged objective sits 1.0% above the optimum
        # while accuracy drops 0.77 vs 0.81).  The enforced bands are
        # below, calibrated per offset regime.
        import jax.numpy as jnp

        from dask_ml_tpu.linear_model.utils import add_intercept
        from dask_ml_tpu.solvers import Logistic
        from dask_ml_tpu.solvers.regularizers import L2

        ref = LogisticRegression(solver="lbfgs", max_iter=300).fit(sX, sy)
        Xi = add_intercept(sX)

        def objective(beta):
            return float(
                Logistic.loss(jnp.asarray(beta), Xi.data, sy.data, Xi.mask)
                + L2.penalty(jnp.asarray(beta), 1.0)
            )

        obj_admm = objective(b_full)
        obj_ref = objective(np.asarray(ref.betas_[0]))
        # band calibration (measured sweep over seeds × rho × offset):
        # at offset 0 every corner lands within 2.2% of the oracle; at
        # offset 1e3 the fp32 ORACLE ITSELF is only certifiable to
        # ~±10% (L-BFGS sometimes sits 4% ABOVE the ADMM solution
        # there — condition ~1e6 design), so the band must absorb the
        # oracle's own noise.  The failure modes this test exists for —
        # divergence, premature stop at untamed rho, the r5 fixed-rho
        # stall — all produce far larger gaps or non-finite betas.
        band = 1.08 if offset == 0.0 else 1.20
        assert obj_admm <= obj_ref * band + 1e-3, (
            obj_admm, obj_ref, rho, offset)
        # catastrophe floor on the classifier itself
        acc = float(lr.score(sX, sy))
        assert acc >= 0.52, (acc, rho, offset)


@settings(max_examples=12, deadline=None)
@given(st.integers(2, 27), st.integers(2, 4), st.integers(0, 2**31 - 1))
def test_hyperband_executes_its_own_metadata(R, eta, seed):
    """The crown-jewel contract across the whole (max_iter,
    aggressiveness) plane, not just the documented examples: the
    EXECUTED schedule (metadata_) must equal the pre-fit bracket math
    (metadata) whenever the parameter space is large enough to fill
    every bracket.  Reference: ``dask_ml/model_selection/_hyperband.py
    :: metadata`` vs ``metadata_``."""
    from dask_ml_tpu.model_selection import HyperbandSearchCV
    from dask_ml_tpu.model_selection.utils_test import LinearFunction

    rng_l = np.random.RandomState(seed % (2**31 - 1))
    X = rng_l.normal(size=(120, 3)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    hb = HyperbandSearchCV(
        LinearFunction(),
        # 200 distinct slopes: no bracket can exhaust the space
        {"slope": list(rng_l.uniform(0.1, 3.0, size=200))},
        max_iter=R, aggressiveness=eta, random_state=0,
    )
    hb.fit(X, y)
    assert hb.metadata_["n_models"] == hb.metadata["n_models"]
    assert (hb.metadata_["partial_fit_calls"]
            == hb.metadata["partial_fit_calls"])
    assert hb.metadata_["brackets"] == hb.metadata["brackets"]


@settings(max_examples=10, deadline=None)
@given(st.integers(0, 2**31 - 1), st.integers(1, 4), st.integers(2, 6))
def test_truncated_svd_streamed_matches_dense(seed, n_blocks, k):
    """fit_streamed (multi-pass randomized range finder over a sparse
    block stream) must agree with the dense TSQR fit on singular values
    and subspace — any block partition, any rank."""
    import scipy.sparse as sp

    from dask_ml_tpu.decomposition import TruncatedSVD

    rng_l = np.random.RandomState(seed % (2**31 - 1))
    d = k + rng_l.randint(2, 6)
    n = n_blocks * rng_l.randint(8, 20)
    X = rng_l.normal(size=(n, d)).astype(np.float32)
    X[rng_l.rand(n, d) < 0.5] = 0.0  # sparse-ish
    bounds = np.linspace(0, n, n_blocks + 1, dtype=int)
    blocks = lambda: (sp.csr_matrix(X[a:b])  # noqa: E731
                      for a, b in zip(bounds[:-1], bounds[1:]))

    dense = TruncatedSVD(n_components=k, random_state=0).fit(X)
    streamed = TruncatedSVD(n_components=k, random_state=0)
    streamed.fit_streamed(blocks, n_features=d)
    np.testing.assert_allclose(
        np.asarray(streamed.singular_values_),
        np.asarray(dense.singular_values_), rtol=2e-2, atol=1e-3)
    # subspace agreement (sign/rotation-invariant): V_s V_s^T == V_d V_d^T
    Vs = np.asarray(streamed.components_, np.float64)
    Vd = np.asarray(dense.components_, np.float64)
    np.testing.assert_allclose(Vs.T @ Vs, Vd.T @ Vd, atol=5e-2)


@settings(max_examples=20, deadline=None)
@given(st.integers(0, 2**31 - 1),
       st.sampled_from(["str", "int", "mixed_len"]),
       st.integers(2, 6))
def test_encoder_roundtrip_any_categories(seed, kind, n_cat):
    """OneHot/Ordinal fit → transform → inverse_transform is the
    identity for ANY category alphabet (unicode, negative ints,
    shared-prefix strings), and categories_ matches sklearn's."""
    from sklearn.preprocessing import OrdinalEncoder as SkOrd

    from dask_ml_tpu.preprocessing import OneHotEncoder, OrdinalEncoder

    rng_l = np.random.RandomState(seed % (2**31 - 1))
    if kind == "str":
        alphabet = np.array(
            ["α", "beta", "Ω", "zz", "a b", ""][:n_cat], dtype=object)
    elif kind == "int":
        alphabet = np.array([-5, -1, 0, 3, 7, 100][:n_cat])
    else:
        alphabet = np.array(
            ["x", "xx", "xxx", "xxxx", "y", "xy"][:n_cat], dtype=object)
    n = int(rng_l.randint(n_cat, 40))
    col = alphabet[rng_l.randint(0, n_cat, size=n)]
    # every category present at least once (fit must see the alphabet)
    col[:n_cat] = alphabet
    X = col.reshape(-1, 1)

    for enc in (OneHotEncoder(sparse_output=False)
                if "sparse_output" in OneHotEncoder().get_params()
                else OneHotEncoder(), OrdinalEncoder()):
        enc.fit(X)
        out = enc.transform(X)
        try:
            import scipy.sparse as sp

            if sp.issparse(out):
                out = out.toarray()
        except ImportError:
            pass
        back = np.asarray(enc.inverse_transform(np.asarray(out)))
        assert (back.ravel() == col).all(), (kind, type(enc).__name__)
    ref = SkOrd().fit(X)
    ours = OrdinalEncoder().fit(X)
    np.testing.assert_array_equal(
        np.asarray(ours.categories_[0]), np.asarray(ref.categories_[0]))


@settings(max_examples=15, deadline=None)
@given(st.integers(0, 2**31 - 1), st.integers(1, 12))
def test_count_vectorizer_matches_sklearn(seed, n_docs):
    """CountVectorizer parity on random small corpora: same vocabulary,
    same counts (the reference wraps sklearn's analyzer; so do we —
    parity must be exact)."""
    from sklearn.feature_extraction.text import (
        CountVectorizer as SkCV,
    )

    from dask_ml_tpu.feature_extraction import CountVectorizer

    rng_l = np.random.RandomState(seed % (2**31 - 1))
    words = ["apple", "banana", "cat", "dog", "egg", "fish", "goat"]
    docs = [
        " ".join(rng_l.choice(words,
                              size=rng_l.randint(0, 8)).tolist())
        for _ in range(n_docs)
    ]
    if not any(d.strip() for d in docs):
        docs[0] = "apple"
    ours = CountVectorizer().fit(docs)
    ref = SkCV().fit(docs)
    assert ours.vocabulary_ == ref.vocabulary_
    a = np.asarray(ours.transform(docs).todense())
    b = np.asarray(ref.transform(docs).todense())
    np.testing.assert_array_equal(a, b)

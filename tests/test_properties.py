"""Property-based pins for the algebraically delicate paths: the
two-level roc_auc prefix sum and the weight-folding helper (round 3).

Bounded example counts keep the suite fast; the properties (exact sklearn
equality under ties/weights, duplication-equivalence of integer weights)
are the invariants hand-picked examples keep missing."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402


@st.composite
def _labeled_scores(draw):
    n = draw(st.integers(min_value=4, max_value=120))
    t = draw(st.lists(st.integers(0, 1), min_size=n, max_size=n))
    if len(set(t)) < 2:
        t[0], t[1] = 0, 1
    # coarse rounding makes heavy ties likely
    s = draw(st.lists(
        st.integers(min_value=-5, max_value=5), min_size=n, max_size=n
    ))
    w = draw(st.lists(
        st.floats(min_value=0.1, max_value=4.0, allow_nan=False),
        min_size=n, max_size=n,
    ))
    return np.asarray(t), np.asarray(s, np.float32), np.asarray(w, np.float32)


class TestRocAucProperties:
    @settings(max_examples=30, deadline=None)
    @given(_labeled_scores())
    def test_matches_sklearn_under_ties_and_weights(self, tsw):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t, s, w = tsw
        ours = dm.roc_auc_score(t, s, sample_weight=w)
        ref = skm.roc_auc_score(t, s, sample_weight=w)
        assert ours == pytest.approx(ref, abs=1e-5)

    @settings(max_examples=15, deadline=None)
    @given(_labeled_scores())
    def test_multiblock_equals_singleblock(self, tsw):
        from dask_ml_tpu.metrics import classification as cl

        t, s, w = tsw
        one = cl.roc_auc_score(t, s, sample_weight=w)
        saved = cl._AUC_BLOCK
        cl._AUC_BLOCK = 8  # force many blocks (restored below)
        try:
            many = cl.roc_auc_score(t, s, sample_weight=w)
        finally:
            cl._AUC_BLOCK = saved
        assert one == pytest.approx(many, abs=1e-6)


class TestEffectiveMaskProperties:
    @settings(max_examples=25, deadline=None)
    @given(
        # weight 0 included: a zero-weight row must drop out entirely,
        # exactly like a row repeated zero times
        st.lists(st.integers(0, 3), min_size=3, max_size=40),
        st.lists(st.integers(0, 2), min_size=3, max_size=40),
    )
    def test_integer_weights_equal_duplication_in_weighted_mean(
        self, sw, labels
    ):
        # weighted mean with integer sample weights == unweighted mean of
        # the duplicated rows (the invariant behind every weighted fit)
        import jax.numpy as jnp

        from dask_ml_tpu.utils import effective_mask

        from hypothesis import assume

        n = min(len(sw), len(labels))
        sw, labels = np.asarray(sw[:n]), np.asarray(labels[:n], np.float32)
        assume(sw.sum() > 0)
        vals = labels * 2.0 - 1.0
        mask = jnp.ones(n, jnp.float32)
        w = effective_mask(mask, sample_weight=sw, n_samples=n)
        weighted_mean = float((jnp.asarray(vals) * w).sum() / w.sum())
        dup_mean = float(np.repeat(vals, sw).mean())
        assert weighted_mean == pytest.approx(dup_mean, abs=1e-5)

    @settings(max_examples=25, deadline=None)
    @given(st.lists(st.integers(0, 2), min_size=6, max_size=60))
    def test_balanced_classes_get_equal_total_weight(self, labels):
        import jax.numpy as jnp

        from dask_ml_tpu.utils import effective_mask

        labels = np.asarray(labels, np.float32)
        classes = np.unique(labels)
        if len(classes) < 2:
            return
        mask = jnp.ones(len(labels), jnp.float32)
        w = effective_mask(
            mask, jnp.asarray(labels), class_weight="balanced",
            classes=classes,
        )
        w = np.asarray(w)
        # balanced: every class's TOTAL weight equals n/K
        totals = [w[labels == c].sum() for c in classes]
        np.testing.assert_allclose(
            totals, len(labels) / len(classes), rtol=1e-5
        )

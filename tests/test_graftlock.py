"""graftlock: whole-program lock-order + shared-state ownership analysis
(static half, three graftlint rules) and the runtime lockset sanitizer
(dynamic half, ``sanitize/locks.py``) with its committed fifth baseline.

Mirrors test_graftlint.py's shape: the package gates itself (zero
findings; the dispatcher and programs-cache locks MUST be in the order
graph; the known-clean concurrent structures MUST resolve as guarded,
not merely unflagged), and every rule is exercised on positive
(flagging) and negative (clean) snippets.  The runtime gates prove the
detector live (both seeded faults caught, through the CLI and through
the env-seeded gate path ``tools/lint.sh --locks`` trusts) and the
committed ``tools/lock_baseline.json`` green, including the
``triple_plane`` workload — serve + search + ingest in one process —
with zero lock violations AND zero graftsan violations simultaneously.
"""

import os
import textwrap
import threading

import pytest

from dask_ml_tpu.analysis import lint_source
from dask_ml_tpu.analysis.core import Context, iter_py_files
from dask_ml_tpu.analysis.graph import Project
from dask_ml_tpu.analysis.rules.locks import _cycles, lock_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dask_ml_tpu")
LOCK_BASELINE = os.path.join(REPO, "tools", "lock_baseline.json")

LOCK_RULES = ("lock-order-cycle", "unguarded-shared-state",
              "lock-held-across-dispatch")


def lint(src, select=LOCK_RULES):
    return lint_source(textwrap.dedent(src), select=list(select))


def active(findings):
    return [f for f in findings if not f.suppressed]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


@pytest.fixture(scope="module")
def pkg_model():
    """ONE LockModel over the whole package, shared by the gate tests."""
    ctxs = [Context(open(p).read(), p) for p in iter_py_files([PKG])]
    project = Project(ctxs)
    for c in ctxs:
        c.project = project
    return lock_model(project)


def _state_facts(model, suffix):
    """(thread classes, common-lock set, atomic_only) for the one state
    whose identity ends with ``suffix`` — the analysis' own verdict, so
    the known-clean gates assert GUARDED, not merely unflagged."""
    for s, writes in model.state_writes():
        if not s.identity.endswith(suffix):
            continue
        classes = set()
        for _node, fn_key, _held, _atomic, _path in writes:
            classes |= model.classes_of(fn_key)
        non_atomic = [w for w in writes if not w[3]]
        common = None
        for _node, _key, held, _atomic, _path in non_atomic:
            common = held if common is None else (common & held)
        return classes, (common or set()), not non_atomic
    raise AssertionError(f"no state matching {suffix!r} in the model")


# ---------------------------------------------------------------------------
# the tier-1 self-gate: the package's lock plane must analyze clean
# ---------------------------------------------------------------------------

class TestPackageGate:
    def test_no_order_cycles_in_package(self, pkg_model):
        assert pkg_model.self_cycles == []
        assert _cycles(pkg_model.edges) == []

    def test_dispatcher_and_cache_locks_enter_the_graph(self, pkg_model):
        # the PR-13 one-dispatcher lock and the programs-cache
        # single-flight lock are the two locks most likely to meet a
        # blocking call — the analysis MUST see them (an analysis that
        # silently lost them would pass every other gate)
        locks = set(pkg_model.locks)
        assert any(i.endswith("_orchestrator._DISPATCHER_LOCK")
                   for i in locks), sorted(locks)
        assert any(i.endswith("CachedProgram._lock")
                   for i in locks), sorted(locks)
        endpoints = {n for e in pkg_model.edges for n in e}
        assert any("_DISPATCHER_LOCK" in n for n in endpoints)
        assert any("CachedProgram._lock" in n for n in endpoints)

    def test_registry_books_are_multiclass_and_guarded(self, pkg_model):
        classes, common, _ = _state_facts(
            pkg_model, "MetricsRegistry._instruments")
        assert len(classes) >= 2, classes  # serve/search/readers/main...
        assert any(c.endswith("MetricsRegistry._lock") for c in common)

    def test_cache_single_flight_is_guarded(self, pkg_model):
        classes, common, _ = _state_facts(
            pkg_model, "CachedProgram._inflight")
        assert "dask-ml-tpu-compile-ahead" in classes and "main" in classes
        assert any(c.endswith("CachedProgram._lock") for c in common)

    def test_supervisor_table_is_guarded(self, pkg_model):
        classes, common, _ = _state_facts(pkg_model, "supervisor._UNITS")
        assert len(classes) >= 2, classes
        assert any(c.endswith("supervisor._LOCK") for c in common)

    def test_flight_ring_is_deque_atomic(self, pkg_model):
        # lock-free by design (obs/flight.py): every write must be a
        # GIL-atomic deque mutation, which is the rule's exemption
        _classes, _common, atomic_only = _state_facts(
            pkg_model, "flight._RING")
        assert atomic_only

    def test_residency_registry_is_single_owner(self, pkg_model):
        # thread-confined, not locked: all mutation on the serve loop
        classes, _common, _ = _state_facts(
            pkg_model, "ModelRegistry._by_name")
        assert classes == {"dask-ml-tpu-serve"}, classes

    def test_package_has_zero_lock_findings(self, pkg_model):
        # the three rules' verdicts over the REAL package, via the same
        # model the fixture built (test_graftlint's full-package gate
        # already covers the engine path; this pins the lock plane)
        from dask_ml_tpu.analysis.rules.locks import (
            LockHeldAcrossDispatchRule,
            LockOrderCycleRule,
            UnguardedSharedStateRule,
        )

        project = pkg_model.project
        found = []
        for rule in (LockOrderCycleRule(), UnguardedSharedStateRule(),
                     LockHeldAcrossDispatchRule()):
            found.extend(f for f in rule.run_project(project)
                         if not f.suppressed)
        assert not found, "\n".join(f.render() for f in found)


# ---------------------------------------------------------------------------
# lock-order-cycle: positive / negative snippets
# ---------------------------------------------------------------------------

class TestLockOrderCycle:
    def test_flags_ab_ba_inversion(self):
        findings = lint("""
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def forward():
                with A:
                    with B:
                        pass

            def reverse():
                with B:
                    with A:
                        pass
        """)
        bad = active(findings)
        assert rule_ids(bad) == ["lock-order-cycle"]
        assert "reverse order" in bad[0].message

    def test_flags_interprocedural_cycle(self):
        findings = lint("""
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def take_b():
                with B:
                    pass

            def take_a():
                with A:
                    pass

            def forward():
                with A:
                    take_b()

            def reverse():
                with B:
                    take_a()
        """)
        assert rule_ids(active(findings)) == ["lock-order-cycle"]

    def test_flags_self_deadlock_on_plain_lock(self):
        findings = lint("""
            import threading

            L = threading.Lock()

            def relock():
                with L:
                    with L:
                        pass
        """)
        bad = active(findings)
        assert rule_ids(bad) == ["lock-order-cycle"]
        assert "re-acquired" in bad[0].message

    def test_consistent_order_is_clean(self):
        findings = lint("""
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A:
                    with B:
                        pass

            def two():
                with A:
                    with B:
                        pass
        """)
        assert not active(findings)

    def test_rlock_reentry_is_clean(self):
        findings = lint("""
            import threading

            L = threading.RLock()

            def relock():
                with L:
                    with L:
                        pass
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# unguarded-shared-state: positive / negative snippets
# ---------------------------------------------------------------------------

class TestUnguardedSharedState:
    def test_flags_cross_class_writes_with_no_lock(self):
        findings = lint("""
            import threading

            BOOK = {}

            def worker():
                BOOK["w"] = 1

            def start():
                t = threading.Thread(target=worker,
                                     name="dask-ml-tpu-pump")
                t.start()
                BOOK["m"] = 2
        """)
        bad = active(findings)
        assert rule_ids(bad) == ["unguarded-shared-state"]
        assert "BOOK" in bad[0].message
        assert "dask-ml-tpu-pump" in bad[0].message

    def test_flags_when_only_one_path_locks(self):
        findings = lint("""
            import threading

            BOOK = {}
            L = threading.Lock()

            def worker():
                with L:
                    BOOK["w"] = 1

            def start():
                t = threading.Thread(target=worker,
                                     name="dask-ml-tpu-pump")
                t.start()
                BOOK["m"] = 2
        """)
        assert rule_ids(active(findings)) == ["unguarded-shared-state"]

    def test_common_lock_on_every_path_is_clean(self):
        findings = lint("""
            import threading

            BOOK = {}
            L = threading.Lock()

            def worker():
                with L:
                    BOOK["w"] = 1

            def start():
                t = threading.Thread(target=worker,
                                     name="dask-ml-tpu-pump")
                t.start()
                with L:
                    BOOK["m"] = 2
        """)
        assert not active(findings)

    def test_single_owner_is_clean(self):
        findings = lint("""
            import threading

            BOOK = {}

            def worker():
                BOOK["w"] = 1
                BOOK["x"] = 2

            def start():
                t = threading.Thread(target=worker,
                                     name="dask-ml-tpu-pump")
                t.start()
        """)
        assert not active(findings)

    def test_atomic_deque_traffic_is_clean(self):
        # the flight-ring design: every write one GIL-atomic deque call
        findings = lint("""
            import threading
            from collections import deque

            RING = deque(maxlen=64)

            def worker():
                RING.append(1)

            def start():
                t = threading.Thread(target=worker,
                                     name="dask-ml-tpu-pump")
                t.start()
                RING.append(2)
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# lock-held-across-dispatch: positive / negative snippets
# ---------------------------------------------------------------------------

class TestLockHeldAcrossDispatch:
    def test_flags_sleep_under_lock(self):
        findings = lint("""
            import threading
            import time

            L = threading.Lock()

            def poll():
                with L:
                    time.sleep(0.1)
        """)
        bad = active(findings)
        assert rule_ids(bad) == ["lock-held-across-dispatch"]
        assert "sleep" in bad[0].message

    def test_flags_transitive_blocking_under_lock(self):
        findings = lint("""
            import threading

            L = threading.Lock()

            def drain(q):
                return q.get(timeout=5.0)

            def step(q):
                with L:
                    return drain(q)
        """)
        bad = active(findings)
        assert rule_ids(bad) == ["lock-held-across-dispatch"]
        assert "drain" in bad[0].message

    def test_snapshot_then_block_outside_is_clean(self):
        findings = lint("""
            import threading
            import time

            L = threading.Lock()
            BOOK = {}

            def poll():
                with L:
                    n = len(BOOK)
                time.sleep(0.1)
                return n
        """)
        assert not active(findings)

    def test_join_of_disjoint_thread_is_exempt(self):
        # the PR-13 dispatcher shape: joining a thread that provably
        # never wants the held lock is serialization, not deadlock
        findings = lint("""
            import threading

            L = threading.Lock()

            def work():
                pass

            def run():
                thread = threading.Thread(target=work, name="w")
                thread.start()
                with L:
                    thread.join()
        """)
        assert not active(findings)

    def test_join_of_lock_wanting_thread_is_flagged(self):
        findings = lint("""
            import threading

            L = threading.Lock()

            def work():
                with L:
                    pass

            def run():
                thread = threading.Thread(target=work, name="w")
                thread.start()
                with L:
                    thread.join()
        """)
        assert rule_ids(active(findings)) == ["lock-held-across-dispatch"]

    def test_join_of_unresolvable_thread_stays_flagged(self):
        # cannot prove disjointness -> keep the finding
        findings = lint("""
            import threading

            L = threading.Lock()

            def run(thread):
                with L:
                    thread.join()
        """)
        assert rule_ids(active(findings)) == ["lock-held-across-dispatch"]


# ---------------------------------------------------------------------------
# runtime half: monitor semantics, seeded faults, contention histograms
# ---------------------------------------------------------------------------

class TestLockMonitor:
    def test_inversion_flagged_once_per_pair(self):
        from dask_ml_tpu.sanitize import locks as rl

        with rl.instrumented_locks(book_metrics=False) as mon:
            rl.inject_inversion()
            rl.inject_inversion()  # same pair again: no duplicate flag
        rep = mon.report()
        inv = [v for v in rep["violations"]
               if v["kind"] == "order-inversion"]
        assert len(inv) == 1
        assert "reverse order" in inv[0]["detail"]
        assert "selftest.alpha -> selftest.beta" in rep["edges"]
        assert "selftest.beta -> selftest.alpha" in rep["edges"]

    def test_cross_thread_class_flagged(self):
        from dask_ml_tpu.sanitize import locks as rl

        with rl.instrumented_locks(book_metrics=False) as mon:
            rl.inject_cross_write()
        kinds = [v["kind"] for v in mon.report()["violations"]]
        assert kinds == ["cross-thread-class"]

    def test_host_thread_on_rostered_lock_is_clean(self):
        from dask_ml_tpu._locks import make_lock
        from dask_ml_tpu.sanitize import locks as rl

        with rl.instrumented_locks(book_metrics=False) as mon:
            with make_lock("serve.server"):  # roster admits "host"
                pass
        assert mon.report()["violations"] == []

    def test_monitor_books_wait_and_held_histograms(self):
        # satellite (a): lock.wait_s{name} / lock.held_s{name} in the
        # PR-7 registry
        from dask_ml_tpu._locks import make_lock
        from dask_ml_tpu.obs.metrics import registry
        from dask_ml_tpu.sanitize import locks as rl

        reg = registry()
        reg.reset("lock.")
        with rl.instrumented_locks():
            with make_lock("serve.server"):
                pass
        snap = reg.snapshot()["histograms"]
        assert snap["lock.wait_s{serve.server}"]["count"] >= 1
        assert snap["lock.held_s{serve.server}"]["count"] >= 1

    def test_monitors_do_not_nest(self):
        from dask_ml_tpu.sanitize import locks as rl

        with rl.instrumented_locks(book_metrics=False):
            with pytest.raises(RuntimeError, match="must not nest"):
                with rl.instrumented_locks(book_metrics=False):
                    pass  # pragma: no cover

    def test_arm_from_env_rejects_typos(self, monkeypatch):
        from dask_ml_tpu.sanitize import locks as rl

        monkeypatch.setenv(rl.MONITOR_ENV, "yess")
        with pytest.raises(ValueError, match=rl.MONITOR_ENV):
            rl.arm_from_env()
        monkeypatch.setenv(rl.MONITOR_ENV, "off")
        assert rl.arm_from_env() is None


# ---------------------------------------------------------------------------
# the committed fifth baseline + the CLI gate (tier-1 ratchet)
# ---------------------------------------------------------------------------

class TestLockBaselineGate:
    def test_committed_baseline_shape(self):
        from dask_ml_tpu.sanitize import locks as rl

        snap = rl.load(LOCK_BASELINE)
        assert snap["tool"] == "graftlock"
        assert "triple_plane" in snap["workloads"]
        # the whole graftsan smoke suite rides the lock suite
        from dask_ml_tpu.sanitize.smoke import WORKLOADS

        assert set(snap["workloads"]) == set(WORKLOADS) | {"triple_plane"}
        for name, m in snap["workloads"].items():
            assert m["violations"] == 0, name
        assert snap["edges"] == sorted(snap["edges"])

    def test_injected_inversion_fails_cli(self, capsys):
        from dask_ml_tpu.sanitize import locks as rl

        assert rl.main(["--inject-inversion"]) == 1
        assert "seeded" in capsys.readouterr().out

    def test_injected_cross_write_fails_cli(self, capsys):
        from dask_ml_tpu.sanitize import locks as rl

        assert rl.main(["--inject-cross-write"]) == 1

    def test_unknown_workload_is_tool_error(self, capsys):
        from dask_ml_tpu.sanitize import locks as rl

        assert rl.main(["--workloads", "nope"]) == 2

    def test_new_edge_fails_unobserved_edge_passes(self):
        from dask_ml_tpu.sanitize import locks as rl

        snap = {"version": rl._VERSION, "tool": "graftlock",
                "edges": ["a -> b"],
                "workloads": {"w": {"acquisitions": 1, "edge_count": 1,
                                    "violations": 0}}}
        # observed edge not in snapshot: a NEW way to deadlock -> fail
        res = {"w": {"acquisitions": 1, "edges": ["a -> b", "b -> c"],
                     "violations": 0, "violation_details": []}}
        delta = rl.compare(snap, res)
        assert delta["regressions"] and "b -> c" in delta["regressions"][0]
        # snapshot edge unobserved (warm jit cache): pass
        res2 = {"w": {"acquisitions": 1, "edges": [],
                      "violations": 0, "violation_details": []}}
        assert rl.is_clean(rl.compare(snap, res2))

    def test_gate_clean_on_subset_vs_committed_baseline(self, capsys):
        from dask_ml_tpu.sanitize import locks as rl

        assert rl.main(["--workloads", "sgd_stream_d0",
                        "--baseline", LOCK_BASELINE]) == 0
        assert "clean" in capsys.readouterr().out

    def test_env_seeded_inversion_fails_gate(self, capsys, monkeypatch):
        # the exact path `tools/lint.sh --locks` trusts: the fault rides
        # the normal ratchet invocation and MUST turn it red
        from dask_ml_tpu.sanitize import locks as rl

        monkeypatch.setenv(rl.INJECT_ENV, "inversion")
        assert rl.main(["--workloads", "sgd_stream_d0",
                        "--baseline", LOCK_BASELINE]) == 1
        assert "VIOLATIONS" in capsys.readouterr().out

    def test_env_seeded_cross_write_fails_gate(self, capsys, monkeypatch):
        from dask_ml_tpu.sanitize import locks as rl

        monkeypatch.setenv(rl.INJECT_ENV, "cross-write")
        assert rl.main(["--workloads", "sgd_stream_d0",
                        "--baseline", LOCK_BASELINE]) == 1

    def test_write_baseline_refuses_partial_suite(self, tmp_path, capsys):
        from dask_ml_tpu.sanitize import locks as rl

        out = tmp_path / "lock.json"
        assert rl.main(["--workloads", "sgd_stream_d0",
                        "--write-baseline", str(out)]) == 2
        assert not out.exists()


class TestTriplePlane:
    def test_triple_plane_clean_under_armed_graftsan(self):
        # serve + search + ingest in ONE process: zero lock violations
        # AND zero graftsan violations, simultaneously — the workload
        # the per-plane suites cannot produce
        from dask_ml_tpu.sanitize import locks as rl

        with rl.instrumented_locks() as mon:
            s = rl.triple_plane()
        rep = mon.report()
        assert rep["violations"] == [], rep["violations"]
        assert rep["acquisitions"] > 0
        assert s.violations == [], s.violations


# ---------------------------------------------------------------------------
# en-route concurrency fixes: regressions stay fixed
# ---------------------------------------------------------------------------

class _TattletaleLock:
    """Context-manager lock whose FIRST release lands a concurrent
    ``record()``'s field updates — the interleaving the old multi-
    acquisition ``Histogram.snapshot`` tore on (count bumped by the
    empty-check release, sum read bare afterwards)."""

    def __init__(self, hist):
        self._inner = threading.Lock()
        self._hist = hist
        self._fired = False

    def __enter__(self):
        self._inner.acquire()
        return self

    def __exit__(self, *exc):
        if not self._fired:
            self._fired = True
            self._hist.count += 1
            self._hist.sum += 100.0
        self._inner.release()
        return False


class TestHistogramSnapshotAtomicity:
    def test_snapshot_is_one_acquisition(self):
        from dask_ml_tpu.obs.metrics import Histogram

        h = Histogram()
        h.record(1.0)
        h._lock = _TattletaleLock(h)
        snap = h.snapshot()
        # one lock hold across every field read: the mutation staged at
        # release must not leak into THIS snapshot (the torn result was
        # count=1 with sum=101.0, or count=2/sum=1.0, depending on
        # which bare read interleaved)
        assert snap["count"] == 1
        assert snap["sum"] == 1.0
        assert snap["min"] == snap["max"] == 1.0


class _IntruderSanitizer:
    """Builds a Sanitizer whose violation log receives a concurrent
    intruder record immediately after every append — the race the old
    ``violations[-1]`` re-read in the fail-fast raisers lost."""

    def __new__(cls):
        from dask_ml_tpu.sanitize.core import Sanitizer

        class _S(Sanitizer):
            def _violation(self, kind, reg, thread, detail):
                rec = super()._violation(kind, reg, thread, detail)
                self.violations.append({
                    "kind": "intruder", "region": reg,
                    "thread": "someone-else",
                    "detail": "NOT THE REAL VIOLATION",
                })
                return rec

        return _S()


class TestViolationAttribution:
    def test_fail_fast_raiser_reports_its_own_violation(self):
        from dask_ml_tpu.sanitize.core import DispatchViolation

        s = _IntruderSanitizer()
        s._primary_ident = threading.get_ident()
        raised = []

        def _rogue():
            try:
                s._record_dispatch("prog")
            except DispatchViolation as e:
                raised.append(str(e))

        t = threading.Thread(target=_rogue, name="rogue-dispatcher")
        t.start()
        t.join()
        assert len(raised) == 1
        assert "rogue-dispatcher" in raised[0]
        assert "NOT THE REAL VIOLATION" not in raised[0]

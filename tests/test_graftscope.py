"""graftscope tests (ISSUE 10 tentpole): device-time accounting, the
live metrics endpoint, and the committed perf ratchet.

Covers the acceptance criteria: a depth-2 streamed SGD fit's Perfetto
export shows a device lane whose busy slices overlap the host
parse/stage slices and ``run_report()["device"]["utilization"]`` > 0.5
on that fit; ``GET /metrics`` during a fit returns valid Prometheus
text including ``device_busy_s`` and ``pipeline_block_s`` quantiles
from a supervisor-registered, graftsan-clean endpoint thread; and the
perf ratchet (``tools/lint.sh --perf``) fails on an injected slowdown
and on a stale baseline entry while the committed
``tools/perf_baseline.json`` gates green.
"""

import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from dask_ml_tpu import diagnostics, obs
from dask_ml_tpu.obs import perf, scope, serve
from dask_ml_tpu.pipeline import stream_partial_fit
from dask_ml_tpu.resilience import supervisor


@pytest.fixture(autouse=True)
def _clean_books():
    """Book isolation; also stop any endpoint a test left running, and
    keep span recording armed (the conftest arms it session-wide, but
    an earlier suite's A/B may have left it disabled — the acceptance
    tests need host spans next to the device lane)."""
    if not obs.enabled():
        obs.enable()
    diagnostics.reset()
    yield
    serve.stop()
    diagnostics.reset()


class _Leaf:
    """A fake dispatch output leaf with a settable readiness flag."""

    def __init__(self, ready=False):
        self._ready = ready

    def is_ready(self):
        return self._ready


class _RaisingLeaf:
    def is_ready(self):
        raise RuntimeError("donated buffer")


def _sgd_blocks(n_blocks=8, rows=16384, dim=32, parse_s=0.001, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(rows, dim)).astype(np.float32)
    w = rng.normal(size=dim)
    y = (X @ w > 0).astype(np.int32)
    for _ in range(n_blocks):
        if parse_s:
            time.sleep(parse_s)
        yield X, y


def _fit_streamed_sgd(depth=2, n_blocks=8):
    from dask_ml_tpu.linear_model import SGDClassifier

    model = SGDClassifier(random_state=0)
    stream_partial_fit(model, _sgd_blocks(n_blocks), depth=depth,
                       fit_kwargs={"classes": np.array([0, 1])})
    return model


# -- device-time accounting (obs/scope.py) -------------------------------

class TestScope:
    def test_track_and_sweep_close_interval(self):
        leaf = _Leaf(ready=False)
        t0 = time.perf_counter()
        assert scope.track("prog.a", t0, [leaf])
        assert scope.pending_count() == 1
        leaf._ready = True
        scope.sweep()
        assert scope.pending_count() == 0
        ivs = [iv for iv in scope.timeline() if iv["program"] == "prog.a"]
        assert len(ivs) == 1 and not ivs[0].get("open")
        assert ivs[0]["t1"] >= ivs[0]["t0"] == t0
        reg = obs.registry()
        assert reg.counter("device.dispatches", "prog.a").value == 1
        assert reg.histogram("device.busy_s", "prog.a").count == 1

    def test_tracer_outputs_are_not_dispatches(self):
        # leaves without is_ready (tracers — a program inlining into an
        # outer trace) must not open an interval or count a dispatch
        assert not scope.track("prog.traced", time.perf_counter(),
                               [object(), 3.0])
        assert scope.pending_count() == 0
        assert obs.registry().family("device.dispatches") == {}

    def test_raising_is_ready_counts_as_ready(self):
        # a donated buffer's is_ready raises: treat as ready, the
        # consuming program's own interval keeps the lane continuous
        assert scope.track("prog.donate", time.perf_counter(),
                           [_RaisingLeaf()])
        scope.sweep()
        assert scope.pending_count() == 0

    def test_open_interval_visible_in_timeline(self):
        leaf = _Leaf(ready=False)
        scope.track("prog.open", time.perf_counter(), [leaf])
        ivs = [iv for iv in scope.timeline()
               if iv["program"] == "prog.open"]
        assert len(ivs) == 1 and ivs[0]["open"] is True
        leaf._ready = True  # let the sampler retire it

    def test_settle_times_out_on_wedged_program(self):
        leaf = _Leaf(ready=False)
        scope.track("prog.wedged", time.perf_counter(), [leaf])
        assert scope.settle(timeout_s=0.05) is False
        leaf._ready = True
        assert scope.settle(timeout_s=2.0) is True

    def test_absorb_is_reentrant_and_thread_local(self):
        assert not scope.absorbed()
        with scope.absorb():
            assert scope.absorbed()
            with scope.absorb():
                assert scope.absorbed()
            assert scope.absorbed()
        assert not scope.absorbed()
        seen = []
        t = threading.Thread(
            target=lambda: seen.append(scope.absorbed()))
        with scope.absorb():
            t.start()
            t.join()
        assert seen == [False]  # absorption never leaks across threads

    def test_cursor_scopes_device_report(self):
        a = _Leaf(ready=True)
        scope.track("prog.before", time.perf_counter(), [a])
        scope.sweep()
        cur = scope.cursor()
        b = _Leaf(ready=True)
        scope.track("prog.after", time.perf_counter(), [b])
        scope.sweep()
        rep = scope.device_report(since=cur)
        assert set(rep["programs"]) == {"prog.after"}
        assert rep["dispatches"] == 1

    def test_device_report_merges_overlaps_and_ranks_gaps(self):
        # hand-build the timeline through the public API: two
        # overlapping busy intervals, a gap, then a third
        base = time.perf_counter()
        for name, dt0, dur in (("p", 0.00, 0.10), ("q", 0.05, 0.10),
                               ("p", 0.45, 0.05)):
            leaf = _Leaf(ready=True)
            with scope._COND:
                scope._PENDING.append(
                    scope._Pending(name, base + dt0, [leaf], scope._SEQ))
                scope._SEQ += 1
                scope._sweep_locked(base + dt0 + dur)
        rep = scope.device_report()
        assert rep["dispatches"] == 3
        assert rep["busy_s"] == pytest.approx(0.20, abs=1e-6)
        assert rep["window_s"] == pytest.approx(0.50, abs=1e-6)
        assert rep["idle_s"] == pytest.approx(0.30, abs=1e-6)
        assert rep["utilization"] == pytest.approx(0.40, abs=1e-3)
        assert len(rep["idle_gaps"]) == 1
        assert rep["idle_gaps"][0]["dur_s"] == pytest.approx(0.30,
                                                            abs=1e-6)
        assert rep["programs"]["p"]["dispatches"] == 2

    def test_empty_report_shape(self):
        rep = scope.device_report()
        assert rep == {"dispatches": 0, "busy_s": 0.0, "window_s": 0.0,
                       "idle_s": 0.0, "utilization": 0.0,
                       "idle_gaps": [], "programs": {}, "pending": 0}

    def test_reset_drops_timeline_keeps_nothing_pending(self):
        scope.track("prog.r", time.perf_counter(), [_Leaf(ready=True)])
        scope.sweep()
        assert scope.timeline()
        scope.reset()
        assert scope.timeline() == []
        assert scope.pending_count() == 0

    def test_sampler_closes_interval_without_host_activity(self):
        """The end of a busy period is found even when the host goes
        quiet: no further track/sweep calls — the sampler thread must
        retire the pending interval on its own."""
        leaf = _Leaf(ready=False)
        scope.track("prog.sampler", time.perf_counter(), [leaf])
        leaf._ready = True
        deadline = time.monotonic() + 5.0
        while scope.pending_count() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert scope.pending_count() == 0
        sampler = supervisor.lookup(scope.SCOPE_THREAD_NAME)
        assert sampler is not None or scope._SAMPLER.is_alive()

    def test_sampler_thread_is_host_only_named(self):
        from dask_ml_tpu.analysis.rules._spmd import (
            HOST_ONLY_THREAD_NAMES)

        assert scope.SCOPE_THREAD_NAME in HOST_ONLY_THREAD_NAMES


# -- acceptance: streamed fit occupancy + the Perfetto device lane -------

class TestStreamedFitAcceptance:
    def test_depth2_sgd_utilization_and_device_lane_overlap(self):
        """Acceptance criterion: export_perfetto() of a depth-2
        streamed SGD fit shows a device lane whose busy slices overlap
        the host parse/stage slices, and
        run_report()["device"]["utilization"] > 0.5 on that fit."""
        _fit_streamed_sgd(depth=2)  # warmup: compiles happen here
        diagnostics.reset()
        _fit_streamed_sgd(depth=2)

        rep = diagnostics.run_report()
        dev = rep["device"]
        assert dev["dispatches"] >= 8
        assert dev["utilization"] > 0.5, dev
        assert dev["busy_s"] > 0
        assert dev["idle_s"] == pytest.approx(
            dev["window_s"] - dev["busy_s"], abs=1e-5)
        assert len(dev["idle_gaps"]) <= 3
        # per-program attribution carries the cache's registry names
        assert any(p["busy_s"] > 0 for p in dev["programs"].values())

        trace = obs.export_perfetto()
        events = trace["traceEvents"]
        names = [e for e in events if e.get("ph") == "M"]
        assert any(e["args"]["name"] == "device" and e["tid"] == 0
                   for e in names)
        device = [e for e in events if e.get("ph") == "X"
                  and e["tid"] == 0]
        host = [e for e in events if e.get("ph") == "X" and e["tid"] != 0
                and e["name"] in ("pipeline.parse", "pipeline.stage")]
        assert device and host
        def overlaps(a, b):
            return a["ts"] < b["ts"] + b["dur"] and \
                b["ts"] < a["ts"] + a["dur"]
        assert any(overlaps(d, h) for d in device for h in host), (
            "no device slice overlaps a host parse/stage slice")
        json.dumps(trace)  # the whole thing is valid trace_event JSON

    def test_device_section_in_run_report_resets(self):
        _fit_streamed_sgd(depth=0, n_blocks=2)
        assert diagnostics.run_report()["device"]["dispatches"] > 0
        diagnostics.reset()
        assert diagnostics.run_report()["device"]["dispatches"] == 0

    def test_depth0_also_accounts_device_time(self):
        # the cache choke point covers the serial path identically
        diagnostics.reset()
        _fit_streamed_sgd(depth=0, n_blocks=3)
        dev = diagnostics.run_report()["device"]
        assert dev["dispatches"] >= 3
        assert dev["busy_s"] > 0


# -- Prometheus text format (obs/serve.py) -------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (NaN|[-+0-9.e]+)$')


def _assert_valid_prometheus(text):
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            assert re.match(
                r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                r"(counter|gauge|summary)$", line), line
        else:
            assert _SAMPLE_RE.match(line), line


class TestPrometheusText:
    def test_counter_gauge_summary_shapes(self):
        reg = obs.registry()
        reg.counter("unit.count", "a").inc(3)
        reg.gauge("unit.depth").set(2.5)
        h = reg.histogram("unit.lat_s")
        for v in (0.01, 0.02, 0.03):
            h.record(v)
        text = serve.prometheus_text()
        _assert_valid_prometheus(text)
        assert "# TYPE unit_count counter" in text
        assert 'unit_count{tag="a"} 3.0' in text
        assert "# TYPE unit_depth gauge" in text
        assert "# TYPE unit_lat_s summary" in text
        assert 'unit_lat_s{quantile="0.5"}' in text
        assert 'unit_lat_s{quantile="0.99"}' in text
        assert "unit_lat_s_sum" in text
        assert "unit_lat_s_count 3" in text

    def test_label_value_escaping(self):
        """Satellite: Prometheus text-format escaping of label values —
        tag names carrying backslash, double-quote, and newline must
        round-trip per the exposition format's three escapes."""
        reg = obs.registry()
        reg.counter("unit.esc", 'say "hi"\nback\\slash').inc()
        text = serve.prometheus_text()
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("unit_esc{"))
        assert '\\"hi\\"' in line
        assert "\\n" in line and "\n" not in line[:-1].replace(
            "\\n", "")
        assert "\\\\slash" in line
        # the raw newline must NOT appear inside the sample line
        assert line == line.strip()
        _assert_valid_prometheus(text)

    def test_name_mangling(self):
        reg = obs.registry()
        reg.counter("1weird.name-x").inc()
        text = serve.prometheus_text()
        assert "# TYPE _1weird_name_x counter" in text

    def test_empty_histogram_quantiles_are_nan(self):
        obs.registry().histogram("unit.empty_s")
        text = serve.prometheus_text()
        assert 'unit_empty_s{quantile="0.5"} NaN' in text
        _assert_valid_prometheus(text)


# -- the live endpoint ---------------------------------------------------

def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return resp.status, dict(resp.headers), resp.read().decode()
    except urllib.error.HTTPError as e:  # 4xx/5xx still carry a body
        return e.code, dict(e.headers), e.read().decode()


class TestMetricsEndpoint:
    def test_scrape_during_fit_serves_device_and_block_quantiles(self):
        """Acceptance criterion: curl localhost:$PORT/metrics during a
        fit returns valid Prometheus text including device_busy_s and
        pipeline_block_s quantiles from a supervisor-registered
        endpoint."""
        srv = serve.start(port=0)
        assert srv is not None and srv.port > 0
        _fit_streamed_sgd(depth=2, n_blocks=4)  # warm compiles

        scraped = {}

        def scrape_mid_fit():
            scraped["mid"] = _get(srv.port, "/metrics")

        t = threading.Thread(target=scrape_mid_fit)
        gen = _sgd_blocks(6)

        def blocks_with_scrape():
            for i, item in enumerate(gen):
                if i == 3:
                    t.start()
                yield item

        from dask_ml_tpu.linear_model import SGDClassifier

        stream_partial_fit(SGDClassifier(random_state=0),
                           blocks_with_scrape(), depth=2,
                           fit_kwargs={"classes": np.array([0, 1])})
        t.join(timeout=10)
        status, headers, text = scraped["mid"]
        assert status == 200
        assert "version=0.0.4" in headers["Content-Type"]
        _assert_valid_prometheus(text)
        assert "# TYPE device_busy_s summary" in text
        assert re.search(r'device_busy_s\{[^}]*quantile="0\.99"\}', text)
        assert "# TYPE pipeline_block_s summary" in text
        assert re.search(r'pipeline_block_s\{quantile="0\.5"\}', text)
        assert "device_dispatches" in text

        hb = supervisor.lookup(serve.METRICS_THREAD_NAME)
        assert hb is not None and hb.verdict() == "healthy"
        assert hb.beats >= 1  # one beat per request served

    def test_healthz_ok_and_degraded(self):
        srv = serve.start(port=0)
        status, _, body = _get(srv.port, "/healthz")
        assert status == 200
        verdict = json.loads(body)
        assert verdict["ok"] is True
        assert serve.METRICS_THREAD_NAME not in verdict["dead"]

        # a supervised unit whose thread died flips the probe to 503
        dead_thread = threading.Thread(target=lambda: None)
        dead_thread.start()
        dead_thread.join()
        hb = supervisor.register("unit-under-test", "pipeline",
                                 thread=dead_thread)
        try:
            status, _, body = _get(srv.port, "/healthz")
            assert status == 503
            assert "unit-under-test" in json.loads(body)["dead"]
        finally:
            hb.retire()
        status, _, _ = _get(srv.port, "/healthz")
        assert status == 200

    def test_unknown_path_404(self):
        srv = serve.start(port=0)
        status, _, body = _get(srv.port, "/nope")
        assert status == 404
        assert "/metrics, /healthz or /readyz" in body

    def test_keep_alive_client_cannot_wedge_the_endpoint(self):
        """The endpoint is ONE serving thread: a client holding its
        connection open between scrapes (a real Prometheus scraper's
        default) must not block other clients — responses close the
        connection, and a silent connection times out instead of
        parking the serve loop forever."""
        import http.client
        import socket

        srv = serve.start(port=0)
        # a keep-alive scraper: the server must answer and CLOSE
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        try:
            conn.request("GET", "/metrics",
                         headers={"Connection": "keep-alive"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.headers.get("Connection") == "close"
            resp.read()
            # while a second raw socket sits connected and SILENT, the
            # endpoint must still serve others (the silent socket is
            # bounded by the handler's socket timeout, not forever)
            quiet = socket.create_connection(("127.0.0.1", srv.port),
                                             timeout=10)
            try:
                status, _, _ = _get(srv.port, "/healthz")
                assert status == 200
            finally:
                quiet.close()
        finally:
            conn.close()

    def test_start_is_idempotent_and_stop_retires(self):
        srv = serve.start(port=0)
        assert serve.start(port=0) is srv
        assert serve.active() is srv
        port = srv.port
        serve.stop()
        assert serve.active() is None
        assert supervisor.lookup(serve.METRICS_THREAD_NAME) is None
        with pytest.raises(OSError):
            _get(port, "/metrics")

    def test_reset_zeroes_books_but_keeps_endpoint_serving(self):
        """Satellite: diagnostics.reset() clears the device books and
        the endpoint survives cleanly — re-registered, zeroed, still
        serving."""
        srv = serve.start(port=0)
        _fit_streamed_sgd(depth=0, n_blocks=2)
        _, _, before = _get(srv.port, "/metrics")
        assert "device_dispatches" in before
        diagnostics.reset()
        assert serve.active() is srv and srv.running()
        assert supervisor.lookup(serve.METRICS_THREAD_NAME) is not None
        status, _, after = _get(srv.port, "/metrics")
        assert status == 200
        assert "device_dispatches" not in after  # books zeroed
        # and it keeps recording fresh fits after the reset
        _fit_streamed_sgd(depth=0, n_blocks=2)
        _, _, again = _get(srv.port, "/metrics")
        assert "device_dispatches" in again

    def test_port_knob_strict_parse(self, monkeypatch):
        monkeypatch.setenv(serve.METRICS_PORT_ENV, "")
        assert serve.resolve_port() is None
        monkeypatch.setenv(serve.METRICS_PORT_ENV, "8081")
        assert serve.resolve_port() == 8081
        monkeypatch.setenv(serve.METRICS_PORT_ENV, "http")
        with pytest.raises(ValueError, match="integer port"):
            serve.resolve_port()
        with pytest.raises(ValueError, match="0..65535"):
            serve.resolve_port(70000)

    def test_env_arming_fail_soft_on_taken_port(self, monkeypatch):
        srv = serve.start(port=0)
        # a second process-level arm on the SAME port must warn and
        # continue, not raise (the fit matters more than its scrape)
        monkeypatch.setenv(serve.METRICS_PORT_ENV, str(srv.port))
        serve.stop()  # clear _ACTIVE so start_from_env truly binds
        blocker = serve.MetricsServer(srv.port)  # hold the port, no start
        try:
            assert serve.start_from_env() is None
        finally:
            blocker._server.server_close()

    def test_endpoint_thread_name_is_the_host_only_literal(self):
        from dask_ml_tpu.analysis.rules._spmd import (
            BLESSED_COMPILE_THREADS, HOST_ONLY_THREAD_NAMES)

        srv = serve.start(port=0)
        assert srv._thread.name == serve.METRICS_THREAD_NAME
        assert serve.METRICS_THREAD_NAME in HOST_ONLY_THREAD_NAMES
        # host-only is NOT the compile blessing: the endpoint may never
        # compile, even where the ahead worker may
        assert serve.METRICS_THREAD_NAME not in BLESSED_COMPILE_THREADS

    def test_scrape_is_graftsan_clean(self, sanitizer):
        """Acceptance criterion: the endpoint thread is graftsan-clean —
        zero steady compiles/dispatches from it.  The sanitizer is
        fail-fast: a dispatch from the metrics thread would raise AT
        the violating enqueue inside the handler (a 500, and a
        violation in the report); steady() makes any compile a
        violation too."""
        srv = serve.start(port=0)
        _fit_streamed_sgd(depth=2, n_blocks=3)  # warmup inside scope
        with sanitizer.steady(guard=False):
            _fit_streamed_sgd(depth=2, n_blocks=3)
            status, _, text = _get(srv.port, "/metrics")
            assert status == 200 and "device_busy_s" in text
            status, _, _ = _get(srv.port, "/healthz")
            assert status == 200
        rep = sanitizer.report()
        assert rep["violations"] == []
        assert rep["totals"]["steady_compiles"] == 0


# -- the perf ratchet (obs/perf.py) --------------------------------------

def _snap(workloads):
    return {"version": 1, "workloads": workloads}


_BASE = {"blocks": 10, "p50_block_s": 0.002, "p99_block_s": 0.008,
         "utilization": 0.8, "stall_fraction": 0.3, "wall_s": 0.05,
         "device_busy_s": 0.03}


def _m(**over):
    m = dict(_BASE)
    m.update(over)
    return m


class TestPerfCompare:
    def test_clean_within_bands(self):
        delta = perf.compare(_snap({"w": _m()}),
                             {"w": _m(p50_block_s=0.004,
                                      utilization=0.6)})
        assert perf.is_clean(delta), delta

    def test_new_and_stale_fail(self):
        delta = perf.compare(_snap({"old": _m()}), {"new": _m()})
        assert delta["new"] == ["new"]
        assert delta["stale"] == ["old"]
        assert not perf.is_clean(delta)

    def test_p50_above_ceiling_is_regression(self):
        # ceiling = 0.002 * 5 + 0.010 = 0.020
        delta = perf.compare(_snap({"w": _m()}),
                             {"w": _m(p50_block_s=0.021)})
        assert any("p50_block_s" in r for r in delta["regressions"])

    def test_p99_above_ceiling_is_regression(self):
        # ceiling = 0.008 * 8 + 0.050 = 0.114
        delta = perf.compare(_snap({"w": _m()}),
                             {"w": _m(p99_block_s=0.12)})
        assert any("p99_block_s" in r for r in delta["regressions"])

    def test_utilization_floor(self):
        delta = perf.compare(_snap({"w": _m()}),
                             {"w": _m(utilization=0.39)})
        assert any("utilization" in r for r in delta["regressions"])

    def test_utilization_floor_skipped_for_tiny_base(self):
        delta = perf.compare(_snap({"w": _m(utilization=0.05)}),
                             {"w": _m(utilization=0.0)})
        assert perf.is_clean(delta)

    def test_stall_ceiling(self):
        # ceiling = 0.3 * 3 + 0.20 = 1.1 -> use a base of 0
        delta = perf.compare(_snap({"w": _m(stall_fraction=0.0)}),
                             {"w": _m(stall_fraction=0.25)})
        assert any("stall_fraction" in r for r in delta["regressions"])

    def test_blocks_drift_is_regression(self):
        delta = perf.compare(_snap({"w": _m()}), {"w": _m(blocks=12)})
        assert any("blocks" in r for r in delta["regressions"])

    def test_errored_workload_is_violation(self):
        delta = perf.compare(_snap({"w": _m()}),
                             {"w": _m(error="Boom: x")})
        assert any("errored" in v for v in delta["violations"])

    def test_baseline_error_cannot_grandfather(self):
        delta = perf.compare(_snap({"w": _m(error="old boom")}),
                             {"w": _m()})
        assert any("grandfather" in v for v in delta["violations"])

    def test_partial_checks_errors_only(self):
        delta = perf.compare(_snap({"w": _m(), "other": _m()}),
                             {"w": _m(p50_block_s=9.9)}, partial=True)
        assert perf.is_clean(delta)
        delta = perf.compare(_snap({"w": _m()}),
                             {"w": _m(error="Boom")}, partial=True)
        assert not perf.is_clean(delta)

    def test_load_refuses_newer_version_and_malformed(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"version": 99, "workloads": {}}))
        with pytest.raises(ValueError, match="newer"):
            perf.load(str(p))
        p.write_text(json.dumps({"version": 1}))
        with pytest.raises(ValueError, match="malformed"):
            perf.load(str(p))


class TestPerfRatchetGate:
    """The tier-1 half of ``tools/lint.sh --perf``: the committed
    baseline is green on this box, and the ratchet actually fails on
    the injected slowdown and on a stale entry."""

    @pytest.fixture(scope="class")
    def committed(self):
        path = perf.default_path()
        assert path is not None, "tools/perf_baseline.json missing"
        return perf.load(path)

    def test_committed_baseline_is_green(self, committed):
        results = perf.run_suite()
        delta = perf.compare(committed, results)
        assert perf.is_clean(delta), delta

    def test_injected_slowdown_fails_the_ratchet(self, committed):
        """Acceptance criterion: a sleep smuggled into a step program
        must fail the gate.  One workload, compared against its own
        committed entry (full semantics, not partial): 50 ms per step
        lands far above the p50 ceiling."""
        name = "sgd_stream_d2"
        results = {name: perf.run_workload(name, inject_s=0.05)}
        subset = {"version": committed["version"],
                  "workloads": {name: committed["workloads"][name]}}
        delta = perf.compare(subset, results)
        assert any("p50_block_s" in r for r in delta["regressions"]), (
            delta, results)

    def test_stale_baseline_entry_fails_the_ratchet(self, committed):
        snap = {"version": committed["version"],
                "workloads": dict(committed["workloads"],
                                  retired_workload=_m())}
        # full-suite semantics: compare a full snapshot against a run
        # missing the retired entry
        delta = perf.compare(snap, {n: _m() for n in
                                    committed["workloads"]})
        assert "retired_workload" in delta["stale"]
        assert not perf.is_clean(delta)

    def test_workload_registry_matches_baseline(self, committed):
        assert sorted(perf.WORKLOADS) == sorted(committed["workloads"])


class TestPerfCli:
    def test_list_workloads(self, capsys):
        assert perf.main(["--list-workloads"]) == 0
        out = capsys.readouterr().out
        assert "sgd_stream_d2" in out

    def test_write_baseline_refuses_subset(self, capsys):
        rc = perf.main(["--write-baseline", "/tmp/x.json",
                        "--workloads", "sgd_stream_d2"])
        assert rc == 2
        assert "full suite" in capsys.readouterr().err

    def test_write_baseline_refuses_injection(self, capsys):
        rc = perf.main(["--write-baseline", "/tmp/x.json",
                        "--inject-slowdown", "0.1"])
        assert rc == 2

    def test_inject_slowdown_refuses_subset(self, capsys):
        # a --workloads subset runs errors-only: the injection would
        # read as a false green — refuse the combination loudly
        rc = perf.main(["--workloads", "sgd_stream_d2",
                        "--inject-slowdown", "0.1"])
        assert rc == 2
        assert "full suite" in capsys.readouterr().err

    def test_unknown_workload_is_exit_2(self, capsys):
        assert perf.main(["--workloads", "nope"]) == 2


# ---------------------------------------------------------------------------
# roofline (ISSUE 12): peak table, cost capture, the device_report join,
# and the per-program ratchet columns
# ---------------------------------------------------------------------------

from dask_ml_tpu.obs import roofline  # noqa: E402


class _FakeCompiled:
    def __init__(self, payload):
        self._payload = payload

    def cost_analysis(self):
        if isinstance(self._payload, Exception):
            raise self._payload
        return self._payload


class TestRoofline:
    def test_default_peaks_have_provenance(self):
        cpu = roofline.peaks_for("cpu")
        tpu = roofline.peaks_for("tpu")
        assert cpu["source"].startswith("measured")
        assert tpu["source"].startswith("assumed")
        assert cpu["flops_per_s"] > 0 and cpu["bytes_per_s"] > 0

    def test_unknown_platform_has_no_peaks(self):
        assert roofline.peaks_for("quantum") is None
        assert roofline.peaks_for(None) is None

    def test_env_override_and_reset(self, monkeypatch):
        monkeypatch.setenv(roofline.PEAKS_ENV,
                           "cpu:flops=2e11,bytes=3e10;xpu:flops=1,bytes=2")
        roofline.reset_cache()
        try:
            cpu = roofline.peaks_for("cpu")
            assert cpu == {"flops_per_s": 2e11, "bytes_per_s": 3e10,
                           "source": "env"}
            assert roofline.peaks_for("xpu")["source"] == "env"
        finally:
            monkeypatch.delenv(roofline.PEAKS_ENV)
            roofline.reset_cache()

    @pytest.mark.parametrize("raw", [
        "cpu", "cpu:flops=1", "cpu:flops=1,bytes=x",
        "cpu:flops=0,bytes=1", "cpu:flops=1,watts=2",
    ])
    def test_malformed_env_raises(self, raw):
        with pytest.raises(ValueError):
            roofline.parse_peaks(raw)

    def test_attribution_memory_bound_equals_bandwidth_fraction(self):
        peaks = {"flops_per_s": 100.0, "bytes_per_s": 10.0,
                 "source": "test"}
        att = roofline.attribution(1.0, 10.0, 2.0, peaks)
        # memory-bound: bound = I * peak_bytes, so the fraction equals
        # achieved bytes/s over peak bytes/s (= 5/10)
        assert att["roofline_frac"] == pytest.approx(0.5)
        assert att["achieved_bytes_per_s"] == pytest.approx(5.0)
        assert att["intensity"] == pytest.approx(0.1)

    def test_attribution_compute_bound_and_zero_flop(self):
        peaks = {"flops_per_s": 100.0, "bytes_per_s": 10.0,
                 "source": "test"}
        # intensity 100 -> bound = peak_flops
        att = roofline.attribution(1000.0, 10.0, 20.0, peaks)
        assert att["roofline_frac"] == pytest.approx(0.5)
        # pure data movement scores on bandwidth alone
        att0 = roofline.attribution(0.0, 10.0, 1.0, peaks)
        assert att0["roofline_frac"] == pytest.approx(1.0)
        assert att0["intensity"] == pytest.approx(0.0)

    def test_attribution_without_peaks_reports_rates_only(self):
        att = roofline.attribution(10.0, 10.0, 1.0, None)
        assert att["roofline_frac"] is None
        assert att["achieved_flops_per_s"] == pytest.approx(10.0)

    def test_capture_cost_shapes_and_failsoft(self):
        ok = roofline.capture_cost(_FakeCompiled(
            [{"flops": 8.0, "bytes accessed": 4.0,
              "bytes accessedout{}": 2.0}]))
        assert ok == {"flops": 8.0, "bytes": 4.0, "out_bytes": 2.0}
        # dict form (newer jax), raising backends, junk, and XLA's
        # negative "unknown" sentinel all stay fail-soft
        assert roofline.capture_cost(_FakeCompiled(
            {"flops": 1.0, "bytes accessed": 1.0}))["flops"] == 1.0
        assert roofline.capture_cost(
            _FakeCompiled(RuntimeError("relayed"))) is None
        assert roofline.capture_cost(_FakeCompiled([])) is None
        assert roofline.capture_cost(_FakeCompiled(
            [{"flops": -1.0, "bytes accessed": 4.0}])) is None

    def test_cached_dispatch_attributes_flops_in_report_and_registry(self):
        from dask_ml_tpu import programs

        def gemm(a, b):
            return a @ b

        prog = programs.cached_program(gemm, name="rftest.gemm")
        a = np.ones((256, 64), np.float32)
        b = np.ones((64, 32), np.float32)
        cur = scope.cursor()
        prog(a, b)
        prog(a, b)
        rep = scope.device_report(since=cur, settle_s=5.0)
        p = rep["programs"]["rftest.gemm"]
        assert p["flops"] > 0 and p["bytes"] > 0
        assert p["roofline_frac"] is not None and p["roofline_frac"] > 0
        assert rep["roofline"]["peaks"]["source"]
        # the registry carries the same attribution for /metrics
        reg = obs.registry()
        assert reg.counter("device.flops", "rftest.gemm").value > 0
        assert reg.counter("device.bytes", "rftest.gemm").value > 0
        txt = serve.prometheus_text()
        assert "device_flops" in txt and "device_roofline_frac" in txt

    def test_fallback_dispatch_reports_time_without_work(self):
        # an interval tracked WITHOUT cost (the jitted-twin fallback /
        # graftsan hook path) must not invent flops
        t0 = time.perf_counter()
        scope.track("rftest.nocost", t0, [_Leaf(ready=True)])
        rep = scope.device_report(settle_s=1.0)
        p = rep["programs"]["rftest.nocost"]
        assert "flops" not in p and "roofline_frac" not in p


_PROGS = {"sgd.step": {"busy_s": 0.01, "flops": 1e6, "bytes": 2e6,
                       "roofline_frac": 0.01}}


class TestPerfRooflineRatchet:
    def test_program_floor_regression(self):
        base = _m(programs=_PROGS)
        meas = _m(programs={"sgd.step": dict(_PROGS["sgd.step"],
                                             roofline_frac=0.001)})
        delta = perf.compare(_snap({"w": base}), {"w": meas})
        assert any("roofline_frac" in r for r in delta["regressions"])

    def test_program_within_floor_is_clean(self):
        base = _m(programs=_PROGS)
        meas = _m(programs={"sgd.step": dict(_PROGS["sgd.step"],
                                             roofline_frac=0.004)})
        delta = perf.compare(_snap({"w": base}), {"w": meas})
        assert perf.is_clean(delta), delta

    def test_program_set_drift_fails(self):
        base = _m(programs=_PROGS)
        meas = _m(programs={"other.prog": dict(_PROGS["sgd.step"])})
        delta = perf.compare(_snap({"w": base}), {"w": meas})
        assert any("program set drifted" in r for r in delta["regressions"])

    def test_v1_snapshot_without_programs_skips_program_checks(self):
        # a pre-roofline baseline entry has no programs table: the v2
        # runner's extra columns must not fail the ratchet by themselves
        delta = perf.compare(_snap({"w": _m()}), {"w": _m(programs=_PROGS)})
        assert perf.is_clean(delta), delta

    def test_tiny_committed_fraction_cannot_floor(self):
        base = _m(programs={"p": {"busy_s": 0.01, "flops": 1.0,
                                  "bytes": 1.0,
                                  "roofline_frac": 1e-6}})
        meas = _m(programs={"p": {"busy_s": 0.01, "flops": 1.0,
                                  "bytes": 1.0, "roofline_frac": 0.0}})
        delta = perf.compare(_snap({"w": base}), {"w": meas})
        assert perf.is_clean(delta), delta

    def test_malformed_peaks_is_failsoft_on_the_sweep_path(self,
                                                           monkeypatch):
        # a typo'd DASK_ML_TPU_PEAKS must not kill the sampler or a
        # dispatch: the sweep's lookup degrades to no-peaks (warn once),
        # while the strict parse still raises on the loud surfaces
        monkeypatch.setenv(roofline.PEAKS_ENV, "tpu:flops=4.9e13")
        roofline.reset_cache()
        try:
            with pytest.raises(ValueError):
                roofline.peaks_for("cpu")
            assert roofline.try_peaks_for("cpu") is None
            t0 = time.perf_counter()
            scope.track("rftest.badpeaks", t0, [_Leaf(ready=True)],
                        cost={"flops": 8.0, "bytes": 4.0})
            scope.sweep()  # must not raise
            rep_programs = {}
            # device_report is a loud surface: it raises on the bad knob
            with pytest.raises(ValueError):
                scope.device_report(settle_s=1.0)
        finally:
            monkeypatch.delenv(roofline.PEAKS_ENV)
            roofline.reset_cache()

"""Elastic fault-domain runtime: budgets, supervision, restart driver,
degraded mode, slice recovery (docs/design.md §13).

Covers the PR-9 satellites too: the compile-ahead set-on-failure
contract (an injected builder crash must not strand a consumer on the
in-flight event), staging faults carrying their block position into
``pipeline.fault`` flight events, the checkpoint-write transient-OSError
retry, and checkpoint resume across a ``DASK_ML_TPU_BUCKET`` policy
change.
"""

import os
import queue
import threading
import time

import numpy as np
import pytest

from dask_ml_tpu import diagnostics, obs
from dask_ml_tpu.pipeline import prefetch_blocks, stream_partial_fit
from dask_ml_tpu.resilience import (
    BudgetExhausted,
    ElasticPolicy,
    FaultBudget,
    FaultInjected,
    FaultPlan,
    SliceLost,
    ThreadCrash,
    fault_plan,
    fault_stats,
    retry,
    run_with_slice_recovery,
    supervisor,
)
from dask_ml_tpu.resilience import elastic as elastic_mod


def _blocks(n=6, rows=4, cols=2):
    return [np.full((rows, cols), i, np.float32) for i in range(n)]


# ---------------------------------------------------------------------------
# FaultBudget
# ---------------------------------------------------------------------------

class TestFaultBudget:
    def test_acquire_until_exhausted_then_denied(self):
        b = FaultBudget(attempts=2, wall_s=60, name="t-budget")
        assert b.acquire("a") and b.acquire("b")
        assert not b.acquire("c")
        assert b.spent == 2 and b.denied == 1
        assert b.remaining_attempts() == 0

    def test_recovery_wall_exhaustion_denies_with_attempts_left(self):
        b = FaultBudget(attempts=100, wall_s=0.01, name="t-wall")
        b.charge_backoff("x", 0.02)
        assert b.expired()
        assert not b.acquire("late")

    def test_fit_age_never_gates_a_healthy_fit(self):
        """The wall budget caps RECOVERY wall, not fit duration: a
        long-running fit with no backoff spend keeps full retry
        capability (pre-fix, any fit older than wall_s lost it all)."""
        b = FaultBudget(attempts=2, wall_s=0.01, name="t-age")
        time.sleep(0.03)  # fit "runs" far past wall_s, zero recovery
        assert not b.expired()
        assert b.acquire("late-but-healthy")

    def test_check_raises_loudly(self):
        b = FaultBudget(attempts=0, name="t-check")
        with pytest.raises(BudgetExhausted, match="t-check"):
            b.check("site")

    def test_registry_backed_books(self):
        b = FaultBudget(attempts=1, name="t-registry")
        b.acquire("x")
        b.acquire("y")
        rep = elastic_mod.budget_report()
        assert rep["t-registry"]["spent"] >= 1
        assert rep["t-registry"]["denied"] >= 1

    @pytest.mark.parametrize("raw,attempts,wall", [
        ("5", 5, 600.0), ("4,30", 4, 30.0), (" 7 , 2.5 ", 7, 2.5),
    ])
    def test_env_parse(self, monkeypatch, raw, attempts, wall):
        monkeypatch.setenv(elastic_mod.FAULT_BUDGET_ENV, raw)
        b = FaultBudget.from_env("t-env")
        assert (b.attempts, b.wall_s) == (attempts, wall)

    @pytest.mark.parametrize("raw", ["nope", "3,x", "1,2,3", "-1", "2,0"])
    def test_env_parse_strict(self, monkeypatch, raw):
        monkeypatch.setenv(elastic_mod.FAULT_BUDGET_ENV, raw)
        with pytest.raises(ValueError):
            FaultBudget.from_env("t-env")

    def test_degraded_knob_strict(self, monkeypatch):
        monkeypatch.setenv(elastic_mod.DEGRADED_ENV, "soon")
        with pytest.raises(ValueError):
            elastic_mod.resolve_degraded_blocks()
        monkeypatch.setenv(elastic_mod.DEGRADED_ENV, "3")
        assert elastic_mod.resolve_degraded_blocks() == 3


# ---------------------------------------------------------------------------
# retry: budget + full jitter
# ---------------------------------------------------------------------------

class TestRetryBudget:
    def test_budget_denial_propagates_and_counts_failure(self):
        budget = FaultBudget(attempts=1, name="t-retry-budget")
        calls = []

        def flaky():
            calls.append(1)
            raise OSError("down")

        before = fault_stats().snapshot()
        with pytest.raises(OSError):
            retry(flaky, retries=10, backoff=0.0, jitter=0.0,
                  budget=budget, tag="t-retry-budget")
        after = fault_stats().snapshot()
        # attempt 1 + the single budgeted re-attempt: the shared budget
        # cut a retries=10 loop to 2 calls
        assert len(calls) == 2
        delta_f = (after["failures"].get("t-retry-budget", 0)
                   - before["failures"].get("t-retry-budget", 0))
        assert delta_f == 1

    def test_full_jitter_delay_below_cap(self):
        sleeps = []

        def flaky():
            if len(sleeps) < 3:
                raise OSError("down")
            return "ok"

        out = retry(flaky, retries=5, backoff=0.1, factor=1.0,
                    full_jitter=True, sleep=sleeps.append,
                    tag="t-full-jitter")
        assert out == "ok"
        assert len(sleeps) == 3
        assert all(0.0 <= s < 0.1 for s in sleeps)

    def test_backoff_totals_registry_backed(self):
        sleeps = []

        def flaky():
            if not sleeps:
                raise OSError("down")
            return "ok"

        retry(flaky, retries=2, backoff=0.05, jitter=0.0,
              sleep=sleeps.append, tag="t-backoff-books")
        rep = diagnostics.fault_report()
        assert rep["backoff_s"].get("t-backoff-books", 0) >= 0.05


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_verdicts(self):
        hb = supervisor.register("t-unit", "t-domain", interval_s=0.02)
        assert hb.verdict() == "healthy"
        time.sleep(0.05)
        assert hb.verdict() == "late"
        hb.beat()
        assert hb.verdict() == "healthy"
        hb.retire()
        assert hb.verdict() == "retired"

    def test_dead_thread_verdict(self):
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        hb = supervisor.register("t-dead", "t-domain", thread=t)
        assert hb.verdict() == "dead"

    def test_retire_drops_registry_entry(self):
        """Long-lived processes register a unit per stream / search
        unit: retirement must drop the table entry, not just flag it,
        or _UNITS grows without bound."""
        hb = supervisor.register("t-retire", "t-domain")
        assert supervisor.lookup("t-retire") is hb
        hb.retire()
        assert supervisor.lookup("t-retire") is None
        assert hb.verdict() == "retired"  # the handle still answers

    def test_report_counts_deaths_and_restarts(self):
        supervisor.note_death("t-dom2", "u", error="boom")
        supervisor.note_restart("t-dom2", "u")
        rep = supervisor.report()
        assert rep["t-dom2"]["deaths"] >= 1
        assert rep["t-dom2"]["restarts"] >= 1


# ---------------------------------------------------------------------------
# the elastic pipeline driver
# ---------------------------------------------------------------------------

class _Restartable:
    restartable_source = True

    def __init__(self, blocks, fire=None):
        self._blocks = list(blocks)
        self._fire = fire
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._i >= len(self._blocks):
            raise StopIteration
        if self._fire:
            from dask_ml_tpu.resilience.testing import maybe_fault

            maybe_fault(self._fire)
        b = self._blocks[self._i]
        self._i += 1
        return b


class TestElasticPipeline:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_worker_crash_restarts_and_replays_exactly(self, depth):
        blocks = _blocks()
        plan = FaultPlan().inject("prefetch-worker", at_call=3, times=1,
                                  exc=ThreadCrash("test"))
        with fault_plan(plan):
            out = list(prefetch_blocks(blocks, depth=depth,
                                       label="t-crash"))
        # depth 0 has no worker: the point never fires; depth >= 1
        # restarts and replays with no loss, no duplication, in order
        assert len(out) == len(blocks)
        assert all(np.array_equal(a, b) for a, b in zip(out, blocks))
        if depth:
            assert plan.fired["prefetch-worker"] == 1

    def test_transient_stage_fault_retried_same_block(self):
        blocks = _blocks()
        plan = FaultPlan().inject("stage", at_call=2, times=1)
        with fault_plan(plan):
            out = list(prefetch_blocks(blocks, depth=2, label="t-stage"))
        assert len(out) == len(blocks)
        assert all(np.array_equal(a, b) for a, b in zip(out, blocks))

    @pytest.mark.parametrize("depth", [0, 2])
    def test_poisoned_block_skipped_under_degraded_knob(self, depth):
        blocks = _blocks()
        policy = ElasticPolicy(degraded_blocks=1, block_retries=1,
                               label="t-skip")
        plan = FaultPlan().inject("stage", at_call=(3, 4), times=2)
        with fault_plan(plan):
            out = list(prefetch_blocks(blocks, depth=depth,
                                       elastic=policy))
        assert len(out) == len(blocks) - 1
        assert np.array_equal(out[2], blocks[3])  # block 2 is gone
        assert policy.skips == [{
            "block": 2, "phase": "stage",
            "error": "FaultInjected: injected fault at 'stage'"}]
        rep = diagnostics.fault_report()
        assert rep["degraded_skips"].get("t-skip", 0) >= 1

    def test_degraded_off_by_default_raises_with_position(self):
        blocks = _blocks()
        plan = FaultPlan().inject("stage", at_call=(4, 5, 6), times=3)
        with pytest.raises(FaultInjected) as ei:
            with fault_plan(plan):
                list(prefetch_blocks(blocks, depth=2,
                                     elastic=ElasticPolicy(
                                         block_retries=2, label="t-pos")))
        assert ei.value.__dmlt_block__ == 3
        assert ei.value.__dmlt_phase__ == "stage"

    def test_parse_fault_on_generator_source_propagates(self):
        """A generator that raised is FINISHED: retrying it would read
        as a silent end-of-stream (data loss), so plain generator
        sources never retry parse faults."""
        def gen():
            yield np.zeros((2, 2), np.float32)
            raise OSError("reader died")

        with pytest.raises(OSError):
            list(prefetch_blocks(gen(), depth=2, label="t-gen"))

    def test_parse_fault_on_restartable_source_retried(self):
        blocks = _blocks()
        plan = FaultPlan().inject("ingest", at_call=3, times=1)
        src = _Restartable(blocks, fire="ingest")
        with fault_plan(plan):
            out = list(prefetch_blocks(src, depth=2, label="t-restart"))
        assert len(out) == len(blocks)
        assert all(np.array_equal(a, b) for a, b in zip(out, blocks))

    def test_budget_exhaustion_stops_restarting(self):
        blocks = _blocks()
        policy = ElasticPolicy(
            budget=FaultBudget(attempts=1, name="t-exhaust"),
            block_retries=10, label="t-exhaust")
        plan = FaultPlan().persistent("stage")
        with pytest.raises(FaultInjected):
            with fault_plan(plan):
                list(prefetch_blocks(blocks, depth=2, elastic=policy))
        # original attempt + exactly ONE budgeted retry, despite
        # block_retries=10
        assert plan.calls["stage"] == 2

    def test_crash_death_and_restart_are_supervised(self):
        before = obs.registry().family("supervisor.death").get(
            "pipeline", 0)
        plan = FaultPlan().inject("prefetch-worker", at_call=2, times=1,
                                  exc=ThreadCrash("test"))
        with fault_plan(plan):
            list(prefetch_blocks(_blocks(), depth=2, label="t-sup"))
        fam = obs.registry().family("supervisor.death")
        assert fam.get("pipeline", 0) == before + 1


class _StepModel:
    """Host-only partial_fit model whose step can fault BEFORE mutating
    state (the retry-safety contract step_retries documents)."""

    def __init__(self, fail_on_call=None):
        self.seen = []
        self.calls = 0
        self.fail_on_call = fail_on_call

    def partial_fit(self, X, y=None):
        self.calls += 1
        if self.calls == self.fail_on_call:
            raise RuntimeError("transient step fault")
        self.seen.append(float(X[0, 0]))
        return self


class TestStepRetry:
    def test_step_retry_opt_in_consumes_block_exactly_once(self):
        model = _StepModel(fail_on_call=3)
        blocks = [(b, None) for b in _blocks()]
        stream_partial_fit(
            model, blocks, depth=2,
            elastic=ElasticPolicy(step_retries=1, label="t-step"))
        assert model.seen == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]

    def test_step_fault_propagates_by_default(self):
        model = _StepModel(fail_on_call=3)
        blocks = [(b, None) for b in _blocks()]
        with pytest.raises(RuntimeError, match="transient step fault"):
            stream_partial_fit(model, blocks, depth=2, label="t-step-off")


class TestFaultEventPosition:
    def test_stage_fault_event_carries_worker_side_position(self):
        """PR-9 satellite: a staging (post-parse H2D) fault's
        ``pipeline.fault`` flight event must carry the FAILING block's
        position and phase — even when the prefetch worker is blocks
        ahead of the consumer."""
        class _Slow(_StepModel):
            def partial_fit(self, X, y=None):
                time.sleep(0.05)  # let the worker run ahead
                return super().partial_fit(X, y)

        blocks = [(b, None) for b in _blocks(n=8)]
        plan = FaultPlan().inject("stage", at_call=(5, 6, 7), times=3)
        obs.flight.clear()
        with pytest.raises(FaultInjected):
            with fault_plan(plan):
                stream_partial_fit(
                    _Slow(), blocks, depth=3,
                    elastic=ElasticPolicy(block_retries=2,
                                          label="t-event"))
        events = [e for e in obs.flight_tail()
                  if e["name"] == "pipeline.fault"]
        assert events, "no pipeline.fault flight event recorded"
        evt = events[-1]
        assert evt["attrs"]["block"] == 4
        assert evt["attrs"]["phase"] == "stage"

    def test_consume_fault_event_keeps_consumer_position(self):
        model = _StepModel(fail_on_call=2)
        blocks = [(b, None) for b in _blocks()]
        obs.flight.clear()
        with pytest.raises(RuntimeError):
            stream_partial_fit(model, blocks, depth=0, label="t-consume")
        evt = [e for e in obs.flight_tail()
               if e["name"] == "pipeline.fault"][-1]
        assert evt["attrs"]["block"] == 1
        assert evt["attrs"]["phase"] == "consume"


# ---------------------------------------------------------------------------
# compile-ahead: set-on-failure (PR-9 satellite regression)
# ---------------------------------------------------------------------------

class TestAheadCrash:
    def test_builder_crash_never_strands_the_consumer(self):
        import jax
        import jax.numpy as jnp
        from dask_ml_tpu.programs import ahead, cache

        ahead._reset_restarts_for_tests()
        prog = cache.cached_program(lambda x: x * 3.0,
                                    name="t_elastic_ahead_crash")
        x = jnp.ones((4, 3), jnp.float32)
        sds = jax.ShapeDtypeStruct((4, 3), jnp.float32)
        plan = FaultPlan().inject("compile-ahead", at_call=1, times=1,
                                  exc=ThreadCrash("test"))
        with fault_plan(plan):
            assert prog.warm((sds,)) is True
            t0 = time.perf_counter()
            out = prog(x)  # pre-fix: hung for the 120 s safety valve
            waited = time.perf_counter() - t0
        assert np.allclose(np.asarray(out), 3.0)
        assert waited < 30.0
        assert prog.report()["ahead_errors"] >= 1
        # the dying worker failed the in-flight marker; nothing leaks
        assert prog.report()["inflight"] == 0

    def test_worker_restarts_after_death(self):
        import jax
        import jax.numpy as jnp
        from dask_ml_tpu.programs import ahead, cache

        ahead._reset_restarts_for_tests()
        prog = cache.cached_program(lambda x: x - 1.0,
                                    name="t_elastic_ahead_restart")
        sds = jax.ShapeDtypeStruct((3, 3), jnp.float32)
        assert prog.warm((sds,)) is True
        assert ahead.drain()
        assert ahead.worker_alive()
        out = prog(jnp.ones((3, 3), jnp.float32))
        assert np.allclose(np.asarray(out), 0.0)
        assert prog.report()["ahead_hits"] == 1

    def test_queued_builds_fail_when_worker_dies(self):
        """A task still queued when the builder dies must have its
        in-flight marker failed by the dying drain — not wait for a
        future submit."""
        from dask_ml_tpu.programs import ahead as ahead_mod

        class _Prog:
            name = "t_fake"

            def __init__(self):
                self.failed = []

            def _ahead_failed(self, sig, exc):
                self.failed.append((sig, exc))

        p = _Prog()
        q = queue.Queue()
        q.put((p, "sig1", (), {}))
        q.put((p, "sig2", (), {}))
        ahead_mod._drain_failed(q, RuntimeError("dead"))
        assert [s for s, _ in p.failed] == ["sig1", "sig2"]
        assert q.unfinished_tasks == 0


# ---------------------------------------------------------------------------
# checkpoint-write retry (PR-9: the one choke point recovers transients)
# ---------------------------------------------------------------------------

class TestCheckpointWriteRetry:
    def test_transient_oserror_absorbed(self, tmp_path):
        from dask_ml_tpu.checkpoint import _atomic_pickle

        path = str(tmp_path / "snap.pkl")
        plan = FaultPlan().inject("checkpoint-write", at_call=1, times=1,
                                  exc=OSError(28, "no space"))
        with fault_plan(plan):
            _atomic_pickle({"v": 1}, path)
        import pickle

        with open(path, "rb") as f:
            assert pickle.load(f) == {"v": 1}
        assert plan.calls["checkpoint-write"] == 2  # fault + clean retry

    def test_injected_crash_still_propagates_unretried(self, tmp_path):
        """The crash-mid-write drill contract: a FaultInjected is a
        simulated CRASH, not a transient — exactly one attempt, the
        previous snapshot untouched."""
        from dask_ml_tpu.checkpoint import _atomic_pickle

        path = str(tmp_path / "snap.pkl")
        _atomic_pickle({"v": 1}, path)
        plan = FaultPlan().inject("checkpoint-write", at_call=1, times=1)
        with pytest.raises(FaultInjected):
            with fault_plan(plan):
                _atomic_pickle({"v": 2}, path)
        assert plan.calls["checkpoint-write"] == 1
        import pickle

        with open(path, "rb") as f:
            assert pickle.load(f) == {"v": 1}


# ---------------------------------------------------------------------------
# slice loss as a resume (submesh recovery)
# ---------------------------------------------------------------------------

class TestSliceRecovery:
    def test_reentry_on_next_mesh_within_budget(self):
        calls = []

        def fit(mesh):
            calls.append(mesh)
            if len(calls) == 1:
                raise SliceLost("slice 1 gone")
            return "fitted"

        out = run_with_slice_recovery(
            fit, [None, None],
            budget=FaultBudget(attempts=4, name="t-slice"))
        assert out == "fitted" and len(calls) == 2

    def test_budget_denial_raises_budget_exhausted(self):
        def fit(mesh):
            raise SliceLost("gone")

        with pytest.raises(BudgetExhausted):
            run_with_slice_recovery(
                fit, [None, None, None],
                budget=FaultBudget(attempts=0, name="t-slice0"))

    def test_non_slice_fault_propagates_immediately(self):
        calls = []

        def fit(mesh):
            calls.append(1)
            raise ValueError("real bug")

        with pytest.raises(ValueError):
            run_with_slice_recovery(
                fit, [None, None],
                budget=FaultBudget(attempts=4, name="t-slice2"))
        assert len(calls) == 1

    def test_kmeans_resumes_on_surviving_submesh(self, tmp_path,
                                                 n_devices):
        """The real thing: a KMeans fit loses its slice mid-fit (an
        injected SliceLost at a segment boundary), and the re-entry on
        the 4-device submesh RESUMES from the FitCheckpoint — the final
        centers match the uninterrupted full-mesh fit."""
        if n_devices < 8:
            pytest.skip("needs the 8-virtual-device mesh")
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core.mesh import device_mesh
        from dask_ml_tpu.resilience import FitCheckpoint

        rng = np.random.RandomState(3)
        X = rng.normal(size=(64, 4)).astype(np.float32)

        def make(ck=None):
            return KMeans(n_clusters=3, max_iter=12, tol=0.0,
                          random_state=0, fit_checkpoint=ck)

        ref = make().fit(X)
        path = str(tmp_path / "ck.pkl")
        attempt = []

        def fit(mesh):
            est = make(FitCheckpoint(path, every_n_iters=4))
            if not attempt:
                attempt.append(1)
                plan = FaultPlan().inject(
                    "step", at_call=2, times=1,
                    exc=SliceLost("slice down"))
                with fault_plan(plan):
                    return est.fit(X)
            return est.fit(X)

        model = run_with_slice_recovery(
            fit, [device_mesh(8), device_mesh(4)],
            budget=FaultBudget(attempts=2, name="t-slice-km"))
        np.testing.assert_allclose(
            np.asarray(model.cluster_centers_),
            np.asarray(ref.cluster_centers_), rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# checkpoint resume across a bucket-policy change (PR-9 satellite)
# ---------------------------------------------------------------------------

class TestBucketPolicyResume:
    @pytest.mark.parametrize("resume_policy", ["off", "64,512,2048"])
    def test_sgd_resume_across_bucket_change(self, tmp_path,
                                             monkeypatch,
                                             resume_policy):
        """Save mid-fit under ``DASK_ML_TPU_BUCKET=auto``, resume under
        ``off`` / an explicit ladder: the padded program SHAPES differ
        (program warmth may differ), but the model must match the
        uninterrupted fit to the documented reassociation bound."""
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.resilience import FitCheckpoint

        rng = np.random.RandomState(5)
        X = rng.normal(size=(300, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)

        def make(ck=None):
            return SGDClassifier(random_state=0, max_iter=10, tol=None,
                                 fit_checkpoint=ck)

        monkeypatch.setenv("DASK_ML_TPU_BUCKET", "auto")
        ref = make().fit(X, y)
        path = str(tmp_path / "sgd.pkl")
        plan = FaultPlan().inject("step", at_call=7, times=1)
        with pytest.raises(FaultInjected):
            with fault_plan(plan):
                make(FitCheckpoint(path, every_n_iters=2)).fit(X, y)
        assert os.path.exists(path)

        monkeypatch.setenv("DASK_ML_TPU_BUCKET", resume_policy)
        resumed = make(FitCheckpoint(path, every_n_iters=2,
                                     keep_on_complete=True)).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(resumed.coef_), np.asarray(ref.coef_),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(resumed.intercept_), np.asarray(ref.intercept_),
            rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# fault_report / run_report integration
# ---------------------------------------------------------------------------

class TestFaultReport:
    def test_shape_and_registry_backing(self):
        b = FaultBudget(attempts=3, name="t-report")
        b.acquire("x")
        rep = diagnostics.fault_report()
        for key in ("faults", "budgets", "backoff_s", "degraded_skips",
                    "supervisor"):
            assert key in rep
        assert rep["budgets"]["t-report"]["spent"] >= 1

    def test_run_report_carries_resilience_view(self):
        rep = diagnostics.run_report()
        assert "resilience" in rep
        assert set(rep["resilience"]) == {
            "faults", "budgets", "backoff_s", "degraded_skips",
            "supervisor"}

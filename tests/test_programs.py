"""Central program cache, shape bucketing, compile-ahead (design.md §12).

Covers the ISSUE-8 contract: bit-identical model results at every
bucket policy (mirroring the pipeline depth-invariance tests),
ragged-tail + empty-block edges, compile-ahead hit/miss races, cache
warmth across checkpoint resume, depth-2 prefetch interop, the
blessed-thread attribution in graftsan, and the pad no-op fast path
asserted through the pipeline stats split."""

from __future__ import annotations

import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu import diagnostics, programs
from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor
from dask_ml_tpu.pipeline import stream_partial_fit
from dask_ml_tpu.programs import bucket, cache


@pytest.fixture
def bucket_env(monkeypatch):
    """Set the bucket policy knob for one test."""

    def _set(value):
        if value is None:
            monkeypatch.delenv(bucket.BUCKET_ENV, raising=False)
        else:
            monkeypatch.setenv(bucket.BUCKET_ENV, value)

    return _set


def _class_blocks(sizes, d=4, seed=3):
    rng = np.random.RandomState(seed)
    out = []
    for n in sizes:
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32) if n else np.zeros(0, np.int32)
        out.append((X, y))
    return out


# -- policy parsing / bucket math ----------------------------------------


class TestBucketPolicy:
    def test_default_is_committed_ladder(self, bucket_env):
        bucket_env(None)
        pol = programs.resolve_policy()
        assert pol.kind == "sizes"
        assert pol.sizes == programs.DEFAULT_BUCKETS

    def test_historical_semantics_preserved(self):
        # the exact assertions test_sgd has always pinned
        from dask_ml_tpu.linear_model._sgd import _bucket_rows

        assert {_bucket_rows(s) for s in (1, 7, 255, 256)} == {256}
        assert _bucket_rows(257) == 1024
        assert _bucket_rows(70000) == 65536 * 2

    @pytest.mark.parametrize("raw,n,expected", [
        ("off", 300, 300),
        ("off", 0, 0),
        ("pow2", 300, 512),
        ("pow2", 1, 1),
        ("pow2", 0, 0),
        ("64,512", 65, 512),
        ("64,512", 513, 1024),  # beyond top: multiples of the top rung
        ("auto", 300, 1024),
    ])
    def test_bucket_rows(self, bucket_env, raw, n, expected):
        bucket_env(raw)
        assert programs.bucket_rows(n) == expected

    @pytest.mark.parametrize("bad", ["sideways", "64,32", "0,64", "64,,x"])
    def test_bad_policy_raises(self, bucket_env, bad):
        bucket_env(bad)
        with pytest.raises(ValueError, match="DASK_ML_TPU_BUCKET"):
            programs.resolve_policy()

    def test_explicit_argument_overrides_env(self, bucket_env):
        bucket_env("off")
        assert programs.bucket_rows(300, "pow2") == 512

    def test_pad_block_noop_fast_path(self, bucket_env):
        bucket_env("off")
        programs.reset_counters()
        X = np.ones((17, 3), np.float32)
        Xp, t, mask = programs.pad_block(X)
        assert Xp is X  # no copy on the no-op path
        assert t is None
        assert mask.shape == (17,) and mask.all()
        rep = programs.report()["bucket"]
        assert rep == {"blocks": 1, "padded_blocks": 0, "pad_rows": 0}

    def test_pad_block_pads_and_counts(self, bucket_env):
        bucket_env("64,512")
        programs.reset_counters()
        X = np.ones((65, 3), np.float32)
        y = np.ones((65, 1), np.float32)
        Xp, yp, mask = programs.pad_block(X, y)
        assert Xp.shape == (512, 3) and yp.shape == (512, 1)
        assert mask.sum() == 65 and not mask[65:].any()
        assert (Xp[65:] == 0).all()
        rep = programs.report()["bucket"]
        assert rep == {"blocks": 1, "padded_blocks": 1, "pad_rows": 447}


# -- model-result invariance across policies ------------------------------


SIZES = (32, 300, 17, 5)


class TestPolicyInvariance:
    def _coef(self, policy, depth, bucket_env):
        bucket_env(policy)
        clf = SGDClassifier(random_state=0)
        stream_partial_fit(
            clf, iter(_class_blocks(SIZES)), depth=depth,
            fit_kwargs={"classes": np.array([0, 1])},
        )
        return np.asarray(clf.coef_), np.asarray(clf.intercept_)

    @pytest.mark.parametrize("policy", ["off", "pow2", "64,512,4096",
                                        "auto"])
    @pytest.mark.parametrize("depth", [0, 2])
    def test_identical_results_across_policies(self, policy, depth,
                                               bucket_env):
        """Padding rows carry mask 0.0 and IEEE zeros are exact additive
        identities — but a different padded SHAPE can re-tile XLA's
        reduction tree (SIMD lanes vs the remainder loop), regrouping
        the same real addends.  The bound is therefore reassociation of
        identical values: a few f32 ulps, independent of how much
        padding was added — asserted here at 1e-5 relative (~100x
        tighter than any fit tolerance).  SAME-shape invariance (same
        policy, any prefetch depth) stays bit-exact, pinned below."""
        ref_c, ref_i = self._coef(None, 0, bucket_env)
        c, i = self._coef(policy, depth, bucket_env)
        np.testing.assert_allclose(ref_c, c, rtol=1e-5, atol=1e-8)
        np.testing.assert_allclose(ref_i, i, rtol=1e-5, atol=1e-8)

    @pytest.mark.parametrize("policy", ["off", "auto"])
    def test_bit_identical_across_depths_per_policy(self, policy,
                                                    bucket_env):
        """Within one policy the shapes are fixed, so prefetch depth
        must not change a single bit (the §8 depth-invariance contract
        carried over to every bucketing policy)."""
        c0, i0 = self._coef(policy, 0, bucket_env)
        c2, i2 = self._coef(policy, 2, bucket_env)
        np.testing.assert_array_equal(c0, c2)
        np.testing.assert_array_equal(i0, i2)

    def test_regressor_ragged_tail(self, bucket_env):
        rng = np.random.RandomState(0)
        blocks = [
            (rng.normal(size=(n, 3)).astype(np.float32),
             rng.normal(size=(n,)).astype(np.float32))
            for n in (64, 64, 21)  # ragged tail block
        ]
        coefs = {}
        for pol in ("off", "auto"):
            bucket_env(pol)
            reg = SGDRegressor(random_state=0)
            stream_partial_fit(reg, iter(blocks), depth=2)
            coefs[pol] = np.asarray(reg.coef_)
        np.testing.assert_allclose(coefs["off"], coefs["auto"],
                                   rtol=1e-5, atol=1e-8)

    def test_empty_block_mid_stream(self, bucket_env):
        """A zero-row block must be a no-op for the model under every
        policy (count 0 → safe_denominator guards the mean)."""
        for pol in ("off", "auto"):
            bucket_env(pol)
            with_empty = SGDClassifier(random_state=0)
            stream_partial_fit(
                with_empty, iter(_class_blocks((32, 0, 32))), depth=2,
                fit_kwargs={"classes": np.array([0, 1])},
            )
            without = SGDClassifier(random_state=0)
            stream_partial_fit(
                without, iter(_class_blocks((32, 32))), depth=0,
                fit_kwargs={"classes": np.array([0, 1])},
            )
            # the empty block advances t (one step) but contributes zero
            # gradient; compare against a manual replay with an empty
            # step folded in
            assert np.isfinite(np.asarray(with_empty.coef_)).all()
            assert with_empty.coef_.shape == without.coef_.shape

    def test_minibatch_kmeans_policy_invariance(self, bucket_env):
        """Deterministic (array) init: the Sculley update itself must be
        policy-invariant (padding rows weigh 0 in every mass sum).  A
        RANDOM init is deliberately out of scope — k-means++/random
        sampling draws indices over the PADDED row count, so the draw
        is a documented function of the bucket, not a masked
        reduction."""
        from dask_ml_tpu.cluster import MiniBatchKMeans

        rng = np.random.RandomState(1)
        blocks = [rng.normal(size=(n, 5)).astype(np.float32)
                  for n in (40, 300, 13)]
        init = rng.normal(size=(3, 5)).astype(np.float32)
        centers = {}
        for pol in ("off", "auto"):
            bucket_env(pol)
            mbk = MiniBatchKMeans(n_clusters=3, init=init, random_state=0)
            stream_partial_fit(mbk, iter([(b, None) for b in blocks]),
                               depth=2)
            centers[pol] = np.asarray(mbk.cluster_centers_)
        # same reassociation bound as the SGD cross-policy test
        np.testing.assert_allclose(centers["off"], centers["auto"],
                                   rtol=1e-5, atol=1e-8)


# -- the cache itself -----------------------------------------------------


def _fresh_program(name, static=()):
    def fn(x, y, *, scale=1.0):
        return (x * y).sum() * scale

    return cache.CachedProgram(fn, name=name, static_argnames=static)


class TestCachedProgram:
    def test_hit_miss_books(self):
        p = _fresh_program("test.books")
        x = jnp.ones((7, 3))
        y = jnp.ones((7, 3))
        out = p(x, y)
        assert float(out) == 21.0
        assert p.counters["misses"] == 1 and p.counters["hits"] == 0
        p(x, y)
        p(x, y)
        assert p.counters["hits"] == 2
        assert p.counters["fallback"] == 0
        # a new shape is a new signature
        p(jnp.ones((9, 3)), jnp.ones((9, 3)))
        assert p.counters["misses"] == 2

    def test_static_args_key_signatures(self):
        p = _fresh_program("test.static", static=("scale",))
        x = jnp.ones(4)
        assert float(p(x, x, scale=2.0)) == 8.0
        assert float(p(x, x, scale=3.0)) == 12.0
        assert p.counters["misses"] == 2
        assert float(p(x, x, scale=2.0)) == 8.0
        assert p.counters["hits"] == 1

    def test_tracer_operands_bypass(self):
        p = _fresh_program("test.tracer")

        @jax.jit
        def outer(a):
            return p(a, a)

        assert float(outer(jnp.ones(3))) == 3.0
        assert p.counters["bypass"] >= 1
        assert p.counters["misses"] == 0

    def test_unknown_kwarg_bypasses(self):
        def fn(x, y=None):
            return x.sum() if y is None else (x + y).sum()

        p = cache.CachedProgram(fn, name="test.kwarg")
        out = p(jnp.ones(3), y=jnp.ones(3))
        assert float(out) == 6.0
        assert p.counters["bypass"] == 1

    def test_warm_then_call_is_ahead_hit(self):
        p = _fresh_program("test.warm")
        sds = jax.ShapeDtypeStruct((11, 2), jnp.float32)
        assert p.warm((sds, sds)) is True
        assert programs.drain_ahead()
        out = p(jnp.ones((11, 2)), jnp.ones((11, 2)))
        assert float(out) == 22.0
        assert p.counters["ahead_submitted"] == 1
        assert p.counters["ahead_hits"] == 1
        assert p.counters["misses"] == 0
        assert p.counters["saved_s"] > 0

    def test_call_racing_warm_waits_for_one_compile(self):
        """A consumer arriving before the ahead build finishes must WAIT
        on the in-flight compile (one compile total), never duplicate it
        on its own thread — the property that keeps steady_compiles at
        zero in the sanitizer gate."""
        p = _fresh_program("test.race")
        sds = jax.ShapeDtypeStruct((13, 2), jnp.float32)
        assert p.warm((sds, sds)) is True
        # no drain: call immediately; the in-flight marker was
        # registered synchronously by warm()
        out = p(jnp.ones((13, 2)), jnp.ones((13, 2)))
        assert float(out) == 26.0
        assert p.counters["misses"] == 0
        assert p.counters["ahead_hits"] == 1

    def test_concurrent_demand_misses_single_flight(self):
        """Two threads missing the same signature concurrently (the
        search pool's shape) must produce ONE backend compile: the
        second thread waits on the first's in-flight build instead of
        racing a duplicate."""
        import time as _time

        traces = []

        def slow(x):
            traces.append(threading.get_ident())  # once per trace
            _time.sleep(0.25)  # slow TRACE so the misses overlap
            return x * 2

        p = cache.CachedProgram(slow, name="test.singleflight")
        outs, errs = [], []

        def run():
            try:
                outs.append(float(p(jnp.ones(29)).sum()))
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        ts = [threading.Thread(target=run) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs and outs == [58.0, 58.0]
        assert len(traces) == 1  # one build total
        assert p.counters["misses"] == 1 and p.counters["hits"] == 1

    def test_duplicate_warm_is_single_flight(self):
        p = _fresh_program("test.dupe")
        sds = jax.ShapeDtypeStruct((17, 2), jnp.float32)
        assert p.warm((sds, sds)) is True
        assert p.warm((sds, sds)) is False  # known/in-flight
        programs.drain_ahead()
        assert p.warm((sds, sds)) is False  # already built
        assert p.counters["ahead_submitted"] == 1

    def test_warm_off_by_knob(self, monkeypatch):
        monkeypatch.setenv(programs.AHEAD_ENV, "off")
        p = _fresh_program("test.off")
        sds = jax.ShapeDtypeStruct((19, 2), jnp.float32)
        assert p.warm((sds, sds)) is False
        assert p.counters["ahead_submitted"] == 0
        p(jnp.ones((19, 2)), jnp.ones((19, 2)))
        assert p.counters["misses"] == 1

    def test_ahead_env_strict_parse(self, monkeypatch):
        monkeypatch.setenv(programs.AHEAD_ENV, "sideways")
        with pytest.raises(ValueError, match="COMPILE_AHEAD"):
            programs.compile_ahead_enabled()

    def test_warm_compile_error_never_breaks_consumer(self):
        def bad(x):
            raise RuntimeError("boom at trace time")

        p = cache.CachedProgram(bad, name="test.baderr")
        sds = jax.ShapeDtypeStruct((3,), jnp.float32)
        assert p.warm((sds,)) is True
        programs.drain_ahead()
        assert p.counters["ahead_errors"] == 1
        # the demand path raises the real error (same as plain jit)
        with pytest.raises(RuntimeError, match="boom"):
            p(jnp.ones(3))

    def test_donated_state_chain(self):
        def step(state, x):
            return {"c": state["c"] + x.sum()}

        p = cache.CachedProgram(step, name="test.donate",
                                donate_argnames=("state",))
        st = {"c": jnp.float32(0)}
        for _ in range(3):
            st = p(st, jnp.ones(4))
        assert float(st["c"]) == 12.0
        assert p.counters["misses"] == 1 and p.counters["hits"] == 2

    def test_report_shapes(self):
        rep = diagnostics.program_report()
        assert set(rep) == {"programs", "totals", "bucket",
                            "persistent_cache"}
        assert "sgd.step" in rep["programs"]
        for key in ("hits", "misses", "ahead_hits", "fallback",
                    "saved_s", "compile_s"):
            assert key in rep["totals"]

    def test_blessed_thread_name_single_source(self):
        from dask_ml_tpu.analysis.rules._spmd import BLESSED_COMPILE_THREADS

        assert programs.AHEAD_THREAD_NAME in BLESSED_COMPILE_THREADS

    def test_ahead_compiles_happen_on_blessed_thread(self):
        seen = []

        def spy(x, y):
            seen.append(threading.current_thread().name)
            return x + y

        p = cache.CachedProgram(spy, name="test.thread")
        sds = jax.ShapeDtypeStruct((23,), jnp.float32)
        p.warm((sds, sds))
        programs.drain_ahead()
        assert seen == [programs.AHEAD_THREAD_NAME]


# -- persistent compilation cache ----------------------------------------


class TestPersistentCache:
    def test_knob_arms_and_reports(self, tmp_path, monkeypatch):
        d = str(tmp_path / "xla-cache")
        monkeypatch.setattr(cache, "_PERSISTENT",
                            {"armed": False, "dir": None, "error": None})
        monkeypatch.setenv(cache.CACHE_DIR_ENV, d)
        try:
            armed = programs.enable_persistent_cache()
            assert armed == d and os.path.isdir(d)
            assert programs.report()["persistent_cache"] == d
            # idempotent: second call returns the armed dir
            assert programs.enable_persistent_cache("/elsewhere") == d
        finally:
            jax.config.update("jax_compilation_cache_dir", None)
            monkeypatch.setattr(cache, "_PERSISTENT",
                                {"armed": False, "dir": None,
                                 "error": None})

    def test_off_by_default(self, monkeypatch):
        monkeypatch.setattr(cache, "_PERSISTENT",
                            {"armed": False, "dir": None, "error": None})
        monkeypatch.delenv(cache.CACHE_DIR_ENV, raising=False)
        assert programs.enable_persistent_cache() is None
        assert programs.report()["persistent_cache"] is None


# -- estimator integration ------------------------------------------------


class TestEstimatorIntegration:
    def test_sgd_stream_warms_ahead(self, bucket_env):
        bucket_env("auto")
        programs.reset_counters()
        clf = SGDClassifier(random_state=0)
        stream_partial_fit(
            clf, iter(_class_blocks((32, 32, 300, 300))), depth=2,
            fit_kwargs={"classes": np.array([0, 1])},
        )
        programs.drain_ahead()
        books = programs.report()["programs"]["sgd.step"]
        # every block either hit a warm program or waited on the ahead
        # build — the consumer thread compiled nothing itself
        assert books["misses"] == 0
        assert books["hits"] == 4

    def test_cache_warm_across_checkpoint_resume(self, tmp_path,
                                                 bucket_env):
        """A resumed fit re-streams the same shapes: every step must be
        a cache hit — zero fresh compiles after resume."""
        from dask_ml_tpu.resilience import FitCheckpoint, fault_plan

        bucket_env("auto")
        rng = np.random.RandomState(0)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        path = str(tmp_path / "sgd.ck")

        def make():
            return SGDClassifier(
                random_state=0, max_iter=12, tol=None,
                fit_checkpoint=FitCheckpoint(path, every_n_iters=4),
            )

        with fault_plan() as plan:
            plan.inject("step", at_call=6)
            with pytest.raises(Exception):
                make().fit(X, y)
        programs.reset_counters()
        resumed = make().fit(X, y)
        books = programs.report()["programs"]
        assert books["sgd.step"]["misses"] == 0  # warm across resume
        ref = SGDClassifier(random_state=0, max_iter=12, tol=None).fit(X, y)
        np.testing.assert_array_equal(resumed.coef_, ref.coef_)

    def test_predict_bucketing_and_noop_assert(self, bucket_env):
        from dask_ml_tpu import _partial
        from dask_ml_tpu.diagnostics import (
            pipeline_report, reset_pipeline_stats,
        )

        bucket_env("64,512")
        rng = np.random.RandomState(0)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        clf = SGDClassifier(random_state=0, max_iter=3).fit(X, y)
        direct = np.asarray(clf.predict(X))
        # ragged chunks: predictions identical, pads counted
        reset_pipeline_stats()
        out = _partial.predict(clf, X, chunk_size=90)
        np.testing.assert_array_equal(out, direct)
        cum = pipeline_report()["cumulative"]["bucket"]
        assert cum["padded_blocks"] == 3  # 90, 90, 20 → all padded
        # bucket-sized chunks: the pad is a no-op fast path, asserted
        # through the pipeline stats split
        reset_pipeline_stats()
        out = _partial.predict(clf, X, chunk_size=64)
        np.testing.assert_array_equal(out, direct)
        cum = pipeline_report()["cumulative"]["bucket"]
        assert cum["blocks"] > 0 and cum["padded_blocks"] == 1
        assert cum["pad_rows"] == 64 - 200 % 64  # only the tail padded

    def test_ipca_tail_warm(self, bucket_env):
        from dask_ml_tpu.decomposition import IncrementalPCA

        bucket_env("auto")
        rng = np.random.RandomState(0)
        ip = IncrementalPCA(n_components=2)
        ip.partial_fit(rng.normal(size=(40, 5)).astype(np.float32))
        programs.reset_counters()
        # state exists now: staging a ragged tail warms its program
        staged = ip._pf_stage(rng.normal(size=(23, 5)).astype(np.float32))
        programs.drain_ahead()
        books = programs.report()["programs"]["ipca.update"]
        assert books["ahead_submitted"] == 1
        ip._pf_consume(staged)
        assert programs.report()["programs"]["ipca.update"]["misses"] == 0


# -- graftsan attribution -------------------------------------------------


class TestSanitizerAttribution:
    def test_steady_blessed_compile_allowed_and_counted(self, bucket_env):
        """The acceptance contract: a steady-phase compile on the
        blessed compile-ahead thread is ATTRIBUTED (ahead counters),
        never a violation — while steady_compiles stays a hard zero."""
        from dask_ml_tpu import sanitize as san

        bucket_env("auto")
        clf = SGDClassifier(random_state=0)
        with san.sanitize(label="ahead-attrib") as s:
            stream_partial_fit(
                clf, iter(_class_blocks((32,) * 3, d=7)), depth=2,
                fit_kwargs={"classes": np.array([0, 1])},
            )
            programs.drain_ahead()
            with s.steady():
                # a NEW bucket mid-steady: its compile must land on the
                # blessed thread (the stage hook warms it; the consumer
                # waits on the in-flight build)
                stream_partial_fit(
                    clf, iter(_class_blocks((300,) * 3, d=7, seed=5)),
                    depth=2,
                    fit_kwargs={"classes": np.array([0, 1])},
                )
                programs.drain_ahead()
        rep = s.last_report()
        assert rep["totals"]["steady_compiles"] == 0
        assert rep["totals"]["steady_ahead_compiles"] >= 1
        assert not rep["violations"]

    def test_unblessed_thread_steady_compile_still_violates(self):
        from dask_ml_tpu import sanitize as san
        from dask_ml_tpu.sanitize.core import (
            CompileViolation, DispatchViolation,
        )

        err = []

        def compile_elsewhere():
            try:
                jax.jit(lambda v: v * 2.0 + 0.123456)(jnp.ones(31))
            except (CompileViolation, DispatchViolation) as e:
                err.append(e)

        with san.sanitize(label="rogue-thread") as s:
            with s.steady(guard=False):
                t = threading.Thread(
                    target=compile_elsewhere, name="rogue-compiler")
                t.start()
                t.join()
        assert err or s.last_report()["violations"]

    def test_smoke_workload_registered(self):
        from dask_ml_tpu.sanitize.smoke import WORKLOADS

        assert "sgd_bucket_ahead" in WORKLOADS

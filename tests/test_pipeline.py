"""Input-pipeline tests (ISSUE 3 tentpole): overlap is real, results are
bit-identical at every prefetch depth, the stage split is measured, and
checkpoint/fault semantics survive prefetched in-flight blocks."""

import time

import numpy as np
import pytest

from dask_ml_tpu import _partial, diagnostics
from dask_ml_tpu.pipeline import (
    DEPTH_ENV,
    prefetch_blocks,
    resolve_depth,
    stream_partial_fit,
)


@pytest.fixture
def xy_blocks(rng):
    X = rng.normal(size=(1200, 6)).astype(np.float32)
    w = rng.normal(size=6)
    y = (X @ w > 0).astype(np.int32)
    return X, y


class TestResolveDepth:
    def test_explicit_wins(self):
        assert resolve_depth(0) == 0
        assert resolve_depth(5) == 5

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv(DEPTH_ENV, "7")
        assert resolve_depth(None) == 7
        monkeypatch.setenv(DEPTH_ENV, "0")
        assert resolve_depth(None) == 0

    def test_default_overlaps(self, monkeypatch):
        monkeypatch.delenv(DEPTH_ENV, raising=False)
        assert resolve_depth(None) >= 1

    def test_invalid(self, monkeypatch):
        with pytest.raises(ValueError):
            resolve_depth(-1)
        monkeypatch.setenv(DEPTH_ENV, "two")
        with pytest.raises(ValueError):
            resolve_depth(None)


class _SleepModel:
    """partial_fit consumer whose compute is a GIL-releasing sleep —
    the deterministic stand-in for a device step in the overlap A/B."""

    def __init__(self, step_s):
        self.step_s = step_s
        self.seen = []

    def partial_fit(self, X, y=None, **kw):
        time.sleep(self.step_s)
        self.seen.append(np.asarray(X).copy())
        return self


def _slow_reader(blocks, delay_s):
    for b in blocks:
        time.sleep(delay_s)  # artificially slowed parse stage
        yield b, None


class TestOverlap:
    def test_depth2_hides_reader_latency(self, rng):
        """Acceptance criterion: with an artificially slowed reader the
        depth>=2 streaming fit is measurably faster than depth=0 —
        overlap, not just buffering: the saving must approach the
        smaller stage's total, not merely beat noise."""
        blocks = [rng.normal(size=(64, 4)).astype(np.float32)
                  for _ in range(8)]
        delay = 0.03

        def run(depth):
            model = _SleepModel(step_s=delay)
            t0 = time.perf_counter()
            stream_partial_fit(
                model, _slow_reader(blocks, delay), depth=depth,
            )
            return time.perf_counter() - t0, model

        t_serial, m_serial = run(0)
        t_overlap, m_overlap = run(2)
        # serial ~ 16*delay, overlapped ~ 9*delay; require >= 20% saving
        assert t_overlap < t_serial * 0.8, (t_serial, t_overlap)
        # ...and identical consumption: same blocks, same order
        assert len(m_serial.seen) == len(m_overlap.seen) == 8
        for a, b in zip(m_serial.seen, m_overlap.seen):
            np.testing.assert_array_equal(a, b)

    def test_prefetch_blocks_orders_and_completes(self, rng):
        blocks = [rng.normal(size=(8, 3)) for _ in range(20)]
        for depth in (0, 1, 4):
            got = list(prefetch_blocks(iter(blocks), depth=depth))
            assert len(got) == 20
            for a, b in zip(blocks, got):
                np.testing.assert_array_equal(a, b)

    def test_early_close_stops_worker(self, rng):
        """Breaking out of a prefetched stream must not hang or keep
        consuming the source unboundedly."""
        pulled = []

        def src():
            for i in range(10_000):
                pulled.append(i)
                yield np.zeros((4, 2))

        it = prefetch_blocks(src(), depth=2)
        next(it)
        it.close()
        assert len(pulled) <= 8  # 1 consumed + bounded lookahead


class TestBitIdentical:
    """Acceptance criterion: every streaming estimator produces
    bit-identical results at every depth (0 = the serial seed path)."""

    DEPTHS = (0, 1, 3)

    def test_sgd_classifier(self, xy_blocks):
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks
        outs = {}
        for depth in self.DEPTHS:
            clf = SGDClassifier(random_state=0)
            _partial.fit(clf, X, y, chunk_size=256, prefetch_depth=depth,
                         classes=[0, 1])
            outs[depth] = (clf.coef_.copy(), clf.intercept_.copy())
        for depth in self.DEPTHS[1:]:
            np.testing.assert_array_equal(outs[0][0], outs[depth][0])
            np.testing.assert_array_equal(outs[0][1], outs[depth][1])

    def test_sgd_regressor(self, xy_blocks):
        from dask_ml_tpu.linear_model import SGDRegressor

        X, _ = xy_blocks
        yr = (X @ np.arange(6, dtype=np.float32)).astype(np.float32)
        outs = {}
        for depth in self.DEPTHS:
            reg = SGDRegressor(random_state=0)
            _partial.fit(reg, X, yr, chunk_size=256, prefetch_depth=depth)
            outs[depth] = reg.coef_.copy()
        for depth in self.DEPTHS[1:]:
            np.testing.assert_array_equal(outs[0], outs[depth])

    def test_minibatch_kmeans(self, xy_blocks):
        from dask_ml_tpu.cluster import MiniBatchKMeans

        X, _ = xy_blocks
        outs = {}
        for depth in self.DEPTHS:
            mbk = MiniBatchKMeans(n_clusters=5, random_state=0)
            _partial.fit(mbk, X, chunk_size=300, prefetch_depth=depth)
            outs[depth] = np.asarray(mbk.cluster_centers_).copy()
        for depth in self.DEPTHS[1:]:
            np.testing.assert_array_equal(outs[0], outs[depth])

    def test_incremental_pca(self, xy_blocks, monkeypatch):
        from dask_ml_tpu.decomposition import IncrementalPCA

        X, _ = xy_blocks
        outs = {}
        for depth in self.DEPTHS:
            monkeypatch.setenv(DEPTH_ENV, str(depth))
            ipca = IncrementalPCA(n_components=3, batch_size=256)
            ipca.fit(X)
            outs[depth] = (
                np.asarray(ipca.components_).copy(),
                np.asarray(ipca.mean_).copy(),
            )
        for depth in self.DEPTHS[1:]:
            np.testing.assert_array_equal(outs[0][0], outs[depth][0])
            np.testing.assert_array_equal(outs[0][1], outs[depth][1])

    def test_wrapped_sklearn_estimator(self, xy_blocks):
        """Host estimators take the raw-block fallback path — identical
        results there too (prefetch only reorders WHEN work happens,
        never WHAT or in what order)."""
        from sklearn.linear_model import SGDClassifier as SkSGD

        from dask_ml_tpu.wrappers import Incremental

        X, y = xy_blocks
        outs = {}
        for depth in self.DEPTHS:
            inc = Incremental(
                SkSGD(random_state=0, max_iter=5, tol=None),
                shuffle_blocks=False, chunk_size=256, prefetch_depth=depth,
            )
            inc.fit(X, y, classes=[0, 1])
            outs[depth] = inc.estimator_.coef_.copy()
        for depth in self.DEPTHS[1:]:
            np.testing.assert_array_equal(outs[0], outs[depth])

    def test_shuffled_spans_still_identical(self, xy_blocks):
        """shuffle_blocks permutes the visit order BEFORE the stream —
        the permutation is a function of random_state, not of depth."""
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks
        outs = {}
        for depth in (0, 2):
            clf = SGDClassifier(random_state=0)
            _partial.fit(clf, X, y, chunk_size=256, shuffle_blocks=True,
                         random_state=42, prefetch_depth=depth,
                         classes=[0, 1])
            outs[depth] = clf.coef_.copy()
        np.testing.assert_array_equal(outs[0], outs[2])


class TestIteratorSource:
    def test_stream_of_tuples(self, xy_blocks):
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks
        ref = SGDClassifier(random_state=0)
        _partial.fit(ref, X, y, chunk_size=300, prefetch_depth=0,
                     classes=[0, 1])
        stream = ((X[i:i + 300], y[i:i + 300])
                  for i in range(0, len(X), 300))
        clf = SGDClassifier(random_state=0)
        _partial.fit(clf, iter(stream), prefetch_depth=2, classes=[0, 1])
        np.testing.assert_array_equal(ref.coef_, clf.coef_)

    def test_iterator_rejects_separate_y(self, xy_blocks):
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks
        with pytest.raises(ValueError, match="ride the stream"):
            _partial.fit(SGDClassifier(), iter([(X, y)]), y,
                         classes=[0, 1])

    def test_iterator_ignores_shuffle(self, xy_blocks):
        """shuffle_blocks is a no-op for one-shot streams — crucially,
        Incremental's DEFAULT (True) must not make direct reader feeds
        error; blocks train in stream order either way."""
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks
        mk = lambda: ((X[i:i + 300], y[i:i + 300])  # noqa: E731
                      for i in range(0, len(X), 300))
        ref = SGDClassifier(random_state=0)
        _partial.fit(ref, iter(mk()), classes=[0, 1])
        clf = SGDClassifier(random_state=0)
        _partial.fit(clf, iter(mk()), shuffle_blocks=True, classes=[0, 1])
        np.testing.assert_array_equal(ref.coef_, clf.coef_)

    def test_incremental_default_args_accept_stream(self, xy_blocks):
        """The advertised direct feed — Incremental(est).fit(reader) —
        must work with an all-default constructor."""
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.wrappers import Incremental

        X, y = xy_blocks
        stream = ((X[i:i + 300], y[i:i + 300])
                  for i in range(0, len(X), 300))
        inc = Incremental(SGDClassifier(random_state=0))
        inc.fit(iter(stream), classes=[0, 1])
        ref = SGDClassifier(random_state=0)
        _partial.fit(ref, X, y, chunk_size=300, prefetch_depth=0,
                     classes=[0, 1])
        np.testing.assert_array_equal(ref.coef_, inc.estimator_.coef_)

    def test_mid_stream_stage_decline_falls_back(self, xy_blocks, mesh):
        """A heterogeneous stream — host blocks with a device-resident
        (ShardedRows) block in the middle — must degrade that one block
        to serial partial_fit, not crash the staged pipeline."""
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks

        def mixed():
            for i in range(0, len(X), 300):
                xb, yb = X[i:i + 300], y[i:i + 300]
                if i == 300:  # second block arrives device-resident
                    yield shard_rows(xb), shard_rows(
                        yb.astype(np.float32))
                else:
                    yield xb, yb

        clf = SGDClassifier(random_state=0)
        _partial.fit(clf, mixed(), prefetch_depth=2, classes=[0, 1])
        ref = SGDClassifier(random_state=0)
        _partial.fit(ref, mixed(), prefetch_depth=0, classes=[0, 1])
        np.testing.assert_array_equal(ref.coef_, clf.coef_)

    def test_predict_iterator_and_depths(self, xy_blocks):
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks
        clf = SGDClassifier(random_state=0)
        _partial.fit(clf, X, y, chunk_size=300, classes=[0, 1])
        p0 = _partial.predict(clf, X, chunk_size=250, prefetch_depth=0)
        p2 = _partial.predict(clf, X, chunk_size=250, prefetch_depth=2)
        pit = _partial.predict(
            clf, iter(X[i:i + 250] for i in range(0, len(X), 250)),
            prefetch_depth=2,
        )
        np.testing.assert_array_equal(p0, p2)
        np.testing.assert_array_equal(p0, pit)


class TestStageSplit:
    def test_pipeline_report_has_split(self, xy_blocks):
        """Acceptance criterion: pipeline_report() returns a
        parse/transfer/compute split for a streamed fit."""
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = xy_blocks
        diagnostics.reset()  # one-call isolation (pipeline + registry)
        clf = SGDClassifier(random_state=0)
        _partial.fit(clf, X, y, chunk_size=256, prefetch_depth=2,
                     classes=[0, 1])
        rep = diagnostics.pipeline_report()
        assert rep["streams"] == 1
        assert rep["blocks"] == 5  # ceil(1200/256)
        assert rep["staged"] is True and rep["depth"] == 2
        for key in ("parse_s", "transfer_s", "compute_s", "stall_s",
                    "wall_s", "hidden_s"):
            assert rep[key] >= 0.0
        assert rep["compute_s"] > 0.0
        assert rep["cumulative"]["streams"] == 1

    def test_report_empty_when_reset(self):
        diagnostics.reset_pipeline_stats()
        assert diagnostics.pipeline_report() == {"streams": 0}


class TestFaultSemantics:
    def test_worker_fault_surfaces_at_position(self, rng):
        """A reader fault propagates to the consumer at the failed
        block's position: earlier blocks are consumed, later never."""

        def src():
            for i in range(6):
                if i == 3:
                    raise OSError("disk went away")
                yield rng.normal(size=(16, 3)).astype(np.float32), None

        model = _SleepModel(step_s=0.0)
        with pytest.raises(OSError, match="disk went away"):
            stream_partial_fit(model, src(), depth=2)
        assert len(model.seen) == 3

    def test_ingest_retry_inside_worker(self, tmp_path, rng):
        """io-reader retries run INSIDE the prefetch worker: an absorbed
        transient fault changes nothing about the delivered stream."""
        from dask_ml_tpu import io as dio
        from dask_ml_tpu.resilience.testing import FaultPlan, fault_plan

        X = rng.normal(size=(400, 5)).astype(np.float32)
        p = tmp_path / "r.bin"
        X.tofile(p)
        clean = [
            b.copy() for b in prefetch_blocks(
                dio.stream_binary_blocks(str(p), 100, 5), depth=2)
        ]
        plan = FaultPlan()
        plan.inject("ingest", at_call=2, times=1)
        with fault_plan(plan):
            got = [
                b.copy() for b in prefetch_blocks(
                    dio.stream_binary_blocks(str(p), 100, 5, retries=2),
                    depth=2)
            ]
        assert plan.fired["ingest"] == 1
        assert len(got) == len(clean) == 4
        for a, b in zip(clean, got):
            np.testing.assert_array_equal(a, b)

    def test_step_fault_count_matches_serial(self, xy_blocks):
        """The staged path fires the 'step' injection point once per
        consumed block, exactly like serial partial_fit."""
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.resilience.testing import FaultPlan, fault_plan

        X, y = xy_blocks
        counts = {}
        for depth in (0, 2):
            plan = FaultPlan()  # no injections: just count arrivals
            with fault_plan(plan):
                clf = SGDClassifier(random_state=0)
                _partial.fit(clf, X, y, chunk_size=256,
                             prefetch_depth=depth, classes=[0, 1])
            counts[depth] = plan.calls["step"]
        assert counts[0] == counts[2] == 5


class TestCheckpointResume:
    def test_ipca_resume_under_prefetch_matches_serial(self, tmp_path,
                                                       xy_blocks,
                                                       monkeypatch):
        """FitCheckpoint safety: a fit killed mid-stream (prefetched
        blocks in flight) resumes to the SAME result as an
        uninterrupted serial fit — in-flight staged blocks never touch
        the state, so the snapshot boundary is exact."""
        from dask_ml_tpu.decomposition import IncrementalPCA
        from dask_ml_tpu.resilience import FitCheckpoint
        from dask_ml_tpu.resilience.testing import (
            FaultInjected, FaultPlan, fault_plan,
        )

        X, _ = xy_blocks
        monkeypatch.setenv(DEPTH_ENV, "2")
        ref = IncrementalPCA(n_components=3, batch_size=200).fit(X)

        path = str(tmp_path / "ipca.ckpt")
        ipca = IncrementalPCA(
            n_components=3, batch_size=200,
            fit_checkpoint=FitCheckpoint(path, every_n_iters=1),
        )
        plan = FaultPlan()
        plan.inject("step", at_call=3, times=1)
        with fault_plan(plan):
            with pytest.raises(FaultInjected):
                ipca.fit(X)
        assert plan.fired["step"] == 1
        ipca.fit(X)  # resumes from the snapshot, finishes the sweep
        np.testing.assert_allclose(
            np.asarray(ipca.components_), np.asarray(ref.components_),
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(ipca.mean_), np.asarray(ref.mean_), rtol=1e-6,
        )


class TestSearchIngest:
    def test_incremental_search_depth_invariant(self, xy_blocks):
        """The adaptive-search ingest path (train_one streamed bursts)
        returns the same winner and scores at every depth."""
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.model_selection import IncrementalSearchCV

        X, y = xy_blocks
        results = {}
        for depth in (0, 2):
            import os
            os.environ[DEPTH_ENV] = str(depth)
            try:
                search = IncrementalSearchCV(
                    SGDClassifier(random_state=0),
                    {"alpha": [1e-4, 1e-2], "eta0": [0.01, 0.1]},
                    n_initial_parameters="grid",
                    random_state=0, max_iter=6, fits_per_score=3,
                )
                search.fit(X, y, classes=[0, 1])
            finally:
                os.environ.pop(DEPTH_ENV, None)
            results[depth] = (
                search.best_params_,
                {m: r[-1]["partial_fit_calls"]
                 for m, r in search.model_history_.items()},
            )
        assert results[0][0] == results[2][0]
        assert results[0][1] == results[2][1]

"""The online inference plane (dask_ml_tpu/serve/, design.md §15).

Covers the serving acceptance criteria end to end: micro-batched
correctness vs direct predict, lane-packed multi-model dispatch,
admission control (queue_full / deadline / oversize as explicit
rejections), residency eviction under an HBM budget, supervised
restart with in-flight replay, the zero-steady-compile contract under
an armed graftsan scope, donation through the serve predict programs
(surviving a bucket-size change), and the /metrics export of request
latency quantiles.
"""

import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from dask_ml_tpu import diagnostics, obs
from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor
from dask_ml_tpu.resilience import supervisor as _supervisor
from dask_ml_tpu.resilience.elastic import FaultBudget
from dask_ml_tpu.resilience.testing import (
    FaultPlan,
    ThreadCrash,
    fault_plan,
)
from dask_ml_tpu.serve import (
    ModelServer,
    RequestRejected,
    SERVE_THREAD_NAME,
    serve_pack_key,
)
from dask_ml_tpu.serve import programs as sprog


def _fitted_clf(seed=0, d=8, n=512, classes=2, **kw):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    if classes == 2:
        y = (X[:, 0] > 0).astype(np.int32)
    else:
        y = (np.argmax(X[:, :classes], axis=1)).astype(np.int32)
    clf = SGDClassifier(random_state=seed, **kw)
    clf.partial_fit(X, y, classes=np.arange(classes))
    return clf, X


def _fitted_reg(seed=0, d=6, n=256):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = X @ rng.normal(size=d).astype(np.float32)
    reg = SGDRegressor(random_state=seed)
    reg.partial_fit(X, y)
    return reg, X


class TestBasicServing:
    def test_classifier_matches_direct_predict(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_basic", window_s=0.0) as srv:
            assert srv.load("m", clf) is True
            for rows in (1, 3, 16):
                got = srv.predict("m", X[:rows])
                np.testing.assert_array_equal(
                    got, np.asarray(clf.predict(X[:rows])))

    def test_single_row_1d_input(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_1d", window_s=0.0) as srv:
            srv.load("m", clf)
            got = srv.predict("m", X[0])
            assert got.shape == (1,)
            np.testing.assert_array_equal(
                got, np.asarray(clf.predict(X[:1])))

    def test_multiclass_and_regressor(self):
        clf, Xc = _fitted_clf(classes=3, d=5)
        reg, Xr = _fitted_reg()
        with ModelServer(label="t_multi", window_s=0.0) as srv:
            srv.load("c", clf)
            srv.load("r", reg)
            np.testing.assert_array_equal(
                srv.predict("c", Xc[:9]), np.asarray(clf.predict(Xc[:9])))
            np.testing.assert_allclose(
                srv.predict("r", Xr[:9]), np.asarray(reg.predict(Xr[:9])),
                rtol=1e-6)

    def test_generic_estimator_serves(self):
        from dask_ml_tpu.cluster import MiniBatchKMeans

        rng = np.random.RandomState(3)
        X = rng.normal(size=(256, 4)).astype(np.float32)
        mbk = MiniBatchKMeans(n_clusters=3, random_state=0)
        mbk.partial_fit(X)
        with ModelServer(label="t_generic", window_s=0.0) as srv:
            srv.load("k", mbk)
            got = srv.predict("k", X[:7])
            np.testing.assert_array_equal(
                got, np.asarray(mbk.predict(X[:7])))

    def test_unknown_model_and_unload(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_unknown", window_s=0.0) as srv:
            with pytest.raises(RequestRejected) as ei:
                srv.submit("nope", X[:1])
            assert ei.value.reason == "unknown_model"
            srv.load("m", clf)
            assert srv.predict("m", X[:1]).shape == (1,)
            assert srv.unload("m") is True
            with pytest.raises(RequestRejected):
                srv.submit("m", X[:1])

    def test_oversize_and_bad_input_reject(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_oversize", window_s=0.0,
                         max_batch=32) as srv:
            srv.load("m", clf)
            with pytest.raises(RequestRejected) as ei:
                srv.submit("m", np.zeros((33, 8), np.float32))
            assert ei.value.reason == "oversize"
            with pytest.raises(RequestRejected) as ei:
                srv.submit("m", np.zeros((4, 5), np.float32))
            assert ei.value.reason == "bad_input"

    def test_empty_request_resolves_immediately(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_empty", window_s=0.0) as srv:
            srv.load("m", clf)
            out = srv.submit("m", np.zeros((0, 8), np.float32)).result(1)
            assert out.shape == (0,)

    def test_closed_server_rejects(self):
        clf, X = _fitted_clf()
        srv = ModelServer(label="t_closed", window_s=0.0)
        srv.load("m", clf)
        srv.close()
        with pytest.raises(RequestRejected) as ei:
            srv.submit("m", X[:1])
        assert ei.value.reason == "shutdown"


class TestMicroBatching:
    def test_concurrent_requests_coalesce(self):
        """Acceptance criterion: batch occupancy > 1 row/dispatch under
        load — submits landing inside one gather window dispatch as ONE
        program."""
        clf, X = _fitted_clf()
        reg = obs.registry()
        reg.reset(prefix="serve.batch_requests")
        with ModelServer(label="t_coalesce", window_s=0.1) as srv:
            srv.load("m", clf)
            srv.predict("m", X[:1])  # warm the request path
            reg.reset(prefix="serve.batch_requests")
            reg.reset(prefix="serve.batch_rows")
            futs = [srv.submit("m", X[i:i + 1]) for i in range(8)]
            outs = [f.result(10) for f in futs]
            for i, o in enumerate(outs):
                np.testing.assert_array_equal(
                    o, np.asarray(clf.predict(X[i:i + 1])))
        snap = reg.histogram("serve.batch_requests").snapshot()
        assert snap["count"] >= 1
        # 8 requests in << window: strictly fewer dispatches than
        # requests, i.e. occupancy above one request per dispatch
        assert snap["count"] < 8, snap
        rows = reg.histogram("serve.batch_rows").snapshot()
        assert rows["sum"] / rows["count"] > 1.0

    def test_row_ceiling_splits_batches(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_ceiling", window_s=0.1,
                         max_batch=8) as srv:
            srv.load("m", clf)
            futs = [srv.submit("m", X[i * 4:(i + 1) * 4])
                    for i in range(4)]  # 16 rows > max_batch 8
            for i, f in enumerate(futs):
                np.testing.assert_array_equal(
                    f.result(10),
                    np.asarray(clf.predict(X[i * 4:(i + 1) * 4])))


class TestLanePacking:
    def test_pack_key_is_shape_based(self):
        clf1, _ = _fitted_clf(seed=0)
        clf2, _ = _fitted_clf(seed=1, penalty="l1")  # different config
        reg, _ = _fitted_reg()
        assert serve_pack_key(clf1) == serve_pack_key(clf2)
        assert serve_pack_key(clf1) != serve_pack_key(reg)
        assert serve_pack_key(object()) is None

    def test_homogeneous_models_lane_dispatch(self):
        clf1, X = _fitted_clf(seed=0)
        clf2, _ = _fitted_clf(seed=1, penalty="l1")
        reg = obs.registry()
        with ModelServer(label="t_lane", window_s=0.1) as srv:
            srv.load("a", clf1)
            srv.load("b", clf2)
            srv.predict("a", X[:1])  # warm the request path
            before = reg.counter("serve.lane_dispatches").value
            fa = srv.submit("a", X[:8])
            fb = srv.submit("b", X[:8])
            np.testing.assert_array_equal(
                fa.result(10), np.asarray(clf1.predict(X[:8])))
            np.testing.assert_array_equal(
                fb.result(10), np.asarray(clf2.predict(X[:8])))
            assert reg.counter("serve.lane_dispatches").value > before


class TestAdmissionControl:
    def test_queue_full_sheds_load_explicitly(self):
        clf, X = _fitted_clf()
        reg = obs.registry()
        with ModelServer(label="t_queue", window_s=0.0,
                         queue_depth=2) as srv:
            srv.load("m", clf)
            srv.predict("m", X[:1])
            srv._test_dispatch_delay_s = 0.3  # wedge the loop briefly
            first = srv.submit("m", X[:1])  # drained, then slow
            time.sleep(0.05)
            held = []
            rejected = 0
            for _ in range(8):
                try:
                    held.append(srv.submit("m", X[:1]))
                except RequestRejected as e:
                    assert e.reason == "queue_full"
                    rejected += 1
            assert rejected >= 1
            assert reg.family("serve.rejected").get("queue_full", 0) >= 1
            srv._test_dispatch_delay_s = 0.0
            first.result(10)
            for f in held:
                f.result(10)

    def test_deadline_drops_stale_work_before_dispatch(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_deadline", window_s=0.0) as srv:
            srv.load("m", clf)
            srv.predict("m", X[:1])
            srv._test_dispatch_delay_s = 0.25
            blocker = srv.submit("m", X[:1])
            time.sleep(0.05)
            stale = srv.submit("m", X[:1], deadline_s=0.01)
            with pytest.raises(RequestRejected) as ei:
                stale.result(10)
            assert ei.value.reason == "deadline"
            srv._test_dispatch_delay_s = 0.0
            blocker.result(10)


class TestResidency:
    def test_lru_eviction_under_hbm_budget(self):
        # three ~16KB states (distinct widths, so no lane pack shares
        # them) against a ~31KB budget: the LRU models park
        fitted = {}
        for i, d in enumerate((4096, 4097, 4098)):
            clf, X = _fitted_clf(seed=i, d=d, n=64)
            fitted[f"m{i}"] = (clf, X)
        reg = obs.registry()
        before = reg.counter("serve.evictions").value
        with ModelServer(label="t_evict", window_s=0.0,
                         hbm_budget_mb=0.03) as srv:
            for name, (clf, _) in fitted.items():
                srv.load(name, clf)
            rep = srv.report()["residency"]
            assert rep["resident_bytes"] <= rep["budget_bytes"], rep
            assert reg.counter("serve.evictions").value > before
            parked = [n for n, info in rep["models"].items()
                      if not info["resident"]]
            assert parked, rep
            # a parked model still serves (one residency fault, then
            # resident again)
            name = parked[0]
            clf, X = fitted[name]
            got = srv.predict(name, X[:4])
            np.testing.assert_array_equal(
                got, np.asarray(clf.predict(X[:4])))
            assert reg.family("serve.residency_fault").get(name, 0) >= 1


class TestSupervisedRestart:
    def test_crash_restart_replays_inflight(self):
        clf, X = _fitted_clf()
        plan = FaultPlan().inject(
            "serve-loop", at_call=2, times=1,
            exc=ThreadCrash("test: serve loop death"))
        with ModelServer(label="t_crash", window_s=0.0,
                         budget=FaultBudget(4, 60.0,
                                            name="t_crash")) as srv:
            unit = srv._unit
            srv.load("m", clf)
            with fault_plan(plan):
                srv.predict("m", X[:2])  # batch 1: healthy
                fut = srv.submit("m", X[:4])  # batch 2: crash in hand
                for _ in range(500):
                    if not srv._thread.is_alive():
                        break
                    time.sleep(0.01)
                assert not srv._thread.is_alive()
                assert unit in _supervisor.healthz()["dead"]
                # the parked future wait triggers restart + exact replay
                got = fut.result(30)
                np.testing.assert_array_equal(
                    got, np.asarray(clf.predict(X[:4])))
                assert unit not in _supervisor.healthz()["dead"]
                assert srv.report()["budget"]["spent"] >= 1
            # post-restart traffic flows
            srv.predict("m", X[:1])

    def test_budget_exhaustion_rejects_loudly(self):
        clf, X = _fitted_clf()
        plan = FaultPlan().persistent(
            "serve-loop", exc=ThreadCrash("test: repeated death"))
        with ModelServer(label="t_budget", window_s=0.0,
                         budget=FaultBudget(0, 60.0,
                                            name="t_budget")) as srv:
            srv.load("m", clf)
            with fault_plan(plan):
                fut = srv.submit("m", X[:1])
                with pytest.raises(RequestRejected) as ei:
                    fut.result(30)
                assert ei.value.reason == "serve_down"
                with pytest.raises(RequestRejected):
                    srv.submit("m", X[:1])


class TestDonation:
    def test_proba_transform_donates_the_margins(self):
        """The device probability transform consumes its margins buffer
        in place (same-shaped output → the donation actually aliases);
        the batch buffer is deliberately NOT donated — the gemm has no
        same-shaped output, so that donation would be a no-op (design.md
        §8's reasoning, applied to serving)."""
        clf, _ = _fitted_clf(d=8)
        coef, inter = clf._state["coef"], clf._state["intercept"]
        xb = jnp.zeros((256, 8), jnp.float32)
        m = sprog.margins(coef, inter, xb)
        assert not xb.is_deleted()  # documented non-donation
        p = sprog.proba(m, loss="log_loss")
        assert p.shape == m.shape == (256, 1)
        assert m.is_deleted(), "margins buffer must be consumed in place"

    def test_donation_survives_a_bucket_size_change(self):
        """Regression: every per-signature AOT executable the cache
        mints — including the fresh one when a coalesced batch crosses
        a bucket rung — carries the donation."""
        clf, _ = _fitted_clf(d=8)
        coef, inter = clf._state["coef"], clf._state["intercept"]
        for rung in (256, 1024, 256, 4096):
            m = sprog.margins(coef, inter,
                              jnp.zeros((rung, 8), jnp.float32))
            sprog.proba(m, loss="log_loss")
            assert m.is_deleted(), f"rung {rung} lost donation"

    def test_lane_refresh_updates_the_stack_in_place(self):
        """The hot-swap program donates BOTH resident stacks: the new
        lane state lands in the same HBM buffers, at every pack size."""
        for M in (2, 3):
            coefs = jnp.zeros((M, 8, 1), jnp.float32)
            inters = jnp.zeros((M, 1), jnp.float32)
            nc, ni = sprog.lane_refresh(
                coefs, inters, jnp.ones((8, 1), jnp.float32),
                jnp.full((1,), 2.0, jnp.float32), jnp.int32(1))
            assert coefs.is_deleted() and inters.is_deleted()
            assert float(nc[1, 0, 0]) == 1.0
            assert float(ni[1, 0]) == 2.0
            assert float(nc[0, 0, 0]) == 0.0

    def test_bucket_crossing_requests_stay_correct(self):
        clf, X = _fitted_clf(d=8, n=2048)
        with ModelServer(label="t_cross", window_s=0.0) as srv:
            srv.load("m", clf)
            for rows in (10, 600, 10):  # 256-rung -> 1024-rung -> back
                np.testing.assert_array_equal(
                    srv.predict("m", X[:rows]),
                    np.asarray(clf.predict(X[:rows])))


class TestProbaServing:
    def test_predict_proba_matches_direct(self):
        clf, X = _fitted_clf()  # log_loss default
        with ModelServer(label="t_proba", window_s=0.0) as srv:
            srv.load("m", clf)
            got = srv.predict_proba("m", X[:12])
            np.testing.assert_allclose(
                got, np.asarray(clf.predict_proba(X[:12])), rtol=1e-6)

    def test_mixed_label_and_proba_requests_share_one_margins(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_mixed", window_s=0.1) as srv:
            srv.load("m", clf)
            srv.predict("m", X[:1])
            fa = srv.submit("m", X[:4])
            fb = srv.submit("m", X[4:8], proba=True)
            np.testing.assert_array_equal(
                fa.result(10), np.asarray(clf.predict(X[:4])))
            np.testing.assert_allclose(
                fb.result(10), np.asarray(clf.predict_proba(X[4:8])),
                rtol=1e-6)

    def test_proba_rejected_for_unsupported_loss(self):
        clf, X = _fitted_clf(loss="hinge")
        reg, Xr = _fitted_reg()
        with ModelServer(label="t_noproba", window_s=0.0) as srv:
            srv.load("h", clf)
            srv.load("r", reg)
            for name, rows in (("h", X[:2]), ("r", Xr[:2])):
                with pytest.raises(RequestRejected) as ei:
                    srv.submit(name, rows, proba=True)
                assert ei.value.reason == "bad_input"


class TestHotSwap:
    def test_reload_refreshes_the_lane_in_place(self):
        clf1, X = _fitted_clf(seed=0)
        clf2, _ = _fitted_clf(seed=1, penalty="l1")
        clf3, _ = _fitted_clf(seed=2, alpha=1e-2)
        reg = obs.registry()
        with ModelServer(label="t_swap", window_s=0.1) as srv:
            srv.load("a", clf1)
            srv.load("b", clf2)
            srv.predict("a", X[:1])
            before = reg.counter("serve.lane_refresh").value
            srv.load("a", clf3)  # deploy: same name, live pack stack
            assert reg.counter("serve.lane_refresh").value == before + 1
            # lane-packed traffic serves the NEW model from the stack
            fa = srv.submit("a", X[:8])
            fb = srv.submit("b", X[:8])
            np.testing.assert_array_equal(
                fa.result(10), np.asarray(clf3.predict(X[:8])))
            np.testing.assert_array_equal(
                fb.result(10), np.asarray(clf2.predict(X[:8])))


class TestLadderRungs:
    def test_rungs_cover_every_reachable_bucket(self):
        from dask_ml_tpu.programs import resolve_policy, bucket_rows

        pol = resolve_policy("auto")
        for max_rows in (1, 100, 1024, 70_000, 300_000):
            rungs = set(pol.rungs(max_rows))
            for n in (1, max_rows // 2 or 1, max_rows):
                assert bucket_rows(n, pol) in rungs, (max_rows, n)

    def test_rungs_off_and_pow2(self):
        from dask_ml_tpu.programs import resolve_policy

        assert resolve_policy("off").rungs(1000) == ()
        p2 = resolve_policy("pow2").rungs(1000)
        assert p2[-1] == 1024 and p2[0] == 1

    def test_knob_strict_parse(self):
        from dask_ml_tpu.serve import config

        with pytest.raises(ValueError):
            config.resolve_max_batch(0)
        with pytest.raises(ValueError):
            config.resolve_window_s(-1.0)
        with pytest.raises(ValueError):
            config.resolve_hbm_budget_bytes(0)

    def test_knob_env_typo_raises(self, monkeypatch):
        from dask_ml_tpu.serve import config

        monkeypatch.setenv(config.MAX_BATCH_ENV, "lots")
        with pytest.raises(ValueError, match="SERVE_MAX_BATCH"):
            config.resolve_max_batch()


class TestServeThreadContract:
    def test_thread_name_single_source(self):
        from dask_ml_tpu.analysis.rules._spmd import (
            BLESSED_DISPATCH_THREADS,
        )

        assert SERVE_THREAD_NAME in BLESSED_DISPATCH_THREADS

    def test_load_is_the_only_compiling_moment(self):
        """Admission pre-compiles every bucket rung the batcher can
        produce, so a request stream that walks the whole ladder adds
        ZERO programs after load (the steady-compile contract's cache
        half — the sanitizer test pins the runtime half)."""
        clf, X = _fitted_clf(d=13, n=2048)  # width no other test uses
        with ModelServer(label="t_warmset", window_s=0.0) as srv:
            srv.load("m", clf)
            before = sprog.margins.report()
            for rows in (1, 200, 300, 1024):
                srv.predict("m", X[:rows])
            after = sprog.margins.report()
            # every dispatch above was a warm hit: no new programs, no
            # demand misses, no jit fallbacks
            assert after["programs"] == before["programs"]
            assert after["misses"] == before["misses"]
            assert after["fallback"] == before["fallback"]


class TestSteadyServeSanitized:
    def test_steady_traffic_zero_compiles_and_blessed_dispatch(
            self, sanitizer):
        """Satellite + acceptance: concurrent clients against two
        resident models sustain traffic under an ARMED graftsan scope —
        zero steady compiles, zero violations, every dispatch on the
        blessed serve thread, occupancy above one row per dispatch."""
        # a width no other test serves: the loads REALLY compile here,
        # on the serve thread, under the armed fail-fast sanitizer —
        # proving load-time warm compiles are legal on that thread
        clf1, X = _fitted_clf(seed=0, d=11)
        clf2, _ = _fitted_clf(seed=1, d=11, penalty="l1")
        reg = obs.registry()
        with ModelServer(label="t_sanitized", window_s=0.02) as srv:
            # warmup phase: loads compile + first traffic settles
            srv.load("a", clf1)
            srv.load("b", clf2)
            for _ in range(3):
                srv.predict("a", X[:1])
                srv.predict("b", X[:3])
            reg.reset(prefix="serve.batch_rows")
            # expected answers computed in WARMUP (direct predict is
            # eager device work — doing it inside a client thread
            # during steady would itself be the violation)
            specs = (("a", clf1, 0), ("a", clf1, 50),
                     ("b", clf2, 100), ("b", clf2, 150))
            expected = {
                (name, lo): [np.asarray(model.predict(
                    X[lo + i:lo + i + 2])) for i in range(10)]
                for name, model, lo in specs
            }
            with sanitizer.steady():
                errs = []

                def client(name, model, lo):
                    try:
                        for i in range(10):
                            got = srv.predict(
                                name, X[lo + i:lo + i + 2], timeout=30)
                            np.testing.assert_array_equal(
                                got, expected[(name, lo)][i])
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

                threads = [
                    threading.Thread(target=client, args=args)
                    for args in specs
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(60)
                assert not errs, errs
        rep = sanitizer.report()
        assert rep["totals"]["steady_compiles"] == 0, rep["violations"]
        assert rep["violations"] == []
        assert SERVE_THREAD_NAME in rep["dispatch_threads"]
        rows = reg.histogram("serve.batch_rows").snapshot()
        assert rows["sum"] / max(rows["count"], 1) > 1.0, rows


class TestObservability:
    def test_serve_report_shapes(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_report", window_s=0.0) as srv:
            srv.load("m", clf)
            srv.predict("m", X[:4])
            rep = diagnostics.serve_report()
            labels = [s["label"] for s in rep["servers"]]
            assert "t_report" in labels
            assert any(k.startswith("serve.request_s") for k in
                       rep["metrics"])
            assert "serve" in diagnostics.run_report()

    def test_request_latency_exported_through_metrics_endpoint(self):
        """Acceptance: measured p50/p99 request latency is scrapeable
        from the live /metrics endpoint."""
        from dask_ml_tpu.obs import serve as obs_serve

        clf, X = _fitted_clf()
        srv_http = obs_serve.start(0)
        try:
            with ModelServer(label="t_scrape", window_s=0.0) as srv:
                srv.load("m", clf)
                for i in range(5):
                    srv.predict("m", X[i:i + 2])
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{srv_http.port}/metrics",
                    timeout=5).read().decode()
            assert "serve_request_s" in body
            assert 'quantile="0.99"' in body
            assert "serve_batch_rows" in body
        finally:
            obs_serve.stop()

    def test_healthz_reflects_serve_unit(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_hz", window_s=0.0) as srv:
            srv.load("m", clf)
            assert _supervisor.verdicts().get(srv._unit) == "healthy"
        assert srv._unit not in _supervisor.verdicts()

    def test_duplicate_labels_get_distinct_units(self):
        """Two servers sharing a label must not share a heartbeat — a
        dead loop hiding behind its twin's live thread would never flip
        /healthz."""
        with ModelServer(label="t_dup", window_s=0.0), \
                ModelServer(label="t_dup", window_s=0.0):
            units = [u for u in _supervisor.verdicts()
                     if u.startswith("serve:t_dup")]
            assert len(units) == 2, units
        assert not [u for u in _supervisor.verdicts()
                    if u.startswith("serve:t_dup")]


class TestGenericWarmup:
    """ISSUE 13 satellite: device-native GENERIC estimators get
    load-time predict warmup + bucket-padded dispatch, so the steady
    request path never compiles for ANY admitted model — pinned under
    an armed sanitizer, like the SGD family above."""

    def _fitted_mbk(self, d=6):
        from dask_ml_tpu.cluster import MiniBatchKMeans

        rng = np.random.RandomState(3)
        X = rng.normal(size=(256, d)).astype(np.float32)
        return MiniBatchKMeans(n_clusters=3, random_state=0).fit(X), X

    def test_generic_steady_requests_never_compile(self, sanitizer):
        mbk, X = self._fitted_mbk()
        with ModelServer(label="t_generic_warm", window_s=0.0) as srv:
            srv.load("mbk", mbk)          # warmup: per-rung compiles
            srv.predict("mbk", X[:1])
            with sanitizer.steady():
                # ladder-walking shapes: every request pads to a rung
                # the load already compiled
                for n in (1, 3, 7, 16, 33):
                    got = srv.predict("mbk", X[:n])
                    assert len(got) == n
        rep = sanitizer.report()
        assert rep["totals"]["steady_compiles"] == 0, rep["violations"]
        assert rep["violations"] == []

    def test_generic_padded_predictions_match_direct(self):
        mbk, X = self._fitted_mbk()
        direct = np.asarray(mbk.predict(X[:33]))
        with ModelServer(label="t_generic_eq", window_s=0.0) as srv:
            srv.load("mbk", mbk)
            served = srv.predict("mbk", X[:33])
        np.testing.assert_array_equal(direct, served)

    def test_host_generic_still_sees_raw_rows(self):
        """Host sklearn models keep the raw-row path: padding would
        waste their whole-batch compute (the _partial.predict gate)."""
        from sklearn.linear_model import LogisticRegression

        rng = np.random.RandomState(5)
        X = rng.normal(size=(64, 4))
        y = (X[:, 0] > 0).astype(int)
        seen = []

        class SpyLR(LogisticRegression):
            def predict(self, X):
                seen.append(np.asarray(X).shape[0])
                return super().predict(X)

        model = SpyLR(max_iter=50).fit(X, y)
        with ModelServer(label="t_generic_host", window_s=0.0) as srv:
            srv.load("lr", model)
            got = srv.predict("lr", X[:5])
        assert len(got) == 5
        assert 5 in seen and all(s in (64, 5) for s in seen), seen


class TestDrainBarrier:
    """PR 19 satellite: ``drain()`` stops admission with an explicit
    ``draining`` rejection (counter + flight event — the honesty
    contract's newest reason), flushes in-flight work, and ``resume()``
    re-admits — the per-replica building block of rolling deploys."""

    def test_drain_rejects_with_counted_reason(self):
        from dask_ml_tpu.obs.metrics import registry as _registry

        clf, X = _fitted_clf()
        reg = _registry()
        with ModelServer(label="t_drain", window_s=0.0) as srv:
            srv.load("m", clf)
            srv.predict("m", X[:2])
            before = reg.family("serve.rejected").get("draining", 0)
            assert srv.drain(timeout_s=5.0) is True
            assert srv.draining() is True
            assert srv.ready() is False
            with pytest.raises(RequestRejected) as ei:
                srv.submit("m", X[:1])
            assert ei.value.reason == "draining"
            assert reg.family("serve.rejected")["draining"] == before + 1
            evts = [e for e in obs.flight_tail()
                    if e.get("name") == "serve.reject"
                    and e.get("attrs", {}).get("reason") == "draining"]
            assert evts, "draining rejection must leave a flight event"
            srv.resume()
            assert srv.draining() is False
            np.testing.assert_array_equal(
                srv.predict("m", X[:3]),
                np.asarray(clf.predict(X[:3])))

    def test_drain_flushes_inflight_before_returning(self):
        clf, X = _fitted_clf()
        with ModelServer(label="t_drain_flush", window_s=0.0) as srv:
            srv.load("m", clf)
            futs = [srv.submit("m", X[i:i + 2]) for i in range(6)]
            assert srv.drain(timeout_s=10.0) is True
            # every accepted request resolved BEFORE drain returned
            for i, f in enumerate(futs):
                np.testing.assert_array_equal(
                    f.result(0.1), np.asarray(clf.predict(X[i:i + 2])))


class TestConcurrentRestart:
    """PR 19 satellite: the budgeted serve-loop restart under
    CONCURRENT submitters — K threads across a ThreadCrash must each
    see exactly-once replay or a counted rejection, never a hang and
    never a duplicate/blended answer."""

    def test_k_threads_across_crash_exactly_once(self):
        clf, X = _fitted_clf()
        K, per = 6, 8
        plan = FaultPlan().inject(
            "serve-loop", at_call=3, times=1,
            exc=ThreadCrash("test: death under concurrency"))
        results: dict = {}
        errors: dict = {}

        with ModelServer(label="t_conc_crash", window_s=0.0,
                         budget=FaultBudget(4, 60.0,
                                            name="t_conc_crash")) as srv:
            srv.load("m", clf)

            def _client(k):
                out = []
                for i in range(per):
                    lo = (k * per + i) % 32
                    try:
                        out.append((lo, srv.predict(
                            "m", X[lo:lo + 2], timeout=30.0)))
                    except RequestRejected as e:
                        out.append((lo, e))
                results[k] = out

            with fault_plan(plan):
                threads = [threading.Thread(target=_client, args=(k,),
                                            name=f"t_conc_{k}")
                           for k in range(K)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60.0)
                    assert not t.is_alive(), \
                        "a submitter hung across the restart"
        assert sum(plan.fired.values()) == 1
        assert srv.report()["budget"]["spent"] >= 1
        reg_rejected = sum(
            1 for outs in results.values() for _, r in outs
            if isinstance(r, RequestRejected))
        answered = 0
        for outs in results.values():
            assert len(outs) == per  # exactly one outcome per request
            for lo, r in outs:
                if isinstance(r, RequestRejected):
                    # a counted rejection is a legal outcome; a wrong
                    # reason (or an uncounted drop) is not
                    assert r.reason in ("serve_down", "queue_full")
                    continue
                answered += 1
                np.testing.assert_array_equal(
                    r, np.asarray(clf.predict(X[lo:lo + 2])))
        assert answered + reg_rejected == K * per
        assert answered > 0, errors


class TestReadiness:
    """PR 19 satellite: liveness (/healthz) vs readiness (/readyz)
    split — a live server with residency warmup still pending must
    read NOT READY (503) so a router never sends it cold traffic."""

    def test_ready_false_during_warmup_window(self):
        from dask_ml_tpu.obs import serve as obs_serve

        clf, X = _fitted_clf()
        with ModelServer(label="t_ready", window_s=0.0) as srv:
            assert srv.ready() is True  # empty server: live AND ready
            srv._test_control_delay_s = 0.25  # widen the warmup window
            fut = srv.submit_load("m", clf)
            # liveness holds through the whole window...
            assert srv._unit not in _supervisor.healthz()["dead"]
            # ...but readiness is down until the load resolves
            assert srv.ready() is False
            verdict = obs_serve.readyz()
            assert verdict["ok"] is False
            assert srv._unit in verdict["not_ready"]
            assert fut.result(30.0) is True
            srv._test_control_delay_s = 0.0
            assert srv.ready() is True
            assert obs_serve.readyz()["ok"] is True

    def test_readyz_endpoint_503_until_warm(self):
        from dask_ml_tpu.obs import serve as obs_serve

        clf, X = _fitted_clf()
        srv_http = obs_serve.start(0)
        try:
            with ModelServer(label="t_readyz_http", window_s=0.0) as srv:
                srv._test_control_delay_s = 0.25
                fut = srv.submit_load("m", clf)
                url = f"http://127.0.0.1:{srv_http.port}"
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(f"{url}/readyz", timeout=5)
                assert ei.value.code == 503
                # liveness endpoint stays 200 through the warmup window
                assert urllib.request.urlopen(
                    f"{url}/healthz", timeout=5).status == 200
                fut.result(30.0)
                srv._test_control_delay_s = 0.0
                assert urllib.request.urlopen(
                    f"{url}/readyz", timeout=5).status == 200
        finally:
            obs_serve.stop()

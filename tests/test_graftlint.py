"""graftlint: the analyzer gates itself (tier-1 self-gate) and every rule
is exercised on a positive (flagging) and negative (clean) snippet.

The snippets are synthetic distillations of the bug each rule encodes —
the PR-1 thread deadlock, the gloo divergent-collective hang, key reuse,
host sync in fit loops, jit retracing, tracer branches, and swallowed
exceptions around collectives (see docs/design.md, "Concurrency & SPMD
contract").
"""

import json
import os
import textwrap

import pytest

from dask_ml_tpu.analysis import (
    RULES,
    all_rules,
    lint_paths,
    lint_source,
    main,
    per_rule_counts,
    render_json,
    render_text,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dask_ml_tpu")
BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")


def lint(src, **kw):
    return lint_source(textwrap.dedent(src), **kw)


def active(findings):
    return [f for f in findings if not f.suppressed]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


@pytest.fixture(scope="module")
def pkg_lint(tmp_path_factory):
    """ONE full-package lint shared by every gate test (through the
    whole-project cache, so repeat calls inside the module are free)."""
    cache = str(tmp_path_factory.mktemp("graftlint") / "cache.json")
    findings, errors = lint_paths([PKG], cache=cache)
    return findings, errors


# ---------------------------------------------------------------------------
# the tier-1 self-gate: the library must lint clean
# ---------------------------------------------------------------------------

class TestPackageGate:
    def test_package_has_zero_unsuppressed_findings(self, pkg_lint):
        findings, errors = pkg_lint
        assert not errors, errors
        bad = active(findings)
        assert not bad, "\n".join(f.render() for f in bad)

    def test_every_suppression_carries_a_justification(self, pkg_lint):
        # bad-suppression findings are themselves active findings, so the
        # gate above covers this — but assert directly so a regression in
        # THAT wiring is also caught
        findings, _ = pkg_lint
        for f in findings:
            if f.suppressed:
                assert f.justification, f.render()

    def test_no_unused_suppressions(self, pkg_lint):
        # the zero-active gate covers this too (unused-suppression
        # findings are active), but assert by name: every justified
        # suppression in the library must still be EARNING its keep
        findings, _ = pkg_lint
        assert not [f for f in findings if f.rule == "unused-suppression"]

    def test_committed_baseline_matches(self, pkg_lint):
        # the ratchet's committed snapshot must match reality exactly:
        # no new findings, no stale entries (refresh via
        # `tools/lint.sh --rebaseline` after intentional changes)
        from dask_ml_tpu.analysis import baseline as bl

        findings, _ = pkg_lint
        snap = bl.load(BASELINE)
        delta = bl.compare(snap, findings, bl.baseline_root([PKG]))
        assert not delta["new"], [f.render() for f in delta["new"]]
        assert not delta["fixed"], delta["fixed"]

    def test_cli_gate_exit_zero(self, capsys):
        assert main([PKG]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out

    def test_cli_ratchet_gate_exit_zero(self, capsys):
        assert main([PKG, "--baseline", BASELINE]) == 0
        out = capsys.readouterr().out
        assert "0 new, 0 stale" in out


# ---------------------------------------------------------------------------
# per-rule positive / negative snippets
# ---------------------------------------------------------------------------

class TestThreadDispatch:
    def test_flags_unguarded_pool(self):
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor

            def fan_out(run, tasks):
                with ThreadPoolExecutor(max_workers=4) as pool:
                    return list(pool.map(run, tasks))
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_flags_bare_thread(self):
        findings = lint("""
            import threading

            def go(fn):
                t = threading.Thread(target=fn)
                t.start()
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_guarded_pool_is_clean(self):
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor

            def fan_out(est, run, tasks):
                n_workers = 4
                if _uses_device_estimator(est):
                    n_workers = 1
                with ThreadPoolExecutor(max_workers=n_workers) as pool:
                    return list(pool.map(run, tasks))
        """)
        assert not active(findings)


class TestDivergentCollective:
    def test_flags_process_index_guard(self):
        findings = lint("""
            import jax

            def maybe_sync(x):
                if jax.process_index() == 0:
                    return jax.lax.psum(x, "data")
                return x
        """)
        assert rule_ids(active(findings)) == ["divergent-collective"]

    def test_flags_wall_clock_guard(self):
        findings = lint("""
            import time
            from jax.experimental import multihost_utils

            def heartbeat(flag, deadline):
                while time.monotonic() < deadline:
                    flag = multihost_utils.process_allgather(flag)
                return flag
        """)
        assert rule_ids(active(findings)) == ["divergent-collective"]

    def test_uniform_condition_is_clean(self):
        findings = lint("""
            import jax

            def sync(x, every_process_same_flag):
                if every_process_same_flag:
                    return jax.lax.psum(x, "data")
                return x
        """)
        assert not active(findings)

    def test_collective_outside_branch_is_clean(self):
        findings = lint("""
            import jax

            def sync(x):
                y = jax.lax.psum(x, "data")
                if jax.process_index() == 0:
                    log(y)
                return y
        """)
        assert not active(findings)


class TestKeyReuse:
    def test_flags_double_sample(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["key-reuse"]
        assert "already consumed" in fs[0].message

    def test_flags_double_split(self):
        findings = lint("""
            import jax

            def children(key):
                a = jax.random.split(key)
                b = jax.random.split(key)
                return a, b
        """)
        assert rule_ids(active(findings)) == ["key-reuse"]

    def test_flags_loop_carried_reuse(self):
        findings = lint("""
            import jax

            def draws(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["key-reuse"]
        assert "loop iteration" in fs[0].message

    def test_split_chain_is_clean(self):
        findings = lint("""
            import jax

            def sample(key):
                key, k1 = jax.random.split(key)
                a = jax.random.normal(k1, (3,))
                key, k2 = jax.random.split(key)
                b = jax.random.uniform(k2, (3,))
                return a + b
        """)
        assert not active(findings)

    def test_loop_with_resplit_is_clean(self):
        findings = lint("""
            import jax

            def draws(key, n):
                out = []
                for _ in range(n):
                    key, sub = jax.random.split(key)
                    out.append(jax.random.normal(sub, (3,)))
                return out
        """)
        assert not active(findings)

    def test_fold_in_is_exempt(self):
        findings = lint("""
            import jax

            def per_shard(key, n):
                return [jax.random.fold_in(key, i) for i in range(n)]
        """)
        assert not active(findings)

    def test_rebind_in_both_branches_is_clean(self):
        # a key refreshed on EVERY surviving path is fresh afterwards
        findings = lint("""
            import jax

            def sample(key, cond):
                a = jax.random.normal(key, (3,))
                if cond:
                    key = jax.random.PRNGKey(0)
                else:
                    key = jax.random.PRNGKey(1)
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert not active(findings)

    def test_rebind_in_one_branch_still_flags(self):
        # ...but refreshed on only ONE path is still a reuse on the other
        findings = lint("""
            import jax

            def sample(key, cond):
                a = jax.random.normal(key, (3,))
                if cond:
                    key = jax.random.PRNGKey(0)
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert rule_ids(active(findings)) == ["key-reuse"]

    def test_host_rng_modules_are_exempt(self):
        # stdlib random / np.random have no key argument: a repeated
        # first-arg Name there is data, not key reuse
        findings = lint("""
            import random
            import numpy as np

            def pick(xs):
                a = random.choice(xs)
                b = random.choice(xs)
                n = np.random.choice(xs)
                m = np.random.choice(xs)
                return a, b, n, m
        """)
        assert not active(findings)

    def test_exclusive_return_branches_are_clean(self):
        # the k_means init ladder: `if mode == a: return sample(key)`
        # followed by another use — exclusive via return, not a reuse
        findings = lint("""
            import jax

            def init(key, mode):
                if mode == "random":
                    return jax.random.normal(key, (3,))
                if mode == "choice":
                    return jax.random.choice(key, 10, (3,))
                raise ValueError(mode)
        """)
        assert not active(findings)


class TestHostSyncLoop:
    def test_flags_float_in_fit_loop(self):
        findings = lint("""
            def fit(self, X):
                for _ in range(10):
                    loss = step(X)
                    if float(loss) < 1e-3:
                        break
                return self
        """)
        assert rule_ids(active(findings)) == ["host-sync-loop"]

    def test_flags_item_and_asarray(self):
        findings = lint("""
            import numpy as np

            def fit_loop(state, xs):
                for x in xs:
                    state = step(state, x)
                    history.append(state.loss.item())
                    snap = np.asarray(state.w)
                return state
        """)
        assert len(active(findings)) == 2

    def test_boundary_sync_outside_loop_is_clean(self):
        findings = lint("""
            def fit(self, X):
                for _ in range(10):
                    loss = step(X)
                return float(loss)
        """)
        assert not active(findings)

    def test_non_fit_function_is_clean(self):
        findings = lint("""
            def render(self, rows):
                for r in rows:
                    print(float(r))
        """)
        assert not active(findings)

    def test_device_reduction_wrapped_sync_is_flagged(self):
        # the canonical convergence check: float(jnp.max(shift)) is a
        # per-iteration device sync — a dotted jnp/np reduction must not
        # read as host-side (only the BARE builtins do)
        findings = lint("""
            import jax.numpy as jnp

            def fit(self, X, tol):
                for _ in range(10):
                    shift = step(X)
                    if float(jnp.max(shift)) < tol:
                        break
                return self
        """)
        assert rule_ids(active(findings)) == ["host-sync-loop"]

    def test_shape_touch_is_clean(self):
        findings = lint("""
            def fit(self, X):
                for _ in range(10):
                    n = float(X.shape[0])
                return n
        """)
        assert not active(findings)


class TestJitInLoop:
    def test_flags_jit_in_loop(self):
        findings = lint("""
            import jax

            def train(xs):
                out = []
                for x in xs:
                    f = jax.jit(lambda v: v * 2)
                    out.append(f(x))
                return out
        """)
        assert rule_ids(active(findings)) == ["jit-in-loop"]

    def test_flags_partial_jit_in_loop(self):
        findings = lint("""
            import jax
            from functools import partial

            def train(xs):
                while xs:
                    step = partial(jax.jit, static_argnums=0)(make_step())
                    xs = step(xs)
        """)
        assert rule_ids(active(findings)) == ["jit-in-loop"]

    def test_hoisted_jit_is_clean(self):
        findings = lint("""
            import jax

            def train(xs):
                f = jax.jit(lambda v: v * 2)
                return [f(x) for x in xs]
        """)
        assert not active(findings)


class TestTracerBranch:
    def test_flags_branch_on_traced_arg(self):
        findings = lint("""
            import jax

            @jax.jit
            def absval(x):
                if x > 0:
                    return x
                return -x
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["tracer-branch"]
        assert "absval" in fs[0].message

    def test_static_argnames_is_clean(self):
        findings = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("mode",))
            def step(x, mode):
                if mode == "fast":
                    return x * 2
                return x
        """)
        assert not active(findings)

    def test_shape_and_none_checks_are_clean(self):
        findings = lint("""
            import jax

            @jax.jit
            def norm(x, w):
                if w is None:
                    return x
                if x.ndim == 2:
                    return x * w
                return x
        """)
        assert not active(findings)

    def test_undecorated_function_is_clean(self):
        findings = lint("""
            def absval(x):
                if x > 0:
                    return x
                return -x
        """)
        assert not active(findings)


class TestSwallowedCollective:
    def test_flags_broad_except(self):
        findings = lint("""
            import jax

            def agree(x):
                try:
                    return jax.lax.psum(x, "data")
                except Exception:
                    return x
        """)
        assert rule_ids(active(findings)) == ["swallowed-collective"]

    def test_flags_bare_except(self):
        findings = lint("""
            from jax.experimental import multihost_utils

            def agree(flag):
                try:
                    return multihost_utils.process_allgather(flag)
                except:
                    return flag
        """)
        assert rule_ids(active(findings)) == ["swallowed-collective"]

    def test_reraise_is_clean(self):
        findings = lint("""
            import jax

            def agree(x):
                try:
                    return jax.lax.psum(x, "data")
                except Exception:
                    log_failure()
                    raise
        """)
        assert not active(findings)

    def test_narrow_except_is_clean(self):
        findings = lint("""
            import jax

            def agree(x):
                try:
                    return jax.lax.psum(x, "data")
                except ValueError:
                    return x
        """)
        assert not active(findings)

    def test_no_collective_in_try_is_clean(self):
        findings = lint("""
            def host_only(path):
                try:
                    return open(path).read()
                except Exception:
                    return None
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# v2 rules: stage-purity, unbounded-retry, checkpoint-schema-drift,
# undocumented-knob — pos+neg snippet per rule
# ---------------------------------------------------------------------------

class TestStagePurity:
    def test_flags_dispatch_in_pf_stage_reachable_helper(self):
        # the acceptance drill: inject a device program into a helper a
        # _pf_stage implementation reaches — the chain must be flagged
        findings = lint("""
            import numpy as np
            import jax.numpy as jnp

            class Est:
                def _prep(self, X):
                    x = np.asarray(X, np.float32)
                    return jnp.dot(jnp.asarray(x), jnp.asarray(x).T)

                def _pf_stage(self, X, y=None, **kwargs):
                    if kwargs:
                        return None
                    return self._prep(X)
        """)
        fs = [f for f in active(findings) if f.rule == "stage-purity"]
        assert fs, rule_ids(findings)
        assert "_pf_stage" in fs[0].message and "_prep" in fs[0].message

    def test_flags_collective_and_consume(self):
        findings = lint("""
            import jax

            class Est:
                def _pf_stage(self, X, y=None):
                    flag = jax.lax.psum(1, "data")
                    return self._pf_consume(X)
        """)
        ids = [f.rule for f in active(findings)]
        assert ids.count("stage-purity") == 2

    def test_transfer_only_stage_is_clean(self):
        # the real contract: host parse + jnp.asarray puts are LEGAL on
        # the worker thread (design.md §8: a put is not a program)
        findings = lint("""
            import numpy as np
            import jax.numpy as jnp

            class Est:
                def _host_pad(self, X):
                    x = np.asarray(X, np.float32)
                    return np.concatenate([x, np.zeros_like(x)])

                def _pf_stage(self, X, y=None, **kwargs):
                    if kwargs or isinstance(X, jnp.ndarray):
                        return None
                    return jnp.asarray(self._host_pad(X))
        """)
        assert not active(findings)

    def test_device_cast_flagged_host_cast_clean(self):
        findings = lint("""
            import numpy as np
            import jax.numpy as jnp

            class Bad:
                def _pf_stage(self, X, y=None):
                    return X.astype(jnp.float32)

            class Good:
                def _pf_stage(self, X, y=None):
                    return jnp.asarray(X.astype(np.float32))
        """)
        fs = [f for f in active(findings) if f.rule == "stage-purity"]
        assert len(fs) == 1
        assert fs[0].line == 7  # the jnp cast, not the np one


class TestUnboundedRetry:
    def test_flags_nonliteral_budget_without_deadline(self):
        findings = lint("""
            from dask_ml_tpu.resilience.retry import retry

            def pull(fetch, retries):
                return retry(fetch, retries=int(retries), backoff=0.1)
        """)
        assert rule_ids(active(findings)) == ["unbounded-retry"]

    def test_deadline_bounds_it(self):
        findings = lint("""
            from dask_ml_tpu.resilience.retry import retry

            def pull(fetch, retries):
                return retry(fetch, retries=int(retries), deadline=120.0)
        """)
        assert not active(findings)

    def test_literal_budget_is_clean(self):
        findings = lint("""
            from dask_ml_tpu.resilience.retry import retry

            def pull(fetch, lockstep):
                a = retry(fetch)                       # default budget
                b = retry(fetch, retries=5)            # literal
                c = retry(fetch, retries=0 if lockstep else 1)  # both literal
                return a, b, c
        """)
        assert not active(findings)

    def test_deadline_none_does_not_count(self):
        findings = lint("""
            from dask_ml_tpu.resilience.retry import retry

            def pull(fetch, n):
                return retry(fetch, retries=n, deadline=None)
        """)
        assert rule_ids(active(findings)) == ["unbounded-retry"]

    def test_unrelated_retry_suffixes_ignored(self):
        findings = lint("""
            def note(stats):
                stats.record_retry("tag")
        """)
        assert not active(findings)

    def test_shared_fault_budget_bounds_it(self):
        """PR 9: a non-None budget= (the per-fit shared FaultBudget,
        design.md §13) attempt-bounds the loop like a Deadline does."""
        findings = lint("""
            from dask_ml_tpu.resilience.retry import retry

            def pull(fetch, retries, budget):
                return retry(fetch, retries=int(retries), budget=budget)
        """)
        assert not active(findings)

    def test_budget_none_does_not_count(self):
        findings = lint("""
            from dask_ml_tpu.resilience.retry import retry

            def pull(fetch, n):
                return retry(fetch, retries=n, budget=None)
        """)
        assert rule_ids(active(findings)) == ["unbounded-retry"]


class TestSwallowedFault:
    """PR 9 satellite: the static twin of the chaos drill suite's
    'every fault is observable' contract — a try/except around a
    FaultPlan-registered call site whose handler neither raises nor
    calls anything erases a fault from the books."""

    def _pkg(self, tmp_path, handler_body):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "sites.py").write_text(textwrap.dedent("""
            def maybe_fault(point):
                pass

            def read_block(path):
                maybe_fault("ingest")
                return path
        """))
        (pkg / "caller.py").write_text(textwrap.dedent(f"""
            from .sites import read_block

            def pull(path):
                try:
                    return read_block(path)
                except Exception:
                    {handler_body}
        """))
        return str(pkg)

    def test_silent_swallow_around_fault_site_flagged(self, tmp_path):
        findings, errors = lint_paths(
            [self._pkg(tmp_path, "return None")])
        assert not errors
        assert "swallowed-fault" in rule_ids(active(findings))

    def test_transitive_reach_through_helper_flagged(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "deep.py").write_text(textwrap.dedent("""
            def maybe_fault(point):
                pass

            def inner():
                maybe_fault("collective")

            def outer():
                return inner()

            def pull():
                try:
                    outer()
                except Exception:
                    pass
        """))
        findings, _ = lint_paths([str(pkg)])
        assert "swallowed-fault" in rule_ids(active(findings))

    def test_logging_handler_is_clean(self, tmp_path):
        findings, _ = lint_paths(
            [self._pkg(tmp_path, "logger.warning('fault dropped')")])
        assert "swallowed-fault" not in rule_ids(active(findings))

    def test_reraise_handler_is_clean(self, tmp_path):
        findings, _ = lint_paths([self._pkg(tmp_path, "raise")])
        assert "swallowed-fault" not in rule_ids(active(findings))

    def test_swallow_around_plain_call_is_clean(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "plain.py").write_text(textwrap.dedent("""
            def host_only(x):
                return x + 1

            def pull(x):
                try:
                    return host_only(x)
                except Exception:
                    return None
        """))
        findings, _ = lint_paths([str(pkg)])
        assert "swallowed-fault" not in rule_ids(active(findings))


class TestBlessedCompileThread:
    """PR-6 stage-purity extension: a Thread constructed with a literal
    name in ``_spmd.BLESSED_COMPILE_THREADS`` may COMPILE off the main
    thread (the ROADMAP [compile] compile-ahead worker); it still may
    not fetch, rendezvous, or run a dispatch surface — and ``_pf_stage``
    workers stay forbidden from compiling entirely."""

    def test_blessed_thread_compiling_is_clean(self):
        findings = lint("""
            import threading
            import jax

            def _warm_cache():
                jax.jit(lambda v: v).lower(1.0).compile()

            t = threading.Thread(
                target=_warm_cache, name="dask-ml-tpu-compile-ahead")
        """)
        assert not active(findings), rule_ids(active(findings))

    def test_blessed_thread_fetch_is_flagged(self):
        findings = lint("""
            import threading
            from dask_ml_tpu.core.sharded import unshard

            def _leak(x):
                return unshard(x)

            t = threading.Thread(
                target=_leak, name="dask-ml-tpu-compile-ahead")
        """)
        fs = [f for f in active(findings) if f.rule == "stage-purity"]
        assert fs and "blessed" in fs[0].message

    def test_blessed_thread_collective_is_flagged(self):
        findings = lint("""
            import threading
            import jax

            def _run():
                jax.lax.psum(1, "i")

            t = threading.Thread(
                target=_run, name="dask-ml-tpu-compile-ahead")
        """)
        assert "stage-purity" in rule_ids(active(findings))

    def test_unblessed_name_still_flags_thread_dispatch(self):
        findings = lint("""
            import threading
            import jax

            def _warm_cache():
                jax.jit(lambda v: v)(1.0)

            t = threading.Thread(
                target=_warm_cache, name="some-random-worker")
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_computed_name_is_not_blessed(self):
        # only a string LITERAL blesses: a computed name is unprovable
        findings = lint("""
            import threading
            import jax

            NAME = "dask-ml-tpu-compile-ahead"

            def _warm_cache():
                jax.jit(lambda v: v)(1.0)

            t = threading.Thread(target=_warm_cache, name=NAME)
        """)
        assert "thread-dispatch" in rule_ids(active(findings))

    def test_pf_stage_still_forbidden_from_compiling(self):
        # the blessing must NOT leak to staging workers: a _pf_stage
        # that compiles keeps flagging regardless of thread names
        findings = lint("""
            import jax

            class Est:
                def _pf_stage(self, X, y=None, **kwargs):
                    return jax.jit(lambda v: v)(X)
        """)
        assert "stage-purity" in rule_ids(active(findings))


class TestHostOnlyThreadNames:
    """PR-10 graftscope extension: a Thread constructed with a literal
    name in ``_spmd.HOST_ONLY_THREAD_NAMES`` (the readiness sampler,
    the metrics endpoint) is DECLARED host-only — the declaration lets
    thread-dispatch accept a target it cannot resolve (the stdlib
    ``serve_forever`` loop), because graftsan's dispatch detector holds
    that name to the contract at runtime.  A target that provably
    reaches device work still flags: the declaration forgives opacity,
    never evidence."""

    def test_unresolvable_target_with_host_only_name_is_clean(self):
        # the obs/serve.py shape: the submitted callable is a method on
        # a stdlib object the index cannot see into
        findings = lint("""
            import threading
            from http.server import HTTPServer

            def serve(server: HTTPServer):
                t = threading.Thread(
                    target=server.serve_forever, daemon=True,
                    name="dask-ml-tpu-metrics")
                t.start()
        """)
        assert "thread-dispatch" not in rule_ids(active(findings))

    def test_unresolvable_target_without_the_name_still_flags(self):
        findings = lint("""
            import threading
            from http.server import HTTPServer

            def serve(server: HTTPServer):
                t = threading.Thread(
                    target=server.serve_forever, daemon=True,
                    name="some-random-worker")
                t.start()
        """)
        assert "thread-dispatch" in rule_ids(active(findings))

    def test_provable_device_work_flags_despite_the_name(self):
        # the declaration must never beat evidence: a host-only-named
        # thread whose target provably dispatches is a contract
        # violation the static rule can see — flag it
        findings = lint("""
            import threading
            import jax

            def _rogue():
                jax.jit(lambda v: v)(1.0)

            t = threading.Thread(
                target=_rogue, name="dask-ml-tpu-scope")
        """)
        assert "thread-dispatch" in rule_ids(active(findings))

    def test_computed_host_only_name_does_not_declare(self):
        findings = lint("""
            import threading
            from http.server import HTTPServer

            NAME = "dask-ml-tpu-metrics"

            def serve(server: HTTPServer):
                t = threading.Thread(
                    target=server.serve_forever, name=NAME)
                t.start()
        """)
        assert "thread-dispatch" in rule_ids(active(findings))

    def test_host_only_is_not_blessed_to_compile(self):
        # HOST_ONLY and BLESSED_COMPILE are disjoint privileges: the
        # sampler/endpoint names must not inherit the compile-ahead
        # thread's compile allowance
        from dask_ml_tpu.analysis.rules._spmd import (
            BLESSED_COMPILE_THREADS, HOST_ONLY_THREAD_NAMES)

        assert not (BLESSED_COMPILE_THREADS & HOST_ONLY_THREAD_NAMES)


class TestJitOutsideCache:
    """PR-8: streamed-step jax.jit wraps must route through programs/
    (scope: reachable from partial_fit/_pf_stage/_pf_consume/
    _step_block; whole-array fit solvers are out of scope)."""

    def test_flags_decorated_step_on_stream_path(self):
        findings = lint("""
            import jax

            @jax.jit
            def _step(x):
                return x + 1

            class Est:
                def partial_fit(self, X):
                    return _step(X)
        """)
        fs = [f for f in active(findings) if f.rule == "jit-outside-cache"]
        assert fs and "cached_program" in fs[0].message

    def test_flags_wrap_at_assignment_through_helper_chain(self):
        # this repo's idiom: partial(jax.jit, ...)(fn), reached via
        # _pf_consume -> self._step_block -> the wrapped name
        findings = lint("""
            import jax
            from functools import partial

            def step(state, x):
                return state

            _jitted_step = partial(jax.jit, donate_argnames=("state",))(step)

            class Est:
                def _pf_consume(self, staged):
                    return self._step_block(staged)

                def _step_block(self, staged):
                    return _jitted_step(self._state, staged)
        """)
        assert "jit-outside-cache" in rule_ids(active(findings))

    def test_flags_bare_jit_import(self):
        findings = lint("""
            from jax import jit

            @jit
            def _moments(x):
                return x

            class Est:
                def partial_fit(self, X):
                    return _moments(X)
        """)
        assert "jit-outside-cache" in rule_ids(active(findings))

    def test_foreign_jit_clean(self):
        findings = lint("""
            from numba import jit

            @jit
            def _step(x):
                return x

            class Est:
                def partial_fit(self, X):
                    return _step(X)
        """)
        assert "jit-outside-cache" not in rule_ids(active(findings))

    def test_fit_only_solver_out_of_scope(self):
        # whole-array fit programs compile once per dataset shape — the
        # streaming recompile tax does not apply, so no finding
        findings = lint("""
            import jax

            @jax.jit
            def _solve(x):
                return x

            class Est:
                def fit(self, X):
                    return _solve(X)
        """)
        assert "jit-outside-cache" not in rule_ids(active(findings))

    def test_jit_not_on_stream_path_clean(self):
        findings = lint("""
            import jax

            @jax.jit
            def _other(x):
                return x

            class Est:
                def partial_fit(self, X):
                    return X
        """)
        assert "jit-outside-cache" not in rule_ids(active(findings))

    def test_cached_program_idiom_clean(self):
        findings = lint("""
            from dask_ml_tpu import programs

            def step(x):
                return x * 2

            _step = programs.cached_program(step, name="m.step")

            class Est:
                def partial_fit(self, X):
                    return _step(X)
        """)
        assert "jit-outside-cache" not in rule_ids(active(findings))

    def test_suppression_lives_only_in_cache_internals(self):
        """The one sanctioned suppression is programs/cache.py's own
        wrap; it must exist (and match, or it becomes an active
        unused-suppression finding)."""
        path = os.path.join(PKG, "programs", "cache.py")
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        assert "disable=jit-outside-cache" in src
        findings = lint_source(src, path=path)
        sup = [f for f in findings if f.rule == "jit-outside-cache"]
        assert sup and all(f.suppressed for f in sup)
        assert "unused-suppression" not in rule_ids(active(findings))


class TestRecompileRisk:
    """PR-6: the static twin of graftsan's compile sanitizer."""

    def test_flags_traced_param_in_reshape(self):
        findings = lint("""
            import jax

            @jax.jit
            def f(x, n):
                return x.reshape(n, -1)
        """)
        fs = [f for f in active(findings) if f.rule == "recompile-risk"]
        assert fs and "n" in fs[0].message and "static_argnames" in \
            fs[0].message

    def test_flags_partial_applied_idiom_with_propagation(self):
        # this repo's module-level wrap: partial(jax.jit, ...)(fn), and
        # the taint flows through a local arithmetic assignment
        findings = lint("""
            import jax
            import jax.numpy as jnp
            from functools import partial

            def step(state, n):
                m = n * 2
                return state + jnp.zeros(m)

            _jitted = partial(jax.jit, donate_argnames=("state",))(step)
        """)
        assert "recompile-risk" in rule_ids(active(findings))

    def test_flags_jit_call_form(self):
        findings = lint("""
            import jax
            import jax.numpy as jnp

            def g(x, k):
                return jnp.arange(k) + x

            wrapped = jax.jit(g)
        """)
        assert "recompile-risk" in rule_ids(active(findings))

    def test_static_argnames_is_clean(self):
        findings = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                return x.reshape(n, -1)
        """)
        assert not active(findings)

    def test_shape_touch_is_shielded(self):
        findings = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                b = x.shape[0]
                return jnp.zeros(b) + x.reshape(x.shape[0], -1)
        """)
        assert not active(findings)

    def test_helper_call_result_does_not_taint(self):
        # a call's result is unknowable (usually a static shape helper):
        # treating it as tainted would flag every _pdim-style helper
        findings = lint("""
            import jax
            import jax.numpy as jnp

            def _pdim(x):
                return x.shape[1]

            @jax.jit
            def f(x):
                d = _pdim(x)
                return jnp.zeros(d)
        """)
        assert not active(findings)

    def test_data_arg_of_reshape_function_form_is_not_shape(self):
        findings = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                return jnp.reshape(x, (2, -1))
        """)
        assert not active(findings)

    def test_nonstandard_module_alias_resolves_as_function_form(self):
        # import-table resolution, not a hardcoded alias list: `jn` must
        # read as jax.numpy, so arg 0 is the DATA, not a shape position
        findings = lint("""
            import jax
            import jax.numpy as jn

            @jax.jit
            def f(x):
                return jn.reshape(x, (2, -1))
        """)
        assert not active(findings)


class TestCheckpointSchemaDrift:
    def test_flags_consumed_key_never_written(self):
        findings = lint("""
            class KM:
                def fit(self, X):
                    ckpt = self.fit_checkpoint
                    snap = ckpt.load_if_matches(self)
                    if snap is not None:
                        it, state = snap
                        centers = state["centres"]
                    for i in range(10):
                        centers = step(X)
                        ckpt.save(self, {"centers": centers}, i)
                    return self
        """)
        fs = [f for f in active(findings)
              if f.rule == "checkpoint-schema-drift"]
        assert len(fs) == 1
        assert "centres" in fs[0].message and "centers" in fs[0].message

    def test_flags_written_key_never_consumed(self):
        findings = lint("""
            class KM:
                def fit(self, X):
                    ckpt = self.fit_checkpoint
                    snap = ckpt.load_if_matches(self)
                    if snap is not None:
                        it, state = snap
                        centers = state["centers"]
                    for i in range(10):
                        centers, counts = step(X)
                        ckpt.save(self, {"centers": centers,
                                         "counts": counts}, i)
                    return self
        """)
        fs = [f for f in active(findings)
              if f.rule == "checkpoint-schema-drift"]
        assert len(fs) == 1 and "'counts'" in fs[0].message

    def test_matching_schema_is_clean(self):
        findings = lint("""
            class KM:
                def fit(self, X):
                    ckpt = self.fit_checkpoint
                    snap = ckpt.load_if_matches(self)
                    if snap is not None:
                        it, state = snap
                        centers = state["centers"]
                        counts = state["counts"]
                    for i in range(10):
                        centers, counts = step(X)
                        state = {"centers": centers, "counts": counts}
                        ckpt.save(self, state, i)
                        check_preemption(ckpt, self, state, i)
                    return self
        """)
        assert not active(findings)

    def test_state_through_local_helper_function(self):
        # the _sgd shape: the snapshot dict is built by a nested helper
        findings = lint("""
            def fit(est, X):
                ckpt = getattr(est, "fit_checkpoint", None)
                def _snapshot_state():
                    return {"state": est._state, "best": est._best}
                snap = ckpt.load_if_matches(est)
                if snap is not None:
                    epoch0, st = snap
                    est._state = st["state"]
                    est._best = st["best"]
                for e in range(10):
                    ckpt.save(est, _snapshot_state(), e)
        """)
        assert not active(findings)

    def test_wildcard_write_skips_module(self):
        # unresolvable snapshot (dict comprehension): wildcard, NOT clean
        # evidence and NOT a finding either
        findings = lint("""
            class IPCA:
                def _fit_state(self):
                    return {a: getattr(self, a) for a in self._ATTRS}

                def fit(self, X):
                    ckpt = self.fit_checkpoint
                    snap = ckpt.load_if_matches(self)
                    if snap is not None:
                        it, state = snap
                        anything = state["whatever"]
                    ckpt.save(self, self._fit_state(), 1)
        """)
        assert not active(findings)

    def test_np_save_is_not_checkpoint_traffic(self):
        findings = lint("""
            import numpy as np

            def dump(path, arr, meta):
                np.save(path, arr)
        """)
        assert not active(findings)


class TestUndocumentedKnob:
    def _tree(self, tmp_path, documented, read_name, via_constant=False):
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "api.md").write_text(
            f"| `{documented}` | int | a knob | — |\n"
            f"`DASK_ML_TPU_BENCH_*` harness knobs\n"
        )
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        if via_constant:
            body = (f'KNOB = "{read_name}"\n'
                    f'import os\n'
                    f'def depth():\n'
                    f'    return int(os.environ.get(KNOB, "2"))\n')
        else:
            body = (f'import os\n'
                    f'def depth():\n'
                    f'    return int(os.environ.get("{read_name}", "2"))\n')
        (pkg / "mod.py").write_text(body)
        return str(pkg)

    def test_flags_undocumented_read(self, tmp_path):
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH",
                         "DASK_ML_TPU_SECRET")
        findings, errors = lint_paths([pkg])
        assert not errors
        fs = [f for f in active(findings) if f.rule == "undocumented-knob"]
        assert len(fs) == 1 and "DASK_ML_TPU_SECRET" in fs[0].message

    def test_documented_read_is_clean(self, tmp_path):
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH", "DASK_ML_TPU_DEPTH")
        findings, _ = lint_paths([pkg])
        assert not active(findings)

    def test_name_resolved_through_module_constant(self, tmp_path):
        # the pipeline/core.py shape: DEPTH_ENV = "DASK_ML_TPU_..." then
        # os.environ.get(DEPTH_ENV)
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH",
                         "DASK_ML_TPU_HIDDEN", via_constant=True)
        findings, _ = lint_paths([pkg])
        fs = [f for f in active(findings) if f.rule == "undocumented-knob"]
        assert len(fs) == 1 and "DASK_ML_TPU_HIDDEN" in fs[0].message

    def test_wildcard_prefix_allows(self, tmp_path):
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH",
                         "DASK_ML_TPU_BENCH_SEED")
        findings, _ = lint_paths([pkg])
        assert not active(findings)

    def test_env_write_is_not_a_read(self, tmp_path):
        # propagating a knob into a spawned worker's env is a WRITE —
        # the _multihost_worker pattern — and must not flag
        pkg = self._tree(tmp_path, "DASK_ML_TPU_DEPTH", "DASK_ML_TPU_DEPTH")
        (tmp_path / "pkg" / "spawn.py").write_text(
            'import os\n'
            'def child_env():\n'
            '    env = dict(os.environ)\n'
            '    os.environ["DASK_ML_TPU_UNLISTED"] = "1"\n'
            '    return env\n')
        findings, _ = lint_paths([pkg])
        assert not [f for f in active(findings)
                    if f.rule == "undocumented-knob"]

    def test_no_api_md_in_reach_is_silent(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            'import os\nV = os.environ.get("DASK_ML_TPU_ANYTHING")\n')
        findings, _ = lint_paths([str(pkg)])
        assert not active(findings)


# ---------------------------------------------------------------------------
# interprocedural upgrades of the v1 rules
# ---------------------------------------------------------------------------

class TestInterproceduralThreadDispatch:
    def test_host_only_target_is_clean_without_guard(self):
        # the _multihost_worker drain shape: resolvable target, pipe
        # reads only — v1 forced a suppression here, v2 proves it clean
        findings = lint("""
            import threading

            def run_all(procs):
                outs = [None] * len(procs)

                def drain(i, p):
                    outs[i], _ = p.communicate(timeout=60)

                threads = [threading.Thread(target=drain, args=(i, p))
                           for i, p in enumerate(procs)]
                for t in threads:
                    t.start()
        """)
        assert not active(findings)

    def test_target_reaching_device_work_is_flagged(self):
        findings = lint("""
            import threading
            import jax.numpy as jnp

            def go(xs):
                def work():
                    return jnp.dot(xs, xs.T)

                threading.Thread(target=work).start()
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["thread-dispatch"]
        assert "work" in fs[0].message

    def test_dynamic_callable_target_still_flags(self):
        # the pipeline worker shape: the staged callable is a parameter —
        # nothing can be proven, the (justified) suppression stays
        findings = lint("""
            import threading

            def staged_iter(src, stage):
                def work():
                    return stage(next(src))

                threading.Thread(target=work).start()
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_pool_with_host_only_submit_is_clean(self):
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor

            def hash_all(chunks):
                def hash_chunk(c):
                    return hash(tuple(c))

                with ThreadPoolExecutor(max_workers=4) as pool:
                    return list(pool.map(hash_chunk, chunks))
        """)
        assert not active(findings)

    def test_second_device_target_not_masked_by_first_host_target(self):
        # regression: resolving thread targets went through an
        # id()-keyed memo with a transient synthesized Call node —
        # after GC the next target could inherit the PREVIOUS target's
        # resolution, judging a device-dispatching thread host-only
        findings = lint("""
            import threading
            import jax.numpy as jnp

            def host_work():
                return sum(range(10))

            def device_work(xs):
                return jnp.dot(xs, xs.T)

            def go(xs):
                t1 = threading.Thread(target=host_work)
                t2 = threading.Thread(target=device_work)
                t3 = threading.Thread(target=host_work)
                for t in (t1, t2, t3):
                    t.start()
        """)
        fs = [f for f in active(findings) if f.rule == "thread-dispatch"]
        assert len(fs) == 1
        assert "device_work" in fs[0].message

    def test_pool_submitting_partial_fit_is_flagged(self):
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor

            def train(model, blocks):
                def unit(b):
                    return model.partial_fit(b)

                with ThreadPoolExecutor(max_workers=4) as pool:
                    return list(pool.map(unit, blocks))
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_unmodelable_callee_shape_is_not_proven_host_only(self):
        # a registry-dispatched callable (subscript call) in the target:
        # nothing can be proven about it, so the Thread must still flag
        findings = lint("""
            import threading

            _CALLBACKS = []

            def worker():
                _CALLBACKS[0]()

            def go():
                threading.Thread(target=worker).start()
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_with_bound_pool_after_earlier_binding_stays_clean(self):
        # regression: ast.withitem has no lineno, so the with-pool's
        # submit used to bind to the EARLIER assignment, leaving the
        # with-pool "no submitted work visible" — a false positive
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor

            def run(chunks):
                def host(c):
                    return hash(c)

                pool = ThreadPoolExecutor(2)
                pool.submit(host, chunks[0])
                pool.shutdown()
                with ThreadPoolExecutor(2) as pool:
                    pool.submit(host, chunks[1])
        """)
        assert not active(findings)

    def test_unindexed_own_package_callee_is_not_proven_host_only(
            self, tmp_path):
        # single-FILE lint: the target calls into a sibling module of
        # the same package that is NOT in this lint's scope — the body
        # exists but cannot be seen, so the Thread must still flag
        pkg = tmp_path / "mypkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "ops.py").write_text(
            "import jax.numpy as jnp\n"
            "def device_helper(x):\n    return jnp.sum(x)\n")
        (pkg / "runner.py").write_text(
            "import threading\n"
            "from .ops import device_helper\n"
            "def go(x):\n"
            "    def work():\n"
            "        return device_helper(x)\n"
            "    threading.Thread(target=work).start()\n")
        # partial scope (runner only): unprovable → flags
        findings, _ = lint_paths([str(pkg / "runner.py")])
        assert "thread-dispatch" in rule_ids(active(findings))
        # full scope: resolvable, genuinely device-reaching → still flags
        findings_full, _ = lint_paths([str(pkg)])
        assert "thread-dispatch" in rule_ids(active(findings_full))

    def test_rebound_pool_variable_judged_per_binding(self):
        # two pools under one name: each constructor is judged on ITS
        # binding's submissions only (def-use chains, not a name match)
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor
            import jax.numpy as jnp

            def run(xs):
                def host(c):
                    return hash(c)

                def dev(c):
                    return jnp.sum(c)

                pool = ThreadPoolExecutor(2)
                pool.submit(host, xs)
                pool = ThreadPoolExecutor(2)
                pool.submit(dev, xs)
        """)
        fs = [f for f in active(findings) if f.rule == "thread-dispatch"]
        assert len(fs) == 1
        assert "dev" in fs[0].message


class TestInterproceduralDivergentCollective:
    def test_collective_through_helper_under_divergent_guard(self):
        findings = lint("""
            import jax
            from jax.experimental import multihost_utils

            def agree(flag):
                return multihost_utils.process_allgather(flag)

            def maybe(flag):
                if jax.process_index() == 0:
                    return agree(flag)
                return flag
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["divergent-collective"]
        assert "agree()" in fs[0].message

    def test_helper_without_collective_is_clean(self):
        findings = lint("""
            import jax

            def log_it(flag):
                print(flag)

            def maybe(flag):
                if jax.process_index() == 0:
                    log_it(flag)
                return flag
        """)
        assert not active(findings)


class TestInterproceduralKeyReuse:
    def test_helper_consuming_key_counts_as_use(self):
        findings = lint("""
            import jax

            def init_centers(X, key):
                return jax.random.choice(key, X.shape[0], (3,))

            def fit(X, key):
                c = init_centers(X, key)
                noise = jax.random.normal(key, (3,))
                return c + noise
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["key-reuse"]
        assert "init_centers" in fs[0].message

    def test_exclusive_helper_branches_are_clean(self):
        # the k_means _init_centers ladder, incl. a `with` body return
        findings = lint("""
            import jax

            def init_scalable(X, key):
                return jax.random.choice(key, X.shape[0], (3,))

            def init(X, key, mode, timer):
                if mode == "scalable":
                    with timer():
                        return init_scalable(X, key)
                if mode == "random":
                    return jax.random.choice(key, X.shape[0], (3,))
                key, sub = jax.random.split(key)
                return jax.random.normal(sub, (3,))
        """)
        assert not active(findings)

    def test_transitive_helper_consumption(self):
        findings = lint("""
            import jax

            def inner(k):
                return jax.random.normal(k, (3,))

            def outer(key):
                return inner(key)

            def fit(key):
                a = outer(key)
                b = outer(key)
                return a + b
        """)
        assert rule_ids(active(findings)) == ["key-reuse"]

    def test_helper_taking_fresh_subkeys_is_clean(self):
        findings = lint("""
            import jax

            def draw(k):
                return jax.random.normal(k, (3,))

            def fit(key, n):
                out = []
                for _ in range(n):
                    key, sub = jax.random.split(key)
                    out.append(draw(sub))
                return out
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# unused suppressions
# ---------------------------------------------------------------------------

class TestUnusedSuppressions:
    def test_stale_suppression_is_flagged(self):
        findings = lint("""
            import jax

            def sample(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, (3,))  # graftlint: disable=key-reuse -- left over from an old refactor
                return a
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["unused-suppression"]
        assert "key-reuse" in fs[0].message

    def test_used_suppression_is_not_flagged(self):
        findings = lint(TestSuppressions.SRC)
        assert not active(findings)

    def test_unused_not_reported_on_partial_runs(self):
        # --select runs a subset: the unselected rules' suppressions are
        # legitimately unmatched and must not be called stale
        src = """
            import jax

            def fit(self, key, xs):
                for x in xs:
                    print(float(step(x)))  # graftlint: disable=host-sync-loop -- boundary sync
        """
        assert not active(lint(src, select=["key-reuse"]))
        # ...but the full run DOES judge them (here the suppression is
        # used, so still clean)
        assert not active(lint(src))

    def test_unused_disable_all_cannot_hide_itself(self):
        findings = lint("""
            x = 1  # graftlint: disable=all -- nothing here ever flags
        """)
        assert rule_ids(active(findings)) == ["unused-suppression"]


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------

class TestBaseline:
    SRC_V1 = textwrap.dedent("""
        import jax

        def sample(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse -- intentional correlated draws
            return a + b
    """)

    def _write_pkg(self, tmp_path, src):
        mod = tmp_path / "mod.py"
        mod.write_text(src)
        return str(tmp_path)

    def test_round_trip_and_clean_compare(self, tmp_path):
        from dask_ml_tpu.analysis import baseline as bl

        pkg = self._write_pkg(tmp_path, self.SRC_V1)
        findings, errors = lint_paths([pkg])
        root = bl.baseline_root([pkg])
        payload = bl.emit(findings, errors, root)
        path = tmp_path / "baseline.json"
        bl.write(str(path), payload)
        delta = bl.compare(bl.load(str(path)), findings, root)
        assert not delta["new"] and not delta["fixed"]

    def test_new_finding_detected(self, tmp_path):
        from dask_ml_tpu.analysis import baseline as bl

        pkg = self._write_pkg(tmp_path, self.SRC_V1)
        findings, errors = lint_paths([pkg])
        root = bl.baseline_root([pkg])
        snap = bl.emit(findings, errors, root)
        # add a second violation
        self._write_pkg(tmp_path, self.SRC_V1 + textwrap.dedent("""
            def more(key):
                c = jax.random.normal(key, (3,))
                d = jax.random.normal(key, (3,))
                return c + d
        """))
        findings2, _ = lint_paths([pkg])
        delta = bl.compare(snap, findings2, root)
        assert len(delta["new"]) == 1 and delta["new"][0].rule == "key-reuse"
        assert not delta["fixed"]

    def test_fixed_finding_reported_stale(self, tmp_path):
        from dask_ml_tpu.analysis import baseline as bl

        pkg = self._write_pkg(tmp_path, self.SRC_V1)
        findings, errors = lint_paths([pkg])
        root = bl.baseline_root([pkg])
        snap = bl.emit(findings, errors, root)
        self._write_pkg(tmp_path, "x = 1\n")
        findings2, _ = lint_paths([pkg])
        delta = bl.compare(snap, findings2, root)
        assert not delta["new"]
        assert {e["rule"] for e in delta["fixed"]} == {"key-reuse"}

    def test_fingerprint_survives_line_drift(self, tmp_path):
        # code inserted ABOVE the finding must not churn the baseline
        from dask_ml_tpu.analysis import baseline as bl

        pkg = self._write_pkg(tmp_path, self.SRC_V1)
        findings, errors = lint_paths([pkg])
        root = bl.baseline_root([pkg])
        snap = bl.emit(findings, errors, root)
        self._write_pkg(tmp_path, "# a new header comment\nVERSION = 1\n"
                        + self.SRC_V1)
        findings2, _ = lint_paths([pkg])
        delta = bl.compare(snap, findings2, root)
        assert not delta["new"] and not delta["fixed"]


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------

class TestSuppressions:
    SRC = """
        import jax

        def sample(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse -- correlated draws are intentional here
            return a + b
    """

    def test_inline_suppression(self):
        findings = lint(self.SRC)
        assert not active(findings)
        sup = [f for f in findings if f.suppressed]
        assert len(sup) == 1
        assert sup[0].justification == "correlated draws are intentional here"

    def test_suppression_on_line_above(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                # graftlint: disable=key-reuse -- intentional
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert not active(findings)

    def test_disable_all(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=all -- test fixture
                return a + b
        """)
        assert not active(findings)

    def test_bare_suppression_is_a_finding(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse
                return a + b
        """)
        assert "bad-suppression" in rule_ids(active(findings))

    def test_unknown_rule_id_is_a_finding(self):
        findings = lint("""
            x = 1  # graftlint: disable=no-such-rule -- whatever
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["bad-suppression"]
        assert "no-such-rule" in fs[0].message

    def test_inline_suppression_does_not_bleed_to_next_line(self):
        # an INLINE disable covers its own statement only; the next
        # line's unjustified violation must still fail the gate
        findings = lint("""
            import jax

            def sample(key, key2):
                a = jax.random.normal(key, (3,))
                c = jax.random.normal(key2, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse -- intentional
                d = jax.random.uniform(key2, (3,))
                return a + b + c + d
        """)
        assert rule_ids(active(findings)) == ["key-reuse"]

    def test_wrong_rule_id_does_not_suppress(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=jit-in-loop -- wrong id
                return a + b
        """)
        assert "key-reuse" in rule_ids(active(findings))


# ---------------------------------------------------------------------------
# framework: registry, reporters, CLI
# ---------------------------------------------------------------------------

class TestFramework:
    def test_registry_has_all_rules(self):
        all_rules()  # force registration
        assert set(RULES) == {
            "thread-dispatch", "divergent-collective", "key-reuse",
            "host-sync-loop", "jit-in-loop", "tracer-branch",
            "swallowed-collective",
            # v2: project-wide contracts
            "stage-purity", "unbounded-retry", "checkpoint-schema-drift",
            "undocumented-knob",
            # PR 6: the static twin of graftsan's compile sanitizer
            "recompile-risk",
            # PR 8: streamed-step jits must route through programs/
            "jit-outside-cache",
            # PR 9: the static twin of the chaos drill suite
            "swallowed-fault",
            # ISSUE 12: every cached program makes a donation decision
            "donation-miss",
            # ISSUE 17 (graftlock): lock-order + shared-state ownership
            "lock-order-cycle", "unguarded-shared-state",
            "lock-held-across-dispatch",
            # ISSUE 20 (graftcontract): stringly-typed contract closure
            "contract-orphan-producer", "contract-dead-consumer",
            "contract-roster-drift", "contract-baseline-drift",
            "contract-undocumented-metric",
        }

    def test_select_unknown_rule_raises(self):
        with pytest.raises(KeyError):
            all_rules(["no-such-rule"])

    def test_select_filters(self):
        src = """
            import jax

            def fit(self, key, xs):
                for x in xs:
                    v = jax.random.normal(key, (3,))
                    print(float(v))
        """
        both = lint(src)
        assert set(rule_ids(active(both))) == {"key-reuse", "host-sync-loop"}
        only = lint(src, select=["key-reuse"])
        assert rule_ids(active(only)) == ["key-reuse"]

    def test_json_reporter(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        payload = json.loads(render_json(findings))
        assert payload["version"] == 2
        assert payload["counts"]["key-reuse"]["active"] == 1
        assert payload["findings"][0]["rule"] == "key-reuse"
        assert "key-reuse" in payload["rules"]

    def test_text_reporter_counts_line(self):
        out = render_text([], [])
        assert "0 finding(s)" in out

    def test_per_rule_counts(self):
        findings = lint(TestSuppressions.SRC)
        counts = per_rule_counts(findings)
        assert counts["key-reuse"] == {"active": 0, "suppressed": 1}

    def test_bare_string_path_accepted(self):
        # a bare str must lint the path, not iterate its characters
        findings_str, errors_str = lint_paths(PKG)
        findings_list, errors_list = lint_paths([PKG])
        assert not errors_str
        assert len(findings_str) == len(findings_list)
        assert findings_str  # the 13 justified suppressions, at least

    def test_missing_path_is_an_error_not_a_clean_pass(self):
        findings, errors = lint_paths(["/no/such/dir/anywhere"])
        assert not findings
        assert errors and "no such file" in errors[0]

    def test_syntax_error_is_reported_not_skipped(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        findings, errors = lint_paths([str(bad)])
        assert errors and "syntax error" in errors[0]


class TestCLI:
    def test_exit_one_on_findings(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text(textwrap.dedent("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """))
        assert main([str(f)]) == 1
        assert "key-reuse" in capsys.readouterr().out

    def test_exit_zero_on_clean(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        assert main([str(f)]) == 0

    def test_exit_two_on_missing_path(self, capsys):
        assert main(["/no/such/dir/anywhere"]) == 2

    def test_exit_two_on_bad_select(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        assert main([str(f), "--select", "bogus"]) == 2

    def test_json_format(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        assert main([str(f), "--format", "json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"] == []

    def test_list_rules(self, capsys):
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "key-reuse" in out and "thread-dispatch" in out


class TestDiagnosticsLintReport:
    def test_lint_report_shape(self):
        from dask_ml_tpu import diagnostics

        report = diagnostics.lint_report()
        assert report["active"] == 0, report
        assert report["errors"] == []
        assert report["suppressed"] >= 1  # the library's justified debt
        for rule, c in report["counts"].items():
            assert set(c) == {"active", "suppressed"}
            assert rule in RULES

    def test_lint_report_explicit_paths(self, tmp_path):
        from dask_ml_tpu import diagnostics

        f = tmp_path / "mod.py"
        f.write_text(textwrap.dedent("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """))
        report = diagnostics.lint_report([str(tmp_path)])
        assert report["active"] == 1
        assert report["counts"]["key-reuse"]["active"] == 1


class TestDonationMiss:
    """ISSUE-12: every cached_program call must make its donation
    decision — donate_argnames wired, or an inline justified
    suppression naming why nothing aliases (the gemm-output-smaller
    class)."""

    def test_flags_cached_program_without_donation(self):
        findings = lint("""
            from dask_ml_tpu import programs as _programs

            def step(state, x):
                return state

            _step = _programs.cached_program(step, name="m.step")
        """)
        fs = [f for f in active(findings) if f.rule == "donation-miss"]
        assert fs and "donate_argnames" in fs[0].message

    def test_explicit_empty_tuple_still_flags(self):
        # an empty donate_argnames=() is "no donation" without the
        # reviewable justification a suppression carries
        findings = lint("""
            from dask_ml_tpu import programs as _programs

            def step(state, x):
                return state

            _step = _programs.cached_program(
                step, name="m.step", donate_argnames=())
        """)
        assert "donation-miss" in rule_ids(active(findings))

    def test_wired_donation_is_clean(self):
        findings = lint("""
            from dask_ml_tpu import programs as _programs

            def step(state, x):
                return state

            _step = _programs.cached_program(
                step, name="m.step", donate_argnames=("state",))
        """)
        assert "donation-miss" not in rule_ids(active(findings))

    def test_justified_suppression_is_honored(self):
        findings = lint("""
            from dask_ml_tpu import programs as _programs

            def loss(state, x):
                return 0.0

            # graftlint: disable=donation-miss -- scalar output, nothing aliases
            _loss = _programs.cached_program(loss, name="m.loss")
        """)
        fs = [f for f in findings if f.rule == "donation-miss"]
        assert fs and all(f.suppressed for f in fs)

    def test_direct_class_form_flags_too(self):
        findings = lint("""
            from dask_ml_tpu.programs.cache import CachedProgram

            def step(state, x):
                return state

            _step = CachedProgram(step, name="m.step")
        """)
        assert "donation-miss" in rule_ids(active(findings))

    def test_foreign_same_name_helper_does_not_match(self):
        findings = lint("""
            from mylib import cached_program

            def step(state, x):
                return state

            _step = cached_program(step, name="m.step")
        """)
        assert "donation-miss" not in rule_ids(active(findings))

"""graftlint: the analyzer gates itself (tier-1 self-gate) and every rule
is exercised on a positive (flagging) and negative (clean) snippet.

The snippets are synthetic distillations of the bug each rule encodes —
the PR-1 thread deadlock, the gloo divergent-collective hang, key reuse,
host sync in fit loops, jit retracing, tracer branches, and swallowed
exceptions around collectives (see docs/design.md, "Concurrency & SPMD
contract").
"""

import json
import os
import textwrap

import pytest

from dask_ml_tpu.analysis import (
    RULES,
    all_rules,
    lint_paths,
    lint_source,
    main,
    per_rule_counts,
    render_json,
    render_text,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dask_ml_tpu")


def lint(src, **kw):
    return lint_source(textwrap.dedent(src), **kw)


def active(findings):
    return [f for f in findings if not f.suppressed]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# the tier-1 self-gate: the library must lint clean
# ---------------------------------------------------------------------------

class TestPackageGate:
    def test_package_has_zero_unsuppressed_findings(self):
        findings, errors = lint_paths([PKG])
        assert not errors, errors
        bad = active(findings)
        assert not bad, "\n".join(f.render() for f in bad)

    def test_every_suppression_carries_a_justification(self):
        # bad-suppression findings are themselves active findings, so the
        # gate above covers this — but assert directly so a regression in
        # THAT wiring is also caught
        findings, _ = lint_paths([PKG])
        for f in findings:
            if f.suppressed:
                assert f.justification, f.render()

    def test_cli_gate_exit_zero(self, capsys):
        assert main([PKG]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out


# ---------------------------------------------------------------------------
# per-rule positive / negative snippets
# ---------------------------------------------------------------------------

class TestThreadDispatch:
    def test_flags_unguarded_pool(self):
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor

            def fan_out(run, tasks):
                with ThreadPoolExecutor(max_workers=4) as pool:
                    return list(pool.map(run, tasks))
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_flags_bare_thread(self):
        findings = lint("""
            import threading

            def go(fn):
                t = threading.Thread(target=fn)
                t.start()
        """)
        assert rule_ids(active(findings)) == ["thread-dispatch"]

    def test_guarded_pool_is_clean(self):
        findings = lint("""
            from concurrent.futures import ThreadPoolExecutor

            def fan_out(est, run, tasks):
                n_workers = 4
                if _uses_device_estimator(est):
                    n_workers = 1
                with ThreadPoolExecutor(max_workers=n_workers) as pool:
                    return list(pool.map(run, tasks))
        """)
        assert not active(findings)


class TestDivergentCollective:
    def test_flags_process_index_guard(self):
        findings = lint("""
            import jax

            def maybe_sync(x):
                if jax.process_index() == 0:
                    return jax.lax.psum(x, "data")
                return x
        """)
        assert rule_ids(active(findings)) == ["divergent-collective"]

    def test_flags_wall_clock_guard(self):
        findings = lint("""
            import time
            from jax.experimental import multihost_utils

            def heartbeat(flag, deadline):
                while time.monotonic() < deadline:
                    flag = multihost_utils.process_allgather(flag)
                return flag
        """)
        assert rule_ids(active(findings)) == ["divergent-collective"]

    def test_uniform_condition_is_clean(self):
        findings = lint("""
            import jax

            def sync(x, every_process_same_flag):
                if every_process_same_flag:
                    return jax.lax.psum(x, "data")
                return x
        """)
        assert not active(findings)

    def test_collective_outside_branch_is_clean(self):
        findings = lint("""
            import jax

            def sync(x):
                y = jax.lax.psum(x, "data")
                if jax.process_index() == 0:
                    log(y)
                return y
        """)
        assert not active(findings)


class TestKeyReuse:
    def test_flags_double_sample(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["key-reuse"]
        assert "already consumed" in fs[0].message

    def test_flags_double_split(self):
        findings = lint("""
            import jax

            def children(key):
                a = jax.random.split(key)
                b = jax.random.split(key)
                return a, b
        """)
        assert rule_ids(active(findings)) == ["key-reuse"]

    def test_flags_loop_carried_reuse(self):
        findings = lint("""
            import jax

            def draws(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["key-reuse"]
        assert "loop iteration" in fs[0].message

    def test_split_chain_is_clean(self):
        findings = lint("""
            import jax

            def sample(key):
                key, k1 = jax.random.split(key)
                a = jax.random.normal(k1, (3,))
                key, k2 = jax.random.split(key)
                b = jax.random.uniform(k2, (3,))
                return a + b
        """)
        assert not active(findings)

    def test_loop_with_resplit_is_clean(self):
        findings = lint("""
            import jax

            def draws(key, n):
                out = []
                for _ in range(n):
                    key, sub = jax.random.split(key)
                    out.append(jax.random.normal(sub, (3,)))
                return out
        """)
        assert not active(findings)

    def test_fold_in_is_exempt(self):
        findings = lint("""
            import jax

            def per_shard(key, n):
                return [jax.random.fold_in(key, i) for i in range(n)]
        """)
        assert not active(findings)

    def test_rebind_in_both_branches_is_clean(self):
        # a key refreshed on EVERY surviving path is fresh afterwards
        findings = lint("""
            import jax

            def sample(key, cond):
                a = jax.random.normal(key, (3,))
                if cond:
                    key = jax.random.PRNGKey(0)
                else:
                    key = jax.random.PRNGKey(1)
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert not active(findings)

    def test_rebind_in_one_branch_still_flags(self):
        # ...but refreshed on only ONE path is still a reuse on the other
        findings = lint("""
            import jax

            def sample(key, cond):
                a = jax.random.normal(key, (3,))
                if cond:
                    key = jax.random.PRNGKey(0)
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert rule_ids(active(findings)) == ["key-reuse"]

    def test_host_rng_modules_are_exempt(self):
        # stdlib random / np.random have no key argument: a repeated
        # first-arg Name there is data, not key reuse
        findings = lint("""
            import random
            import numpy as np

            def pick(xs):
                a = random.choice(xs)
                b = random.choice(xs)
                n = np.random.choice(xs)
                m = np.random.choice(xs)
                return a, b, n, m
        """)
        assert not active(findings)

    def test_exclusive_return_branches_are_clean(self):
        # the k_means init ladder: `if mode == a: return sample(key)`
        # followed by another use — exclusive via return, not a reuse
        findings = lint("""
            import jax

            def init(key, mode):
                if mode == "random":
                    return jax.random.normal(key, (3,))
                if mode == "choice":
                    return jax.random.choice(key, 10, (3,))
                raise ValueError(mode)
        """)
        assert not active(findings)


class TestHostSyncLoop:
    def test_flags_float_in_fit_loop(self):
        findings = lint("""
            def fit(self, X):
                for _ in range(10):
                    loss = step(X)
                    if float(loss) < 1e-3:
                        break
                return self
        """)
        assert rule_ids(active(findings)) == ["host-sync-loop"]

    def test_flags_item_and_asarray(self):
        findings = lint("""
            import numpy as np

            def fit_loop(state, xs):
                for x in xs:
                    state = step(state, x)
                    history.append(state.loss.item())
                    snap = np.asarray(state.w)
                return state
        """)
        assert len(active(findings)) == 2

    def test_boundary_sync_outside_loop_is_clean(self):
        findings = lint("""
            def fit(self, X):
                for _ in range(10):
                    loss = step(X)
                return float(loss)
        """)
        assert not active(findings)

    def test_non_fit_function_is_clean(self):
        findings = lint("""
            def render(self, rows):
                for r in rows:
                    print(float(r))
        """)
        assert not active(findings)

    def test_device_reduction_wrapped_sync_is_flagged(self):
        # the canonical convergence check: float(jnp.max(shift)) is a
        # per-iteration device sync — a dotted jnp/np reduction must not
        # read as host-side (only the BARE builtins do)
        findings = lint("""
            import jax.numpy as jnp

            def fit(self, X, tol):
                for _ in range(10):
                    shift = step(X)
                    if float(jnp.max(shift)) < tol:
                        break
                return self
        """)
        assert rule_ids(active(findings)) == ["host-sync-loop"]

    def test_shape_touch_is_clean(self):
        findings = lint("""
            def fit(self, X):
                for _ in range(10):
                    n = float(X.shape[0])
                return n
        """)
        assert not active(findings)


class TestJitInLoop:
    def test_flags_jit_in_loop(self):
        findings = lint("""
            import jax

            def train(xs):
                out = []
                for x in xs:
                    f = jax.jit(lambda v: v * 2)
                    out.append(f(x))
                return out
        """)
        assert rule_ids(active(findings)) == ["jit-in-loop"]

    def test_flags_partial_jit_in_loop(self):
        findings = lint("""
            import jax
            from functools import partial

            def train(xs):
                while xs:
                    step = partial(jax.jit, static_argnums=0)(make_step())
                    xs = step(xs)
        """)
        assert rule_ids(active(findings)) == ["jit-in-loop"]

    def test_hoisted_jit_is_clean(self):
        findings = lint("""
            import jax

            def train(xs):
                f = jax.jit(lambda v: v * 2)
                return [f(x) for x in xs]
        """)
        assert not active(findings)


class TestTracerBranch:
    def test_flags_branch_on_traced_arg(self):
        findings = lint("""
            import jax

            @jax.jit
            def absval(x):
                if x > 0:
                    return x
                return -x
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["tracer-branch"]
        assert "absval" in fs[0].message

    def test_static_argnames_is_clean(self):
        findings = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("mode",))
            def step(x, mode):
                if mode == "fast":
                    return x * 2
                return x
        """)
        assert not active(findings)

    def test_shape_and_none_checks_are_clean(self):
        findings = lint("""
            import jax

            @jax.jit
            def norm(x, w):
                if w is None:
                    return x
                if x.ndim == 2:
                    return x * w
                return x
        """)
        assert not active(findings)

    def test_undecorated_function_is_clean(self):
        findings = lint("""
            def absval(x):
                if x > 0:
                    return x
                return -x
        """)
        assert not active(findings)


class TestSwallowedCollective:
    def test_flags_broad_except(self):
        findings = lint("""
            import jax

            def agree(x):
                try:
                    return jax.lax.psum(x, "data")
                except Exception:
                    return x
        """)
        assert rule_ids(active(findings)) == ["swallowed-collective"]

    def test_flags_bare_except(self):
        findings = lint("""
            from jax.experimental import multihost_utils

            def agree(flag):
                try:
                    return multihost_utils.process_allgather(flag)
                except:
                    return flag
        """)
        assert rule_ids(active(findings)) == ["swallowed-collective"]

    def test_reraise_is_clean(self):
        findings = lint("""
            import jax

            def agree(x):
                try:
                    return jax.lax.psum(x, "data")
                except Exception:
                    log_failure()
                    raise
        """)
        assert not active(findings)

    def test_narrow_except_is_clean(self):
        findings = lint("""
            import jax

            def agree(x):
                try:
                    return jax.lax.psum(x, "data")
                except ValueError:
                    return x
        """)
        assert not active(findings)

    def test_no_collective_in_try_is_clean(self):
        findings = lint("""
            def host_only(path):
                try:
                    return open(path).read()
                except Exception:
                    return None
        """)
        assert not active(findings)


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------

class TestSuppressions:
    SRC = """
        import jax

        def sample(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse -- correlated draws are intentional here
            return a + b
    """

    def test_inline_suppression(self):
        findings = lint(self.SRC)
        assert not active(findings)
        sup = [f for f in findings if f.suppressed]
        assert len(sup) == 1
        assert sup[0].justification == "correlated draws are intentional here"

    def test_suppression_on_line_above(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                # graftlint: disable=key-reuse -- intentional
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        assert not active(findings)

    def test_disable_all(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=all -- test fixture
                return a + b
        """)
        assert not active(findings)

    def test_bare_suppression_is_a_finding(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse
                return a + b
        """)
        assert "bad-suppression" in rule_ids(active(findings))

    def test_unknown_rule_id_is_a_finding(self):
        findings = lint("""
            x = 1  # graftlint: disable=no-such-rule -- whatever
        """)
        fs = active(findings)
        assert rule_ids(fs) == ["bad-suppression"]
        assert "no-such-rule" in fs[0].message

    def test_inline_suppression_does_not_bleed_to_next_line(self):
        # an INLINE disable covers its own statement only; the next
        # line's unjustified violation must still fail the gate
        findings = lint("""
            import jax

            def sample(key, key2):
                a = jax.random.normal(key, (3,))
                c = jax.random.normal(key2, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse -- intentional
                d = jax.random.uniform(key2, (3,))
                return a + b + c + d
        """)
        assert rule_ids(active(findings)) == ["key-reuse"]

    def test_wrong_rule_id_does_not_suppress(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=jit-in-loop -- wrong id
                return a + b
        """)
        assert "key-reuse" in rule_ids(active(findings))


# ---------------------------------------------------------------------------
# framework: registry, reporters, CLI
# ---------------------------------------------------------------------------

class TestFramework:
    def test_registry_has_all_rules(self):
        all_rules()  # force registration
        assert set(RULES) == {
            "thread-dispatch", "divergent-collective", "key-reuse",
            "host-sync-loop", "jit-in-loop", "tracer-branch",
            "swallowed-collective",
        }

    def test_select_unknown_rule_raises(self):
        with pytest.raises(KeyError):
            all_rules(["no-such-rule"])

    def test_select_filters(self):
        src = """
            import jax

            def fit(self, key, xs):
                for x in xs:
                    v = jax.random.normal(key, (3,))
                    print(float(v))
        """
        both = lint(src)
        assert set(rule_ids(active(both))) == {"key-reuse", "host-sync-loop"}
        only = lint(src, select=["key-reuse"])
        assert rule_ids(active(only)) == ["key-reuse"]

    def test_json_reporter(self):
        findings = lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """)
        payload = json.loads(render_json(findings))
        assert payload["version"] == 1
        assert payload["counts"]["key-reuse"]["active"] == 1
        assert payload["findings"][0]["rule"] == "key-reuse"
        assert "key-reuse" in payload["rules"]

    def test_text_reporter_counts_line(self):
        out = render_text([], [])
        assert "0 finding(s)" in out

    def test_per_rule_counts(self):
        findings = lint(TestSuppressions.SRC)
        counts = per_rule_counts(findings)
        assert counts["key-reuse"] == {"active": 0, "suppressed": 1}

    def test_bare_string_path_accepted(self):
        # a bare str must lint the path, not iterate its characters
        findings_str, errors_str = lint_paths(PKG)
        findings_list, errors_list = lint_paths([PKG])
        assert not errors_str
        assert len(findings_str) == len(findings_list)
        assert findings_str  # the 13 justified suppressions, at least

    def test_missing_path_is_an_error_not_a_clean_pass(self):
        findings, errors = lint_paths(["/no/such/dir/anywhere"])
        assert not findings
        assert errors and "no such file" in errors[0]

    def test_syntax_error_is_reported_not_skipped(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        findings, errors = lint_paths([str(bad)])
        assert errors and "syntax error" in errors[0]


class TestCLI:
    def test_exit_one_on_findings(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text(textwrap.dedent("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """))
        assert main([str(f)]) == 1
        assert "key-reuse" in capsys.readouterr().out

    def test_exit_zero_on_clean(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        assert main([str(f)]) == 0

    def test_exit_two_on_missing_path(self, capsys):
        assert main(["/no/such/dir/anywhere"]) == 2

    def test_exit_two_on_bad_select(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        assert main([str(f), "--select", "bogus"]) == 2

    def test_json_format(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        assert main([str(f), "--format", "json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"] == []

    def test_list_rules(self, capsys):
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "key-reuse" in out and "thread-dispatch" in out


class TestDiagnosticsLintReport:
    def test_lint_report_shape(self):
        from dask_ml_tpu import diagnostics

        report = diagnostics.lint_report()
        assert report["active"] == 0, report
        assert report["errors"] == []
        assert report["suppressed"] >= 1  # the library's justified debt
        for rule, c in report["counts"].items():
            assert set(c) == {"active", "suppressed"}
            assert rule in RULES

    def test_lint_report_explicit_paths(self, tmp_path):
        from dask_ml_tpu import diagnostics

        f = tmp_path / "mod.py"
        f.write_text(textwrap.dedent("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """))
        report = diagnostics.lint_report([str(tmp_path)])
        assert report["active"] == 1
        assert report["counts"]["key-reuse"]["active"] == 1

"""Round-1 API gaps (VERDICT item 8): OneHotEncoder(drop), multiclass
LogisticRegression, make_classification_df, device LabelEncoder."""

import numpy as np
import pytest

import jax

from dask_ml_tpu.core import ShardedRows, shard_rows, unshard


class TestOneHotDrop:
    def _data(self):
        return np.array(
            [[0, 10], [1, 20], [2, 10], [0, 20], [1, 10]], dtype=np.float64
        )

    @pytest.mark.parametrize("drop", [None, "first", "if_binary"])
    def test_parity_with_sklearn(self, drop):
        from sklearn.preprocessing import OneHotEncoder as SkOHE

        from dask_ml_tpu.preprocessing import OneHotEncoder

        X = self._data()
        ours = OneHotEncoder(drop=drop).fit(X)
        theirs = SkOHE(drop=drop, sparse_output=False).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X)
        )
        assert list(ours.get_feature_names_out()) == list(
            theirs.get_feature_names_out()
        )

    def test_drop_array(self):
        from sklearn.preprocessing import OneHotEncoder as SkOHE

        from dask_ml_tpu.preprocessing import OneHotEncoder

        X = self._data()
        drop = [1.0, 20.0]
        ours = OneHotEncoder(drop=drop).fit(X)
        theirs = SkOHE(drop=np.asarray(drop), sparse_output=False).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X)
        )

    def test_drop_bad_value_raises(self):
        from dask_ml_tpu.preprocessing import OneHotEncoder

        with pytest.raises(ValueError, match="not a category"):
            OneHotEncoder(drop=[99.0, 20.0]).fit(self._data())

    def test_drop_sharded_roundtrip(self, mesh):
        from dask_ml_tpu.preprocessing import OneHotEncoder

        X = self._data()
        enc = OneHotEncoder(drop="first").fit(X)
        out = enc.transform(shard_rows(X))
        assert isinstance(out, ShardedRows)
        back = enc.inverse_transform(out)
        np.testing.assert_allclose(back.astype(np.float64), X)

    def test_drop_frame(self):
        import pandas as pd

        from dask_ml_tpu.preprocessing import OneHotEncoder

        df = pd.DataFrame({"a": ["x", "y", "x"], "b": [1, 2, 1]})
        out = OneHotEncoder(drop="first").fit_transform(df)
        assert list(out.columns) == ["a_y", "b_2"]


class TestMulticlassLogistic:
    def test_three_classes_labels_and_proba(self, rng, mesh):
        from sklearn.datasets import make_blobs

        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = make_blobs(n_samples=600, n_features=5, centers=3,
                          cluster_std=1.0, random_state=0)
        X = X.astype(np.float32)
        lr = LogisticRegression(solver="lbfgs", max_iter=100).fit(
            shard_rows(X), y
        )
        assert list(lr.classes_) == [0, 1, 2]
        pred = lr.predict(shard_rows(X))
        assert pred.dtype == y.dtype
        assert (pred == y).mean() > 0.95
        proba = np.asarray(lr.predict_proba(shard_rows(X)))
        assert proba.shape == (600, 3)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
        assert lr.coef_.shape == (3, 5)
        assert np.asarray(lr.intercept_).shape == (3,)

    def test_string_labels(self, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(300, 4)).astype(np.float32)
        y = np.where(X[:, 0] > 0, "pos", "neg")
        lr = LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
        assert set(lr.classes_) == {"neg", "pos"}
        assert set(lr.predict(X[:20])) <= {"neg", "pos"}
        assert lr.score(X, y) > 0.9

    def test_binary_backward_compatible_shapes(self, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(300, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        lr = LogisticRegression(solver="admm", max_iter=50).fit(
            shard_rows(X), shard_rows(y)
        )
        assert np.asarray(lr.coef_).shape == (6,)
        assert isinstance(lr.intercept_, float)
        assert lr.score(X, y) > 0.9

    def test_parity_with_sklearn_multiclass(self, rng):
        from sklearn.datasets import make_blobs
        from sklearn.linear_model import LogisticRegression as SkLR

        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = make_blobs(n_samples=450, n_features=4, centers=3,
                          cluster_std=1.5, random_state=1)
        X = X.astype(np.float32)
        ours = LogisticRegression(solver="lbfgs", max_iter=200).fit(X, y)
        theirs = SkLR(max_iter=200).fit(X, y)
        ours_acc = (ours.predict(X) == y).mean()
        theirs_acc = theirs.score(X, y)
        assert ours_acc >= theirs_acc - 0.03

    def test_inert_params_warn(self, rng):
        # class_weight is REAL since round 3 (no warning); warm_start is
        # the one remaining accepted-inert param (reference behavior)
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(60, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(int)
        with pytest.warns(UserWarning, match="warm_start"):
            LogisticRegression(warm_start=True, max_iter=5).fit(X, y)
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            LogisticRegression(class_weight="balanced", max_iter=5).fit(X, y)

    def test_single_class_raises(self, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(40, 3)).astype(np.float32)
        with pytest.raises(ValueError, match="2 classes"):
            LogisticRegression().fit(X, np.zeros(40))


class TestMakeClassificationDf:
    def test_shapes_and_names(self):
        import pandas as pd

        from dask_ml_tpu.datasets import make_classification_df

        df, y = make_classification_df(
            n_samples=120, n_features=7, chunks=40, random_state=0
        )
        assert isinstance(df, pd.DataFrame) and isinstance(y, pd.Series)
        assert df.shape == (120, 7)
        assert list(df.columns) == [f"feature_{i}" for i in range(7)]
        assert y.name == "target"
        assert set(y.unique()) == {0, 1}

    def test_dates_column(self):
        from dask_ml_tpu.datasets import make_classification_df

        df, _ = make_classification_df(
            n_samples=50, n_features=5, random_state=0,
            dates=("2024-01-01", "2024-02-01"),
        )
        assert df.columns[0] == "date"
        assert df["date"].between("2024-01-01", "2024-02-01").all()

    def test_deterministic(self):
        from dask_ml_tpu.datasets import make_classification_df

        a, ya = make_classification_df(n_samples=60, n_features=4, random_state=7)
        b, yb = make_classification_df(n_samples=60, n_features=4, random_state=7)
        np.testing.assert_allclose(a.to_numpy(), b.to_numpy())
        assert (ya == yb).all()


class TestLabelEncoderDevice:
    def test_sharded_numeric_stays_sharded(self, rng, mesh):
        from dask_ml_tpu.preprocessing import LabelEncoder

        y = rng.choice([3.0, 7.0, 11.0], size=101).astype(np.float32)
        ys = shard_rows(y)
        le = LabelEncoder().fit(ys)
        out = le.transform(ys)
        assert isinstance(out, ShardedRows)
        np.testing.assert_array_equal(
            unshard(out), np.searchsorted(le.classes_, y)
        )
        back = le.inverse_transform(out)
        np.testing.assert_allclose(back, y)

    def test_sharded_unseen_raises(self, rng, mesh):
        from dask_ml_tpu.preprocessing import LabelEncoder

        le = LabelEncoder().fit(np.array([1.0, 2.0]))
        with pytest.raises(ValueError, match="unseen"):
            le.transform(shard_rows(np.array([1.0, 3.0], dtype=np.float32)))

    def test_parity_with_sklearn(self, rng):
        from sklearn.preprocessing import LabelEncoder as SkLE

        from dask_ml_tpu.preprocessing import LabelEncoder

        y = rng.choice(["a", "b", "c"], size=50)
        ours = LabelEncoder().fit(y)
        theirs = SkLE().fit(y)
        np.testing.assert_array_equal(ours.classes_, theirs.classes_)
        np.testing.assert_array_equal(
            np.asarray(ours.transform(y)), theirs.transform(y)
        )


class TestReviewRegressions:
    def test_precomputed_rejects_nonsquare(self, rng, mesh):
        from dask_ml_tpu.cluster import SpectralClustering

        X = rng.normal(size=(40, 5)).astype(np.float32)
        for nc in (10, None):
            with pytest.raises(ValueError, match="n_samples, n_samples"):
                SpectralClustering(
                    affinity="precomputed", n_components=nc
                ).fit(shard_rows(X))

    def test_callable_metric_eager_numpy_ok(self, rng, mesh):
        # numpy-based callables must keep working on sharded x sharded
        # input (they run eagerly on the global operands, not in the ring)
        from dask_ml_tpu.metrics import pairwise_distances

        def np_metric(a, b):
            a = np.asarray(a)
            b = np.asarray(b)
            return np.abs(a[:, None, 0] - b[None, :, 0])

        X = rng.normal(size=(17, 3)).astype(np.float32)
        Y = rng.normal(size=(9, 3)).astype(np.float32)
        out = pairwise_distances(shard_rows(X), shard_rows(Y), metric=np_metric)
        np.testing.assert_allclose(
            np.asarray(out), np.abs(X[:, None, 0] - Y[None, :, 0]), rtol=1e-5
        )

"""Round-1 API gaps (VERDICT item 8): OneHotEncoder(drop), multiclass
LogisticRegression, make_classification_df, device LabelEncoder."""

import numpy as np
import pytest

import jax

from dask_ml_tpu.core import ShardedRows, shard_rows, unshard


class TestOneHotDrop:
    def _data(self):
        return np.array(
            [[0, 10], [1, 20], [2, 10], [0, 20], [1, 10]], dtype=np.float64
        )

    @pytest.mark.parametrize("drop", [None, "first", "if_binary"])
    def test_parity_with_sklearn(self, drop):
        from sklearn.preprocessing import OneHotEncoder as SkOHE

        from dask_ml_tpu.preprocessing import OneHotEncoder

        X = self._data()
        ours = OneHotEncoder(drop=drop).fit(X)
        theirs = SkOHE(drop=drop, sparse_output=False).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X)
        )
        assert list(ours.get_feature_names_out()) == list(
            theirs.get_feature_names_out()
        )

    def test_drop_array(self):
        from sklearn.preprocessing import OneHotEncoder as SkOHE

        from dask_ml_tpu.preprocessing import OneHotEncoder

        X = self._data()
        drop = [1.0, 20.0]
        ours = OneHotEncoder(drop=drop).fit(X)
        theirs = SkOHE(drop=np.asarray(drop), sparse_output=False).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X)
        )

    def test_drop_bad_value_raises(self):
        from dask_ml_tpu.preprocessing import OneHotEncoder

        with pytest.raises(ValueError, match="not a category"):
            OneHotEncoder(drop=[99.0, 20.0]).fit(self._data())

    def test_drop_sharded_roundtrip(self, mesh):
        from dask_ml_tpu.preprocessing import OneHotEncoder

        X = self._data()
        enc = OneHotEncoder(drop="first").fit(X)
        out = enc.transform(shard_rows(X))
        assert isinstance(out, ShardedRows)
        back = enc.inverse_transform(out)
        np.testing.assert_allclose(back.astype(np.float64), X)

    def test_drop_frame(self):
        import pandas as pd

        from dask_ml_tpu.preprocessing import OneHotEncoder

        df = pd.DataFrame({"a": ["x", "y", "x"], "b": [1, 2, 1]})
        out = OneHotEncoder(drop="first").fit_transform(df)
        assert list(out.columns) == ["a_y", "b_2"]


class TestMulticlassLogistic:
    def test_three_classes_labels_and_proba(self, rng, mesh):
        from sklearn.datasets import make_blobs

        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = make_blobs(n_samples=600, n_features=5, centers=3,
                          cluster_std=1.0, random_state=0)
        X = X.astype(np.float32)
        lr = LogisticRegression(solver="lbfgs", max_iter=100).fit(
            shard_rows(X), y
        )
        assert list(lr.classes_) == [0, 1, 2]
        pred = lr.predict(shard_rows(X))
        assert pred.dtype == y.dtype
        assert (pred == y).mean() > 0.95
        proba = np.asarray(lr.predict_proba(shard_rows(X)))
        assert proba.shape == (600, 3)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
        assert lr.coef_.shape == (3, 5)
        assert np.asarray(lr.intercept_).shape == (3,)

    def test_string_labels(self, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(300, 4)).astype(np.float32)
        y = np.where(X[:, 0] > 0, "pos", "neg")
        lr = LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
        assert set(lr.classes_) == {"neg", "pos"}
        assert set(lr.predict(X[:20])) <= {"neg", "pos"}
        assert lr.score(X, y) > 0.9

    def test_binary_backward_compatible_shapes(self, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(300, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        lr = LogisticRegression(solver="admm", max_iter=50).fit(
            shard_rows(X), shard_rows(y)
        )
        assert np.asarray(lr.coef_).shape == (6,)
        assert isinstance(lr.intercept_, float)
        assert lr.score(X, y) > 0.9

    def test_parity_with_sklearn_multiclass(self, rng):
        from sklearn.datasets import make_blobs
        from sklearn.linear_model import LogisticRegression as SkLR

        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = make_blobs(n_samples=450, n_features=4, centers=3,
                          cluster_std=1.5, random_state=1)
        X = X.astype(np.float32)
        ours = LogisticRegression(solver="lbfgs", max_iter=200).fit(X, y)
        theirs = SkLR(max_iter=200).fit(X, y)
        ours_acc = (ours.predict(X) == y).mean()
        theirs_acc = theirs.score(X, y)
        assert ours_acc >= theirs_acc - 0.03

    def test_no_inert_param_warnings(self, rng):
        # class_weight is REAL since round 3; warm_start is REAL since
        # round 5 (seeds the solver with the previous coefficients) —
        # nothing left to warn about
        import warnings

        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(60, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(int)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            LogisticRegression(warm_start=True, max_iter=5).fit(X, y)
            LogisticRegression(class_weight="balanced", max_iter=5).fit(X, y)

    def test_warm_start_seeds_previous_solution(self, rng):
        """A warm refit on the SAME data starts at the previous optimum:
        the solver converges in (far) fewer iterations and reproduces
        the cold solution.  Covers binary, packed OvR, and multinomial."""
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(200, 6)).astype(np.float32)
        w = rng.normal(size=6)
        yb = (X @ w > 0).astype(np.float32)
        y3 = np.digitize(X @ w, [-0.5, 0.5]).astype(np.float32)

        for y, kw in [
            (yb, {}),
            (y3, {}),  # OvR (sequential on CPU by auto policy)
            (y3, {"multi_class": "multinomial"}),
        ]:
            cold = LogisticRegression(
                solver="lbfgs", max_iter=200, warm_start=True, **kw
            ).fit(X, y)
            first_iters = int(np.max(cold.n_iter_))
            coef_first = np.asarray(cold.coef_).copy()
            cold.fit(X, y)  # warm refit, same data
            assert int(np.max(cold.n_iter_)) <= max(first_iters // 2, 2), (
                kw, cold.n_iter_, first_iters)
            np.testing.assert_allclose(
                np.asarray(cold.coef_), coef_first, atol=1e-3)

    def test_warm_start_packed_lanes(self, rng, monkeypatch):
        """The vmapped packed-OvR path consumes the per-lane Beta0 stack
        (auto falls back to sequential on CPU, so force packed)."""
        from dask_ml_tpu.linear_model import LogisticRegression

        monkeypatch.setenv("DASK_ML_TPU_PACK", "packed")
        X = rng.normal(size=(200, 6)).astype(np.float32)
        w = rng.normal(size=6)
        y3 = np.digitize(X @ w, [-0.5, 0.5]).astype(np.float32)
        clf = LogisticRegression(
            solver="lbfgs", max_iter=200, warm_start=True).fit(X, y3)
        first = int(np.max(clf.n_iter_))
        coef_first = np.asarray(clf.coef_).copy()
        clf.fit(X, y3)
        assert int(np.max(clf.n_iter_)) <= max(first // 2, 2), (
            clf.n_iter_, first)
        np.testing.assert_allclose(
            np.asarray(clf.coef_), coef_first, atol=1e-3)

    def test_warm_start_cold_starts_on_changed_geometry(self, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(100, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        clf = LogisticRegression(
            solver="lbfgs", max_iter=50, warm_start=True).fit(X, y)
        # different feature count: silently cold-starts, must not crash
        X2 = rng.normal(size=(100, 6)).astype(np.float32)
        y2 = (X2[:, 0] > 0).astype(np.float32)
        clf.fit(X2, y2)
        assert clf.coef_.shape == (6,)

    def test_single_class_raises(self, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(40, 3)).astype(np.float32)
        with pytest.raises(ValueError, match="2 classes"):
            LogisticRegression().fit(X, np.zeros(40))


class TestMakeClassificationDf:
    def test_shapes_and_names(self):
        import pandas as pd

        from dask_ml_tpu.datasets import make_classification_df

        df, y = make_classification_df(
            n_samples=120, n_features=7, chunks=40, random_state=0
        )
        assert isinstance(df, pd.DataFrame) and isinstance(y, pd.Series)
        assert df.shape == (120, 7)
        assert list(df.columns) == [f"feature_{i}" for i in range(7)]
        assert y.name == "target"
        assert set(y.unique()) == {0, 1}

    def test_dates_column(self):
        from dask_ml_tpu.datasets import make_classification_df

        df, _ = make_classification_df(
            n_samples=50, n_features=5, random_state=0,
            dates=("2024-01-01", "2024-02-01"),
        )
        assert df.columns[0] == "date"
        assert df["date"].between("2024-01-01", "2024-02-01").all()

    def test_deterministic(self):
        from dask_ml_tpu.datasets import make_classification_df

        a, ya = make_classification_df(n_samples=60, n_features=4, random_state=7)
        b, yb = make_classification_df(n_samples=60, n_features=4, random_state=7)
        np.testing.assert_allclose(a.to_numpy(), b.to_numpy())
        assert (ya == yb).all()


class TestLabelEncoderDevice:
    def test_sharded_numeric_stays_sharded(self, rng, mesh):
        from dask_ml_tpu.preprocessing import LabelEncoder

        y = rng.choice([3.0, 7.0, 11.0], size=101).astype(np.float32)
        ys = shard_rows(y)
        le = LabelEncoder().fit(ys)
        out = le.transform(ys)
        assert isinstance(out, ShardedRows)
        np.testing.assert_array_equal(
            unshard(out), np.searchsorted(le.classes_, y)
        )
        back = le.inverse_transform(out)
        np.testing.assert_allclose(back, y)

    def test_sharded_unseen_raises(self, rng, mesh):
        from dask_ml_tpu.preprocessing import LabelEncoder

        le = LabelEncoder().fit(np.array([1.0, 2.0]))
        with pytest.raises(ValueError, match="unseen"):
            le.transform(shard_rows(np.array([1.0, 3.0], dtype=np.float32)))

    def test_parity_with_sklearn(self, rng):
        from sklearn.preprocessing import LabelEncoder as SkLE

        from dask_ml_tpu.preprocessing import LabelEncoder

        y = rng.choice(["a", "b", "c"], size=50)
        ours = LabelEncoder().fit(y)
        theirs = SkLE().fit(y)
        np.testing.assert_array_equal(ours.classes_, theirs.classes_)
        np.testing.assert_array_equal(
            np.asarray(ours.transform(y)), theirs.transform(y)
        )


class TestReviewRegressions:
    def test_precomputed_rejects_nonsquare(self, rng, mesh):
        from dask_ml_tpu.cluster import SpectralClustering

        X = rng.normal(size=(40, 5)).astype(np.float32)
        for nc in (10, None):
            with pytest.raises(ValueError, match="n_samples, n_samples"):
                SpectralClustering(
                    affinity="precomputed", n_components=nc
                ).fit(shard_rows(X))

    def test_callable_metric_eager_numpy_ok(self, rng, mesh):
        # numpy-based callables must keep working on sharded x sharded
        # input (they run eagerly on the global operands, not in the ring)
        from dask_ml_tpu.metrics import pairwise_distances

        def np_metric(a, b):
            a = np.asarray(a)
            b = np.asarray(b)
            return np.abs(a[:, None, 0] - b[None, :, 0])

        X = rng.normal(size=(17, 3)).astype(np.float32)
        Y = rng.normal(size=(9, 3)).astype(np.float32)
        out = pairwise_distances(shard_rows(X), shard_rows(Y), metric=np_metric)
        np.testing.assert_allclose(
            np.asarray(out), np.abs(X[:, None, 0] - Y[None, :, 0]), rtol=1e-5
        )


class TestContractGapsRound3:
    """score(sample_weight=), predict_log_proba, scaler partial_fit —
    sklearn-contract surface a switching user expects (round-3 sweep)."""

    def _clf_data(self, rng):
        X = rng.normal(size=(200, 5)).astype(np.float32)
        w = rng.normal(size=5)
        y = (X @ w > 0).astype(np.int64)
        return X, y

    def test_logreg_weighted_score_and_log_proba(self, rng):
        from sklearn.metrics import accuracy_score as sk_acc

        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = self._clf_data(rng)
        sw = rng.rand(200)
        m = LogisticRegression(max_iter=60).fit(X, y)
        assert m.score(X, y, sample_weight=sw) == pytest.approx(
            sk_acc(y, np.asarray(m.predict(X)), sample_weight=sw), abs=1e-6
        )
        lp = np.asarray(m.predict_log_proba(X))
        np.testing.assert_allclose(
            np.exp(lp), np.asarray(m.predict_proba(X)), atol=1e-6
        )

    def test_sgd_weighted_score_and_log_proba(self, rng):
        from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor

        X, y = self._clf_data(rng)
        sw = rng.rand(200)
        m = SGDClassifier(max_iter=60, tol=None).fit(X, y)
        s_w = m.score(X, y, sample_weight=sw)
        assert 0.0 <= s_w <= 1.0
        lp = np.asarray(m.predict_log_proba(X))
        np.testing.assert_allclose(
            np.exp(lp), np.asarray(m.predict_proba(X)), atol=1e-6
        )
        yr = (X[:, 0] * 2).astype(np.float32)
        r = SGDRegressor(max_iter=100, tol=None).fit(X, yr)
        assert r.score(X, yr, sample_weight=sw) <= 1.0

    def test_kmeans_weighted_score(self, rng):
        from dask_ml_tpu.cluster import KMeans, MiniBatchKMeans

        X = rng.normal(size=(120, 3)).astype(np.float32)
        w = np.zeros(120); w[:60] = 1.0
        for cls in (KMeans, MiniBatchKMeans):
            m = cls(n_clusters=3, random_state=0).fit(X)
            # zero-weighted rows contribute nothing: score == score on X[:60]
            assert m.score(X, sample_weight=w) == pytest.approx(
                m.score(X[:60]), rel=1e-4
            )

    def test_standard_scaler_partial_fit_matches_fit(self, rng):
        from dask_ml_tpu.preprocessing import StandardScaler

        X = rng.normal(size=(300, 4)).astype(np.float32) * 3 + 1
        full = StandardScaler().fit(X)
        stream = StandardScaler()
        for lo in range(0, 300, 100):
            stream.partial_fit(X[lo:lo + 100])
        np.testing.assert_allclose(
            np.asarray(stream.mean_), np.asarray(full.mean_), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(stream.var_), np.asarray(full.var_), rtol=1e-4
        )
        assert stream.n_samples_seen_ == 300
        # refit resets the stream state
        refit = stream.fit(X[:100])
        assert refit.n_samples_seen_ == 100

    def test_minmax_maxabs_partial_fit(self, rng):
        from dask_ml_tpu.preprocessing import MaxAbsScaler, MinMaxScaler

        X = rng.normal(size=(200, 3)).astype(np.float32) * 5
        for cls, attrs in ((MinMaxScaler, ("data_min_", "data_max_")),
                           (MaxAbsScaler, ("max_abs_",))):
            full = cls().fit(X)
            stream = cls()
            for lo in range(0, 200, 64):
                stream.partial_fit(X[lo:lo + 64])
            for a in attrs:
                np.testing.assert_allclose(
                    np.asarray(getattr(stream, a)),
                    np.asarray(getattr(full, a)), rtol=1e-6,
                )
            assert stream.n_samples_seen_ == 200

    def test_scaler_partial_fit_streams_through_incremental(self, rng):
        from dask_ml_tpu.wrappers import Incremental
        from dask_ml_tpu.preprocessing import StandardScaler

        X = rng.normal(size=(256, 4)).astype(np.float32)
        inc = Incremental(StandardScaler(), chunk_size=64).fit(X)
        np.testing.assert_allclose(
            np.asarray(inc.estimator_.mean_),
            X.mean(axis=0), rtol=1e-4, atol=1e-5,
        )

    def test_weighted_score_string_labels(self, rng):
        from dask_ml_tpu.linear_model import SGDClassifier

        X = rng.normal(size=(150, 4)).astype(np.float32)
        y = np.where(X[:, 0] > 0, "pos", "neg")
        sw = rng.rand(150)
        m = SGDClassifier(max_iter=50, tol=None).fit(X, y)
        s = m.score(X, y, sample_weight=sw)
        hits = np.asarray(m.predict(X)) == y
        assert s == pytest.approx(np.average(hits, weights=sw))

    def test_standard_scaler_stream_checkpoint_roundtrip(self, rng, tmp_path):
        from dask_ml_tpu.checkpoint import load_estimator, save_estimator
        from dask_ml_tpu.preprocessing import StandardScaler

        X = rng.normal(size=(300, 4)).astype(np.float32) * 2 + 3
        a = StandardScaler().partial_fit(X[:100]).partial_fit(X[100:200])
        p = str(tmp_path / "scaler.ckpt")
        save_estimator(a, p)
        b = load_estimator(p)
        b.partial_fit(X[200:])
        full = StandardScaler().fit(X)
        np.testing.assert_allclose(
            np.asarray(b.mean_), np.asarray(full.mean_), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(b.var_), np.asarray(full.var_), rtol=1e-4
        )
        assert b.n_samples_seen_ == 300

    def test_balanced_class_weight_sub_unit_mask_mass(self, rng):
        # regression: the balanced branch clamped per-class weight mass
        # to 1, shrinking balanced weights whenever mass < 1
        import jax.numpy as jnp

        from dask_ml_tpu.utils import effective_mask

        y_idx = jnp.asarray(np.r_[np.zeros(30), np.ones(10)], jnp.float32)
        tiny = jnp.full((40,), 1e-3, jnp.float32)  # mask IS the weight
        m = effective_mask(
            tiny, y_idx, class_weight="balanced", classes=[0, 1],
            n_samples=40,
        )
        m = np.asarray(m)
        # balanced: minority rows upweighted by exactly count ratio 3x
        assert m[39] / m[0] == pytest.approx(3.0, rel=1e-4)


class TestFeatureNamesAndPcaScore:
    """Round-5 API slivers: get_feature_names_out across the transformer
    surface (sklearn OneToOne / ClassNamePrefix mixin contracts) and the
    probabilistic-PCA log-likelihood (``PCA.score[_samples]``)."""

    def test_one_to_one_names(self, rng):
        from dask_ml_tpu.impute import SimpleImputer
        from dask_ml_tpu.preprocessing import (
            MaxAbsScaler,
            MinMaxScaler,
            Normalizer,
            QuantileTransformer,
            RobustScaler,
            StandardScaler,
        )

        X = rng.normal(size=(30, 3)).astype(np.float64)
        for est in (StandardScaler(), MinMaxScaler(), MaxAbsScaler(),
                    RobustScaler(), QuantileTransformer(n_quantiles=5),
                    Normalizer(), SimpleImputer()):
            est.fit(X)
            assert list(est.get_feature_names_out()) == ["x0", "x1", "x2"]
            assert list(est.get_feature_names_out(["a", "b", "c"])) == [
                "a", "b", "c"]

    def test_imputer_indicator_names_match_width(self, rng):
        from dask_ml_tpu.impute import SimpleImputer

        X = rng.normal(size=(30, 3)).astype(np.float64)
        X[2, 1] = np.nan
        im = SimpleImputer(add_indicator=True).fit(X)
        names = im.get_feature_names_out()
        assert list(names) == ["x0", "x1", "x2", "missingindicator_x1"]
        assert np.asarray(im.transform(X)).shape[1] == len(names)

    def test_decomposition_names(self, rng):
        from dask_ml_tpu.decomposition import (
            PCA,
            IncrementalPCA,
            TruncatedSVD,
        )

        X = rng.normal(size=(40, 4)).astype(np.float64)
        assert list(
            PCA(n_components=2).fit(X).get_feature_names_out()
        ) == ["pca0", "pca1"]
        assert list(
            TruncatedSVD(n_components=2).fit(X).get_feature_names_out()
        ) == ["truncatedsvd0", "truncatedsvd1"]
        assert list(
            IncrementalPCA(n_components=2).fit(X).get_feature_names_out()
        ) == ["incrementalpca0", "incrementalpca1"]

    @pytest.mark.parametrize("whiten", [False, True])
    def test_pca_score_samples_matches_sklearn(self, rng, whiten):
        from sklearn.decomposition import PCA as SkPCA

        from dask_ml_tpu.decomposition import PCA

        X = rng.normal(size=(60, 5)).astype(np.float64)
        ours = PCA(n_components=3, whiten=whiten).fit(X)
        ref = SkPCA(n_components=3, whiten=whiten).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.get_covariance()), ref.get_covariance(),
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(ours.score_samples(X)), ref.score_samples(X),
            atol=1e-4,
        )
        assert ours.score(X) == pytest.approx(ref.score(X), abs=1e-4)
        # sharded input path slices to real rows
        s = shard_rows(X.astype(np.float32))
        assert np.asarray(ours.score_samples(s)).shape == (60,)


class TestRound5Slivers:
    """Continuation-session sliver sweep: methods a migrating sklearn
    user would reach for that the surface audit found missing."""

    @pytest.mark.parametrize("whiten", [False, True])
    @pytest.mark.parametrize("k", [3, 5])
    def test_pca_get_precision_parity(self, rng, whiten, k):
        from sklearn.decomposition import PCA as SkPCA

        from dask_ml_tpu.decomposition import PCA

        X = (rng.normal(size=(80, 5)) * np.linspace(2, 0.3, 5)).astype(
            np.float64
        )
        ours = PCA(n_components=k, whiten=whiten).fit(X)
        ref = SkPCA(n_components=k, whiten=whiten, svd_solver="full").fit(X)
        scale = np.abs(ref.get_precision()).max()
        np.testing.assert_allclose(
            np.asarray(ours.get_precision()) / scale,
            ref.get_precision() / scale, atol=2e-5,
        )

    def test_incremental_pca_covariance_tracks_full_pca(self, rng):
        # deliberate deviation from sklearn's IPCA (docstring): our
        # noise_variance_ is the PCA-consistent residual estimator, so
        # the model covariance/precision must track FULL PCA on the
        # same data — sklearn's IPCA tail-spectrum estimate does not
        from sklearn.decomposition import PCA as SkPCA

        from dask_ml_tpu.decomposition import IncrementalPCA

        X = (rng.normal(size=(300, 8)) * np.linspace(3, 0.1, 8)).astype(
            np.float32
        )
        io = IncrementalPCA(n_components=4, batch_size=60).fit(X)
        ref = SkPCA(n_components=4, svd_solver="full").fit(
            X.astype(np.float64)
        )
        # streamed fit: loose sanity on the covariance (incremental
        # components/noise carry estimation error of their own; the
        # precision INVERSE amplifies it, so exactness is asserted via
        # the transplanted-attributes check below instead)
        got = np.asarray(io.get_covariance())
        want = ref.get_covariance()
        scale = np.abs(want).max()
        np.testing.assert_allclose(got / scale, want / scale, atol=5e-2)
        # formula exactness: with identical fitted attributes the two
        # classes must produce identical covariance/precision
        io.components_ = np.asarray(ref.components_, np.float64)
        io.explained_variance_ = np.asarray(
            ref.explained_variance_, np.float64
        )
        io.noise_variance_ = float(ref.noise_variance_)
        io.n_components_ = 4
        for m in ("get_covariance", "get_precision"):
            got, want = np.asarray(getattr(io, m)()), getattr(ref, m)()
            scale = np.abs(want).max()
            # f32 device math: formula-identical up to roundoff
            np.testing.assert_allclose(
                got / scale, want / scale, atol=1e-5
            )

    def test_kmeans_get_feature_names_out(self, rng):
        from dask_ml_tpu.cluster import KMeans

        X = rng.normal(size=(100, 4)).astype(np.float32)
        km = KMeans(n_clusters=3, random_state=0).fit(shard_rows(X))
        assert list(km.get_feature_names_out()) == [
            "kmeans0", "kmeans1", "kmeans2",
        ]
        # names describe transform's output width
        assert np.asarray(km.transform(shard_rows(X))).shape[1] == 3

    def test_ordinal_encoder_get_feature_names_out(self):
        import pandas as pd

        from dask_ml_tpu.preprocessing import OrdinalEncoder

        Xc = np.array([["a", "x"], ["b", "y"], ["a", "y"]], dtype=object)
        oe = OrdinalEncoder().fit(Xc)
        assert list(oe.get_feature_names_out()) == ["x0", "x1"]
        assert list(oe.get_feature_names_out(["u", "v"])) == ["u", "v"]
        df = pd.DataFrame({"c1": ["a", "b"], "c2": [1.0, 2.0]})
        oe2 = OrdinalEncoder().fit(df)
        assert list(oe2.get_feature_names_out()) == ["c1", "c2"]

    def test_simple_imputer_inverse_transform(self, rng):
        from sklearn.impute import SimpleImputer as SkImputer

        from dask_ml_tpu.impute import SimpleImputer

        X = rng.normal(size=(50, 4)).astype(np.float64)
        X[rng.rand(*X.shape) < 0.25] = np.nan
        ours = SimpleImputer(strategy="mean", add_indicator=True).fit(X)
        ref = SkImputer(strategy="mean", add_indicator=True).fit(X)
        t = np.asarray(ours.transform(X))
        inv, inv_ref = (
            np.asarray(ours.inverse_transform(t)),
            ref.inverse_transform(ref.transform(X)),
        )
        np.testing.assert_array_equal(np.isnan(inv), np.isnan(inv_ref))
        np.testing.assert_allclose(
            np.nan_to_num(inv), np.nan_to_num(inv_ref), atol=1e-6
        )
        # sharded roundtrip preserves the container
        s = shard_rows(X.astype(np.float32))
        ts = ours.transform(s)
        invs = ours.inverse_transform(ts)
        assert isinstance(invs, ShardedRows)
        assert invs.n_samples == 50
        with pytest.raises(ValueError, match="add_indicator"):
            SimpleImputer().fit(X).inverse_transform(t[:, :4])


class TestRound5AdviceFixes:
    """ISSUE 1 satellites: validation/regularization fixes flagged by the
    round-5 advice review."""

    def test_imputer_inverse_transform_rejects_wrong_width(self, rng):
        from dask_ml_tpu.impute import SimpleImputer

        X = rng.normal(size=(30, 4)).astype(np.float64)
        X[rng.rand(*X.shape) < 0.3] = np.nan
        imp = SimpleImputer(strategy="mean", add_indicator=True).fit(X)
        t = np.asarray(imp.transform(X))
        # truncated input used to be SILENTLY split at d columns; now the
        # width must be exactly d + len(indicator_features_)
        with pytest.raises(ValueError, match="columns"):
            imp.inverse_transform(t[:, :-1])
        with pytest.raises(ValueError, match="columns"):
            imp.inverse_transform(np.hstack([t, t[:, :1]]))
        # the exact width still round-trips
        assert np.asarray(imp.inverse_transform(t)).shape == X.shape

    def test_ordinal_encoder_feature_names_validates_input(self):
        import pandas as pd

        from dask_ml_tpu.preprocessing import OrdinalEncoder

        Xc = np.array([["a", "x"], ["b", "y"], ["a", "y"]], dtype=object)
        oe = OrdinalEncoder().fit(Xc)
        with pytest.raises(ValueError, match="2 features"):
            oe.get_feature_names_out(["only_one"])
        df = pd.DataFrame({"c1": ["a", "b"], "c2": [1.0, 2.0]})
        oe2 = OrdinalEncoder().fit(df)
        # frame fit: the fitted column names verbatim, or an error
        assert list(oe2.get_feature_names_out(["c1", "c2"])) == ["c1", "c2"]
        with pytest.raises(ValueError, match="columns seen at fit"):
            oe2.get_feature_names_out(["c2", "c1"])

    def test_pca_get_precision_unjittered_when_well_posed(self, rng):
        """The full-rank branch must report the PLAIN inverse when it is
        finite — the 1e-12·trace jitter only rescues a singular
        covariance (it used to be applied unconditionally)."""
        from sklearn.decomposition import PCA as SkPCA

        from dask_ml_tpu.decomposition import PCA

        X = (rng.normal(size=(60, 4)) * np.linspace(2, 0.5, 4)).astype(
            np.float64
        )
        ours = PCA(n_components=4).fit(X)  # k == d: full-rank branch
        ref = SkPCA(n_components=4, svd_solver="full").fit(X)
        scale = np.abs(ref.get_precision()).max()
        np.testing.assert_allclose(
            np.asarray(ours.get_precision()) / scale,
            ref.get_precision() / scale, atol=1e-6,
        )

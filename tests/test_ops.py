"""Pallas kernel parity tests (interpret mode on the CPU mesh)."""

import numpy as np
import jax.numpy as jnp

import jax

from dask_ml_tpu.ops import lloyd_assign_reduce


def _reference(x, mask, centers):
    d2 = (
        np.sum(x * x, axis=1)[:, None]
        + np.sum(centers * centers, axis=1)[None, :]
        - 2 * x @ centers.T
    )
    labels = np.argmin(d2, axis=1)
    min_d2 = np.maximum(d2[np.arange(len(x)), labels], 0.0)
    k = centers.shape[0]
    onehot = (labels[:, None] == np.arange(k)[None, :]).astype(np.float32) * mask[:, None]
    return onehot.T @ x, onehot.sum(axis=0), float((min_d2 * mask).sum())


class TestLloydKernel:
    def test_matches_xla_reference(self, rng):
        n, d, k = 300, 7, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        mask = np.ones(n, dtype=np.float32)
        mask[-13:] = 0.0  # padding rows must contribute nothing
        centers = x[:k].copy()
        sums, counts, inertia = lloyd_assign_reduce(
            jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers), interpret=True
        )
        esums, ecounts, einertia = _reference(x, mask, centers)
        np.testing.assert_allclose(np.asarray(sums), esums, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(counts), ecounts)
        np.testing.assert_allclose(float(inertia), einertia, rtol=1e-4)

    def test_multi_tile_accumulation(self, rng):
        # more rows than one tile: grid accumulation across steps
        import dask_ml_tpu.ops.lloyd as L

        orig = L._TILE
        L._TILE = 128
        try:
            n, d, k = 1000, 4, 3
            x = rng.normal(size=(n, d)).astype(np.float32)
            mask = np.ones(n, dtype=np.float32)
            centers = x[:k].copy()
            sums, counts, inertia = lloyd_assign_reduce(
                jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers),
                interpret=True,
            )
            esums, ecounts, einertia = _reference(x, mask, centers)
            np.testing.assert_allclose(np.asarray(sums), esums, rtol=1e-4, atol=1e-3)
            np.testing.assert_allclose(np.asarray(counts), ecounts)
            np.testing.assert_allclose(float(inertia), einertia, rtol=1e-4)
        finally:
            L._TILE = orig

    def test_pallas_parity_on_tpu(self, rng):
        # Hardware (Mosaic-lowered) parity check — the gate that lets
        # DASK_ML_TPU_PALLAS=1 be safely enabled (cluster.k_means._pallas_ok).
        import pytest

        if jax.default_backend() != "tpu":
            pytest.skip("requires a real TPU backend")
        n, d, k = 4096, 16, 8
        x = rng.normal(size=(n, d)).astype(np.float32)
        mask = np.ones(n, dtype=np.float32)
        mask[-100:] = 0.0
        centers = x[:k].copy()
        sums, counts, inertia = lloyd_assign_reduce(
            jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers)
        )
        esums, ecounts, einertia = _reference(x, mask, centers)
        np.testing.assert_allclose(np.asarray(sums), esums, rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(np.asarray(counts), ecounts)
        np.testing.assert_allclose(float(inertia), einertia, rtol=1e-3)

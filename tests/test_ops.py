"""Device-op policy tests: k-means precision modes and the scatter
strategy (segment_sum vs one-hot gemm).

The Pallas Lloyd kernel these tests originally covered was deleted after
its win-or-delete chip adjudication (XLA won every variant — see
docs/design.md "Pallas negative result" and cluster/k_means.py).
"""

import numpy as np
import pytest
import jax.numpy as jnp


class TestKMeansPrecision:
    def test_kmeans_fast_env_matches_highest(self, rng, monkeypatch, mesh):
        # end-to-end: DASK_ML_TPU_KMEANS_PRECISION=fast must converge to
        # the same clustering as highest on well-separated blobs
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows

        centers_true = np.array(
            [[0, 0, 0], [8, 8, 8], [-8, 8, -8]], dtype=np.float32)
        X = np.concatenate([
            c + rng.normal(scale=0.5, size=(120, 3)).astype(np.float32)
            for c in centers_true
        ])
        sX = shard_rows(X)
        km_hi = KMeans(n_clusters=3, init="random", random_state=0,
                       max_iter=30).fit(sX)
        monkeypatch.setenv("DASK_ML_TPU_KMEANS_PRECISION", "fast")
        km_fast = KMeans(n_clusters=3, init="random", random_state=0,
                         max_iter=30).fit(sX)
        np.testing.assert_allclose(
            np.sort(np.asarray(km_fast.cluster_centers_), axis=0),
            np.sort(np.asarray(km_hi.cluster_centers_), axis=0),
            rtol=1e-3, atol=1e-3)
        assert km_fast.inertia_ == pytest.approx(km_hi.inertia_, rel=1e-3)


class TestScatterPolicy:
    """ops.scatter: one policy for segment_sum vs one-hot gemm, shared by
    the quantile sketch and the k-means reduce (r3 verdict #5b)."""

    def _agree(self, rng, monkeypatch, values, ids, k):
        import jax as _jax

        from dask_ml_tpu.ops import bucket_sum

        outs = {}
        for strat in ("segsum", "onehot"):
            monkeypatch.setenv("DASK_ML_TPU_SCATTER", strat)
            _jax.clear_caches()  # strategy is read at trace time
            outs[strat] = np.asarray(bucket_sum(
                jnp.asarray(values), jnp.asarray(ids), k))
        monkeypatch.delenv("DASK_ML_TPU_SCATTER")
        _jax.clear_caches()
        np.testing.assert_allclose(outs["segsum"], outs["onehot"],
                                   rtol=1e-5, atol=1e-5)
        return outs["segsum"]

    def test_strategies_agree_1d(self, rng, monkeypatch):
        ids = rng.randint(0, 17, size=400).astype(np.int32)
        vals = rng.normal(size=400).astype(np.float32)
        got = self._agree(rng, monkeypatch, vals, ids, 17)
        want = np.zeros(17, np.float32)
        np.add.at(want, ids, vals)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_strategies_agree_2d_weighted(self, rng, monkeypatch):
        ids = rng.randint(0, 9, size=300).astype(np.int32)
        w = rng.uniform(0.1, 2.0, size=300).astype(np.float32)
        x = rng.normal(size=(300, 4)).astype(np.float32)
        got = self._agree(rng, monkeypatch, x * w[:, None], ids, 9)
        want = np.zeros((9, 4), np.float32)
        np.add.at(want, ids, x * w[:, None])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_large_segment_count_forces_segsum(self, monkeypatch):
        from dask_ml_tpu.ops import scatter_strategy

        assert scatter_strategy(4096) == "segsum"  # one-hot would be
        # memory-quadratic at sketch bin counts, on every platform
        # ...and the guard binds even when onehot is FORCED via env:
        # A/B-ing the k-means reduce must not OOM the quantile sketch
        monkeypatch.setenv("DASK_ML_TPU_SCATTER", "onehot")
        assert scatter_strategy(4096) == "segsum"
        assert scatter_strategy(64) == "onehot"

    def test_bad_env_rejected(self, monkeypatch):
        from dask_ml_tpu.ops import scatter_strategy

        monkeypatch.setenv("DASK_ML_TPU_SCATTER", "matmulish")
        with pytest.raises(ValueError, match="DASK_ML_TPU_SCATTER"):
            scatter_strategy(8)

    def test_kmeans_equal_under_both_strategies(self, rng, monkeypatch,
                                                mesh):
        import jax as _jax

        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows

        X = np.concatenate([
            c + rng.normal(scale=0.4, size=(100, 3)).astype(np.float32)
            for c in ([0, 0, 0], [6, 6, 6], [-6, 6, -6])
        ]).astype(np.float32)
        w = rng.uniform(0.5, 1.5, size=300).astype(np.float32)
        sX = shard_rows(X)
        results = {}
        for strat in ("segsum", "onehot"):
            monkeypatch.setenv("DASK_ML_TPU_SCATTER", strat)
            _jax.clear_caches()
            km = KMeans(n_clusters=3, init="random", random_state=0,
                        max_iter=20).fit(sX, sample_weight=w)
            results[strat] = np.asarray(km.cluster_centers_)
        monkeypatch.delenv("DASK_ML_TPU_SCATTER")
        _jax.clear_caches()
        np.testing.assert_allclose(
            np.sort(results["segsum"], axis=0),
            np.sort(results["onehot"], axis=0), rtol=1e-4, atol=1e-4)

"""Pallas kernel parity tests (interpret mode on the CPU mesh)."""

import numpy as np
import pytest
import jax.numpy as jnp

import jax

from dask_ml_tpu.ops import lloyd_assign_reduce


def _reference(x, mask, centers):
    d2 = (
        np.sum(x * x, axis=1)[:, None]
        + np.sum(centers * centers, axis=1)[None, :]
        - 2 * x @ centers.T
    )
    labels = np.argmin(d2, axis=1)
    min_d2 = np.maximum(d2[np.arange(len(x)), labels], 0.0)
    k = centers.shape[0]
    onehot = (labels[:, None] == np.arange(k)[None, :]).astype(np.float32) * mask[:, None]
    return onehot.T @ x, onehot.sum(axis=0), float((min_d2 * mask).sum())


class TestLloydKernel:
    def test_matches_xla_reference(self, rng):
        n, d, k = 300, 7, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        mask = np.ones(n, dtype=np.float32)
        mask[-13:] = 0.0  # padding rows must contribute nothing
        centers = x[:k].copy()
        sums, counts, inertia = lloyd_assign_reduce(
            jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers), interpret=True
        )
        esums, ecounts, einertia = _reference(x, mask, centers)
        np.testing.assert_allclose(np.asarray(sums), esums, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(counts), ecounts)
        np.testing.assert_allclose(float(inertia), einertia, rtol=1e-4)

    def test_multi_tile_accumulation(self, rng):
        # more rows than one tile: grid accumulation across steps
        import dask_ml_tpu.ops.lloyd as L

        orig = L._TILE
        L._TILE = 128
        try:
            n, d, k = 1000, 4, 3
            x = rng.normal(size=(n, d)).astype(np.float32)
            mask = np.ones(n, dtype=np.float32)
            centers = x[:k].copy()
            sums, counts, inertia = lloyd_assign_reduce(
                jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers),
                interpret=True,
            )
            esums, ecounts, einertia = _reference(x, mask, centers)
            np.testing.assert_allclose(np.asarray(sums), esums, rtol=1e-4, atol=1e-3)
            np.testing.assert_allclose(np.asarray(counts), ecounts)
            np.testing.assert_allclose(float(inertia), einertia, rtol=1e-4)
        finally:
            L._TILE = orig

    def test_fast_mode_matches_reference(self, rng):
        # "fast" (bf16-split gemms) must stay within k-means-irrelevant
        # error of the float64 reference: label-flip-free data here, so
        # sums/inertia agree to ~1e-4 relative
        n, d, k = 600, 9, 48
        x = rng.normal(size=(n, d)).astype(np.float32)
        mask = np.ones(n, dtype=np.float32)
        mask[-17:] = 0.0
        centers = (x[:k] + 3.0 * rng.normal(size=(k, d))).astype(np.float32)
        sums, counts, inertia = lloyd_assign_reduce(
            jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers),
            interpret=True, mode="fast",
        )
        esums, ecounts, einertia = _reference(x, mask, centers)
        np.testing.assert_allclose(np.asarray(sums), esums,
                                   rtol=2e-4, atol=2e-3)
        np.testing.assert_allclose(np.asarray(counts), ecounts)
        np.testing.assert_allclose(float(inertia), einertia, rtol=2e-4)

    def test_fast_mode_fractional_weights(self, rng):
        # the mask carries SAMPLE WEIGHTS (utils.reweight_rows), which
        # are not bf16-exact — a bare bf16 cast of the one-hot operand
        # would bias sums vs the fp32 counts denominator (r4 review
        # finding); the 3-pass split must keep weighted sums accurate
        n, d, k = 500, 6, 24
        x = rng.normal(size=(n, d)).astype(np.float32)
        mask = rng.uniform(0.1, 3.0, size=n).astype(np.float32)
        mask[-11:] = 0.0
        centers = (x[:k] + 2.0 * rng.normal(size=(k, d))).astype(np.float32)
        sums, counts, inertia = lloyd_assign_reduce(
            jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers),
            interpret=True, mode="fast",
        )
        esums, ecounts, einertia = _reference(x, mask, centers)
        np.testing.assert_allclose(np.asarray(sums), esums,
                                   rtol=2e-4, atol=2e-3)
        np.testing.assert_allclose(np.asarray(counts), ecounts,
                                   rtol=1e-6)
        np.testing.assert_allclose(float(inertia), einertia, rtol=2e-4)

    def test_bad_mode_rejected(self, rng):
        x = rng.normal(size=(8, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="mode"):
            lloyd_assign_reduce(
                jnp.asarray(x), jnp.ones(8, dtype=np.float32),
                jnp.asarray(x[:2]), interpret=True, mode="banana",
            )

    def test_kmeans_fast_env_matches_highest(self, rng, monkeypatch, mesh):
        # end-to-end: DASK_ML_TPU_KMEANS_PRECISION=fast must converge to
        # the same clustering as highest on well-separated blobs
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows

        centers_true = np.array(
            [[0, 0, 0], [8, 8, 8], [-8, 8, -8]], dtype=np.float32)
        X = np.concatenate([
            c + rng.normal(scale=0.5, size=(120, 3)).astype(np.float32)
            for c in centers_true
        ])
        sX = shard_rows(X)
        km_hi = KMeans(n_clusters=3, init="random", random_state=0,
                       max_iter=30).fit(sX)
        monkeypatch.setenv("DASK_ML_TPU_KMEANS_PRECISION", "fast")
        km_fast = KMeans(n_clusters=3, init="random", random_state=0,
                         max_iter=30).fit(sX)
        np.testing.assert_allclose(
            np.sort(np.asarray(km_fast.cluster_centers_), axis=0),
            np.sort(np.asarray(km_hi.cluster_centers_), axis=0),
            rtol=1e-3, atol=1e-3)
        assert km_fast.inertia_ == pytest.approx(km_hi.inertia_, rel=1e-3)

    def test_pallas_parity_on_tpu(self, rng):
        # Hardware (Mosaic-lowered) parity check — the gate that lets
        # DASK_ML_TPU_PALLAS=1 be safely enabled (cluster.k_means._pallas_ok).
        if jax.default_backend() != "tpu":
            pytest.skip("requires a real TPU backend")
        n, d, k = 4096, 16, 8
        x = rng.normal(size=(n, d)).astype(np.float32)
        mask = np.ones(n, dtype=np.float32)
        mask[-100:] = 0.0
        centers = x[:k].copy()
        sums, counts, inertia = lloyd_assign_reduce(
            jnp.asarray(x), jnp.asarray(mask), jnp.asarray(centers)
        )
        esums, ecounts, einertia = _reference(x, mask, centers)
        np.testing.assert_allclose(np.asarray(sums), esums, rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(np.asarray(counts), ecounts)
        np.testing.assert_allclose(float(inertia), einertia, rtol=1e-3)


class TestScatterPolicy:
    """ops.scatter: one policy for segment_sum vs one-hot gemm, shared by
    the quantile sketch and the k-means reduce (r3 verdict #5b)."""

    def _agree(self, rng, monkeypatch, values, ids, k):
        import jax as _jax

        from dask_ml_tpu.ops import bucket_sum

        outs = {}
        for strat in ("segsum", "onehot"):
            monkeypatch.setenv("DASK_ML_TPU_SCATTER", strat)
            _jax.clear_caches()  # strategy is read at trace time
            outs[strat] = np.asarray(bucket_sum(
                jnp.asarray(values), jnp.asarray(ids), k))
        monkeypatch.delenv("DASK_ML_TPU_SCATTER")
        _jax.clear_caches()
        np.testing.assert_allclose(outs["segsum"], outs["onehot"],
                                   rtol=1e-5, atol=1e-5)
        return outs["segsum"]

    def test_strategies_agree_1d(self, rng, monkeypatch):
        ids = rng.randint(0, 17, size=400).astype(np.int32)
        vals = rng.normal(size=400).astype(np.float32)
        got = self._agree(rng, monkeypatch, vals, ids, 17)
        want = np.zeros(17, np.float32)
        np.add.at(want, ids, vals)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_strategies_agree_2d_weighted(self, rng, monkeypatch):
        ids = rng.randint(0, 9, size=300).astype(np.int32)
        w = rng.uniform(0.1, 2.0, size=300).astype(np.float32)
        x = rng.normal(size=(300, 4)).astype(np.float32)
        got = self._agree(rng, monkeypatch, x * w[:, None], ids, 9)
        want = np.zeros((9, 4), np.float32)
        np.add.at(want, ids, x * w[:, None])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_large_segment_count_forces_segsum(self, monkeypatch):
        from dask_ml_tpu.ops import scatter_strategy

        assert scatter_strategy(4096) == "segsum"  # one-hot would be
        # memory-quadratic at sketch bin counts, on every platform
        # ...and the guard binds even when onehot is FORCED via env:
        # A/B-ing the k-means reduce must not OOM the quantile sketch
        monkeypatch.setenv("DASK_ML_TPU_SCATTER", "onehot")
        assert scatter_strategy(4096) == "segsum"
        assert scatter_strategy(64) == "onehot"

    def test_bad_env_rejected(self, monkeypatch):
        from dask_ml_tpu.ops import scatter_strategy

        monkeypatch.setenv("DASK_ML_TPU_SCATTER", "matmulish")
        with pytest.raises(ValueError, match="DASK_ML_TPU_SCATTER"):
            scatter_strategy(8)

    def test_kmeans_equal_under_both_strategies(self, rng, monkeypatch,
                                                mesh):
        import jax as _jax

        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows

        X = np.concatenate([
            c + rng.normal(scale=0.4, size=(100, 3)).astype(np.float32)
            for c in ([0, 0, 0], [6, 6, 6], [-6, 6, -6])
        ]).astype(np.float32)
        w = rng.uniform(0.5, 1.5, size=300).astype(np.float32)
        sX = shard_rows(X)
        results = {}
        for strat in ("segsum", "onehot"):
            monkeypatch.setenv("DASK_ML_TPU_SCATTER", strat)
            _jax.clear_caches()
            km = KMeans(n_clusters=3, init="random", random_state=0,
                        max_iter=20).fit(sX, sample_weight=w)
            results[strat] = np.asarray(km.cluster_centers_)
        monkeypatch.delenv("DASK_ML_TPU_SCATTER")
        _jax.clear_caches()
        np.testing.assert_allclose(
            np.sort(results["segsum"], axis=0),
            np.sort(results["onehot"], axis=0), rtol=1e-4, atol=1e-4)

"""Device-op policy tests: k-means precision modes and the scatter
strategy (segment_sum vs one-hot gemm).

The Pallas Lloyd kernel these tests originally covered was deleted after
its win-or-delete chip adjudication (XLA won every variant — see
docs/design.md "Pallas negative result" and cluster/k_means.py).
"""

import numpy as np
import pytest
import jax.numpy as jnp


class TestKMeansPrecision:
    def test_kmeans_fast_env_matches_highest(self, rng, monkeypatch, mesh):
        # end-to-end: DASK_ML_TPU_KMEANS_PRECISION=fast must converge to
        # the same clustering as highest on well-separated blobs
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows

        centers_true = np.array(
            [[0, 0, 0], [8, 8, 8], [-8, 8, -8]], dtype=np.float32)
        X = np.concatenate([
            c + rng.normal(scale=0.5, size=(120, 3)).astype(np.float32)
            for c in centers_true
        ])
        sX = shard_rows(X)
        km_hi = KMeans(n_clusters=3, init="random", random_state=0,
                       max_iter=30).fit(sX)
        monkeypatch.setenv("DASK_ML_TPU_KMEANS_PRECISION", "fast")
        km_fast = KMeans(n_clusters=3, init="random", random_state=0,
                         max_iter=30).fit(sX)
        np.testing.assert_allclose(
            np.sort(np.asarray(km_fast.cluster_centers_), axis=0),
            np.sort(np.asarray(km_hi.cluster_centers_), axis=0),
            rtol=1e-3, atol=1e-3)
        assert km_fast.inertia_ == pytest.approx(km_hi.inertia_, rel=1e-3)


class TestScatterPolicy:
    """ops.scatter: one policy for segment_sum vs one-hot gemm, shared by
    the quantile sketch and the k-means reduce (r3 verdict #5b)."""

    def _agree(self, rng, monkeypatch, values, ids, k):
        import jax as _jax

        from dask_ml_tpu.ops import bucket_sum

        outs = {}
        for strat in ("segsum", "onehot"):
            monkeypatch.setenv("DASK_ML_TPU_SCATTER", strat)
            _jax.clear_caches()  # strategy is read at trace time
            outs[strat] = np.asarray(bucket_sum(
                jnp.asarray(values), jnp.asarray(ids), k))
        monkeypatch.delenv("DASK_ML_TPU_SCATTER")
        _jax.clear_caches()
        np.testing.assert_allclose(outs["segsum"], outs["onehot"],
                                   rtol=1e-5, atol=1e-5)
        return outs["segsum"]

    def test_strategies_agree_1d(self, rng, monkeypatch):
        ids = rng.randint(0, 17, size=400).astype(np.int32)
        vals = rng.normal(size=400).astype(np.float32)
        got = self._agree(rng, monkeypatch, vals, ids, 17)
        want = np.zeros(17, np.float32)
        np.add.at(want, ids, vals)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_strategies_agree_2d_weighted(self, rng, monkeypatch):
        ids = rng.randint(0, 9, size=300).astype(np.int32)
        w = rng.uniform(0.1, 2.0, size=300).astype(np.float32)
        x = rng.normal(size=(300, 4)).astype(np.float32)
        got = self._agree(rng, monkeypatch, x * w[:, None], ids, 9)
        want = np.zeros((9, 4), np.float32)
        np.add.at(want, ids, x * w[:, None])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_large_segment_count_forces_segsum(self, monkeypatch):
        from dask_ml_tpu.ops import scatter_strategy

        assert scatter_strategy(4096) == "segsum"  # one-hot would be
        # memory-quadratic at sketch bin counts, on every platform
        # ...and the guard binds even when onehot is FORCED via env:
        # A/B-ing the k-means reduce must not OOM the quantile sketch
        monkeypatch.setenv("DASK_ML_TPU_SCATTER", "onehot")
        assert scatter_strategy(4096) == "segsum"
        assert scatter_strategy(64) == "onehot"

    def test_bad_env_rejected(self, monkeypatch):
        from dask_ml_tpu.ops import scatter_strategy

        monkeypatch.setenv("DASK_ML_TPU_SCATTER", "matmulish")
        with pytest.raises(ValueError, match="DASK_ML_TPU_SCATTER"):
            scatter_strategy(8)

    def test_sharding_mismatch_raises(self, rng):
        # the error path: a row-sharded/PADDED values zipped with an
        # unpadded ids (the shard_rows pad divergence) must fail loudly at
        # trace time, not misalign rows to buckets
        from dask_ml_tpu.ops import bucket_sum

        vals = jnp.asarray(rng.normal(size=(48, 3)).astype(np.float32))
        ids = jnp.asarray(rng.randint(0, 4, size=40).astype(np.int32))
        with pytest.raises(ValueError, match="padded/sharded"):
            bucket_sum(vals, ids, 4)

    def test_sharded_padded_inputs_align(self, rng, mesh):
        # positive twin of the mismatch case: when values AND ids ride the
        # same padded row sharding, the scatter sums match the host oracle
        # (pad rows neutralized by zero pre-weighting, as consumers do)
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.ops import bucket_sum

        n, k = 37, 5  # deliberately not divisible by the 8-device mesh
        x = rng.normal(size=(n, 3)).astype(np.float32)
        ids = rng.randint(0, k, size=n).astype(np.int32)
        sx = shard_rows(x)
        sids = shard_rows(ids)
        w = np.asarray(shard_rows(np.ones(n, np.float32)).mask)[
            : sx.data.shape[0]]
        got = np.asarray(bucket_sum(
            sx.data * jnp.asarray(w)[:, None], sids.data, k))
        want = np.zeros((k, 3), np.float32)
        np.add.at(want, ids, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_bad_rank_rejected(self, rng):
        from dask_ml_tpu.ops import bucket_sum

        ids = jnp.asarray(rng.randint(0, 4, size=8).astype(np.int32))
        with pytest.raises(ValueError, match="1-d or 2-d"):
            bucket_sum(jnp.zeros((8, 2, 2)), ids, 4)
        with pytest.raises(ValueError, match="ids must be 1-d"):
            bucket_sum(jnp.zeros((8,)), jnp.zeros((8, 2), jnp.int32), 4)

    def test_bad_explicit_strategy_rejected(self, rng):
        from dask_ml_tpu.ops import bucket_sum

        ids = jnp.asarray(rng.randint(0, 4, size=8).astype(np.int32))
        with pytest.raises(ValueError, match="strategy"):
            bucket_sum(jnp.zeros((8,)), ids, 4, strategy="matmulish")
        # ...and the typo must surface even when the large-segment OOM
        # guard would have overridden the strategy anyway
        big_ids = jnp.asarray(rng.randint(0, 2000, size=8).astype(np.int32))
        with pytest.raises(ValueError, match="strategy"):
            bucket_sum(jnp.zeros((8,)), big_ids, 2000, strategy="matmulish")

    def test_explicit_strategy_pass_through(self, rng):
        # callers inside jit resolve the strategy OUTSIDE the trace and
        # pass it through; both explicit forms must agree with the oracle
        from dask_ml_tpu.ops import bucket_sum

        vals = rng.normal(size=16).astype(np.float32)
        ids = rng.randint(0, 4, size=16).astype(np.int32)
        want = np.zeros(4, np.float32)
        np.add.at(want, ids, vals)
        for strat in ("segsum", "onehot"):
            got = np.asarray(bucket_sum(
                jnp.asarray(vals), jnp.asarray(ids), 4, strategy=strat))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_explicit_onehot_overridden_above_segment_cap(self, rng):
        # the OOM guard binds even for an explicit strategy argument:
        # 4096 one-hot columns is memory-quadratic everywhere
        from dask_ml_tpu.ops import bucket_sum

        vals = rng.normal(size=32).astype(np.float32)
        ids = rng.randint(0, 4096, size=32).astype(np.int32)
        want = np.zeros(4096, np.float32)
        np.add.at(want, ids, vals)
        got = np.asarray(bucket_sum(
            jnp.asarray(vals), jnp.asarray(ids), 4096, strategy="onehot"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_kmeans_equal_under_both_strategies(self, rng, monkeypatch,
                                                mesh):
        import jax as _jax

        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows

        X = np.concatenate([
            c + rng.normal(scale=0.4, size=(100, 3)).astype(np.float32)
            for c in ([0, 0, 0], [6, 6, 6], [-6, 6, -6])
        ]).astype(np.float32)
        w = rng.uniform(0.5, 1.5, size=300).astype(np.float32)
        sX = shard_rows(X)
        results = {}
        for strat in ("segsum", "onehot"):
            monkeypatch.setenv("DASK_ML_TPU_SCATTER", strat)
            _jax.clear_caches()
            km = KMeans(n_clusters=3, init="random", random_state=0,
                        max_iter=20).fit(sX, sample_weight=w)
            results[strat] = np.asarray(km.cluster_centers_)
        monkeypatch.delenv("DASK_ML_TPU_SCATTER")
        _jax.clear_caches()
        np.testing.assert_allclose(
            np.sort(results["segsum"], axis=0),
            np.sort(results["onehot"], axis=0), rtol=1e-4, atol=1e-4)

"""Sharded dataset layer (dask_ml_tpu/data, design.md §18): columnar
format roundtrip + validation, key-derived shuffle determinism (and the
host Threefry twin's bit-equality with jax.random.fold_in), merge-queue
order independence from reader count, exact-once delivery under reader
crashes, FitCheckpoint-style mid-epoch resume, the pad-no-op contract
of format-aligned streams, and the estimator entrypoints (dataset
accepted wherever block iterators are)."""

import os

import numpy as np
import pytest

import jax

from dask_ml_tpu import _partial, data, io
from dask_ml_tpu.data import format as dformat
from dask_ml_tpu.data import shuffle as dshuffle
from dask_ml_tpu.obs.metrics import registry as _registry
from dask_ml_tpu.pipeline import stream_partial_fit
from dask_ml_tpu.resilience.elastic import BudgetExhausted, FaultBudget
from dask_ml_tpu.resilience.testing import (FaultPlan, ThreadCrash,
                                            fault_plan)

_SEED = 5


def _xy(n=2048, d=8, seed=_SEED):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.normal(size=n) > 0).astype(np.int32)
    return X, y


def _build(tmp_path, n=2048, d=8, shards=4, block_rows=256, **kw):
    X, y = _xy(n, d)
    m = data.write_dataset(str(tmp_path / "ds"), X, y, shards=shards,
                           block_rows=block_rows, **kw)
    return X, y, m, str(tmp_path / "ds")


def _drain(ds, epoch=None, start=None):
    out = []
    with ds.iter_blocks(epoch=epoch, start=start) as st:
        for xb, yb in st:
            out.append((xb.copy(), None if yb is None else yb.copy()))
    return out


class TestShuffle:
    def test_fold_in_matches_jax_bit_exact(self):
        """The host Threefry twin IS jax.random.fold_in — bit-identical
        keys, so the SURVEY §3.2 recipe holds without any device work
        on the reader threads."""
        for seed in (0, 1, 42, 123456789):
            k = jax.random.PRNGKey(seed)
            assert np.array_equal(np.asarray(k),
                                  dshuffle.key_from_seed(seed))
            for d in (0, 1, 7, 1000, 2**31 - 1):
                want = np.asarray(jax.random.fold_in(k, d))
                got = dshuffle.fold_in(np.asarray(k), d)
                assert np.array_equal(want, got), (seed, d)

    def test_as_key_accepts_jax_key(self):
        k = jax.random.PRNGKey(3)
        assert np.array_equal(dshuffle.as_key(k), np.asarray(k))
        assert np.array_equal(dshuffle.as_key(3), np.asarray(k))
        with pytest.raises(ValueError):
            dshuffle.as_key(np.zeros(3, np.uint32))

    def test_permutation_deterministic_and_complete(self):
        k = dshuffle.key_from_seed(9)
        p1 = dshuffle.permutation(k, 1000)
        p2 = dshuffle.permutation(k, 1000)
        assert np.array_equal(p1, p2)
        assert np.array_equal(np.sort(p1), np.arange(1000))
        assert not np.array_equal(
            p1, dshuffle.permutation(dshuffle.fold_in(k, 1), 1000))

    def test_epoch_plan_identity_and_shuffle(self):
        plan = dshuffle.epoch_plan(0, 0, [3, 2, 4], shuffle=False)
        assert list(plan.order()) == [(0, 0), (0, 1), (0, 2), (1, 0),
                                      (1, 1), (2, 0), (2, 1), (2, 2),
                                      (2, 3)]
        sh = dshuffle.epoch_plan(0, 0, [3, 2, 4], shuffle=True)
        assert sorted(sh.order()) == sorted(plan.order())
        assert sh.n_blocks == 9
        # locate() inverts the flat order
        flat = list(sh.order())
        for seq in (0, 4, 8):
            p, off = sh.locate(seq)
            s = sh.shard_order[p]
            assert flat[seq] == (s, int(sh.block_orders[s][off]))


class TestFormat:
    def test_roundtrip_compressed_and_raw(self, tmp_path):
        X, y = _xy(700, 5)
        for comp in ("zlib", "none"):
            p = str(tmp_path / f"shard-{comp}.dmltc")
            cols = [dformat.ColumnSpec("X", "float32", (5,)),
                    dformat.ColumnSpec("y", "int32")]
            with dformat.ColumnarWriter(p, cols, block_rows=256,
                                        compression=comp) as w:
                w.append(X[:100], y[:100])   # slabs smaller than a block
                w.append(X[100:], y[100:])   # …and larger
            with dformat.ColumnarReader(p) as r:
                assert r.rows == 700
                assert r.n_blocks == 3       # 256 + 256 + 188 tail
                xs, ys = [], []
                for i in range(r.n_blocks):
                    xb, yb = r.read_block(i)
                    xs.append(xb)
                    ys.append(yb)
                assert np.array_equal(np.concatenate(xs), X)
                assert np.array_equal(np.concatenate(ys), y)
                assert ys[0].dtype == np.int32

    def test_writer_rejects_off_ladder_block_rows(self, tmp_path):
        cols = [dformat.ColumnSpec("X", "float32", (4,))]
        with pytest.raises(ValueError, match="rung"):
            dformat.ColumnarWriter(str(tmp_path / "x.dmltc"), cols,
                                   block_rows=100)
        # policy='off' opts out deliberately
        w = dformat.ColumnarWriter(str(tmp_path / "x.dmltc"), cols,
                                   block_rows=100, policy="off")
        w.append(np.zeros((100, 4), np.float32))
        w.close()

    def test_truncated_file_fails_at_open(self, tmp_path):
        X, y = _xy(600, 4)
        p = str(tmp_path / "shard.dmltc")
        cols = [dformat.ColumnSpec("X", "float32", (4,)),
                dformat.ColumnSpec("y", "int32")]
        with dformat.ColumnarWriter(p, cols, block_rows=256) as w:
            w.append(X, y)
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(size - 7)
        with pytest.raises(ValueError, match="truncated|tail"):
            dformat.ColumnarReader(p)
        with open(str(tmp_path / "junk.dmltc"), "wb") as f:
            f.write(b"not a shard at all" * 4)
        with pytest.raises(ValueError):
            dformat.ColumnarReader(str(tmp_path / "junk.dmltc"))

    def test_manifest_validate_and_for_host(self, tmp_path):
        _X, _y, m, d = _build(tmp_path, shards=5)
        loaded = data.DatasetManifest.load(d)
        loaded.validate()
        assert loaded.rows == m.rows and loaded.n_blocks == m.n_blocks
        parts = [loaded.for_host(i, 2) for i in range(2)]
        assert sum(p.n_shards for p in parts) == loaded.n_shards
        assert sum(p.rows for p in parts) == loaded.rows
        with pytest.raises(ValueError):
            loaded.for_host(2, 2)


class TestShardedDataset:
    def test_order_independent_of_reader_count(self, tmp_path):
        """Same key ⇒ the SAME global permutation at every reader
        count (the merge queue re-serializes), and across runs."""
        X, y, m, d = _build(tmp_path)
        ref = _drain(data.ShardedDataset(d, key=7, readers=1), epoch=0)
        for readers in (2, 4):
            got = _drain(data.ShardedDataset(d, key=7, readers=readers),
                         epoch=0)
            assert len(got) == len(ref) == m.n_blocks
            for (xa, ya), (xb, yb) in zip(ref, got):
                assert np.array_equal(xa, xb)
                assert np.array_equal(ya, yb)
        again = _drain(data.ShardedDataset(d, key=7, readers=4), epoch=0)
        assert all(np.array_equal(a[0], b[0])
                   for a, b in zip(ref, again))

    def test_epochs_differ_and_cover_all_rows(self, tmp_path):
        X, y, m, d = _build(tmp_path)
        e0 = _drain(data.ShardedDataset(d, key=7, readers=2), epoch=0)
        e1 = _drain(data.ShardedDataset(d, key=7, readers=2), epoch=1)
        assert not all(np.array_equal(a[0], b[0])
                       for a, b in zip(e0, e1))
        for ep in (e0, e1):  # every epoch is a full permutation
            assert sum(b[0].shape[0] for b in ep) == X.shape[0]
            assert np.isclose(
                sum(float(b[0].sum()) for b in ep), float(X.sum()),
                rtol=1e-4)

    def test_multi_epoch_stream_and_start_resume(self, tmp_path):
        X, y, m, d = _build(tmp_path)
        full = _drain(data.ShardedDataset(d, key=3, epochs=2, readers=2))
        assert len(full) == 2 * m.n_blocks
        # start=k replays exactly the unseen suffix — across the
        # epoch boundary too
        for k in (3, m.n_blocks, m.n_blocks + 2):
            suf = _drain(data.ShardedDataset(d, key=3, epochs=2,
                                             readers=2), start=k)
            assert len(suf) == len(full) - k
            for (xa, _), (xb, _) in zip(full[k:], suf):
                assert np.array_equal(xa, xb)

    def test_identity_scan_matches_file_order(self, tmp_path):
        X, y, m, d = _build(tmp_path, shards=2)
        got = _drain(data.ShardedDataset(d, readers=1, shuffle=False),
                     epoch=0)
        want = []
        for i in range(m.n_shards):
            with m.open_shard(i) as r:
                for b in range(r.n_blocks):
                    want.append(r.read_block(b))
        for (xa, ya), (xw, yw) in zip(got, want):
            assert np.array_equal(xa, xw)
            assert np.array_equal(ya, yw)

    def test_reader_crash_budgeted_restart_exact_once(self, tmp_path):
        X, y, m, d = _build(tmp_path)
        ref = _drain(data.ShardedDataset(d, key=2, readers=2), epoch=0)
        plan = FaultPlan().inject("data-reader", at_call=3, times=1,
                                  exc=ThreadCrash("test"))
        budget = FaultBudget(4, 60.0, name="t-data")
        ds = data.ShardedDataset(d, key=2, readers=2, budget=budget,
                                 label="t-data")
        with fault_plan(plan):
            got = _drain(ds, epoch=0)
        assert sum(plan.fired.values()) == 1
        assert budget.spent == 1  # ONE budgeted restart
        assert len(got) == len(ref)  # exact-once: no skip, no dup
        for (xa, _), (xb, _) in zip(ref, got):
            assert np.array_equal(xa, xb)

    def test_reported_reader_fault_restarts_too(self, tmp_path):
        X, y, m, d = _build(tmp_path)
        ref = _drain(data.ShardedDataset(d, key=2, readers=2), epoch=0)
        plan = FaultPlan().inject("data-reader", at_call=2, times=1,
                                  exc=OSError(5, "injected io error"))
        with fault_plan(plan):
            got = _drain(data.ShardedDataset(
                d, key=2, readers=2,
                budget=FaultBudget(4, 60.0, name="t-data2")), epoch=0)
        assert len(got) == len(ref)
        assert all(np.array_equal(a[0], b[0]) for a, b in zip(ref, got))

    def test_two_crashes_same_shard_still_exact_once(self, tmp_path):
        """A replacement reader that ALSO dies on the same shard: the
        second restart must replay the recorded claim (an unrecorded
        resume would skip the shard forever and hang the consumer) —
        the double-death regression."""
        X, y, m, d = _build(tmp_path, shards=2)
        ref = _drain(data.ShardedDataset(d, key=2, readers=1), epoch=0)
        plan = FaultPlan().inject("data-reader", at_call=(2, 3), times=2,
                                  exc=ThreadCrash("test"))
        budget = FaultBudget(6, 60.0, name="t-double")
        with fault_plan(plan):
            got = _drain(data.ShardedDataset(d, key=2, readers=1,
                                             budget=budget,
                                             label="t-double"), epoch=0)
        assert sum(plan.fired.values()) == 2
        assert budget.spent == 2
        assert len(got) == len(ref)
        assert all(np.array_equal(a[0], b[0]) for a, b in zip(ref, got))

    def test_persistent_crash_exhausts_budget_loudly(self, tmp_path):
        _X, _y, _m, d = _build(tmp_path)
        plan = FaultPlan().persistent("data-reader",
                                      exc=ThreadCrash("always"))
        ds = data.ShardedDataset(d, key=2, readers=2,
                                 budget=FaultBudget(2, 60.0, name="t3"),
                                 label="t3")
        with fault_plan(plan):
            with pytest.raises(BudgetExhausted):
                _drain(ds, epoch=0)

    def test_knob_resolvers_strict(self, monkeypatch):
        monkeypatch.setenv(data.READERS_ENV, "6")
        assert data.resolve_readers() == 6
        monkeypatch.setenv(data.READERS_ENV, "zero")
        with pytest.raises(ValueError):
            data.resolve_readers()
        monkeypatch.setenv(data.QUEUE_ENV, "0")
        with pytest.raises(ValueError):
            data.resolve_queue_blocks()
        monkeypatch.delenv(data.READERS_ENV)
        monkeypatch.delenv(data.QUEUE_ENV)
        assert data.resolve_queue_blocks(readers=3) == 6


class TestEstimatorEntrypoints:
    def test_stream_partial_fit_pad_noop_and_equality(self, tmp_path):
        """A format-aligned dataset stream dispatches with ZERO padded
        blocks (the bucket no-op fast path), and the model equals one
        trained on the same blocks in memory."""
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y, m, d = _build(tmp_path, block_rows=256)
        blocks = _drain(data.ShardedDataset(d, key=0, readers=1),
                        epoch=0)
        m_mem = SGDClassifier(random_state=0)
        stream_partial_fit(m_mem, blocks, depth=2,
                           fit_kwargs={"classes": np.array([0, 1])})
        reg = _registry()
        pad0 = reg.family("bucket.padded_blocks").get("", 0)
        blk0 = reg.family("bucket.blocks").get("", 0)
        m_ds = SGDClassifier(random_state=0)
        stream_partial_fit(m_ds,
                           data.ShardedDataset(d, key=0, readers=4),
                           depth=2,
                           fit_kwargs={"classes": np.array([0, 1])})
        assert reg.family("bucket.blocks").get("", 0) - blk0 == \
            m.n_blocks
        assert reg.family("bucket.padded_blocks").get("", 0) == pad0
        np.testing.assert_allclose(np.asarray(m_ds.coef_),
                                   np.asarray(m_mem.coef_), rtol=1e-5)

    def test_partial_fit_and_incremental_accept_dataset(self, tmp_path):
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.wrappers import Incremental

        X, y, m, d = _build(tmp_path)
        est = SGDClassifier(random_state=0)
        _partial.fit(est, data.ShardedDataset(d, key=0, readers=2),
                     classes=np.array([0, 1]))
        assert np.asarray(est.coef_).shape[-1] == X.shape[1]
        with pytest.raises(ValueError, match="ride the dataset"):
            _partial.fit(SGDClassifier(),
                         data.ShardedDataset(d, key=0), y)
        inc = Incremental(SGDClassifier(random_state=0))
        inc.fit(data.ShardedDataset(d, key=0, readers=2),
                classes=np.array([0, 1]))
        np.testing.assert_allclose(np.asarray(inc.estimator_.coef_),
                                   np.asarray(est.coef_), rtol=1e-5)

    def test_predict_and_predict_blocks_accept_dataset(self, tmp_path):
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.wrappers import ParallelPostFit

        X, y, m, d = _build(tmp_path)
        est = SGDClassifier(random_state=0)
        est.partial_fit(X, y, classes=np.array([0, 1]))
        direct = np.asarray(est.predict(X))
        ds = data.ShardedDataset(d, key=0, readers=2, shuffle=False)
        p = _partial.predict(est, ds)
        assert p.shape == direct.shape
        ppf = ParallelPostFit(estimator=est)
        ppf.fit(X[:64], y[:64], classes=np.array([0, 1]))
        chunks = list(ppf.predict_blocks(
            data.ShardedDataset(d, key=0, readers=2, shuffle=False)))
        assert sum(c.shape[0] for c in chunks) == X.shape[0]

    def test_fit_checkpoint_style_resume_replays_suffix(self, tmp_path):
        """A fit that consumed k blocks resumes with start=k and lands
        on the full-epoch model exactly (the FitCheckpoint mid-epoch
        resume contract: the unseen suffix replays, nothing else)."""
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y, m, d = _build(tmp_path)
        full = SGDClassifier(random_state=0)
        stream_partial_fit(full,
                           data.ShardedDataset(d, key=1, readers=2),
                           depth=2,
                           fit_kwargs={"classes": np.array([0, 1])})

        class _Stop(Exception):
            pass

        part = SGDClassifier(random_state=0)
        k = 3
        seen = [0]

        def _on_block(i, model):
            seen[0] = i
            if i == k:
                raise _Stop

        with pytest.raises(_Stop):
            stream_partial_fit(
                part, data.ShardedDataset(d, key=1, readers=2), depth=2,
                fit_kwargs={"classes": np.array([0, 1])},
                on_block=_on_block)
        assert seen[0] == k
        stream_partial_fit(
            part,
            data.ShardedDataset(d, key=1, readers=2).iter_blocks(
                start=k),
            depth=2, fit_kwargs={"classes": np.array([0, 1])})
        np.testing.assert_allclose(np.asarray(part.coef_),
                                   np.asarray(full.coef_), rtol=1e-5)


class TestConverters:
    def test_csv_converter_roundtrip(self, tmp_path):
        X, y = _xy(600, 6)
        csvp = str(tmp_path / "in.csv")
        arr = np.hstack([X, y[:, None].astype(np.float32)])
        with open(csvp, "w") as f:
            for row in arr:
                f.write(",".join(f"{v:.7g}" for v in row) + "\n")
        m = io.to_columnar(csvp, str(tmp_path / "out"), label_col=-1,
                           shards=2, block_rows=256)
        assert m.rows == 600
        got_x, got_y = [], []
        for xb, yb in data.ShardedDataset(m, shuffle=False,
                                          readers=1).iter_blocks(epoch=0):
            got_x.append(xb)
            got_y.append(yb)
        assert sum(b.shape[0] for b in got_x) == 600
        assert int(np.concatenate(got_y).sum()) == int(y.sum())
        # float roundtrip through %.7g text: near-exact
        np.testing.assert_allclose(
            np.sort(np.concatenate(got_x).ravel()),
            np.sort(X.ravel()), rtol=1e-5, atol=1e-6)

    def test_binary_converter_roundtrip(self, tmp_path):
        X, _y = _xy(500, 4)
        binp = str(tmp_path / "in.bin")
        X.tofile(binp)
        m = io.to_columnar(binp, str(tmp_path / "out"), n_features=4,
                           shards=2, block_rows=256)
        assert m.rows == 500
        tot = np.concatenate([
            xb for xb, _ in data.ShardedDataset(
                m, shuffle=False, readers=1).iter_blocks(epoch=0)])
        assert np.isclose(float(tot.sum()), float(X.sum()), rtol=1e-5)
        with pytest.raises(ValueError, match="n_features"):
            io.to_columnar(binp, str(tmp_path / "out2"))

    def test_convert_blocks_preserves_wide_int_labels(self, tmp_path):
        """Integer labels above 2**24 must not round-trip through the
        float32 feature cast (the converter splits the label column off
        first)."""
        rng = np.random.RandomState(0)
        X = rng.normal(size=(300, 3)).astype(np.float64)
        ids = (np.arange(300, dtype=np.int64) + 2**24 + 1)
        slab = np.concatenate([X, ids[:, None].astype(np.float64)],
                              axis=1)
        # float64 carries the ids exactly; a float32 detour would not
        m = data.convert_blocks(
            str(tmp_path / "out"), [slab], n_features=4, label_col=-1,
            label_dtype="int64", shards=1, block_rows=256)
        got = np.concatenate([
            yb for _xb, yb in data.ShardedDataset(
                m, shuffle=False, readers=1).iter_blocks(epoch=0)])
        assert got.dtype == np.int64
        assert np.array_equal(np.sort(got), ids)


class TestIOHardening:
    def test_stream_binary_blocks_validates_size_up_front(self,
                                                          tmp_path):
        X, _y = _xy(100, 8)
        binp = str(tmp_path / "t.bin")
        X.tofile(binp)
        with pytest.raises(ValueError, match="truncated|needs"):
            # generator validates eagerly — no iteration required
            io.stream_binary_blocks(binp, 16, 8, n_rows=200)
        # derived n_rows still streams every complete row
        got = sum(b.shape[0]
                  for b in io.stream_binary_blocks(binp, 16, 8))
        assert got == 100

    def test_stream_text_lines_retry_exact(self, tmp_path):
        p = str(tmp_path / "t.txt")
        with open(p, "w") as f:
            f.write("\n".join(f"line{i}" for i in range(25)) + "\n")
        plan = FaultPlan().inject("ingest", at_call=2, times=1)
        with fault_plan(plan):
            out = [ln for blk in io.stream_text_lines(
                p, 10, retries=2, retry_backoff=0.0) for ln in blk]
        assert sum(plan.fired.values()) == 1
        assert out == [f"line{i}" for i in range(25)]

    def test_stream_text_lines_no_retry_propagates(self, tmp_path):
        from dask_ml_tpu.resilience.testing import FaultInjected

        p = str(tmp_path / "t.txt")
        with open(p, "w") as f:
            f.write("a\nb\n")
        plan = FaultPlan().inject("ingest", at_call=1, times=1)
        with fault_plan(plan):
            with pytest.raises(FaultInjected):
                list(io.stream_text_lines(p, 10))

"""graftpilot (dask_ml_tpu/control, design.md §21): the live knob
registry and the verdict-driven controller loop.

Registry half: strict parse / bounds clamp / unknown-name round-trips,
the resolution-order contract (explicit arg PINS, override beats env,
clear restores), and the graftlock posture — concurrent setters vs
lock-free readers produce ZERO violations and ZERO new lock-order edges
vs the committed ``tools/lock_baseline.json``.

Controller half: the policy table moves the right knob for each verdict
class, hysteresis holds (confidence gate, cooldown, step caps,
revert-on-regression), the ``saturation_pinned`` hard guard freezes
every move — including an injected one — and the seeded false-verdict
self-test (``python -m dask_ml_tpu.control --self-test``) exits 0 only
for a LIVE controller (disabled ⇒ nonzero: a blind controller must
never gate).
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from dask_ml_tpu.control import knobs as K
from dask_ml_tpu.control import pilot as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOCK_BASELINE = os.path.join(REPO, "tools", "lock_baseline.json")

_CONTROL_ENVS = (P.AUTOPILOT_ENV, P.CADENCE_ENV, P.INJECT_ENV)


@pytest.fixture(autouse=True)
def _knob_isolation(monkeypatch):
    """Every test starts and ends with a clean override table and no
    control env vars leaking in either direction (tier-1 tests must be
    order-independent)."""
    for env in _CONTROL_ENVS:
        monkeypatch.delenv(env, raising=False)
    for k in K.KNOBS.values():
        monkeypatch.delenv(k.env, raising=False)
    K.clear_overrides()
    yield
    P.stop_pilot()
    K.clear_overrides()


# ---------------------------------------------------------------------------
# the knob registry
# ---------------------------------------------------------------------------

class TestKnobRegistry:
    def test_six_documented_levers(self):
        assert sorted(K.KNOBS) == ["data_queue", "data_readers",
                                   "prefetch_depth", "search_inflight",
                                   "serve_max_batch", "serve_window_ms"]
        for k in K.KNOBS.values():
            assert k.env.startswith("DASK_ML_TPU_")
            assert k.lo <= k.hi

    def test_unknown_name_lists_the_registry(self):
        with pytest.raises(KeyError, match="data_queue, data_readers"):
            K.knob("warp_factor")
        with pytest.raises(KeyError):
            K.set_knob("warp_factor", 9)

    def test_strict_parse_round_trips(self):
        k = K.KNOBS["data_readers"]
        assert k.parse(3) == 3
        assert k.parse("3") == 3
        f = K.KNOBS["serve_window_ms"]
        assert f.parse(2) == 2.0 and isinstance(f.parse(2), float)
        assert f.parse("1.5") == 1.5

    @pytest.mark.parametrize("bad", [True, False, 2.5, "2.5", "many",
                                     None, [4]])
    def test_int_knob_rejects_junk(self, bad):
        with pytest.raises(ValueError, match="data_readers"):
            K.KNOBS["data_readers"].parse(bad)

    def test_set_knob_clamps_to_bounds_and_counts(self):
        k = K.KNOBS["data_readers"]
        before = k.changes
        assert K.set_knob("data_readers", 10 ** 6) == k.hi
        assert K.set_knob("data_readers", 0) == k.lo
        assert k.changes == before + 2

    def test_override_round_trip_and_clear(self):
        assert K.override("prefetch_depth") is None
        K.set_knob("prefetch_depth", 8)
        assert K.override("prefetch_depth") == 8
        assert K.override_or("prefetch_depth", 2) == 8
        K.clear_override("prefetch_depth")
        assert K.override_or("prefetch_depth", 2) == 2

    def test_effective_resolution_order(self, monkeypatch):
        k = K.KNOBS["search_inflight"]
        assert k.effective() == 8                    # static default
        monkeypatch.setenv(k.env, "16")
        assert k.effective() == 16                   # env beats default
        K.observe("search_inflight", 4)
        assert k.effective() == 4                    # observed beats env
        K.set_knob("search_inflight", 32)
        assert k.effective() == 32                   # override wins

    def test_env_strict_parse_raises(self, monkeypatch):
        monkeypatch.setenv(K.KNOBS["data_readers"].env, "lots")
        with pytest.raises(ValueError, match="DASK_ML_TPU_DATA_READERS"):
            K.KNOBS["data_readers"].env_value()
        # report() stays usable even over a junk env (effective=None)
        assert K.report()["data_readers"]["effective"] is None

    def test_dynamic_default_has_no_base(self):
        assert K.KNOBS["data_queue"].effective() is None

    def test_set_knob_books_gauge_and_counter(self):
        from dask_ml_tpu.obs.metrics import registry

        reg = registry()
        reg.reset("control.")
        K.set_knob("serve_window_ms", 4.0, source="test")
        fam = reg.family("control.knob_value")
        assert fam.get("serve_window_ms") == 4.0
        assert reg.family("control.knob_set").get("test") == 1

    def test_report_shape(self):
        rep = K.report()
        for name, row in rep.items():
            assert set(row) >= {"override", "observed", "effective",
                                "changes", "lo", "hi", "env", "unit"}


class TestKnobConcurrency:
    def test_concurrent_set_vs_read_zero_new_lock_edges(self):
        """Hammer set_knob/clear against override_or readers under the
        runtime lockset sanitizer: zero violations, and every observed
        lock-order edge already exists in the committed baseline — the
        control.knobs lock never nests (in either direction)."""
        from dask_ml_tpu.sanitize import locks as rl

        stop = threading.Event()
        seen = []

        def reader():
            while not stop.is_set():
                v = K.override_or("data_readers", 4)
                assert isinstance(v, int)
                seen.append(v)

        with rl.instrumented_locks(book_metrics=False) as mon:
            threads = [threading.Thread(target=reader) for _ in range(3)]
            for t in threads:
                t.start()
            for i in range(200):
                K.set_knob("data_readers", 1 + (i % 8))
                if i % 50 == 0:
                    K.clear_overrides()
            stop.set()
            for t in threads:
                t.join()
        rep = mon.report()
        assert rep["violations"] == [], rep["violations"]
        with open(LOCK_BASELINE) as f:
            base_edges = set(json.load(f)["edges"])
        new = set(rep["edges"]) - base_edges
        assert not new, f"new lock-order edges: {sorted(new)}"
        assert not any("control.knobs" in e for e in rep["edges"])
        assert all(v == 4 or 1 <= v <= 8 for v in seen)


# ---------------------------------------------------------------------------
# plane integration: the live re-read points honor the pin doctrine
# ---------------------------------------------------------------------------

class TestPlaneResolution:
    def test_pipeline_depth_override(self):
        from dask_ml_tpu.pipeline.core import resolve_depth

        assert resolve_depth(3) == 3
        K.set_knob("prefetch_depth", 7)
        assert resolve_depth() == 7        # override beats default
        assert resolve_depth(3) == 3       # explicit arg still pins
        K.clear_overrides()
        assert resolve_depth() == 2

    def test_data_resolvers_override(self):
        from dask_ml_tpu.data.readers import (resolve_queue_blocks,
                                              resolve_readers)

        K.set_knob("data_readers", 2)
        K.set_knob("data_queue", 5)
        assert resolve_readers() == 2
        assert resolve_queue_blocks(readers=2) == 5
        assert resolve_readers(6) == 6     # explicit pins

    def test_serve_resolvers_override(self):
        from dask_ml_tpu.serve.config import (resolve_max_batch,
                                              resolve_window_s)

        K.set_knob("serve_window_ms", 8.0)
        K.set_knob("serve_max_batch", 64)
        assert resolve_window_s() == pytest.approx(0.008)
        assert resolve_max_batch() == 64
        assert resolve_window_s(0.001) == pytest.approx(0.001)  # pins

    def test_search_inflight_live_vs_pinned(self):
        from dask_ml_tpu.model_selection._orchestrator import (
            SearchScheduler)

        live = SearchScheduler()
        assert live.effective_inflight() == 8
        K.set_knob("search_inflight", 2)
        assert live.effective_inflight() == 2
        pinned = SearchScheduler(inflight=16)
        assert pinned.effective_inflight() == 16  # explicit arg pins

    def test_dataset_reader_pin_flags(self, tmp_path):
        from dask_ml_tpu import data

        rng = np.random.RandomState(0)
        X = rng.normal(size=(1024, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        data.write_dataset(str(tmp_path / "ds"), X, y, shards=2,
                           block_rows=256)
        pinned = data.ShardedDataset(str(tmp_path / "ds"), readers=1)
        assert pinned._readers_pinned
        live = data.ShardedDataset(str(tmp_path / "ds"))
        assert not live._readers_pinned
        # and the stream's live window honors an override mid-run
        K.set_knob("data_readers", 2)
        with live.iter_blocks(epoch=0) as st:
            blocks = list(st)
        assert len(blocks) == 4
        # pinned stream delivers identically regardless of the override
        with pinned.iter_blocks(epoch=0) as st:
            ref = list(st)
        assert len(ref) == 4

    def test_live_prefetch_stream_survives_mid_run_retune(self):
        from dask_ml_tpu.pipeline import core as pc

        blocks = [np.ones((4, 2)) * i for i in range(8)]
        out = []
        gen = pc.prefetch_blocks(iter(blocks))  # env/default: live
        for i, b in enumerate(gen):
            out.append(b)
            if i == 1:
                K.set_knob("prefetch_depth", 6)  # deepen mid-stream
            if i == 4:
                K.set_knob("prefetch_depth", 1)  # shrink mid-stream
        assert len(out) == 8
        assert [b[0, 0] for b in out] == [float(i) for i in range(8)]

    def test_serve_refresh_honors_pins_and_ceiling(self):
        from dask_ml_tpu.serve.runtime import ModelServer

        with ModelServer(label="t_knobs", window_s=0.0,
                         max_batch=32) as srv:
            # both pinned by explicit args: refresh must not move them
            K.set_knob("serve_window_ms", 50.0)
            K.set_knob("serve_max_batch", 4096)
            srv._refresh_knobs()
            assert srv.window_s == 0.0
            assert srv.max_batch == 32
        K.clear_overrides()
        with ModelServer(label="t_knobs_live") as srv:
            K.set_knob("serve_window_ms", 1.0)
            K.set_knob("serve_max_batch", 1 << 19)
            srv._refresh_knobs()
            assert srv.window_s == pytest.approx(0.001)
            # live raise clamps to the construction compile ceiling
            assert srv.max_batch == srv._max_batch_ceiling


# ---------------------------------------------------------------------------
# the controller loop
# ---------------------------------------------------------------------------

def _spin(p, n):
    for _ in range(n):
        p._cycle()


class TestAutopilot:
    def test_injected_verdict_moves_readers_up(self, monkeypatch):
        monkeypatch.setenv(P.INJECT_ENV, "false-verdict")
        p = P.Autopilot(cadence_ms=5.0, cooldown=1, _test_cpu_frac=0.0)
        _spin(p, 4)
        assert p.moves and p.moves[0]["knob"] == "data_readers"
        assert p.moves[0]["direction"] == "up"
        assert p.moves[0]["injected"]
        assert K.override("data_readers") > 4

    def test_saturation_freezes_even_injected_verdicts(self, monkeypatch):
        monkeypatch.setenv(P.INJECT_ENV, "false-verdict")
        p = P.Autopilot(cadence_ms=5.0, cooldown=1, _test_cpu_frac=1.0)
        _spin(p, 4)
        assert p.moves == []
        assert p.freezes.get("saturation_pinned", 0) >= 3
        assert K.override("data_readers") is None

    def test_cooldown_spaces_moves(self, monkeypatch):
        monkeypatch.setenv(P.INJECT_ENV, "false-verdict")
        p = P.Autopilot(cadence_ms=5.0, cooldown=3, _test_cpu_frac=0.0)
        _spin(p, 4)
        # prime at cycle 1, move at cycle 2, then the cooldown holds
        # cycles 3-4 (cycles-since-move 1, 2 < 3)
        assert len(p.moves) == 1
        _spin(p, 1)  # cycles-since-move reaches 3: next move lands
        assert len(p.moves) == 2

    def test_step_caps_and_bounds_burn(self):
        p = P.Autopilot(cadence_ms=5.0, cooldown=1, max_moves=2,
                        _test_cpu_frac=0.0)
        v = {"class": "parse-bound", "confidence": 1.0,
             "confident": True, "injected": True}
        for _ in range(8):
            p._cycles_since_move = 10
            p._apply("fit", v)
        # 2 moves on readers, then the chain escalates to prefetch_depth
        # for 2 more, then policy_exhausted freezes
        by_knob = {}
        for m in p.moves:
            by_knob.setdefault(m["knob"], []).append(m)
        assert len(by_knob["data_readers"]) == 2
        assert len(by_knob["prefetch_depth"]) == 2
        assert p.freezes.get("policy_exhausted", 0) >= 1

    def test_low_confidence_freezes(self):
        p = P.Autopilot(cadence_ms=5.0, cooldown=1, _test_cpu_frac=0.0)
        p._cycles_since_move = 10
        p._apply("fit", {"class": "parse-bound", "confidence": 0.1,
                         "confident": False})
        assert p.moves == []
        assert p.freezes.get("low_confidence") == 1

    def test_device_bound_is_goal_state(self):
        p = P.Autopilot(cadence_ms=5.0, cooldown=1, _test_cpu_frac=0.0)
        p._cycles_since_move = 10
        p._apply("fit", {"class": "device-bound", "confidence": 0.9,
                         "confident": True})
        assert p.moves == []
        assert p.freezes.get("no_policy") == 1

    def test_step_semantics(self):
        p = P.Autopilot()
        readers = K.KNOBS["data_readers"]
        assert p._step(readers, 4, "up") == 8
        assert p._step(readers, 1, "up") == 2
        assert p._step(readers, 8, "down") == 4
        assert p._step(readers, 1, "down") == 1  # clamped at lo
        win = K.KNOBS["serve_window_ms"]
        assert p._step(win, 2.0, "up") == 4.0
        assert p._step(win, 0.0, "up") == 1.0
        assert p._step(win, 2.0, "down") == 1.0
        assert p._step(win, 0.4, "down") == 0.0

    def test_revert_on_regression(self):
        p = P.Autopilot(cadence_ms=5.0, cooldown=2)
        K.set_knob("data_readers", 8, source="pilot")
        p._pending = {"knob": "data_readers", "direction": "up",
                      "prev": 4, "to": 8, "rate_before": 100.0}
        p._cycles_since_move = 2
        # cooked samples: rate collapsed to ~10/s after the move
        p._samples = [(0.0, 0), (1.0, 10), (2.0, 20)]
        p._settle_pending()
        assert p.reverts and p.reverts[0]["action"] == "revert"
        assert K.override("data_readers") == 4
        assert ("data_readers", "up") in p._burned

    def test_flat_result_burns_direction_keeps_value(self):
        p = P.Autopilot(cadence_ms=5.0, cooldown=2)
        K.set_knob("data_readers", 8, source="pilot")
        # after = 10/s vs before = 10.4/s: above the revert line
        # (0.95x = 9.88) but below the noise floor (0.98x = 10.19) —
        # measurably not helping: keep the value, burn the direction
        p._pending = {"knob": "data_readers", "direction": "up",
                      "prev": 4, "to": 8, "rate_before": 10.4}
        p._cycles_since_move = 2
        p._samples = [(0.0, 0), (1.0, 10), (2.0, 20)]
        p._settle_pending()
        assert p.reverts == []
        assert K.override("data_readers") == 8
        assert ("data_readers", "up") in p._burned

    def test_ambiguous_settle_keeps_chain_alive(self):
        p = P.Autopilot(cadence_ms=5.0, cooldown=2)
        K.set_knob("data_readers", 8, source="pilot")
        # after ~= before: inside the noise floor — no burn, no revert
        p._pending = {"knob": "data_readers", "direction": "up",
                      "prev": 4, "to": 8, "rate_before": 10.0}
        p._cycles_since_move = 2
        p._samples = [(0.0, 0), (1.0, 10), (2.0, 20)]
        p._settle_pending()
        assert p.reverts == []
        assert p._burned == set()
        assert K.override("data_readers") == 8

    def test_serve_window_verdict_from_leg_deltas(self):
        from dask_ml_tpu.obs.metrics import registry

        reg = registry()
        reg.reset("serve.req_")
        p = P.Autopilot(cadence_ms=5.0, cooldown=1)
        assert p._serve_window_verdict() is None  # primes
        reg.histogram("serve.req_queue_s", "m").record(0.9)
        reg.histogram("serve.req_window_s", "m").record(0.05)
        reg.histogram("serve.req_device_s", "m").record(0.05)
        plane, v = p._serve_window_verdict()
        assert plane == "serve"
        assert v["class"] == "queue-bound"
        assert v["confident"]
        assert p._serve_window_verdict() is None  # no NEW traffic

    def test_policy_covers_every_actionable_class(self):
        for (plane, cls), chain in P.POLICY.items():
            assert plane in ("fit", "search", "serve")
            for name, direction in chain:
                assert name in K.KNOBS
                assert direction in ("up", "down")

    def test_report_and_converged(self, monkeypatch):
        monkeypatch.setenv(P.INJECT_ENV, "false-verdict")
        p = P.Autopilot(cadence_ms=5.0, cooldown=1, _test_cpu_frac=0.0)
        assert p.converged()  # no moves yet
        _spin(p, 2)
        assert not p.converged()  # just moved
        rep = p.report()
        assert rep["cycles"] == 2 and rep["moves"]
        assert "knobs" in rep and "freezes" in rep

    def test_run_loop_swallows_and_counts_cycle_errors(self, monkeypatch):
        p = P.Autopilot(cadence_ms=5.0, cooldown=1)

        calls = []

        def boom(self):
            calls.append(1)
            if len(calls) >= 3:
                p._stop.set()
            raise RuntimeError("boom")

        monkeypatch.setattr(P.Autopilot, "_cycle", boom)
        p._run()  # must return (stop honored), never propagate
        assert p.errors == 3


class TestPilotLifecycle:
    def test_thread_name_is_rostered_host_only(self):
        from dask_ml_tpu.analysis.rules._spmd import (
            HOST_ONLY_THREAD_NAMES)

        assert P.PILOT_THREAD_NAME in HOST_ONLY_THREAD_NAMES

    def test_scoped_autopilot_clears_overrides(self):
        with P.autopilot(cadence_ms=50.0) as p:
            assert p.running()
            assert threading.active_count() >= 2
            K.set_knob("data_readers", 9, source="pilot")
        assert not p.running()
        assert K.override("data_readers") is None

    def test_maybe_autostart_off_by_default(self):
        assert P.maybe_autostart() is None
        assert P.current_pilot() is None

    def test_maybe_autostart_armed(self, monkeypatch):
        monkeypatch.setenv(P.AUTOPILOT_ENV, "1")
        p = P.maybe_autostart()
        assert p is not None and p.running()
        assert P.maybe_autostart() is p  # idempotent
        P.stop_pilot()
        assert P.current_pilot() is None

    def test_env_junk_raises(self, monkeypatch):
        monkeypatch.setenv(P.AUTOPILOT_ENV, "yess")
        with pytest.raises(ValueError, match=P.AUTOPILOT_ENV):
            P.maybe_autostart()
        monkeypatch.setenv(P.CADENCE_ENV, "fast")
        with pytest.raises(ValueError, match=P.CADENCE_ENV):
            P.resolve_cadence_ms()
        monkeypatch.setenv(P.INJECT_ENV, "true-verdict")
        with pytest.raises(ValueError, match=P.INJECT_ENV):
            P.resolve_inject()

    def test_supervised_heartbeat_registered(self):
        from dask_ml_tpu.resilience import supervisor

        with P.autopilot(cadence_ms=50.0):
            hb = supervisor.lookup("control:pilot")
            assert hb is not None and hb.domain == "control"
        assert supervisor.lookup("control:pilot") is None


# ---------------------------------------------------------------------------
# the gate-of-the-gate: the CLI self-test
# ---------------------------------------------------------------------------

class TestSelfTestCLI:
    def _run(self, env=None):
        e = dict(os.environ, JAX_PLATFORMS="cpu")
        for k in _CONTROL_ENVS:
            e.pop(k, None)
        e.update(env or {})
        return subprocess.run(
            [sys.executable, "-m", "dask_ml_tpu.control", "--self-test"],
            capture_output=True, text=True, env=e, timeout=120)

    def test_live_controller_exits_zero(self):
        r = self._run()
        assert r.returncode == 0, r.stdout + r.stderr
        assert "PASS" in r.stdout

    def test_disabled_controller_fails_the_gate(self):
        r = self._run({P.AUTOPILOT_ENV: "off"})
        assert r.returncode == 1, r.stdout + r.stderr
        assert "DISABLED" in r.stdout

    def test_in_process_self_test_restores_env(self, monkeypatch):
        monkeypatch.delenv(P.INJECT_ENV, raising=False)
        assert P.self_test(verbose=False) == 0
        assert P.INJECT_ENV not in os.environ

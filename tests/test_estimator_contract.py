"""Sklearn-contract sweep over the whole public estimator surface.

The reference's API promise (SURVEY.md §0) is that everything follows the
sklearn estimator contract: every constructor arg is introspectable via
``get_params``, settable via ``set_params``, and ``clone`` produces an
equivalent unfitted copy.  One parametrized sweep pins that for every
public estimator at once, so a contract regression in any module fails
loudly here rather than deep inside a search/pipeline.
"""

import numpy as np
import pytest
from sklearn.base import clone

import dask_ml_tpu
from dask_ml_tpu.base import TPUEstimator


def _public_estimators():
    import inspect

    seen = {}
    mods = [
        dask_ml_tpu.cluster, dask_ml_tpu.decomposition,
        dask_ml_tpu.linear_model, dask_ml_tpu.preprocessing,
        dask_ml_tpu.feature_extraction.text, dask_ml_tpu.ensemble,
        dask_ml_tpu.compose, dask_ml_tpu.model_selection,
        dask_ml_tpu.wrappers, dask_ml_tpu.impute, dask_ml_tpu.naive_bayes,
    ]
    for mod in mods:
        for name in getattr(mod, "__all__", dir(mod)):
            obj = getattr(mod, name, None)
            if not (inspect.isclass(obj) and hasattr(obj, "get_params")):
                continue
            if name.startswith("_") or name.startswith("Base"):
                continue  # private/abstract bases are not user surface
            seen.setdefault(name, obj)
    return sorted(seen.items())


ESTIMATORS = _public_estimators()

# estimators whose constructor REQUIRES an argument
_REQUIRED_ARGS = {
    "Incremental": lambda cls: cls(estimator=None),
    "ParallelPostFit": lambda cls: cls(estimator=None),
    "BlockwiseVotingClassifier": lambda cls: cls(estimator=None),
    "BlockwiseVotingRegressor": lambda cls: cls(estimator=None),
    "ColumnTransformer": lambda cls: cls(transformers=[]),
    "GridSearchCV": lambda cls: cls(estimator=None, param_grid={}),
    "RandomizedSearchCV": lambda cls: cls(
        estimator=None, param_distributions={}
    ),
    "IncrementalSearchCV": lambda cls: cls(estimator=None, parameters={}),
    "InverseDecaySearchCV": lambda cls: cls(estimator=None, parameters={}),
    "SuccessiveHalvingSearchCV": lambda cls: cls(
        estimator=None, parameters={}
    ),
    "HyperbandSearchCV": lambda cls: cls(estimator=None, parameters={}),
    "BlockTransformer": lambda cls: cls(func=np.asarray),
}


def _construct(name, cls):
    if name in _REQUIRED_ARGS:
        return _REQUIRED_ARGS[name](cls)
    return cls()


def test_inventory_is_broad():
    names = [n for n, _ in ESTIMATORS]
    # spot-check the sweep actually sees the whole surface
    for must in ("KMeans", "MiniBatchKMeans", "PCA", "LogisticRegression",
                 "SGDClassifier", "StandardScaler", "OneHotEncoder",
                 "HashingVectorizer", "SimpleImputer", "GaussianNB",
                 "HyperbandSearchCV", "Incremental", "GridSearchCV"):
        assert must in names, f"{must} missing from sweep: {names}"
    assert len(names) >= 30


@pytest.mark.parametrize("name,cls", ESTIMATORS, ids=[n for n, _ in ESTIMATORS])
def test_params_roundtrip_and_clone(name, cls):
    est = _construct(name, cls)
    params = est.get_params(deep=False)
    # every param is settable with its own value (sklearn contract)
    est.set_params(**params)
    c = clone(est)
    assert type(c) is type(est)
    p2 = c.get_params(deep=False)
    for k, v in params.items():
        if isinstance(v, (int, float, str, bool, type(None), tuple)):
            same = p2[k] == v or (v != v and p2[k] != p2[k])  # NaN==NaN
            assert same, (name, k)


@pytest.mark.parametrize("name,cls", ESTIMATORS, ids=[n for n, _ in ESTIMATORS])
def test_constructor_does_no_work(name, cls):
    """sklearn contract: __init__ only stores params — no validation, no
    device touch (validation happens in fit)."""
    est = _construct(name, cls)
    # no fitted attributes at construction
    fitted = [
        k for k in vars(est)
        if k.endswith("_") and not k.startswith("__") and k != "_"
    ]
    assert fitted == [], (name, fitted)


def test_all_are_tpuestimator_or_sklearn():
    for name, cls in ESTIMATORS:
        from sklearn.base import BaseEstimator

        assert issubclass(cls, (TPUEstimator, BaseEstimator)), name


class TestDtypePreservation:
    """Reference test strategy #5 (SURVEY.md §4): float32 in, float32 out
    on the transform surface — the device-canonical dtype must survive
    every scaler round-trip."""

    SCALERS = ["StandardScaler", "MinMaxScaler", "RobustScaler",
               "MaxAbsScaler", "Normalizer", "QuantileTransformer"]

    @pytest.mark.parametrize("name", SCALERS)
    def test_f32_in_f32_out(self, name):
        import dask_ml_tpu.preprocessing as dp
        from dask_ml_tpu.core import shard_rows

        rng = np.random.RandomState(0)
        X = rng.normal(size=(64, 3)).astype(np.float32) + 2.0
        est = getattr(dp, name)()
        out = est.fit(shard_rows(X)).transform(shard_rows(X))
        assert out.data.dtype == np.float32, name

    def test_bf16_matrix_survives_solver(self):
        import jax.numpy as jnp

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import LogisticRegression

        rng = np.random.RandomState(0)
        X = rng.normal(size=(128, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        sX = shard_rows(X, dtype=jnp.bfloat16)
        # the SOLVER-side contract: the design matrix enters the solve in
        # bf16 (no silent f32 copy) while targets promote to f32
        from dask_ml_tpu.solvers.algorithms import _prep

        xd, yv, _ = _prep(sX, y)
        assert xd.dtype == jnp.bfloat16
        assert yv.dtype == jnp.float32
        lr = LogisticRegression(solver="lbfgs").fit(sX, y)
        assert np.asarray(lr.coef_).dtype == np.float32


class TestPickleRoundtrip:
    """Fitted estimators must pickle/unpickle with predictions intact —
    the reference's estimators are plain-pickle portable (model handoff
    between processes/jobs), so ours must be too, device arrays and all."""

    def test_fitted_estimators_roundtrip(self, rng, mesh):
        import pickle

        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.preprocessing import StandardScaler

        X = rng.normal(size=(200, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        cases = [
            (KMeans(n_clusters=3, init="random", random_state=0),
             (shard_rows(X),)),
            (LogisticRegression(solver="lbfgs"),
             (shard_rows(X), shard_rows(y))),
            (StandardScaler(), (shard_rows(X),)),
        ]
        for est, args in cases:
            est.fit(*args)
            est2 = pickle.loads(pickle.dumps(est))
            name = type(est).__name__
            if hasattr(est2, "predict"):
                np.testing.assert_array_equal(
                    np.asarray(est.predict(X[:20])),
                    np.asarray(est2.predict(X[:20])), err_msg=name)
            else:
                np.testing.assert_allclose(
                    np.asarray(est.transform(shard_rows(X)).data),
                    np.asarray(est2.transform(shard_rows(X)).data),
                    err_msg=name)

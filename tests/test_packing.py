"""Multi-model packing tests (VERDICT round-1 item 3, SURVEY.md §2.2
"model-parallel search" / §7 hard-part (c)): N models trained with ≪N
dispatches; MODEL_AXIS actually consumed."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu.core.mesh import MODEL_AXIS, device_mesh, use_mesh
from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor
from dask_ml_tpu.model_selection._packing import (
    Cohort,
    DISPATCH_STATS,
    pack_key,
    reset_dispatch_stats,
)


def _data(rng, n=800, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int64)
    return X, y


class TestPackKey:
    def test_same_static_config_same_key(self):
        a = SGDClassifier(alpha=1e-4, eta0=0.1)
        b = SGDClassifier(alpha=1e-2, eta0=0.5)
        assert pack_key(a) == pack_key(b) is not None

    def test_different_loss_different_key(self):
        assert pack_key(SGDClassifier(loss="hinge")) != pack_key(
            SGDClassifier(loss="log_loss")
        )

    def test_non_sgd_unpackable(self):
        from sklearn.linear_model import SGDClassifier as SkSGD

        assert pack_key(SkSGD()) is None


class TestCohort:
    def test_packed_matches_individual(self, rng):
        # The packed stack must produce the same models as individual
        # partial_fit calls on the same blocks.
        X, y = _data(rng)
        hypers = [(1e-4, 0.1), (1e-3, 0.3), (1e-2, 0.5), (1e-4, 0.7)]
        packed = [
            SGDClassifier(alpha=a, eta0=e, learning_rate="constant")
            for a, e in hypers
        ]
        solo = [
            SGDClassifier(alpha=a, eta0=e, learning_rate="constant")
            for a, e in hypers
        ]
        classes = np.unique(y)
        cohort = Cohort(packed, classes=classes)
        for _ in range(10):
            cohort.step(X, y)
        cohort.finalize()
        for m in solo:
            for _ in range(10):
                m.partial_fit(X, y, classes=classes)
        for p, s in zip(packed, solo):
            np.testing.assert_allclose(p.coef_, s.coef_, rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(
                p.intercept_, s.intercept_, rtol=1e-4, atol=1e-5
            )
            assert p.t_ == s.t_ == 10

    def test_one_dispatch_per_block(self, rng):
        X, y = _data(rng)
        models = [
            SGDClassifier(alpha=a, learning_rate="constant", eta0=0.2)
            for a in np.logspace(-5, -1, 12)
        ]
        reset_dispatch_stats()
        cohort = Cohort(models, classes=np.unique(y))
        for _ in range(7):
            cohort.step(X, y)
        cohort.finalize()
        assert DISPATCH_STATS["dispatches"] == 7  # not 12*7
        assert DISPATCH_STATS["models_stepped"] == 12 * 7

    def test_mixed_configs_rejected(self):
        with pytest.raises(ValueError, match="not packable"):
            Cohort([SGDClassifier(loss="hinge"), SGDClassifier(loss="log_loss")])

    def test_regressor_cohort(self, rng):
        n, d = 500, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X @ rng.normal(size=d)).astype(np.float32)
        models = [
            SGDRegressor(eta0=e, learning_rate="constant")
            for e in (0.05, 0.1, 0.2)
        ]
        cohort = Cohort(models)
        for _ in range(100):
            cohort.step(X, y)
        cohort.finalize()
        for m in models:
            assert m.score(X, y) > 0.9

    def test_model_axis_consumed(self, rng):
        # On a mesh with a nontrivial model axis the stacked state is
        # sharded over MODEL_AXIS: 2-D (model x data) parallelism.
        X, y = _data(rng, n=512)
        from conftest import require_devices_divisible

        mesh = device_mesh(require_devices_divisible(4), model_axis=4)
        with use_mesh(mesh):
            models = [
                SGDClassifier(alpha=a, learning_rate="constant", eta0=0.2)
                for a in np.logspace(-5, -2, 8)
            ]
            cohort = Cohort(models, classes=np.unique(y))
            cohort.step(X, y)
            stacked_coef = cohort._stacked["coef"]
            spec = stacked_coef.sharding.spec
            assert spec[0] == MODEL_AXIS
            cohort.finalize()
        for m in models:
            assert m.t_ == 1


class TestSearchIntegration:
    def test_hyperband_packs_rounds(self, rng):
        from dask_ml_tpu.model_selection import HyperbandSearchCV

        X, y = _data(rng, n=1200)
        reset_dispatch_stats()
        search = HyperbandSearchCV(
            SGDClassifier(learning_rate="constant"),
            {"eta0": np.logspace(-2, 0, 20), "alpha": np.logspace(-5, -2, 20)},
            max_iter=9,
            random_state=0,
        )
        search.fit(X, y, classes=np.unique(y))
        # the packed plane did the bulk of the training: far fewer fused
        # dispatches than model-steps
        assert DISPATCH_STATS["models_stepped"] > 0
        ratio = DISPATCH_STATS["models_stepped"] / max(
            DISPATCH_STATS["dispatches"], 1
        )
        assert ratio > 2.0, DISPATCH_STATS
        assert search.best_score_ > 0.8

    def test_sha_schedule_unchanged_by_packing(self, rng):
        # Packing is an execution detail: SHA's deterministic ladder on
        # fake (unpackable) models is untouched, and on packable models the
        # partial_fit_calls bookkeeping is identical.
        from dask_ml_tpu.model_selection import SuccessiveHalvingSearchCV

        X, y = _data(rng, n=600)
        search = SuccessiveHalvingSearchCV(
            SGDClassifier(learning_rate="constant"),
            {"eta0": [0.1, 0.2, 0.3, 0.4, 0.5, 0.6]},
            n_initial_parameters=6,
            n_initial_iter=2,
            random_state=0,
        )
        search.fit(X, y, classes=np.unique(y))
        calls = sorted(
            rec[-1]["partial_fit_calls"] for rec in search.model_history_.values()
        )
        # 6 models at 2 calls, survivors grow x3: the [2,2,2,2,6,18]-style
        # ladder must match the unpacked policy math
        assert calls[0] == 2 and calls[-1] > 2


class TestPackedValidationParity:
    def test_single_class_rejected_in_cohort(self, rng):
        X, y = _data(rng, n=50)
        with pytest.raises(ValueError, match="2 classes"):
            Cohort(
                [SGDClassifier(), SGDClassifier(alpha=1e-3)], classes=[0]
            ).step(X, np.zeros(50))


class TestDeviceResidentSearchPath:
    def test_device_input_search_never_unshards(self, rng, monkeypatch):
        """ShardedRows input + device-native SGD models: the adaptive
        search's TRAINING plane must do zero O(n) device→host transfers
        (blocks are device slices, targets encode on device).  Only the
        held-out test split may cross to host (scorers are host-side)."""
        import dask_ml_tpu.model_selection._incremental as inc
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.model_selection import IncrementalSearchCV

        X, y = _data(rng, n=400)
        sX, sy = shard_rows(X), shard_rows(y.astype(np.float32))

        real_unshard = inc.unshard
        calls = []

        def counting_unshard(a):
            calls.append(getattr(a, "n_samples", None))
            return real_unshard(a)

        monkeypatch.setattr(inc, "unshard", counting_unshard)
        search = IncrementalSearchCV(
            SGDClassifier(learning_rate="constant", eta0=0.1),
            {"alpha": [1e-4, 1e-3]},
            n_initial_parameters=2, max_iter=3, random_state=0,
        )
        search.fit(sX, sy, classes=[0.0, 1.0])
        assert search.best_score_ > 0
        # the only permitted unshards are the test split (~15% of rows)
        assert all(c is not None and c <= 0.2 * 400 for c in calls), calls

    def test_device_blocks_with_host_labels(self, rng):
        """Relaxed device X blocks (length NOT a data-axis multiple) +
        host numpy y: host-encoded targets must align with the block's
        exact row count (regression: re-sharding targets padded them to
        the 8-device multiple and diverged from xb)."""
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.model_selection import IncrementalSearchCV

        X, y = _data(rng, n=410)  # 410/4-block chunks are not 8-multiples
        search = IncrementalSearchCV(
            SGDClassifier(learning_rate="constant", eta0=0.1),
            {"alpha": [1e-4, 1e-3]},
            n_initial_parameters=2, max_iter=2, random_state=0,
            chunk_size=103,
        )
        search.fit(shard_rows(X), y, classes=[0.0, 1.0])
        assert search.best_score_ > 0


class TestPackedScoring:
    def test_packed_accuracy_matches_individual_scores(self, rng, mesh):
        import numpy as np

        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.model_selection._packing import Cohort

        X = rng.normal(size=(512, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        models = [
            SGDClassifier(alpha=a, random_state=0, tol=None)
            for a in (1e-5, 1e-4, 1e-3, 1e-2)
        ]
        cohort = Cohort(models, classes=[0.0, 1.0])
        for _ in range(3):
            cohort.step(X, y)
        packed = cohort.packed_accuracy(X, y)
        cohort.finalize()
        individual = [m.score(X, y) for m in models]
        np.testing.assert_allclose(packed, individual, atol=1e-6)

    def test_packed_accuracy_rejects_regressor_cohort(self, rng, mesh):
        import numpy as np
        import pytest

        from dask_ml_tpu.linear_model import SGDRegressor
        from dask_ml_tpu.model_selection._packing import Cohort

        X = rng.normal(size=(128, 4)).astype(np.float32)
        y = X[:, 0].astype(np.float32)
        cohort = Cohort([SGDRegressor(), SGDRegressor(alpha=1e-3)])
        cohort.step(X, y)
        with pytest.raises(TypeError, match="classifier"):
            cohort.packed_accuracy(X, y)


class TestClassWeightedPacking:
    def test_weighted_models_pack_and_match_individual(self, rng, mesh):
        # per-model masks: lanes with DIFFERENT class_weight dicts train
        # packed yet match their standalone partial_fit exactly
        import numpy as np

        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.model_selection._packing import Cohort, pack_key

        X = rng.normal(size=(512, 5)).astype(np.float32)
        y = (X[:, 0] + 0.8 > 0).astype(np.float32)
        cws = [None, {0.0: 5.0, 1.0: 1.0}, {0.0: 1.0, 1.0: 3.0}, None]
        packed_models = [
            SGDClassifier(alpha=1e-4, random_state=0, tol=None,
                          class_weight=cw)
            for cw in cws
        ]
        assert all(pack_key(m) is not None for m in packed_models)
        cohort = Cohort(packed_models, classes=[0.0, 1.0])
        for _ in range(3):
            cohort.step(X, y)
        cohort.finalize()
        for cw, pm in zip(cws, packed_models):
            solo = SGDClassifier(alpha=1e-4, random_state=0, tol=None,
                                 class_weight=cw)
            for _ in range(3):
                solo.partial_fit(X, y, classes=[0.0, 1.0])
            np.testing.assert_allclose(
                pm.coef_, solo.coef_, rtol=1e-5, atol=1e-6
            )

    def test_balanced_still_unpackable(self, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.model_selection._packing import pack_key

        assert pack_key(SGDClassifier(class_weight="balanced")) is None

"""Chaos drill suite: the tier-1 recovery gate (docs/design.md §13).

One full suite run is shared by the gate assertions (the drills are
the expensive part — each is a real streamed fit with an injected
fault); the ratchet compares against the COMMITTED
``tools/drill_baseline.json`` exactly as CI does via
``tools/lint.sh --drills``.
"""

import copy
import json
import os

import pytest

from dask_ml_tpu.resilience import drills
from dask_ml_tpu.resilience.testing import INJECTION_POINTS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRILL_BASELINE = os.path.join(REPO, "tools", "drill_baseline.json")


# ---------------------------------------------------------------------------
# the gate: one full run, ratcheted against the committed snapshot
# ---------------------------------------------------------------------------

class TestDrillGate:
    @pytest.fixture(scope="class")
    def suite(self):
        return drills.run_suite()

    def test_every_drill_recovers_with_matching_model(self, suite):
        """The acceptance criterion: every registered fault point, at
        prefetch depth 0 AND 2, recovers and lands on the unfaulted
        twin's model."""
        for name, m in sorted(suite.items()):
            assert not m.get("error"), f"{name}: {m.get('error')}"
            assert m["recovered"], f"{name}: recovery path broken"
            assert m["model_match"], (
                f"{name}: recovered model diverged from the unfaulted "
                f"twin (max_rel_diff={m['max_rel_diff']})")

    def test_every_injection_point_has_a_drill(self, suite):
        covered = {m["point"] for m in suite.values()}
        assert set(INJECTION_POINTS) <= covered

    def test_thread_death_drills_clean_under_armed_sanitizer(self, suite):
        """Prefetch-worker crash and compile-ahead crash recover with
        ZERO steady-state compile/dispatch violations — recovery may
        not smuggle work past graftsan."""
        for name in ("prefetch_crash_sgd_d0", "prefetch_crash_sgd_d2",
                     "ahead_crash_sgd_d0", "ahead_crash_sgd_d2"):
            assert suite[name]["steady_violations"] == 0, name
        # and at depth 2 the faults actually fired (not vacuous)
        assert suite["prefetch_crash_sgd_d2"]["faults_injected"] == 1
        assert suite["ahead_crash_sgd_d2"]["faults_injected"] == 1

    def test_degraded_skip_recorded_exactly_once(self, suite):
        for depth in (0, 2):
            m = suite[f"stage_skip_ipca_d{depth}"]
            assert m["degraded_skips"] == 1

    def test_committed_baseline_matches(self, suite):
        """The ratchet gate: clean against the COMMITTED snapshot —
        new/stale drills, broken recovery, retry counts above the
        ceilings all fail."""
        snap = drills.load_baseline(DRILL_BASELINE)
        delta = drills.compare(snap, suite)
        assert drills.is_clean(delta), delta


# ---------------------------------------------------------------------------
# ratchet semantics (pure-python, no fits)
# ---------------------------------------------------------------------------

def _clean_metrics(point="ingest", **over):
    m = {"point": point, "depth": 0, "recovered": True,
         "model_match": True, "max_rel_diff": 0.0, "retries": 1,
         "faults_injected": 1, "degraded_skips": 0,
         "steady_violations": 0}
    m.update(over)
    return m


def _full_results():
    return {f"d_{p}": _clean_metrics(point=p) for p in INJECTION_POINTS}


class TestCompare:
    def test_clean_round_trip(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        assert drills.is_clean(drills.compare(snap, results))

    def test_new_drill_fails(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        results["d_extra"] = _clean_metrics()
        delta = drills.compare(snap, results)
        assert delta["new"] == ["d_extra"]

    def test_stale_entry_fails(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        snap["drills"]["d_gone"] = _clean_metrics()
        delta = drills.compare(snap, results)
        assert delta["stale"] == ["d_gone"]

    def test_uncovered_point_fails(self):
        results = _full_results()
        del results["d_ingest"]
        snap = {"drills": copy.deepcopy(results)}
        delta = drills.compare(snap, results)
        assert any("'ingest'" in line for line in delta["uncovered"])

    def test_retry_ceiling_regression_fails(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        results["d_ingest"]["retries"] = 5
        delta = drills.compare(snap, results)
        assert any("retries 5 > baseline 1" in line
                   for line in delta["regressions"])

    def test_broken_recovery_is_a_hard_violation(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        results["d_step"]["recovered"] = False
        delta = drills.compare(snap, results)
        assert any("recovered" in line for line in delta["violations"])

    def test_snapshot_cannot_grandfather_broken_recovery(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        snap["drills"]["d_step"]["model_match"] = False
        delta = drills.compare(snap, results)
        assert any("grandfather" in line for line in delta["violations"])

    def test_steady_violation_is_hard_zero(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        results["d_compile-ahead"]["steady_violations"] = 1
        delta = drills.compare(snap, results)
        assert any("steady_violations" in line
                   for line in delta["violations"])

    def test_partial_subset_skips_stale_and_coverage(self):
        results = {"d_ingest": _clean_metrics()}
        snap = {"drills": _full_results()}
        delta = drills.compare(snap, results, partial=True)
        assert drills.is_clean(delta)

    def test_errored_drill_is_a_violation(self):
        results = _full_results()
        snap = {"drills": copy.deepcopy(results)}
        results["d_stage"]["error"] = "RuntimeError: boom"
        delta = drills.compare(snap, results)
        assert any("errored" in line for line in delta["violations"])


class TestBaselineStore:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "b.json")
        drills.write_baseline(path, drills.emit_baseline(_full_results()))
        snap = drills.load_baseline(path)
        assert snap["tool"] == "graftdrill"
        assert set(snap["drills"]) == set(_full_results())

    def test_newer_version_refused(self, tmp_path):
        path = tmp_path / "b.json"
        path.write_text(json.dumps({"version": 99, "drills": {}}))
        with pytest.raises(ValueError, match="newer"):
            drills.load_baseline(str(path))

    def test_malformed_refused(self, tmp_path):
        path = tmp_path / "b.json"
        path.write_text(json.dumps({"version": 1}))
        with pytest.raises(ValueError, match="malformed"):
            drills.load_baseline(str(path))

    def test_committed_baseline_carries_no_violations(self):
        """A committed snapshot may never grandfather a broken recovery
        path — checked standalone so a bad hand-edit fails even before
        the suite runs."""
        snap = drills.load_baseline(DRILL_BASELINE)
        delta = drills.compare(snap, {k: dict(v) for k, v in
                                      snap["drills"].items()})
        assert not delta["violations"], delta["violations"]
        assert not delta["uncovered"], delta["uncovered"]


class TestCLI:
    def test_partial_write_baseline_refused(self, tmp_path, capsys):
        rc = drills.main(["--write-baseline", str(tmp_path / "b.json"),
                          "--drills", "ingest_retry_sgd_d0"])
        assert rc == 2
        assert not (tmp_path / "b.json").exists()

    def test_unknown_drill_exits_two(self, capsys):
        rc = drills.main(["--drills", "no_such_drill"])
        assert rc == 2

    def test_unreadable_baseline_exits_two(self, tmp_path, capsys,
                                           monkeypatch):
        monkeypatch.setattr(drills, "run_suite",
                            lambda names=None: _full_results())
        rc = drills.main(["--baseline", str(tmp_path / "missing.json")])
        assert rc == 2

    def test_violating_run_never_writes_baseline(self, tmp_path, capsys,
                                                 monkeypatch):
        bad = _full_results()
        bad["d_step"]["recovered"] = False
        monkeypatch.setattr(drills, "run_suite",
                            lambda names=None: bad)
        path = tmp_path / "b.json"
        rc = drills.main(["--write-baseline", str(path)])
        assert rc == 1
        assert not path.exists()

    def test_clean_run_round_trips_and_gates(self, tmp_path, capsys,
                                             monkeypatch):
        monkeypatch.setattr(drills, "run_suite",
                            lambda names=None: _full_results())
        path = str(tmp_path / "b.json")
        assert drills.main(["--write-baseline", path]) == 0
        assert drills.main(["--baseline", path]) == 0

    def test_list_drills(self, capsys):
        assert drills.main(["--list-drills"]) == 0
        out = capsys.readouterr().out
        assert "ingest_retry_sgd_d0" in out
        assert "ahead_crash_sgd_d2" in out

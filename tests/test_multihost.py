"""Multi-host plane tests (VERDICT round-1 item 4; SURVEY.md §2.3).

The in-process suite runs on one process, so the cross-process path is
exercised the way the reference exercises multi-node behavior — a real
protocol stack on localhost (``gen_cluster`` analogue): subprocesses form a
``jax.distributed`` group with Gloo CPU collectives and run the flagship
SPMD programs over the global mesh.
"""

import os
import sys

from dask_ml_tpu.core._multihost_worker import spawn_group

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestMultihost:
    def test_two_process_admm_and_lloyd(self):
        for rc, out in spawn_group(2, 4, timeout_s=240):
            assert rc == 0, out
            assert "multihost OK" in out

    def test_graft_entry_dryrun_multihost(self):
        # the driver-facing wrapper end-to-end
        sys.path.insert(0, REPO)
        try:
            import __graft_entry__ as g

            g.dryrun_multihost(2, local_devices=2)
        finally:
            sys.path.remove(REPO)


class TestGlobalMeshSingleProcess:
    """Mesh/axis logic that doesn't need a real process group."""

    def test_global_mesh_flat_axes(self, mesh):
        from dask_ml_tpu.core import distributed as dist

        m = dist.global_mesh()
        assert m.axis_names == ("data", "model")
        assert len(m.devices.flat) == 8

    def test_hierarchical_single_process(self, mesh):
        from dask_ml_tpu.core import distributed as dist

        m = dist.global_mesh(hierarchical=True)
        assert m.axis_names == ("dcn", "data", "model")
        assert m.shape["dcn"] == 1  # one process

    def test_shard_rows_global_single_process(self, mesh, rng):
        import numpy as np

        from dask_ml_tpu.core import distributed as dist
        from dask_ml_tpu.core import unshard

        X = rng.normal(size=(37, 3)).astype(np.float32)
        s = dist.shard_rows_global(X, dist.global_mesh())
        assert s.n_samples == 37
        np.testing.assert_allclose(unshard(s), X)

    def test_mesh_process_mismatch_clear_error(self, mesh):
        import numpy as np
        import pytest

        from dask_ml_tpu.core import distributed as dist

        m = dist.global_mesh(model_axis=8)  # data axis size 1, 1 process ok
        # fake a larger process count via monkeypatching is brittle; instead
        # check the validation logic directly
        with pytest.raises(ValueError, match="evenly"):
            # simulate: 1 data shard cannot split over 2 processes
            import jax

            orig = jax.process_count
            jax.process_count = lambda: 2
            try:
                dist.shard_rows_global(np.zeros((4, 2), np.float32), m)
            finally:
                jax.process_count = orig

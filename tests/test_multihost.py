"""Multi-host plane tests (VERDICT round-1 item 4; SURVEY.md §2.3).

The in-process suite runs on one process, so the cross-process path is
exercised the way the reference exercises multi-node behavior — a real
protocol stack on localhost (``gen_cluster`` analogue): subprocesses form a
``jax.distributed`` group with Gloo CPU collectives and run the flagship
SPMD programs over the global mesh.
"""

import os
import sys

import jax
import pytest

from conftest import retry_flaky
from dask_ml_tpu.core._multihost_worker import spawn_group

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestMultihost:
    def test_two_process_admm_and_lloyd(self):
        outs = []
        for rc, out in spawn_group(2, 4, timeout_s=720):
            assert rc == 0, out
            assert "multihost OK" in out
            # flagship 5: ADMM + Lloyd over the hierarchical
            # ('dcn','data','model') mesh with dcn spanning the two
            # processes, parity-asserted against the flat-mesh fits
            # inside the worker
            assert "dcn_mesh OK" in out
            outs.append(out)
        # cross-host packed search (VERDICT r2 next #3): the worker runs a
        # 4-model IncrementalSearchCV with the cohort's MODEL_AXIS spanning
        # both processes; every dispatch must step the whole cohort and
        # both processes must agree on every score
        import ast
        import re

        parsed = []
        for out in outs:
            m = re.search(r"search_scores=(\[[^\]]*\])", out)
            assert m, out
            parsed.append(ast.literal_eval(m.group(1)))
            s = re.search(r"dispatch_stats=(\{[^}]*\})", out)
            stats = ast.literal_eval(s.group(1))
            assert stats["models_stepped"] == 4 * stats["dispatches"], stats
        assert parsed[0] == parsed[1]  # identical across processes

        # sequential-bracket Hyperband (flagship 4): both processes must
        # report the identical best score and model count — the whole
        # point of the lockstep form is cross-controller agreement
        hbs = []
        for out in outs:
            m = re.search(r"hyperband_best=([0-9.]+) n_models=(\d+)", out)
            assert m, out
            hbs.append((m.group(1), m.group(2)))
        assert hbs[0] == hbs[1], hbs

        # identical to single-host: the same global dataset on one
        # process's 8-device mesh must produce the same scores
        import numpy as np

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.core.mesh import device_mesh, use_mesh
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.model_selection import IncrementalSearchCV

        n_per, d = 400, 6
        rng = np.random.RandomState(0)
        w_true = rng.normal(size=d).astype(np.float32)
        Xg = np.vstack([
            np.random.RandomState(100 + pid).normal(
                size=(n_per, d)).astype(np.float32)
            for pid in range(2)
        ])
        yg = (Xg @ w_true > 0).astype(np.float32)
        from conftest import require_devices_divisible

        mesh2 = device_mesh(require_devices_divisible(2), model_axis=2)
        with use_mesh(mesh2):
            search = IncrementalSearchCV(
                SGDClassifier(random_state=0, tol=None),
                {"alpha": [1e-5, 1e-4, 1e-3, 1e-2]},
                n_initial_parameters="grid", max_iter=3, patience=False,
                random_state=0,
            ).fit(shard_rows(Xg, mesh2), shard_rows(yg, mesh2),
                  classes=[0.0, 1.0])
        single = [round(s, 6) for s in search.cv_results_["test_score"]]
        np.testing.assert_allclose(single, parsed[0], atol=1e-4)

    @retry_flaky(
        attempts=2,
        match=(r"heartbeat|coordination.?service|barrier.*timed?.?out|"
               r"deadline.?exceeded|unavailable"),
    )
    def test_three_process_group(self):
        """Odd process count (3 × 2 devices): the mesh math, the
        hierarchical dcn axis (size 3), and the cross-controller
        agreement must all be nproc-generic, not 2-hardcoded.  All
        three processes must report identical search scores and
        Hyperband results.

        Auto-retried on heartbeat/coordination noise only: 3 jax
        processes on the 2-core box intermittently starve the
        coordination service (ROADMAP env note) — that flake class
        passes in isolation and must not eat a tier-1 lane, while any
        real score/agreement assertion still fails on the first run.
        """
        import re

        outs = []
        for rc, out in spawn_group(3, 2, timeout_s=900):
            assert rc == 0, out
            assert "multihost OK" in out
            assert "dcn_mesh OK" in out
            outs.append(out)
        scores = [re.search(r"search_scores=(\[[^\]]*\])", o).group(1)
                  for o in outs]
        assert scores[0] == scores[1] == scores[2]
        hbs = [re.search(r"hyperband_best=([0-9.]+) n_models=(\d+)",
                         o).groups() for o in outs]
        assert hbs[0] == hbs[1] == hbs[2]

    def test_graft_entry_dryrun_multihost(self):
        # the driver-facing wrapper end-to-end
        sys.path.insert(0, REPO)
        try:
            import __graft_entry__ as g

            g.dryrun_multihost(2, local_devices=2)
        finally:
            sys.path.remove(REPO)


class TestRetryFlaky:
    """The auto-retry harness itself: retries ONLY the matched flake
    class, surfaces real failures immediately."""

    def test_matched_flake_is_retried(self):
        calls = []

        @retry_flaky(attempts=2, match="heartbeat")
        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise AssertionError("coordination heartbeat timed out")
            return "ok"

        with pytest.warns(UserWarning, match="retrying"):
            assert flaky() == "ok"
        assert len(calls) == 2

    def test_unmatched_failure_is_not_retried(self):
        calls = []

        @retry_flaky(attempts=3, match="heartbeat")
        def broken():
            calls.append(1)
            raise AssertionError("scores diverged across processes")

        with pytest.raises(AssertionError, match="diverged"):
            broken()
        assert len(calls) == 1

    def test_exhausted_retries_raise_the_flake(self):
        @retry_flaky(attempts=2, match="heartbeat")
        def always():
            raise RuntimeError("heartbeat lost")

        with pytest.warns(UserWarning, match="retrying"):
            with pytest.raises(RuntimeError, match="heartbeat"):
                always()


class TestGlobalMeshSingleProcess:
    """Mesh/axis logic that doesn't need a real process group."""

    def test_global_mesh_flat_axes(self, mesh):
        from dask_ml_tpu.core import distributed as dist

        m = dist.global_mesh()
        assert m.axis_names == ("data", "model")
        assert len(m.devices.flat) == len(jax.devices())

    def test_hierarchical_single_process(self, mesh):
        from dask_ml_tpu.core import distributed as dist

        m = dist.global_mesh(hierarchical=True)
        assert m.axis_names == ("dcn", "data", "model")
        assert m.shape["dcn"] == 1  # one process

    def test_shard_rows_global_single_process(self, mesh, rng):
        import numpy as np

        from dask_ml_tpu.core import distributed as dist
        from dask_ml_tpu.core import unshard

        X = rng.normal(size=(37, 3)).astype(np.float32)
        s = dist.shard_rows_global(X, dist.global_mesh())
        assert s.n_samples == 37
        np.testing.assert_allclose(unshard(s), X)

    def test_mesh_process_mismatch_clear_error(self, mesh):
        import numpy as np
        import pytest

        from dask_ml_tpu.core import distributed as dist

        from conftest import require_devices_divisible

        require_devices_divisible(8)
        m = dist.global_mesh(model_axis=8)  # data axis size 1, 1 process ok
        # fake a larger process count via monkeypatching is brittle; instead
        # check the validation logic directly
        with pytest.raises(ValueError, match="evenly"):
            # simulate: 1 data shard cannot split over 2 processes
            import jax

            orig = jax.process_count
            jax.process_count = lambda: 2
            try:
                dist.shard_rows_global(np.zeros((4, 2), np.float32), m)
            finally:
                jax.process_count = orig


class TestHierarchicalMeshCompat:
    """Every shard_map program now runs NATIVELY on the ('dcn','data')
    axis tuple (``core.mesh.data_axes``): TSQR's R all_gather and the
    pairwise ppermute ring span the slice boundary (flattened ring
    semantics over the tuple), ADMM's psums likewise (covered by the
    worker flagship).  This pin proves correctness of those collectives
    on a mesh whose rows are genuinely split over BOTH axes."""

    def test_programs_correct_on_dcn_mesh(self, rng):
        import numpy as np

        from conftest import require_devices_divisible

        require_devices_divisible(8)
        from dask_ml_tpu.core import use_mesh
        from dask_ml_tpu.core import distributed as dist
        from dask_ml_tpu.core.mesh import Mesh

        devs = np.array(jax.devices()[:8]).reshape(2, 4, 1)
        hmesh = Mesh(devs, ("dcn", "data", "model"))
        X = rng.normal(size=(160, 6)).astype(np.float32)
        with use_mesh(hmesh):
            s = dist.shard_rows_global(X, hmesh)
            # rows genuinely split over BOTH axes
            assert "dcn" in str(s.data.sharding.spec)

            from dask_ml_tpu.linalg.tsqr import tsqr

            q, r = tsqr(s)
            qh = np.asarray(q)[:160].astype(np.float64)
            rr = np.asarray(r).astype(np.float64)
            assert np.abs(qh @ rr - X).max() < 1e-5
            assert np.abs(qh.T @ qh - np.eye(6)).max() < 1e-5

            from sklearn.metrics.pairwise import (
                euclidean_distances as sk_euc,
            )

            from dask_ml_tpu.metrics import euclidean_distances

            Y = dist.shard_rows_global(X[:80], hmesh)
            d_ring = np.asarray(euclidean_distances(s, Y))
            ref = sk_euc(X.astype(np.float64), X[:80].astype(np.float64))
            assert np.abs(d_ring - ref).max() < 1e-5

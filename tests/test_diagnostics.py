"""Diagnostics harness tests."""

import jax.numpy as jnp

import jax

from dask_ml_tpu.diagnostics import benchmark_step, trace


def test_benchmark_step_times_jitted_fn():
    f = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((64, 8))
    stats = benchmark_step(f, x, warmup=1, iters=3)
    assert stats["iters"] == 3
    assert stats["min_s"] >= 0
    assert stats["mean_s"] >= stats["min_s"]


def test_trace_writes_profile(tmp_path):
    with trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
    # a trace directory with at least one event file appears
    produced = list(tmp_path.rglob("*"))
    assert produced, "profiler produced no output"

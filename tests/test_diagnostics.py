"""Diagnostics harness tests."""

import jax.numpy as jnp

import jax

from dask_ml_tpu.diagnostics import benchmark_step, trace


def test_benchmark_step_times_jitted_fn():
    f = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((64, 8))
    stats = benchmark_step(f, x, warmup=1, iters=3)
    assert stats["iters"] == 3
    assert stats["min_s"] >= 0
    assert stats["mean_s"] >= stats["min_s"]


def test_trace_writes_profile(tmp_path):
    with trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
    # a trace directory with at least one event file appears
    produced = list(tmp_path.rglob("*"))
    assert produced, "profiler produced no output"


class TestBenchmarkSlope:
    def test_slope_of_chained_loop(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from dask_ml_tpu.diagnostics import benchmark_slope

        # random data + a carry-dependent nonlinearity: constant inputs or
        # hoistable bodies get folded by XLA and the slope measures nothing
        x = jnp.asarray(np.random.RandomState(0).normal(size=(50_000, 16)),
                        jnp.float32)

        @jax.jit
        def chained(n):
            def body(_, c):
                return c + jnp.sum(jnp.sin(x + c)) * 1e-9
            return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))

        out = benchmark_slope(lambda n: float(chained(jnp.int32(n))),
                              counts=(2, 20), reps=2)
        assert out["per_iter_s"] > 0.0
        assert set(out["raw_s"]) == {2, 20}

    def test_benchmark_step_fetch_sync(self):
        import jax
        import jax.numpy as jnp

        from dask_ml_tpu.diagnostics import benchmark_step

        f = jax.jit(lambda x: (x * 2, {"loss": jnp.sum(x)}))
        stats = benchmark_step(f, jnp.ones((64, 8)), iters=3)
        assert stats["min_s"] > 0 and stats["iters"] == 3

"""graftlint v2 engine: the project-wide machinery UNDER the rules.

The rules' pos/neg snippets live in test_graftlint.py; this file pins
the engine itself — module indexing and import resolution (aliased,
relative, from-imports), call-graph resolution (lexical nesting,
methods, super(), cycles), def-use chains, the whole-project cache, the
CLI's exit-code contract (findings=1 vs crash/bad-args=2), and the
timing budget that keeps the tier-1 gate negligible."""

import ast
import json
import os
import textwrap
import time

import pytest

from dask_ml_tpu.analysis import Context, lint_paths, main
from dask_ml_tpu.analysis import cache as glcache
from dask_ml_tpu.analysis import dataflow
from dask_ml_tpu.analysis.graph import Project, module_name_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "dask_ml_tpu")


def ctx_of(src, path="<string>"):
    return Context(textwrap.dedent(src), path)


def project_of(*srcs_paths):
    return Project([ctx_of(s, p) for s, p in srcs_paths])


def first_call(ctx, name):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                got = node.func.attr
            elif isinstance(node.func, ast.Name):
                got = node.func.id
            else:
                continue
            if got == name:
                return node
    raise AssertionError(f"no call to {name}")


# ---------------------------------------------------------------------------
# module naming + import resolution
# ---------------------------------------------------------------------------

class TestModuleIndex:
    def test_module_name_walks_packages(self, tmp_path):
        d = tmp_path / "pkg" / "sub"
        d.mkdir(parents=True)
        (tmp_path / "pkg" / "__init__.py").write_text("")
        (d / "__init__.py").write_text("")
        (d / "mod.py").write_text("")
        assert module_name_for(str(d / "mod.py")) == "pkg.sub.mod"
        assert module_name_for(str(d / "__init__.py")) == "pkg.sub"

    def test_module_name_outside_package(self, tmp_path):
        p = tmp_path / "script.py"
        p.write_text("")
        assert module_name_for(str(p)) == "script"

    def test_aliased_and_from_imports(self):
        ctx = ctx_of("""
            import jax.numpy as jnp
            import os
            from concurrent.futures import ThreadPoolExecutor as TPE
            from functools import partial
        """)
        mod = Project([ctx]).modules[0]
        assert mod.imports["jnp"] == "jax.numpy"
        assert mod.imports["os"] == "os"
        assert mod.imports["TPE"] == "concurrent.futures.ThreadPoolExecutor"
        assert mod.expand_alias("jnp.asarray") == "jax.numpy.asarray"
        assert mod.expand_alias("partial") == "functools.partial"

    def test_relative_imports_resolve(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "sub").mkdir(parents=True)
        (pkg / "__init__.py").write_text("")
        (pkg / "util.py").write_text("def helper():\n    return 1\n")
        (pkg / "sub" / "__init__.py").write_text("")
        (pkg / "sub" / "mod.py").write_text(
            "from ..util import helper as h\n"
            "from .. import util\n"
            "def go():\n    return h() + util.helper()\n"
        )
        ctxs = []
        for p in [pkg / "util.py", pkg / "sub" / "mod.py"]:
            ctxs.append(Context(p.read_text(), str(p)))
        project = Project(ctxs)
        mod = project.by_name["pkg.sub.mod"]
        assert mod.imports["h"] == "pkg.util.helper"
        assert mod.imports["util"] == "pkg.util"
        # both call forms resolve to the same indexed function
        r1 = project.resolve_call(mod, first_call(mod.ctx, "h"))
        r2 = project.resolve_call(mod, first_call(mod.ctx, "helper"))
        assert r1.kind == "function" and r1.target.qualname == \
            "pkg.util.helper"
        assert r2.kind == "function" and r2.target is r1.target

    def test_module_level_str_constants_indexed(self):
        ctx = ctx_of('DEPTH_ENV = "DASK_ML_TPU_PREFETCH_DEPTH"\nX = 3\n')
        mod = Project([ctx]).modules[0]
        assert mod.str_constants == {
            "DEPTH_ENV": "DASK_ML_TPU_PREFETCH_DEPTH"}


# ---------------------------------------------------------------------------
# call resolution
# ---------------------------------------------------------------------------

class TestCallResolution:
    SRC = """
        import math

        def outer(cb):
            def inner():
                return helper()
            return inner() + cb() + math.sqrt(2) + len("x") + mystery()

        def helper():
            return 1

        class Base:
            def shared(self):
                return 1

        class Est(Base):
            def shared(self):
                return 2

            def run(self):
                return self.shared() + super().shared() + self.ghost()
    """

    @pytest.fixture()
    def proj(self):
        ctx = ctx_of(self.SRC)
        return Project([ctx]), ctx

    def _resolve(self, proj, ctx, name):
        project = proj
        return project.resolve_call(project.modules[0],
                                    first_call(ctx, name))

    def test_kinds(self, proj):
        project, ctx = proj
        assert self._resolve(project, ctx, "inner").kind == "function"
        assert self._resolve(project, ctx, "helper").kind == "function"
        assert self._resolve(project, ctx, "cb").kind == "dynamic"
        assert self._resolve(project, ctx, "sqrt").kind == "external"
        assert self._resolve(project, ctx, "len").kind == "builtin"
        assert self._resolve(project, ctx, "mystery").kind == "unknown"

    def test_self_method_resolves_to_override(self, proj):
        project, ctx = proj
        res = self._resolve(project, ctx, "shared")
        assert res.kind == "function" and res.bound
        assert res.target.qualname.endswith("Est.shared")

    def test_super_resolves_to_base(self, proj):
        project, ctx = proj
        calls = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr == "shared"]
        supers = [c for c in calls if isinstance(c.func.value, ast.Call)]
        res = project.resolve_call(project.modules[0], supers[0])
        assert res.kind == "function"
        assert res.target.qualname.endswith("Base.shared")

    def test_unknown_self_method_is_method_kind(self, proj):
        project, ctx = proj
        assert self._resolve(project, ctx, "ghost").kind == "method"

    def test_reachable_handles_cycles(self):
        ctx = ctx_of("""
            def a():
                return b()

            def b():
                return a()
        """)
        project = Project([ctx])
        mod = project.modules[0]
        names = [fn.name for fn, _ in
                 project.reachable(mod.functions["a"])]
        assert names == ["a", "b"]  # terminates, each visited once

    def test_reaches_collective_through_chain_and_cycle(self):
        ctx = ctx_of("""
            import jax

            def leaf(x):
                return jax.lax.psum(x, "data")

            def mid(x):
                return leaf(x)

            def loopy(x):
                return loopy(x) + mid(x)

            def clean(x):
                return x + 1
        """)
        project = Project([ctx])
        mod = project.modules[0]
        assert project.reaches_collective(mod.functions["mid"])
        assert project.reaches_collective(mod.functions["loopy"])
        assert not project.reaches_collective(mod.functions["clean"])

    def test_key_consuming_params_transitive(self):
        ctx = ctx_of("""
            import jax

            def inner(k):
                return jax.random.normal(k, (3,))

            def outer(data, key):
                return inner(key)

            def fresh(key):
                key, sub = jax.random.split(key)
                return sub
        """)
        project = Project([ctx])
        mod = project.modules[0]
        assert project.key_consuming_params(mod.functions["inner"]) == \
            frozenset({"k"})
        assert project.key_consuming_params(mod.functions["outer"]) == \
            frozenset({"key"})
        # `fresh` consumes its key too (split consumes) — the CALLER's
        # protection is rebinding, which the rule models separately
        assert "key" in project.key_consuming_params(mod.functions["fresh"])


# ---------------------------------------------------------------------------
# dataflow
# ---------------------------------------------------------------------------

class TestDefUse:
    def test_chains_attribute_uses_to_nearest_def(self):
        fn = ast.parse(textwrap.dedent("""
            def f(a):
                x = 1
                y = x + a
                x = 2
                z = x + y
                return z
        """)).body[0]
        du = dataflow.def_use(fn)
        xs = du.defs["x"]
        assert len(xs) == 2
        # first def of x used once (line `y = x + a`), second once
        assert [len(uses) for (_n, _v, uses) in xs] == [1, 1]
        assert len(du.uses_of("a")) == 1
        assert [v.value for v in du.values_of("x")] == [1, 2]

    def test_attribution_is_by_line_not_collection_order(self):
        # BFS collects the top-level line-5 def BEFORE the nested
        # line-3 def; the use on line 6 must still bind to line 5
        fn = ast.parse(textwrap.dedent("""
            def f(c, other):
                if c:
                    pool = make_a()
                pool = other
                return pool.submit
        """)).body[0]
        du = dataflow.def_use(fn)
        entries = du.defs["pool"]
        by_line = {getattr(n, "lineno", 0): uses
                   for (n, _v, uses) in entries}
        assert [len(u) for u in (by_line[4], by_line[5])] == [0, 1]

    def test_unpack_and_with_and_walrus_defs(self):
        fn = ast.parse(textwrap.dedent("""
            def f(snap, mk):
                it, state = snap
                with mk() as fh:
                    data = fh.read()
                if (n := len(data)) > 0:
                    return state, n
        """)).body[0]
        du = dataflow.def_use(fn)
        assert "state" in du.defs and "it" in du.defs
        assert du.unpack_sources("state")  # tuple-unpack recorded
        assert "fh" in du.defs and "n" in du.defs

    def test_nested_function_bodies_excluded(self):
        fn = ast.parse(textwrap.dedent("""
            def f():
                x = 1
                def g():
                    return x
                return g
        """)).body[0]
        du = dataflow.def_use(fn)
        assert du.uses_of("x") == []  # the closure use is g's business

    def test_resolve_dict_keys_through_name_and_call(self):
        ctx = ctx_of("""
            def make():
                return {"a": 1, "b": 2}

            def f():
                d = {"x": 1}
                d = {"y": 2}
                e = make()
                return d, e
        """)
        project = Project([ctx])
        mod = project.modules[0]
        fn = mod.functions["f"].node
        du = dataflow.DefUse(fn)
        ret = [n for n in ast.walk(fn) if isinstance(n, ast.Return)][0]
        d_expr, e_expr = ret.value.elts
        assert dataflow.resolve_dict_keys(d_expr, du, mod, project) == \
            frozenset({"x", "y"})  # union over reassignments
        assert dataflow.resolve_dict_keys(e_expr, du, mod, project) == \
            frozenset({"a", "b"})

    def test_resolve_dict_keys_wildcards(self):
        ctx = ctx_of("""
            def make(ks):
                return {k: 1 for k in ks}

            def f(ks):
                return make(ks)
        """)
        project = Project([ctx])
        mod = project.modules[0]
        fn = mod.functions["f"].node
        ret = [n for n in ast.walk(fn) if isinstance(n, ast.Return)][0]
        assert dataflow.resolve_dict_keys(
            ret.value, dataflow.DefUse(fn), mod, project) is None

    def test_resolve_str_constant_local_and_module(self):
        ctx = ctx_of("""
            KNOB = "DASK_ML_TPU_A"

            def f():
                local = "DASK_ML_TPU_B"
                return local, KNOB
        """)
        mod = Project([ctx]).modules[0]
        fn = mod.functions["f"].node
        du = dataflow.DefUse(fn)
        ret = [n for n in ast.walk(fn) if isinstance(n, ast.Return)][0]
        local_name, knob_name = ret.value.elts
        assert dataflow.resolve_str_constant(local_name, du, mod) == \
            "DASK_ML_TPU_B"
        assert dataflow.resolve_str_constant(knob_name, du, mod) == \
            "DASK_ML_TPU_A"


# ---------------------------------------------------------------------------
# the whole-project cache
# ---------------------------------------------------------------------------

class TestLintCache:
    SRC = """
        import jax

        def sample(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
    """

    def test_warm_hit_and_invalidation(self, tmp_path):
        mod = tmp_path / "mod.py"
        mod.write_text(textwrap.dedent(self.SRC))
        cache = str(tmp_path / "cache.json")
        f1, e1 = lint_paths([str(tmp_path)], cache=cache)
        assert os.path.exists(cache)
        f2, e2 = lint_paths([str(tmp_path)], cache=cache)
        assert [f.render() for f in f2] == [f.render() for f in f1]
        # an edit anywhere invalidates the whole entry
        mod.write_text("x = 1\n")
        f3, _ = lint_paths([str(tmp_path)], cache=cache)
        assert f3 == []

    def test_select_keys_the_digest(self, tmp_path):
        (tmp_path / "mod.py").write_text(textwrap.dedent(self.SRC))
        cache = str(tmp_path / "cache.json")
        full, _ = lint_paths([str(tmp_path)], cache=cache)
        only, _ = lint_paths([str(tmp_path)], select=["host-sync-loop"],
                             cache=cache)
        assert full and not only  # the select run must not reuse full's

    def test_corrupt_cache_is_a_miss_not_a_crash(self, tmp_path):
        (tmp_path / "mod.py").write_text(textwrap.dedent(self.SRC))
        cache = tmp_path / "cache.json"
        cache.write_text("{not json")
        findings, errors = lint_paths([str(tmp_path)], cache=str(cache))
        assert findings and not errors

    def test_cwd_keys_the_digest(self, tmp_path, monkeypatch):
        # findings carry as-given (often cwd-relative) paths: a cache
        # entry warmed from one cwd must not serve another cwd's run
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "mod.py").write_text(textwrap.dedent(self.SRC))
        cache = str(tmp_path / "cache.json")
        monkeypatch.chdir(tmp_path)
        f1, _ = lint_paths(["pkg"], cache=cache)
        monkeypatch.chdir(pkg)
        f2, _ = lint_paths([str(pkg)], cache=cache)
        assert f1 and f2
        # the second run must NOT have inherited the first run's
        # relative path strings
        assert all(os.path.exists(f.path) or os.path.isabs(f.path)
                   for f in f2), [f.path for f in f2]

    def test_env_knob_disables(self, tmp_path, monkeypatch):
        monkeypatch.setenv(glcache.CACHE_ENV, "")
        assert glcache.resolve_cache_path(True, [str(tmp_path)]) is None
        monkeypatch.setenv(glcache.CACHE_ENV, str(tmp_path / "c.json"))
        assert glcache.resolve_cache_path(True, [str(tmp_path)]) == \
            str(tmp_path / "c.json")

    def test_syntax_errors_cached_missing_paths_not(self, tmp_path):
        (tmp_path / "bad.py").write_text("def broken(:\n")
        cache = str(tmp_path / "cache.json")
        _, e1 = lint_paths([str(tmp_path)], cache=cache)
        _, e2 = lint_paths([str(tmp_path)], cache=cache)
        assert e1 == e2 and any("syntax error" in e for e in e1)
        _, e3 = lint_paths([str(tmp_path), "/no/such/dir"], cache=cache)
        assert any("no such file" in e for e in e3)


class TestTimingBudget:
    def test_cold_under_10s_warm_under_2s(self, tmp_path):
        # the acceptance numbers that keep the tier-1 gate negligible:
        # full-package cold < 10 s, warm (digest hit) < 2 s
        cache = str(tmp_path / "cache.json")
        t0 = time.monotonic()
        findings, errors = lint_paths([PKG], cache=cache)
        cold = time.monotonic() - t0
        assert not errors
        t0 = time.monotonic()
        findings2, _ = lint_paths([PKG], cache=cache)
        warm = time.monotonic() - t0
        assert len(findings2) == len(findings)
        assert cold < 10.0, f"cold full-package lint took {cold:.1f}s"
        assert warm < 2.0, f"warm (cached) lint took {warm:.1f}s"


# ---------------------------------------------------------------------------
# CLI exit-code contract: findings=1, crash/bad-args=2
# ---------------------------------------------------------------------------

class TestCliExitCodes:
    def test_findings_exit_one_crash_exit_two(self, tmp_path, capsys,
                                              monkeypatch):
        mod = tmp_path / "mod.py"
        mod.write_text(textwrap.dedent(TestLintCache.SRC))
        assert main([str(mod), "--no-cache"]) == 1
        capsys.readouterr()

        # an analyzer crash must NOT masquerade as a findings verdict
        from dask_ml_tpu.analysis import cli

        def boom(*a, **k):
            raise RuntimeError("engine exploded")

        monkeypatch.setattr(cli, "lint_paths", boom)
        assert cli.main([str(mod), "--no-cache"]) == 2
        err = capsys.readouterr().err
        assert "analyzer crash" in err and "engine exploded" in err

    def test_bad_args_exit_two(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        mod.write_text("x = 1\n")
        assert main([str(mod), "--select", "bogus"]) == 2
        assert main(["/no/such/path/at/all"]) == 2
        assert main([str(mod), "--baseline",
                     str(tmp_path / "missing.json")]) == 2

    def test_baseline_ratchet_flow(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        mod.write_text(textwrap.dedent("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # graftlint: disable=key-reuse -- intentional
                return a + b
        """))
        base = str(tmp_path / "base.json")
        assert main([str(tmp_path), "--write-baseline", base,
                     "--no-cache"]) == 0
        capsys.readouterr()
        # unchanged tree: ratchet passes
        assert main([str(tmp_path), "--baseline", base, "--no-cache"]) == 0
        capsys.readouterr()
        # a NEW suppressed finding still fails the ratchet
        mod.write_text(mod.read_text() + textwrap.dedent("""
            def more(key2):
                c = jax.random.normal(key2, (3,))
                d = jax.random.normal(key2, (3,))  # graftlint: disable=key-reuse -- smuggled debt
                return c + d
        """))
        assert main([str(tmp_path), "--baseline", base, "--no-cache"]) == 1
        out = capsys.readouterr().out
        assert "1 new" in out and "new vs baseline" in out
        # fixing EVERYTHING leaves the baseline stale: also a failure
        mod.write_text("x = 1\n")
        assert main([str(tmp_path), "--baseline", base, "--no-cache"]) == 1
        out = capsys.readouterr().out
        assert "stale" in out and "rebaseline" in out

    def test_scope_mismatch_is_exit_two_not_mass_churn(self, tmp_path,
                                                       capsys):
        # a --select subset (or a different target root) compared
        # against a full-run baseline must refuse loudly, not report
        # every entry stale
        mod = tmp_path / "mod.py"
        mod.write_text(textwrap.dedent(TestLintCache.SRC))
        base = str(tmp_path / "base.json")
        assert main([str(tmp_path), "--write-baseline", base,
                     "--no-cache"]) == 0
        capsys.readouterr()
        assert main([str(tmp_path), "--baseline", base, "--select",
                     "key-reuse", "--no-cache"]) == 2
        assert "different rule set" in capsys.readouterr().err

        from dask_ml_tpu.analysis import baseline as bl

        other = tmp_path / "elsewhere"
        other.mkdir()
        (other / "mod.py").write_text("x = 1\n")
        snap = bl.load(base)
        with pytest.raises(ValueError, match="target root"):
            bl.compare(snap, [], str(other), rules=None)

    def test_new_rule_drift_ratchets_instead_of_refusing(self, tmp_path):
        # registering a NEW rule later must flow through the normal
        # ratchet (new findings → exit 1 → rebaseline), not read as a
        # scope error — only an explicit --select is refused
        from dask_ml_tpu.analysis import baseline as bl

        (tmp_path / "mod.py").write_text("x = 1\n")
        findings, errors = lint_paths([str(tmp_path)])
        root = bl.baseline_root([str(tmp_path)])
        snap = bl.emit(findings, errors, root,
                       rules=["only-the-old-rules"])
        delta = bl.compare(snap, findings, root, rules=None)  # full run
        assert delta == {"new": [], "fixed": []}
        with pytest.raises(ValueError, match="different rule set"):
            bl.compare(snap, findings, root, rules=["key-reuse"])

    def test_write_baseline_wins_over_baseline_flag(self, tmp_path,
                                                    capsys):
        # bootstrap: both flags, no snapshot on disk yet — must WRITE,
        # not die trying to read
        (tmp_path / "mod.py").write_text("x = 1\n")
        base = str(tmp_path / "base.json")
        assert main([str(tmp_path), "--write-baseline", base,
                     "--baseline", base, "--no-cache"]) == 0
        assert os.path.exists(base)

    def test_json_carries_baseline_block(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        mod.write_text("x = 1\n")
        base = str(tmp_path / "base.json")
        assert main([str(tmp_path), "--write-baseline", base,
                     "--no-cache"]) == 0
        capsys.readouterr()
        mod.write_text(textwrap.dedent(TestLintCache.SRC))
        assert main([str(tmp_path), "--baseline", base, "--format",
                     "json", "--no-cache"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["baseline"]["new"][0]["rule"] == "key-reuse"
        assert payload["baseline"]["stale"] == []


# ---------------------------------------------------------------------------
# diagnostics.lint_report: per-rule new/fixed deltas vs baseline
# ---------------------------------------------------------------------------

class TestLintReportDeltas:
    def test_package_report_against_committed_baseline(self):
        from dask_ml_tpu import diagnostics

        report = diagnostics.lint_report()
        assert report["active"] == 0, report
        assert report["baseline"] is not None
        assert report["baseline"]["new"] == 0
        assert report["baseline"]["fixed"] == 0

    def test_explicit_baseline_deltas(self, tmp_path):
        from dask_ml_tpu import diagnostics
        from dask_ml_tpu.analysis import baseline as bl

        mod = tmp_path / "mod.py"
        mod.write_text("x = 1\n")
        findings, errors = lint_paths([str(tmp_path)])
        base = tmp_path / "base.json"
        bl.write(str(base), bl.emit(findings, errors,
                                    bl.baseline_root([str(tmp_path)])))
        mod.write_text(textwrap.dedent(TestLintCache.SRC))
        report = diagnostics.lint_report([str(tmp_path)],
                                         baseline=str(base))
        assert report["active"] == 1
        assert report["baseline"]["new"] == 1
        assert report["baseline"]["per_rule"]["key-reuse"]["new"] == 1

    def test_no_baseline_block_when_none(self, tmp_path):
        from dask_ml_tpu import diagnostics

        (tmp_path / "mod.py").write_text("x = 1\n")
        report = diagnostics.lint_report([str(tmp_path)], baseline=None)
        assert report["baseline"] is None

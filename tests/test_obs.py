"""grafttrace tests (ISSUE 7 tentpole): the span/metrics/flight spine.

Covers the acceptance criteria: a depth-2 streamed SGD fit yields ONE
span tree with pipeline stage children + a retry event from an injected
``FaultPlan`` fault + registry histograms with p50/p99; the Perfetto
export of the same fit is valid ``trace_event`` JSON; tracing enabled
stays within 3% wall of disabled; the JSONL log round-trips through its
schema; the flight recorder leaves a post-mortem for step faults; and
the legacy reporters keep their shapes as registry views.
"""

import io as _io
import json
import math
import threading
import time

import numpy as np
import pytest

from dask_ml_tpu import _partial, diagnostics, obs
from dask_ml_tpu.pipeline import PREFETCH_THREAD_NAME, stream_partial_fit


@pytest.fixture(autouse=True)
def _clean_obs():
    """Book isolation + restore the session-wide arming the conftest set
    up (tests below toggle enable/disable for the A/B)."""
    diagnostics.reset()
    yield
    diagnostics.reset()
    if not obs.enabled():
        obs.enable()


def _tree_names(node, out=None):
    """Flatten a span tree to [(name, thread)], spans and events."""
    if out is None:
        out = []
    out.append((node["name"], node["thread"]))
    for e in node.get("events", ()):
        out.append((e["name"], e["thread"]))
    for c in node.get("children", ()):
        _tree_names(c, out)
    return out


def _collect_nodes(node, out=None):
    """Flatten a span tree to its span-node dicts."""
    if out is None:
        out = []
    out.append(node)
    for c in node.get("children", ()):
        _collect_nodes(c, out)
    return out


class TestMetricsRegistry:
    def test_counter_gauge_basics(self):
        reg = obs.registry()
        reg.counter("t.count").inc()
        reg.counter("t.count").inc(4)
        assert reg.counter("t.count").value == 5
        reg.gauge("t.depth").set(3.5)
        assert reg.gauge("t.depth").value == 3.5

    def test_histogram_quantiles_log_bucketed(self):
        reg = obs.registry()
        h = reg.histogram("t.lat_s")
        for v in range(1, 101):
            h.record(v / 1000.0)  # 1..100 ms
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["min"] == pytest.approx(0.001)
        assert snap["max"] == pytest.approx(0.100)
        # log buckets at 2^(1/4) growth: ~19% relative resolution
        assert snap["p50"] == pytest.approx(0.050, rel=0.25)
        assert snap["p99"] == pytest.approx(0.099, rel=0.25)
        assert snap["p50"] <= snap["p95"] <= snap["p99"] <= snap["max"]

    def test_histogram_single_sample_reports_sample(self):
        h = obs.registry().histogram("t.one")
        h.record(0.42)
        s = h.snapshot()
        assert s["p50"] == pytest.approx(0.42)
        assert s["p99"] == pytest.approx(0.42)

    def test_empty_histogram_nan_quantile(self):
        h = obs.registry().histogram("t.empty")
        assert math.isnan(h.quantile(0.5))
        assert h.snapshot() == {"count": 0}

    def test_tag_families(self):
        reg = obs.registry()
        reg.counter("t.retry", "ingest").inc(2)
        reg.counter("t.retry", "step").inc()
        assert reg.family("t.retry") == {"ingest": 2, "step": 1}
        snap = reg.snapshot()
        assert snap["counters"]["t.retry{ingest}"] == 2

    def test_histogram_concurrent_writers_lose_nothing(self):
        """Two threads recording into one ``pipeline.block_s`` family —
        the shape the serving lane's handler pool will drive — must not
        lose observations or corrupt the running sum: every record is
        one lock acquisition (obs/metrics.py), so count/sum/min/max
        stay exact under contention, including when both writers share
        ONE instrument and when they write sibling tags of a family."""
        reg = obs.registry()
        n = 4000

        def write(tag, value):
            h = reg.histogram("pipeline.block_s", tag)
            for _ in range(n):
                h.record(value)

        # same (name, tag) instrument from both threads
        t1 = threading.Thread(target=write, args=("", 0.001))
        t2 = threading.Thread(target=write, args=("", 0.004))
        t1.start(); t2.start(); t1.join(); t2.join()
        h = reg.histogram("pipeline.block_s")
        assert h.count == 2 * n
        assert h.sum == pytest.approx(n * 0.001 + n * 0.004)
        assert h.min == pytest.approx(0.001)
        assert h.max == pytest.approx(0.004)

        # sibling tags of the same family, created under the race
        t3 = threading.Thread(target=write, args=("lane-a", 0.002))
        t4 = threading.Thread(target=write, args=("lane-b", 0.003))
        t3.start(); t4.start(); t3.join(); t4.join()
        assert reg.histogram("pipeline.block_s", "lane-a").count == n
        assert reg.histogram("pipeline.block_s", "lane-b").count == n
        snap = reg.snapshot()["histograms"]
        assert snap["pipeline.block_s{lane-a}"]["count"] == n

    def test_kind_conflict_raises(self):
        reg = obs.registry()
        reg.counter("t.kind")
        with pytest.raises(ValueError, match="counter"):
            reg.histogram("t.kind")

    def test_prefix_reset(self):
        reg = obs.registry()
        reg.counter("a.x").inc()
        reg.counter("b.x").inc()
        reg.reset(prefix="a.")
        assert reg.family("a.x") == {}
        assert reg.counter("b.x").value == 1


class TestSpans:
    def test_nesting_and_events(self):
        with obs.span("fit"):
            with obs.span("round", round=1):
                obs.event("mark", k="v")
        tree = obs.span_tree()
        assert tree["name"] == "fit"
        (child,) = tree["children"]
        assert child["name"] == "round"
        assert child["attrs"] == {"round": 1}
        (ev,) = child["events"]
        assert ev["name"] == "mark" and ev["attrs"] == {"k": "v"}

    def test_detached_span_skips_stack(self):
        with obs.span("outer") as outer:
            with obs.span("async_scope", parent=outer.span_id,
                          detached=True):
                # a detached span must NOT become the implicit parent
                assert obs.current_span_id() == outer.span_id
        tree = obs.span_tree()
        assert [c["name"] for c in tree["children"]] == ["async_scope"]

    def test_adopt_stitches_worker_thread(self):
        with obs.span("owner") as owner:
            pid = owner.span_id

            def work():
                with obs.adopt(pid):
                    with obs.span("worker_side"):
                        obs.event("worker_event")

            t = threading.Thread(target=work, name="test-worker")
            t.start()
            t.join()
        tree = obs.span_tree()
        names = _tree_names(tree)
        assert ("worker_side", "test-worker") in names
        assert ("worker_event", "test-worker") in names

    def test_open_span_paths_distinguishes_same_named_threads(self):
        """Concurrent same-named workers (a pool search's prefetch
        threads all share PREFETCH_THREAD_NAME) must each show their
        own open-span path in a hang dump."""
        release = threading.Event()
        ready = []

        def work(tag):
            with obs.span(f"inflight_{tag}"):
                ready.append(tag)
                release.wait(5.0)

        threads = [threading.Thread(target=work, args=(i,),
                                    name="same-name") for i in range(2)]
        for t in threads:
            t.start()
        while len(ready) < 2:
            time.sleep(0.005)
        try:
            paths = obs.open_span_paths()
            inflight = sorted(p for p in paths.values()
                              if p.startswith("inflight_"))
            assert inflight == ["inflight_0", "inflight_1"], paths
            assert all(k.startswith("same-name#") for k in paths
                       if paths[k].startswith("inflight_")), paths
        finally:
            release.set()
            for t in threads:
                t.join()

    def test_disabled_is_noop(self):
        obs.disable()
        try:
            with obs.span("ghost"):
                obs.event("ghost_event")
            assert obs.last_root() is None
            assert obs.span_tree() is None
        finally:
            obs.enable()
        # the event still reached the always-on flight recorder
        assert any(e["name"] == "ghost_event" for e in obs.flight_tail())

    def test_error_recorded_on_span(self):
        with pytest.raises(ValueError):
            with obs.span("failing"):
                raise ValueError("boom")
        tree = obs.span_tree()
        assert tree["name"] == "failing"
        assert "ValueError: boom" in tree["error"]

    def test_clear_spans_drops_records(self):
        with obs.span("gone"):
            pass
        assert obs.last_root() is not None
        obs.clear_spans()
        assert obs.last_root() is None
        assert obs.span_records() == []


def _block_stream(rng, n_blocks=6, rows=64, d=5, parse_s=0.0):
    w = rng.normal(size=d)
    for _ in range(n_blocks):
        if parse_s:
            time.sleep(parse_s)
        X = rng.normal(size=(rows, d)).astype(np.float32)
        yield X, (X @ w > 0).astype(np.int32)


class TestRunReportAcceptance:
    def test_streamed_sgd_fit_single_tree_with_retry_and_quantiles(
            self, tmp_path, rng):
        """Acceptance criterion: run_report() on a depth-2 streamed SGD
        fit = ONE span tree with pipeline stage children, >=1 retry
        event from an injected FaultPlan ingest fault, and registry
        histograms with p50/p99; the Perfetto export of the same fit
        loads as valid trace_event JSON."""
        from dask_ml_tpu import io as dio
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.resilience.testing import FaultPlan, fault_plan

        X = rng.normal(size=(500, 5)).astype(np.float32)
        p = tmp_path / "rows.bin"
        X.tofile(p)

        def blocks():
            for xb in dio.stream_binary_blocks(str(p), 100, 5, retries=2):
                yield xb, (xb[:, 0] > 0).astype(np.int32)

        clf = SGDClassifier(random_state=0)
        plan = FaultPlan()
        plan.inject("ingest", at_call=2, times=1)
        with fault_plan(plan):
            _partial.fit(clf, blocks(), prefetch_depth=2,
                         classes=[0, 1])
        assert plan.fired["ingest"] == 1

        rep = diagnostics.run_report()
        tree = rep["span_tree"]
        assert tree["name"] == "fit"
        names = [n for n, _ in _tree_names(tree)]
        for stage in ("pipeline.stream", "pipeline.parse",
                      "pipeline.stage", "pipeline.compute"):
            assert stage in names, f"missing {stage} in {sorted(set(names))}"
        # the absorbed ingest fault left its retry event IN the tree
        assert "resilience.retry" in names
        # registry histograms carry p50/p99
        hist = rep["metrics"]["histograms"]["pipeline.block_s"]
        assert hist["count"] == 5
        assert hist["p50"] > 0 and hist["p99"] >= hist["p50"]
        # legacy reporters unchanged shape, same store
        assert rep["pipeline"]["streams"] == 1
        assert rep["faults"]["retries"]["ingest"] == 1

        # Perfetto export of the same fit: valid trace_event JSON
        out = tmp_path / "trace.json"
        obs.export_perfetto(str(out))
        with open(out) as f:
            trace = json.load(f)
        events = trace["traceEvents"]
        assert events, "empty perfetto export"
        for e in events:
            assert {"name", "ph", "pid", "tid"} <= set(e)
            if e["ph"] == "X":
                assert e["dur"] >= 0 and e["ts"] >= 0
        assert any(e.get("name") == "pipeline.stream" for e in events)
        # one tid lane per recorded thread, with thread-name metadata
        assert any(e["ph"] == "M" and e["args"]["name"]
                   == PREFETCH_THREAD_NAME for e in events)


class TestStitching:
    def test_prefetch_worker_spans_inside_stream_tree(self, rng):
        """Acceptance: the prefetch worker's parse/stage spans stitch
        into the consumer's stream span (thread-adoption rule)."""

        class Sink:
            def partial_fit(self, X, y=None):
                time.sleep(0.001)

        stream_partial_fit(Sink(), _block_stream(rng), depth=2)
        tree = obs.span_tree()
        assert tree["name"] == "pipeline.stream"
        names = _tree_names(tree)
        assert ("pipeline.parse", PREFETCH_THREAD_NAME) in names
        assert ("pipeline.stage", PREFETCH_THREAD_NAME) in names
        assert ("pipeline.compute", "MainThread") in names

    def test_healthy_stream_has_no_error_spans(self, rng):
        """StopIteration ends every stream through the parse span —
        control flow, not a failure: no span of a clean fit may carry
        an error flag (post-mortem filters key on it)."""

        class Sink:
            def partial_fit(self, X, y=None):
                pass

        for depth in (0, 2):
            diagnostics.reset()
            stream_partial_fit(Sink(), _block_stream(rng), depth=depth)
            errors = [n for n in _collect_nodes(obs.span_tree())
                      if n.get("error")]
            assert errors == [], f"depth={depth}: {errors}"

    def test_depth0_stages_on_consumer_thread(self, rng):
        class Sink:
            def partial_fit(self, X, y=None):
                pass

        stream_partial_fit(Sink(), _block_stream(rng), depth=0)
        names = _tree_names(obs.span_tree())
        assert ("pipeline.parse", "MainThread") in names
        assert (("pipeline.parse", PREFETCH_THREAD_NAME)) not in names


class TestJsonlExport:
    def test_round_trip_schema(self, tmp_path, rng):
        path = str(tmp_path / "trace.jsonl")
        obs.disable()
        obs.enable(jsonl_path=path)
        try:
            with obs.span("fit", estimator="X"):
                obs.event("mark", k=1)
        finally:
            obs.disable()
            obs.enable()
        header, records = obs.read_jsonl(path)
        assert header["schema"] == "grafttrace"
        assert header["version"] == obs.SCHEMA_VERSION
        assert {"pid", "unix_time", "perf_counter"} <= set(header)
        kinds = {(r["kind"], r["name"]) for r in records}
        assert ("span", "fit") in kinds and ("event", "mark") in kinds
        for r in records:
            assert {"kind", "span_id", "name", "t0", "t1",
                    "dur_s", "thread"} <= set(r)

    def test_newer_schema_rejected(self, tmp_path):
        path = tmp_path / "future.jsonl"
        path.write_text(json.dumps(
            {"schema": "grafttrace",
             "version": obs.SCHEMA_VERSION + 1}) + "\n")
        with pytest.raises(ValueError, match="newer"):
            obs.read_jsonl(str(path))

    def test_torn_final_line_tolerated(self, tmp_path):
        """kill -9 mid-write leaves a partial last line: the intact
        records must still read back (the crash-forensics contract);
        a torn line ANYWHERE else is corruption and raises."""
        path = str(tmp_path / "torn.jsonl")
        obs.disable()
        obs.enable(jsonl_path=path)
        try:
            with obs.span("kept"):
                pass
        finally:
            obs.disable()
            obs.enable()
        with open(path, "a") as f:
            f.write('{"kind":"span","na')  # the torn tail
        _, records = obs.read_jsonl(path)
        assert [r["name"] for r in records] == ["kept"]
        # mid-file corruption is NOT forgiven
        bad = tmp_path / "mid.jsonl"
        bad.write_text(
            json.dumps({"schema": "grafttrace",
                        "version": obs.SCHEMA_VERSION}) + "\n"
            + '{"torn\n'
            + '{"kind":"event","span_id":1,"parent_id":null,'
              '"name":"x","t0":0,"t1":0,"dur_s":0,"thread":"t"}\n')
        with pytest.raises(ValueError, match="malformed record"):
            obs.read_jsonl(str(bad))

    def test_failed_rearm_keeps_working_sink(self, tmp_path):
        """enable() onto an unwritable path must raise WITHOUT
        destroying the sink that was already streaming."""
        good = str(tmp_path / "good.jsonl")
        obs.disable()
        obs.enable(jsonl_path=good)
        try:
            with pytest.raises(OSError):
                obs.enable(
                    jsonl_path=str(tmp_path / ("x" * 300) / "t.jsonl"))
            with obs.span("still_recorded"):
                pass
        finally:
            obs.disable()
            obs.enable()
        _, records = obs.read_jsonl(good)
        assert any(r["name"] == "still_recorded" for r in records)

    def test_bad_env_trace_path_degrades_to_ring_only(self):
        """An unwritable DASK_ML_TPU_TRACE must not kill the import of
        the traced job: arming degrades to ring-only with a warning
        (the explicit enable(jsonl_path=...) API still raises)."""
        import os
        import subprocess
        import sys

        env = dict(os.environ)
        env["DASK_ML_TPU_TRACE"] = "/proc/nonexistent-dir/t.jsonl"
        r = subprocess.run(
            [sys.executable, "-c",
             "from dask_ml_tpu import obs; "
             "assert obs.enabled(); print('ring-only ok')"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr[-800:]
        assert "ring-only ok" in r.stdout

    def test_not_a_trace_rejected(self, tmp_path):
        path = tmp_path / "junk.jsonl"
        path.write_text('{"hello": 1}\n')
        with pytest.raises(ValueError, match="grafttrace"):
            obs.read_jsonl(str(path))

    def test_multi_session_append_round_trips(self, tmp_path):
        """The sink appends: two sessions on one path (the documented
        multi-process DASK_ML_TPU_TRACE usage) leave two header lines —
        both validated, neither returned as a record, and the combined
        records still render as Perfetto."""
        path = str(tmp_path / "two.jsonl")
        for session in range(2):
            obs.disable()
            obs.enable(jsonl_path=path)
            try:
                with obs.span(f"session{session}"):
                    pass
            finally:
                obs.disable()
        obs.enable()
        header, records = obs.read_jsonl(path)
        assert header["schema"] == "grafttrace"
        names = [r["name"] for r in records]
        assert names == ["session0", "session1"]
        assert all("schema" not in r for r in records)
        trace = obs.perfetto_trace(records)  # must not KeyError
        assert len([e for e in trace["traceEvents"]
                    if e["ph"] == "X"]) == 2

    def test_perfetto_from_jsonl_records(self, tmp_path):
        """A trace re-renders offline from the JSONL alone (dict-form
        records accepted)."""
        path = str(tmp_path / "t.jsonl")
        obs.disable()
        obs.enable(jsonl_path=path)
        try:
            with obs.span("offline"):
                pass
        finally:
            obs.disable()
            obs.enable()
        _, records = obs.read_jsonl(path)
        trace = obs.perfetto_trace(records)
        assert any(e["name"] == "offline" for e in trace["traceEvents"])


class TestFlightRecorder:
    def test_step_fault_leaves_post_mortem(self, rng):
        """Satellite acceptance: an injected FaultPlan step fault leaves
        the failed block position in the flight recorder."""
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.resilience.testing import (
            FaultInjected, FaultPlan, fault_plan,
        )

        X = rng.normal(size=(600, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        clf = SGDClassifier(random_state=0)
        plan = FaultPlan()
        plan.inject("step", at_call=3, times=1)
        with fault_plan(plan):
            with pytest.raises(FaultInjected):
                _partial.fit(clf, X, y, chunk_size=100,
                             prefetch_depth=2, classes=[0, 1])
        faults = [e for e in obs.flight_tail()
                  if e["name"] == "pipeline.fault"]
        assert faults, "stream fault left no flight event"
        assert faults[-1]["attrs"]["block"] == 2  # blocks 1-2 consumed
        text = obs.flight_post_mortem("test")
        assert "pipeline.fault" in text and "FaultInjected" in text

    def test_dump_shows_open_span_path(self):
        """The watchdog half: a dump taken MID-fit names the open span
        path (which block/round was in flight), not just events."""
        buf = _io.StringIO()
        with obs.span("fit"):
            with obs.span("pipeline.stream"):
                obs.flight_dump(reason="watchdog-test", file=buf)
        out = buf.getvalue()
        assert "watchdog-test" in out
        assert "fit > pipeline.stream" in out

    def test_dump_never_raises(self):
        class Exploding:
            def write(self, *_a, **_k):
                raise OSError("sink died")

            def flush(self):
                raise OSError("sink died")

        obs.flight_dump(file=Exploding())  # must not raise

    def test_tail_bounded(self):
        from dask_ml_tpu.obs import flight

        for i in range(flight.FLIGHT_SIZE + 50):
            obs.event("spam", i=i)
        tail = obs.flight_tail()
        assert len(tail) == flight.FLIGHT_SIZE
        assert tail[-1]["attrs"]["i"] == flight.FLIGHT_SIZE + 49


class TestOverheadAB:
    def test_traced_streamed_fit_within_3pct(self, rng):
        """Acceptance criterion: a depth-2 streamed SGD fit with tracing
        enabled stays within 3% wall of tracing disabled.

        The stream wall is pinned by deterministic reader sleeps (the
        pipeline hides compute behind them), so the ratio isolates the
        per-block span/registry cost instead of XLA dispatch noise, and
        the wall is long enough that 3% is an order of magnitude above
        sleep/scheduler jitter.

        Estimator: the MEDIAN OF PAIRED PER-ROUND RATIOS.  Each round
        runs both arms back to back (order alternating to cancel any
        systematic first-runner bias) and contributes one on/off ratio;
        the verdict is the median over rounds.  This replaces the
        best-of-6 per-arm wall comparison, whose min statistic needed
        ONE clean scheduling draw per arm — under sustained scheduler
        starvation on the 2-core CI box one arm sometimes never got
        one (tripped again in the PR-9 full run).  A starvation burst
        now lands on both halves of the SAME round (ratio ≈ unaffected)
        or skews at most that round's ratio, and the median tolerates
        up to two bad rounds in either direction out of six.  The 3%
        threshold itself is unchanged.
        """
        import statistics

        from dask_ml_tpu.linear_model import SGDClassifier

        n_blocks, parse_s = 30, 0.008  # wall ~0.25 s; 3% >> timer noise
        X0 = rng.normal(size=(128, 5)).astype(np.float32)
        w = rng.normal(size=5)

        def blocks():
            for _ in range(n_blocks):
                time.sleep(parse_s)
                yield X0, (X0 @ w > 0).astype(np.int32)

        def one_fit():
            clf = SGDClassifier(random_state=0)
            t0 = time.perf_counter()
            _partial.fit(clf, blocks(), prefetch_depth=2,
                         classes=[0, 1])
            return time.perf_counter() - t0

        def one_arm(arm):
            if arm == "off":
                obs.disable()
                try:
                    return one_fit()
                finally:
                    obs.enable()
            return one_fit()

        one_fit()  # warm the XLA cache outside both arms

        ratios, raw = [], []
        for i in range(6):
            order = ("off", "on") if i % 2 == 0 else ("on", "off")
            walls = {arm: one_arm(arm) for arm in order}
            ratios.append(walls["on"] / walls["off"])
            raw.append(walls)
        med = statistics.median(ratios)
        assert med <= 1.03, (
            f"tracing overhead {med - 1:.2%} (median of paired ratios "
            f"{[round(r, 4) for r in sorted(ratios)]}, raw={raw})"
        )


class TestLegacyReportersAreViews:
    def test_fault_stats_backed_by_registry(self):
        from dask_ml_tpu.resilience.retry import retry

        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert retry(flaky, retries=3, backoff=0.0, jitter=0.0,
                     tag="obs-test") == "ok"
        snap = diagnostics.fault_stats().snapshot()
        assert snap["faults"]["obs-test"] == 2
        assert snap["retries"]["obs-test"] == 2
        # the SAME counters in the registry (view, not copy)
        assert obs.registry().family("resilience.retry") == {"obs-test": 2}
        assert obs.registry().family("resilience.fault") == {"obs-test": 2}

    def test_private_fault_stats_stay_private(self):
        from dask_ml_tpu.resilience.retry import FaultStats

        private = FaultStats()
        private.record_fault("mine")
        assert private.faults["mine"] == 1
        assert private.total("faults") == 1
        assert obs.registry().family("resilience.fault") == {}
        private.reset()
        assert private.total("faults") == 0

    def test_pipeline_cumulative_is_registry_view(self, rng):
        class Sink:
            def partial_fit(self, X, y=None):
                pass

        stream_partial_fit(Sink(), _block_stream(rng, n_blocks=4),
                           depth=0)
        stream_partial_fit(Sink(), _block_stream(rng, n_blocks=4),
                           depth=0)
        rep = diagnostics.pipeline_report()
        assert rep["streams"] == 2
        assert rep["cumulative"]["blocks"] == 8
        assert obs.registry().counter("pipeline.streams").value == 2
        hist = obs.registry().histogram("pipeline.wall_s")
        assert hist.count == 2

    def test_diagnostics_reset_clears_everything(self, rng):
        class Sink:
            def partial_fit(self, X, y=None):
                pass

        stream_partial_fit(Sink(), _block_stream(rng, n_blocks=2),
                           depth=0)
        diagnostics.fault_stats().record_fault("x")
        obs.event("e")
        diagnostics.reset()
        assert diagnostics.pipeline_report() == {"streams": 0}
        assert diagnostics.fault_stats().snapshot()["faults"] == {}
        assert obs.metrics_snapshot() == {
            "counters": {}, "gauges": {}, "histograms": {}}
        assert obs.flight_tail() == []
        assert obs.span_tree() is None


class TestTraceExceptionSafety:
    def test_failed_start_does_not_mask_error(self, monkeypatch):
        """Satellite: if start_trace raises, the REAL error propagates
        and stop_trace is never called on a never-started trace."""
        import jax

        stopped = {"n": 0}

        def bad_start(_dir):
            raise RuntimeError("trace dir unwritable")

        monkeypatch.setattr(jax.profiler, "start_trace", bad_start)
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: stopped.__setitem__("n",
                                                        stopped["n"] + 1))
        with pytest.raises(RuntimeError, match="trace dir unwritable"):
            with diagnostics.trace("/nonexistent"):
                pass  # pragma: no cover - never reached
        assert stopped["n"] == 0

    def test_stop_runs_on_body_failure(self, monkeypatch):
        import jax

        calls = []
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: calls.append("start"))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: calls.append("stop"))
        with pytest.raises(ValueError):
            with diagnostics.trace("/tmp/x"):
                raise ValueError("body failed")
        assert calls == ["start", "stop"]

"""Out-of-core text + streaming ingestion (VERDICT round-1 item 10): lazy
corpus chunking (no list(seq)), streaming vectorizer blocks, and the
end-to-end file -> vectorizer -> device-native SGD pipeline."""

import numpy as np
import pytest

import jax

from dask_ml_tpu import io as dio
from dask_ml_tpu.feature_extraction.text import (
    CountVectorizer,
    HashingVectorizer,
    densify_to_device,
)
from dask_ml_tpu.linear_model import SGDClassifier


class CountingIter:
    """A one-shot document iterator that records peak simultaneous
    materialization (would be len(corpus) if anything list()'d it)."""

    def __init__(self, docs):
        self._docs = list(docs)
        self.yielded = 0

    def __iter__(self):
        for d in self._docs:
            self.yielded += 1
            yield d


class TestLazyChunking:
    def test_chunks_is_lazy(self):
        from dask_ml_tpu.feature_extraction.text import _chunks

        def gen():
            for i in range(100):
                yield f"doc {i}"

        it = _chunks(gen(), 10)
        first = next(it)
        assert len(first) == 10  # only one chunk pulled so far

    def test_hashing_transform_accepts_generator(self):
        docs = [f"word{i % 7} common text" for i in range(500)]
        hv = HashingVectorizer(n_features=64)
        out_gen = hv.transform(iter(docs))
        out_list = hv.transform(docs)
        assert (out_gen != out_list).nnz == 0

    def test_count_fit_accepts_generator(self):
        docs = ["apple banana", "banana cherry", "apple apple"] * 50
        cv_gen = CountVectorizer().fit(iter(docs))
        cv_list = CountVectorizer().fit(docs)
        assert cv_gen.vocabulary_ == cv_list.vocabulary_

    def test_count_min_df_fraction_with_generator(self):
        # n_docs must be counted during the streaming pass
        docs = ["rare word"] + ["common text"] * 99
        cv = CountVectorizer(min_df=0.5).fit(iter(docs))
        assert set(cv.vocabulary_) == {"common", "text"}

    def test_stream_transform_blocks(self):
        docs = [f"tok{i % 5} filler" for i in range(250)]
        hv = HashingVectorizer(n_features=32)
        hv.chunk_size = 100
        blocks = list(hv.stream_transform(iter(docs)))
        assert [b.shape[0] for b in blocks] == [100, 100, 50]
        import scipy.sparse

        np.testing.assert_allclose(
            scipy.sparse.vstack(blocks).toarray(), hv.transform(docs).toarray()
        )

    def test_count_stream_transform(self):
        docs = ["apple banana", "banana cherry"] * 60
        cv = CountVectorizer().fit(docs)
        cv.chunk_size = 50
        blocks = list(cv.stream_transform(iter(docs)))
        import scipy.sparse

        np.testing.assert_allclose(
            scipy.sparse.vstack(blocks).toarray(), cv.transform(docs).toarray()
        )


class TestEndToEndStreaming:
    def test_text_file_to_device_sgd(self, tmp_path, rng, mesh):
        # file -> stream_text_lines -> HashingVectorizer.stream_transform
        # -> densify -> device-native SGD partial_fit: the full out-of-core
        # text pipeline, with labels derived per line
        n = 2000
        lines, labels = [], []
        for i in range(n):
            if rng.rand() > 0.5:
                lines.append("good great excellent fine product")
                labels.append(1)
            else:
                lines.append("bad awful poor terrible product")
                labels.append(0)
        p = tmp_path / "docs.txt"
        p.write_text("\n".join(lines) + "\n")
        labels = np.asarray(labels)

        hv = HashingVectorizer(n_features=128)
        clf = SGDClassifier(learning_rate="constant", eta0=0.5)
        offset = 0
        for _ in range(3):  # epochs over the stream
            offset = 0
            for block_lines in dio.stream_text_lines(str(p), block_lines=256):
                Xb = np.asarray(hv.transform(block_lines).todense(), np.float32)
                yb = labels[offset: offset + len(block_lines)]
                offset += len(block_lines)
                clf.partial_fit(Xb, yb, classes=[0, 1])
        assert offset == n
        X_all = np.asarray(hv.transform(lines).todense(), np.float32)
        assert (clf.predict(X_all) == labels).mean() > 0.99
        assert isinstance(clf._state["coef"], jax.Array)

    def test_csv_stream_to_sgd_regressor(self, tmp_path, rng, mesh):
        # numeric side: stream_csv_blocks -> device SGD partial_fit
        from dask_ml_tpu.linear_model import SGDRegressor

        n, d = 3000, 6
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32)
        y = X @ w
        p = tmp_path / "data.csv"
        np.savetxt(p, np.column_stack([X, y]), delimiter=",", fmt="%.6f")

        reg = SGDRegressor(learning_rate="constant", eta0=0.1)
        for _ in range(15):
            for block in dio.stream_csv_blocks(str(p), block_rows=512):
                reg.partial_fit(block[:, :d], block[:, d])
        assert reg.score(X, y) > 0.98

    def test_densify_to_device_sharded(self, rng, mesh):
        import scipy.sparse

        from dask_ml_tpu.core import ShardedRows

        S = scipy.sparse.random(37, 8, density=0.3, random_state=0, format="csr")
        out = densify_to_device(S)
        assert isinstance(out, ShardedRows)
        np.testing.assert_allclose(
            np.asarray(out.unpad()), S.toarray(), rtol=1e-6
        )


class TestReviewRegressions:
    def test_stream_transform_fixed_vocab_unfitted(self):
        cv = CountVectorizer(vocabulary={"apple": 0, "banana": 1})
        blocks = list(cv.stream_transform(["apple banana", "banana"]))
        assert blocks[0].shape == (2, 2)

    def test_fit_transform_fixed_vocab_streams(self):
        # one-shot generator + fixed vocabulary: single pass, no list()
        cv = CountVectorizer(vocabulary={"apple": 0, "banana": 1})
        out = cv.fit_transform(d for d in ["apple", "banana banana"])
        np.testing.assert_allclose(out.toarray(), [[1, 0], [0, 2]])

    def test_multinomial_is_implemented(self, rng):
        # round 2 warned-and-fell-back to OvR; round 3 implements the true
        # softmax family, so the fit must succeed with NO warning
        import warnings

        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(90, 3)).astype(np.float32)
        y = rng.randint(0, 3, size=90)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            lr = LogisticRegression(
                solver="lbfgs", max_iter=5, multi_class="multinomial"
            ).fit(X, y)
        assert lr.betas_.shape[0] == 3

    def test_dates_seed_does_not_alias_chunk_seed(self):
        from dask_ml_tpu.datasets import make_classification_df

        a, _ = make_classification_df(
            n_samples=60, n_features=5, chunks=30, random_state=3
        )
        b, _ = make_classification_df(
            n_samples=60, n_features=5, chunks=30, random_state=3,
            dates=("2024-01-01", "2024-02-01"),
        )
        # feature data identical whether or not dates are requested
        np.testing.assert_allclose(
            a.to_numpy(), b.drop(columns="date").to_numpy()
        )


class TestStreamedClustering:
    def test_file_to_device_minibatch_kmeans(self, tmp_path, rng):
        """Out-of-core clustering: CSV -> native prefetched blocks ->
        device-resident MiniBatchKMeans partial_fit (the reference's
        Incremental(sklearn.MiniBatchKMeans) streaming pattern, with the
        model on device instead of hopping hosts)."""
        from sklearn.datasets import make_blobs
        from sklearn.metrics import adjusted_rand_score

        from dask_ml_tpu.cluster import MiniBatchKMeans

        X, y = make_blobs(n_samples=3000, centers=4, n_features=6,
                          cluster_std=0.5, random_state=2)
        p = tmp_path / "blobs.csv"
        np.savetxt(p, X.astype(np.float32), delimiter=",", fmt="%.6f")

        mbk = MiniBatchKMeans(n_clusters=4, random_state=0)
        for block in dio.stream_csv_blocks(str(p), 512, prefetch=2):
            mbk.partial_fit(block)
        pred = np.asarray(mbk.predict(X.astype(np.float32)))
        assert adjusted_rand_score(y, pred) > 0.95


class TestStreamedBlocksFit:
    """SURVEY §7 hard-part (b): a stream larger than device memory fits
    through partial_fit with only one live block (bench.py's streamed_sgd
    workload runs this same path at >HBM scale on chip)."""

    def test_stream_fit_accuracy_and_laziness(self, mesh):
        from dask_ml_tpu.datasets import stream_classification_blocks
        from dask_ml_tpu.linear_model import SGDClassifier

        gen = stream_classification_blocks(6, 4096, 8, seed=0)
        import types

        assert isinstance(gen, types.GeneratorType)  # lazy, block-at-a-time
        clf = SGDClassifier(random_state=0)
        total_rows = 0
        for Xb, yb in gen:
            clf.partial_fit(Xb, yb, classes=[0.0, 1.0])
            total_rows += Xb.n_samples
        assert total_rows == 6 * 4096
        # held-out generalization: block index 6 shares the stream's true
        # coefficient (same seed) but was never trained on (fold_in(key,6))
        Xt, yt = list(stream_classification_blocks(7, 4096, 8, seed=0))[-1]
        import numpy as np

        acc = (np.asarray(clf.predict(Xt))[:4096]
               == np.asarray(yt.data)).mean()
        assert acc > 0.8

    def test_blocks_differ_across_stream(self, mesh):
        from dask_ml_tpu.datasets import stream_classification_blocks
        import numpy as np

        b = list(stream_classification_blocks(2, 256, 4, seed=1))
        assert not np.allclose(
            np.asarray(b[0][0].data), np.asarray(b[1][0].data)
        )


class TestKitchenSinkPipeline:
    """The realistic dask-ml user journey end to end: pandas DataFrame →
    Categorizer → DummyEncoder → StandardScaler → LogisticRegression,
    searched with GridSearchCV — every stage a dask_ml_tpu component."""

    def test_dataframe_to_glm_grid_search(self, rng):
        import pandas as pd
        from sklearn.pipeline import Pipeline

        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.model_selection import GridSearchCV
        from dask_ml_tpu.preprocessing import (
            Categorizer,
            DummyEncoder,
            StandardScaler,
        )

        n = 400
        city = rng.choice(["nyc", "sf", "tok"], size=n)
        xnum = rng.normal(size=n).astype(np.float32)
        # signal: city=sf shifts the decision strongly
        logits = 2.0 * xnum + 3.0 * (city == "sf") - 1.0
        y = (logits + 0.3 * rng.normal(size=n) > 0).astype(int)
        df = pd.DataFrame({"city": city, "xnum": xnum})

        class ToFloat32:
            """pandas → float32 array at the device boundary."""

            def fit(self, X, y=None):
                return self

            def transform(self, X):
                return np.asarray(X, dtype=np.float32)

            def fit_transform(self, X, y=None):
                return self.transform(X)

            def get_params(self, deep=True):
                return {}

            def set_params(self, **kw):
                return self

        pipe = Pipeline([
            ("cat", Categorizer()),
            ("dum", DummyEncoder()),
            ("asf", ToFloat32()),
            ("sc", StandardScaler()),
            ("clf", LogisticRegression(max_iter=60)),
        ])
        gs = GridSearchCV(pipe, {"clf__C": [0.1, 1.0, 10.0]}, cv=3).fit(df, y)
        assert gs.best_score_ > 0.85
        pred = np.asarray(gs.predict(df))
        assert (pred == y).mean() > 0.85

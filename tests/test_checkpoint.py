"""Checkpoint/resume subsystem tests (SURVEY.md §5: absent in the
reference; designed in here as the fault-recovery story)."""

import numpy as np
import pytest

from dask_ml_tpu.checkpoint import SearchCheckpoint, load_estimator, save_estimator
from dask_ml_tpu.core import shard_rows, unshard
from dask_ml_tpu.model_selection import (
    HyperbandSearchCV,
    IncrementalSearchCV,
    SuccessiveHalvingSearchCV,
)
from dask_ml_tpu.model_selection.utils_test import LinearFunction


class TestEstimatorSaveLoad:
    def test_kmeans_roundtrip(self, tmp_path, rng):
        from dask_ml_tpu.cluster import KMeans

        X = rng.normal(size=(200, 4)).astype(np.float32)
        X[:100] += 5
        km = KMeans(n_clusters=2, random_state=0).fit(X)
        save_estimator(km, str(tmp_path / "km"))
        restored = load_estimator(str(tmp_path / "km"))
        np.testing.assert_allclose(
            np.asarray(km.cluster_centers_),
            np.asarray(restored.cluster_centers_),
            rtol=1e-6,
        )
        np.testing.assert_array_equal(
            np.asarray(km.predict(X)), np.asarray(restored.predict(X))
        )
        assert restored.get_params() == km.get_params()

    def test_scaler_roundtrip(self, tmp_path, rng):
        from dask_ml_tpu.preprocessing import StandardScaler

        X = rng.normal(size=(64, 3)).astype(np.float32) * 4 + 2
        sc = StandardScaler().fit(X)
        save_estimator(sc, str(tmp_path / "sc"))
        restored = load_estimator(str(tmp_path / "sc"))
        np.testing.assert_allclose(
            unshard(restored.transform(X)), unshard(sc.transform(X)), rtol=1e-6
        )

    def test_glm_roundtrip(self, tmp_path, rng):
        from dask_ml_tpu.linear_model import LogisticRegression

        X = rng.normal(size=(80, 4)).astype(np.float32)
        y = (X @ rng.normal(size=4) > 0).astype(np.float32)
        lr = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
        save_estimator(lr, str(tmp_path / "lr"))
        restored = load_estimator(str(tmp_path / "lr"))
        np.testing.assert_allclose(
            np.asarray(restored.coef_), np.asarray(lr.coef_), rtol=1e-6
        )
        np.testing.assert_array_equal(
            np.asarray(restored.predict(X)), np.asarray(lr.predict(X))
        )

    def test_sharded_attr_roundtrip(self, tmp_path, rng, mesh):
        # an estimator holding a ShardedRows fitted attr must restore it
        # as a re-sharded array on the active mesh
        from dask_ml_tpu.preprocessing import StandardScaler

        sc = StandardScaler()
        X = rng.normal(size=(30, 2)).astype(np.float32)
        sc.fit(X)
        sc.debug_rows_ = shard_rows(X)
        save_estimator(sc, str(tmp_path / "s"))
        restored = load_estimator(str(tmp_path / "s"))
        from dask_ml_tpu.core.sharded import ShardedRows

        assert isinstance(restored.debug_rows_, ShardedRows)
        np.testing.assert_allclose(unshard(restored.debug_rows_), X)


def _xy(rng, n=64, d=3):
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.float32)
    return X, y


class TestSearchCheckpoint:
    def test_resume_after_crash(self, tmp_path, rng):
        """Kill the search mid-flight; a re-fit resumes from the snapshot
        instead of restarting, and reaches the same result."""
        X, y = _xy(rng)
        path = str(tmp_path / "search.pkl")
        params = {"slope": [0.1, 0.5, 1.0, 2.0]}

        # un-checkpointed reference run
        ref = IncrementalSearchCV(
            LinearFunction(), params, n_initial_parameters="grid",
            max_iter=6, random_state=0,
        ).fit(X, y)

        crashing = IncrementalSearchCV(
            LinearFunction(), params, n_initial_parameters="grid",
            max_iter=6, random_state=0, checkpoint=path,
        )
        calls = {"n": 0}
        orig = type(crashing)._additional_calls

        def boom(self, info):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("simulated preemption")
            return orig(self, info)

        import unittest.mock as mock

        with mock.patch.object(type(crashing), "_additional_calls", boom):
            with pytest.raises(RuntimeError, match="preemption"):
                crashing.fit(X, y)
        assert SearchCheckpoint(path).exists()

        # resumed run: models pick up their partial_fit_calls counts
        resumed = IncrementalSearchCV(
            LinearFunction(), params, n_initial_parameters="grid",
            max_iter=6, random_state=0, checkpoint=path,
        ).fit(X, y)
        assert resumed.best_params_ == ref.best_params_
        assert resumed.best_score_ == ref.best_score_
        # final per-model budgets identical to the uninterrupted run
        ref_calls = {
            i: recs[-1]["partial_fit_calls"]
            for i, recs in ref.model_history_.items()
        }
        res_calls = {
            i: recs[-1]["partial_fit_calls"]
            for i, recs in resumed.model_history_.items()
        }
        assert res_calls == ref_calls
        # snapshot removed on successful completion
        assert not SearchCheckpoint(path).exists()

    def test_sha_policy_state_resumes(self, tmp_path, rng):
        """SHA's _steps/_survivors counters are part of the snapshot: a
        resume must not restart the halving schedule from step 0."""
        X, y = _xy(rng)
        path = str(tmp_path / "sha.pkl")
        kwargs = dict(
            parameters={"slope": [0.1, 0.5, 1.0, 2.0, 3.0, 4.0]},
            n_initial_parameters=6, n_initial_iter=2, max_iter=8,
            random_state=0,
        )
        ref = SuccessiveHalvingSearchCV(LinearFunction(), **kwargs).fit(X, y)

        crashing = SuccessiveHalvingSearchCV(
            LinearFunction(), checkpoint=path, **kwargs
        )
        calls = {"n": 0}
        orig = SuccessiveHalvingSearchCV._additional_calls

        def boom(self, info):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("simulated preemption")
            return orig(self, info)

        import unittest.mock as mock

        with mock.patch.object(SuccessiveHalvingSearchCV, "_additional_calls", boom):
            with pytest.raises(RuntimeError):
                crashing.fit(X, y)

        resumed = SuccessiveHalvingSearchCV(
            LinearFunction(), checkpoint=path, **kwargs
        ).fit(X, y)
        assert resumed.best_params_ == ref.best_params_
        ref_calls = {
            i: recs[-1]["partial_fit_calls"]
            for i, recs in ref.model_history_.items()
        }
        res_calls = {
            i: recs[-1]["partial_fit_calls"]
            for i, recs in resumed.model_history_.items()
        }
        assert res_calls == ref_calls

    def test_hyperband_bracket_checkpoints(self, tmp_path, rng):
        X, y = _xy(rng)
        hb = HyperbandSearchCV(
            LinearFunction(), {"slope": [0.5, 1.0, 2.0]}, max_iter=9,
            random_state=0, checkpoint=str(tmp_path / "hb"),
        ).fit(X, y)
        assert hasattr(hb, "best_params_")
        # all bracket snapshots cleaned up after a successful fit
        assert list((tmp_path / "hb").glob("*.pkl")) == []

    def test_mismatched_config_ignored(self, tmp_path, rng):
        """A snapshot from a DIFFERENT search config must not be loaded."""
        X, y = _xy(rng)
        path = str(tmp_path / "s.pkl")

        crashing = IncrementalSearchCV(
            LinearFunction(), {"slope": [1.0, 2.0]}, n_initial_parameters="grid",
            max_iter=6, random_state=0, checkpoint=path,
        )
        calls = {"n": 0}
        orig = type(crashing)._additional_calls

        def boom(self, info):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("simulated preemption")
            return orig(self, info)

        import unittest.mock as mock

        with mock.patch.object(type(crashing), "_additional_calls", boom):
            with pytest.raises(RuntimeError):
                crashing.fit(X, y)
        assert SearchCheckpoint(path).exists()

        # different max_iter and slope grid: snapshot must be ignored and
        # the fresh run must reflect the NEW parameter space
        fresh = IncrementalSearchCV(
            LinearFunction(), {"slope": [5.0]}, n_initial_parameters="grid",
            max_iter=3, random_state=0, checkpoint=path,
        ).fit(X, y)
        assert fresh.best_params_ == {"slope": 5.0}
        assert max(
            recs[-1]["partial_fit_calls"] for recs in fresh.model_history_.values()
        ) <= 3

    def test_resume_preserves_wall_time_ordering(self, tmp_path, rng):
        """history_ stays chronological across a resume: post-resume records
        must carry elapsed_wall_time >= pre-crash records."""
        X, y = _xy(rng)
        path = str(tmp_path / "s.pkl")
        kwargs = dict(
            parameters={"slope": [0.5, 1.0, 2.0]}, n_initial_parameters="grid",
            max_iter=6, random_state=0, checkpoint=path,
        )
        crashing = IncrementalSearchCV(LinearFunction(), **kwargs)
        calls = {"n": 0}
        orig = type(crashing)._additional_calls

        def boom(self, info):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("simulated preemption")
            return orig(self, info)

        import unittest.mock as mock

        with mock.patch.object(type(crashing), "_additional_calls", boom):
            with pytest.raises(RuntimeError):
                crashing.fit(X, y)

        resumed = IncrementalSearchCV(LinearFunction(), **kwargs).fit(X, y)
        times = [r["elapsed_wall_time"] for r in resumed.history_]
        assert times == sorted(times)
        pf = [r["partial_fit_calls"] for r in resumed.history_]
        # chronological => per-model call counts never decrease in history_
        by_model = {}
        for r in resumed.history_:
            prev = by_model.get(r["model_id"], 0)
            assert r["partial_fit_calls"] >= prev
            by_model[r["model_id"]] = r["partial_fit_calls"]
        assert max(pf) == 6

    def test_completed_run_leaves_no_snapshot(self, tmp_path, rng):
        X, y = _xy(rng)
        path = str(tmp_path / "s.pkl")
        IncrementalSearchCV(
            LinearFunction(), {"slope": [1.0, 2.0]}, n_initial_parameters="grid",
            max_iter=3, random_state=0, checkpoint=path,
        ).fit(X, y)
        assert not SearchCheckpoint(path).exists()


import collections

from dask_ml_tpu.base import TPUEstimator

_NTState = collections.namedtuple("_NTState", ["w", "n"])

#: module-level (pickle-able) namedtuple solver state + carrier estimator
#: for the mesh-shape-change roundtrip below
_SolverNTState = collections.namedtuple("_SolverNTState", ["w", "step"])


class _WithState(TPUEstimator):
    def __init__(self):
        pass


class _SolverEst(TPUEstimator):
    _checkpoint_private_attrs = ("_solver_state",)

    def __init__(self):
        pass


class TestHostConversion:
    def test_namedtuple_fitted_attr_roundtrip(self, tmp_path):
        # Tuple subclasses with positional fields (NamedTuple solver states)
        # must be rebuilt field-wise, not passed a single list argument.
        import jax.numpy as jnp

        from dask_ml_tpu.checkpoint import _from_host, _to_host

        State = _NTState
        s = State(w=jnp.arange(3.0), n=7)
        back = _from_host(_to_host(s))
        assert isinstance(back, State)
        np.testing.assert_allclose(np.asarray(back.w), [0.0, 1.0, 2.0])
        assert back.n == 7

        est = _WithState()
        est.state_ = s
        save_estimator(est, str(tmp_path / "ns"))
        loaded = load_estimator(str(tmp_path / "ns"))
        assert isinstance(loaded.state_, State)
        np.testing.assert_allclose(np.asarray(loaded.state_.w), [0.0, 1.0, 2.0])


class TestFingerprint:
    def test_large_array_params_distinguished(self):
        # numpy truncates reprs of >1000-element arrays; the fingerprint
        # must still tell two different big grids apart.
        from dask_ml_tpu.checkpoint import search_fingerprint

        a = np.zeros(2000)
        b = np.zeros(2000)
        b[1500] = 1.0
        s1 = IncrementalSearchCV(
            LinearFunction(), {"intercept": a}, max_iter=3
        )
        s2 = IncrementalSearchCV(
            LinearFunction(), {"intercept": b}, max_iter=3
        )
        assert search_fingerprint(s1) != search_fingerprint(s2)

    def test_identical_config_same_fingerprint(self):
        from dask_ml_tpu.checkpoint import search_fingerprint

        g = {"intercept": np.linspace(0, 1, 5)}
        s1 = IncrementalSearchCV(LinearFunction(), g, max_iter=3)
        s2 = IncrementalSearchCV(LinearFunction(), dict(g), max_iter=3)
        assert search_fingerprint(s1) == search_fingerprint(s2)


class TestDeviceEstimatorRoundtrips:
    def test_sgd_classifier_roundtrip(self, tmp_path, rng):
        from dask_ml_tpu.linear_model import SGDClassifier

        X = rng.normal(size=(200, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        clf = SGDClassifier(max_iter=30, random_state=0).fit(X, y)
        save_estimator(clf, str(tmp_path / "sgd"))
        back = load_estimator(str(tmp_path / "sgd"))
        np.testing.assert_array_equal(back.predict(X), clf.predict(X))
        np.testing.assert_array_equal(back.classes_, clf.classes_)

    def test_minibatch_kmeans_roundtrip(self, tmp_path, rng):
        from dask_ml_tpu.cluster import MiniBatchKMeans

        X = rng.normal(size=(300, 4)).astype(np.float32)
        mbk = MiniBatchKMeans(n_clusters=3, random_state=0, max_iter=10).fit(X)
        save_estimator(mbk, str(tmp_path / "mbk"))
        back = load_estimator(str(tmp_path / "mbk"))
        np.testing.assert_allclose(
            np.asarray(back.cluster_centers_),
            np.asarray(mbk.cluster_centers_), rtol=1e-6,
        )
        # the restored model keeps STREAMING: counts survived the roundtrip
        back.partial_fit(X[:64])
        assert back.n_steps_ == mbk.n_steps_ + 1


class TestCrashMatrix:
    """VERDICT r5 target: resume from a crash at EVERY point of a
    Hyperband run, including mid-bracket and double-crash — each resume
    must reach the uninterrupted run's exact result.  A single crash
    point (the old test) can miss state that only goes stale deeper
    into the bracket ladder."""

    _kwargs = dict(
        parameters={"slope": [0.1, 0.4, 0.8, 1.2, 2.0, 3.0]},
        max_iter=4, aggressiveness=2, random_state=0,
        sequential_brackets=True,  # deterministic call order: the crash
        # index then hits the same schedule point every run
    )

    def _reference(self, X, y):
        return HyperbandSearchCV(LinearFunction(), **self._kwargs).fit(X, y)

    def _crash_at(self, X, y, path, crash_calls):
        """Run with a bracket-checkpoint dir, raising at each SHA call
        index in ``crash_calls`` (consumed in order), resuming in
        between.  Hyperband delegates rounds to per-bracket
        SuccessiveHalvingSearchCV instances, so the crash hook is SHA's
        ``_additional_calls``."""
        import os
        import unittest.mock as mock

        orig = SuccessiveHalvingSearchCV._additional_calls
        for k in crash_calls:
            calls = {"n": 0}

            def boom(self, info, _k=k, _calls=calls):
                _calls["n"] += 1
                if _calls["n"] == _k:
                    raise RuntimeError("simulated preemption")
                return orig(self, info)

            hb = HyperbandSearchCV(
                LinearFunction(), checkpoint=path, **self._kwargs
            )
            with mock.patch.object(
                    SuccessiveHalvingSearchCV, "_additional_calls", boom):
                with pytest.raises(RuntimeError, match="preemption"):
                    hb.fit(X, y)
            # at least one bracket snapshot survives the crash
            assert os.path.isdir(path) and os.listdir(path), path
        resumed = HyperbandSearchCV(
            LinearFunction(), checkpoint=path, **self._kwargs
        ).fit(X, y)
        return resumed

    def _count_calls(self, X, y):
        """Total SHA _additional_calls invocations of a full run."""
        import unittest.mock as mock

        orig = SuccessiveHalvingSearchCV._additional_calls
        counter = {"n": 0}

        def counting(self, info):
            counter["n"] += 1
            return orig(self, info)

        with mock.patch.object(
                SuccessiveHalvingSearchCV, "_additional_calls", counting):
            HyperbandSearchCV(LinearFunction(), **self._kwargs).fit(X, y)
        return counter["n"]

    def test_crash_matrix_every_point(self, tmp_path, rng):
        """Crash at EVERY schedule point the run actually has."""
        X = rng.normal(size=(120, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        ref = self._reference(X, y)
        total = self._count_calls(X, y)
        assert total >= 2, "schedule too short to be a matrix"
        ref_calls = {i: r[-1]["partial_fit_calls"]
                     for i, r in ref.model_history_.items()}
        import os

        for k in range(1, total + 1):
            path = str(tmp_path / f"hb_c{k}")
            res = self._crash_at(X, y, path, [k])
            assert res.best_params_ == ref.best_params_, k
            assert res.best_score_ == ref.best_score_, k
            res_calls = {i: r[-1]["partial_fit_calls"]
                         for i, r in res.model_history_.items()}
            assert res_calls == ref_calls, k
            # bracket snapshots cleaned up after the successful resume
            assert not [f for f in os.listdir(path)
                        if f.endswith(".pkl")], k

    def test_double_crash(self, tmp_path, rng):
        """Crash, resume, crash again later, resume again."""
        X = rng.normal(size=(120, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        ref = self._reference(X, y)
        res = self._crash_at(X, y, str(tmp_path / "hb_cc"), [1, 1])
        assert res.best_params_ == ref.best_params_
        assert res.best_score_ == ref.best_score_


class TestSearchCheckpointEdgeCases:
    """ISSUE 1 satellite: the SearchCheckpoint corners the crash matrix
    above doesn't isolate — the atomic-write window itself, foreign-
    snapshot preservation, and namedtuple state across a MESH change."""

    def test_crash_mid_atomic_write_keeps_previous_snapshot(self, tmp_path):
        """The checkpoint-write injection point fires BETWEEN the tmp
        write and the atomic rename: the previous snapshot must survive
        byte-identical and the tmp file must not leak."""
        from dask_ml_tpu.resilience import FaultInjected, fault_plan

        path = tmp_path / "s.pkl"
        ck = SearchCheckpoint(str(path), fingerprint="fp")
        ck.save({"m": 1}, {"i": [1]}, {"round": 1}, elapsed=1.0)
        first = path.read_bytes()

        with fault_plan() as plan:
            plan.inject("checkpoint-write", at_call=1)
            with pytest.raises(FaultInjected):
                ck.save({"m": 2}, {"i": [1, 2]}, {"round": 2}, elapsed=2.0)

        assert path.read_bytes() == first
        _, _, policy, elapsed = ck.load_if_matches()
        assert policy == {"round": 1} and elapsed == 1.0
        assert [p.name for p in tmp_path.iterdir()] == ["s.pkl"]

    def test_fingerprint_mismatch_keeps_foreign_snapshot_file(self, tmp_path):
        """A mismatched fingerprint starts fresh but must NOT consume or
        delete the foreign snapshot — it belongs to another search."""
        path = tmp_path / "s.pkl"
        SearchCheckpoint(str(path), fingerprint="theirs").save(
            {"m": 1}, {}, {"round": 3}
        )
        raw = path.read_bytes()

        ours = SearchCheckpoint(str(path), fingerprint="ours")
        assert ours.load_if_matches() is None
        assert path.read_bytes() == raw  # untouched on disk

    def test_namedtuple_state_resharded_across_mesh_change(self, tmp_path,
                                                           rng):
        """An estimator checkpoint holding a namedtuple solver-state attr
        with a ShardedRows leaf must round-trip onto a DIFFERENT mesh
        shape: the namedtuple rebuilds field-wise and the _ShardedMarker
        re-shards onto whatever mesh is active at load time."""
        import jax

        from dask_ml_tpu.core.mesh import device_mesh, use_mesh

        State = _SolverNTState

        n_dev = len(jax.devices())
        if n_dev < 2 or n_dev % 2:
            pytest.skip("needs an even device count >= 2 to halve the mesh")

        arr = rng.normal(size=(48, 4)).astype(np.float32)
        est = _SolverEst()
        est._solver_state = State(w=shard_rows(arr), step=5)
        est.coef_ = np.ones(4, np.float32)
        save_estimator(est, str(tmp_path / "solver"))

        half = device_mesh(n_dev // 2)
        with use_mesh(half):
            loaded = load_estimator(str(tmp_path / "solver"))
            st = loaded._solver_state
            assert isinstance(st, State) and st.step == 5
            # re-sharded over the SMALLER mesh, values intact
            assert len(st.w.data.sharding.device_set) == n_dev // 2
            np.testing.assert_allclose(unshard(st.w), arr, rtol=1e-6)

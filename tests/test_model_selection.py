import numpy as np
import pytest
from sklearn.linear_model import SGDClassifier

import dask_ml_tpu.linear_model as dlm
import dask_ml_tpu.model_selection as dms
from dask_ml_tpu.core import shard_rows, unshard
from dask_ml_tpu.core.sharded import ShardedRows
from dask_ml_tpu.model_selection.utils_test import ConstantFunction, LinearFunction


@pytest.fixture
def clf_data(rng):
    n, d = 300, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int64)
    return X, y


class TestSplit:
    def test_train_test_split_sizes(self, clf_data):
        X, y = clf_data
        Xtr, Xte, ytr, yte = dms.train_test_split(X, y, test_size=0.2, random_state=0)
        assert Xtr.shape == (240, 5) and Xte.shape == (60, 5)
        assert ytr.shape == (240,) and yte.shape == (60,)

    def test_split_no_overlap_covers_all(self, clf_data):
        X, _ = clf_data
        Xi = np.arange(300)
        tr, te = dms.train_test_split(Xi, test_size=0.25, random_state=1)
        assert len(set(tr) & set(te)) == 0
        assert len(set(tr) | set(te)) == 300

    def test_sharded_in_sharded_out(self, clf_data):
        X, y = clf_data
        s = shard_rows(X)
        Xtr, Xte = dms.train_test_split(s, test_size=0.2, random_state=0)
        assert isinstance(Xtr, ShardedRows) and isinstance(Xte, ShardedRows)
        assert Xtr.n_samples == 240 and Xte.n_samples == 60

    def test_no_shuffle_contiguous(self):
        X = np.arange(100).reshape(100, 1)
        Xtr, Xte = dms.train_test_split(X, test_size=0.2, shuffle=False)
        np.testing.assert_array_equal(Xtr[:, 0], np.arange(80))
        np.testing.assert_array_equal(Xte[:, 0], np.arange(80, 100))

    def test_mismatched_lengths_raise(self):
        with pytest.raises(ValueError, match="same length"):
            dms.train_test_split(np.ones(10), np.ones(11))

    def test_kfold_contiguous_slabs(self):
        X = np.zeros((100, 2))
        folds = list(dms.KFold(n_splits=5).split(X))
        assert len(folds) == 5
        np.testing.assert_array_equal(folds[0][1], np.arange(20))
        for train, test in folds:
            assert len(train) == 80 and len(test) == 20
            assert len(set(train) & set(test)) == 0

    def test_kfold_validates(self):
        with pytest.raises(ValueError, match="n_splits"):
            list(dms.KFold(n_splits=1).split(np.zeros((10, 1))))

    def test_shuffle_split_deterministic(self):
        X = np.zeros((50, 2))
        a = list(dms.ShuffleSplit(n_splits=3, random_state=7).split(X))
        b = list(dms.ShuffleSplit(n_splits=3, random_state=7).split(X))
        for (tr1, te1), (tr2, te2) in zip(a, b):
            np.testing.assert_array_equal(tr1, tr2)
            np.testing.assert_array_equal(te1, te2)


class TestGridSearchCV:
    def test_parity_with_sklearn(self, clf_data):
        import sklearn.model_selection as sms

        X, y = clf_data
        param_grid = {"alpha": [1e-4, 1e-2, 1.0]}
        est = SGDClassifier(tol=1e-3, random_state=0)
        ours = dms.GridSearchCV(est, param_grid, cv=3).fit(X, y)
        theirs = sms.GridSearchCV(est, param_grid, cv=3).fit(X, y)
        assert ours.best_params_ == theirs.best_params_
        assert set(ours.cv_results_["param_alpha"]) == set(
            theirs.cv_results_["param_alpha"]
        )

    def test_best_estimator_refit(self, clf_data):
        X, y = clf_data
        gs = dms.GridSearchCV(
            SGDClassifier(tol=1e-3, random_state=0), {"alpha": [1e-4, 1.0]}, cv=3
        ).fit(X, y)
        assert hasattr(gs, "best_estimator_")
        assert gs.predict(X).shape == (300,)
        assert gs.score(X, y) > 0.5

    def test_refit_false_blocks_predict(self, clf_data):
        X, y = clf_data
        gs = dms.GridSearchCV(
            SGDClassifier(tol=1e-3), {"alpha": [1e-4]}, cv=3, refit=False
        ).fit(X, y)
        with pytest.raises(AttributeError, match="refit"):
            gs.predict(X)

    def test_pipeline_prefix_cache(self, clf_data):
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        X, y = clf_data
        calls = {"n": 0}

        class CountingScaler(StandardScaler):
            def fit_transform(self, X, y=None, **kw):
                calls["n"] += 1
                return super().fit_transform(X, y, **kw)

        pipe = Pipeline([("sc", CountingScaler()), ("clf", SGDClassifier(tol=1e-3, random_state=0))])
        gs = dms.GridSearchCV(
            pipe, {"clf__alpha": [1e-4, 1e-3, 1e-2]}, cv=3, refit=False
        ).fit(X, y)
        # shared scaler prefix must be fit once per fold, not per candidate
        # (3 folds x 3 candidates would be 9 without the cache)
        assert calls["n"] == 3
        assert gs.best_score_ > 0.5

    def test_sharded_input(self, clf_data):
        X, y = clf_data
        gs = dms.GridSearchCV(
            SGDClassifier(tol=1e-3, random_state=0), {"alpha": [1e-4, 1.0]}, cv=3
        ).fit(shard_rows(X), shard_rows(y))
        assert gs.best_score_ > 0.5

    def test_randomized_search(self, clf_data):
        X, y = clf_data
        rs = dms.RandomizedSearchCV(
            SGDClassifier(tol=1e-3, random_state=0),
            {"alpha": np.logspace(-5, 0, 20)}, n_iter=4, random_state=0, cv=3,
        ).fit(X, y)
        assert len(rs.cv_results_["params"]) == 4


class TestIncrementalSearchCV:
    def test_trains_to_max_iter_without_patience(self, clf_data):
        X, y = clf_data
        search = dms.IncrementalSearchCV(
            ConstantFunction(), {"value": [0.1, 0.5, 0.9]},
            n_initial_parameters="grid", max_iter=5, chunk_size=50,
        )
        search.fit(X, y)
        assert search.best_score_ == 0.9
        # every model trained exactly max_iter calls
        assert all(
            recs[-1]["partial_fit_calls"] == 5
            for recs in search.model_history_.values()
        )

    def test_patience_stops_plateaued_models(self, clf_data):
        X, y = clf_data
        search = dms.IncrementalSearchCV(
            ConstantFunction(), {"value": [0.2, 0.8]},
            n_initial_parameters="grid", max_iter=50, patience=3, tol=1e-3,
            chunk_size=50,
        )
        search.fit(X, y)
        # constant scores plateau immediately -> far fewer than max_iter calls
        assert all(
            recs[-1]["partial_fit_calls"] < 50
            for recs in search.model_history_.values()
        )

    def test_history_records_structure(self, clf_data):
        X, y = clf_data
        search = dms.IncrementalSearchCV(
            LinearFunction(), {"slope": [1.0, 2.0]},
            n_initial_parameters="grid", max_iter=3, chunk_size=50,
        ).fit(X, y)
        rec = search.history_[0]
        for key in ("model_id", "params", "partial_fit_calls", "score",
                    "elapsed_wall_time"):
            assert key in rec
        assert search.cv_results_["rank_test_score"][search.best_index_] == 1

    def test_real_sgd_improves(self, clf_data):
        X, y = clf_data
        search = dms.IncrementalSearchCV(
            SGDClassifier(tol=None, random_state=0),
            {"alpha": [1e-4, 1e-3]}, n_initial_parameters="grid",
            max_iter=10, chunk_size=50,
        )
        search.fit(X, y, classes=[0, 1])
        assert search.best_score_ > 0.7

    def test_inverse_decay(self, clf_data):
        X, y = clf_data
        search = dms.InverseDecaySearchCV(
            LinearFunction(), {"slope": [1.0, 2.0, 3.0, 4.0]},
            n_initial_parameters="grid", max_iter=8, chunk_size=50,
        ).fit(X, y)
        # the best (steepest) model survives to the end
        assert search.best_params_["slope"] == 4.0
        calls = [r[-1]["partial_fit_calls"] for r in search.model_history_.values()]
        assert max(calls) > min(calls)  # losers stopped early


class TestSuccessiveHalving:
    def test_exact_schedule_with_fake_models(self, clf_data):
        X, y = clf_data
        # 9 models, eta=3: rounds keep 9 -> 3 -> 1; budgets 1 -> 3 -> 9
        values = {i: i / 10 for i in range(9)}
        search = dms.SuccessiveHalvingSearchCV(
            ConstantFunction(), {"value": [values[i] for i in range(9)]},
            n_initial_parameters="grid", n_initial_iter=1, aggressiveness=3,
            max_iter=9, chunk_size=50,
        ).fit(X, y)
        hist = search.model_history_
        final_calls = sorted(
            recs[-1]["partial_fit_calls"] for recs in hist.values()
        )
        # 6 losers stop at 1 call, 2 mid at 3 calls, the winner gets 9
        assert final_calls == [1, 1, 1, 1, 1, 1, 3, 3, 9]
        assert search.best_score_ == 0.8

    def test_requires_n_initial_iter(self, clf_data):
        X, y = clf_data
        with pytest.raises(ValueError, match="n_initial_iter"):
            dms.SuccessiveHalvingSearchCV(
                ConstantFunction(), {"value": [0.1]},
            ).fit(X, y)

    def test_patience_stops_plateaued_bracket(self, clf_data):
        # patience is a BASE-loop post-filter, so SHA brackets honor it
        # too: constant scores plateau immediately and the winner stops
        # long before its granted r_i budget
        X, y = clf_data
        kw = dict(
            n_initial_parameters="grid", n_initial_iter=1, aggressiveness=3,
            max_iter=81, chunk_size=50,
        )
        grid = {"value": [i / 10 for i in range(9)]}
        full = dms.SuccessiveHalvingSearchCV(
            ConstantFunction(), grid, **kw).fit(X, y)
        stopped = dms.SuccessiveHalvingSearchCV(
            ConstantFunction(), grid, patience=3, tol=1e-3, **kw).fit(X, y)
        calls = lambda s: sum(  # noqa: E731
            recs[-1]["partial_fit_calls"]
            for recs in s.model_history_.values()
        )
        assert stopped.best_score_ == full.best_score_ == 0.8
        assert calls(stopped) < calls(full)


class TestHyperband:
    def test_bracket_params_r81(self):
        from dask_ml_tpu.model_selection._hyperband import _get_hyperband_params

        # canonical Li et al. example: R=81, eta=3
        out = _get_hyperband_params(81, 3)
        assert [(n, r) for _, n, r in out] == [
            (81, 1), (34, 3), (15, 9), (8, 27), (5, 81)
        ]

    def test_metadata_counts(self):
        search = dms.HyperbandSearchCV(
            ConstantFunction(), {"value": [0.1]}, max_iter=9, aggressiveness=3
        )
        meta = search.metadata
        # R=9, eta=3: brackets (n=9,r=1), (n=5,r=3), (n=3,r=9)
        assert [b["n_models"] for b in meta["brackets"]] == [9, 5, 3]
        assert meta["n_models"] == 17
        assert meta["partial_fit_calls"] == sum(
            b["partial_fit_calls"] for b in meta["brackets"]
        )

    def test_fit_finds_best_and_metadata_matches(self, clf_data, rng):
        X, y = clf_data
        search = dms.HyperbandSearchCV(
            LinearFunction(),
            {"slope": list(rng.uniform(0.1, 2.0, size=30)),
             "intercept": list(rng.uniform(0, 0.1, size=10))},
            max_iter=9, aggressiveness=3, random_state=0, chunk_size=50,
        ).fit(X, y)
        assert search.metadata_["n_models"] == search.metadata["n_models"]
        assert search.best_score_ > 0
        assert hasattr(search, "cv_results_")
        assert "bracket" in search.history_[0]
        # model ids globally unique across brackets
        ids = list(search.model_history_)
        assert len(ids) == len(set(ids)) == search.metadata_["n_models"]

    def test_real_sgd_hyperband(self, clf_data):
        X, y = clf_data
        search = dms.HyperbandSearchCV(
            SGDClassifier(tol=None, random_state=0),
            {"alpha": np.logspace(-5, 1, 30)},
            max_iter=9, random_state=0, chunk_size=50,
        )
        search.fit(X, y, classes=[0, 1])
        assert search.best_score_ > 0.7
        assert search.predict(X).shape == (300,)


class TestReviewRegressions:
    def test_sha_refit_same_instance(self, clf_data):
        X, y = clf_data
        search = dms.SuccessiveHalvingSearchCV(
            ConstantFunction(), {"value": [i / 10 for i in range(9)]},
            n_initial_parameters="grid", n_initial_iter=1, aggressiveness=3,
            max_iter=9, chunk_size=50,
        )
        search.fit(X, y)
        first = sorted(r[-1]["partial_fit_calls"] for r in search.model_history_.values())
        search.fit(X, y)
        second = sorted(r[-1]["partial_fit_calls"] for r in search.model_history_.values())
        assert first == second == [1, 1, 1, 1, 1, 1, 3, 3, 9]

    def test_patience_with_improving_model_keeps_training(self, clf_data):
        X, y = clf_data
        search = dms.IncrementalSearchCV(
            LinearFunction(), {"slope": [1.0]}, n_initial_parameters="grid",
            max_iter=10, patience=2, tol=1e-3, chunk_size=50,
        ).fit(X, y)
        # monotonically improving model must NOT stop after the first score
        calls = list(search.model_history_.values())[0][-1]["partial_fit_calls"]
        assert calls == 10

    def test_split_integer_sizes_are_counts(self):
        X = np.arange(100).reshape(100, 1)
        Xtr, Xte = dms.train_test_split(X, test_size=1, random_state=0)
        assert Xte.shape == (1, 1) and Xtr.shape == (99, 1)

    def test_incremental_requires_y(self, clf_data):
        X, _ = clf_data
        with pytest.raises(ValueError, match="y is required"):
            dms.IncrementalSearchCV(
                ConstantFunction(), {"value": [0.1]}, n_initial_parameters="grid"
            ).fit(X)

    def test_grid_fit_params_unsupervised(self, rng):
        from dask_ml_tpu.cluster import KMeans

        X = rng.normal(size=(60, 3)).astype(np.float32)
        gs = dms.GridSearchCV(KMeans(init="random", random_state=0), {"n_clusters": [2, 3]}, cv=2)
        gs.fit(X)  # y=None path
        assert gs.best_params_["n_clusters"] in (2, 3)


class TestDeviceSideSplit:
    def test_take_no_host_materialization(self, rng, mesh):
        # the sharded path must not call np.asarray on X-sized data
        import unittest.mock as um

        import numpy as np

        from dask_ml_tpu.core import shard_rows, unshard
        from dask_ml_tpu.model_selection import _split

        X = rng.normal(size=(200, 4)).astype(np.float32)
        Xs = shard_rows(X)
        idx = rng.permutation(150)
        real_asarray = np.asarray
        big_pulls = []

        def spy(a, *args, **kw):
            out = real_asarray(a, *args, **kw)
            import jax

            if isinstance(a, jax.Array) and out.size >= 100 * 4:
                big_pulls.append(out.shape)
            return out

        with um.patch.object(_split.np, "asarray", side_effect=spy):
            taken = _split._take(Xs, idx)
        assert big_pulls == []  # gather stayed on device
        np.testing.assert_allclose(unshard(taken), X[idx])

    def test_take_result_row_sharded(self, rng, mesh):
        import numpy as np

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.core.mesh import DATA_AXIS
        from dask_ml_tpu.model_selection._split import _take

        X = rng.normal(size=(100, 3)).astype(np.float32)
        taken = _take(shard_rows(X), np.arange(37))
        from conftest import spec_axis

        assert taken.n_samples == 37
        assert spec_axis(taken.data.sharding.spec[0]) == DATA_AXIS


class TestKMeansParInitDeviceSide:
    def test_no_length_n_host_pull_per_round(self, rng, mesh):
        import unittest.mock as um

        import numpy as np

        from dask_ml_tpu.cluster import k_means as km
        from dask_ml_tpu.core import shard_rows

        n = 4096
        X = np.concatenate([
            rng.normal(i * 5, 0.5, size=(n // 4, 8)) for i in range(4)
        ]).astype(np.float32)
        Xs = shard_rows(X)
        real_asarray = np.asarray
        big_pulls = []

        def spy(a, *args, **kw):
            out = real_asarray(a, *args, **kw)
            import jax

            # guard against O(n)-sized pulls (the old per-round boolean
            # vector); the legitimate end-of-init candidate pull is
            # O(k log n * d), far below n*4 at this shape
            if isinstance(a, jax.Array) and out.size >= n * 4:
                big_pulls.append(out.shape)
            return out

        import jax

        with um.patch.object(km.np, "asarray", side_effect=spy):
            centers = km.init_scalable(
                Xs, 4, jax.random.PRNGKey(0), oversampling_factor=2
            )
        assert big_pulls == [], big_pulls
        # init still finds the 4 well-separated blobs
        got = np.sort(np.asarray(centers)[:, 0])
        expect = np.array([0.0, 5.0, 10.0, 15.0])
        np.testing.assert_allclose(got, expect, atol=1.5)


class TestMultimetricScoring:
    """sklearn's multimetric contract on GridSearchCV (reference surface:
    dask-ml forwards sklearn's scoring semantics): list/dict scoring,
    per-metric cv_results_ columns, refit-by-name, refit=False."""

    def _data(self, rng):
        X = rng.normal(size=(120, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        return X, y

    def test_list_scoring_refit_by_name(self, rng):
        from sklearn.tree import DecisionTreeClassifier

        X, y = self._data(rng)
        gs = dms.GridSearchCV(
            DecisionTreeClassifier(random_state=0), {"max_depth": [1, 3]},
            scoring=["accuracy", "neg_log_loss"], refit="accuracy", cv=3,
        ).fit(X, y)
        assert gs.multimetric_
        for m in ("accuracy", "neg_log_loss"):
            assert f"mean_test_{m}" in gs.cv_results_
            assert f"rank_test_{m}" in gs.cv_results_
            assert f"split0_test_{m}" in gs.cv_results_
        best = int(np.argmax(gs.cv_results_["mean_test_accuracy"]))
        assert gs.best_index_ == best
        assert gs.score(X, y) == pytest.approx(
            gs.best_estimator_.score(X, y))

    def test_dict_scoring_with_callable(self, rng):
        from sklearn.tree import DecisionTreeClassifier

        from dask_ml_tpu.metrics import accuracy_score

        X, y = self._data(rng)

        def my_scorer(est, Xv, yv):
            return float(accuracy_score(yv, est.predict(Xv)))

        gs = dms.GridSearchCV(
            DecisionTreeClassifier(random_state=0), {"max_depth": [1, 3]},
            scoring={"acc": "accuracy", "mine": my_scorer}, refit="mine",
            cv=3,
        ).fit(X, y)
        np.testing.assert_allclose(
            gs.cv_results_["mean_test_acc"], gs.cv_results_["mean_test_mine"]
        )

    def test_refit_false_builds_columns_without_best(self, rng):
        from sklearn.tree import DecisionTreeClassifier

        X, y = self._data(rng)
        gs = dms.GridSearchCV(
            DecisionTreeClassifier(random_state=0), {"max_depth": [1, 3]},
            scoring=["accuracy", "r2"], refit=False, cv=3,
        ).fit(X, y)
        assert "mean_test_accuracy" in gs.cv_results_
        assert not hasattr(gs, "best_index_")

    def test_bad_refit_name_raises(self, rng):
        from sklearn.tree import DecisionTreeClassifier

        X, y = self._data(rng)
        with pytest.raises(ValueError, match="refit must be False"):
            dms.GridSearchCV(
                DecisionTreeClassifier(), {"max_depth": [1]},
                scoring=["accuracy"], refit=True, cv=3,
            ).fit(X, y)

    def test_single_metric_keys_unchanged(self, rng):
        from sklearn.tree import DecisionTreeClassifier

        X, y = self._data(rng)
        gs = dms.GridSearchCV(
            DecisionTreeClassifier(random_state=0), {"max_depth": [1, 3]},
            cv=3,
        ).fit(X, y)
        assert not gs.multimetric_
        assert "mean_test_score" in gs.cv_results_
        assert "rank_test_score" in gs.cv_results_

    def test_stratified_cv_for_library_classifiers(self, rng):
        """Our own GLM classifiers must stratify under cv=int like sklearn
        estimators do (is_classifier sees the ClassifierMixin)."""
        from sklearn.base import is_classifier

        from dask_ml_tpu.linear_model import LogisticRegression

        assert is_classifier(LogisticRegression())
        # class-sorted labels: unstratified contiguous folds would give a
        # single-class train split and error
        X = rng.normal(size=(90, 3)).astype(np.float32)
        y = np.repeat([0, 1, 2], 30)
        X[y == 1] += 3.0
        X[y == 2] -= 3.0
        gs = dms.GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=30),
            {"C": [1.0]}, cv=3,
        ).fit(X, y)
        assert gs.best_score_ > 0.5

    def test_multimetric_prediction_caching(self, rng):
        from sklearn.base import BaseEstimator

        calls = {"n": 0}

        class Counting(BaseEstimator):
            def __init__(self, c=1.0):
                self.c = c
            def fit(self, X, y):
                self.classes_ = np.unique(y)
                return self
            def predict(self, X):
                calls["n"] += 1
                return np.zeros(len(X), dtype=np.int64)
            def predict_proba(self, X):
                p = np.full((len(X), 2), 0.5)
                return p

        X = rng.normal(size=(60, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        dms.GridSearchCV(
            Counting(), {"c": [1.0]},
            scoring={"a": "accuracy", "b": "accuracy"}, refit="a",
            cv=2, n_jobs=1,
        ).fit(X, y)
        # 2 folds x 1 candidate: one predict per fold despite 2 metrics
        assert calls["n"] == 2


class TestDataFrameSplit:
    def test_train_test_split_preserves_pandas(self, rng):
        import pandas as pd

        df = pd.DataFrame({"a": range(20), "b": np.arange(20.0)})
        y = pd.Series(np.arange(20) % 2, name="t")
        Xtr, Xte, ytr, yte = dms.train_test_split(
            df, y, test_size=0.25, random_state=0
        )
        assert isinstance(Xtr, pd.DataFrame) and isinstance(yte, pd.Series)
        assert len(Xtr) == 15 and len(Xte) == 5
        # row alignment preserved between X and y
        assert (Xtr["a"].to_numpy() % 2 == ytr.to_numpy()).all()

    def test_pandas_X_in_grid_search(self, rng):
        import pandas as pd
        from sklearn.tree import DecisionTreeClassifier

        df = pd.DataFrame({
            "a": rng.normal(size=100), "b": rng.normal(size=100),
        })
        y = (df["a"] > 0).astype(int)
        gs = dms.GridSearchCV(
            DecisionTreeClassifier(random_state=0), {"max_depth": [1, 2]},
            cv=3,
        ).fit(df, y)
        assert gs.best_score_ > 0.9

    def test_callable_refit_selects_index(self, rng):
        from sklearn.tree import DecisionTreeClassifier

        X = rng.normal(size=(120, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)

        def pick_simplest_within_1pct(cv_results):
            scores = np.asarray(cv_results["mean_test_score"])
            ok = scores >= scores.max() - 0.01
            return int(np.flatnonzero(ok)[0])  # candidates ordered simple->complex

        gs = dms.GridSearchCV(
            DecisionTreeClassifier(random_state=0),
            {"max_depth": [1, 2, 4, 8]}, cv=3,
            refit=pick_simplest_within_1pct,
        ).fit(X, y)
        assert gs.best_params_["max_depth"] in (1, 2)
        assert hasattr(gs, "best_estimator_")
        assert not hasattr(gs, "best_score_")

    def test_multimetric_roc_auc_proba_only_estimator(self, rng):
        """The prediction-caching proxy must not invent decision_function:
        a probability-only classifier goes through predict_proba."""
        from dask_ml_tpu.naive_bayes import GaussianNB

        X = rng.normal(size=(150, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        gs = dms.GridSearchCV(
            GaussianNB(), {"var_smoothing": [1e-9, 1e-7]},
            scoring=["accuracy", "roc_auc"], refit="roc_auc", cv=3,
        ).fit(X, y)
        assert gs.cv_results_["mean_test_roc_auc"][gs.best_index_] > 0.8


class TestDeviceResidentSearch:
    """VERDICT r2 next #4: sharded data stays on device through the CV
    searches — fold slicing by device gather, scoring by scalar fetch."""

    def _tpu_est(self, **kw):
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD

        kw.setdefault("max_iter", 30)
        kw.setdefault("random_state", 0)
        kw.setdefault("tol", None)
        return TpuSGD(**kw)

    def test_grid_no_host_materialization(self, clf_data, monkeypatch, mesh):
        # transfer guard: any unshard inside the search layer is a bug on
        # the device path (fold gathers run on device, scores are scalars)
        import dask_ml_tpu.model_selection._search as search_mod

        def _boom(a):
            raise AssertionError("O(n) unshard on the device search path")

        monkeypatch.setattr(search_mod, "unshard", _boom)
        X, y = clf_data
        sX, sy = shard_rows(X), shard_rows(y.astype(np.float32))
        gs = dms.GridSearchCV(
            self._tpu_est(), {"alpha": [1e-4, 1e-2]}, cv=3
        ).fit(sX, sy)
        assert gs.best_score_ > 0.5
        # post-fit inference keeps sharded input on device too
        gs.predict(sX)
        assert gs.score(sX, sy) > 0.5

    def test_device_path_matches_host_path(self, clf_data, mesh):
        from sklearn.model_selection import KFold

        X, y = clf_data
        yf = y.astype(np.float32)
        host = dms.GridSearchCV(
            self._tpu_est(), {"alpha": [1e-4, 1e-2]}, cv=KFold(3),
            refit=False,
        ).fit(X, yf)
        dev = dms.GridSearchCV(
            self._tpu_est(), {"alpha": [1e-4, 1e-2]}, cv=KFold(3),
            refit=False,
        ).fit(shard_rows(X), shard_rows(yf))
        np.testing.assert_allclose(
            host.cv_results_["mean_test_score"],
            dev.cv_results_["mean_test_score"], rtol=1e-4,
        )

    def test_incremental_keeps_test_split_sharded(self, clf_data, monkeypatch, mesh):
        import dask_ml_tpu.model_selection._incremental as inc_mod

        def _boom(a):
            raise AssertionError("O(n) unshard in incremental search")

        monkeypatch.setattr(inc_mod, "unshard", _boom)
        X, y = clf_data
        sX, sy = shard_rows(X), shard_rows(y.astype(np.float32))
        search = dms.IncrementalSearchCV(
            self._tpu_est(tol=1e-3), {"alpha": [1e-4, 1e-2]},
            n_initial_parameters=2, max_iter=3, random_state=0,
        ).fit(sX, sy, classes=[0.0, 1.0])
        assert search.best_score_ > 0.0


class TestPrefixCacheEviction:
    def test_refcount_evicts_all_entries(self, clf_data, monkeypatch):
        import dask_ml_tpu.model_selection._search as search_mod
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        created = []
        orig = search_mod._OnceCache

        class Spy(orig):
            def __init__(self):
                super().__init__()
                created.append(self)

        monkeypatch.setattr(search_mod, "_OnceCache", Spy)
        X, y = clf_data
        pipe = Pipeline([
            ("sc", StandardScaler()),
            ("clf", SGDClassifier(tol=1e-3, random_state=0)),
        ])
        gs = dms.GridSearchCV(
            pipe, {"clf__alpha": [1e-4, 1e-3, 1e-2]}, cv=3, refit=False
        ).fit(X, y)
        assert gs.best_score_ > 0.5
        # every (prefix, fold) entry was released by its last consumer:
        # transformed fold data must not be pinned for the fit's lifetime
        assert created and len(created[0]) == 0


class TestSequentialBrackets:
    def test_sequential_matches_concurrent(self, clf_data, mesh):
        # same brackets, same per-bracket seeds -> identical results; only
        # the scheduling differs (sequential is the multi-controller form)
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD

        X, y = clf_data
        yf = y.astype(np.float32)
        kw = dict(
            parameters={"alpha": [1e-5, 1e-4, 1e-3, 1e-2]},
            max_iter=4, aggressiveness=2, random_state=0,
        )
        conc = dms.HyperbandSearchCV(
            TpuSGD(random_state=0, tol=None), **kw
        ).fit(X, yf, classes=[0.0, 1.0])
        seq = dms.HyperbandSearchCV(
            TpuSGD(random_state=0, tol=None), sequential_brackets=True, **kw
        ).fit(X, yf, classes=[0.0, 1.0])
        assert seq.best_score_ == pytest.approx(conc.best_score_, abs=1e-6)
        assert seq.metadata_["n_models"] == conc.metadata_["n_models"]
        assert (
            seq.cv_results_["test_score"] == conc.cv_results_["test_score"]
        )

    def test_patience_forwarded_to_brackets(self, mesh):
        hb = dms.HyperbandSearchCV(
            SGDClassifier(tol=None), {"alpha": [1e-4, 1e-3]},
            max_iter=9, patience=2, tol=1e-3,
        )
        for _s, sha in hb._make_brackets():
            assert sha.patience == 2 and sha.tol == 1e-3

    def test_patience_reduces_hyperband_budget(self, clf_data):
        # behavioral, not just forwarding: plateaued models stop early in
        # every bracket, so the observed budget drops below metadata's
        X, y = clf_data
        grid = {"value": [i / 10 for i in range(10)]}
        full = dms.HyperbandSearchCV(
            ConstantFunction(), grid, max_iter=27, random_state=0,
            chunk_size=50,
        ).fit(X, y)
        stopped = dms.HyperbandSearchCV(
            ConstantFunction(), grid, max_iter=27, random_state=0,
            patience=2, tol=1e-3, chunk_size=50,
        ).fit(X, y)
        assert (
            stopped.metadata_["partial_fit_calls"]
            < full.metadata_["partial_fit_calls"]
        )
        assert stopped.best_score_ == full.best_score_

    def test_patience_true_auto_sizes(self):
        search = dms.IncrementalSearchCV(
            ConstantFunction(), {"value": [0.1]}, max_iter=30, patience=True,
        )
        assert search._patience_calls() == 10

    def test_completed_fit_cleans_bracket_checkpoints(self, clf_data, mesh,
                                                      tmp_path):
        import os

        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD

        X, y = clf_data
        ckdir = tmp_path / "hb"
        ckdir.mkdir()
        hb = dms.HyperbandSearchCV(
            TpuSGD(random_state=0, tol=None), {"alpha": [1e-5, 1e-4]},
            max_iter=4, aggressiveness=2, random_state=0,
            sequential_brackets=True, checkpoint=str(ckdir),
        ).fit(X, y.astype(np.float32), classes=[0.0, 1.0])
        assert hb.best_score_ > 0.5
        # bracket snapshots are kept while the fit runs (crash recovery)
        # and removed once the WHOLE fit completes
        assert not [f for f in os.listdir(ckdir) if f.endswith(".pkl")]


class TestVerboseLogging:
    def test_verbose_emits_round_decisions(self, clf_data, caplog):
        import logging

        X, y = clf_data
        with caplog.at_level(
            logging.INFO, logger="dask_ml_tpu.model_selection._incremental"
        ):
            dms.IncrementalSearchCV(
                ConstantFunction(), {"value": [0.2, 0.8]},
                n_initial_parameters="grid", max_iter=3, chunk_size=50,
                verbose=True,
            ).fit(X, y)
        rounds = [r for r in caplog.records if "models continue" in r.message]
        assert len(rounds) >= 2
        assert "best score" in rounds[0].message

    def test_silent_by_default(self, clf_data, caplog):
        import logging

        X, y = clf_data
        with caplog.at_level(
            logging.INFO, logger="dask_ml_tpu.model_selection._incremental"
        ):
            dms.IncrementalSearchCV(
                ConstantFunction(), {"value": [0.5]},
                n_initial_parameters="grid", max_iter=2, chunk_size=50,
            ).fit(X, y)
        assert not [r for r in caplog.records if "models continue" in r.message]

    def test_hyperband_forwards_verbose(self):
        hb = dms.HyperbandSearchCV(
            SGDClassifier(tol=None), {"alpha": [1e-4]}, max_iter=9,
            verbose=True,
        )
        assert all(sha.verbose for _s, sha in hb._make_brackets())


class TestStratifiedSplit:
    def test_stratify_preserves_proportions(self, rng):
        X = rng.normal(size=(300, 3)).astype(np.float32)
        y = np.r_[np.zeros(270), np.ones(30)]  # 10% minority
        Xtr, Xte, ytr, yte = dms.train_test_split(
            X, y, stratify=y, test_size=0.2, random_state=0
        )
        assert yte.mean() == pytest.approx(0.1, abs=0.02)
        assert ytr.mean() == pytest.approx(0.1, abs=0.02)
        # sharded X with host stratify labels also works
        sXtr, sXte, ytr2, yte2 = dms.train_test_split(
            shard_rows(X), y, stratify=y, test_size=0.2, random_state=0
        )
        assert isinstance(sXtr, ShardedRows)
        assert yte2.mean() == pytest.approx(0.1, abs=0.02)

    def test_stratify_rejects_sharded_labels(self, rng):
        X = rng.normal(size=(80, 2)).astype(np.float32)
        y = (rng.rand(80) > 0.5).astype(np.float32)
        with pytest.raises(ValueError, match="host labels"):
            dms.train_test_split(X, y, stratify=shard_rows(y))
        with pytest.raises(ValueError, match="shuffle"):
            dms.train_test_split(X, y, stratify=y, shuffle=False)


class TestNBCheckpointRoundtrip:
    def test_mid_stream_checkpoint_exact(self, rng, tmp_path):
        from dask_ml_tpu.checkpoint import load_estimator, save_estimator
        from dask_ml_tpu.naive_bayes import GaussianNB

        X = rng.normal(size=(200, 3)).astype(np.float32)
        y = rng.randint(0, 2, 200)
        nb = GaussianNB().partial_fit(X[:100], y[:100], classes=[0, 1])
        p = str(tmp_path / "nb.ckpt")
        save_estimator(nb, p)
        nb2 = load_estimator(p)
        nb2.partial_fit(X[100:], y[100:])
        full = GaussianNB().fit(X, y)
        np.testing.assert_allclose(
            np.asarray(nb2.theta_), np.asarray(full.theta_), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(nb2.var_), np.asarray(full.var_), rtol=1e-4
        )


class TestPackedGlmGridSweep:
    """GridSearchCV fast path: a binary LogisticRegression grid over only
    C runs as ONE vmapped solve per fold (solvers.lambda_sweep) + one
    scoring gemm — r4's packed-search feature.  Results must be
    indistinguishable from the per-candidate path."""

    def _data(self, rng):
        X = rng.normal(size=(600, 8)).astype(np.float32)
        y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float32)
        return X, {"C": np.logspace(-2, 2, 7).tolist()}, y

    def test_matches_sequential_and_skips_dispatches(self, rng, mesh,
                                                     monkeypatch):
        from dask_ml_tpu import solvers

        X, grid, y = self._data(rng)
        results = {}
        for strat in ("packed", "sequential"):
            monkeypatch.setenv("DASK_ML_TPU_GRID_PACK", strat)
            solvers.reset_dispatch_counts()
            gs = dms.GridSearchCV(
                dlm.LogisticRegression(solver="lbfgs", max_iter=60),
                grid, cv=3, refit=False, return_train_score=True)
            gs.fit(X, y)
            results[strat] = (gs, solvers.DISPATCH_COUNTS["solves"])
        gp, dp = results["packed"]
        gq, dq = results["sequential"]
        np.testing.assert_allclose(
            gp.cv_results_["mean_test_score"],
            gq.cv_results_["mean_test_score"], atol=1e-6)
        np.testing.assert_allclose(
            gp.cv_results_["mean_train_score"],
            gq.cv_results_["mean_train_score"], atol=1e-6)
        assert gp.best_index_ == gq.best_index_
        assert dp == 3          # one sweep per fold
        assert dq == 7 * 3      # one solve per (candidate, fold)

    def test_sharded_inputs_take_fast_path(self, rng, mesh, monkeypatch):
        import warnings

        from dask_ml_tpu import solvers
        from dask_ml_tpu.core import shard_rows

        X, grid, y = self._data(rng)
        monkeypatch.setenv("DASK_ML_TPU_GRID_PACK", "packed")
        solvers.reset_dispatch_counts()
        gs = dms.GridSearchCV(
            dlm.LogisticRegression(solver="lbfgs", max_iter=60),
            grid, cv=3, refit=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # unshuffled-KFold notice
            gs.fit(shard_rows(X), shard_rows(y))
        assert solvers.DISPATCH_COUNTS["solves"] == 3
        assert 0.9 < gs.best_score_ <= 1.0

    def test_ineligible_grids_fall_back(self, rng, mesh, monkeypatch):
        from dask_ml_tpu import solvers

        X, grid, y = self._data(rng)
        monkeypatch.setenv("DASK_ML_TPU_GRID_PACK", "packed")
        # a second swept param: not a pure-C grid -> per-candidate path
        solvers.reset_dispatch_counts()
        gs = dms.GridSearchCV(
            dlm.LogisticRegression(solver="lbfgs", max_iter=60),
            {"C": [0.1, 1.0], "fit_intercept": [True, False]},
            cv=2, refit=False)
        gs.fit(X, y)
        assert solvers.DISPATCH_COUNTS["solves"] == 2 * 2 * 2
        # multiclass labels: fall back (sweep is binary-only)
        y3 = rng.randint(0, 3, size=len(y)).astype(np.float32)
        solvers.reset_dispatch_counts()
        gs3 = dms.GridSearchCV(
            dlm.LogisticRegression(solver="lbfgs", max_iter=60),
            {"C": [0.1, 1.0]}, cv=2, refit=False)
        gs3.fit(X, y3)
        assert hasattr(gs3, "cv_results_")

    def test_randomized_search_takes_fast_path(self, rng, mesh,
                                               monkeypatch):
        from scipy.stats import loguniform

        from dask_ml_tpu import solvers

        X, _, y = self._data(rng)
        monkeypatch.setenv("DASK_ML_TPU_GRID_PACK", "packed")
        solvers.reset_dispatch_counts()
        rs = dms.RandomizedSearchCV(
            dlm.LogisticRegression(solver="lbfgs", max_iter=60),
            {"C": loguniform(1e-2, 1e2)}, n_iter=6, cv=2,
            random_state=0, refit=False)
        rs.fit(X, y)
        assert solvers.DISPATCH_COUNTS["solves"] == 2  # one sweep/fold
        best = float(np.max(np.asarray(rs.cv_results_["mean_test_score"])))
        assert 0.9 < best <= 1.0

    def test_linear_regression_sweep_matches_sequential(self, rng, mesh,
                                                        monkeypatch):
        from dask_ml_tpu import solvers

        X = rng.normal(size=(500, 6)).astype(np.float32)
        w = rng.normal(size=6).astype(np.float32)
        y = (X @ w + 0.3 + 0.05 * rng.normal(size=500)).astype(np.float32)
        grid = {"C": np.logspace(0, 6, 5).tolist()}
        results = {}
        for strat in ("packed", "sequential"):
            monkeypatch.setenv("DASK_ML_TPU_GRID_PACK", strat)
            solvers.reset_dispatch_counts()
            gs = dms.GridSearchCV(
                dlm.LinearRegression(solver="lbfgs", max_iter=80),
                grid, cv=3, refit=False)
            gs.fit(X, y)
            results[strat] = (gs, solvers.DISPATCH_COUNTS["solves"])
        gp, dp = results["packed"]
        gq, dq = results["sequential"]
        np.testing.assert_allclose(
            np.asarray(gp.cv_results_["mean_test_score"]),
            np.asarray(gq.cv_results_["mean_test_score"]), atol=1e-5)
        assert gp.best_index_ == gq.best_index_
        assert dp == 3 and dq == 5 * 3

    def test_inplace_mutating_pipeline_is_safe(self, rng):
        # host fold slices must be FRESH per candidate: a Pipeline step
        # with copy=False mutates its input in place, and a shared
        # cached slice would poison every later candidate of the fold
        # (r4 review finding — device slices stay shared: jax arrays
        # are immutable)
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        X = (rng.normal(size=(200, 4)) * 5 + 3).astype(np.float64)
        y = (X[:, 0] > 3).astype(int)
        pipe = Pipeline([
            ("sc", StandardScaler(copy=False)),
            ("clf", SGDClassifier(tol=1e-3, random_state=0)),
        ])
        # the same candidate twice: identical params MUST score
        # identically; under the shared-slice bug the second run fits
        # on already-scaled data
        gs = dms.GridSearchCV(
            pipe, {"clf__alpha": [1e-4, 1e-4]}, cv=2, refit=False,
            cache_cv=False)
        gs.fit(X, y)
        s = np.asarray(gs.cv_results_["mean_test_score"], dtype=float)
        np.testing.assert_allclose(s[0], s[1])

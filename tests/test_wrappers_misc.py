"""Tests for wrappers, impute, naive_bayes, ensemble, compose."""

import numpy as np
import pytest
from sklearn.linear_model import SGDClassifier, SGDRegressor
from sklearn.tree import DecisionTreeClassifier, DecisionTreeRegressor

import dask_ml_tpu as dmt
from dask_ml_tpu.core import shard_rows, unshard
from dask_ml_tpu.ensemble import BlockwiseVotingClassifier, BlockwiseVotingRegressor
from dask_ml_tpu.impute import SimpleImputer
from dask_ml_tpu.naive_bayes import GaussianNB
from dask_ml_tpu.wrappers import Incremental, ParallelPostFit


@pytest.fixture
def clf_data(rng):
    n, d = 400, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int64)
    return X, y


class TestParallelPostFit:
    def test_fit_and_predict(self, clf_data):
        X, y = clf_data
        ppf = ParallelPostFit(DecisionTreeClassifier(max_depth=4)).fit(X, y)
        pred = ppf.predict(shard_rows(X))
        assert pred.shape == (400,)
        assert (pred == ppf.estimator_.predict(X)).all()

    def test_predict_proba(self, clf_data):
        X, y = clf_data
        ppf = ParallelPostFit(DecisionTreeClassifier(max_depth=4)).fit(X, y)
        proba = ppf.predict_proba(X)
        np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-6)

    def test_prefitted_estimator(self, clf_data):
        X, y = clf_data
        inner = DecisionTreeClassifier(max_depth=3).fit(X, y)
        ppf = ParallelPostFit(inner)
        np.testing.assert_array_equal(ppf.predict(X), inner.predict(X))

    def test_score(self, clf_data):
        X, y = clf_data
        ppf = ParallelPostFit(DecisionTreeClassifier(max_depth=8)).fit(X, y)
        assert ppf.score(X, y) > 0.9

    def test_copies_learned_attributes(self, clf_data):
        X, y = clf_data
        ppf = ParallelPostFit(DecisionTreeClassifier(max_depth=3)).fit(X, y)
        assert hasattr(ppf, "classes_")


class TestIncremental:
    def test_streams_partial_fit(self, clf_data):
        X, y = clf_data
        inc = Incremental(
            SGDClassifier(loss="log_loss", random_state=0, tol=None),
            shuffle_blocks=False, chunk_size=50,
        )
        inc.fit(shard_rows(X), shard_rows(y), classes=[0, 1])
        assert inc.score(X, y) > 0.8
        assert hasattr(inc, "coef_")

    def test_partial_fit_continues(self, clf_data):
        X, y = clf_data
        inc = Incremental(
            SGDClassifier(loss="log_loss", random_state=0, tol=None),
            shuffle_blocks=False, chunk_size=100,
        )
        inc.fit(X, y, classes=[0, 1])
        c1 = inc.estimator_.t_
        inc.partial_fit(X, y)
        assert inc.estimator_.t_ > c1  # SGD iteration counter advanced

    def test_shuffle_blocks_deterministic(self, clf_data):
        X, y = clf_data
        kw = dict(shuffle_blocks=True, random_state=3, chunk_size=50)
        a = Incremental(SGDClassifier(random_state=0, tol=None), **kw).fit(X, y, classes=[0, 1])
        b = Incremental(SGDClassifier(random_state=0, tol=None), **kw).fit(X, y, classes=[0, 1])
        np.testing.assert_array_equal(np.asarray(a.coef_), np.asarray(b.coef_))

    def test_regressor(self, rng):
        X = rng.normal(size=(300, 4)).astype(np.float32)
        y = X @ rng.normal(size=4) + 0.01 * rng.normal(size=300)
        inc = Incremental(SGDRegressor(random_state=0, tol=None), chunk_size=100)
        inc.fit(X, y.astype(np.float32))
        assert inc.score(X, y) > 0.8

    def test_length_mismatch_raises(self, clf_data):
        X, y = clf_data
        inc = Incremental(SGDClassifier(tol=None))
        with pytest.raises(ValueError, match="different lengths"):
            inc.fit(X, y[:-5], classes=[0, 1])


class TestSimpleImputer:
    @pytest.mark.parametrize("strategy", ["mean", "median", "most_frequent"])
    def test_parity_with_sklearn(self, rng, strategy):
        from sklearn.impute import SimpleImputer as SkImputer

        X = rng.normal(size=(60, 4)).astype(np.float64)
        X[rng.uniform(size=X.shape) < 0.2] = np.nan
        X[:, 2] = np.round(X[:, 2])  # give most_frequent real ties structure
        ours = SimpleImputer(strategy=strategy).fit(X)
        theirs = SkImputer(strategy=strategy).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.statistics_), theirs.statistics_, atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X), atol=1e-3
        )

    def test_constant(self, rng):
        X = rng.normal(size=(20, 3)).astype(np.float32)
        X[0, 0] = np.nan
        out = np.asarray(SimpleImputer(strategy="constant", fill_value=-1.0).fit_transform(X))
        assert out[0, 0] == -1.0

    def test_constant_requires_fill_value(self, rng):
        with pytest.raises(ValueError, match="fill_value"):
            SimpleImputer(strategy="constant").fit(np.ones((5, 2), dtype=np.float32))

    def test_bad_strategy(self):
        with pytest.raises(ValueError, match="strategy"):
            SimpleImputer(strategy="mode").fit(np.ones((5, 2), dtype=np.float32))

    def test_sharded_input(self, rng):
        X = rng.normal(size=(37, 3)).astype(np.float32)
        X[5, 1] = np.nan
        s = shard_rows(X)
        imp = SimpleImputer().fit(s)
        out = unshard(imp.transform(s))
        assert np.isfinite(out).all()

    def test_all_missing_column_raises(self):
        X = np.ones((10, 2), dtype=np.float32)
        X[:, 1] = np.nan
        with pytest.raises(ValueError, match="no observed values"):
            SimpleImputer().fit(X)


class TestGaussianNB:
    def test_parity_with_sklearn(self, rng):
        from sklearn.naive_bayes import GaussianNB as SkGNB

        from sklearn.datasets import make_blobs

        X, y = make_blobs(n_samples=300, centers=3, n_features=4, random_state=0)
        X = X.astype(np.float32)
        ours = GaussianNB().fit(shard_rows(X), y)
        theirs = SkGNB().fit(X, y)
        np.testing.assert_allclose(np.asarray(ours.theta_), theirs.theta_, atol=1e-3)
        np.testing.assert_allclose(np.asarray(ours.var_), theirs.var_, rtol=1e-2)
        np.testing.assert_array_equal(np.asarray(ours.predict(X)), theirs.predict(X))
        assert ours.score(X, y.astype(np.float32)) == pytest.approx(theirs.score(X, y))

    def test_predict_proba_normalized(self, rng):
        X = rng.normal(size=(50, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        nb = GaussianNB().fit(X, y)
        proba = np.asarray(nb.predict_proba(X))
        np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)

    def test_priors(self, rng):
        X = rng.normal(size=(50, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        nb = GaussianNB(priors=[0.9, 0.1]).fit(X, y)
        np.testing.assert_allclose(np.asarray(nb.class_prior_), [0.9, 0.1])


class TestBlockwiseEnsembles:
    def test_classifier_hard_vote(self, clf_data):
        X, y = clf_data
        ens = BlockwiseVotingClassifier(
            DecisionTreeClassifier(max_depth=4), n_blocks=5
        ).fit(shard_rows(X), y)
        assert len(ens.estimators_) == 5
        assert ens.score(X, y) > 0.8

    def test_classifier_soft_vote(self, clf_data):
        X, y = clf_data
        ens = BlockwiseVotingClassifier(
            DecisionTreeClassifier(max_depth=4), voting="soft", n_blocks=4
        ).fit(X, y)
        proba = ens.predict_proba(X)
        np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-6)
        assert ens.score(X, y) > 0.8

    def test_hard_vote_no_predict_proba(self, clf_data):
        X, y = clf_data
        ens = BlockwiseVotingClassifier(DecisionTreeClassifier(), voting="hard").fit(X, y)
        with pytest.raises(AttributeError, match="soft"):
            ens.predict_proba(X)

    def test_regressor_mean(self, rng):
        X = rng.normal(size=(300, 4)).astype(np.float32)
        y = (X @ rng.normal(size=4)).astype(np.float32)
        ens = BlockwiseVotingRegressor(DecisionTreeRegressor(max_depth=6), n_blocks=4).fit(X, y)
        assert ens.score(X, y) > 0.7

    def test_bad_voting(self, clf_data):
        X, y = clf_data
        with pytest.raises(ValueError, match="voting"):
            BlockwiseVotingClassifier(DecisionTreeClassifier(), voting="mean").fit(X, y)

    def test_packed_fit_never_unshards_device_input(self, clf_data, monkeypatch):
        """Packable (SGD) members + ShardedRows input must slice blocks on
        device: the fit path may not call unshard (an O(n) device→host
        fetch — minutes at scale on the axon relay)."""
        import dask_ml_tpu.ensemble._blockwise as bw
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = clf_data

        def _forbidden(*a, **k):  # pragma: no cover - should not run
            raise AssertionError("unshard called on the packed fit path")

        monkeypatch.setattr(bw, "unshard", _forbidden)
        ens = BlockwiseVotingClassifier(
            SGDClassifier(max_iter=20, random_state=0, tol=None), n_blocks=4
        ).fit(shard_rows(X), shard_rows(y.astype(np.float32)))
        assert len(ens.estimators_) == 4
        assert sorted(ens.classes_.tolist()) == sorted(np.unique(y).tolist())
        # inference back on host data still works
        assert (ens.predict(X) == y).mean() > 0.7

    def test_packed_fit_matches_threaded_quality(self, clf_data):
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = clf_data
        ens = BlockwiseVotingClassifier(
            SGDClassifier(max_iter=50, random_state=0), n_blocks=3
        ).fit(X, y)
        assert ens.score(X, y) > 0.8


class TestColumnTransformer:
    def test_basic_columns(self, rng):
        import pandas as pd
        from dask_ml_tpu.compose import ColumnTransformer
        from dask_ml_tpu.preprocessing import StandardScaler as OurScaler
        from sklearn.preprocessing import StandardScaler

        df = pd.DataFrame({"a": rng.normal(size=30), "b": rng.normal(size=30) * 5})
        ct = ColumnTransformer([("s", StandardScaler(), ["a", "b"])])
        out = ct.fit_transform(df)
        np.testing.assert_allclose(np.asarray(out).std(0), 1.0, rtol=1e-2)

    def test_make_column_transformer(self, rng):
        from dask_ml_tpu.compose import make_column_transformer
        from sklearn.preprocessing import StandardScaler

        ct = make_column_transformer((StandardScaler(), [0, 1]))
        out = ct.fit_transform(rng.normal(size=(30, 3)))
        assert np.asarray(out).shape == (30, 2)


class TestReviewRegressions:
    def test_gaussian_nb_large_mean_variance(self, rng):
        from sklearn.naive_bayes import GaussianNB as SkGNB

        X = (rng.normal(size=(2000, 3)) + 5000).astype(np.float32)
        y = (X[:, 0] > 5000).astype(np.int64)
        ours = GaussianNB().fit(X, y)
        theirs = SkGNB().fit(X, y)
        np.testing.assert_allclose(np.asarray(ours.var_), theirs.var_, rtol=0.05)
        assert float(ours.score(X, y.astype(np.float32))) > 0.95

    def test_soft_vote_aligns_partial_classes(self, rng):
        # each block sees only a subset of the 3 classes
        X = rng.normal(size=(90, 2)).astype(np.float32)
        y = np.repeat([0, 1, 2], 30)
        ens = BlockwiseVotingClassifier(
            DecisionTreeClassifier(), voting="soft", n_blocks=3
        ).fit(X, y)
        proba = ens.predict_proba(X)
        assert proba.shape == (90, 3)
        np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-6)

    def test_hard_vote_unsorted_classes_param(self, rng):
        X = rng.normal(size=(60, 2)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        ens = BlockwiseVotingClassifier(
            DecisionTreeClassifier(max_depth=3), classes=[1, 0], n_blocks=3
        ).fit(X, y)
        pred = ens.predict(X)
        assert set(np.unique(pred)) <= {0, 1}

    def test_imputer_add_indicator(self, rng):
        from sklearn.impute import SimpleImputer as SkImputer

        X = rng.normal(size=(30, 3)).astype(np.float64)
        X[::5, 1] = np.nan
        ours = np.asarray(SimpleImputer(add_indicator=True).fit_transform(X))
        theirs = SkImputer(add_indicator=True).fit_transform(X)
        assert ours.shape == theirs.shape == (30, 4)
        np.testing.assert_allclose(ours, theirs, atol=1e-3)

    def test_ppf_device_native_passthrough(self, rng):
        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core.sharded import ShardedRows

        X = rng.normal(size=(64, 3)).astype(np.float32)
        s = shard_rows(X)
        ppf = ParallelPostFit(KMeans(n_clusters=2, random_state=0)).fit(s)
        out = ppf.predict(s)
        assert np.asarray(out).shape == (64,)

    def test_make_column_transformer_sparse_threshold(self, rng):
        from dask_ml_tpu.compose import make_column_transformer
        from sklearn.preprocessing import StandardScaler

        ct = make_column_transformer((StandardScaler(), [0]), sparse_threshold=0.5)
        assert ct.sparse_threshold == 0.5


class TestBlockwiseParallelFits:
    """VERDICT round-1 weak #5: per-block fits are genuinely parallel —
    packed single-dispatch for device-native members, thread pool for
    host sklearn members."""

    def test_packed_sgd_ensemble_trains_on_device(self, rng):
        import jax

        from dask_ml_tpu.ensemble import BlockwiseVotingClassifier
        from dask_ml_tpu.linear_model import SGDClassifier

        n, d = 2000, 6
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X @ rng.normal(size=d) > 0).astype(np.int64)
        ens = BlockwiseVotingClassifier(
            SGDClassifier(learning_rate="constant", eta0=0.3, max_iter=200,
                          tol=None),
            n_blocks=4,
        ).fit(X, y)
        assert len(ens.estimators_) == 4
        for m in ens.estimators_:
            assert isinstance(m._state["coef"], jax.Array)
            assert m.t_ > 0
        assert (ens.predict(X) == y).mean() > 0.9

    def test_packed_members_differ_across_blocks(self, rng):
        # each member must train on ITS block, not shared data
        from dask_ml_tpu.ensemble import BlockwiseVotingRegressor
        from dask_ml_tpu.linear_model import SGDRegressor

        n, d = 1600, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d)
        y = (X @ w).astype(np.float32)
        y[: n // 2] += 5.0  # first two blocks see a shifted target
        ens = BlockwiseVotingRegressor(
            SGDRegressor(learning_rate="constant", eta0=0.1, max_iter=300,
                         tol=None),
            n_blocks=4,
        ).fit(X, y)
        ints = [float(m.intercept_[0]) for m in ens.estimators_]
        assert abs(ints[0] - 5) < 1 and abs(ints[-1]) < 1

    def test_sklearn_threadpool_speedup(self, rng):
        import time as _t

        from sklearn.base import BaseEstimator

        from dask_ml_tpu.ensemble import BlockwiseVotingRegressor

        class Sleepy(BaseEstimator):
            def fit(self, X, y=None):
                _t.sleep(0.08)
                self.fitted_ = True
                return self

            def predict(self, X):
                return np.zeros(len(X))

        X = rng.normal(size=(80, 3))
        y = np.zeros(80)
        t0 = _t.perf_counter()
        BlockwiseVotingRegressor(Sleepy(), n_blocks=8).fit(X, y)
        wall = _t.perf_counter() - t0
        assert wall < 8 * 0.08 / 1.5, wall  # overlapped, not serial

    def test_parity_with_serial_semantics(self, rng):
        # thread-pool fits must produce the same members as the old serial
        # loop (deterministic estimators)
        from sklearn.linear_model import LinearRegression

        from dask_ml_tpu.ensemble import BlockwiseVotingRegressor

        n, d = 800, 5
        X = rng.normal(size=(n, d)).astype(np.float64)
        y = X @ rng.normal(size=d)
        ens = BlockwiseVotingRegressor(LinearRegression(), n_blocks=4).fit(X, y)
        bounds = np.linspace(0, n, 5, dtype=int)
        for m, (lo, hi) in zip(ens.estimators_, zip(bounds[:-1], bounds[1:])):
            ref = LinearRegression().fit(X[lo:hi], y[lo:hi])
            np.testing.assert_allclose(m.coef_, ref.coef_, rtol=1e-8)

    def test_threadpool_members_see_caller_mesh(self, rng):
        from sklearn.base import BaseEstimator

        from dask_ml_tpu.core.mesh import device_mesh, get_mesh, use_mesh
        from dask_ml_tpu.ensemble import BlockwiseVotingRegressor

        seen = []

        class MeshSpy(BaseEstimator):
            def fit(self, X, y=None):
                seen.append(dict(get_mesh().shape))
                self.fitted_ = True
                return self

            def predict(self, X):
                return np.zeros(len(X))

        from conftest import require_devices_divisible

        X = rng.normal(size=(80, 3))
        n_dev = require_devices_divisible(4)
        with use_mesh(device_mesh(n_dev, model_axis=4)):
            BlockwiseVotingRegressor(MeshSpy(), n_blocks=4).fit(X, np.zeros(80))
        assert seen and all(
            s == {"data": n_dev // 4, "model": 4} for s in seen)


class TestPackedEnsembleNoSilentCaps:
    def test_ragged_tail_rows_are_kept(self, rng, mesh, monkeypatch):
        # n chosen so linspace spans are UNEQUAL (307 over 4 blocks:
        # 76/77/77/77); the packed path must mask-pad, not trim rows —
        # the total mask weight entering the epoch program must equal n
        from dask_ml_tpu.ensemble import _blockwise as bw
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD

        n = 307
        X = rng.normal(size=(n, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        seen = {}
        orig = bw._ensemble_epoch

        def spy(states, xb, yb, mask, hypers, **kw):
            seen["mask_total"] = float(np.asarray(mask).sum())
            return orig(states, xb, yb, mask, hypers, **kw)

        monkeypatch.setattr(bw, "_ensemble_epoch", spy)
        BlockwiseVotingClassifier(
            TpuSGD(max_iter=2, random_state=0), n_blocks=4
        ).fit(X, y, classes=[0.0, 1.0])
        assert seen["mask_total"] == n

    def test_packed_parity_on_ragged_blocks(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD

        n = 307
        X = rng.normal(size=(n, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        ens = BlockwiseVotingClassifier(
            TpuSGD(max_iter=20, random_state=0), n_blocks=4
        ).fit(X, y, classes=[0.0, 1.0])
        assert len(ens.estimators_) == 4
        assert ens.score(X, y) > 0.8


class TestCohortModelAxisSkipLogs:
    def test_warning_logged_when_not_divisible(self, rng, caplog):
        import logging

        import jax
        from jax.sharding import Mesh

        from dask_ml_tpu.core.mesh import use_mesh
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD
        from dask_ml_tpu.model_selection._packing import Cohort

        if len(jax.devices()) < 8:
            pytest.skip("needs >= 8 devices")
        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh2d = Mesh(devs, ("data", "model"))
        X = rng.normal(size=(64, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        models = [TpuSGD(alpha=a, random_state=0) for a in (1e-4, 1e-3, 1e-2)]
        with use_mesh(mesh2d):
            cohort = Cohort(models, classes=[0.0, 1.0])
            with caplog.at_level(
                logging.WARNING, logger="dask_ml_tpu.model_selection._packing"
            ):
                cohort.step(X, y)
        assert any("MODEL_AXIS" in r.message for r in caplog.records)


class TestStreamingInference:
    def test_predict_blocks_matches_predict(self, rng, mesh):
        from sklearn.linear_model import LogisticRegression as SkLR

        from dask_ml_tpu.wrappers import ParallelPostFit

        X = rng.normal(size=(1000, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(int)
        pf = ParallelPostFit(SkLR(max_iter=200)).fit(X[:200], y[:200])
        chunks = list(pf.predict_blocks(X, chunk_size=300))
        assert [c.shape[0] for c in chunks] == [300, 300, 300, 100]
        np.testing.assert_array_equal(
            np.concatenate(chunks), pf.predict(X)
        )

    def test_predict_blocks_from_block_iterable(self, rng, mesh):
        # inference over a stream of blocks that never exists as one array
        from sklearn.linear_model import LogisticRegression as SkLR

        from dask_ml_tpu.wrappers import ParallelPostFit

        X = rng.normal(size=(600, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(int)
        pf = ParallelPostFit(SkLR(max_iter=200)).fit(X, y)
        blocks = (X[lo: lo + 150] for lo in range(0, 600, 150))
        outs = list(pf.predict_blocks(blocks))
        np.testing.assert_array_equal(np.concatenate(outs), pf.predict(X))

    def test_predict_proba_blocks(self, rng, mesh):
        from sklearn.linear_model import LogisticRegression as SkLR

        from dask_ml_tpu.wrappers import ParallelPostFit

        X = rng.normal(size=(400, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(int)
        pf = ParallelPostFit(SkLR(max_iter=200)).fit(X, y)
        outs = list(pf.predict_blocks(X, method="predict_proba",
                                      chunk_size=100))
        assert all(o.shape == (100, 2) for o in outs)

    def test_predict_blocks_sparse_matrix_stays_sparse(self, rng, mesh):
        import scipy.sparse
        from sklearn.linear_model import LogisticRegression as SkLR

        from dask_ml_tpu.wrappers import ParallelPostFit

        Xd = rng.normal(size=(500, 8)).astype(np.float32)
        y = (Xd[:, 0] > 0).astype(int)
        pf = ParallelPostFit(SkLR(max_iter=200)).fit(Xd, y)
        Xs = scipy.sparse.csr_matrix(Xd)
        seen_sparse = []
        orig = pf.estimator_.predict

        def spy(b):
            seen_sparse.append(scipy.sparse.issparse(b))
            return orig(b)

        pf.estimator_.predict = spy
        outs = list(pf.predict_blocks(Xs, chunk_size=200))
        assert all(seen_sparse) and len(outs) == 3
        np.testing.assert_array_equal(
            np.concatenate(outs), pf.estimator_.predict(Xd)
        )

    def test_predict_blocks_sharded_no_full_unshard(self, rng, mesh, monkeypatch):
        # device estimator + sharded input: one sharded program, chunked
        # result fetches, NO unshard of the input
        import dask_ml_tpu.wrappers as wr
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD
        from dask_ml_tpu.wrappers import ParallelPostFit

        X = rng.normal(size=(800, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        pf = ParallelPostFit(TpuSGD(max_iter=20, random_state=0)).fit(
            X, y, classes=[0.0, 1.0]
        )

        def _boom(a):
            raise AssertionError("full unshard in predict_blocks")

        monkeypatch.setattr(wr, "unshard", _boom)
        outs = list(pf.predict_blocks(shard_rows(X), chunk_size=250))
        assert sum(o.shape[0] for o in outs) == 800

    def test_weighted_members_use_fallback_and_keep_weights(self, rng, mesh):
        # a class-weighted member must NOT take the packed ensemble path
        # (which has no weight plumbing) — the threaded fallback applies
        # the weights through est.fit
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD

        n = 400
        X = rng.normal(size=(n, 4)).astype(np.float32)
        y = (X[:, 0] + 1.0 > 0).astype(np.float32)
        up = BlockwiseVotingClassifier(
            TpuSGD(max_iter=40, random_state=0, tol=None,
                   class_weight={0.0: 8.0, 1.0: 1.0}),
            n_blocks=2,
        ).fit(X, y, classes=[0.0, 1.0])
        plain = BlockwiseVotingClassifier(
            TpuSGD(max_iter=40, random_state=0, tol=None), n_blocks=2
        ).fit(X, y, classes=[0.0, 1.0])
        rec0 = lambda m: float(  # noqa: E731
            ((np.asarray(m.predict(X)) == 0) & (y == 0)).sum()
        ) / max((y == 0).sum(), 1)
        assert rec0(up) > rec0(plain)

    def test_predict_blocks_sparse_outputs_stay_sparse(self, rng, mesh):
        import scipy.sparse
        from sklearn.feature_extraction.text import TfidfTransformer

        from dask_ml_tpu.wrappers import ParallelPostFit

        counts = scipy.sparse.random(
            300, 50, density=0.1, random_state=0, format="csr"
        )
        pf = ParallelPostFit(TfidfTransformer()).fit(counts)
        outs = list(pf.predict_blocks(counts, method="transform",
                                      chunk_size=100))
        assert all(scipy.sparse.issparse(o) for o in outs)
        assert sum(o.shape[0] for o in outs) == 300


class TestGaussianNBPartialFit:
    """sklearn-contract partial_fit: per-class Chan moment merges — a
    stream of blocks must reproduce the whole-array fit exactly."""

    def test_stream_matches_fit(self, rng):
        from dask_ml_tpu.naive_bayes import GaussianNB

        X = rng.normal(size=(300, 4)).astype(np.float32) * 2 + 5
        y = rng.randint(0, 3, size=300)
        full = GaussianNB().fit(X, y)
        stream = GaussianNB()
        for lo in range(0, 300, 100):
            stream.partial_fit(X[lo:lo + 100], y[lo:lo + 100],
                               classes=[0, 1, 2])
        np.testing.assert_allclose(
            np.asarray(stream.theta_), np.asarray(full.theta_), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(stream.var_), np.asarray(full.var_), rtol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(stream.class_count_), np.asarray(full.class_count_)
        )

    def test_parity_with_sklearn_stream(self, rng):
        from sklearn.naive_bayes import GaussianNB as SkNB

        from dask_ml_tpu.naive_bayes import GaussianNB

        X = rng.normal(size=(240, 3)).astype(np.float32)
        X[:120] += 2.0
        y = np.r_[np.zeros(120, int), np.ones(120, int)]
        ours, sk = GaussianNB(), SkNB()
        for lo in range(0, 240, 80):
            ours.partial_fit(X[lo:lo + 80], y[lo:lo + 80], classes=[0, 1])
            sk.partial_fit(X[lo:lo + 80], y[lo:lo + 80], classes=[0, 1])
        np.testing.assert_allclose(
            np.asarray(ours.theta_), sk.theta_, rtol=1e-4, atol=1e-5
        )
        agree = (np.asarray(ours.predict(X)) == sk.predict(X)).mean()
        assert agree > 0.99

    def test_requires_classes_first_call(self, rng):
        from dask_ml_tpu.naive_bayes import GaussianNB

        X = rng.normal(size=(50, 3)).astype(np.float32)
        with pytest.raises(ValueError, match="classes"):
            GaussianNB().partial_fit(X, np.zeros(50, int))

    def test_unknown_label_raises(self, rng):
        from dask_ml_tpu.naive_bayes import GaussianNB

        X = rng.normal(size=(50, 3)).astype(np.float32)
        nb = GaussianNB().partial_fit(
            X, np.zeros(50, int), classes=[0, 1]
        )
        with pytest.raises(ValueError, match="not in classes_"):
            nb.partial_fit(X, np.full(50, 7))

    def test_streams_through_incremental(self, rng):
        from dask_ml_tpu.naive_bayes import GaussianNB
        from dask_ml_tpu.wrappers import Incremental

        X = rng.normal(size=(256, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        X[y == 1] += 3.0
        inc = Incremental(GaussianNB(), chunk_size=64).fit(
            X, y, classes=[0, 1]
        )
        assert (np.asarray(inc.predict(X)) == y).mean() > 0.9

    def test_weighted_variance_correct(self, rng):
        # regression: the two-pass dev must select class means through the
        # BINARY onehot, not the weighted mask (which scaled the mean by
        # each row's weight and inflated variances ~25x)
        from sklearn.naive_bayes import GaussianNB as SkNB

        from dask_ml_tpu.naive_bayes import GaussianNB

        X = (rng.normal(size=(200, 3)) + 4).astype(np.float32)
        y = rng.randint(0, 2, 200)
        w = rng.uniform(0.5, 3.0, 200)
        ours = GaussianNB().fit(X, y, sample_weight=w)
        sk = SkNB().fit(X, y, sample_weight=w)
        np.testing.assert_allclose(
            np.asarray(ours.var_), sk.var_, rtol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(ours.theta_), sk.theta_, rtol=1e-4
        )

    def test_classes_mismatch_on_later_call_raises(self, rng):
        from dask_ml_tpu.naive_bayes import GaussianNB

        X = rng.normal(size=(40, 2)).astype(np.float32)
        nb = GaussianNB().partial_fit(
            X, np.zeros(40, int), classes=[0, 1]
        )
        with pytest.raises(ValueError, match="not the same"):
            nb.partial_fit(X, np.zeros(40, int), classes=[1, 2])
        nb.partial_fit(X, np.zeros(40, int), classes=[1, 0])  # same set: ok

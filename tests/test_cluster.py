import numpy as np
import pytest
import sklearn.cluster as sc
from sklearn.metrics import adjusted_rand_score

import dask_ml_tpu.cluster as dc
from dask_ml_tpu import datasets
from dask_ml_tpu.core import shard_rows, unshard


@pytest.fixture
def blobs(rng):
    from sklearn.datasets import make_blobs

    X, y = make_blobs(n_samples=500, centers=4, n_features=5,
                      cluster_std=0.5, random_state=7)
    return X.astype(np.float32), y


class TestKMeans:
    def test_recovers_blobs(self, blobs):
        X, y = blobs
        km = dc.KMeans(n_clusters=4, random_state=0).fit(shard_rows(X))
        assert adjusted_rand_score(y, np.asarray(km.labels_)) > 0.95

    def test_matches_sklearn_inertia(self, blobs):
        X, y = blobs
        ours = dc.KMeans(n_clusters=4, random_state=0).fit(X)
        theirs = sc.KMeans(n_clusters=4, n_init=10, random_state=0).fit(X)
        assert ours.inertia_ == pytest.approx(theirs.inertia_, rel=0.05)

    def test_fitted_attributes(self, blobs):
        X, _ = blobs
        km = dc.KMeans(n_clusters=4, random_state=0).fit(X)
        assert km.cluster_centers_.shape == (4, 5)
        assert np.asarray(km.labels_).shape == (500,)
        assert km.inertia_ > 0
        assert 1 <= km.n_iter_ <= 300

    def test_predict_consistent_with_labels(self, blobs):
        X, _ = blobs
        km = dc.KMeans(n_clusters=4, random_state=0).fit(X)
        np.testing.assert_array_equal(np.asarray(km.predict(X)), np.asarray(km.labels_))

    def test_transform_shape_and_meaning(self, blobs):
        X, _ = blobs
        km = dc.KMeans(n_clusters=4, random_state=0).fit(X)
        d = np.asarray(km.transform(X))
        assert d.shape == (500, 4)
        np.testing.assert_array_equal(d.argmin(1), np.asarray(km.labels_))

    def test_explicit_init_array(self, blobs):
        X, y = blobs
        init = X[np.random.RandomState(0).choice(500, 4, replace=False)]
        km = dc.KMeans(n_clusters=4, init=init).fit(X)
        assert adjusted_rand_score(y, np.asarray(km.labels_)) > 0.5

    def test_random_init(self, blobs):
        X, y = blobs
        km = dc.KMeans(n_clusters=4, init="random", random_state=2).fit(X)
        assert km.inertia_ > 0

    def test_score_is_negative_inertia(self, blobs):
        X, _ = blobs
        km = dc.KMeans(n_clusters=4, random_state=0).fit(X)
        assert km.score(X) == pytest.approx(-km.inertia_, rel=1e-5)

    def test_too_few_samples_raises(self):
        with pytest.raises(ValueError, match="n_samples"):
            dc.KMeans(n_clusters=10).fit(np.ones((5, 2), dtype=np.float32))

    def test_bad_init_shape_raises(self, blobs):
        X, _ = blobs
        with pytest.raises(ValueError, match="init array"):
            dc.KMeans(n_clusters=4, init=np.ones((3, 5))).fit(X)

    def test_oversampling_factor(self, blobs):
        X, y = blobs
        km = dc.KMeans(n_clusters=4, oversampling_factor=4, random_state=0).fit(X)
        assert adjusted_rand_score(y, np.asarray(km.labels_)) > 0.9

    def test_sharded_uneven_rows(self, rng):
        # row count not divisible by mesh: mask must keep padding out of centers
        from sklearn.datasets import make_blobs

        X, y = make_blobs(n_samples=501, centers=3, n_features=4,
                          cluster_std=0.3, random_state=1)
        X = X.astype(np.float32) + 100.0  # far from the zero pad rows
        km = dc.KMeans(n_clusters=3, random_state=0).fit(shard_rows(X))
        assert adjusted_rand_score(y, np.asarray(km.labels_)) > 0.95
        # no center got dragged toward the origin by pad rows
        assert np.linalg.norm(np.asarray(km.cluster_centers_), axis=1).min() > 50


class TestSpectralClustering:
    def test_concentric_circles(self, rng):
        from sklearn.datasets import make_circles

        X, y = make_circles(n_samples=400, factor=0.3, noise=0.05, random_state=0)
        X = X.astype(np.float32)
        spec = dc.SpectralClustering(
            n_clusters=2, n_components=100, gamma=30.0, random_state=0
        ).fit(shard_rows(X))
        assert adjusted_rand_score(y, np.asarray(spec.labels_)) > 0.9

    def test_blobs(self, blobs):
        X, y = blobs
        spec = dc.SpectralClustering(
            n_clusters=4, n_components=80, random_state=0
        ).fit(X)
        assert adjusted_rand_score(y, np.asarray(spec.labels_)) > 0.8

    def test_persist_embedding(self, blobs):
        X, _ = blobs
        spec = dc.SpectralClustering(
            n_clusters=4, n_components=50, random_state=0, persist_embedding=True
        ).fit(X)
        assert unshard(spec.embedding_).shape == (500, 4)

    def test_bad_affinity(self, blobs):
        X, _ = blobs
        with pytest.raises(ValueError, match="affinity"):
            dc.SpectralClustering(affinity="chi2").fit(X)

    def test_nearest_neighbors_affinity(self, rng):
        from sklearn.datasets import make_circles

        X, y = make_circles(n_samples=400, factor=0.3, noise=0.05, random_state=0)
        spec = dc.SpectralClustering(
            n_clusters=2, n_components=120, affinity="nearest_neighbors",
            n_neighbors=12, random_state=0,
        ).fit(shard_rows(X.astype(np.float32)))
        assert adjusted_rand_score(y, np.asarray(spec.labels_)) > 0.8

    def test_precomputed_affinity_matches_rbf(self, rng):
        from sklearn.datasets import make_circles
        from sklearn.metrics.pairwise import rbf_kernel as sk_rbf

        X, y = make_circles(n_samples=300, factor=0.3, noise=0.05, random_state=0)
        X = X.astype(np.float32)
        W = sk_rbf(X, gamma=30.0).astype(np.float32)
        spec = dc.SpectralClustering(
            n_clusters=2, n_components=100, affinity="precomputed",
            random_state=0,
        ).fit(shard_rows(W))
        assert adjusted_rand_score(y, np.asarray(spec.labels_)) > 0.9

    def test_exact_path_n_components_none(self, rng):
        from sklearn.datasets import make_circles

        X, y = make_circles(n_samples=300, factor=0.3, noise=0.05, random_state=0)
        spec = dc.SpectralClustering(
            n_clusters=2, n_components=None, gamma=30.0, random_state=0,
        ).fit(shard_rows(X.astype(np.float32)))
        assert adjusted_rand_score(y, np.asarray(spec.labels_)) > 0.9

    def test_exact_path_affinity_variants(self, rng):
        # precomputed (non-divisible n -> column padding), polynomial, and
        # callable all flow through _full_affinity's exact branches
        from sklearn.datasets import make_blobs
        from sklearn.metrics.pairwise import rbf_kernel as sk_rbf

        X, y = make_blobs(n_samples=203, n_features=4, centers=3,
                          cluster_std=0.5, random_state=0)
        X = X.astype(np.float32)

        W = sk_rbf(X, gamma=2.0).astype(np.float32)
        pre = dc.SpectralClustering(
            n_clusters=3, n_components=None, affinity="precomputed",
            random_state=0,
        ).fit(shard_rows(W))
        assert adjusted_rand_score(y, np.asarray(pre.labels_)) > 0.9

        import jax.numpy as jnp

        def my_affinity(a, b):
            d2 = (
                jnp.sum(a * a, 1)[:, None] + jnp.sum(b * b, 1)[None, :]
                - 2 * a @ b.T
            )
            return jnp.exp(-2.0 * jnp.maximum(d2, 0))

        cal = dc.SpectralClustering(
            n_clusters=3, n_components=None, affinity=my_affinity,
            random_state=0,
        ).fit(shard_rows(X))
        assert adjusted_rand_score(y, np.asarray(cal.labels_)) > 0.9

    def test_exact_path_negative_eigenvalue_spectrum(self, rng):
        # near-bipartite graph: dominant NEGATIVE eigenvalues must not
        # crowd the wanted positive eigenvectors out of the subspace
        import scipy.linalg as sla

        k, sz = 6, 12
        blocks = []
        for _ in range(k):
            half = sz // 2
            B = np.zeros((sz, sz), np.float32)
            B[:half, half:] = 1.0
            B[half:, :half] = 1.0
            blocks.append(B)
        W = sla.block_diag(*blocks).astype(np.float32)
        y = np.repeat(np.arange(k), sz)
        spec = dc.SpectralClustering(
            n_clusters=k, n_components=None, affinity="precomputed",
            random_state=0,
        ).fit(shard_rows(W))
        np.testing.assert_allclose(np.asarray(spec.eigenvalues_), 1.0, atol=1e-3)
        assert adjusted_rand_score(y, np.asarray(spec.labels_)) > 0.99

    def test_knn_exact_neighbor_count_with_duplicates(self, rng):
        # ties at the kth distance must not blow degrees past k
        from dask_ml_tpu.cluster.spectral import _knn_graph
        import jax.numpy as jnp

        X = np.repeat(rng.normal(size=(4, 3)).astype(np.float32), 10, axis=0)
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        mask = np.ones(40, np.float32)
        W = np.asarray(_knn_graph(jnp.asarray(d2), jnp.asarray(mask), k_nn=5))
        # out-degree before symmetrization is exactly 5; after union-
        # symmetrization degree is bounded by 2k, not the duplicate-group
        # size (10+ under the old tie-inclusive threshold)
        assert W.sum(axis=1).max() <= 10

    def test_exact_guard_rejects_huge_n(self, rng):
        from dask_ml_tpu.cluster import spectral as sp

        spec = dc.SpectralClustering(n_clusters=2, n_components=None)
        orig = sp._EXACT_MAX_ROWS
        sp._EXACT_MAX_ROWS = 100
        try:
            with pytest.raises(ValueError, match="exceeds"):
                spec.fit(shard_rows(rng.normal(size=(200, 3)).astype(np.float32)))
        finally:
            sp._EXACT_MAX_ROWS = orig


class TestDatasets:
    def test_make_blobs_sharded(self):
        X, y = datasets.make_blobs(n_samples=200, n_features=3, centers=4,
                                   chunks=50, random_state=0)
        assert X.shape == (200, 3)
        assert y.shape == (200,)
        assert len(np.unique(unshard(y))) == 4

    def test_make_classification(self):
        X, y = datasets.make_classification(n_samples=100, n_features=10,
                                            chunks=25, random_state=0)
        assert X.shape == (100, 10)
        assert set(np.unique(unshard(y))) == {0, 1}

    def test_make_regression(self):
        X, y = datasets.make_regression(n_samples=100, n_features=7,
                                        random_state=0)
        assert X.shape == (100, 7)
        assert unshard(y).std() > 0

    def test_make_counts(self):
        X, y = datasets.make_counts(n_samples=100, n_features=5, random_state=0)
        yv = unshard(y)
        assert (yv >= 0).all() and yv.dtype.kind == "f"

    def test_chunk_seeds_differ(self):
        X, _ = datasets.make_blobs(n_samples=100, chunks=50, random_state=0)
        a = unshard(X)[:50]
        b = unshard(X)[50:]
        assert not np.allclose(a, b)


class TestReviewRegressions:
    def test_tol_not_inflated_by_padding(self):
        # heavy padding + data far from origin: must still iterate, not stop at 1
        rng = np.random.RandomState(0)
        X = (rng.normal(size=(33, 4)) + 100).astype(np.float32)  # pads 33->40
        km = dc.KMeans(n_clusters=3, init="random", random_state=0, tol=1e-6).fit(shard_rows(X))
        assert np.isfinite(km.inertia_)

    def test_kmeanspp_respects_random_state(self, blobs):
        # assert on the INIT centers themselves: even one Lloyd round can
        # snap two different seeds' inits onto the identical partition
        # means on well-separated blobs, which is convergence working,
        # not the seed being ignored
        X, _ = blobs
        from dask_ml_tpu.cluster.k_means import _ingest_float
        from dask_ml_tpu.core.prng import as_key

        km = dc.KMeans(n_clusters=4, init="k-means++")
        Xi = _ingest_float(km, X)
        c1 = km._init_centers(Xi, as_key(1))
        c2 = km._init_centers(Xi, as_key(2))
        assert not np.allclose(np.asarray(c1), np.asarray(c2))

    def test_make_blobs_seed_changes_centers(self):
        X1, _ = datasets.make_blobs(n_samples=50, n_features=2, centers=3, random_state=1)
        X2, _ = datasets.make_blobs(n_samples=50, n_features=2, centers=3, random_state=2)
        assert not np.allclose(unshard(X1), unshard(X2))

    def test_make_counts_chunks_effective(self):
        X1, y1 = datasets.make_counts(n_samples=100, n_features=5, chunks=50, random_state=0)
        a, b = unshard(X1)[:50], unshard(X1)[50:]
        assert not np.allclose(a, b)  # distinct per-chunk seeds

    def test_spectral_kmeans_params_random_state(self, blobs):
        X, _ = blobs
        spec = dc.SpectralClustering(
            n_clusters=4, n_components=40, random_state=0,
            kmeans_params={"random_state": 5, "max_iter": 50},
        ).fit(X)
        assert np.asarray(spec.labels_).shape == (500,)


class TestFloat16KMeans:
    def test_fit_float16_input(self, rng, mesh):
        # the validity sentinel must be dtype-aware: 1e30 overflows to inf
        # in float16 and would NaN-poison the init distances
        import numpy as np

        from dask_ml_tpu.cluster import KMeans
        from dask_ml_tpu.core import shard_rows

        X = np.concatenate([
            rng.normal(0, 0.3, (200, 4)), rng.normal(8, 0.3, (200, 4))
        ]).astype(np.float16)
        km = KMeans(n_clusters=2, random_state=0).fit(shard_rows(X))
        got = np.sort(np.asarray(km.cluster_centers_)[:, 0].astype(np.float64))
        np.testing.assert_allclose(got, [0.0, 8.0], atol=1.0)


class TestMiniBatchKMeans:
    def test_recovers_blobs(self, blobs):
        X, y = blobs
        mbk = dc.MiniBatchKMeans(
            n_clusters=4, batch_size=128, random_state=0, max_iter=50
        ).fit(shard_rows(X))
        assert adjusted_rand_score(y, np.asarray(mbk.labels_)) > 0.95
        assert mbk.cluster_centers_.shape == (4, 5)
        assert mbk.n_iter_ >= 1 and mbk.inertia_ > 0

    def test_near_full_kmeans_quality(self, blobs):
        X, y = blobs
        mbk = dc.MiniBatchKMeans(
            n_clusters=4, batch_size=128, random_state=0, max_iter=50
        ).fit(X)
        full = sc.KMeans(n_clusters=4, n_init=10, random_state=0).fit(X)
        # Sculley's bound: minibatch inertia within a few % of Lloyd's
        assert mbk.inertia_ <= full.inertia_ * 1.10

    def test_partial_fit_streaming(self, blobs):
        X, y = blobs
        mbk = dc.MiniBatchKMeans(n_clusters=4, random_state=0)
        for lo in range(0, len(X), 100):
            mbk.partial_fit(X[lo:lo + 100])
        assert mbk.n_steps_ == 5
        pred = np.asarray(mbk.predict(X))
        assert adjusted_rand_score(y, pred) > 0.9

    def test_incremental_wrapper_streams_device_model(self, blobs):
        from dask_ml_tpu.wrappers import Incremental

        X, y = blobs
        inc = Incremental(
            dc.MiniBatchKMeans(n_clusters=4, random_state=0), chunk_size=100
        ).fit(shard_rows(X))
        pred = np.asarray(inc.estimator_.predict(X))
        assert adjusted_rand_score(y, pred) > 0.9

    def test_transform_and_score(self, blobs):
        X, y = blobs
        mbk = dc.MiniBatchKMeans(n_clusters=4, random_state=0, max_iter=20).fit(X)
        d = np.asarray(mbk.transform(X[:10]))
        assert d.shape == (10, 4) and (d >= 0).all()
        assert mbk.score(X) == pytest.approx(-mbk.inertia_, rel=1e-5)

    def test_uneven_rows_pad_mask(self, rng):
        X = rng.normal(size=(1003, 3)).astype(np.float32)
        mbk = dc.MiniBatchKMeans(n_clusters=3, random_state=0, max_iter=10)
        mbk.fit(shard_rows(X))
        assert mbk.labels_.shape == (1003,)

    def test_init_array_and_random(self, blobs):
        X, y = blobs
        init = X[:4].copy()
        mbk = dc.MiniBatchKMeans(n_clusters=4, init=init, max_iter=10).fit(X)
        assert mbk.cluster_centers_.shape == (4, 5)
        mbk2 = dc.MiniBatchKMeans(
            n_clusters=4, init="random", random_state=3, max_iter=10
        ).fit(X)
        assert mbk2.cluster_centers_.shape == (4, 5)

    def test_partial_fit_requires_enough_samples(self, rng):
        X = rng.normal(size=(3, 2)).astype(np.float32)
        with pytest.raises(ValueError, match="n_samples"):
            dc.MiniBatchKMeans(n_clusters=8).partial_fit(X)


class TestAdvisorRound2Fixes:
    def test_minibatch_max_iter_zero_raises(self, rng, mesh):
        X = rng.normal(size=(64, 4)).astype(np.float32)
        with pytest.raises(ValueError, match="max_iter"):
            dc.MiniBatchKMeans(n_clusters=2, max_iter=0).fit(X)

    def test_minibatch_counts_kahan_pair_exact(self, rng, mesh):
        import jax.numpy as jnp

        X = rng.normal(size=(256, 4)).astype(np.float32)
        mbk = dc.MiniBatchKMeans(n_clusters=3, random_state=0)
        mbk.partial_fit(X)
        # mass lives in a (2, k) f32 Kahan pair: accurate far past the
        # 2^24 ceiling where a plain f32 count would freeze the 1/n_c
        # decay, and it admits fractional sample_weight
        assert mbk._counts.shape == (2, 3)
        assert mbk._counts.dtype == jnp.float32
        assert float(mbk._counts.sum()) == 256.0

    def test_minibatch_kahan_mass_no_f32_saturation(self, mesh):
        import jax.numpy as jnp

        from dask_ml_tpu.cluster.minibatch_kmeans import _mbk_step

        # one center, mass already past 2^24, +1-mass batches: a plain
        # f32 accumulator rounds 2^24+1 back to 2^24 every step (ulp=2,
        # ties-to-even) and freezes; the compensated lo term keeps the
        # increments.  (+256 batches would be exactly representable and
        # could not distinguish the two.)
        centers = jnp.zeros((1, 2), jnp.float32)
        counts = jnp.stack([
            jnp.full((1,), 2.0 ** 24, jnp.float32), jnp.zeros((1,))
        ])
        xb = jnp.ones((1, 2), jnp.float32)
        mask = jnp.ones((1,), jnp.float32)
        for _ in range(8):
            centers, counts, _ = _mbk_step(centers, counts, xb, mask)
        total = float(counts[0, 0]) + float(counts[1, 0])
        assert total == 2.0 ** 24 + 8
        # the plain-f32 control: same stream, no compensation
        plain = jnp.full((), 2.0 ** 24, jnp.float32)
        for _ in range(8):
            plain = plain + jnp.float32(1.0)
        assert float(plain) == 2.0 ** 24  # frozen — what the pair prevents

    def test_sgd_max_iter_zero_raises(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier

        X = rng.normal(size=(64, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        with pytest.raises(ValueError, match="max_iter"):
            SGDClassifier(max_iter=0).fit(X, y)


class TestKMeansSampleWeight:
    def test_integer_weights_equal_duplication(self, rng, mesh):
        import sklearn.cluster as skc

        n = 160
        X = rng.normal(size=(n, 3)).astype(np.float32) + np.repeat(
            np.eye(3, dtype=np.float32) * 6, n // 3 + 1, axis=0
        )[:n]
        sw = rng.randint(1, 4, size=n).astype(np.float64)
        init = X[:3].copy()
        ours = dc.KMeans(n_clusters=3, init=init, max_iter=50, tol=1e-6).fit(
            X, sample_weight=sw
        )
        dup = dc.KMeans(n_clusters=3, init=init, max_iter=50, tol=1e-6).fit(
            np.repeat(X, sw.astype(int), axis=0)
        )
        np.testing.assert_allclose(
            np.asarray(ours.cluster_centers_),
            np.asarray(dup.cluster_centers_), rtol=1e-4, atol=1e-4,
        )
        sk = skc.KMeans(n_clusters=3, init=init, n_init=1, max_iter=50).fit(
            X, sample_weight=sw
        )
        assert ours.inertia_ == pytest.approx(sk.inertia_, rel=1e-3)

    def test_zero_weight_outlier_never_seeds_kmeanspp(self, rng, mesh):
        X = rng.normal(size=(400, 3)).astype(np.float32)
        X[0] = 1e6  # extreme outlier, weight 0
        w = np.ones(400); w[0] = 0.0
        km = dc.KMeans(
            n_clusters=3, init="k-means++", random_state=0, max_iter=20
        ).fit(X, sample_weight=w)
        assert float(np.abs(np.asarray(km.cluster_centers_)).max()) < 1e3

    def test_minibatch_sample_weight_moves_centers(self, rng, mesh):
        # two separated blobs; weighting one blob 100x pulls a 1-cluster
        # model's center to it (weighted mean semantics)
        a = rng.normal(size=(100, 2)).astype(np.float32)
        b = rng.normal(size=(100, 2)).astype(np.float32) + 10.0
        X = np.vstack([a, b])
        w = np.r_[np.full(100, 100.0), np.ones(100)]
        m = dc.MiniBatchKMeans(
            n_clusters=1, init=np.zeros((1, 2), np.float32), max_iter=20,
            random_state=0,
        ).fit(X, sample_weight=w)
        c = float(np.asarray(m.cluster_centers_)[0, 0])
        # weighted mean of x-coords ~ (100*0 + 1*10)/101 ~ 0.1
        assert c < 1.0

    def test_minibatch_integer_weights_match_duplication(self, rng, mesh):
        X = rng.normal(size=(90, 3)).astype(np.float32) + np.repeat(
            np.eye(3, dtype=np.float32) * 8, 30, axis=0
        )
        sw = rng.randint(1, 4, size=90).astype(np.float64)
        init = X[[0, 30, 60]].copy()
        kw = dict(n_clusters=3, init=init, max_iter=30, random_state=0,
                  batch_size=1 << 20)  # one batch per epoch: same windows
        ours = dc.MiniBatchKMeans(**kw).fit(X, sample_weight=sw)
        dup = dc.MiniBatchKMeans(**kw).fit(np.repeat(X, sw.astype(int), axis=0))
        # same cluster structure (duplication changes batch windows, so
        # exact center equality is not expected at finite batch sizes —
        # with one whole-data batch per epoch the updates coincide)
        ours_labels = np.asarray(ours.predict(X))
        dup_labels = np.asarray(dup.predict(X))
        assert (ours_labels == dup_labels).mean() > 0.95

    def test_minibatch_partial_fit_weighted_stream(self, rng, mesh):
        X = rng.normal(size=(64, 3)).astype(np.float32)
        m = dc.MiniBatchKMeans(n_clusters=2, random_state=0)
        m.partial_fit(X, sample_weight=np.full(64, 0.5))
        assert float(m._counts.sum()) == pytest.approx(32.0)

    def test_minibatch_legacy_int_counts_migrate(self, rng, mesh):
        import jax.numpy as jnp

        X = rng.normal(size=(64, 3)).astype(np.float32)
        m = dc.MiniBatchKMeans(n_clusters=2, random_state=0)
        m.partial_fit(X)
        # simulate a pre-Kahan checkpoint: (k,) int32 row counts
        m._counts = jnp.asarray([40, 24], jnp.int32)
        m.partial_fit(X)
        assert m._counts.shape == (2, 2)
        assert float(m._counts.sum()) == pytest.approx(64.0 + 64.0)

    def test_minibatch_reassignment_rescues_empty_cluster(self, rng, mesh):
        # both centers init at the SAME far-away point: without
        # reassignment one cluster captures everything and the other
        # starves forever; reassignment_ratio re-seeds it from the data
        from sklearn.datasets import make_blobs

        X, y = make_blobs(n_samples=400, centers=2, n_features=3,
                          cluster_std=0.4, random_state=3)
        X = X.astype(np.float32)
        bad_init = np.full((2, 3), 50.0, np.float32)
        stuck = dc.MiniBatchKMeans(
            n_clusters=2, init=bad_init, max_iter=30, random_state=0,
            reassignment_ratio=0.0,
        ).fit(X)
        rescued = dc.MiniBatchKMeans(
            n_clusters=2, init=bad_init, max_iter=30, random_state=0,
            reassignment_ratio=0.05,
        ).fit(X)
        from sklearn.metrics import adjusted_rand_score as ari

        assert ari(y, np.asarray(rescued.labels_)) > 0.95
        assert rescued.inertia_ < stuck.inertia_

    def test_sub_unit_weight_mass_centers_exact(self, rng, mesh):
        # regression: maximum(mass, 1.0) denominators silently shrank
        # centers whenever a cluster's total weight mass was < 1
        import sklearn.cluster as skc

        X = rng.normal(size=(300, 3)).astype(np.float32) + np.repeat(
            np.eye(3, dtype=np.float32) * 6, 100, axis=0
        )
        w = np.full(300, 1e-3)  # per-cluster mass ~0.1
        init = X[[0, 100, 200]].copy()
        ours = dc.KMeans(n_clusters=3, init=init, max_iter=50,
                         tol=1e-9).fit(X, sample_weight=w)
        sk = skc.KMeans(n_clusters=3, init=init, n_init=1,
                        max_iter=50).fit(X, sample_weight=w)
        np.testing.assert_allclose(
            np.asarray(ours.cluster_centers_), sk.cluster_centers_,
            atol=1e-4,
        )


class TestDonation:
    """Aliasing regression tests for the ISSUE-12 donation sites (the
    serve/ donation tests from PR 11 are the template): donated buffers
    must really be consumed in place, deliberately-undonated buffers
    must really survive — in both directions, a silent change is an
    HBM-footprint or correctness regression."""

    def _xmc(self, n=512, d=16, k=8, seed=3):
        import jax.numpy as jnp

        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        mask = jnp.ones((n,), jnp.float32)
        centers = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        return x, mask, centers

    def test_lloyd_loop_donates_centers_not_data(self):
        import jax.numpy as jnp

        from dask_ml_tpu.cluster.k_means import _lloyd_loop

        x, mask, centers = self._xmc()
        out = _lloyd_loop(x, mask, centers, jnp.float32(0.0),
                          jnp.int32(3), mode="highest", scatter="segsum")
        assert centers.is_deleted(), "centers must be consumed in place"
        assert not x.is_deleted(), "x is deliberately NOT donated"
        assert not mask.is_deleted(), "mask is deliberately NOT donated"
        assert not out[0].is_deleted()

    def test_lloyd_step_donates_centers(self):
        from dask_ml_tpu.cluster.k_means import _lloyd_step

        x, mask, centers = self._xmc()
        new_c, _, _ = _lloyd_step(x, mask, centers, mode="highest",
                                  scatter="segsum")
        assert centers.is_deleted()
        assert not x.is_deleted() and not mask.is_deleted()
        assert new_c.shape == (8, 16)

    def test_assign_deliberately_donates_nothing(self):
        from dask_ml_tpu.cluster.k_means import _assign

        x, mask, centers = self._xmc()
        _assign(x, mask, centers)
        # documented non-donation (gemm-output-smaller class): fit and
        # predict keep using x/centers right after the assignment
        assert not x.is_deleted()
        assert not mask.is_deleted()
        assert not centers.is_deleted()

    def test_user_init_array_survives_kmeans_fit(self, blobs):
        import jax.numpy as jnp

        X, _ = blobs
        init = jnp.asarray(X[:4])  # user-owned device array
        km = dc.KMeans(n_clusters=4, init=init, max_iter=5).fit(X)
        # the donated loop must consume a COPY, never the user's buffer
        assert not init.is_deleted()
        assert km.cluster_centers_.shape == (4, 5)

    def test_mbk_step_donates_state_across_bucket_rungs(self):
        import jax.numpy as jnp

        from dask_ml_tpu.cluster.minibatch_kmeans import _mbk_step

        rng = np.random.RandomState(5)
        k, d = 8, 16
        centers = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        counts = jnp.zeros((2, k), jnp.float32)
        # two bucket rungs = two per-signature AOT executables; the
        # donation must follow every one the cache mints
        for rows in (256, 1024):
            xb = jnp.asarray(rng.normal(size=(rows, d)).astype(np.float32))
            mb = jnp.ones((rows,), jnp.float32)
            old_c, old_n = centers, counts
            centers, counts, _ = _mbk_step(centers, counts, xb, mb)
            assert old_c.is_deleted(), f"rung {rows} lost centers donation"
            assert old_n.is_deleted(), f"rung {rows} lost counts donation"
            assert not xb.is_deleted(), "block buffer must NOT be donated"
            assert not mb.is_deleted()

    def test_mbk_epoch_donates_state_not_data(self):
        import jax.numpy as jnp

        from dask_ml_tpu.cluster.minibatch_kmeans import _mbk_epoch

        rng = np.random.RandomState(6)
        k, d, n = 4, 8, 512
        centers = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        counts = jnp.zeros((2, k), jnp.float32)
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        mask = jnp.ones((n,), jnp.float32)
        new_c, new_n, _ = _mbk_epoch(centers, counts, x, mask,
                                     jnp.int32(0), batch_size=128,
                                     n_batches=4)
        assert centers.is_deleted() and counts.is_deleted()
        assert not x.is_deleted(), "epoch windows re-slice x every epoch"
        assert not mask.is_deleted()
        assert new_c.shape == (k, d) and new_n.shape == (2, k)

    def test_mbk_partial_fit_stream_stays_consistent_under_donation(self):
        # end-to-end: the streamed state chain survives donation and
        # matches a fresh-array (donation-free) replay of the same math
        rng = np.random.RandomState(9)
        X1 = rng.normal(size=(300, 6)).astype(np.float32)
        X2 = rng.normal(size=(300, 6)).astype(np.float32)
        m = dc.MiniBatchKMeans(n_clusters=3, random_state=0)
        m.partial_fit(X1)
        c_after_1 = np.asarray(m.cluster_centers_)  # host copy
        m.partial_fit(X2)
        m2 = dc.MiniBatchKMeans(n_clusters=3, random_state=0)
        m2.partial_fit(X1)
        np.testing.assert_allclose(np.asarray(m2.cluster_centers_),
                                   c_after_1, rtol=1e-6)

    def test_mbk_fit_attrs_stay_live_on_mid_loop_exit(self):
        # the epoch program donates centers/counts; a preemption/fault
        # exit between epochs must still leave a READABLE estimator
        # (attrs reassigned at every boundary, never deleted buffers)
        from dask_ml_tpu.resilience.preemption import TrainingPreempted

        rng = np.random.RandomState(11)
        X = rng.normal(size=(400, 5)).astype(np.float32)
        m = dc.MiniBatchKMeans(n_clusters=3, max_iter=50, batch_size=64,
                               random_state=0, tol=0.0,
                               max_no_improvement=None)
        calls = {"n": 0}
        # fit imports check_preemption from the preemption module at
        # call time — patch it at the source
        from dask_ml_tpu.resilience import preemption as _pre

        orig = _pre.check_preemption

        def boom(*a, **k):
            calls["n"] += 1
            if calls["n"] >= 3:
                raise TrainingPreempted(calls["n"])
            return orig(*a, **k)

        _pre.check_preemption = boom
        try:
            with pytest.raises(TrainingPreempted):
                m.fit(X)
        finally:
            _pre.check_preemption = orig
        # the held state is live: predict works on the partial model
        labels = np.asarray(m.predict(X))
        assert labels.shape == (400,)
        assert not m.cluster_centers_.is_deleted()
        assert not m._counts.is_deleted()

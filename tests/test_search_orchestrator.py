"""The concurrent search control plane (ISSUE 13, design.md §17).

Four contracts:

* **Span-tree correctness under interleaved brackets** — every
  ``search.unit`` span parents under a ``search.round`` of ITS OWN
  bracket (detached spans with explicit parents; stack parentage would
  cross-link coroutines interleaving on the one loop thread), at
  prefetch depth 0 and 2.
* **Result equality** — the concurrent orchestrator produces the same
  scores as the sequential single-controller path at rtol 1e-5 (same
  configs, same seeds, same block order per model).
* **Dispatch-thread discipline** — orchestrated device work runs on the
  blessed ``dask-ml-tpu-search`` thread (the BLESSED_DISPATCH_THREADS
  contract both graftlint and graftsan key on).
* **Fault semantics parity** — a failed async unit requeues once from
  its round-start snapshot with the same ``search-unit`` fault books as
  the thread-pool path, and persistent faults propagate loudly.
"""

import os

import numpy as np
import pytest

from dask_ml_tpu import obs
from dask_ml_tpu.linear_model import SGDClassifier
from dask_ml_tpu.model_selection import (
    HyperbandSearchCV,
    IncrementalSearchCV,
)
from dask_ml_tpu.model_selection._orchestrator import (
    SEARCH_THREAD_NAME,
    concurrency_enabled,
    resolve_inflight,
)


@pytest.fixture
def xy(rng):
    X = rng.normal(size=(512, 8)).astype(np.float32)
    w = rng.normal(size=8)
    y = (X @ w > 0).astype(np.int32)
    return X, y


@pytest.fixture
def sequential_env(monkeypatch):
    monkeypatch.setenv("DASK_ML_TPU_SEARCH_CONCURRENCY", "off")


def _hyperband(**kw):
    kw.setdefault("max_iter", 4)
    kw.setdefault("random_state", 0)
    kw.setdefault("test_size", 0.25)
    return HyperbandSearchCV(
        SGDClassifier(random_state=0),
        {"alpha": [1e-4, 3e-4, 1e-3, 3e-3]}, **kw,
    )


def _collect(node, name, out):
    if node is None:
        return out
    if node["name"] == name:
        out.append(node)
    for c in node.get("children", ()):
        _collect(c, name, out)
    return out


class TestKnobs:
    def test_concurrency_strict_parse(self, monkeypatch):
        monkeypatch.setenv("DASK_ML_TPU_SEARCH_CONCURRENCY", "banana")
        with pytest.raises(ValueError, match="SEARCH_CONCURRENCY"):
            concurrency_enabled()
        monkeypatch.setenv("DASK_ML_TPU_SEARCH_CONCURRENCY", "off")
        assert concurrency_enabled() is False
        monkeypatch.delenv("DASK_ML_TPU_SEARCH_CONCURRENCY")
        assert concurrency_enabled() is True

    def test_inflight_strict_parse(self, monkeypatch):
        monkeypatch.setenv("DASK_ML_TPU_SEARCH_INFLIGHT", "0")
        with pytest.raises(ValueError, match="SEARCH_INFLIGHT"):
            resolve_inflight()
        monkeypatch.setenv("DASK_ML_TPU_SEARCH_INFLIGHT", "nope")
        with pytest.raises(ValueError, match="SEARCH_INFLIGHT"):
            resolve_inflight()
        monkeypatch.setenv("DASK_ML_TPU_SEARCH_INFLIGHT", "3")
        assert resolve_inflight() == 3


class TestSpanTree:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_units_parent_under_their_own_bracket(self, xy, depth,
                                                  monkeypatch):
        """Interleaved brackets: each bracket's units nest under that
        bracket's rounds — no cross-linking, at depth 0 and 2."""
        monkeypatch.setenv("DASK_ML_TPU_PREFETCH_DEPTH", str(depth))
        X, y = xy
        obs.clear_spans()
        _hyperband().fit(X, y, classes=np.array([0, 1]))
        tree = obs.span_tree()
        assert tree is not None and tree["name"] == "search.fit"
        brackets = _collect(tree, "search.bracket", [])
        assert len(brackets) >= 2, "expected a multi-bracket schedule"
        seen_units = 0
        for b in brackets:
            tag = f"bracket={b['attrs']['bracket']}"
            rounds = _collect(b, "search.round", [])
            assert rounds, b["attrs"]
            for r in rounds:
                units = _collect(r, "search.unit", [])
                for u in units:
                    seen_units += 1
                    # the unit's own prefix attr names its bracket —
                    # a cross-linked unit would sit under a round
                    # whose bracket tag disagrees
                    assert tag in u["attrs"]["prefix"], (
                        tag, u["attrs"])
        assert seen_units >= len(brackets), "no units recorded"
        # units never leak to the root through stack parentage
        root_units = [
            u for u in _collect(tree, "search.unit", [])
        ]
        rounds_all = _collect(tree, "search.round", [])
        units_in_rounds = sum(
            len(_collect(r, "search.unit", [])) for r in rounds_all)
        assert len(root_units) == units_in_rounds

    def test_unit_pipeline_spans_nest_under_unit(self, xy, monkeypatch):
        monkeypatch.setenv("DASK_ML_TPU_PREFETCH_DEPTH", "2")
        X, y = xy
        obs.clear_spans()
        IncrementalSearchCV(
            SGDClassifier(random_state=0),
            {"penalty": ["l2", "l1"]}, n_initial_parameters=2,
            max_iter=2, random_state=0,
        ).fit(X, y, classes=np.array([0, 1]))
        tree = obs.span_tree()
        units = _collect(tree, "search.unit", [])
        assert units
        streams = [s for u in units
                   for s in _collect(u, "pipeline.stream", [])]
        assert streams, "unit staged feeds must nest under their units"


class TestResultEquality:
    def test_concurrent_matches_sequential(self, xy, monkeypatch):
        X, y = xy
        conc = _hyperband(max_iter=9).fit(X, y, classes=np.array([0, 1]))
        monkeypatch.setenv("DASK_ML_TPU_SEARCH_CONCURRENCY", "off")
        seq = _hyperband(max_iter=9, sequential_brackets=True).fit(
            X, y, classes=np.array([0, 1]))
        assert conc.best_params_ == seq.best_params_
        np.testing.assert_allclose(
            np.asarray(conc.cv_results_["test_score"]),
            np.asarray(seq.cv_results_["test_score"]), rtol=1e-5)
        assert (conc.cv_results_["partial_fit_calls"]
                == seq.cv_results_["partial_fit_calls"])

    def test_incremental_depth0_matches_depth2(self, xy, monkeypatch):
        X, y = xy

        def run(depth):
            monkeypatch.setenv("DASK_ML_TPU_PREFETCH_DEPTH", str(depth))
            return IncrementalSearchCV(
                SGDClassifier(random_state=0),
                {"alpha": [1e-4, 1e-2]}, n_initial_parameters=2,
                max_iter=3, random_state=0,
            ).fit(X, y, classes=np.array([0, 1]))

        a, b = run(0), run(2)
        np.testing.assert_allclose(
            np.asarray(a.cv_results_["test_score"]),
            np.asarray(b.cv_results_["test_score"]), rtol=1e-5)


class TestDispatchDiscipline:
    def test_device_work_runs_on_blessed_search_thread(self, xy):
        import threading

        seen = set()

        class SpySGD(SGDClassifier):
            def _pf_consume(self, staged):
                seen.add(threading.current_thread().name)
                return super()._pf_consume(staged)

        X, y = xy
        IncrementalSearchCV(
            SpySGD(random_state=0), {"penalty": ["l2", "l1"]},
            n_initial_parameters=2, max_iter=2, random_state=0,
        ).fit(X, y, classes=np.array([0, 1]))
        assert seen == {SEARCH_THREAD_NAME}, seen

    def test_off_switch_restores_caller_thread(self, xy, sequential_env):
        import threading

        seen = set()

        class SpySGD(SGDClassifier):
            def _pf_consume(self, staged):
                seen.add(threading.current_thread().name)
                return super()._pf_consume(staged)

        X, y = xy
        IncrementalSearchCV(
            SpySGD(random_state=0), {"penalty": ["l2", "l1"]},
            n_initial_parameters=2, max_iter=2, random_state=0,
        ).fit(X, y, classes=np.array([0, 1]))
        assert seen == {threading.current_thread().name}, seen

    def test_scheduler_books_land_in_device_report(self, xy):
        from dask_ml_tpu import diagnostics
        from dask_ml_tpu.obs import scope

        diagnostics.reset()
        X, y = xy
        IncrementalSearchCV(
            SGDClassifier(random_state=0), {"alpha": [1e-4, 1e-2]},
            n_initial_parameters=2, max_iter=2, random_state=0,
        ).fit(X, y, classes=np.array([0, 1]))
        rep = scope.device_report()
        assert "search" in rep
        assert rep["search"]["dispatch_turns"] > 0
        assert rep["search"]["round_s"]["count"] >= 2

    def test_search_section_absent_without_search(self):
        from dask_ml_tpu import diagnostics
        from dask_ml_tpu.obs import scope

        diagnostics.reset()
        assert "search" not in scope.device_report()

    def test_concurrent_fits_serialize_on_one_dispatcher(self, xy):
        """Two device searches from two user threads: the process-wide
        dispatcher lock means at most ONE blessed search thread is ever
        live (graftsan blesses by NAME — two live dispatchers would
        each look legal while interleaving enqueues), and both fits
        still complete correctly."""
        import threading

        live_peak = []

        class SpySGD(SGDClassifier):
            def _pf_consume(self, staged):
                live_peak.append(sum(
                    1 for t in threading.enumerate()
                    if t.name == SEARCH_THREAD_NAME and t.is_alive()))
                return super()._pf_consume(staged)

        X, y = xy
        results = {}

        def fit_one(tag):
            # heterogeneous statics: units stay unpacked so the spy's
            # _pf_consume (not the cohort's) observes every dispatch
            s = IncrementalSearchCV(
                SpySGD(random_state=0), {"penalty": ["l2", "l1"]},
                n_initial_parameters=2, max_iter=2, random_state=0,
            ).fit(X, y, classes=np.array([0, 1]))
            results[tag] = s.best_score_

        threads = [threading.Thread(target=fit_one, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert set(results) == {0, 1}
        assert results[0] == pytest.approx(results[1])
        assert live_peak, "spy saw no dispatches"
        assert max(live_peak) == 1, max(live_peak)


class TestFaultParity:
    def _faulty(self):
        from dask_ml_tpu.resilience.testing import maybe_fault

        class FaultySGD(SGDClassifier):
            def _pf_consume(self, staged):
                maybe_fault("orchestrated-step")
                return super()._pf_consume(staged)

        return FaultySGD

    def test_transient_fault_requeues_once(self, xy):
        from dask_ml_tpu import diagnostics
        from dask_ml_tpu.resilience import fault_plan
        from dask_ml_tpu.resilience.retry import fault_stats

        diagnostics.reset()
        X, y = xy
        # heterogeneous statics: units stay UNPACKED, so the injection
        # rides each model's own _pf_consume
        with fault_plan() as plan:
            plan.inject("orchestrated-step", at_call=4)
            search = IncrementalSearchCV(
                self._faulty()(random_state=0),
                {"penalty": ["l2", "l1", "elasticnet"]},
                n_initial_parameters=3, max_iter=3, random_state=0,
            ).fit(X, y, classes=np.array([0, 1]))
        assert plan.fired["orchestrated-step"] == 1
        assert search.fit_failures_ == 1
        s = fault_stats().snapshot()
        assert s["faults"].get("search-unit") == 1
        assert s["retries"].get("search-unit") == 1
        assert "search-unit" not in s["failures"]
        reg = obs.registry()
        assert sum(reg.family("search.requeues").values()) == 1

    def test_transient_fault_recovery_is_exact_state(self, xy):
        from dask_ml_tpu import diagnostics
        from dask_ml_tpu.resilience import fault_plan

        X, y = xy

        def run(inject):
            diagnostics.reset()
            with fault_plan() as plan:
                if inject:
                    plan.inject("orchestrated-step", at_call=4)
                return IncrementalSearchCV(
                    self._faulty()(random_state=0),
                    {"penalty": ["l2", "l1", "elasticnet"]},
                    n_initial_parameters=3, max_iter=3, random_state=0,
                ).fit(X, y, classes=np.array([0, 1]))

        clean, faulty = run(False), run(True)
        assert faulty.fit_failures_ == 1
        np.testing.assert_allclose(
            np.asarray(clean.cv_results_["test_score"]),
            np.asarray(faulty.cv_results_["test_score"]), rtol=1e-5)

    def test_persistent_fault_propagates(self, xy):
        import threading
        import time

        from dask_ml_tpu import diagnostics
        from dask_ml_tpu.resilience import FaultInjected, fault_plan
        from dask_ml_tpu.resilience.retry import fault_stats

        diagnostics.reset()
        X, y = xy
        with fault_plan() as plan:
            plan.persistent("orchestrated-step")
            with pytest.raises(FaultInjected):
                IncrementalSearchCV(
                    self._faulty()(random_state=0),
                    {"penalty": ["l2", "l1"]}, n_initial_parameters=2,
                    max_iter=2, random_state=0,
                ).fit(X, y, classes=np.array([0, 1]))
        s = fault_stats().snapshot()
        assert s["failures"].get("search-unit", 0) >= 1
        # the abort path tears down units cancelled mid-stage: their
        # UnitStreams must still stop their prefetch workers (the
        # deferred-close handshake) — a leaked worker busy-polls its
        # bounded queue forever
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            leaked = [t for t in threading.enumerate()
                      if t.name == "dask-ml-tpu-prefetch" and t.is_alive()]
            if not leaked:
                break
            time.sleep(0.05)
        assert not leaked, leaked


class TestCohortStagingProtocol:
    """Cohort._pf_stage/_pf_consume — the re-pack twin of the SGD
    staged protocol the orchestrator streams cohorts through."""

    def _cohort(self, n=3, classes=(0, 1)):
        from dask_ml_tpu.model_selection._packing import Cohort

        models = [SGDClassifier(alpha=10.0 ** -(i + 2), random_state=0)
                  for i in range(n)]
        return Cohort(models, classes=np.asarray(classes))

    def test_stage_consume_matches_step(self, rng):
        X = rng.normal(size=(64, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        a, b = self._cohort(), self._cohort()
        staged = a._pf_stage(X, y)
        assert staged is not None
        a._pf_consume(staged)
        b.step(X, y)
        for ma, mb in zip(a.finalize(), b.finalize()):
            np.testing.assert_allclose(
                np.asarray(ma._state["coef"]),
                np.asarray(mb._state["coef"]), rtol=1e-6)

    def test_stage_declines_device_blocks(self, rng):
        from dask_ml_tpu.core.sharded import shard_rows

        X = rng.normal(size=(64, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        cohort = self._cohort(classes=(0.0, 1.0))
        assert cohort._pf_stage(shard_rows(X), shard_rows(y)) is None

    def test_stage_declines_weighted_members(self, rng):
        from dask_ml_tpu.model_selection._packing import Cohort

        X = rng.normal(size=(64, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        models = [SGDClassifier(class_weight={0: 1.0, 1: 2.0},
                                alpha=10.0 ** -(i + 2), random_state=0)
                  for i in range(2)]
        cohort = Cohort(models, classes=np.array([0, 1]))
        assert cohort._pf_stage(X, y) is None

    def test_warm_ahead_hits(self, rng):
        """Cohort.warm pre-builds the re-packed signature on the
        blessed compile thread and the first packed dispatch HITS it —
        the programs/ half of the orchestrator lane."""
        from dask_ml_tpu import programs
        from dask_ml_tpu.model_selection._packing import _packed_step

        X = rng.normal(size=(48, 7)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        cohort = self._cohort(n=4)
        staged = cohort._pf_stage(X, y)  # stage() warms as a side effect
        assert staged is not None
        assert programs.drain_ahead(timeout=30.0)
        before = dict(_packed_step.counters)
        cohort._pf_consume(staged)
        after = _packed_step.counters
        assert after["misses"] == before["misses"], \
            "packed dispatch missed the warm-ahead signature"

"""Device-native SGD estimator tests.

Pattern per SURVEY.md §4: convergence parity vs sklearn at the accuracy
level (loose tolerance for iterative solvers), plus the contracts the
adaptive searches rely on (partial_fit block streaming, classes on first
call, warm restart, device residency).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu.core import shard_rows, unshard
from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor


def _binary_data(rng, n=600, d=8):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w + 0.1 * rng.normal(size=n) > 0).astype(np.int64)
    return X, y


def clf_targets(clf, y, classes):
    """Encode y the way partial_fit would (for shape inspection in tests)."""
    if not hasattr(clf, "classes_"):
        clf.classes_ = np.sort(np.asarray(classes))
    return clf._encode_targets(np.asarray(y))


def _multiclass_data(rng, n=900, d=6, k=4):
    from sklearn.datasets import make_blobs

    X, y = make_blobs(n_samples=n, n_features=d, centers=k,
                      cluster_std=1.0, random_state=7)
    return X.astype(np.float32), y


class TestSGDClassifier:
    def test_binary_parity_with_sklearn(self, rng):
        from sklearn.linear_model import SGDClassifier as SkSGD

        X, y = _binary_data(rng)
        ours = SGDClassifier(alpha=1e-4, max_iter=300, tol=None).fit(X, y)
        theirs = SkSGD(alpha=1e-4, max_iter=50, tol=None, random_state=0).fit(X, y)
        acc_ours = (ours.predict(X) == y).mean()
        acc_theirs = (theirs.predict(X) == y).mean()
        assert acc_ours > 0.9
        assert acc_ours >= acc_theirs - 0.05

    def test_multiclass_labels_and_proba(self, rng):
        X, y = _multiclass_data(rng)
        clf = SGDClassifier(max_iter=300, tol=None).fit(X, y)
        assert list(clf.classes_) == [0, 1, 2, 3]
        pred = clf.predict(X)
        assert pred.dtype == y.dtype  # real labels, not booleans
        assert (pred == y).mean() > 0.9
        proba = np.asarray(clf.predict_proba(X))
        assert proba.shape == (len(y), 4)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
        assert clf.coef_.shape == (4, X.shape[1])

    def test_string_labels(self, rng):
        X, y = _binary_data(rng)
        labels = np.array(["neg", "pos"])[y]
        clf = SGDClassifier(max_iter=200, tol=None).fit(X, labels)
        assert set(clf.predict(X[:10])) <= {"neg", "pos"}
        assert clf.coef_.shape == (1, X.shape[1])

    def test_partial_fit_stream_requires_classes(self, rng):
        X, y = _binary_data(rng)
        clf = SGDClassifier()
        with pytest.raises(ValueError, match="classes"):
            clf.partial_fit(X[:100], y[:100])

    def test_partial_fit_stream_converges(self, rng):
        X, y = _binary_data(rng, n=2000)
        clf = SGDClassifier(learning_rate="constant", eta0=0.5)
        classes = np.unique(y)
        for epoch in range(30):
            for lo in range(0, len(X), 256):
                clf.partial_fit(X[lo:lo + 256], y[lo:lo + 256], classes=classes)
        assert (clf.predict(X) == y).mean() > 0.9
        assert clf.t_ == 30 * len(range(0, len(X), 256))

    def test_ragged_blocks_bounded_compiles(self, rng):
        # Streaming ragged chunk sizes must hit the bucket padding, not
        # recompile per shape: every chunk <=256 pads to the SAME 256-row
        # program shape.
        from dask_ml_tpu.linear_model._sgd import _bucket_rows

        sizes = (100, 101, 117, 250, 255, 256, 90)
        assert {_bucket_rows(s) for s in sizes} == {256}
        assert _bucket_rows(257) == 1024
        assert _bucket_rows(70000) == 65536 * 2  # beyond top bucket: rounded up

        X, y = _binary_data(rng, n=700)
        clf = SGDClassifier(learning_rate="constant", eta0=0.1)
        classes = np.unique(y)
        shapes = set()
        for size in sizes:
            xb, yb, mask = clf._prep_block(
                X[:size], clf_targets(clf, y[:size], classes)
            )
            shapes.add(xb.shape)
            clf.partial_fit(X[:size], y[:size], classes=classes)
        assert shapes == {(256, X.shape[1])}  # one compiled shape for all

    def test_sharded_rows_input(self, rng, mesh):
        X, y = _binary_data(rng, n=333)  # not divisible by 8: pad+mask path
        Xs, ys = shard_rows(X), shard_rows(y.astype(np.float32))
        clf = SGDClassifier(max_iter=300, tol=None).fit(Xs, ys)
        assert (clf.predict(Xs) == y).mean() > 0.9
        dense = SGDClassifier(max_iter=300, tol=None).fit(X, y)
        np.testing.assert_allclose(
            clf.coef_, dense.coef_, rtol=1e-3, atol=1e-4
        )

    def test_device_resident_state(self, rng):
        X, y = _binary_data(rng)
        clf = SGDClassifier(max_iter=20, tol=None).fit(X, y)
        assert isinstance(clf._state["coef"], jax.Array)

    def test_hinge_and_penalties(self, rng):
        X, y = _binary_data(rng)
        for loss in ("hinge", "squared_hinge", "modified_huber", "log_loss"):
            for penalty in ("l2", "l1", "elasticnet"):
                clf = SGDClassifier(loss=loss, penalty=penalty, max_iter=150,
                                    tol=None).fit(X, y)
                assert (clf.predict(X) == y).mean() > 0.85, (loss, penalty)

    def test_proba_unavailable_for_hinge(self, rng):
        X, y = _binary_data(rng)
        clf = SGDClassifier(loss="hinge", max_iter=20).fit(X, y)
        with pytest.raises(AttributeError):
            clf.predict_proba(X)

    def test_clone_contract(self):
        from sklearn.base import clone

        clf = SGDClassifier(alpha=0.5, loss="hinge")
        c = clone(clf)
        assert c.get_params()["alpha"] == 0.5
        assert c.get_params()["loss"] == "hinge"


class TestSGDRegressor:
    def test_parity_with_sklearn(self, rng):
        from sklearn.linear_model import SGDRegressor as SkSGD

        n, d = 800, 6
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d)
        y = (X @ w + 0.05 * rng.normal(size=n)).astype(np.float32)
        ours = SGDRegressor(max_iter=500, tol=None,
                            learning_rate="constant", eta0=0.1).fit(X, y)
        assert ours.score(X, y) > 0.98
        theirs = SkSGD(max_iter=100, tol=None, random_state=0).fit(X, y)
        assert ours.score(X, y) >= theirs.score(X, y) - 0.02
        np.testing.assert_allclose(ours.coef_, w, rtol=0.1, atol=0.05)

    def test_huber_loss(self, rng):
        n, d = 600, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d)
        y = X @ w
        y[::50] += 50.0  # outliers
        hub = SGDRegressor(loss="huber", epsilon=0.5, max_iter=800, tol=None,
                           learning_rate="constant", eta0=0.05).fit(X, y)
        clean = ~(np.arange(n) % 50 == 0)
        pred = np.asarray(hub.predict(X))
        assert np.corrcoef(pred[clean], y[clean])[0, 1] > 0.95

    def test_partial_fit_stream(self, rng):
        n, d = 2000, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d)
        y = X @ w
        reg = SGDRegressor(learning_rate="constant", eta0=0.1)
        for _ in range(40):
            for lo in range(0, n, 500):
                reg.partial_fit(X[lo:lo + 500], y[lo:lo + 500])
        assert reg.score(X, y) > 0.98

    def test_sharded_input(self, rng, mesh):
        n, d = 331, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = X @ rng.normal(size=d).astype(np.float32)
        reg = SGDRegressor(max_iter=400, tol=None, learning_rate="constant",
                           eta0=0.1).fit(shard_rows(X), shard_rows(y))
        assert reg.score(X, y) > 0.97


class TestDeviceNativeAdaptivePlane:
    """VERDICT round-1 item 2: the adaptive-search plane trains ON DEVICE
    when given our SGD estimators — partial_fit is an XLA program, not a
    host sklearn call."""

    def test_incremental_wrapper_device_native(self, rng):
        from dask_ml_tpu.wrappers import Incremental

        X, y = _binary_data(rng, n=1500)
        inc = Incremental(
            SGDClassifier(learning_rate="constant", eta0=0.5),
            chunk_size=256,
        )
        for _ in range(20):
            inc.partial_fit(X, y, classes=np.unique(y))
        est = inc.estimator_
        assert isinstance(est._state["coef"], jax.Array)
        assert (np.asarray(inc.predict(X)) == y).mean() > 0.9

    def test_incremental_search_device_native(self, rng):
        from dask_ml_tpu.model_selection import IncrementalSearchCV

        X, y = _binary_data(rng, n=1200)
        search = IncrementalSearchCV(
            SGDClassifier(learning_rate="constant"),
            {"eta0": [0.01, 0.1, 0.5], "alpha": [1e-4, 1e-2]},
            n_initial_parameters=6,
            max_iter=15,
            random_state=0,
        )
        search.fit(X, y, classes=np.unique(y))
        assert hasattr(search, "best_estimator_")
        assert isinstance(search.best_estimator_._state["coef"], jax.Array)
        assert search.best_score_ > 0.85

    def test_hyperband_device_native(self, rng):
        from dask_ml_tpu.model_selection import HyperbandSearchCV

        X, y = _binary_data(rng, n=1200)
        search = HyperbandSearchCV(
            SGDClassifier(learning_rate="constant"),
            {"eta0": [0.01, 0.1, 0.5, 1.0], "alpha": [1e-4, 1e-3, 1e-2]},
            max_iter=9,
            random_state=0,
        )
        search.fit(X, y, classes=np.unique(y))
        assert isinstance(search.best_estimator_._state["coef"], jax.Array)
        # the search actually exercised partial_fit as XLA programs
        assert search.best_estimator_.t_ > 0


class TestReviewRegressions:
    def test_optimal_schedule_rejects_alpha_zero(self, rng):
        X, y = _binary_data(rng, n=100)
        with pytest.raises(ValueError, match="alpha"):
            SGDClassifier(alpha=0.0, learning_rate="optimal").fit(X, y)

    def test_one_bad_epoch_does_not_stop_fit(self, rng):
        # A single non-improving epoch (oscillation at constant LR) must not
        # halt training; only n_iter_no_change consecutive ones may.
        n, d = 400, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = X @ rng.normal(size=d).astype(np.float32)
        reg = SGDRegressor(learning_rate="constant", eta0=0.9, max_iter=300,
                           tol=1e-4).fit(X, y)
        assert reg.score(X, y) > 0.9

    def test_warm_start_rejects_new_labels(self, rng):
        X, y = _multiclass_data(rng)
        clf = SGDClassifier(max_iter=30, warm_start=True).fit(X, y)
        y2 = y.copy()
        y2[:] = 7  # label outside fitted classes_
        with pytest.raises(ValueError, match="warm_start"):
            clf.fit(X, y2)
        # subset of fitted classes is fine
        keep = y < 2
        clf.fit(X[keep], y[keep])
        assert clf.coef_.shape[0] == 4  # state keeps the full class set

    def test_modified_huber_proba_matches_sklearn_formula(self, rng):
        X, y = _binary_data(rng)
        clf = SGDClassifier(loss="modified_huber", max_iter=100,
                            tol=None).fit(X, y)
        m = np.asarray(clf.decision_function(X))
        expect_p1 = (np.clip(m, -1, 1) + 1) / 2
        got = np.asarray(clf.predict_proba(X))
        np.testing.assert_allclose(got[:, 1], expect_p1, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got.sum(axis=1), 1.0, rtol=1e-5)

    def test_set_param_fingerprint_stable(self):
        from dask_ml_tpu.checkpoint import _param_repr

        assert _param_repr({"hinge", "log_loss"}) == _param_repr(
            {"log_loss", "hinge"}
        )


class TestReviewRegressions2:
    def test_single_class_fit_rejected(self, rng):
        X, _ = _binary_data(rng, n=50)
        with pytest.raises(ValueError, match="2 classes"):
            SGDClassifier(max_iter=5).fit(X, np.zeros(50))

    def test_single_class_partial_fit_rejected(self, rng):
        X, _ = _binary_data(rng, n=50)
        with pytest.raises(ValueError, match="2 classes"):
            SGDClassifier().partial_fit(X, np.zeros(50), classes=[0])

    def test_packed_plane_validates_like_unpacked(self, rng):
        from dask_ml_tpu.model_selection._packing import Cohort

        bad = SGDClassifier(alpha=0.0, learning_rate="optimal")
        ok = SGDClassifier(alpha=1e-4, learning_rate="optimal")
        with pytest.raises(ValueError, match="alpha"):
            Cohort([bad, ok], classes=[0, 1])


class TestMixedPrecisionSGD:
    def test_bf16_blocks_train_f32_params(self, rng):
        import jax.numpy as jnp

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.linear_model import SGDClassifier

        X = rng.normal(size=(512, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        sX = shard_rows(X, dtype=jnp.bfloat16)
        sy = shard_rows(y)
        clf = SGDClassifier(learning_rate="constant", eta0=0.3, max_iter=80)
        clf.fit(sX, sy)
        assert clf._state["coef"].dtype == jnp.float32
        acc = (np.asarray(clf.predict(sX)) == y).mean()
        assert acc > 0.9


class TestSGDWeights:
    def test_sample_weight_equals_duplication(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier

        n, d = 150, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        sw = rng.randint(1, 3, size=n)
        a = SGDClassifier(max_iter=40, random_state=0, tol=None).fit(
            X, y, sample_weight=sw
        )
        # duplication changes the padded batch size/bucket, so exact
        # trajectory parity is not expected — compare the weighted loss
        # direction instead: the weighted fit must classify high-weight
        # rows better than an unweighted fit of the same budget
        b = SGDClassifier(max_iter=40, random_state=0, tol=None).fit(X, y)
        heavy = sw >= 2
        acc_a = (np.asarray(a.predict(X[heavy])) == y[heavy]).mean()
        acc_b = (np.asarray(b.predict(X[heavy])) == y[heavy]).mean()
        assert acc_a >= acc_b - 0.05

    def test_class_weight_dict_changes_balance(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier

        n, d = 400, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X[:, 0] + 1.0 > 0).astype(np.float32)  # imbalanced
        plain = SGDClassifier(max_iter=60, random_state=0, tol=None).fit(X, y)
        up = SGDClassifier(
            max_iter=60, random_state=0, tol=None,
            class_weight={0.0: 8.0, 1.0: 1.0},
        ).fit(X, y)
        rec0 = lambda m: float(  # noqa: E731
            ((np.asarray(m.predict(X)) == 0) & (y == 0)).sum()
        ) / max((y == 0).sum(), 1)
        assert rec0(up) >= rec0(plain)

    def test_balanced_class_weight_in_fit_works(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier

        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] + 1.0 > 0).astype(np.float32)
        m = SGDClassifier(
            max_iter=30, random_state=0, tol=None, class_weight="balanced"
        ).fit(X, y)
        assert hasattr(m, "classes_")

    def test_balanced_rejected_in_partial_fit(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier

        X = rng.normal(size=(64, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        with pytest.raises(ValueError, match="partial_fit"):
            SGDClassifier(class_weight="balanced").partial_fit(
                X, y, classes=[0.0, 1.0]
            )

    def test_regressor_sample_weight(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDRegressor

        X = rng.normal(size=(150, 4)).astype(np.float32)
        y = (X @ rng.normal(size=4)).astype(np.float32)
        m = SGDRegressor(max_iter=30, random_state=0, tol=None).fit(
            X, y, sample_weight=np.ones(150)
        )
        assert hasattr(m, "_state")

    def test_sample_and_class_weight_combine_linearly(self, rng, mesh):
        # combining sample_weight with class_weight must apply each ONCE:
        # integer sw + dict cw == duplication + dict cw (review regression:
        # two chained effective_mask calls squared the sample weights)
        from dask_ml_tpu.linear_model import SGDClassifier

        n, d = 120, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        sw = rng.randint(1, 3, size=n)
        cw = {0.0: 3.0, 1.0: 1.0}
        a = SGDClassifier(max_iter=1, random_state=0, tol=None,
                          learning_rate="constant", eta0=0.1,
                          class_weight=cw).fit(X, y, sample_weight=sw)
        b = SGDClassifier(max_iter=1, random_state=0, tol=None,
                          learning_rate="constant", eta0=0.1,
                          class_weight=cw).fit(
            np.repeat(X, sw, axis=0), np.repeat(y, sw))
        # ONE gradient step on the weighted mean loss: duplication and
        # integer weights give the same weighted mean -> same step
        np.testing.assert_allclose(
            a.coef_, b.coef_, rtol=1e-5, atol=1e-6
        )


class TestConvergenceCanary:
    def test_fixed_problem_budget(self, rng, mesh):
        # VERDICT r2 weak #6: the loose accuracy-level parity tests would
        # not catch a 2x convergence regression — pin a budget on a fixed
        # problem: the fit must reach both the accuracy AND the epoch
        # count below the bound (historically n_iter_ ~ 30-60 here)
        from dask_ml_tpu.linear_model import SGDClassifier

        X = rng.normal(size=(512, 8)).astype(np.float32)
        w = rng.normal(size=8)
        y = (X @ w > 0).astype(np.float32)
        # FIXED budget: a convergence regression shows up as an accuracy
        # drop at constant epochs (currently ~0.99 at 60 epochs)
        m = SGDClassifier(max_iter=60, tol=None, random_state=0).fit(X, y)
        assert m.score(X, y) > 0.97


class TestMinibatchEpochs:
    """fit(batch_size=B): epoch = one scanned program of n_pad/B minibatch
    steps over stride interleaves (closer to sklearn's per-sample SGD than
    the default full-batch epoch)."""

    def test_minibatch_fit_matches_fullbatch_accuracy(self, rng):
        X, y = _binary_data(rng, n=600)
        full = SGDClassifier(max_iter=60, tol=None).fit(X, y)
        mb = SGDClassifier(max_iter=60, tol=None, batch_size=128).fit(X, y)
        acc_full = (full.predict(X) == y).mean()
        acc_mb = (mb.predict(X) == y).mean()
        assert acc_mb > 0.9
        assert acc_mb >= acc_full - 0.03

    def test_minibatch_advances_t_per_step(self, rng):
        X, y = _binary_data(rng, n=512)
        mb = SGDClassifier(max_iter=1, tol=None, batch_size=128).fit(X, y)
        # 512 rows pad to a 1024 bucket -> nearest divisor split of 1024/128
        assert mb.t_ > 1.0  # several steps in the single epoch
        full = SGDClassifier(max_iter=1, tol=None).fit(X, y)
        assert full.t_ == 1.0

    def test_minibatch_sharded_parity(self, rng, mesh):
        X, y = _binary_data(rng, n=640)
        sX, sy = shard_rows(X), shard_rows(y)
        host = SGDClassifier(max_iter=40, tol=None, batch_size=80).fit(X, y)
        dev = SGDClassifier(max_iter=40, tol=None, batch_size=80).fit(sX, sy)
        acc_dev = (dev.predict(X) == y).mean()
        assert acc_dev > 0.9
        assert abs(acc_dev - (host.predict(X) == y).mean()) < 0.05

    def test_minibatch_regressor(self, rng):
        X = rng.normal(size=(500, 6)).astype(np.float32)
        w = rng.normal(size=6).astype(np.float32)
        y = X @ w + 0.01 * rng.normal(size=500).astype(np.float32)
        mb = SGDRegressor(
            max_iter=200, tol=None, batch_size=64, learning_rate="constant",
            eta0=0.05, penalty=None,
        ).fit(X, y)
        from sklearn.metrics import r2_score

        assert r2_score(y, np.asarray(mb.predict(X))) > 0.95

    def test_batch_size_larger_than_n_is_fullbatch(self, rng):
        X, y = _binary_data(rng, n=300)
        mb = SGDClassifier(max_iter=3, tol=None, batch_size=10_000).fit(X, y)
        assert mb.t_ == 3.0  # one step per epoch: the full-batch path

    def test_batch_size_validated(self, rng):
        X, y = _binary_data(rng, n=100)
        with pytest.raises(ValueError, match="batch_size"):
            SGDClassifier(batch_size=0.5).fit(X, y)
        with pytest.raises(ValueError, match="batch_size"):
            SGDClassifier(batch_size=-128).fit(X, y)

    def test_tiny_batch_size_capped_at_n_real(self, rng):
        # n=300 bucket-pads to 1024; batch_size=2 would ask for 512
        # minibatches, but n_mb caps at n_real (then the divisor clamp)
        # so no minibatch is padding-only
        X, y = _binary_data(rng, n=300)
        mb = SGDClassifier(max_iter=2, tol=None, batch_size=2).fit(X, y)
        n_mb = mb.t_ / 2  # steps per epoch
        assert n_mb <= 300
        assert (mb.predict(X) == y).mean() > 0.85

    def test_batch_size_over_n_real_is_fullbatch_despite_padding(self, rng):
        # n=300 pads to a 1024 bucket: batch_size=400 exceeds n_samples so
        # the documented full-batch path must win over the padded count
        X, y = _binary_data(rng, n=300)
        mb = SGDClassifier(max_iter=3, tol=None, batch_size=400).fit(X, y)
        assert mb.t_ == 3.0


class TestEarlyStoppingAndAdaptive:
    def test_early_stopping_halts_before_max_iter(self, rng):
        X, y = _binary_data(rng, n=800)
        es = SGDClassifier(
            max_iter=500, tol=1e-3, early_stopping=True,
            validation_fraction=0.2, random_state=0,
            learning_rate="constant", eta0=0.1,
        ).fit(X, y)
        assert es.n_iter_ < 500
        assert (es.predict(X) == y).mean() > 0.9

    def test_early_stopping_requires_tol(self, rng):
        X, y = _binary_data(rng, n=100)
        with pytest.raises(ValueError, match="early_stopping requires"):
            SGDClassifier(tol=None, early_stopping=True).fit(X, y)
        with pytest.raises(ValueError, match="validation_fraction"):
            SGDClassifier(
                early_stopping=True, validation_fraction=1.5
            ).fit(X, y)

    def test_early_stopping_sharded(self, rng, mesh):
        X, y = _binary_data(rng, n=640)
        es = SGDClassifier(
            max_iter=300, tol=1e-4, early_stopping=True, random_state=0,
        ).fit(shard_rows(X), shard_rows(y))
        assert es.n_iter_ <= 300
        assert (es.predict(X) == y).mean() > 0.9

    def test_adaptive_learning_rate_decays_and_stops(self, rng):
        X, y = _binary_data(rng, n=400)
        ad = SGDClassifier(
            learning_rate="adaptive", eta0=0.5, max_iter=2000, tol=1e-3,
            n_iter_no_change=3, random_state=0,
        ).fit(X, y)
        # plateau -> eta/5 cascades until 1e-6 floor: stops well short
        assert ad.n_iter_ < 2000
        assert (ad.predict(X) == y).mean() > 0.9

    def test_adaptive_beats_fixed_tiny_eta_on_budget(self, rng):
        # adaptive starts big and decays on plateau (tol active so the
        # eta/5 branch actually runs); a fixed tiny eta crawls
        X, y = _binary_data(rng, n=400)
        ad = SGDClassifier(
            learning_rate="adaptive", eta0=0.5, max_iter=200, tol=1e-3,
            n_iter_no_change=3, random_state=0,
        ).fit(X, y)
        slow = SGDClassifier(
            learning_rate="constant", eta0=1e-4, max_iter=200, tol=None,
            random_state=0,
        ).fit(X, y)
        assert ad.n_iter_ < 200  # the decay cascade terminated the fit
        assert (ad.predict(X) == y).mean() >= (slow.predict(X) == y).mean()

    def test_regressor_early_stopping(self, rng):
        X = rng.normal(size=(600, 6)).astype(np.float32)
        w = rng.normal(size=6).astype(np.float32)
        y = X @ w + 0.01 * rng.normal(size=600).astype(np.float32)
        es = SGDRegressor(
            max_iter=500, tol=1e-5, early_stopping=True, random_state=0,
            learning_rate="constant", eta0=0.05, penalty=None,
        ).fit(X, y)
        assert es.n_iter_ < 500
        from sklearn.metrics import r2_score

        assert r2_score(y, np.asarray(es.predict(X))) > 0.9

    def test_ensemble_routes_adaptive_to_member_fit(self, rng):
        from dask_ml_tpu.ensemble import BlockwiseVotingClassifier

        X, y = _binary_data(rng, n=400)
        ens = BlockwiseVotingClassifier(
            SGDClassifier(learning_rate="adaptive", eta0=0.5, tol=1e-3,
                          random_state=0),
            n_blocks=4,
        ).fit(X, y)
        # fell back to per-member fit (each ran its own adaptive decay)
        assert len(ens.estimators_) == 4
        assert all(m.n_iter_ >= 1 for m in ens.estimators_)
        assert (np.asarray(ens.predict(X)) == y).mean() > 0.85

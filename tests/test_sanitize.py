"""graftsan: the runtime SPMD sanitizer gates itself (tier-1).

Three layers, mirroring tests/test_graftlint.py's structure for the
static half:

* detector semantics on synthetic programs — compile attribution,
  steady-phase compile violations, off-thread dispatch fail-fast,
  blessed-thread allowance, the implicit-transfer guard and its
  AllowSite escapes;
* the committed per-workload contract — the smoke suite
  (``dask_ml_tpu/sanitize/smoke.py``) must run clean against
  ``tools/sanitize_baseline.json`` (steady-state streamed fits compile
  ZERO new programs at prefetch depth 0 and 2, dispatch from one
  thread, and perform zero unallowed transfers), and the ratchet must
  fail on a deliberately-introduced steady-state compile, on new/stale
  workloads, and on count regressions;
* the static↔runtime bridge — every AllowSite citation must resolve to
  a suppressed finding in the committed graftlint baseline, so a dead
  suppression cannot keep a live runtime escape.
"""

import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu import sanitize
from dask_ml_tpu.sanitize import baseline as san_baseline
from dask_ml_tpu.sanitize.smoke import (
    WORKLOADS,
    metrics_from,
    run_smoke,
    run_workload,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAN_BASELINE = os.path.join(REPO, "tools", "sanitize_baseline.json")
LINT_BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")


def _fresh_jit():
    """A jitted callable no other test can have warmed: compiling it is
    guaranteed to emit a backend-compile event."""
    return jax.jit(lambda v: v * 2.0 + 1.0)


#: module-level so a re-run cannot trip the duplicate-site guard
_TEST_SITE = sanitize.AllowSite(
    "test-escape", rule="host-sync-loop", cites="0" * 16,
    note="unit-test fixture site")


# ---------------------------------------------------------------------------
# detector semantics
# ---------------------------------------------------------------------------

class TestCompileDetector:
    def test_compile_counted_and_attributed(self, sanitizer):
        f = _fresh_jit()
        x = jnp.ones(4)
        with sanitize.region("unit.compile"):
            f(x)
        rep = sanitizer.report()
        assert rep["regions"]["unit.compile"]["compiles"] >= 1
        assert rep["regions"]["unit.compile"]["dispatches"] >= 1
        assert not rep["violations"]

    def test_warm_call_compiles_nothing(self, sanitizer):
        f = _fresh_jit()
        x = jnp.ones(4)
        f(x)
        before = sanitizer.report()["totals"]["compiles"]
        f(x)
        assert sanitizer.report()["totals"]["compiles"] == before

    def test_steady_state_compile_is_a_violation(self, sanitizer):
        """The acceptance regression test: a deliberately-introduced new
        steady-state compile must fail the gate."""
        f = _fresh_jit()
        x = jnp.ones(4)
        f(x)  # warmup
        with sanitizer.steady(guard=False):
            f(jnp.ones(5))  # new shape -> new program IN STEADY
        rep = sanitizer.report()
        assert any(v["kind"] == "steady-state-compile"
                   for v in rep["violations"])
        with pytest.raises(sanitize.CompileViolation):
            sanitizer.assert_clean()
        # and the same run fails the baseline ratchet as a hard invariant
        m = metrics_from(sanitizer)
        assert m["steady_compiles"] >= 1
        delta = san_baseline.compare(
            {"workloads": {"wl": {**m, "steady_compiles": 0,
                                  "violations": 0}}}, {"wl": m})
        assert any("steady_compiles" in v for v in delta["violations"])

    def test_off_thread_compile_fails_fast_in_that_thread(self):
        errs = []
        with sanitize.sanitize(label="t") as s:
            def rogue():
                try:
                    _fresh_jit()(jnp.ones(3))
                except sanitize.CompileViolation as e:
                    errs.append(e)
                except sanitize.DispatchViolation as e:
                    errs.append(e)
            t = threading.Thread(target=rogue, name="rogue-compiler")
            t.start()
            t.join()
        assert errs, "off-thread compile/dispatch must raise in the worker"
        assert s.report()["violations"]


class TestDispatchDetector:
    def test_second_thread_dispatch_fails_fast(self):
        f = _fresh_jit()
        x = jnp.ones(4)
        f(x)  # warm OUTSIDE the scope: the rogue dispatch is compile-free
        errs = []
        with sanitize.sanitize(label="t") as s:
            f(x)

            def rogue():
                try:
                    f(x)
                except sanitize.DispatchViolation as e:
                    errs.append(e)

            t = threading.Thread(target=rogue, name="rogue-dispatcher")
            t.start()
            t.join()
        assert len(errs) == 1
        assert any(v["kind"] == "off-thread-dispatch"
                   for v in s.report()["violations"])

    def test_blessed_compile_thread_is_allowed(self):
        f = _fresh_jit()
        x = jnp.ones(4)
        ok = []
        with sanitize.sanitize(label="t") as s:
            def warmer():
                ok.append(f(x) is not None)

            t = threading.Thread(
                target=warmer, name="dask-ml-tpu-compile-ahead")
            t.start()
            t.join()
        assert ok == [True]
        assert not s.report()["violations"]
        assert "dask-ml-tpu-compile-ahead" in s.report()["dispatch_threads"]
        # PR-8 attribution: the blessed thread's compile lands in the
        # separately-ratcheted ahead counters, not in "compiles"
        totals = s.report()["totals"]
        assert totals["ahead_compiles"] >= 1
        assert totals["compiles"] == 0

    def test_blessed_steady_compile_attributed_not_violating(self):
        """A steady-phase compile on the blessed compile-ahead thread is
        that thread's job: counted in steady_ahead_compiles (a ratchet
        ceiling), never a steady-state-compile violation — while the
        same compile on the main thread (sibling test below) stays a
        hard zero."""
        f = _fresh_jit()
        x = jnp.ones(6)
        with sanitize.sanitize(label="t") as s:
            with s.steady(guard=False):
                t = threading.Thread(
                    target=lambda: f(x), name="dask-ml-tpu-compile-ahead")
                t.start()
                t.join()
        rep = s.report()
        assert not rep["violations"]
        assert rep["totals"]["steady_compiles"] == 0
        assert rep["totals"]["steady_ahead_compiles"] >= 1

    def test_prefetch_worker_name_is_not_blessed(self):
        """The §8 contract at runtime: the staging worker's thread name
        dispatching a program IS the deadlock class, caught at the
        violating enqueue."""
        from dask_ml_tpu.pipeline.core import PREFETCH_THREAD_NAME

        f = _fresh_jit()
        x = jnp.ones(4)
        f(x)
        errs = []
        with sanitize.sanitize(label="t"):
            def bad_worker():
                try:
                    f(x)
                except (sanitize.DispatchViolation,
                        sanitize.CompileViolation) as e:
                    errs.append(e)

            t = threading.Thread(target=bad_worker,
                                 name=PREFETCH_THREAD_NAME)
            t.start()
            t.join()
        assert errs

    def test_nested_sanitize_raises(self, sanitizer):
        with pytest.raises(RuntimeError, match="already active"):
            with sanitize.sanitize(label="inner"):
                pass  # pragma: no cover


class TestTransferDetector:
    def test_steady_guard_blocks_implicit_transfer(self, sanitizer):
        with sanitizer.steady():
            with pytest.raises(Exception, match="Disallowed"):
                jnp.zeros(3)  # scalar-const materialization: implicit

    def test_explicit_staging_put_stays_legal(self, sanitizer):
        # the §8 staging contract: jnp.asarray of host numpy is a put
        with sanitizer.steady():
            out = jnp.asarray(np.ones(3, np.float32))
        assert out.shape == (3,)

    def test_allow_site_escape_and_count(self, sanitizer):
        site = _TEST_SITE
        with sanitizer.steady():
            with site.allow():
                jnp.zeros(3)  # implicit, but explicitly allowed here
        assert sanitizer.report()["allow_sites"]["test-escape"] == 1

    def test_d2h_sync_counter(self, sanitizer):
        x = jnp.ones(3) + 0.0
        with sanitize.region("unit.d2h"):
            float(jnp.sum(x))
        assert sanitizer.report()["regions"]["unit.d2h"]["d2h_syncs"] >= 1

    def test_unshard_counted_at_definition(self, sanitizer):
        """The API-boundary fetch is instrumented IN unshard itself —
        call sites that bound the name at import time (most of the
        package) must still count."""
        from dask_ml_tpu.core.sharded import unshard

        x = jnp.ones(8) + 0.0
        with sanitize.region("unit.unshard"):
            out = unshard(x)
        assert out.shape == (8,)
        assert sanitizer.report()["regions"]["unit.unshard"][
            "d2h_syncs"] >= 1

    def test_steady_guard_false_disarms_step_guard(self, sanitizer):
        """steady(guard=False) must govern the estimator-internal
        step_guard() calls too — the per-steady choice, not the
        constructor default."""
        with sanitizer.steady(guard=False):
            with sanitize.step_guard():
                jnp.zeros(3)  # implicit transfer: must NOT raise

    def test_ambient_skips_when_scoped_sanitizer_active(self, sanitizer):
        # atomic-or-skip: the ambient env wrapper must never crash a
        # fit on the no-nesting rule when an explicit scope is open
        with sanitize.ambient("ambient:race") as a:
            assert a is None
        assert sanitize.active_sanitizer() is sanitizer


# ---------------------------------------------------------------------------
# the committed per-workload contract (the tier-1 gate)
# ---------------------------------------------------------------------------

class TestWorkloadGate:
    @pytest.fixture(scope="class")
    def smoke_results(self):
        """ONE full smoke run shared by the gate tests (the suite is
        the expensive part; every assertion reads the same results)."""
        return run_smoke()

    def test_streamed_fits_steady_clean_depth_0_and_2(self, smoke_results):
        """The acceptance criterion: steady-state SGD / MiniBatchKMeans /
        IncrementalPCA streamed fits compile ZERO post-warmup programs
        and perform zero unallowed transfers at prefetch depth 0 AND 2,
        dispatching from a single thread throughout."""
        for wl in ("sgd_stream_d0", "sgd_stream_d2", "mbk_stream_d0",
                   "mbk_stream_d2", "ipca_stream_d0", "ipca_stream_d2"):
            m = smoke_results[wl]
            assert not m.get("error"), f"{wl}: {m.get('error')}"
            assert m["steady_compiles"] == 0, wl
            assert m["violations"] == 0, wl
            assert m["transfer_errors"] == 0, wl
            assert m["steady_d2h_syncs"] == 0, wl
            assert len(m["dispatch_threads"]) == 1, wl

    def test_prefetch_worker_never_dispatches(self, smoke_results):
        from dask_ml_tpu.pipeline.core import PREFETCH_THREAD_NAME

        for wl in ("sgd_stream_d2", "mbk_stream_d2", "ipca_stream_d2"):
            assert PREFETCH_THREAD_NAME not in \
                smoke_results[wl]["dispatch_threads"], wl

    def test_committed_baseline_matches(self, smoke_results):
        """The ratchet gate: the run must be clean against the COMMITTED
        snapshot — new compiles/transfers fail, stale entries fail."""
        snap = san_baseline.load(SAN_BASELINE)
        delta = san_baseline.compare(snap, smoke_results)
        assert san_baseline.is_clean(delta), delta

    def test_whole_array_fits_compile_free_on_refit(self, smoke_results):
        for wl in ("kmeans_fit", "kmeans_fit_ckpt", "mbk_fit", "glm_fit"):
            m = smoke_results[wl]
            assert not m.get("error"), f"{wl}: {m.get('error')}"
            assert m["steady_compiles"] == 0, wl
            assert m["violations"] == 0, wl

    def test_allow_sites_exercised_not_vacuous(self, smoke_results):
        """The boundary-sync ratchet must have teeth: the checkpointed
        Lloyd and MBK epoch workloads pass their AllowSites a NONZERO
        number of times, so a regression that syncs more often fails
        the committed allow-site ceiling rather than sailing through an
        all-empty table."""
        assert smoke_results["kmeans_fit_ckpt"]["allow_sites"].get(
            "kmeans-segment-sync", 0) >= 1
        assert smoke_results["mbk_fit"]["allow_sites"].get(
            "mbk-epoch-sync", 0) >= 1


class TestFaultInjection:
    def test_worker_ingest_retry_does_not_double_count(self, tmp_path, rng):
        """An absorbed transient ingest fault (retried INSIDE the
        prefetch worker) must not mint compiles or violations: the
        retry re-reads host bytes, it never re-dispatches."""
        from dask_ml_tpu import io as dio
        from dask_ml_tpu.linear_model import SGDRegressor
        from dask_ml_tpu.pipeline import stream_partial_fit
        from dask_ml_tpu.resilience.testing import FaultPlan, fault_plan

        X = rng.normal(size=(400, 5)).astype(np.float32)
        p = tmp_path / "r.bin"
        X.tofile(p)

        def blocks(retries=0):
            for b in dio.stream_binary_blocks(str(p), 100, 5,
                                              retries=retries):
                yield b[:, :4], b[:, 4]

        model = SGDRegressor(random_state=0)
        with sanitize.sanitize(label="fault") as s:
            stream_partial_fit(model, blocks(), depth=2)  # warmup
            plan = FaultPlan()
            plan.inject("ingest", at_call=2, times=1)
            with s.steady(), fault_plan(plan):
                stream_partial_fit(model, blocks(retries=2), depth=2)
        assert plan.fired["ingest"] == 1
        m = metrics_from(s)
        assert m["steady_compiles"] == 0
        assert m["violations"] == 0
        assert m["transfer_errors"] == 0

    def test_step_fault_retry_does_not_recompile(self, rng):
        """A failed step retried at the stream level re-dispatches the
        SAME program: steady-state compile count stays zero across the
        retry (the 'retries must not double-count compiles' contract)."""
        from dask_ml_tpu.linear_model import SGDRegressor
        from dask_ml_tpu.pipeline import stream_partial_fit
        from dask_ml_tpu.resilience.testing import (
            FaultInjected, FaultPlan, fault_plan,
        )

        def blocks():
            r = np.random.RandomState(3)
            for _ in range(4):
                X = r.normal(size=(64, 4)).astype(np.float32)
                yield X, X[:, 0]

        model = SGDRegressor(random_state=0)
        with sanitize.sanitize(label="stepfault") as s:
            stream_partial_fit(model, blocks(), depth=0)  # warmup
            plan = FaultPlan()
            plan.inject("step", at_call=2, times=1)
            with s.steady():
                with fault_plan(plan):
                    with pytest.raises(FaultInjected):
                        stream_partial_fit(model, blocks(), depth=0)
                # the retry: same shapes, same programs — no compile
                stream_partial_fit(model, blocks(), depth=0)
        m = metrics_from(s)
        assert m["steady_compiles"] == 0
        assert m["violations"] == 0


# ---------------------------------------------------------------------------
# baseline ratchet semantics (mirrors test_graftlint's TestBaseline)
# ---------------------------------------------------------------------------

def _clean_metrics(**over):
    m = {"warmup_compiles": 5, "steady_compiles": 0, "steady_d2h_syncs": 2,
         "violations": 0, "transfer_errors": 0,
         "allow_sites": {"site-a": 3}, "dispatch_threads": ["MainThread"]}
    m.update(over)
    return m


class TestBaselineRatchet:
    def test_round_trip_and_clean_compare(self, tmp_path):
        results = {"wl": _clean_metrics()}
        path = str(tmp_path / "san.json")
        san_baseline.write(path, san_baseline.emit(results))
        snap = san_baseline.load(path)
        assert snap["tool"] == "graftsan"
        delta = san_baseline.compare(snap, results)
        assert san_baseline.is_clean(delta)

    def test_new_workload_fails(self):
        snap = {"workloads": {"wl": _clean_metrics()}}
        delta = san_baseline.compare(
            snap, {"wl": _clean_metrics(), "extra": _clean_metrics()})
        assert delta["new"] == ["extra"]

    def test_stale_entry_fails(self):
        """The committed snapshot must always match the suite: an entry
        whose workload no longer runs is itself a gate failure."""
        snap = {"workloads": {"wl": _clean_metrics(),
                              "gone": _clean_metrics()}}
        delta = san_baseline.compare(snap, {"wl": _clean_metrics()})
        assert delta["stale"] == ["gone"]
        assert not san_baseline.is_clean(delta)

    def test_new_compiles_ratchet(self):
        snap = {"workloads": {"wl": _clean_metrics()}}
        delta = san_baseline.compare(
            snap, {"wl": _clean_metrics(warmup_compiles=6)})
        assert any("warmup_compiles" in r for r in delta["regressions"])

    def test_fewer_compiles_pass(self):
        # ceilings, not identities: a warm jit cache legitimately
        # observes fewer compiles than the cold rebaseline run
        snap = {"workloads": {"wl": _clean_metrics()}}
        delta = san_baseline.compare(
            snap, {"wl": _clean_metrics(warmup_compiles=0)})
        assert san_baseline.is_clean(delta)

    def test_new_transfers_ratchet(self):
        snap = {"workloads": {"wl": _clean_metrics()}}
        delta = san_baseline.compare(
            snap, {"wl": _clean_metrics(steady_d2h_syncs=9)})
        assert any("steady_d2h_syncs" in r for r in delta["regressions"])

    def test_allow_site_count_ratchet(self):
        snap = {"workloads": {"wl": _clean_metrics()}}
        delta = san_baseline.compare(
            snap, {"wl": _clean_metrics(allow_sites={"site-a": 4})})
        assert any("site-a" in r for r in delta["regressions"])
        delta2 = san_baseline.compare(
            snap, {"wl": _clean_metrics(allow_sites={"rogue": 1,
                                                     "site-a": 3})})
        assert any("rogue" in r for r in delta2["regressions"])

    def test_snapshot_cannot_grandfather_violations(self):
        snap = {"workloads": {"wl": _clean_metrics(steady_compiles=2)}}
        delta = san_baseline.compare(snap, {"wl": _clean_metrics()})
        assert any("grandfather" in v for v in delta["violations"])

    def test_partial_run_checks_invariants_only(self):
        snap = {"workloads": {"wl": _clean_metrics(),
                              "other": _clean_metrics()}}
        delta = san_baseline.compare(
            snap, {"wl": _clean_metrics(warmup_compiles=99)}, partial=True)
        assert san_baseline.is_clean(delta)
        delta2 = san_baseline.compare(
            snap, {"wl": _clean_metrics(steady_compiles=1)}, partial=True)
        assert not san_baseline.is_clean(delta2)

    def test_newer_version_refused(self, tmp_path):
        path = str(tmp_path / "future.json")
        with open(path, "w") as fh:
            json.dump({"version": 99, "workloads": {}}, fh)
        with pytest.raises(ValueError, match="newer"):
            san_baseline.load(path)

    def test_malformed_refused(self, tmp_path):
        path = str(tmp_path / "bad.json")
        with open(path, "w") as fh:
            json.dump({"version": 1}, fh)
        with pytest.raises(ValueError, match="malformed"):
            san_baseline.load(path)


# ---------------------------------------------------------------------------
# the static <-> runtime bridge
# ---------------------------------------------------------------------------

class TestAllowSiteCitations:
    def test_every_site_cites_a_live_suppression(self):
        """Each runtime allow-site must cite a suppressed finding in the
        COMMITTED graftlint baseline, same rule — a deleted suppression
        invalidates its runtime escape, and this test is what notices."""
        import dask_ml_tpu  # noqa: F401  (registers every module's sites)
        import dask_ml_tpu.cluster.spectral  # noqa: F401  (lazy module)

        with open(LINT_BASELINE) as fh:
            snap = json.load(fh)
        suppressed = {
            e["fingerprint"]: e["rule"]
            for e in snap["findings"] if e["suppressed"]
        }
        sites = sanitize.registered_sites()
        # every production module's sites are registered by the imports
        # (search-packed-scores retired with ISSUE 13: the cohort
        # refactor removed the static host-sync-loop finding it
        # bridged — float() of an already-fetched numpy vector)
        assert {"kmeans-segment-sync", "mbk-epoch-sync",
                "spectral-ritz-sync", "ensemble-epoch-sync"} <= set(sites)
        for site in sites.values():
            if site.site_id.startswith("test-"):
                continue  # unit-test fixtures register throwaway sites
            for fp in site.cites:
                assert fp in suppressed, (
                    f"AllowSite {site.site_id!r} cites {fp} which is not "
                    f"a suppressed finding in tools/graftlint_baseline"
                    f".json — the static suppression it runtime-verifies "
                    f"is gone; delete or re-cite the site")
                assert suppressed[fp] == site.rule, site.site_id

    def test_suppression_budget(self):
        """The PR-6 triage target: ≤ 13 inline suppression comments.
        The runtime sanitizer proved the truncated_svd streaming path
        host-only, so its four suppressions became a named host tail
        (count 8); PR-8 added exactly ONE — the ``jit-outside-cache``
        rule's sanctioned escape at the program cache's own internal
        ``jax.jit`` wrap (programs/cache.py), the single place a raw
        jit must exist (count 9).  PR-9 added TWO, both runtime-
        verified by the new machinery itself: the blessed compile-ahead
        thread's ``thread-dispatch`` escape (programs/ahead.py — its
        supervisor/flight bookkeeping is host-only but dynamically
        dispatched, and graftsan's dispatch detector plus the
        ahead-crash drill verify the thread never dispatches) and the
        JSONL sink's shutdown ``swallowed-fault`` escape
        (obs/export.py — the sink already warned once when it was
        dropped; the exporter-ENOSPC drill pins that contract) — count
        11.  ISSUE 12 added FIVE, all ``donation-miss`` justifications
        for the deliberate non-donations (the gemm-output-smaller
        class: kmeans.assign, sgd.eval_loss, naive_bayes
        class_moments, serve margins + lane_margins) — each
        runtime-verified by an aliasing regression test asserting the
        undonated buffers really survive — count 16.  ISSUE 13
        REMOVED one: the packed-scores ``host-sync-loop`` suppression
        (and its ``search-packed-scores`` AllowSite twin) retired when
        the cohort refactor made the finding vanish — the per-model
        ``float()`` reads an already-fetched numpy vector (count 15).
        ISSUE 15 added ONE: the data-reader spawn's
        ``thread-dispatch`` escape (data/readers.py) — the readers now
        record graftpath ``data.parse``/``data.fetch`` intervals via
        ``obs.record_span``, a pure-stdlib call the static prover
        cannot resolve cross-module; the ``ingest_parallel`` graftsan
        workload runtime-verifies the contract (any dispatch
        attributed to a reader thread is a hard violation) — count 16.
        ISSUE 18 added SEVEN, all on the graftpilot controller
        (control/pilot.py): the host-only ``dask-ml-tpu-pilot`` thread
        (``thread-dispatch``; it is in ``HOST_ONLY_THREAD_NAMES`` and
        graftsan's dispatch detector would flag any dispatch it made)
        plus its single-owner cycle state (``unguarded-shared-state``;
        written only from the pilot thread itself) — count 23.  PR 19
        added ONE: the fleet-deploy drill's traffic thread
        (resilience/drills.py, ``thread-dispatch``) — it only ENQUEUES
        via ``ModelServer.submit`` and parks on the future; every
        device dispatch stays on the replicas' blessed serve loops,
        runtime-verified by the dispatch detector across the serve
        drills — count 24.  ISSUE 20 added ONE: the lock sanitizer's
        rogue-writer drill thread (sanitize/locks.py,
        ``contract-roster-drift``) — the thread is deliberately OFF
        the ``_spmd`` roster because the drill EXISTS to prove the
        runtime roster check catches an unreviewed package-prefixed
        thread; rostering it would blind the very check it verifies —
        so the count is now 25."""
        import subprocess

        out = subprocess.run(
            ["grep", "-rc", "graftlint: disable=", "--include=*.py",
             os.path.join(REPO, "dask_ml_tpu")],
            capture_output=True, text=True)
        total = sum(int(line.rsplit(":", 1)[1])
                    for line in out.stdout.splitlines() if ":" in line)
        # analysis/core.py's docstring EXAMPLE is not a live suppression
        assert total - 1 <= 27
        assert total - 1 == 25, (
            "suppression count moved — update this test AND re-audit "
            "the AllowSite citations")


class TestIpcaFitBoundary:
    def test_uninstrumented_fit_has_no_per_block_sync(self, rng):
        """IncrementalPCA.fit without a checkpoint/watcher must not pay
        the boundary-state device fetch per block — the regression the
        on-device count refactor could have reintroduced through the
        eager ``_fit_state()`` in the on_block hook."""
        from dask_ml_tpu.decomposition import IncrementalPCA

        X = rng.normal(size=(160, 4)).astype(np.float32)
        with sanitize.sanitize(label="ipca_fit") as s:
            IncrementalPCA(n_components=2, batch_size=16).fit(X)
        assert s.report()["totals"]["d2h_syncs"] == 0


class TestHostOnlyPathsStayHostOnly:
    def test_truncated_svd_stream_never_touches_device(self, rng):
        """The de-suppressed truncated_svd streaming path, runtime
        verified: a full streamed fit under an armed sanitizer performs
        ZERO device dispatches, compiles, and transfers — the claim the
        four deleted host-sync-loop suppressions used to assert
        statically is now measured."""
        from dask_ml_tpu.decomposition import TruncatedSVD

        blocks = [rng.normal(size=(50, 8)).astype(np.float32)
                  for _ in range(3)]

        with sanitize.sanitize(label="tsvd_stream") as s:
            with s.steady():  # guard armed for the WHOLE fit
                est = TruncatedSVD(n_components=3, random_state=0)
                est.fit_streamed(lambda: iter(blocks), n_features=8)
        rep = s.report()
        assert rep["totals"]["dispatches"] == 0
        assert rep["totals"]["compiles"] == 0
        assert not rep["violations"]
        assert est.components_.shape == (3, 8)


# ---------------------------------------------------------------------------
# diagnostics + ambient mode + CLI
# ---------------------------------------------------------------------------

class TestDiagnosticsReport:
    def test_live_and_last_report(self):
        from dask_ml_tpu import diagnostics

        with sanitize.sanitize(label="diag") as s:
            _fresh_jit()(jnp.ones(2))
            live = diagnostics.sanitize_report()
            assert live["label"] == "diag"
            assert live["totals"]["compiles"] >= 1
        last = diagnostics.sanitize_report()
        assert last["label"] == "diag"
        assert last["totals"] == s.report()["totals"]

    def test_report_shape(self, sanitizer):
        rep = sanitizer.report()
        assert set(rep) == {"label", "phase", "regions", "totals",
                            "violations", "allow_sites",
                            "dispatch_threads"}


class TestAmbientMode:
    def test_env_knob_wraps_streams(self, monkeypatch, rng):
        from dask_ml_tpu import diagnostics
        from dask_ml_tpu.linear_model import SGDRegressor
        from dask_ml_tpu.pipeline import stream_partial_fit

        monkeypatch.setenv(sanitize.SANITIZE_ENV, "1")
        blocks = [(rng.normal(size=(64, 4)).astype(np.float32),
                   rng.normal(size=64).astype(np.float32))
                  for _ in range(3)]
        stream_partial_fit(SGDRegressor(random_state=0), iter(blocks),
                           depth=2, label="ambient_test")
        rep = diagnostics.sanitize_report()
        assert rep is not None
        assert rep["label"] == "ambient:ambient_test"
        assert rep["totals"]["dispatches"] >= 3

    def test_env_knob_off_by_default(self, monkeypatch):
        monkeypatch.delenv(sanitize.SANITIZE_ENV, raising=False)
        assert not sanitize.enabled_by_env()

    def test_env_knob_strict_values(self, monkeypatch):
        # 'false'/'no'/'OFF' are off, case-insensitive; a typo is a loud
        # error, never silently 'on' (ambient mode suppresses the pjit
        # fastpath — nobody should pay that for a bad value)
        for off in ("false", "no", "OFF", "0"):
            monkeypatch.setenv(sanitize.SANITIZE_ENV, off)
            assert not sanitize.enabled_by_env(), off
        for on in ("1", "ON", "true", "yes"):
            monkeypatch.setenv(sanitize.SANITIZE_ENV, on)
            assert sanitize.enabled_by_env(), on
        monkeypatch.setenv(sanitize.SANITIZE_ENV, "maybe")
        with pytest.raises(ValueError, match="DASK_ML_TPU_SANITIZE"):
            sanitize.enabled_by_env()


class TestCLI:
    def test_list_workloads(self, capsys):
        from dask_ml_tpu.sanitize.cli import main

        assert main(["--list-workloads"]) == 0
        out = capsys.readouterr().out
        for wl in WORKLOADS:
            assert wl in out

    def test_unknown_workload_exits_two(self, capsys):
        from dask_ml_tpu.sanitize.cli import main

        assert main(["--workloads", "nope"]) == 2

    def test_unreadable_baseline_exits_two(self, tmp_path, capsys):
        from dask_ml_tpu.sanitize.cli import main

        rc = main(["--workloads", "sgd_stream_d0",
                   "--baseline", str(tmp_path / "missing.json")])
        assert rc == 2

    def test_run_one_workload_json(self, tmp_path, capsys):
        from dask_ml_tpu.sanitize.cli import main

        rc = main(["--workloads", "sgd_stream_d0", "--format", "json",
                   "--baseline", SAN_BASELINE])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["clean"] is True
        assert "sgd_stream_d0" in payload["workloads"]

    def test_partial_write_baseline_refused(self, tmp_path, capsys):
        """A subset snapshot would shadow the committed full-suite
        baseline (everything unselected reads as new on the next gate):
        usage error, exit 2, nothing written."""
        from dask_ml_tpu.sanitize.cli import main

        path = str(tmp_path / "partial.json")
        rc = main(["--workloads", "sgd_stream_d0",
                   "--write-baseline", path])
        assert rc == 2
        assert not os.path.exists(path)

    def test_full_write_baseline_round_trip(self, tmp_path, capsys):
        from dask_ml_tpu.sanitize.cli import main

        path = str(tmp_path / "full.json")
        rc = main(["--write-baseline", path, "--format", "json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["clean"] is True
        assert set(json.load(open(path))["workloads"]) == set(WORKLOADS)

    def test_violating_run_never_writes_baseline(self, tmp_path,
                                                 monkeypatch, capsys):
        """A snapshot may never carry a hard-invariant violation: the
        write is gated BEFORE touching disk, so a bad rebaseline leaves
        the committed file exactly as it was."""
        from dask_ml_tpu.sanitize import smoke
        from dask_ml_tpu.sanitize.cli import main

        bad = {"wl": _clean_metrics(steady_compiles=3)}
        monkeypatch.setattr(smoke, "run_smoke", lambda names=None: bad)
        path = str(tmp_path / "bad.json")
        rc = main(["--write-baseline", path])
        assert rc == 1
        assert not os.path.exists(path)


class TestWorkloadRunner:
    def test_unknown_name_raises(self):
        with pytest.raises(KeyError):
            run_smoke(["nope"])

    def test_workload_error_becomes_metric(self, monkeypatch):
        from dask_ml_tpu.sanitize import smoke

        def boom():
            raise RuntimeError("synthetic workload crash")

        monkeypatch.setitem(smoke.WORKLOADS, "boom", boom)
        m = run_workload("boom")
        assert m["violations"] == 1
        assert "synthetic workload crash" in m["error"]

import numpy as np
import pytest
import sklearn.linear_model as sl

import dask_ml_tpu.linear_model as dlm
from dask_ml_tpu.core import shard_rows


@pytest.fixture
def clf_data(rng):
    n, d = 400, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    p = 1 / (1 + np.exp(-(X @ w + 0.3)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


@pytest.fixture
def reg_data(rng):
    n, d = 300, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w + 1.7 + 0.05 * rng.normal(size=n)).astype(np.float32)
    return X, y


class TestLogisticRegression:
    @pytest.mark.parametrize("solver", ["admm", "lbfgs", "newton", "proximal_grad"])
    def test_parity_with_sklearn(self, clf_data, solver):
        X, y = clf_data
        ours = dlm.LogisticRegression(solver=solver, C=1e4, max_iter=200).fit(
            shard_rows(X), shard_rows(y)
        )
        theirs = sl.LogisticRegression(C=1e4, tol=1e-8).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(ours.coef_), theirs.coef_[0], atol=0.1
        )
        assert ours.intercept_ == pytest.approx(theirs.intercept_[0], abs=0.1)

    def test_predict_and_score(self, clf_data):
        X, y = clf_data
        lr = dlm.LogisticRegression(solver="lbfgs", C=10.0).fit(X, y)
        acc = lr.score(X, y)
        # sklearn scores exactly 0.815 on this fixture; match it
        assert acc > 0.80

    def test_predict_proba_shape_and_range(self, clf_data):
        X, y = clf_data
        lr = dlm.LogisticRegression(solver="lbfgs").fit(X, y)
        proba = np.asarray(lr.predict_proba(X))
        assert proba.shape == (400, 2)
        np.testing.assert_allclose(proba.sum(1), np.ones(400), atol=1e-5)

    def test_decision_function(self, clf_data):
        X, y = clf_data
        lr = dlm.LogisticRegression(solver="lbfgs").fit(X, y)
        eta = np.asarray(lr.decision_function(X))
        assert eta.shape == (400,)
        np.testing.assert_array_equal(
            eta > 0, np.asarray(lr.predict(X)).astype(bool)
        )

    def test_l1_penalty_sparsifies(self, clf_data):
        X, y = clf_data
        Xw = np.hstack([X, np.zeros((X.shape[0], 3), dtype=np.float32)])
        lr = dlm.LogisticRegression(penalty="l1", C=0.01, solver="admm").fit(Xw, y)
        coef = np.asarray(lr.coef_)
        assert np.sum(np.abs(coef[-3:]) < 1e-4) == 3

    def test_no_intercept(self, clf_data):
        X, y = clf_data
        lr = dlm.LogisticRegression(fit_intercept=False, solver="lbfgs").fit(X, y)
        assert lr.intercept_ == 0.0

    def test_bad_solver(self, clf_data):
        X, y = clf_data
        with pytest.raises(ValueError, match="solver"):
            dlm.LogisticRegression(solver="saga").fit(X, y)


class TestLinearRegression:
    def test_parity_with_sklearn(self, reg_data):
        X, y = reg_data
        ours = dlm.LinearRegression(solver="lbfgs", C=1e6, max_iter=300).fit(X, y)
        theirs = sl.LinearRegression().fit(X, y)
        np.testing.assert_allclose(np.asarray(ours.coef_), theirs.coef_, atol=2e-2)
        assert ours.intercept_ == pytest.approx(theirs.intercept_, abs=2e-2)

    def test_admm_solver(self, reg_data):
        X, y = reg_data
        ours = dlm.LinearRegression(solver="admm", C=1e6, max_iter=200).fit(
            shard_rows(X), shard_rows(y)
        )
        theirs = sl.LinearRegression().fit(X, y)
        np.testing.assert_allclose(np.asarray(ours.coef_), theirs.coef_, atol=5e-2)

    def test_r2_score(self, reg_data):
        X, y = reg_data
        lr = dlm.LinearRegression(solver="lbfgs", C=1e6).fit(X, y)
        assert lr.score(X, y) > 0.98


class TestPoissonRegression:
    def test_recovers_coefficients(self, rng):
        n, d = 500, 4
        X = (rng.normal(size=(n, d)) * 0.4).astype(np.float32)
        w = (rng.normal(size=d) * 0.5).astype(np.float32)
        y = rng.poisson(np.exp(X @ w + 0.2)).astype(np.float32)
        ours = dlm.PoissonRegression(solver="lbfgs", C=1e6, max_iter=300).fit(X, y)
        sk = sl.PoissonRegressor(alpha=0.0, tol=1e-8, max_iter=1000).fit(X, y)
        np.testing.assert_allclose(np.asarray(ours.coef_), sk.coef_, atol=5e-2)
        assert ours.intercept_ == pytest.approx(sk.intercept_, abs=5e-2)

    def test_predict_positive(self, rng):
        X = rng.normal(size=(100, 3)).astype(np.float32)
        y = rng.poisson(1.0, size=100).astype(np.float32)
        pr = dlm.PoissonRegression(solver="lbfgs").fit(X, y)
        assert (np.asarray(pr.predict(X)) > 0).all()

    def test_deviance_decreases_with_fit(self, rng):
        X = (rng.normal(size=(200, 3)) * 0.4).astype(np.float32)
        w = np.array([0.5, -0.3, 0.2], dtype=np.float32)
        y = rng.poisson(np.exp(X @ w)).astype(np.float32)
        fitted = dlm.PoissonRegression(solver="lbfgs", C=1e6).fit(X, y)
        unfitted = dlm.PoissonRegression(solver="lbfgs", max_iter=0 or 1, C=1e6)
        unfitted.coef_ = np.zeros(3, dtype=np.float32)
        unfitted.intercept_ = 0.0
        assert fitted.get_deviance(X, y) < unfitted.get_deviance(X, y)


class TestReviewRegressions:
    def test_score_with_sharded_y(self, clf_data):
        X, y = clf_data
        sX, sy = shard_rows(X), shard_rows(y)
        lr = dlm.LogisticRegression(solver="lbfgs", C=10.0).fit(sX, sy)
        assert lr.score(sX, sy) > 0.5

    def test_linear_score_with_sharded_y(self, reg_data):
        X, y = reg_data
        sX, sy = shard_rows(X), shard_rows(y)
        lr = dlm.LinearRegression(solver="lbfgs", C=1e6).fit(sX, sy)
        assert lr.score(sX, sy) > 0.9


class TestMixedPrecision:
    """bf16 design matrix + f32 parameters/accumulation: X's HBM traffic
    halves (the dominant solver cost on TPU) while every reduction and the
    fitted coefficients stay float32 (solvers.algorithms._param_dtype)."""

    @pytest.mark.parametrize("solver", ["admm", "lbfgs", "gradient_descent"])
    def test_bf16_design_matrix_converges(self, clf_data, solver):
        import jax.numpy as jnp

        X, y = clf_data
        f32 = dlm.LogisticRegression(solver=solver, C=10.0).fit(
            shard_rows(X), y
        )
        bf16 = dlm.LogisticRegression(solver=solver, C=10.0).fit(
            shard_rows(X, dtype=jnp.bfloat16), y
        )
        assert np.asarray(bf16.coef_).dtype == np.float32
        acc_f32 = f32.score(shard_rows(X), y)
        acc_bf16 = bf16.score(shard_rows(X, dtype=jnp.bfloat16), y)
        assert acc_bf16 >= acc_f32 - 0.02

    def test_bf16_regression(self, reg_data):
        import jax.numpy as jnp

        X, y = reg_data
        lr = dlm.LinearRegression(solver="lbfgs", C=1e6).fit(
            shard_rows(X, dtype=jnp.bfloat16), shard_rows(y)
        )
        assert np.asarray(lr.coef_).dtype == np.float32
        assert lr.score(shard_rows(X), y) > 0.85


class TestNIter:
    @pytest.mark.parametrize("solver", ["admm", "lbfgs", "newton",
                                        "gradient_descent", "proximal_grad"])
    def test_n_iter_recorded(self, clf_data, solver):
        X, y = clf_data
        lr = dlm.LogisticRegression(solver=solver).fit(shard_rows(X), y)
        assert lr.n_iter_.shape == (1,) and 1 <= lr.n_iter_[0] <= lr.max_iter

    def test_multiclass_n_iter_per_class(self, rng):
        X = rng.normal(size=(300, 5)).astype(np.float32)
        y = rng.randint(0, 3, size=300)
        lr = dlm.LogisticRegression(solver="lbfgs").fit(shard_rows(X), y)
        assert lr.n_iter_.shape == (3,)

    def test_linear_regression_n_iter(self, reg_data):
        X, y = reg_data
        lr = dlm.LinearRegression(solver="lbfgs").fit(shard_rows(X), y)
        assert lr.n_iter_.shape == (1,)


@pytest.fixture
def multiclass_data(rng):
    n, d, K = 1200, 6, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, K))
    y = (X @ W + rng.normal(scale=0.5, size=(n, K))).argmax(1)
    return X, y


class TestPackedOvR:
    """VERDICT r2 next #5: the K one-vs-rest solves run as ONE vmapped
    program (O(1) dispatches), with parity against sklearn OvR."""

    @pytest.mark.parametrize(
        "solver", ["lbfgs", "admm", "gradient_descent", "proximal_grad"]
    )
    def test_single_dispatch_and_accuracy(self, multiclass_data, mesh,
                                          solver, monkeypatch):
        from dask_ml_tpu import solvers

        X, y = multiclass_data
        # this test pins the PACKED path specifically (auto resolves to
        # sequential on CPU per the measured r3 number)
        monkeypatch.setenv("DASK_ML_TPU_PACK", "packed")
        solvers.reset_dispatch_counts()
        lr = dlm.LogisticRegression(
            solver=solver, C=1.0, max_iter=150
        ).fit(X, y)
        assert solvers.DISPATCH_COUNTS["solves"] == 1
        assert lr.betas_.shape[0] == 4
        assert lr.n_iter_.shape == (4,)
        acc = float((lr.predict(X) == y).mean())
        sk = sl.LogisticRegression(C=1.0, max_iter=300).fit(X, y)
        assert acc >= sk.score(X, y) - 0.03

    def test_sharded_multiclass_single_dispatch(self, multiclass_data, mesh,
                                                monkeypatch):
        from dask_ml_tpu import solvers

        X, y = multiclass_data
        monkeypatch.setenv("DASK_ML_TPU_PACK", "packed")
        sX, sy = shard_rows(X), shard_rows(y.astype(np.float32))
        solvers.reset_dispatch_counts()
        lr = dlm.LogisticRegression(solver="lbfgs", C=1.0, max_iter=150).fit(
            sX, sy
        )
        assert solvers.DISPATCH_COUNTS["solves"] == 1
        assert float((lr.predict(sX)[: len(y)] == y).mean()) > 0.8

    def test_packed_matches_sequential_loop(self, multiclass_data, mesh,
                                            monkeypatch):
        # the packed program must agree with K independent solves
        monkeypatch.setenv("DASK_ML_TPU_PACK", "packed")
        from dask_ml_tpu.solvers import Logistic, lbfgs, packed_solve
        from dask_ml_tpu.core import shard_rows as _sr

        X, y = multiclass_data
        sX = _sr(X)
        n_pad = sX.data.shape[0]
        classes = np.unique(y)
        Y = np.zeros((len(classes), n_pad), np.float32)
        for i, c in enumerate(classes):
            Y[i, : len(y)] = (y == c)
        betas, n_its = packed_solve(
            "lbfgs", sX, Y, family=Logistic, lamduh=1.0, max_iter=150,
        )
        for i, c in enumerate(classes):
            b, n_it = lbfgs(
                sX, Y[i], family=Logistic, lamduh=1.0, max_iter=150,
                return_n_iter=True,
            )
            # loose rtol: the batched (vmapped) gemm accumulates in a
            # different order than K independent gemms, and converged
            # lanes hold their carry while stragglers iterate
            np.testing.assert_allclose(
                np.asarray(betas[i]), np.asarray(b), rtol=5e-3, atol=1e-3
            )


class TestMultinomial:
    def test_parity_with_sklearn(self, multiclass_data, mesh):
        X, y = multiclass_data
        ours = dlm.LogisticRegression(
            solver="lbfgs", C=1.0, max_iter=300, multi_class="multinomial"
        ).fit(X, y)
        sk = sl.LogisticRegression(C=1.0, max_iter=300).fit(X, y)
        p_ours = np.asarray(ours.predict_proba(X))
        p_sk = sk.predict_proba(X)
        assert np.abs(p_ours - p_sk).max() < 0.02
        # coefs agree in the sum-to-zero gauge (softmax is shift-invariant
        # per feature; sklearn's multinomial is centered the same way)
        np.testing.assert_allclose(
            np.asarray(ours.coef_) - np.asarray(ours.coef_).mean(0),
            sk.coef_ - sk.coef_.mean(0), atol=5e-2,
        )
        assert ours.n_iter_.shape == (1,)

    def test_binary_multinomial_uses_sigmoid_path(self, clf_data, mesh):
        X, y = clf_data
        lr = dlm.LogisticRegression(
            solver="lbfgs", multi_class="multinomial", max_iter=100
        ).fit(X, y)
        assert lr.coef_.ndim == 1  # binary contract unchanged
        assert float((lr.predict(X) == y).mean()) > 0.8

    def test_invalid_multi_class_raises(self, clf_data, mesh):
        X, y = clf_data
        with pytest.raises(ValueError, match="multi_class"):
            dlm.LogisticRegression(multi_class="bogus").fit(X, y)

    def test_multinomial_newton_rejected(self, multiclass_data, mesh):
        X, y = multiclass_data
        with pytest.raises(ValueError, match="newton"):
            dlm.LogisticRegression(
                solver="newton", multi_class="multinomial"
            ).fit(X, y)


class TestSampleClassWeights:
    """VERDICT r2 next #6: weights thread through the masked reductions."""

    def _imbalanced(self, rng, n=600, d=5, noisy=False):
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d)
        if noisy:
            p = 1 / (1 + np.exp(-(X @ w + 1.2)))
            return X, (rng.uniform(size=n) < p).astype(np.float32)
        return X, (X @ w + 1.2 > 0).astype(np.float32)  # skewed positive

    def test_logreg_balanced_parity_with_sklearn(self, rng, mesh):
        # noisy labels: a separable set makes the optimum ill-conditioned
        # and amplifies solver-tolerance differences
        X, y = self._imbalanced(rng, noisy=True)
        ours = dlm.LogisticRegression(
            solver="lbfgs", C=1.0, max_iter=500, tol=1e-8,
            class_weight="balanced",
        ).fit(X, y)
        sk = sl.LogisticRegression(
            C=1.0, max_iter=500, tol=1e-8, class_weight="balanced"
        ).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(ours.coef_), sk.coef_[0], rtol=5e-2, atol=2e-2
        )
        np.testing.assert_allclose(
            float(ours.intercept_), sk.intercept_[0], rtol=5e-2, atol=2e-2
        )

    def test_logreg_integer_weights_equal_duplication(self, rng, mesh):
        X, y = self._imbalanced(rng, n=200)
        sw = rng.randint(1, 4, size=200)
        Xd = np.repeat(X, sw, axis=0)
        yd = np.repeat(y, sw)
        a = dlm.LogisticRegression(solver="lbfgs", C=1.0, max_iter=300).fit(
            X, y, sample_weight=sw
        )
        b = dlm.LogisticRegression(solver="lbfgs", C=1.0, max_iter=300).fit(
            Xd, yd
        )
        np.testing.assert_allclose(
            np.asarray(a.coef_), np.asarray(b.coef_), rtol=1e-3, atol=1e-4
        )

    def test_logreg_class_weight_dict_shifts_boundary(self, rng, mesh):
        X, y = self._imbalanced(rng)
        plain = dlm.LogisticRegression(solver="lbfgs", max_iter=200).fit(X, y)
        up = dlm.LogisticRegression(
            solver="lbfgs", max_iter=200, class_weight={0.0: 10.0, 1.0: 1.0}
        ).fit(X, y)
        # upweighting the minority class must increase its recall
        minority_recall = lambda m: float(  # noqa: E731
            ((np.asarray(m.predict(X)) == 0) & (y == 0)).sum()
        ) / max((y == 0).sum(), 1)
        assert minority_recall(up) >= minority_recall(plain)

    def test_linear_regression_sample_weight(self, rng, mesh):
        n, d = 200, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X @ rng.normal(size=d)).astype(np.float32)
        sw = rng.randint(1, 4, size=n)
        a = dlm.LinearRegression(solver="lbfgs", max_iter=300).fit(
            X, y, sample_weight=sw
        )
        b = dlm.LinearRegression(solver="lbfgs", max_iter=300).fit(
            np.repeat(X, sw, axis=0), np.repeat(y, sw)
        )
        np.testing.assert_allclose(
            np.asarray(a.coef_), np.asarray(b.coef_), rtol=1e-3, atol=1e-3
        )

    def test_string_labels_with_sample_weight(self, rng, mesh):
        # host string labels must survive the weighted path (no device cast)
        n, d = 200, 4
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = np.where(X[:, 0] > 0, "dog", "cat")
        sw = rng.rand(n).astype(np.float32) + 0.5
        lr = dlm.LogisticRegression(
            solver="lbfgs", max_iter=100, class_weight="balanced"
        ).fit(X, y, sample_weight=sw)
        assert set(np.asarray(lr.predict(X)).tolist()) <= {"cat", "dog"}

    def test_sgd_regressor_rejects_short_sample_weight(self, rng, mesh):
        from dask_ml_tpu.linear_model import SGDRegressor

        X = rng.normal(size=(100, 4)).astype(np.float32)
        y = X[:, 0].astype(np.float32)
        with pytest.raises(ValueError, match="sample_weight"):
            SGDRegressor(max_iter=5).fit(X, y, sample_weight=np.ones(50))


class TestBinaryMultinomialPenalty:
    def test_binary_multinomial_equals_sigmoid_at_double_C(self, clf_data, mesh):
        # 2-class softmax == sigmoid at half the penalty (w0 = -w1 splits
        # the norm): the multinomial path must solve at lamduh/2
        X, y = clf_data
        mn = dlm.LogisticRegression(
            solver="lbfgs", C=1.0, max_iter=300, tol=1e-8,
            multi_class="multinomial",
        ).fit(X, y)
        sig2c = dlm.LogisticRegression(
            solver="lbfgs", C=2.0, max_iter=300, tol=1e-8,
        ).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(mn.coef_), np.asarray(sig2c.coef_),
            rtol=1e-3, atol=1e-4,
        )


    def test_binary_multinomial_l1_matches_full_penalty_sigmoid(self, clf_data, mesh):
        # L1: the split-pair penalty minimizes to |w1-w0| in the optimal
        # gauge, so the true binary softmax L1 fit equals the sigmoid fit
        # at FULL lamduh (NOT half, which is the L2-only scaling)
        X, y = clf_data
        mn = dlm.LogisticRegression(
            multi_class="multinomial", penalty="l1",
            solver="proximal_grad", C=0.05, max_iter=500, tol=1e-9,
        ).fit(X, y)
        sig = dlm.LogisticRegression(
            penalty="l1", solver="proximal_grad", C=0.05, max_iter=500,
            tol=1e-9,
        ).fit(X, y)
        assert np.asarray(mn.coef_).shape == np.asarray(sig.coef_).shape
        np.testing.assert_allclose(
            np.asarray(mn.coef_), np.asarray(sig.coef_), atol=3e-2
        )


class TestClassWeightPackingRules:
    def test_class_weight_packing_rules(self, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier as TpuSGD
        from dask_ml_tpu.model_selection._packing import pack_key

        assert pack_key(TpuSGD()) is not None
        # dict class weights pack (per-model stacked masks carry them);
        # 'balanced' stays unpackable — it needs the full label
        # distribution, which the block-streaming plane cannot give
        assert pack_key(TpuSGD(class_weight={0.0: 2.0})) is not None
        assert pack_key(TpuSGD(class_weight="balanced")) is None


class TestDeviceScore:
    def test_glm_device_score_matches_host(self, rng, mesh):
        n, d = 501, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        lr = dlm.LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
        dev = lr.score(shard_rows(X), shard_rows(y))
        host = lr.score(X, y)
        assert dev == pytest.approx(host, abs=1e-6)

    def test_glm_device_score_multiclass(self, rng, mesh):
        X = rng.normal(size=(600, 5)).astype(np.float32)
        W = rng.normal(size=(5, 3))
        y = (X @ W).argmax(1).astype(np.float32)
        lr = dlm.LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
        assert lr.score(shard_rows(X), shard_rows(y)) == pytest.approx(
            lr.score(X, y), abs=1e-6
        )


class TestClassWeightValidation:
    def test_unknown_dict_key_raises(self, clf_data, mesh):
        X, y = clf_data
        with pytest.raises(ValueError, match="class_weight keys"):
            dlm.LogisticRegression(
                solver="lbfgs", max_iter=10, class_weight={7.0: 2.0}
            ).fit(X, y)

    def test_sgd_unknown_dict_key_raises(self, clf_data, mesh):
        from dask_ml_tpu.linear_model import SGDClassifier

        X, y = clf_data
        with pytest.raises(ValueError, match="class_weight keys"):
            SGDClassifier(max_iter=5, class_weight={"dog": 2.0}).fit(X, y)


class TestPackStrategy:
    """DASK_ML_TPU_PACK auto-fallback (r3 verdict #3): the OvR execution
    strategy follows the measured per-platform winner and both forms
    agree numerically."""

    def test_auto_is_sequential_on_cpu(self):
        from dask_ml_tpu.solvers import pack_strategy

        assert pack_strategy() == "sequential"  # measured: fixed-work
        # pack loses on CPU (0.84x, packed_ovr_fixedwork; vmap
        # serializes the lanes)

    def test_auto_is_packed_on_tpu(self, monkeypatch):
        # pins the TPU branch (clean fixed-work chip wins at every
        # measured K: 1.6x@4 .. 7.6x@64 — pack_strategy docstring)
        # without TPU hardware: the policy reads jax.default_backend()
        # at call time
        import dask_ml_tpu.solvers.algorithms as algos

        monkeypatch.delenv("DASK_ML_TPU_PACK", raising=False)
        monkeypatch.setattr(algos.jax, "default_backend", lambda: "tpu")
        for k in (None, 4, 16, 64):
            assert algos.pack_strategy(k) == "packed"
        monkeypatch.setenv("DASK_ML_TPU_PACK", "sequential")
        assert algos.pack_strategy(16) == "sequential"  # env force wins


class TestDeviceIngest:
    """Raw jax.Array inputs stay on device end to end (the r5 ingest
    round-trip fix): wrapping is a device-side reshard and label
    discovery fetches only the K unique values."""

    def test_raw_device_labels_full_estimator(self, mesh, rng):
        # raw jnp X AND y through the estimator: classes discovered on
        # device (only K scalars cross), OvR and multinomial both solve
        import jax.numpy as _jnp

        from dask_ml_tpu.linear_model import LogisticRegression

        X = _jnp.asarray(rng.normal(size=(300, 8)).astype(np.float32))
        w = rng.normal(size=8)
        y = _jnp.asarray(
            np.digitize(np.asarray(X) @ w, [-0.5, 0.5]).astype(np.float32))
        for mc in ("ovr", "multinomial"):
            lr = LogisticRegression(solver="lbfgs", C=10.0, max_iter=60,
                                    multi_class=mc).fit(X, y)
            assert set(np.asarray(lr.classes_)) == {0.0, 1.0, 2.0}
            acc = (np.asarray(lr.predict(X)) == np.asarray(y)).mean()
            assert acc > 0.8, (mc, acc)

    def test_device_input_stays_on_device(self, monkeypatch, mesh, rng):
        # the r5 round-trip bug: shard_rows/_prep must never fetch a
        # device-resident input back to host (np.asarray on a jax.Array
        # is a device->host transfer; on a relay-attached chip that is
        # ~2x the array's transfer time PER SOLVER CALL)
        import jax as _jax
        import jax.numpy as _jnp

        import dask_ml_tpu.core.sharded as sharded_mod
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.solvers import Logistic, lbfgs

        Xd = _jnp.asarray(rng.normal(size=(64, 5)).astype(np.float32))
        yd = (Xd[:, 0] > 0).astype(_jnp.float32)

        real_asarray = np.asarray

        def guarded(a, *args, **kw):
            assert not isinstance(a, _jax.Array), (
                "np.asarray called on a device array inside the ingest "
                "path — device->host round trip")
            return real_asarray(a, *args, **kw)

        monkeypatch.setattr(sharded_mod.np, "asarray", guarded)
        sX = shard_rows(Xd)
        assert sX.n_samples == 64
        monkeypatch.undo()
        # end-to-end: device X and device y through the solver wrapper
        b = lbfgs(Xd, yd, family=Logistic, lamduh=0.1, max_iter=20)
        assert np.isfinite(np.asarray(b)).all()

    def test_bad_env_rejected(self, monkeypatch):
        from dask_ml_tpu.solvers import pack_strategy

        monkeypatch.setenv("DASK_ML_TPU_PACK", "vectorised")
        import pytest as _pytest

        with _pytest.raises(ValueError, match="DASK_ML_TPU_PACK"):
            pack_strategy()

    def test_sequential_matches_packed(self, multiclass_data, mesh,
                                       monkeypatch):
        from dask_ml_tpu import solvers

        X, y = multiclass_data
        outs = {}
        for strat in ("packed", "sequential"):
            monkeypatch.setenv("DASK_ML_TPU_PACK", strat)
            solvers.reset_dispatch_counts()
            lr = dlm.LogisticRegression(
                solver="lbfgs", C=1.0, max_iter=150).fit(X, y)
            outs[strat] = (np.asarray(lr.betas_),
                           solvers.DISPATCH_COUNTS["solves"])
        # tolerance = the stagnation-exit noise floor: both arms stop
        # when the fp32 objective can no longer certify progress
        # (lbfgs_core round-5 exit), and lane-vs-loop accumulation order
        # differs inside that certified band — observed 2.1e-3 on a
        # near-zero coefficient at 7 devices, identical predictions
        np.testing.assert_allclose(outs["packed"][0],
                                   outs["sequential"][0],
                                   rtol=5e-3, atol=5e-3)
        assert outs["packed"][1] == 1
        assert outs["sequential"][1] == len(np.unique(y))

import numpy as np
import pytest
import sklearn.metrics as sm
import sklearn.metrics.pairwise as smp

import dask_ml_tpu.metrics as dmm
from dask_ml_tpu.core import shard_rows


@pytest.fixture
def XY(rng):
    X = rng.normal(size=(33, 6)).astype(np.float32)
    Y = rng.normal(size=(7, 6)).astype(np.float32)
    return X, Y


class TestPairwise:
    def test_euclidean_parity(self, XY):
        X, Y = XY
        got = np.asarray(dmm.euclidean_distances(X, Y))
        np.testing.assert_allclose(got, smp.euclidean_distances(X, Y), atol=1e-4)

    def test_euclidean_sharded_rows(self, XY):
        X, Y = XY
        s = shard_rows(X)
        got = np.asarray(dmm.euclidean_distances(s, Y))[: s.n_samples]
        np.testing.assert_allclose(got, smp.euclidean_distances(X, Y), atol=1e-4)

    def test_argmin_min(self, XY):
        X, Y = XY
        idx, dist = dmm.pairwise_distances_argmin_min(X, Y)
        eidx, edist = smp.pairwise_distances_argmin_min(X, Y)
        np.testing.assert_array_equal(np.asarray(idx), eidx)
        np.testing.assert_allclose(np.asarray(dist), edist, atol=1e-4)

    @pytest.mark.parametrize("name", ["linear", "polynomial", "rbf", "sigmoid"])
    def test_kernels_parity(self, XY, name):
        X, Y = XY
        ours = dmm.PAIRWISE_KERNEL_FUNCTIONS[name]
        theirs = {
            "linear": smp.linear_kernel,
            "polynomial": smp.polynomial_kernel,
            "rbf": smp.rbf_kernel,
            "sigmoid": smp.sigmoid_kernel,
        }[name]
        np.testing.assert_allclose(
            np.asarray(ours(X, Y)), theirs(X, Y), atol=1e-4, rtol=1e-4
        )

    def test_cosine_metric(self, XY):
        X, Y = XY
        got = np.asarray(dmm.pairwise_distances(X, Y, metric="cosine"))
        np.testing.assert_allclose(got, smp.cosine_distances(X, Y), atol=1e-4)

    def test_bad_metric_raises(self, XY):
        with pytest.raises(ValueError, match="Unsupported metric"):
            dmm.pairwise_distances(*XY, metric="mahalanobis")


class TestClassification:
    def test_accuracy_parity(self, rng):
        y = rng.randint(0, 2, size=51)
        p = rng.randint(0, 2, size=51)
        assert dmm.accuracy_score(y, p) == pytest.approx(sm.accuracy_score(y, p))

    def test_accuracy_unnormalized(self, rng):
        y = rng.randint(0, 2, size=51)
        p = rng.randint(0, 2, size=51)
        assert dmm.accuracy_score(y, p, normalize=False) == pytest.approx(
            sm.accuracy_score(y, p, normalize=False)
        )

    def test_accuracy_sharded_mask_excludes_padding(self, rng):
        y = rng.randint(0, 2, size=51)
        p = y.copy()
        s_y, s_p = shard_rows(y), shard_rows(p)
        assert dmm.accuracy_score(s_y, s_p) == pytest.approx(1.0)

    def test_accuracy_sample_weight(self, rng):
        y = rng.randint(0, 2, size=40)
        p = rng.randint(0, 2, size=40)
        w = rng.uniform(size=40)
        assert dmm.accuracy_score(y, p, sample_weight=w) == pytest.approx(
            sm.accuracy_score(y, p, sample_weight=w), abs=1e-6
        )

    def test_log_loss_binary_proba_matrix(self, rng):
        y = rng.randint(0, 2, size=60)
        proba = rng.uniform(0.01, 0.99, size=(60, 2)).astype(np.float64)
        proba /= proba.sum(1, keepdims=True)
        assert dmm.log_loss(y, proba) == pytest.approx(sm.log_loss(y, proba), rel=1e-5)

    def test_log_loss_multiclass(self, rng):
        y = rng.randint(0, 3, size=60)
        proba = rng.uniform(0.01, 0.99, size=(60, 3)).astype(np.float64)
        proba /= proba.sum(1, keepdims=True)
        assert dmm.log_loss(y, proba) == pytest.approx(sm.log_loss(y, proba), rel=1e-5)

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError, match="different lengths"):
            dmm.accuracy_score(np.ones(5), np.ones(6))


class TestRegression:
    @pytest.mark.parametrize(
        "ours,theirs",
        [
            (dmm.mean_squared_error, sm.mean_squared_error),
            (dmm.mean_absolute_error, sm.mean_absolute_error),
            (dmm.r2_score, sm.r2_score),
        ],
    )
    def test_parity(self, rng, ours, theirs):
        y = rng.normal(size=45).astype(np.float64)
        p = y + 0.3 * rng.normal(size=45)
        assert ours(y, p) == pytest.approx(theirs(y, p), rel=1e-4)

    def test_msle_parity(self, rng):
        y = rng.uniform(0.1, 5.0, size=45)
        p = rng.uniform(0.1, 5.0, size=45)
        assert dmm.mean_squared_log_error(y, p) == pytest.approx(
            sm.mean_squared_log_error(y, p), rel=1e-4
        )

    def test_rmse(self, rng):
        y = rng.normal(size=45)
        p = y + 0.3 * rng.normal(size=45)
        assert dmm.mean_squared_error(y, p, squared=False) == pytest.approx(
            np.sqrt(sm.mean_squared_error(y, p)), rel=1e-4
        )

    def test_sample_weight(self, rng):
        y = rng.normal(size=45)
        p = y + 0.3 * rng.normal(size=45)
        w = rng.uniform(size=45)
        assert dmm.mean_squared_error(y, p, sample_weight=w) == pytest.approx(
            sm.mean_squared_error(y, p, sample_weight=w), rel=1e-4
        )


class TestScorer:
    def test_get_scorer_known(self):
        assert callable(dmm.get_scorer("accuracy"))

    def test_get_scorer_unknown(self):
        with pytest.raises(ValueError, match="not a valid scoring"):
            dmm.get_scorer("nope")

    def test_scorer_applies_sign(self, rng):
        class Dummy:
            def predict(self, X):
                return np.zeros(len(X))

        y = np.ones(10)
        score = dmm.SCORERS["neg_mean_squared_error"](Dummy(), np.zeros((10, 2)), y)
        assert score == pytest.approx(-1.0)


class TestReviewRegressions:
    """Cases from code review: mixed sharded/plain inputs, constant y, labels=."""

    def test_mixed_sharded_plain_accuracy(self, rng):
        y = rng.randint(0, 2, size=33)
        s = shard_rows(y)
        assert dmm.accuracy_score(y, s) == pytest.approx(1.0)
        assert dmm.accuracy_score(s, y) == pytest.approx(1.0)

    def test_sharded_weights_plain_y(self, rng):
        y = rng.randint(0, 2, size=33)
        p = rng.randint(0, 2, size=33)
        w = rng.uniform(size=33)
        import sklearn.metrics as sm
        assert dmm.accuracy_score(y, p, sample_weight=shard_rows(w)) == pytest.approx(
            sm.accuracy_score(y, p, sample_weight=w), abs=1e-6
        )

    def test_r2_constant_y(self):
        assert dmm.r2_score(np.ones(10), np.zeros(10)) == 0.0
        assert dmm.r2_score(np.ones(10), np.ones(10)) == 1.0

    def test_log_loss_unseen_label_raises(self, rng):
        proba = np.full((3, 2), 0.5)
        with pytest.raises(ValueError, match="not in `labels`"):
            dmm.log_loss(np.array([0, 1, 5]), proba, labels=[0, 1])

    def test_pairwise_sharded_output_unpadded(self, rng):
        X = rng.normal(size=(33, 4)).astype(np.float32)
        s = shard_rows(X)
        D = dmm.euclidean_distances(s)
        assert D.shape == (33, 33)
        K = dmm.rbf_kernel(s)
        assert K.shape == (33, 33)
        idx, dist = dmm.pairwise_distances_argmin_min(s, X[:5])
        assert idx.shape == (33,)


class TestRingPairwise:
    """Sharded x sharded pairwise via the ppermute ring (VERDICT round-1
    item 7; SURVEY.md §5: structurally ring attention's outer loop)."""

    def _xy(self, rng, n=101, m=53, d=5):
        X = rng.normal(size=(n, d)).astype(np.float32)
        Y = rng.normal(size=(m, d)).astype(np.float32)
        return X, Y

    def test_euclidean_ring_matches_replicated(self, rng, mesh):
        from dask_ml_tpu.metrics.pairwise import euclidean_distances

        X, Y = self._xy(rng)
        ring = np.asarray(euclidean_distances(shard_rows(X), shard_rows(Y)))
        rep = np.asarray(euclidean_distances(shard_rows(X), Y))
        assert ring.shape == (101, 53)
        np.testing.assert_allclose(ring, rep, rtol=1e-4, atol=1e-4)

    def test_sq_and_cosine_and_kernels(self, rng, mesh):
        from dask_ml_tpu.metrics.pairwise import (
            euclidean_distances,
            linear_kernel,
            pairwise_distances,
            polynomial_kernel,
            rbf_kernel,
        )

        X, Y = self._xy(rng, n=64, m=40)
        Xs, Ys = shard_rows(X), shard_rows(Y)
        for ring, rep in [
            (euclidean_distances(Xs, Ys, squared=True),
             euclidean_distances(Xs, Y, squared=True)),
            (pairwise_distances(Xs, Ys, metric="cosine"),
             pairwise_distances(Xs, Y, metric="cosine")),
            (rbf_kernel(Xs, Ys, gamma=0.7), rbf_kernel(Xs, Y, gamma=0.7)),
            (linear_kernel(Xs, Ys), linear_kernel(Xs, Y)),
            (polynomial_kernel(Xs, Ys, degree=2), polynomial_kernel(Xs, Y, degree=2)),
        ]:
            np.testing.assert_allclose(
                np.asarray(ring), np.asarray(rep), rtol=1e-4, atol=1e-4
            )

    def test_near_duplicate_rows_no_cancellation(self, rng, mesh):
        # Regression: the ‖x‖²+‖y‖²−2x·y expansion loses ~all precision
        # when rows are near-duplicates (true distance 1e-6 came out
        # 7e-4, r3 verdict weak #1).  The safe path must recompute those
        # entries with the exact (x−y)² form.
        from sklearn.metrics.pairwise import euclidean_distances as sk_euc
        from sklearn.metrics.pairwise import rbf_kernel as sk_rbf

        from dask_ml_tpu.metrics.pairwise import (
            euclidean_distances,
            rbf_kernel,
        )

        base = rng.normal(size=(37, 6)).astype(np.float32)
        X = base
        # Y rows are X rows nudged by ~1e-6 — deep in cancellation land
        Y = (base[:29] + 1e-6 * rng.normal(size=(29, 6))).astype(np.float32)
        ours = np.asarray(euclidean_distances(shard_rows(X), shard_rows(Y)))
        ref = sk_euc(X, Y)
        np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-5)
        # rbf with a sharp gamma: affinity between near-duplicates must
        # be ~1, not exp(-gamma * (cancellation noise))
        g = 1e6
        ours_k = np.asarray(rbf_kernel(shard_rows(X), shard_rows(Y), gamma=g))
        ref_k = sk_rbf(X.astype(np.float64), Y.astype(np.float64), gamma=g)
        np.testing.assert_allclose(ours_k, ref_k, atol=1e-3)
        # replicated (non-ring) paths too
        ours2 = np.asarray(euclidean_distances(shard_rows(X), Y))
        np.testing.assert_allclose(ours2, ref, rtol=1e-3, atol=1e-5)
        ours_k2 = np.asarray(rbf_kernel(shard_rows(X), Y, gamma=g))
        np.testing.assert_allclose(ours_k2, ref_k, atol=1e-3)
        # Y=None self path: diagonal exactly 0, off-diagonal still safe
        ours_self = np.asarray(euclidean_distances(shard_rows(X)))
        np.testing.assert_allclose(np.diag(ours_self), 0.0)
        np.testing.assert_allclose(ours_self, sk_euc(X, X),
                                   rtol=1e-3, atol=1e-5)
        # zero-row operand must trace and return an empty result
        empty = np.zeros((0, 6), dtype=np.float32)
        assert euclidean_distances(shard_rows(X), empty).shape == (37, 0)
        # X-vs-X self RING (same ShardedRows object twice): global
        # diagonal exactly 0 even though blocks meet off-device
        Xs = shard_rows(X)
        ours_ring = np.asarray(euclidean_distances(Xs, Xs))
        np.testing.assert_allclose(np.diag(ours_ring), 0.0)
        np.testing.assert_allclose(ours_ring, sk_euc(X, X),
                                   rtol=1e-3, atol=1e-5)
        k_ring = np.asarray(rbf_kernel(Xs, Xs, gamma=g))
        np.testing.assert_allclose(np.diag(k_ring), 1.0)
        np.testing.assert_allclose(
            k_ring, sk_rbf(X.astype(np.float64), X.astype(np.float64),
                           gamma=g), atol=1e-3)

    def test_ring_result_row_sharded(self, rng, mesh):
        from dask_ml_tpu.core.mesh import DATA_AXIS
        from dask_ml_tpu.metrics.pairwise import _ring_impl, _sq_euclidean
        from dask_ml_tpu.core.mesh import MeshHolder, get_mesh

        X, Y = self._xy(rng, n=64, m=32)
        Xs, Ys = shard_rows(X), shard_rows(Y)
        out = _ring_impl(
            Xs.data, Ys.data, mesh_holder=MeshHolder(get_mesh()),
            fn=_sq_euclidean,
        )
        from conftest import spec_axis

        # never replicated
        assert spec_axis(out.sharding.spec[0]) == DATA_AXIS

    def test_uneven_rows(self, rng, mesh):
        # both operands need pad+mask handling (neither divisible by 8)
        from dask_ml_tpu.metrics.pairwise import euclidean_distances

        X, Y = self._xy(rng, n=13, m=11)
        ring = np.asarray(euclidean_distances(shard_rows(X), shard_rows(Y)))
        from sklearn.metrics.pairwise import euclidean_distances as sk_euc

        np.testing.assert_allclose(ring, sk_euc(X, Y), rtol=1e-4, atol=1e-4)


class TestManhattan:
    def test_matches_sklearn(self, rng, mesh):
        from sklearn.metrics import pairwise_distances as sk_pd

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.metrics import pairwise_distances

        X = rng.normal(size=(101, 7)).astype(np.float32)
        Y = rng.normal(size=(23, 7)).astype(np.float32)
        for name in ("manhattan", "cityblock", "l1"):
            D = np.asarray(pairwise_distances(shard_rows(X), Y, metric=name))
            np.testing.assert_allclose(
                D, sk_pd(X, Y, metric="manhattan"), rtol=1e-4, atol=1e-4
            )

    def test_sharded_x_sharded_rides_ring(self, rng, mesh):
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.metrics import pairwise_distances

        X = rng.normal(size=(64, 5)).astype(np.float32)
        Y = rng.normal(size=(40, 5)).astype(np.float32)
        D = np.asarray(pairwise_distances(shard_rows(X), shard_rows(Y),
                                          metric="manhattan"))
        ref = np.abs(X[:, None, :] - Y[None, :, :]).sum(-1)
        np.testing.assert_allclose(D, ref, rtol=1e-4, atol=1e-4)


class TestPrecisionRecallF1:
    def _data(self, rng, k=2):
        t = rng.randint(0, k, size=403)
        p = t.copy()
        flip = rng.rand(403) < 0.3
        p[flip] = rng.randint(0, k, size=flip.sum())
        return t, p

    @pytest.mark.parametrize("average", ["binary", "macro", "micro", "weighted"])
    def test_binary_parity(self, rng, mesh, average):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm
        from dask_ml_tpu.core import shard_rows

        t, p = self._data(rng, 2)
        for fn, name in ((dm.precision_score, "precision_score"),
                         (dm.recall_score, "recall_score"),
                         (dm.f1_score, "f1_score")):
            ours = fn(shard_rows(t.astype(np.float32)),
                      shard_rows(p.astype(np.float32)), average=average)
            theirs = getattr(skm, name)(t, p, average=average)
            assert ours == pytest.approx(theirs, abs=1e-6), (name, average)

    @pytest.mark.parametrize("average", ["macro", "micro", "weighted"])
    def test_multiclass_parity(self, rng, mesh, average):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t, p = self._data(rng, 4)
        assert dm.f1_score(t, p, average=average) == pytest.approx(
            skm.f1_score(t, p, average=average), abs=1e-6)
        assert dm.precision_score(t, p, average=average) == pytest.approx(
            skm.precision_score(t, p, average=average), abs=1e-6)

    def test_per_class_and_weights(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t, p = self._data(rng, 3)
        w = rng.rand(403)
        np.testing.assert_allclose(
            dm.recall_score(t, p, average=None, sample_weight=w),
            skm.recall_score(t, p, average=None, sample_weight=w),
            atol=1e-6,
        )

    def test_scorer_registry(self, rng, mesh):
        from dask_ml_tpu.metrics import get_scorer

        for name in ("f1", "f1_macro", "precision", "recall_macro"):
            assert callable(get_scorer(name))

    def test_binary_average_rejects_multiclass(self, rng, mesh):
        from dask_ml_tpu import metrics as dm

        t, p = self._data(rng, 3)
        with pytest.raises(ValueError, match="multiclass"):
            dm.f1_score(t, p)  # default average='binary'

    def test_absent_pos_label_scores_zero_with_warning(self, mesh):
        from sklearn.exceptions import UndefinedMetricWarning

        from dask_ml_tpu import metrics as dm

        with pytest.warns(UndefinedMetricWarning):
            assert dm.precision_score([0, 0, 0], [0, 0, 0]) == 0.0

    def test_labels_order_preserved(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t, p = self._data(rng, 3)
        order = [2, 0, 1]
        np.testing.assert_allclose(
            dm.recall_score(t, p, average=None, labels=order),
            skm.recall_score(t, p, average=None, labels=order),
            atol=1e-6,
        )


class TestRocAuc:
    def test_parity_with_sklearn(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm
        from dask_ml_tpu.core import shard_rows

        t = rng.randint(0, 2, size=501)
        s = rng.normal(size=501).astype(np.float32) + t  # informative
        ours = dm.roc_auc_score(shard_rows(t.astype(np.float32)),
                                shard_rows(s))
        assert ours == pytest.approx(skm.roc_auc_score(t, s), abs=1e-6)

    def test_ties_and_weights(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = rng.randint(0, 2, size=400)
        s = np.round(rng.normal(size=400) + t, 1)  # heavy ties
        w = rng.rand(400)
        assert dm.roc_auc_score(t, s, sample_weight=w) == pytest.approx(
            skm.roc_auc_score(t, s, sample_weight=w), abs=1e-6)

    def test_single_class_raises(self, mesh):
        from dask_ml_tpu import metrics as dm

        with pytest.raises(ValueError, match="2 classes"):
            dm.roc_auc_score([1, 1, 1], [0.1, 0.2, 0.3])

    def test_scorer_uses_decision_function(self, rng, mesh):
        from sklearn.linear_model import LogisticRegression as SKLR

        from dask_ml_tpu.metrics import get_scorer

        X = rng.normal(size=(200, 4)); y = (X[:, 0] > 0).astype(int)
        est = SKLR().fit(X, y)
        auc = get_scorer("roc_auc")(est, X, y)
        assert 0.9 < auc <= 1.0


class TestConfusionMatrix:
    def test_parity_with_sklearn(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm
        from dask_ml_tpu.core import shard_rows

        t = rng.randint(0, 4, size=333)
        p = rng.randint(0, 4, size=333)
        ours = dm.confusion_matrix(shard_rows(t.astype(np.float32)),
                                   shard_rows(p.astype(np.float32)))
        np.testing.assert_array_equal(ours, skm.confusion_matrix(t, p))
        assert ours.dtype == np.int64

    @pytest.mark.parametrize("normalize", ["true", "pred", "all"])
    def test_normalized(self, rng, mesh, normalize):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = rng.randint(0, 3, size=200)
        p = rng.randint(0, 3, size=200)
        np.testing.assert_allclose(
            dm.confusion_matrix(t, p, normalize=normalize),
            skm.confusion_matrix(t, p, normalize=normalize), atol=1e-6)

    def test_weighted_and_labels(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = rng.randint(0, 3, size=150)
        p = rng.randint(0, 3, size=150)
        w = rng.rand(150)
        np.testing.assert_allclose(
            dm.confusion_matrix(t, p, labels=[2, 1, 0], sample_weight=w),
            skm.confusion_matrix(t, p, labels=[2, 1, 0], sample_weight=w),
            atol=1e-5)

    def test_balanced_accuracy(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = rng.randint(0, 3, size=300)
        p = rng.randint(0, 3, size=300)
        assert dm.balanced_accuracy_score(t, p) == pytest.approx(
            skm.balanced_accuracy_score(t, p), abs=1e-6)

    def test_balanced_accuracy_predicted_only_class(self, mesh):
        """A class appearing only in y_pred must not drag the average
        (sklearn drops true-absent classes)."""
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = [0, 0, 1]
        p = [0, 0, 2]
        assert dm.balanced_accuracy_score(t, p) == pytest.approx(
            skm.balanced_accuracy_score(t, p))

    def test_balanced_accuracy_adjusted(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = rng.randint(0, 3, size=200)
        p = rng.randint(0, 3, size=200)
        assert dm.balanced_accuracy_score(t, p, adjusted=True) == pytest.approx(
            skm.balanced_accuracy_score(t, p, adjusted=True), abs=1e-6)

    def test_normalized_absent_class_zero_filled(self, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        ours = dm.confusion_matrix([0, 1], [0, 1], labels=[0, 1, 2],
                                   normalize="true")
        theirs = skm.confusion_matrix([0, 1], [0, 1], labels=[0, 1, 2],
                                      normalize="true")
        # sklearn zero-fills the absent class rows (nan_to_num)
        np.testing.assert_allclose(ours, theirs)


class TestExtraRegressionMetrics:
    def test_parity_with_sklearn(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm
        from dask_ml_tpu.core import shard_rows

        t = rng.normal(size=501).astype(np.float32) + 3.0
        p = t + 0.3 * rng.normal(size=501).astype(np.float32)
        w = rng.rand(501)
        st, sp = shard_rows(t), shard_rows(p)
        assert dm.mean_absolute_percentage_error(st, sp, sample_weight=w) == \
            pytest.approx(skm.mean_absolute_percentage_error(t, p, sample_weight=w), rel=1e-5)
        assert dm.median_absolute_error(st, sp) == pytest.approx(
            skm.median_absolute_error(t, p), rel=1e-5)
        assert dm.explained_variance_score(st, sp, sample_weight=w) == \
            pytest.approx(skm.explained_variance_score(t, p, sample_weight=w), rel=1e-4)

    def test_median_even_and_odd(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        for n in (10, 11):
            t = rng.normal(size=n).astype(np.float32)
            p = rng.normal(size=n).astype(np.float32)
            assert dm.median_absolute_error(t, p) == pytest.approx(
                skm.median_absolute_error(t, p), rel=1e-5)

    def test_constant_target_explained_variance(self, mesh):
        from dask_ml_tpu import metrics as dm

        assert dm.explained_variance_score([2.0, 2.0], [2.0, 2.0]) == 1.0
        assert dm.explained_variance_score([2.0, 2.0], [1.0, 3.0]) == 0.0

    def test_mape_zero_target_matches_sklearn(self, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = np.array([0.0, 1.0], np.float32)
        p = np.array([0.5, 1.0], np.float32)
        ours = dm.mean_absolute_percentage_error(t, p)
        theirs = skm.mean_absolute_percentage_error(t, p)
        assert ours == pytest.approx(theirs, rel=1e-4)

    def test_multioutput_uniform_average(self, rng, mesh):
        import sklearn.metrics as skm

        from dask_ml_tpu import metrics as dm

        t = rng.normal(size=(60, 3)).astype(np.float32) + 4.0
        p = t + 0.2 * rng.normal(size=(60, 3)).astype(np.float32)
        for name in ("mean_absolute_percentage_error",
                     "median_absolute_error", "explained_variance_score"):
            assert getattr(dm, name)(t, p) == pytest.approx(
                getattr(skm, name)(t, p), rel=1e-4), name


class TestAdvisorRound2Fixes:
    """Pins for the round-2 advisor findings (ADVICE.md)."""

    def test_roc_auc_multiblock_prefix_matches_sklearn(self, rng, mesh, monkeypatch):
        # shrink the two-level prefix-sum block so a small input spans
        # many blocks — exercises the f64 block-base assembly end to end
        import sklearn.metrics as skm

        from dask_ml_tpu.metrics import classification as cl

        monkeypatch.setattr(cl, "_AUC_BLOCK", 64)
        t = rng.randint(0, 2, size=1000)
        s = np.round(rng.normal(size=1000) + t, 1)  # heavy ties
        w = rng.rand(1000)
        got = cl.roc_auc_score(t, s, sample_weight=w)
        assert got == pytest.approx(
            skm.roc_auc_score(t, s, sample_weight=w), abs=1e-6)
        # unweighted too
        assert cl.roc_auc_score(t, s) == pytest.approx(
            skm.roc_auc_score(t, s), abs=1e-6)

    def test_explicit_labels_with_absent_pos_label_raises(self, mesh):
        from dask_ml_tpu import metrics as dm

        with pytest.raises(ValueError, match="not a valid label"):
            dm.precision_score([0, 0, 1], [0, 1, 1], labels=[0, 1],
                               pos_label=2)

"""Parity tests for feature_extraction.text vs sklearn (SURVEY.md §4.1)."""

import numpy as np
import pytest
import scipy.sparse

import sklearn.feature_extraction.text as sk_text
from sklearn.feature_extraction import FeatureHasher as SkFeatureHasher

from dask_ml_tpu.feature_extraction import (
    CountVectorizer,
    FeatureHasher,
    HashingVectorizer,
    densify_to_device,
)

DOCS = [
    "the quick brown fox jumps over the lazy dog",
    "the cat sat on the mat",
    "foxes and dogs and cats",
    "jax compiles programs for the tpu",
    "the tpu multiplies matrices quickly",
    "sparse matrices stay on the host",
] * 7  # 42 docs; with chunk_size=5 this exercises multi-chunk paths


@pytest.fixture
def small_chunks(monkeypatch):
    monkeypatch.setattr(HashingVectorizer, "chunk_size", 5)
    monkeypatch.setattr(CountVectorizer, "chunk_size", 5)
    monkeypatch.setattr(FeatureHasher, "chunk_size", 5)


class TestHashingVectorizer:
    def test_matches_sklearn(self, small_chunks):
        ours = HashingVectorizer(n_features=128).fit_transform(DOCS)
        theirs = sk_text.HashingVectorizer(n_features=128).transform(DOCS)
        assert scipy.sparse.issparse(ours)
        np.testing.assert_allclose(ours.toarray(), theirs.toarray())

    def test_params_forward(self):
        v = HashingVectorizer(n_features=64, norm=None, alternate_sign=False)
        out = v.transform(DOCS[:3])
        ref = sk_text.HashingVectorizer(
            n_features=64, norm=None, alternate_sign=False
        ).transform(DOCS[:3])
        np.testing.assert_allclose(out.toarray(), ref.toarray())

    def test_empty_input(self):
        out = HashingVectorizer(n_features=32).transform([])
        assert out.shape == (0, 32)


class TestFeatureHasher:
    def test_matches_sklearn(self, small_chunks):
        samples = [{"a": 1, "b": 2}, {"b": 3, "c": 1}, {"d": 4}] * 6
        ours = FeatureHasher(n_features=64).transform(samples)
        theirs = SkFeatureHasher(n_features=64).transform(samples)
        np.testing.assert_allclose(ours.toarray(), theirs.toarray())


class TestCountVectorizer:
    def test_matches_sklearn(self, small_chunks):
        ours_vec = CountVectorizer()
        ours = ours_vec.fit_transform(DOCS)
        theirs_vec = sk_text.CountVectorizer()
        theirs = theirs_vec.fit_transform(DOCS)
        # identical sorted vocabulary → identical matrix
        assert ours_vec.vocabulary_ == theirs_vec.vocabulary_
        np.testing.assert_array_equal(ours.toarray(), theirs.toarray())

    def test_transform_after_fit(self, small_chunks):
        vec = CountVectorizer().fit(DOCS)
        out = vec.transform(DOCS[:4])
        ref = sk_text.CountVectorizer().fit(DOCS).transform(DOCS[:4])
        np.testing.assert_array_equal(out.toarray(), ref.toarray())

    def test_fixed_vocabulary(self):
        vocab = ["cat", "dog", "fox", "tpu"]
        vec = CountVectorizer(vocabulary=vocab)
        out = vec.fit_transform(DOCS)
        ref = sk_text.CountVectorizer(vocabulary=vocab).fit_transform(DOCS)
        np.testing.assert_array_equal(out.toarray(), ref.toarray())
        assert vec.fixed_vocabulary_

    def test_transform_empty_batch(self):
        vec = CountVectorizer().fit(DOCS)
        out = vec.transform([])
        assert out.shape == (0, len(vec.vocabulary_))

    def test_unfitted_raises(self):
        with pytest.raises(ValueError, match="not fitted"):
            CountVectorizer().transform(DOCS)

    def test_min_df_global_not_per_chunk(self, small_chunks):
        # 'rare' appears once in two different chunks: per-chunk df=1 would
        # drop it under min_df=2, but global df=2 keeps it (sklearn parity)
        docs = ["rare term here"] + ["common words"] * 6 + ["rare again"] + ["common words"] * 6
        ours_vec = CountVectorizer(min_df=2)
        theirs_vec = sk_text.CountVectorizer(min_df=2)
        ours = ours_vec.fit_transform(docs)
        theirs = theirs_vec.fit_transform(docs)
        assert ours_vec.vocabulary_ == theirs_vec.vocabulary_
        assert "rare" in ours_vec.vocabulary_
        np.testing.assert_array_equal(ours.toarray(), theirs.toarray())

    def test_max_df_and_max_features(self, small_chunks):
        ours_vec = CountVectorizer(max_df=0.8, max_features=5)
        theirs_vec = sk_text.CountVectorizer(max_df=0.8, max_features=5)
        ours = ours_vec.fit_transform(DOCS)
        theirs = theirs_vec.fit_transform(DOCS)
        assert ours_vec.vocabulary_ == theirs_vec.vocabulary_
        np.testing.assert_array_equal(ours.toarray(), theirs.toarray())

    def test_empty_chunk_tolerated(self, small_chunks):
        # one whole chunk of stop-word-only docs: global fit must survive
        docs = ["the a an of"] * 5 + ["real content here"] * 5
        vec = CountVectorizer(stop_words="english")
        out = vec.fit_transform(docs)
        ref = sk_text.CountVectorizer(stop_words="english").fit_transform(docs)
        np.testing.assert_array_equal(out.toarray(), ref.toarray())

    def test_all_stopwords_raises(self):
        with pytest.raises(ValueError, match="empty vocabulary"):
            CountVectorizer(stop_words="english").fit(["the a an", "of and"])

    def test_string_input_rejected(self):
        with pytest.raises(ValueError, match="string object received"):
            CountVectorizer().fit("a bare string")
        with pytest.raises(ValueError, match="string object received"):
            HashingVectorizer().transform("a bare string")

    def test_numpy_integer_min_df(self, small_chunks):
        ours = CountVectorizer(min_df=np.int64(2)).fit(DOCS)
        theirs = sk_text.CountVectorizer(min_df=2).fit(DOCS)
        assert ours.vocabulary_ == theirs.vocabulary_

    def test_invalid_param_propagates(self):
        with pytest.raises(ValueError, match="ngram_range"):
            CountVectorizer(ngram_range=(2, 1)).fit(DOCS)

    def test_fixed_vocab_transform_only(self):
        vec = CountVectorizer(vocabulary=["cat", "dog"])
        vec.transform(DOCS[:3])
        assert vec.fixed_vocabulary_

    def test_ngram_params_forward(self, small_chunks):
        ours = CountVectorizer(ngram_range=(1, 2), min_df=1).fit_transform(DOCS)
        theirs = sk_text.CountVectorizer(ngram_range=(1, 2), min_df=1).fit_transform(DOCS)
        np.testing.assert_array_equal(ours.toarray(), theirs.toarray())


class TestDensifyToDevice:
    def test_sparse_to_sharded(self, mesh):
        X = sk_text.CountVectorizer().fit_transform(DOCS)
        s = densify_to_device(X)
        assert s.shape == X.shape
        np.testing.assert_allclose(
            np.asarray(s.unpad()), X.toarray().astype(np.float32)
        )

    def test_pipeline_into_truncated_svd(self, mesh):
        from dask_ml_tpu.decomposition import TruncatedSVD

        docs = DOCS * 2
        X = HashingVectorizer(n_features=8).transform(docs)
        s = densify_to_device(X)
        svd = TruncatedSVD(n_components=3, random_state=0)
        out = svd.fit_transform(s)
        from dask_ml_tpu.core import unshard

        assert unshard(out).shape == (len(docs), 3)

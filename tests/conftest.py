"""Test harness: an 8-device virtual CPU mesh.

This is the direct analogue of the reference's ``distributed.utils_test.
gen_cluster`` (in-process scheduler + workers — SURVEY.md §4.3): the same
SPMD code paths that run on a TPU pod run here on 8 virtual CPU devices, so
multi-device sharding and collectives are exercised on every test run.

Must set XLA flags BEFORE jax initializes — hence the top of conftest.
"""

import os

# DASK_ML_TPU_TEST_TPU=1 keeps the preset TPU backend so hardware-only
# tests (e.g. the Pallas parity blessing) can run on a real chip.
_USE_TPU = os.environ.get("DASK_ML_TPU_TEST_TPU") not in (None, "", "0")

# DASK_ML_TPU_TEST_DEVICES sweeps the virtual mesh size (default 8):
# odd counts (5, 7) are the adversarial cases for pad+mask divisibility.
_N_DEV = int(os.environ.get("DASK_ML_TPU_TEST_DEVICES", "8"))

if not _USE_TPU:
    import re as _re

    os.environ["JAX_PLATFORMS"] = "cpu"  # image presets JAX_PLATFORMS=axon (TPU)
    _flags = os.environ.get("XLA_FLAGS", "")
    # REWRITE any pre-existing count rather than skip: a stale flag from
    # the caller's shell would silently override the sweep knob
    _flags = _re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", _flags
    ).strip()
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={_N_DEV}"
    ).strip()

# The image's sitecustomize imports jax at interpreter start, so jax.config
# captured JAX_PLATFORMS=axon before this file ran — override via config too.
import jax  # noqa: E402

if not _USE_TPU:
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", _N_DEV)
    except AttributeError:
        # older jax (< 0.4.38) has no jax_num_cpu_devices option; the
        # XLA_FLAGS host-platform count set above covers it as long as
        # jax hasn't created its backends yet
        pass

import faulthandler  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# -- crash / hang forensics (VERDICT r5 weak #1) -------------------------
# The round-5 suite died once with a bare "Fatal Python error" and no
# traceback.  faulthandler is armed explicitly (pytest's builtin plugin
# usually does this too, but an explicit enable survives
# `-p no:faulthandler` runs and pre-collection crashes), and every test
# arms a watchdog that dumps ALL thread stacks when the test exceeds
# DASK_ML_TPU_TEST_TIMEOUT_S (default 300 s; 0 disables).  The dump is
# NON-fatal: the driver's outer `timeout -k` still bounds the suite, but
# a hang/crash now leaves stacks on stderr instead of a silent abort.
faulthandler.enable()

_TEST_TIMEOUT_S = float(os.environ.get("DASK_ML_TPU_TEST_TIMEOUT_S", "300"))

# grafttrace armed for the whole suite: span rings + flight recorder
# cost is within the tier-1 noise floor (the obs overhead A/B test
# gates it at <=3% on the streamed path), and it buys the watchdog dump
# below the "which block/round was in flight" context — faulthandler
# alone shows frames, not fit structure.
from dask_ml_tpu import obs as _obs  # noqa: E402

_obs.enable()


def _watchdog_dump(nodeid: str) -> None:
    """Flight-recorder half of the hang dump (runs on a plain timer
    thread: faulthandler's C-level dumper cannot run Python, so the
    span-path/flight context needs its own timer)."""
    _obs.flight_dump(
        reason=f"test watchdog: {nodeid} exceeded {_TEST_TIMEOUT_S:g}s",
        n=32,
    )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item):
    import threading as _threading

    timer = None
    if _TEST_TIMEOUT_S > 0:
        faulthandler.dump_traceback_later(_TEST_TIMEOUT_S, exit=False)
        timer = _threading.Timer(
            _TEST_TIMEOUT_S, _watchdog_dump, args=(item.nodeid,)
        )
        timer.daemon = True
        timer.start()
    try:
        yield
    finally:
        if _TEST_TIMEOUT_S > 0:
            faulthandler.cancel_dump_traceback_later()
        if timer is not None:
            timer.cancel()


@pytest.fixture(scope="session")
def n_devices():
    """The harness-configured virtual device count (None in TPU mode,
    where the physical chip count is whatever the hardware exposes)."""
    return None if _USE_TPU else _N_DEV


def require_devices_divisible(k: int) -> int:
    """Skip the calling test unless the device count divides by ``k``
    (mesh-shape-sensitive tests under the DASK_ML_TPU_TEST_DEVICES
    sweep); returns the device count."""
    n = len(jax.devices())
    if n % k:
        pytest.skip(f"needs a device count divisible by {k} (have {n})")
    return n


def spec_axis(entry):
    """Unwrap one PartitionSpec entry to its axis name: jax versions
    differ on whether a propagated entry is the name or a 1-tuple of it
    (jax < 0.4.38 tuple-wraps)."""
    return entry[0] if isinstance(entry, tuple) else entry


@pytest.fixture(scope="session")
def mesh():
    from dask_ml_tpu.core import get_mesh

    return get_mesh()


def retry_flaky(attempts=2, match=None):
    """Auto-retry decorator for LOAD-flaky tests (not logic-flaky ones).

    Re-runs the test up to ``attempts`` times, but ONLY when the failure
    text matches ``match`` (a regex) — a real assertion failure must
    surface on the first run, not burn retries.  Use sparingly: the only
    legitimate customer is resource-starvation noise like
    ``test_three_process_group``'s coordination-service heartbeat
    timeouts when 3 jax processes starve the 2-core box (ROADMAP env
    note); that class passes in isolation and wastes a tier-1 lane when
    it loses the scheduling lottery.
    """
    import functools
    import re as _re

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            last = None
            for attempt in range(attempts):
                try:
                    return fn(*args, **kwargs)
                except Exception as e:  # noqa: BLE001 - filtered below
                    text = f"{type(e).__name__}: {e}"
                    if match is not None and not _re.search(
                            match, text, _re.IGNORECASE | _re.DOTALL):
                        raise
                    last = e
                    if attempt + 1 < attempts:
                        import warnings

                        warnings.warn(
                            f"retry_flaky: {fn.__name__} attempt "
                            f"{attempt + 1}/{attempts} hit a matched "
                            f"flake, retrying: {text[:200]}",
                            stacklevel=2,
                        )
            raise last

        return wrapper

    return deco


@pytest.fixture
def sanitizer():
    """A scoped graftsan runtime sanitizer (dask_ml_tpu/sanitize/):
    compile/transfer/dispatch detectors armed for exactly this test.
    Fail-fast: an off-thread dispatch or compile raises at the violating
    call; use ``with sanitizer.steady():`` around the post-warmup phase
    to arm the implicit-transfer guard and make new compiles
    violations."""
    from dask_ml_tpu import sanitize

    with sanitize.sanitize(label="pytest") as s:
        yield s


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture
def xy_classification(rng):
    """Small dense classification problem (reference conftest pattern)."""
    n, d = 100, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w + 0.1 * rng.normal(size=n) > 0).astype(np.int32)
    return X, y


@pytest.fixture
def xy_regression(rng):
    n, d = 120, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w + 0.05 * rng.normal(size=n)).astype(np.float32)
    return X, y


# -- Hypothesis profiles -------------------------------------------------
# Default = derandomized: the suite must be deterministic-green for CI /
# the driver (r3 verdict: random draws made the suite flaky at head —
# property tests are a DISCOVERY tool, and discovery belongs in the
# explicit 'explore' profile, not in every CI run).
#   HYPOTHESIS_PROFILE=explore python -m pytest tests/test_properties.py
# runs the randomized search that has found real bugs each round.
try:
    from hypothesis import settings as _hyp_settings

    _hyp_settings.register_profile("ci", derandomize=True)
    _hyp_settings.register_profile("explore", derandomize=False)
    _hyp_settings.load_profile(
        os.environ.get("HYPOTHESIS_PROFILE", "ci"))
except ImportError:  # pragma: no cover
    pass

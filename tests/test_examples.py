"""Examples must keep running — they are the user-facing front door.

Two fast ones run as subprocesses (fresh interpreter, the way a user
would); the heavier ones are exercised by the suites covering the same
paths.
"""
import os
import pathlib
import subprocess
import sys

import pytest

_EXAMPLES = pathlib.Path(__file__).resolve().parents[1] / "examples"


@pytest.mark.parametrize(
    "script", ["streaming_out_of_core.py", "text_pipeline.py",
               "multihost_mesh.py"]
)
def test_example_runs(script):
    proc = subprocess.run(
        [sys.executable, str(_EXAMPLES / script)],
        capture_output=True, text=True, timeout=420,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": os.environ.get("HOME", "/tmp")},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

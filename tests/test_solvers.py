import numpy as np
import pytest

import jax.numpy as jnp

import dask_ml_tpu.solvers as solvers
from dask_ml_tpu.core import shard_rows
from dask_ml_tpu.solvers import (
    L1,
    L2,
    ElasticNet,
    Logistic,
    Normal,
    Poisson,
    lambda_sweep,
    lbfgs_minimize,
    multinomial,
)


@pytest.fixture
def logistic_data(rng):
    n, d = 300, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    p = 1 / (1 + np.exp(-(X @ w)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y, w


@pytest.fixture
def normal_data(rng):
    n, d = 300, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w + 0.01 * rng.normal(size=n)).astype(np.float32)
    return X, y, w


class TestLBFGSCore:
    def test_quadratic_exact(self):
        A = jnp.asarray(np.diag([1.0, 10.0, 100.0]), dtype=jnp.float32)
        b = jnp.asarray([1.0, -2.0, 3.0])

        def f(x):
            return 0.5 * x @ A @ x - b @ x

        x, state = lbfgs_minimize(f, jnp.zeros(3), max_iter=100, tol=1e-6)
        np.testing.assert_allclose(np.asarray(x), np.linalg.solve(np.asarray(A), b), atol=1e-3)
        assert bool(state.converged)

    def test_rosenbrock(self):
        def f(z):
            return (1 - z[0]) ** 2 + 100 * (z[1] - z[0] ** 2) ** 2

        x, state = lbfgs_minimize(f, jnp.asarray([-1.2, 1.0]), max_iter=400, tol=1e-6)
        np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=1e-2)

    def test_inside_jit_and_vmap(self):
        import jax

        def f(x):
            return jnp.sum((x - 1.5) ** 2)

        solve = jax.jit(jax.vmap(lambda x0: lbfgs_minimize(f, x0, max_iter=50)[0]))
        out = solve(jnp.zeros((4, 3)))
        np.testing.assert_allclose(np.asarray(out), 1.5 * np.ones((4, 3)), atol=1e-4)


def _sklearn_logistic(X, y, C=1e5):
    from sklearn.linear_model import LogisticRegression as SkLR

    return SkLR(C=C, fit_intercept=False, tol=1e-8).fit(X, y).coef_[0]


class TestSolverParity:
    """All solvers minimize the same objective -> same optimum."""

    @pytest.mark.parametrize("name", ["lbfgs", "newton", "gradient_descent", "proximal_grad", "admm"])
    def test_logistic_unregularized(self, logistic_data, name):
        X, y, _ = logistic_data
        fn = getattr(solvers, name)
        kwargs = {"family": Logistic, "lamduh": 1e-5, "max_iter": 200}
        beta = fn(shard_rows(X), shard_rows(y), **kwargs)
        expected = _sklearn_logistic(X, y)
        np.testing.assert_allclose(np.asarray(beta), expected, atol=5e-2)

    @pytest.mark.parametrize("name", ["lbfgs", "newton", "admm"])
    def test_normal_family(self, normal_data, name):
        X, y, w = normal_data
        fn = getattr(solvers, name)
        beta = fn(shard_rows(X), shard_rows(y), family=Normal, lamduh=1e-6, max_iter=200)
        expected = np.linalg.lstsq(X, y, rcond=None)[0]
        np.testing.assert_allclose(np.asarray(beta), expected, atol=2e-2)

    def test_poisson_family(self, rng):
        n, d = 400, 4
        X = rng.normal(size=(n, d)).astype(np.float32) * 0.5
        w = rng.normal(size=d) * 0.5
        y = rng.poisson(np.exp(X @ w)).astype(np.float32)
        beta = solvers.lbfgs(shard_rows(X), shard_rows(y), family=Poisson, lamduh=1e-6, max_iter=300)
        from sklearn.linear_model import PoissonRegressor

        sk = PoissonRegressor(alpha=0, fit_intercept=False, tol=1e-8, max_iter=1000).fit(X, y)
        np.testing.assert_allclose(np.asarray(beta), sk.coef_, atol=5e-2)

    def test_l1_sparsity(self, normal_data):
        X, y, w = normal_data
        beta = solvers.admm(
            shard_rows(X), shard_rows(y), family=Normal, regularizer=L1,
            lamduh=300.0, max_iter=200,
        )
        # strong l1 must zero out some coordinates exactly
        assert np.sum(np.abs(np.asarray(beta)) < 1e-6) > 0

    def test_l1_proximal_grad_matches_admm(self, normal_data):
        X, y, _ = normal_data
        kw = dict(family=Normal, regularizer=L1, lamduh=50.0, max_iter=400)
        b1 = solvers.admm(shard_rows(X), shard_rows(y), **kw)
        b2 = solvers.proximal_grad(shard_rows(X), shard_rows(y), **kw)
        np.testing.assert_allclose(np.asarray(b1), np.asarray(b2), atol=2e-2)

    def test_lbfgs_rejects_l1(self, normal_data):
        X, y, _ = normal_data
        with pytest.raises(ValueError, match="smooth"):
            solvers.lbfgs(shard_rows(X), shard_rows(y), regularizer=L1, lamduh=1.0)

    def test_l2_regularization_shrinks(self, normal_data):
        X, y, _ = normal_data
        b_weak = solvers.lbfgs(shard_rows(X), shard_rows(y), family=Normal, lamduh=1e-6)
        b_strong = solvers.lbfgs(shard_rows(X), shard_rows(y), family=Normal, regularizer=L2, lamduh=1e3)
        assert np.linalg.norm(np.asarray(b_strong)) < np.linalg.norm(np.asarray(b_weak))


class TestRegularizers:
    def test_l1_prox_soft_threshold(self):
        b = jnp.asarray([3.0, -0.5, 0.2])
        out = np.asarray(L1.prox(b, 1.0))
        np.testing.assert_allclose(out, [2.0, 0.0, 0.0])

    def test_l2_prox_shrinks(self):
        out = np.asarray(L2.prox(jnp.asarray([2.0]), 1.0))
        np.testing.assert_allclose(out, [1.0])

    def test_elastic_net_between(self):
        b = jnp.asarray([2.0])
        en = float(ElasticNet.prox(b, 1.0)[0])
        assert float(L1.prox(b, 1.0)[0]) >= 0 and en > 0

    def test_get_regularizer_names(self):
        assert solvers.get_regularizer("l1") is L1
        assert solvers.get_regularizer("elastic_net") is ElasticNet
        with pytest.raises(ValueError, match="Unknown regularizer"):
            solvers.get_regularizer("l7")


class TestLineSearchStrategies:
    """Both weak-Wolfe strategies must agree on convergence quality.
    The chip delta is now measured (probe_grid 1.24-1.38x on TPU,
    backtrack wins on CPU) and ``lbfgs`` defaults to ``auto`` — the
    per-platform winner via ``line_search_strategy`` / the
    ``DASK_ML_TPU_LINE_SEARCH`` knob."""

    def test_rosenbrock_probe_grid(self):
        import jax.numpy as jnp

        from dask_ml_tpu.solvers.lbfgs_core import lbfgs_minimize

        def f(z):
            return (1 - z[0]) ** 2 + 100 * (z[1] - z[0] ** 2) ** 2

        x, state = lbfgs_minimize(
            f, jnp.asarray([-1.2, 1.0]), max_iter=400, tol=1e-6,
            line_search="probe_grid",
        )
        np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=1e-2)

    def test_strategies_agree_on_logistic(self, rng):
        from dask_ml_tpu.solvers import Logistic, lbfgs

        X = rng.normal(size=(2000, 8)).astype(np.float32)
        w = rng.normal(size=8)
        y = (X @ w > 0).astype(np.float32)
        outs = {
            ls: np.asarray(lbfgs(
                X, y, family=Logistic, lamduh=1.0, max_iter=100, tol=1e-6,
                line_search=ls,
            ))
            for ls in ("backtrack", "probe_grid")
        }
        np.testing.assert_allclose(
            outs["backtrack"], outs["probe_grid"], rtol=0.05, atol=1e-3
        )

    def test_unknown_strategy_raises(self, rng):
        from dask_ml_tpu.solvers import Logistic, lbfgs

        X = rng.normal(size=(64, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        with pytest.raises(ValueError, match="line_search"):
            lbfgs(X, y, family=Logistic, line_search="bogus")


class TestLineSearchPolicy:
    """DASK_ML_TPU_LINE_SEARCH resolution rules (same contract shape as
    pack_strategy/scatter_strategy: explicit request > env knob > the
    measured per-platform auto)."""

    def test_auto_resolves_per_platform(self, monkeypatch):
        import jax

        from dask_ml_tpu.solvers.algorithms import line_search_strategy

        monkeypatch.delenv("DASK_ML_TPU_LINE_SEARCH", raising=False)
        expect = ("probe_grid" if jax.default_backend() == "tpu"
                  else "backtrack")
        assert line_search_strategy("auto") == expect

    def test_env_knob_overrides_auto(self, monkeypatch):
        from dask_ml_tpu.solvers.algorithms import line_search_strategy

        monkeypatch.setenv("DASK_ML_TPU_LINE_SEARCH", "probe_grid")
        assert line_search_strategy("auto") == "probe_grid"

    def test_explicit_request_beats_env(self, monkeypatch):
        from dask_ml_tpu.solvers.algorithms import line_search_strategy

        monkeypatch.setenv("DASK_ML_TPU_LINE_SEARCH", "probe_grid")
        assert line_search_strategy("backtrack") == "backtrack"

    def test_bad_env_rejected(self, monkeypatch):
        from dask_ml_tpu.solvers.algorithms import line_search_strategy

        monkeypatch.setenv("DASK_ML_TPU_LINE_SEARCH", "newton_exact")
        with pytest.raises(ValueError, match="DASK_ML_TPU_LINE_SEARCH"):
            line_search_strategy("auto")

    def test_packed_default_never_resolves_to_probe_grid(
            self, rng, monkeypatch, mesh):
        # packed_solve's own 'auto' default must NOT opt the sequential
        # fallback's admm/gd/newton dispatches into probe_grid (their
        # entry points keep backtrack as the measured-safe default);
        # an env knob forcing probe_grid with a non-lbfgs solver must
        # still converge to the same optimum — resolution correctness,
        # not performance, is what this pins
        from dask_ml_tpu.solvers import Logistic, packed_solve

        monkeypatch.setenv("DASK_ML_TPU_PACK", "sequential")
        X = rng.normal(size=(256, 5)).astype(np.float32)
        sX = shard_rows(X)
        w = rng.normal(size=5)
        Y = np.stack([
            (X @ w > 0).astype(np.float32),
            (X @ w > 0.5).astype(np.float32),
        ])
        Yp = np.zeros((2, sX.data.shape[0]), np.float32)
        Yp[:, :256] = Y
        B, _ = packed_solve("admm", sX, Yp, family=Logistic,
                            lamduh=0.1, max_iter=30)
        B2, _ = packed_solve("admm", sX, Yp, family=Logistic,
                            lamduh=0.1, max_iter=30,
                            line_search="backtrack")
        np.testing.assert_allclose(
            np.asarray(B), np.asarray(B2), rtol=1e-4, atol=1e-5)


class TestLambdaSweep:
    """solvers.lambda_sweep: K solves of the same (X, y) at different
    regularization strengths as one vmapped program — each lane must
    match the standalone solver at its lamduh."""

    def _data(self, rng):
        X = rng.normal(size=(300, 5)).astype(np.float32)
        y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
        return X, y

    @pytest.mark.parametrize("solver", ["lbfgs", "admm",
                                        "gradient_descent",
                                        "proximal_grad"])
    def test_lanes_match_standalone(self, rng, mesh, solver):
        X, y = self._data(rng)
        lams = [0.01, 0.1, 1.0]
        # tol=0: every lane and every standalone run executes exactly
        # max_iter rounds, so a convergence-criterion difference cannot
        # masquerade as a numeric one
        kwargs = dict(family=Logistic, max_iter=80, tol=0.0)
        if solver == "admm":
            kwargs["inner_iter"] = 20
            kwargs["abstol"] = kwargs.pop("tol")
            kwargs["reltol"] = 0.0  # Boyd rule fully disabled: every
            # lane and standalone run does exactly max_iter rounds
        betas, n_its = lambda_sweep(solver, X, y, lams, **kwargs)
        assert betas.shape[0] == len(lams)
        assert n_its.shape == (len(lams),)
        solo_fn = getattr(solvers, solver)
        for i, lam in enumerate(lams):
            solo = solo_fn(X, y, lamduh=lam, **kwargs)
            np.testing.assert_allclose(
                np.asarray(betas[i]), np.asarray(solo),
                rtol=5e-3, atol=2e-3,
                err_msg=f"{solver} lane {i} (lam={lam})")

    def test_newton_matrix_family_rejected(self, rng, mesh):
        X, y = self._data(rng)
        with pytest.raises(ValueError, match="matrix-parameter"):
            lambda_sweep("newton", X, y, [0.1], family=multinomial(3))

    def test_bad_lams_shape_rejected(self, rng, mesh):
        X, y = self._data(rng)
        with pytest.raises(ValueError, match="1-D"):
            lambda_sweep("lbfgs", X, y, [[0.1, 1.0]], family=Logistic)

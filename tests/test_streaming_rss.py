"""Bounded-RSS out-of-core streaming — the HOST half of the >HBM
contract (reference: ``dask_ml/_partial.py :: fit``, SURVEY.md §7 hard
part (b): the whole point of the reference is fitting data that doesn't
fit).

The native streaming session (``native/loader.cpp :: dmlt_stream_*``) is
WINDOWED: the file moves through a ~32 MB window and is never fully
resident, so a dataset far beyond any memory budget streams through
``partial_fit`` with peak RSS bounded by (jax baseline + window + ring
blocks) — NOT by file size.  Measured baseline of the child pipeline
(jax-cpu + loader + SGD) is ~430 MB; the 1200 MB bound fails loudly if
the session ever regresses to whole-file reads (the pre-round-5 design
malloc'd the entire file: a 2 GB stream would peak >2.4 GB).

Runs in a subprocess so ``ru_maxrss`` measures exactly this pipeline,
not the test session's accumulated peak.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from dask_ml_tpu.linear_model import SGDClassifier
from dask_ml_tpu.io import stream_csv_blocks

def peak_mb():
    # VmHWM, NOT ru_maxrss: a forked child's ru_maxrss includes the
    # PARENT'S resident set at fork time (the COW window before exec),
    # so under a fat parent — a pytest session 790 tests deep, ~4 GB —
    # ru_maxrss reports the parent's peak no matter what this process
    # does.  VmHWM belongs to the post-exec mm and measures only us.
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmHWM"):
                return int(line.split()[1]) / 1024.0
    raise RuntimeError("VmHWM not found")

path = sys.argv[1]
clf = SGDClassifier(random_state=0)
n = 0
first_peak = None
for blk in stream_csv_blocks(path, 65536):
    clf.partial_fit(
        blk[:, :-1], (blk[:, -1] > 0.5).astype(np.float32),
        classes=[0.0, 1.0],
    )
    n += blk.shape[0]
    if first_peak is None:
        first_peak = peak_mb()  # baseline: jax + loader + one block
print(json.dumps({"rows": n, "steps": float(clf.t_),
                  "peak_mb": peak_mb(), "first_peak_mb": first_peak}))
"""


def _write_big_csv(path, target_gb: float) -> int:
    """Write ~target_gb of numeric CSV by repeating one formatted block
    (generation must be disk-bound, not Python-format-bound).  Returns
    the exact row count."""
    rng = np.random.RandomState(7)
    block = rng.rand(4000, 16).astype(np.float32)
    txt = "\n".join(
        ",".join(f"{v:.6g}" for v in row) for row in block
    ) + "\n"
    reps = int(target_gb * 1e9) // len(txt) + 1
    with open(path, "w") as f:
        for _ in range(reps):
            f.write(txt)
    return 4000 * reps


def _stream_in_child(path: str) -> dict:
    out = subprocess.run(
        [sys.executable, "-c", _CHILD, path],
        capture_output=True, text=True, timeout=1800, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def _kernel_has_vmhwm() -> bool:
    try:
        with open("/proc/self/status") as f:
            return "VmHWM" in f.read()
    except OSError:
        return False


class TestBoundedRSSStreaming:
    def test_2gb_stream_bounded_rss(self, tmp_path):
        if not _kernel_has_vmhwm():
            pytest.skip("kernel does not expose VmHWM (sandboxed /proc)")
        p = tmp_path / "big.csv"
        rows = _write_big_csv(p, 2.0)
        try:
            res = _stream_in_child(str(p))
        finally:
            p.unlink()
        assert res["rows"] == rows
        assert res["steps"] > 0  # the model actually stepped
        # two invariants: (a) RSS growth after the first block stays
        # bounded — the stream must not ACCUMULATE (measured ~40 MB;
        # generous margin for allocator variance under a loaded suite);
        # (b) absolute peak far below the ~2430 MB a whole-file-resident
        # session would need for this ~2000 MB file.
        assert res["peak_mb"] - res["first_peak_mb"] < 500, res
        assert res["peak_mb"] < 1500, res

    @pytest.mark.skipif(
        not os.environ.get("DASK_ML_TPU_TEST_BIG"),
        reason="set DASK_ML_TPU_TEST_BIG=1 for the >=10 GB tier",
    )
    def test_12gb_stream_bounded_rss(self, tmp_path):
        """The VERDICT r4 item-#6 scale: >=10 GB on disk, RSS bounded.
        Run manually (DASK_ML_TPU_TEST_BIG=1) — result recorded in
        docs/design.md §6."""
        p = tmp_path / "huge.csv"
        rows = _write_big_csv(p, 12.0)
        try:
            res = _stream_in_child(str(p))
        finally:
            p.unlink()
        assert res["rows"] == rows
        assert res["peak_mb"] - res["first_peak_mb"] < 500, res
        assert res["peak_mb"] < 1500, res

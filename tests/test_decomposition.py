import numpy as np
import pytest
import sklearn.decomposition as sd

import dask_ml_tpu.decomposition as dd
from dask_ml_tpu.core import shard_rows, unshard
from dask_ml_tpu.core.sharded import ShardedRows
from dask_ml_tpu.linalg import randomized_svd, tsqr, tsqr_svd


@pytest.fixture
def X(rng):
    # tall-skinny with decaying spectrum
    base = rng.normal(size=(200, 10)).astype(np.float64)
    scale = np.linspace(3.0, 0.1, 10)
    return (base * scale).astype(np.float64)


class TestTSQR:
    def test_qr_reconstruction(self, X):
        s = shard_rows(X)
        q, r = tsqr(s)
        np.testing.assert_allclose(np.asarray(q @ r), unshard(s.data), atol=1e-3)

    def test_q_orthonormal(self, X):
        q, r = tsqr(shard_rows(X))
        qtq = np.asarray(q.T @ q)
        np.testing.assert_allclose(qtq, np.eye(X.shape[1]), atol=1e-3)

    def test_r_upper_triangular(self, X):
        _, r = tsqr(shard_rows(X))
        r = np.asarray(r)
        np.testing.assert_allclose(r, np.triu(r), atol=1e-5)

    def test_svd_singular_values_parity(self, X):
        _, s, _ = tsqr_svd(shard_rows(X))
        expected = np.linalg.svd(X, compute_uv=False)
        np.testing.assert_allclose(np.asarray(s), expected, rtol=1e-3)

    def test_too_wide_raises(self):
        with pytest.raises(ValueError, match="tall-skinny"):
            tsqr(shard_rows(np.ones((8, 10), dtype=np.float32)))

    def test_wide_rejected_despite_padding(self):
        # 9x10 pads to 16 rows on an 8-device mesh; the TRUE shape (9 < 10)
        # must still be rejected — padding must not mask rank deficiency
        with pytest.raises(ValueError, match="tall-skinny"):
            tsqr(shard_rows(np.ones((9, 10), dtype=np.float32)))

    def test_short_shards_ok(self, rng):
        # 16x10 over 8 shards: each shard is short (2 rows < 10 cols) but the
        # stacked R (16 rows) recovers full rank — must factor correctly.
        X = rng.normal(size=(16, 10)).astype(np.float64)
        q, r = tsqr(shard_rows(X))
        # slice padding: 16 divides an 8-device mesh but not e.g. 5
        qh = np.asarray(q)[:16]
        np.testing.assert_allclose(qh @ np.asarray(r), X, atol=1e-5)
        sv = np.linalg.svd(np.asarray(r), compute_uv=False)
        np.testing.assert_allclose(sv, np.linalg.svd(X, compute_uv=False), rtol=1e-5)

    def test_padding_zero_rows_safe(self, rng):
        # 37 rows over 8 shards -> 3 zero pad rows; R must match unpadded
        X = rng.normal(size=(370, 4)).astype(np.float64)
        s = shard_rows(X)
        _, r = tsqr(s)
        sv_padded = np.linalg.svd(np.asarray(r), compute_uv=False)
        sv_true = np.linalg.svd(X, compute_uv=False)
        np.testing.assert_allclose(sv_padded, sv_true, rtol=1e-4)


class TestCholQR2:
    """The CholeskyQR2 fast path (``strategy='cholqr2'``) and its guarded
    Householder fallback — linalg/tsqr.py docstring."""

    def test_parity_with_householder(self, X):
        Xf = X.astype(np.float32)
        q1, r1 = tsqr(shard_rows(Xf), strategy="cholqr2")
        q1 = np.asarray(q1)[: Xf.shape[0]].astype(np.float64)
        r1 = np.asarray(r1).astype(np.float64)
        np.testing.assert_allclose(q1.T @ q1, np.eye(10), atol=1e-4)
        np.testing.assert_allclose(q1 @ r1, Xf, atol=1e-4)
        np.testing.assert_allclose(r1, np.triu(r1), atol=1e-5)
        # Cholesky R has a positive diagonal by construction
        assert (np.diag(r1) > 0).all()
        # same factorization as Householder up to column signs
        _, r2 = tsqr(shard_rows(Xf), strategy="householder")
        r2 = np.asarray(r2).astype(np.float64)
        np.testing.assert_allclose(
            np.abs(r1), np.abs(r2), rtol=1e-3, atol=1e-4
        )

    def test_rank_deficient_falls_back(self, rng):
        # duplicate columns: the Gram Cholesky degenerates, the guard must
        # route to the Householder body and still return an orthonormal Q
        A = rng.normal(size=(400, 6)).astype(np.float32)
        Xd = np.concatenate([A, A[:, :3]], axis=1)
        q, r = tsqr(shard_rows(Xd), strategy="cholqr2")
        qh = np.asarray(q)[:400].astype(np.float64)
        np.testing.assert_allclose(qh.T @ qh, np.eye(9), atol=5e-4)
        np.testing.assert_allclose(
            qh @ np.asarray(r).astype(np.float64), Xd, atol=1e-4
        )

    def test_moderate_conditioning_holds_fast_path(self, rng):
        # cond ~ 3e2 in f32: inside CholeskyQR2's provable regime — the
        # result must be machine-orthonormal (if the fallback fired this
        # would also pass, so the A/B bench is what pins the perf claim;
        # this pins correctness at the regime boundary)
        U, _ = np.linalg.qr(rng.normal(size=(600, 12)))
        V, _ = np.linalg.qr(rng.normal(size=(12, 12)))
        s = np.logspace(0, -2.5, 12)
        Xc = ((U * s) @ V.T).astype(np.float32)
        q, _ = tsqr(shard_rows(Xc), strategy="cholqr2")
        qh = np.asarray(q)[:600].astype(np.float64)
        np.testing.assert_allclose(qh.T @ qh, np.eye(12), atol=5e-4)

    def test_env_knob(self, X, monkeypatch):
        from dask_ml_tpu.linalg.tsqr import tsqr_strategy

        monkeypatch.setenv("DASK_ML_TPU_TSQR", "cholqr2")
        assert tsqr_strategy() == "cholqr2"
        q, r = tsqr(shard_rows(X.astype(np.float32)))
        r = np.asarray(r)
        assert (np.diag(r) > 0).all()  # the cholqr2 signature
        monkeypatch.setenv("DASK_ML_TPU_TSQR", "bogus")
        with pytest.raises(ValueError, match="DASK_ML_TPU_TSQR"):
            tsqr_strategy()

    def test_pca_parity_under_cholqr2(self, rng, monkeypatch):
        monkeypatch.setenv("DASK_ML_TPU_TSQR", "cholqr2")
        X = rng.normal(size=(300, 8)).astype(np.float32) * np.linspace(
            2.0, 0.2, 8
        ).astype(np.float32)
        ours = dd.PCA(n_components=4, svd_solver="tsqr").fit(shard_rows(X))
        sk = sd.PCA(n_components=4, svd_solver="full").fit(X)
        np.testing.assert_allclose(
            ours.explained_variance_, sk.explained_variance_, rtol=1e-3
        )
        np.testing.assert_allclose(
            np.abs(np.asarray(ours.components_)),
            np.abs(sk.components_), atol=1e-3
        )


class TestRandomizedSVD:
    def test_topk_parity(self, X):
        u, s, vt = randomized_svd(shard_rows(X), 3, random_state=0)
        expected = np.linalg.svd(X, compute_uv=False)[:3]
        np.testing.assert_allclose(np.asarray(s), expected, rtol=1e-2)

    def test_low_rank_reconstruction(self, rng):
        # exactly rank-3 matrix is recovered to numerical precision
        A = rng.normal(size=(100, 3)) @ rng.normal(size=(3, 8))
        A = A.astype(np.float64)
        u, s, vt = randomized_svd(shard_rows(A), 3, random_state=0)
        approx = np.asarray(u * s @ vt)[:100]
        np.testing.assert_allclose(approx, A, atol=1e-3)


class TestPCA:
    def test_parity_full(self, X):
        ours = dd.PCA(n_components=4, svd_solver="full").fit(shard_rows(X))
        theirs = sd.PCA(n_components=4, svd_solver="full").fit(X)
        np.testing.assert_allclose(np.asarray(ours.mean_), theirs.mean_, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(ours.singular_values_), theirs.singular_values_, rtol=1e-3
        )
        np.testing.assert_allclose(
            np.abs(np.asarray(ours.components_)), np.abs(theirs.components_), atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(ours.explained_variance_ratio_),
            theirs.explained_variance_ratio_,
            rtol=1e-3,
        )

    def test_signs_deterministic_match_sklearn(self, X):
        ours = dd.PCA(n_components=3).fit(X)
        theirs = sd.PCA(n_components=3).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.components_), theirs.components_, atol=1e-3
        )

    def test_transform_parity(self, X):
        ours = dd.PCA(n_components=3).fit(X)
        theirs = sd.PCA(n_components=3).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X), atol=1e-3
        )

    def test_fit_transform_equals_transform(self, X):
        p = dd.PCA(n_components=3)
        ft = np.asarray(p.fit_transform(X))
        t = np.asarray(p.transform(X))
        np.testing.assert_allclose(ft, t, atol=1e-3)

    def test_randomized_solver(self, X):
        ours = dd.PCA(n_components=3, svd_solver="randomized", random_state=0).fit(X)
        theirs = sd.PCA(n_components=3).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.singular_values_), theirs.singular_values_, rtol=1e-2
        )

    def test_fraction_n_components(self, X):
        ours = dd.PCA(n_components=0.9, svd_solver="full").fit(X)
        theirs = sd.PCA(n_components=0.9, svd_solver="full").fit(X)
        assert ours.n_components_ == theirs.n_components_

    def test_inverse_transform_roundtrip(self, X):
        p = dd.PCA(n_components=10).fit(X)  # full rank
        np.testing.assert_allclose(
            np.asarray(p.inverse_transform(p.transform(X))), X, atol=1e-3
        )

    def test_wide_raises(self):
        with pytest.raises(ValueError, match="tall-skinny|n_samples"):
            dd.PCA(n_components=2).fit(np.ones((5, 50), dtype=np.float32))

    def test_whiten(self, X):
        ours = dd.PCA(n_components=3, whiten=True).fit(X)
        out = np.asarray(ours.transform(X))
        np.testing.assert_allclose(out.std(axis=0, ddof=1), np.ones(3), rtol=1e-2)


class TestTruncatedSVD:
    def test_parity_attrs(self, X):
        ours = dd.TruncatedSVD(n_components=3).fit(shard_rows(X))
        theirs = sd.TruncatedSVD(n_components=3, algorithm="arpack").fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.singular_values_), theirs.singular_values_, rtol=1e-3
        )
        np.testing.assert_allclose(
            np.abs(np.asarray(ours.components_)), np.abs(theirs.components_), atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(ours.explained_variance_), theirs.explained_variance_, rtol=1e-2
        )

    def test_fit_transform_sharded(self, X):
        s = shard_rows(X)
        out = dd.TruncatedSVD(n_components=3).fit_transform(s)
        assert isinstance(out, ShardedRows)
        assert unshard(out).shape == (200, 3)

    def test_transform_then_inverse(self, X):
        t = dd.TruncatedSVD(n_components=9).fit(X)
        recon = np.asarray(t.inverse_transform(t.transform(X)))
        assert np.linalg.norm(recon - X) / np.linalg.norm(X) < 0.1

    def test_bad_n_components(self, X):
        with pytest.raises(ValueError, match="n_components"):
            dd.TruncatedSVD(n_components=10).fit(X)  # == n_features

    def test_randomized(self, X):
        ours = dd.TruncatedSVD(n_components=3, algorithm="randomized", random_state=0).fit(X)
        theirs = sd.TruncatedSVD(n_components=3, algorithm="arpack").fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.singular_values_), theirs.singular_values_, rtol=1e-2
        )


class TestIncrementalPCA:
    def test_parity_with_sklearn(self, X):
        ours = dd.IncrementalPCA(n_components=3, batch_size=50).fit(X)
        theirs = sd.IncrementalPCA(n_components=3, batch_size=50).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.singular_values_), theirs.singular_values_, rtol=1e-2
        )
        np.testing.assert_allclose(np.asarray(ours.mean_), theirs.mean_, atol=1e-4)
        np.testing.assert_allclose(
            np.abs(np.asarray(ours.components_)), np.abs(theirs.components_), atol=5e-2
        )

    def test_partial_fit_accumulates(self, X):
        ipca = dd.IncrementalPCA(n_components=3)
        ipca.partial_fit(X[:100])
        ipca.partial_fit(X[100:])
        assert ipca.n_samples_seen_ == 200

    def test_small_batch_raises(self, X):
        ipca = dd.IncrementalPCA(n_components=5)
        with pytest.raises(ValueError, match="n_components"):
            ipca.partial_fit(X[:3])

    def test_transform_shape(self, X):
        ipca = dd.IncrementalPCA(n_components=3, batch_size=50).fit(X)
        assert np.asarray(ipca.transform(X)).shape == (200, 3)


class TestReviewRegressions:
    def test_tsvd_nonzero_padded_rows(self, rng):
        # sharded input whose pad rows are nonzero (e.g. from a scaler)
        import dask_ml_tpu.preprocessing as dp
        X = rng.normal(loc=5.0, size=(83, 6)).astype(np.float64)  # pads to 88
        s = shard_rows(X)
        scaled = dp.StandardScaler().fit(s).transform(s)  # pad rows = -mean/scale != 0
        ours = dd.TruncatedSVD(n_components=3).fit(scaled)
        X_scaled = (X - X.mean(0)) / X.std(0)
        expected = np.linalg.svd(X_scaled, compute_uv=False)[:3]
        np.testing.assert_allclose(
            np.asarray(ours.singular_values_), expected, rtol=1e-2
        )

    def test_tsvd_fit_transform_plain_in_plain_out(self):
        out = dd.TruncatedSVD(n_components=2).fit_transform(np.random.RandomState(0).normal(size=(37, 5)))
        assert not isinstance(out, ShardedRows)
        assert np.asarray(out).shape == (37, 2)

    def test_ipca_default_components_small_tail(self, rng):
        X = rng.normal(size=(105, 10)).astype(np.float32)
        ipca = dd.IncrementalPCA(batch_size=50).fit(X)  # tail of 5 rows must be dropped
        assert ipca.n_samples_seen_ == 100

    def test_ipca_noise_variance_finite(self, rng):
        X = rng.normal(size=(5, 10)).astype(np.float32)
        ipca = dd.IncrementalPCA().partial_fit(X)
        assert np.isfinite(float(ipca.noise_variance_))

    def test_pca_fraction_one(self, rng):
        X = rng.normal(size=(50, 6)).astype(np.float64)
        p = dd.PCA(n_components=1.0, svd_solver="full").fit(X)
        assert p.n_components_ == p.components_.shape[0] <= 6


class TestStreamedTruncatedSVD:
    """VERDICT r2 next #9: sparse stream -> SVD without densifying the
    corpus; peak dense memory is O(n_features * sketch)."""

    def _sparse_blocks(self, rng, n=1200, d=300, block=100, density=0.05):
        import scipy.sparse

        rows = []
        for lo in range(0, n, block):
            b = min(block, n - lo)
            rows.append(scipy.sparse.random(
                b, d, density=density, random_state=lo + 1, dtype=np.float32,
                format="csr",
            ))
        return rows

    def test_parity_with_dense_fit(self, rng, mesh):
        # low-rank + noise: a separated spectrum is what sketching can
        # recover accurately (a flat random spectrum is adversarial for
        # ANY randomized method, dense or streamed)
        import scipy.sparse

        from dask_ml_tpu.decomposition import TruncatedSVD

        n, d, r = 1200, 300, 8
        latent = rng.normal(size=(n, r)) * np.linspace(10, 2, r)
        dense_np = (
            latent @ rng.normal(size=(r, d)) + 0.01 * rng.normal(size=(n, d))
        ).astype(np.float32)
        blocks = [
            scipy.sparse.csr_matrix(dense_np[lo: lo + 100])
            for lo in range(0, n, 100)
        ]
        dense = dense_np
        streamed = TruncatedSVD(
            n_components=5, n_iter=7, random_state=0
        ).fit_streamed(lambda: iter(blocks))
        ref = TruncatedSVD(
            n_components=5, algorithm="tsqr"
        ).fit(dense)
        np.testing.assert_allclose(
            np.asarray(streamed.singular_values_),
            np.asarray(ref.singular_values_), rtol=1e-2,
        )
        # subspace parity (signs already canonicalized on both paths)
        np.testing.assert_allclose(
            np.abs(np.asarray(streamed.components_)),
            np.abs(np.asarray(ref.components_)), atol=5e-2,
        )
        np.testing.assert_allclose(
            np.asarray(streamed.explained_variance_),
            np.asarray(ref.explained_variance_), rtol=5e-2,
        )

    def test_bounded_peak_memory(self, rng, mesh):
        import tracemalloc

        from dask_ml_tpu.decomposition import TruncatedSVD

        n, d = 4000, 2000
        blocks = self._sparse_blocks(rng, n=n, d=d, block=200, density=0.01)
        dense_bytes = n * d * 4
        tracemalloc.start()
        TruncatedSVD(n_components=8, n_iter=4, random_state=0).fit_streamed(
            lambda: iter(blocks)
        )
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # the whole fit must stay well under one dense corpus copy
        assert peak < dense_bytes / 2, (peak, dense_bytes)

    def test_text_pipeline_end_to_end(self, mesh):
        from dask_ml_tpu.decomposition import TruncatedSVD
        from dask_ml_tpu.feature_extraction.text import HashingVectorizer

        docs = [f"word{i % 7} token{i % 13} common text" for i in range(500)]
        vec = HashingVectorizer(n_features=4096)
        svd = TruncatedSVD(n_components=4, n_iter=4, random_state=0)
        svd.fit_streamed(
            lambda: vec.stream_transform(docs), n_features=4096
        )
        assert np.asarray(svd.components_).shape == (4, 4096)
        emb = svd.transform(vec.transform(docs[:50]))
        assert np.asarray(emb).shape == (50, 4)

    def test_empty_stream_raises(self, mesh):
        from dask_ml_tpu.decomposition import TruncatedSVD

        with pytest.raises(ValueError, match="empty"):
            TruncatedSVD(n_components=2).fit_streamed(lambda: iter([]))


class TestIPCADonation:
    """ISSUE-12 aliasing regression: the rank-update's five-tensor state
    chain is donated (in-place in HBM), the batch buffer is not."""

    def test_update_donates_state_chain_not_batch(self):
        import jax.numpy as jnp

        from dask_ml_tpu.decomposition.incremental_pca import _update

        rng = np.random.RandomState(2)
        k, d, n = 3, 8, 64
        comp = jnp.zeros((k, d), jnp.float32)
        sv = jnp.zeros((k,), jnp.float32)
        mean = jnp.zeros((d,), jnp.float32)
        var = jnp.zeros((d,), jnp.float32)
        n_seen = jnp.asarray(0, jnp.int32)
        batch = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        out = _update(comp, sv, mean, var, n_seen, batch, k=k)
        for name, arr in (("components", comp), ("singular_values", sv),
                          ("mean", mean), ("var", var),
                          ("n_seen", n_seen)):
            assert arr.is_deleted(), f"{name} must be consumed in place"
        assert not batch.is_deleted(), "batch is deliberately NOT donated"
        assert out[0].shape == (k, d)

    def test_partial_fit_chain_consistent_under_donation(self):
        rng = np.random.RandomState(4)
        X1 = rng.normal(size=(50, 8)).astype(np.float32)
        X2 = rng.normal(size=(50, 8)).astype(np.float32)
        a = dd.IncrementalPCA(n_components=3)
        a.partial_fit(X1)
        comp_after_1 = np.asarray(a.components_)
        a.partial_fit(X2)  # donation must not corrupt the chain
        b = dd.IncrementalPCA(n_components=3)
        b.partial_fit(X1)
        np.testing.assert_allclose(np.asarray(b.components_),
                                   comp_after_1, rtol=1e-5)

import numpy as np
import pytest
import sklearn.preprocessing as sp

import dask_ml_tpu.preprocessing as dp
from dask_ml_tpu.core import shard_rows, unshard
from dask_ml_tpu.core.sharded import ShardedRows


@pytest.fixture
def X(rng):
    return rng.normal(loc=2.0, scale=3.0, size=(41, 5)).astype(np.float64)


class TestStandardScaler:
    def test_parity(self, X):
        ours = dp.StandardScaler().fit(X)
        theirs = sp.StandardScaler().fit(X)
        np.testing.assert_allclose(np.asarray(ours.mean_), theirs.mean_, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ours.scale_), theirs.scale_, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X), atol=1e-4
        )

    def test_sharded_in_sharded_out(self, X):
        s = shard_rows(X)
        ours = dp.StandardScaler().fit(s)
        out = ours.transform(s)
        assert isinstance(out, ShardedRows)
        theirs = sp.StandardScaler().fit(X)
        np.testing.assert_allclose(unshard(out), theirs.transform(X), atol=1e-4)

    def test_inverse_roundtrip(self, X):
        scaler = dp.StandardScaler().fit(X)
        np.testing.assert_allclose(
            np.asarray(scaler.inverse_transform(scaler.transform(X))), X, atol=1e-4
        )

    def test_constant_feature_no_nan(self):
        X = np.ones((20, 2), dtype=np.float32)
        out = np.asarray(dp.StandardScaler().fit(X).transform(X))
        assert np.isfinite(out).all()

    def test_with_mean_false(self, X):
        ours = dp.StandardScaler(with_mean=False).fit(X)
        theirs = sp.StandardScaler(with_mean=False).fit(X)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X), atol=1e-4
        )


class TestMinMaxScaler:
    def test_parity(self, X):
        ours = dp.MinMaxScaler().fit(X)
        theirs = sp.MinMaxScaler().fit(X)
        np.testing.assert_allclose(np.asarray(ours.data_min_), theirs.data_min_, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ours.data_max_), theirs.data_max_, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X), atol=1e-5
        )

    def test_feature_range(self, X):
        ours = dp.MinMaxScaler(feature_range=(-1, 1)).fit(X)
        out = np.asarray(ours.transform(X))
        assert out.min() >= -1 - 1e-5 and out.max() <= 1 + 1e-5

    def test_padding_does_not_leak_into_minmax(self, X):
        # padded rows are zeros; min must come from real rows only
        Xpos = np.abs(X) + 5.0  # all real values > 5, padding zeros would corrupt min
        s = shard_rows(Xpos)
        ours = dp.MinMaxScaler().fit(s)
        np.testing.assert_allclose(np.asarray(ours.data_min_), Xpos.min(0), rtol=1e-5)

    def test_inverse_roundtrip(self, X):
        scaler = dp.MinMaxScaler().fit(X)
        np.testing.assert_allclose(
            np.asarray(scaler.inverse_transform(scaler.transform(X))), X, atol=1e-4
        )


class TestRobustScaler:
    def test_parity(self, X):
        ours = dp.RobustScaler().fit(X)
        theirs = sp.RobustScaler().fit(X)
        np.testing.assert_allclose(np.asarray(ours.center_), theirs.center_, atol=1e-4)
        np.testing.assert_allclose(np.asarray(ours.scale_), theirs.scale_, rtol=1e-3)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X), atol=1e-3
        )

    def test_bad_quantile_range(self, X):
        with pytest.raises(ValueError, match="Invalid quantile_range"):
            dp.RobustScaler(quantile_range=(80, 20)).fit(X)


class TestQuantileTransformer:
    def test_uniform_output(self, X):
        ours = dp.QuantileTransformer(n_quantiles=41).fit(X)
        out = np.asarray(ours.transform(X))
        assert out.min() >= 0 and out.max() <= 1
        theirs = sp.QuantileTransformer(n_quantiles=41).fit(X)
        np.testing.assert_allclose(out, theirs.transform(X), atol=5e-2)

    def test_normal_output(self, X):
        ours = dp.QuantileTransformer(n_quantiles=41, output_distribution="normal").fit(X)
        out = np.asarray(ours.transform(X))
        assert np.isfinite(out).all()

    def test_inverse_roundtrip(self, X):
        qt = dp.QuantileTransformer(n_quantiles=41).fit(X)
        np.testing.assert_allclose(
            np.asarray(qt.inverse_transform(qt.transform(X))), X, atol=1e-2
        )

    def test_bad_distribution(self, X):
        with pytest.raises(ValueError, match="output_distribution"):
            dp.QuantileTransformer(output_distribution="cauchy").fit(X)


class TestLabelEncoder:
    def test_parity(self):
        y = np.array([3, 1, 3, 7, 1])
        ours = dp.LabelEncoder().fit(y)
        theirs = sp.LabelEncoder().fit(y)
        np.testing.assert_array_equal(ours.classes_, theirs.classes_)
        np.testing.assert_array_equal(np.asarray(ours.transform(y)), theirs.transform(y))

    def test_string_labels(self):
        y = np.array(["b", "a", "b", "c"])
        enc = dp.LabelEncoder().fit(y)
        np.testing.assert_array_equal(np.asarray(enc.transform(y)), [1, 0, 1, 2])
        np.testing.assert_array_equal(enc.inverse_transform([1, 0, 2]), ["b", "a", "c"])

    def test_unseen_label_raises(self):
        enc = dp.LabelEncoder().fit(np.array([0, 1]))
        with pytest.raises(ValueError, match="unseen"):
            enc.transform(np.array([2]))

    def test_2d_raises(self):
        with pytest.raises(ValueError, match="1d"):
            dp.LabelEncoder().fit(np.ones((3, 2)))


class TestBlockTransformer:
    def test_applies_function(self, X):
        bt = dp.BlockTransformer(lambda a: a * 2.0)
        np.testing.assert_allclose(np.asarray(bt.fit(X).transform(X)), X * 2.0, rtol=1e-6)

    def test_sharded(self, X):
        s = shard_rows(X)
        out = dp.BlockTransformer(lambda a: a + 1.0).fit_transform(s)
        assert isinstance(out, ShardedRows)
        np.testing.assert_allclose(unshard(out), X + 1.0, rtol=1e-6)


class TestReviewRegressions:
    def test_minmax_integer_input(self):
        X = np.arange(10).reshape(5, 2)
        import sklearn.preprocessing as sp
        ours = dp.MinMaxScaler().fit(X)
        theirs = sp.MinMaxScaler().fit(X)
        np.testing.assert_allclose(np.asarray(ours.transform(X)), theirs.transform(X), atol=1e-6)

    def test_block_transformer_validate(self):
        bt = dp.BlockTransformer(lambda a: a, validate=True)
        with pytest.raises(ValueError):
            bt.transform(np.arange(5.0))  # 1-D rejected when validate=True


class TestApproxQuantiles:
    """Merge-based quantile sketch (VERDICT round-1 weak #9; SURVEY §7
    hard-part (d)): histogram-merge path kicks in past the row threshold
    and matches the exact quantiles to bin resolution."""

    def test_hist_matches_exact(self, rng, mesh):
        import jax.numpy as jnp

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.preprocessing.data import (
            _hist_quantiles,
            _masked_quantiles,
        )

        X = rng.normal(size=(20_000, 4)).astype(np.float32) * [1, 10, 0.1, 100]
        s = shard_rows(X)
        probs = [0.25, 0.5, 0.75]
        exact = np.asarray(_masked_quantiles(s.data, s.mask, probs, method="exact"))
        approx = np.asarray(_hist_quantiles(s.data, s.mask, jnp.asarray(probs)))
        spread = X.max(axis=0) - X.min(axis=0)
        assert np.all(np.abs(exact - approx) <= spread / 8192 * 4 + 1e-6)

    def test_threshold_switches_methods(self, rng, mesh, monkeypatch):
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.preprocessing import RobustScaler

        monkeypatch.setenv("DASK_ML_TPU_EXACT_QUANTILE_MAX_ROWS", "100")
        X = rng.normal(size=(5000, 3)).astype(np.float32)
        s = shard_rows(X)
        rs = RobustScaler().fit(s)  # histogram path (5000 > 100)
        med = np.median(X, axis=0)
        np.testing.assert_allclose(np.asarray(rs.center_), med, atol=0.01)
        iqr = np.percentile(X, 75, axis=0) - np.percentile(X, 25, axis=0)
        np.testing.assert_allclose(np.asarray(rs.scale_), iqr, rtol=0.02)

    def test_masked_rows_excluded(self, rng, mesh):
        import jax.numpy as jnp

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.preprocessing.data import _hist_quantiles

        X = rng.normal(size=(999, 2)).astype(np.float32)  # pad+mask path
        s = shard_rows(X)
        # poison would-be pad contributions: approx median must track the
        # REAL rows only
        got = np.asarray(_hist_quantiles(s.data, s.mask, jnp.asarray([0.5])))
        np.testing.assert_allclose(got[0], np.median(X, axis=0), atol=0.01)

    def test_outlier_robust_sketch(self, rng, mesh):
        # one 1e9 outlier must not collapse the sketch's resolution on a
        # [0,1]-scale bulk: the refined passes re-focus the histogram
        import jax.numpy as jnp

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.preprocessing.data import _hist_quantiles

        X = rng.uniform(0, 1, size=(50_000, 2)).astype(np.float32)
        X[0, 0] = 1e9
        X[1, 1] = -1e9
        s = shard_rows(X)
        got = np.asarray(
            _hist_quantiles(s.data, s.mask, jnp.asarray([0.25, 0.5, 0.75]))
        )
        expect = np.percentile(X, [25, 50, 75], axis=0)
        np.testing.assert_allclose(got, expect, atol=5e-3)

    def test_outlier_with_full_prob_grid(self, rng, mesh):
        """QuantileTransformer's grid includes p=0 and p=1: those must map
        to the exact masked min/max WITHOUT pinning the refinement window
        to the outlier's bin (which would leave every interior quantile at
        one-bin-of-the-full-range resolution)."""
        import jax.numpy as jnp

        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.preprocessing.data import _hist_quantiles

        X = rng.uniform(0, 1, size=(50_000, 2)).astype(np.float32)
        X[0, 0] = 1e9
        X[1, 1] = -1e9
        s = shard_rows(X)
        probs = np.linspace(0.0, 1.0, 101)
        got = np.asarray(_hist_quantiles(s.data, s.mask, jnp.asarray(probs)))
        expect = np.percentile(X, probs * 100, axis=0).astype(np.float32)
        # endpoints exact
        np.testing.assert_allclose(got[0], X.min(axis=0), rtol=1e-6)
        np.testing.assert_allclose(got[-1], X.max(axis=0), rtol=1e-6)
        # interior quantiles resolve the [0,1] bulk despite the 1e9 range
        np.testing.assert_allclose(got[1:-1], expect[1:-1], atol=5e-3)

    def test_quantile_transformer_sketch_path(self, rng, mesh, monkeypatch):
        """End-to-end: QuantileTransformer past the row threshold uses the
        sketch and still produces a near-uniform output on outlier data."""
        monkeypatch.setenv("DASK_ML_TPU_EXACT_QUANTILE_MAX_ROWS", "1000")
        from dask_ml_tpu.core import shard_rows
        from dask_ml_tpu.preprocessing import QuantileTransformer

        X = rng.uniform(0, 1, size=(20_000, 2)).astype(np.float32)
        X[0, 0] = 1e9
        s = shard_rows(X)
        qt = QuantileTransformer(n_quantiles=101).fit(s)
        out = np.asarray(qt.transform(s).unpad())
        # the bulk must spread over [0,1], not collapse to ~0
        assert np.percentile(out[:, 0], 50) == pytest.approx(0.5, abs=0.05)
        assert np.percentile(out[:, 1], 50) == pytest.approx(0.5, abs=0.05)


class TestMaxAbsScaler:
    def test_parity_with_sklearn(self, rng, mesh):
        import sklearn.preprocessing as skp

        from dask_ml_tpu.core import shard_rows, unshard
        from dask_ml_tpu.preprocessing import MaxAbsScaler

        X = rng.normal(size=(203, 5)).astype(np.float32) * [1, 10, 0.1, 5, 2]
        ours = MaxAbsScaler().fit(shard_rows(X))
        theirs = skp.MaxAbsScaler().fit(X)
        np.testing.assert_allclose(np.asarray(ours.scale_), theirs.scale_, rtol=1e-6)
        np.testing.assert_allclose(
            unshard(ours.transform(shard_rows(X))), theirs.transform(X), rtol=1e-5)
        np.testing.assert_allclose(
            unshard(ours.inverse_transform(ours.transform(shard_rows(X)))),
            X, rtol=1e-4, atol=1e-5)

    def test_zero_feature_safe(self, mesh):
        from dask_ml_tpu.preprocessing import MaxAbsScaler

        X = np.zeros((10, 2), np.float32)
        out = MaxAbsScaler().fit(X).transform(X)
        assert np.isfinite(np.asarray(out)).all()


class TestNormalizer:
    @pytest.mark.parametrize("norm", ["l1", "l2", "max"])
    def test_parity_with_sklearn(self, rng, mesh, norm):
        import sklearn.preprocessing as skp

        from dask_ml_tpu.core import shard_rows, unshard
        from dask_ml_tpu.preprocessing import Normalizer

        X = rng.normal(size=(101, 4)).astype(np.float32)
        X[3] = 0.0  # zero row stays zero
        ours = unshard(Normalizer(norm=norm).fit(shard_rows(X)).transform(shard_rows(X)))
        theirs = skp.Normalizer(norm=norm).fit(X).transform(X)
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    def test_bad_norm(self):
        from dask_ml_tpu.preprocessing import Normalizer

        with pytest.raises(ValueError, match="norm"):
            Normalizer(norm="l3").fit(np.ones((3, 2), np.float32))


class TestHistQuantileWindowFallback:
    def test_endpoint_only_probs_survive_refinement(self, rng, mesh):
        # probs with NO interior entries used to invert the refinement
        # window (bmin > bmax); the window must stay the genuine full
        # span so every pass's histogram remains valid
        import jax.numpy as jnp

        from dask_ml_tpu.preprocessing.data import _hist_quantiles

        x = rng.normal(size=(512, 3)).astype(np.float32) * 100
        mask = np.ones(512, np.float32)
        vals = np.asarray(_hist_quantiles(
            jnp.asarray(x), jnp.asarray(mask),
            jnp.asarray([0.0, 1.0], np.float32)))
        np.testing.assert_allclose(vals[0], x.min(axis=0), rtol=1e-6)
        np.testing.assert_allclose(vals[1], x.max(axis=0), rtol=1e-6)

    def test_mixed_probs_interior_still_refined(self, rng, mesh):
        import jax.numpy as jnp

        from dask_ml_tpu.preprocessing.data import _hist_quantiles

        # outlier-heavy column: refinement must still resolve the median
        x = rng.normal(size=(4096, 1)).astype(np.float32)
        x[0, 0] = 1e9
        mask = np.ones(4096, np.float32)
        vals = np.asarray(_hist_quantiles(
            jnp.asarray(x), jnp.asarray(mask),
            jnp.asarray([0.0, 0.5, 1.0], np.float32)))
        med = np.median(x[:, 0])
        assert abs(vals[1, 0] - med) < 2e-3

"""Encoder suite tests: parity vs sklearn / pandas semantics.

Mirrors the reference tests for ``dask_ml/preprocessing/_encoders.py`` and
the categorical transformers in ``dask_ml/preprocessing/data.py``.
"""

import numpy as np
import pandas as pd
import pytest
import sklearn.preprocessing as sp

import dask_ml_tpu.preprocessing as dp
from dask_ml_tpu.core import shard_rows


@pytest.fixture
def Xcat(rng):
    return rng.randint(0, 4, size=(37, 3)).astype(np.float32)


@pytest.fixture
def df():
    return pd.DataFrame(
        {
            "A": pd.Categorical(["a", "b", "c", "a", "b"], categories=["a", "b", "c"]),
            "B": ["x", "y", "x", "y", "x"],
            "C": [1.0, 2.0, 3.0, 4.0, 5.0],
        }
    )


class TestOneHotEncoder:
    def test_parity_numeric(self, Xcat):
        ours = dp.OneHotEncoder().fit(Xcat)
        theirs = sp.OneHotEncoder(sparse_output=False).fit(Xcat)
        np.testing.assert_allclose(
            np.asarray(ours.transform(Xcat)), theirs.transform(Xcat)
        )
        for a, b in zip(ours.categories_, theirs.categories_):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_sharded_input(self, Xcat):
        from dask_ml_tpu.core import unshard

        s = shard_rows(Xcat)
        ours = dp.OneHotEncoder().fit(s)
        theirs = sp.OneHotEncoder(sparse_output=False).fit(Xcat)
        np.testing.assert_allclose(unshard(ours.transform(s)), theirs.transform(Xcat))

    def test_handle_unknown_error(self, Xcat):
        enc = dp.OneHotEncoder().fit(Xcat)
        bad = Xcat.copy()
        bad[0, 0] = 99.0
        with pytest.raises(ValueError, match="unknown categories"):
            enc.transform(bad)

    def test_handle_unknown_ignore(self, Xcat):
        enc = dp.OneHotEncoder(handle_unknown="ignore").fit(Xcat)
        bad = Xcat.copy()
        bad[0, 0] = 99.0
        out = np.asarray(enc.transform(bad))
        n0 = len(enc.categories_[0])
        assert out[0, :n0].sum() == 0.0

    def test_inverse_transform(self, Xcat):
        enc = dp.OneHotEncoder().fit(Xcat)
        back = enc.inverse_transform(enc.transform(Xcat))
        np.testing.assert_allclose(back.astype(np.float32), Xcat)

    def test_strings(self):
        X = np.array([["a", "x"], ["b", "y"], ["a", "y"]], dtype=object)
        ours = dp.OneHotEncoder().fit(X)
        theirs = sp.OneHotEncoder(sparse_output=False).fit(X)
        np.testing.assert_allclose(np.asarray(ours.transform(X)), theirs.transform(X))
        np.testing.assert_array_equal(
            ours.get_feature_names_out(), theirs.get_feature_names_out()
        )

    def test_dataframe(self, df):
        enc = dp.OneHotEncoder().fit(df[["A", "B"]])
        out = enc.transform(df[["A", "B"]])
        assert isinstance(out, pd.DataFrame)
        assert list(out.columns) == ["A_a", "A_b", "A_c", "B_x", "B_y"]
        np.testing.assert_allclose(out["A_a"].to_numpy(), [1, 0, 0, 1, 0])

    def test_user_categories_unsorted_order(self):
        X = np.array([[0.0], [1.0], [2.0]])
        enc = dp.OneHotEncoder(categories=[np.array([2.0, 1.0, 0.0])]).fit(X)
        out = np.asarray(enc.transform(X))
        np.testing.assert_allclose(out, [[0, 0, 1], [0, 1, 0], [1, 0, 0]])

    def test_frame_column_mismatch_raises(self, df):
        enc = dp.OneHotEncoder().fit(df[["A", "B"]])
        with pytest.raises(ValueError, match="Column mismatch"):
            enc.transform(df[["B", "A"]])

    def test_sharded_in_sharded_out(self, Xcat):
        from dask_ml_tpu.core.sharded import ShardedRows

        enc = dp.OneHotEncoder().fit(Xcat)
        out = enc.transform(shard_rows(Xcat))
        assert isinstance(out, ShardedRows)

    def test_missing_values_fit(self):
        df = pd.DataFrame({"B": ["x", None, "y", "x"]})
        enc = dp.OneHotEncoder(handle_unknown="ignore").fit(df)
        np.testing.assert_array_equal(np.asarray(enc.categories_[0]), ["x", "y"])
        out = enc.transform(df)
        np.testing.assert_allclose(out.to_numpy(dtype=float)[1], [0, 0])

    def test_nan_numeric_fit(self):
        X = np.array([[0.0], [np.nan], [1.0]])
        enc = dp.OneHotEncoder(handle_unknown="ignore").fit(X)
        assert len(enc.categories_[0]) == 2
        out = np.asarray(enc.transform(X))
        np.testing.assert_allclose(out[1], [0, 0])

    def test_array_fit_frame_transform_raises(self, df):
        enc = dp.OneHotEncoder().fit(np.array([[0.0], [1.0]]))
        with pytest.raises(ValueError, match="fitted on an array"):
            enc.transform(pd.DataFrame({"a": [0.0, 1.0]}))

    def test_sparse_output(self, Xcat):
        import scipy.sparse

        enc = dp.OneHotEncoder(sparse_output=True).fit(Xcat)
        out = enc.transform(Xcat)
        assert scipy.sparse.issparse(out)
        theirs = sp.OneHotEncoder(sparse_output=False).fit(Xcat)
        np.testing.assert_allclose(out.toarray(), theirs.transform(Xcat))


class TestOrdinalEncoder:
    def test_parity_array(self, Xcat):
        ours = dp.OrdinalEncoder().fit(Xcat)
        theirs = sp.OrdinalEncoder().fit(Xcat)
        np.testing.assert_allclose(np.asarray(ours.transform(Xcat)), theirs.transform(Xcat))

    def test_inverse_array(self, Xcat):
        enc = dp.OrdinalEncoder().fit(Xcat)
        back = enc.inverse_transform(enc.transform(Xcat))
        np.testing.assert_allclose(back.astype(np.float32), Xcat)

    def test_sharded_in_sharded_out(self, Xcat):
        from dask_ml_tpu.core import unshard
        from dask_ml_tpu.core.sharded import ShardedRows

        s = shard_rows(Xcat)
        enc = dp.OrdinalEncoder().fit(s)
        out = enc.transform(s)
        assert isinstance(out, ShardedRows)
        theirs = sp.OrdinalEncoder().fit(Xcat)
        np.testing.assert_allclose(unshard(out), theirs.transform(Xcat))

    def test_dataframe_roundtrip(self, df):
        enc = dp.OrdinalEncoder().fit(df)
        out = enc.transform(df)
        assert list(enc.categorical_columns_) == ["A", "B"]
        assert out["A"].tolist() == [0, 1, 2, 0, 1]
        assert (out["C"] == df["C"]).all()
        back = enc.inverse_transform(out)
        assert back["A"].tolist() == df["A"].tolist()
        assert back["B"].tolist() == df["B"].tolist()


class TestCategorizer:
    def test_categorizes_object_columns(self, df):
        cat = dp.Categorizer().fit(df)
        out = cat.transform(df)
        assert isinstance(out["B"].dtype, pd.CategoricalDtype)
        assert isinstance(out["A"].dtype, pd.CategoricalDtype)
        assert out["C"].dtype == np.float64
        assert set(cat.categories_) == {"A", "B"}

    def test_transform_uses_fitted_categories(self, df):
        cat = dp.Categorizer().fit(df)
        df2 = df.copy()
        df2["B"] = ["x", "x", "x", "x", "x"]
        out = cat.transform(df2)
        assert list(out["B"].dtype.categories) == ["x", "y"]

    def test_columns_subset(self, df):
        cat = dp.Categorizer(columns=["B"]).fit(df)
        out = cat.transform(df)
        assert set(cat.categories_) == {"B"}
        assert isinstance(out["B"].dtype, pd.CategoricalDtype)

    def test_rejects_array(self, rng):
        with pytest.raises(TypeError):
            dp.Categorizer().fit(rng.normal(size=(5, 2)))


class TestDummyEncoder:
    def test_basic(self, df):
        df = dp.Categorizer().fit_transform(df)
        enc = dp.DummyEncoder().fit(df)
        out = enc.transform(df)
        assert "A_a" in out.columns and "B_x" in out.columns and "C" in out.columns
        np.testing.assert_allclose(out["A_b"].to_numpy(dtype=float), [0, 1, 0, 0, 1])

    def test_inverse(self, df):
        df = dp.Categorizer().fit_transform(df)
        enc = dp.DummyEncoder().fit(df)
        back = enc.inverse_transform(enc.transform(df))
        assert back["A"].tolist() == df["A"].tolist()
        assert back["B"].tolist() == df["B"].tolist()
        np.testing.assert_allclose(back["C"].to_numpy(), df["C"].to_numpy())

    def test_drop_first(self, df):
        df = dp.Categorizer().fit_transform(df)
        enc = dp.DummyEncoder(drop_first=True).fit(df)
        out = enc.transform(df)
        assert "A_a" not in out.columns and "A_b" in out.columns
        back = enc.inverse_transform(out)
        assert back["A"].tolist() == df["A"].tolist()

    def test_non_categorical_raises(self, df):
        with pytest.raises(ValueError, match="not categorical"):
            dp.DummyEncoder(columns=["B"]).fit(df)


class TestPolynomialFeatures:
    @pytest.mark.parametrize("degree", [2, 3])
    @pytest.mark.parametrize("interaction_only", [False, True])
    @pytest.mark.parametrize("include_bias", [False, True])
    def test_parity(self, rng, degree, interaction_only, include_bias):
        X = rng.normal(size=(23, 4)).astype(np.float64)
        ours = dp.PolynomialFeatures(
            degree=degree, interaction_only=interaction_only, include_bias=include_bias
        ).fit(X)
        theirs = sp.PolynomialFeatures(
            degree=degree, interaction_only=interaction_only, include_bias=include_bias
        ).fit(X)
        assert ours.n_output_features_ == theirs.n_output_features_
        np.testing.assert_array_equal(ours.powers_, theirs.powers_)
        np.testing.assert_allclose(
            np.asarray(ours.transform(X)), theirs.transform(X), rtol=1e-5
        )

    def test_feature_names(self, rng):
        X = rng.normal(size=(5, 3))
        ours = dp.PolynomialFeatures().fit(X)
        theirs = sp.PolynomialFeatures().fit(X)
        np.testing.assert_array_equal(
            ours.get_feature_names_out(), theirs.get_feature_names_out()
        )

    def test_sharded_in_sharded_out(self, rng):
        from dask_ml_tpu.core.sharded import ShardedRows

        X = rng.normal(size=(19, 3)).astype(np.float32)
        s = shard_rows(X)
        out = dp.PolynomialFeatures().fit(s).transform(s)
        assert isinstance(out, ShardedRows)
        theirs = sp.PolynomialFeatures().fit_transform(X)
        from dask_ml_tpu.core import unshard

        np.testing.assert_allclose(unshard(out), theirs, rtol=1e-4)

    def test_feature_count_mismatch_raises(self, rng):
        pf = dp.PolynomialFeatures().fit(rng.normal(size=(4, 3)))
        with pytest.raises(ValueError, match="features"):
            pf.transform(rng.normal(size=(4, 2)))

    def test_preserve_dataframe(self, rng):
        X = pd.DataFrame(rng.normal(size=(7, 2)), columns=["u", "v"])
        out = dp.PolynomialFeatures(preserve_dataframe=True).fit(X).transform(X)
        assert isinstance(out, pd.DataFrame)
        assert list(out.columns) == ["1", "u", "v", "u^2", "u v", "v^2"]

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu.core import (
    DATA_AXIS,
    ShardedRows,
    data_axis_size,
    device_mesh,
    get_mesh,
    shard_rows,
    unshard,
    use_mesh,
)
from dask_ml_tpu.core.sharded import masked_mean, masked_sum, masked_var
from dask_ml_tpu.utils import handle_zeros_in_scale, svd_flip


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_default_mesh_covers_devices():
    mesh = get_mesh()
    assert data_axis_size(mesh) * mesh.shape["model"] == 8


def test_use_mesh_scoping():
    small = device_mesh(4)
    with use_mesh(small):
        assert get_mesh() is small
    assert get_mesh() is not small


@pytest.mark.parametrize("n", [16, 17, 23, 8])
def test_shard_rows_pads_and_masks(n):
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    s = shard_rows(x)
    assert s.n_samples == n
    assert s.padded % data_axis_size() == 0
    assert float(jnp.sum(s.mask)) == n
    np.testing.assert_array_equal(unshard(s), x)


def test_sharding_is_row_partitioned():
    x = np.ones((16, 4), dtype=np.float32)
    s = shard_rows(x)
    spec = s.data.sharding.spec
    assert spec[0] == DATA_AXIS


def test_masked_reductions_match_numpy():
    rng = np.random.RandomState(0)
    x = rng.normal(size=(37, 5)).astype(np.float32)
    s = shard_rows(x)
    np.testing.assert_allclose(
        np.asarray(masked_sum(s.data, s.mask)), x.sum(0), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(masked_mean(s.data, s.mask)), x.mean(0), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(masked_var(s.data, s.mask)), x.var(0), rtol=1e-4
    )


def test_masked_reduction_compiles_once_under_jit():
    x = np.ones((24, 2), dtype=np.float32)
    s = shard_rows(x)
    out = jax.jit(masked_sum)(s.data, s.mask)
    np.testing.assert_allclose(np.asarray(out), [24.0, 24.0])


def test_handle_zeros_in_scale():
    scale = jnp.array([1.0, 0.0, 2.0])
    out = np.asarray(handle_zeros_in_scale(scale))
    np.testing.assert_array_equal(out, [1.0, 1.0, 2.0])


def test_svd_flip_deterministic_signs():
    rng = np.random.RandomState(1)
    a = rng.normal(size=(20, 4))
    u, s, vt = np.linalg.svd(a, full_matrices=False)
    u1, v1 = svd_flip(jnp.asarray(u), jnp.asarray(vt))
    u2, v2 = svd_flip(jnp.asarray(-u), jnp.asarray(-vt))
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(u1) * s @ np.asarray(v1), a, atol=1e-5
    )


def test_sharded_rows_is_frozen():
    s = shard_rows(np.ones((8, 2), dtype=np.float32))
    assert isinstance(s, ShardedRows)
    with pytest.raises(Exception):
        s.n_samples = 5

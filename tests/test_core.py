import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu.core import (
    DATA_AXIS,
    ShardedRows,
    data_axis_size,
    device_mesh,
    get_mesh,
    shard_rows,
    unshard,
    use_mesh,
)
from dask_ml_tpu.core.sharded import masked_mean, masked_sum, masked_var
from dask_ml_tpu.utils import handle_zeros_in_scale, svd_flip


def test_harness_device_count_applied(n_devices):
    if n_devices is None:
        pytest.skip("TPU mode: physical chip count, no knob to assert")
    assert len(jax.devices()) == n_devices


def test_default_mesh_covers_devices():
    mesh = get_mesh()
    assert (data_axis_size(mesh) * mesh.shape["model"]
            == len(jax.devices()))


def test_use_mesh_scoping():
    small = device_mesh(4)
    with use_mesh(small):
        assert get_mesh() is small
    assert get_mesh() is not small


@pytest.mark.parametrize("n", [16, 17, 23, 8])
def test_shard_rows_pads_and_masks(n):
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    s = shard_rows(x)
    assert s.n_samples == n
    assert s.padded % data_axis_size() == 0
    assert float(jnp.sum(s.mask)) == n
    np.testing.assert_array_equal(unshard(s), x)


def test_sharding_is_row_partitioned():
    x = np.ones((16, 4), dtype=np.float32)
    s = shard_rows(x)
    from conftest import spec_axis

    assert spec_axis(s.data.sharding.spec[0]) == DATA_AXIS


def test_masked_reductions_match_numpy():
    rng = np.random.RandomState(0)
    x = rng.normal(size=(37, 5)).astype(np.float32)
    s = shard_rows(x)
    np.testing.assert_allclose(
        np.asarray(masked_sum(s.data, s.mask)), x.sum(0), rtol=1e-5
    )
    # atol floor: the anchor-shifted mean rounds differently from
    # np.mean by ~1 ulp of the spread, which for a near-zero column
    # mean exceeds any pure-rtol bound
    np.testing.assert_allclose(
        np.asarray(masked_mean(s.data, s.mask)), x.mean(0), rtol=1e-5,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(masked_var(s.data, s.mask)), x.var(0), rtol=1e-4
    )


def test_masked_reduction_compiles_once_under_jit():
    x = np.ones((24, 2), dtype=np.float32)
    s = shard_rows(x)
    out = jax.jit(masked_sum)(s.data, s.mask)
    np.testing.assert_allclose(np.asarray(out), [24.0, 24.0])


def test_handle_zeros_in_scale():
    scale = jnp.array([1.0, 0.0, 2.0])
    out = np.asarray(handle_zeros_in_scale(scale))
    np.testing.assert_array_equal(out, [1.0, 1.0, 2.0])


def test_svd_flip_deterministic_signs():
    rng = np.random.RandomState(1)
    a = rng.normal(size=(20, 4))
    u, s, vt = np.linalg.svd(a, full_matrices=False)
    u1, v1 = svd_flip(jnp.asarray(u), jnp.asarray(vt))
    u2, v2 = svd_flip(jnp.asarray(-u), jnp.asarray(-vt))
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(u1) * s @ np.asarray(v1), a, atol=1e-5
    )


def test_sharded_rows_is_frozen():
    s = shard_rows(np.ones((8, 2), dtype=np.float32))
    assert isinstance(s, ShardedRows)
    with pytest.raises(Exception):
        s.n_samples = 5


class TestChunkHelpers:
    """Reference: ``dask_ml/utils.py :: check_chunks / check_matching_blocks /
    slice_columns`` — the chunk-spec trio, re-done for the row-shard layout."""

    def test_check_chunks_auto(self):
        from dask_ml_tpu.utils import check_chunks

        assert check_chunks(160) == 10  # <=16 blocks
        assert check_chunks(5) == 1

    def test_check_chunks_int_and_tuple(self):
        from dask_ml_tpu.utils import check_chunks

        assert check_chunks(100, 4, 25) == 25
        assert check_chunks(100, 4, (25, 4)) == 25
        with pytest.raises(ValueError, match="column chunking"):
            check_chunks(100, 4, (25, 2))
        with pytest.raises(ValueError, match="positive"):
            check_chunks(100, 4, 0)

    def test_check_matching_blocks(self):
        from dask_ml_tpu.utils import check_matching_blocks

        a = shard_rows(np.ones((20, 2), dtype=np.float32))
        b = shard_rows(np.ones((20, 3), dtype=np.float32))
        check_matching_blocks(a, b)  # same layout: fine
        c = shard_rows(np.ones((21, 2), dtype=np.float32))
        with pytest.raises(ValueError, match="[Ii]nconsistent"):
            check_matching_blocks(a, c)

    def test_slice_columns_array_and_sharded(self):
        import pandas as pd

        from dask_ml_tpu.utils import slice_columns

        x = np.arange(24, dtype=np.float32).reshape(6, 4)
        np.testing.assert_array_equal(
            slice_columns(x, [1, 3]), x[:, [1, 3]]
        )
        assert slice_columns(x, None) is x
        s = shard_rows(x)
        out = slice_columns(s, [0, 2])
        assert isinstance(out, ShardedRows) and out.n_samples == 6
        np.testing.assert_array_equal(unshard(out), x[:, [0, 2]])
        df = pd.DataFrame(x, columns=list("abcd"))
        assert list(slice_columns(df, ["b", "d"]).columns) == ["b", "d"]

    def test_slice_columns_boolean_mask(self):
        from dask_ml_tpu.utils import slice_columns

        x = np.arange(24, dtype=np.float32).reshape(6, 4)
        mask = np.array([True, False, True, False])
        np.testing.assert_array_equal(
            unshard(slice_columns(shard_rows(x), mask)), x[:, mask]
        )

    def test_partial_fit_accepts_tuple_chunks(self):
        from sklearn.linear_model import SGDClassifier as SkSGD

        from dask_ml_tpu import _partial

        rng = np.random.RandomState(0)
        x = rng.rand(60, 4).astype(np.float32)
        y = (rng.rand(60) > 0.5).astype(np.int32)
        m = _partial.fit(
            SkSGD(random_state=0), x, y, chunk_size=(20, 4),
            classes=[0, 1],
        )
        assert hasattr(m, "coef_")
        with pytest.raises(ValueError, match="column chunking"):
            _partial.fit(SkSGD(), x, y, chunk_size=(20, 2), classes=[0, 1])

"""Native loader tests (C++ shim via ctypes)."""

import numpy as np
import pytest

from dask_ml_tpu import io as dio

# hypothesis gates ONLY the property classes below — a module-level
# importorskip silently dropped the entire deterministic loader suite on
# images without it (this one), which is exactly the coverage hole the
# ISSUE-3 satellite closes
try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - environment-dependent
    HAVE_HYPOTHESIS = False

    def given(*_a, **_k):  # placeholder decorators so the module imports
        return lambda fn: fn

    settings = given

    class _St:
        def __getattr__(self, _name):
            return lambda *a, **k: None

    st = _St()

needs_hypothesis = pytest.mark.skipif(
    not HAVE_HYPOTHESIS, reason="hypothesis not installed"
)


@pytest.fixture(scope="module")
def csv_file(tmp_path_factory):
    rng = np.random.RandomState(0)
    X = np.round(rng.normal(size=(537, 6)).astype(np.float32), 5)
    p = tmp_path_factory.mktemp("io") / "data.csv"
    np.savetxt(p, X, delimiter=",", fmt="%.5f")
    return str(p), X


class TestCSV:
    def test_dims(self, csv_file):
        p, X = csv_file
        assert dio.csv_dims(p) == X.shape

    def test_read_matches_numpy(self, csv_file):
        p, X = csv_file
        out = dio.read_csv(p)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, X, rtol=1e-5)

    def test_multithreaded_identical(self, csv_file):
        p, X = csv_file
        np.testing.assert_array_equal(
            dio.read_csv(p, n_threads=1), dio.read_csv(p, n_threads=7)
        )

    def test_header_skipped(self, tmp_path):
        p = tmp_path / "h.csv"
        p.write_text("a,b\n1.5,2.5\n3.0,4.0\n")
        out = dio.read_csv(str(p), has_header=True)
        np.testing.assert_allclose(out, [[1.5, 2.5], [3.0, 4.0]])
        assert dio.csv_dims(str(p), has_header=True) == (2, 2)

    def test_malformed_raises(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1.0,2.0\nfoo,bar\n")
        with pytest.raises(OSError):
            dio.read_csv(str(p))

    def test_missing_file_raises(self):
        with pytest.raises(OSError):
            dio.csv_dims("/nonexistent/x.csv")

    def test_short_row_raises(self, tmp_path):
        # A row with fewer fields must NOT silently consume values from the
        # next line (strtof skips '\n' as whitespace).
        p = tmp_path / "short.csv"
        p.write_text("1.0,2.0\n3.0\n5.0,6.0\n")
        with pytest.raises(OSError):
            dio.read_csv(str(p))

    def test_long_row_raises(self, tmp_path):
        p = tmp_path / "long.csv"
        p.write_text("1.0,2.0\n3.0,4.0,9.9\n")
        with pytest.raises(OSError):
            dio.read_csv(str(p))

    def test_no_trailing_newline_ok(self, tmp_path):
        p = tmp_path / "nonl.csv"
        p.write_text("1.0,2.0\n3.0,4.0")
        out = dio.read_csv(str(p))
        np.testing.assert_allclose(out, [[1.0, 2.0], [3.0, 4.0]])

    def test_stream_blocks(self, csv_file):
        p, X = csv_file
        blocks = list(dio.stream_csv_blocks(p, 100))
        assert [b.shape[0] for b in blocks] == [100] * 5 + [37]
        np.testing.assert_allclose(np.vstack(blocks), X, rtol=1e-5)

    def test_sharded_ingest(self, csv_file, mesh):
        p, X = csv_file
        s = dio.read_csv_sharded(p)
        from dask_ml_tpu.core import unshard

        assert s.shape == X.shape
        np.testing.assert_allclose(unshard(s), X, rtol=1e-5)


class TestBinary:
    def test_roundtrip(self, tmp_path, rng):
        X = rng.normal(size=(64, 5)).astype(np.float32)
        p = tmp_path / "x.bin"
        X.tofile(p)
        out = dio.read_binary(str(p), (64, 5))
        np.testing.assert_array_equal(out, X)

    def test_offset(self, tmp_path, rng):
        X = rng.normal(size=(10, 4)).astype(np.float32)
        p = tmp_path / "x.bin"
        X.tofile(p)
        out = dio.read_binary(str(p), (5, 4), offset_bytes=5 * 4 * 4)
        np.testing.assert_array_equal(out, X[5:])

    def test_short_file_raises(self, tmp_path):
        p = tmp_path / "short.bin"
        np.zeros(3, dtype=np.float32).tofile(p)
        with pytest.raises(OSError):
            dio.read_binary(str(p), (100, 100))


class TestIncrementalPipeline:
    def test_stream_into_incremental(self, csv_file, mesh):
        """End-to-end: native loader blocks → Incremental partial_fit."""
        from sklearn.linear_model import SGDClassifier

        from dask_ml_tpu.wrappers import Incremental

        p, X = csv_file
        w = np.ones(X.shape[1])
        y = (X @ w > 0).astype(np.int32)
        inc = Incremental(SGDClassifier(random_state=0))
        lo = 0
        for block in dio.stream_csv_blocks(p, 128):
            inc.partial_fit(block, y[lo: lo + len(block)], classes=[0, 1])
            lo += len(block)
        acc = (inc.predict(X) == y).mean()
        assert acc > 0.8


class TestNativeStreamSession:
    def test_blocks_match_full_read(self, tmp_path, rng):
        p = tmp_path / "s.csv"
        X = rng.normal(size=(997, 5)).astype(np.float32)
        np.savetxt(p, X, delimiter=",", fmt="%.6f")
        full = dio.read_csv(str(p))
        blocks = list(dio.stream_csv_blocks(str(p), 100, prefetch=3))
        assert [b.shape[0] for b in blocks] == [100] * 9 + [97]
        np.testing.assert_array_equal(np.concatenate(blocks), full)

    def test_abandoned_generator_closes_cleanly(self, tmp_path, rng):
        p = tmp_path / "s.csv"
        np.savetxt(p, rng.normal(size=(500, 3)), delimiter=",", fmt="%.4f")
        gen = dio.stream_csv_blocks(str(p), 50, prefetch=2)
        next(gen)
        next(gen)
        gen.close()  # must join the native worker without hanging

    def test_malformed_row_errors(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1.0,2.0\n3.0\n5.0,6.0\n")
        with pytest.raises(OSError):
            list(dio.stream_csv_blocks(str(p), 2))

    def test_empty_file(self, tmp_path):
        p = tmp_path / "empty.csv"
        p.write_text("")
        assert list(dio.stream_csv_blocks(str(p), 10)) == []

    def test_error_surfaces_after_valid_prefix(self, tmp_path):
        """All valid blocks before a malformed row are yielded, THEN the
        error raises — deterministic prefix despite prefetch."""
        p = tmp_path / "mid.csv"
        lines = ["%d.0,%d.0" % (i, i) for i in range(10)]
        lines[7] = "bad_row"
        p.write_text("\n".join(lines) + "\n")
        got = []
        with pytest.raises(OSError):
            for b in dio.stream_csv_blocks(str(p), 2, prefetch=4):
                got.append(b)
        assert len(got) == 3  # rows 0-5 (3 full blocks before row 7's block)

    def test_zero_block_rows_rejected(self, tmp_path):
        p = tmp_path / "z.csv"
        p.write_text("1.0,2.0\n")
        with pytest.raises(ValueError, match="block_rows"):
            next(dio.stream_csv_blocks(str(p), 0))


class TestFastFloatParse:
    """The C++ fast field parser (Clinger fast path) must agree with
    Python's float() across the decimal forms numeric CSV actually
    contains, and fall back cleanly on the forms it rejects."""

    def test_adversarial_forms(self, tmp_path):
        fields = [
            "0", "-0", "1", "-1", "0.5", "-.5", "+.25", "3.", "1e0",
            "1E5", "-2.5e-3", "6.02214076e23", "1e-22", "9.999999e21",
            # fallback territory: >19 digits, big exponents, inf/nan
            "123456789012345678901234567890", "1e300", "1e-300",
            "-1.7976931348623157e308", "4.9e-324", "inf", "-inf", "nan",
            "0.000000000000000000001", "1234567.1234567890123",
            # hex floats: the fast path must punt these to strtof whole
            "0x1A", "-0X2p1", "0x0.8p1", "7", "8", "9",
        ]
        assert len(fields) % 5 == 0
        rows = [fields[i:i + 5] for i in range(0, len(fields), 5)]
        txt = "\n".join(",".join(r) for r in rows) + "\n"
        p = tmp_path / "adv.csv"
        p.write_text(txt)
        out = dio.read_csv(str(p))

        def pyfloat(v):
            try:
                return float(v)
            except ValueError:  # hex floats: Python needs fromhex
                return float.fromhex(v)

        expect = np.array(
            [[np.float32(pyfloat(v)) for v in r] for r in rows],
            dtype=np.float32)
        np.testing.assert_array_equal(
            np.nan_to_num(out, nan=12345.0),
            np.nan_to_num(expect, nan=12345.0))

    def test_random_float_roundtrip_property(self, tmp_path):
        # float32 values formatted the ways writers actually format them
        r = np.random.RandomState(3)
        vals = np.concatenate([
            r.normal(scale=10.0 ** r.randint(-20, 20, 500), size=500),
            r.rand(500), np.zeros(10),
        ]).astype(np.float32)
        vals = vals[: (len(vals) // 4) * 4].reshape(-1, 4)
        for fmt in ("%.6g", "%.9g", "%r", "%.17g"):
            p = tmp_path / "r.csv"
            if fmt == "%r":
                txt = "\n".join(
                    ",".join(repr(float(v)) for v in row) for row in vals)
            else:
                txt = "\n".join(
                    ",".join(fmt % v for v in row) for row in vals)
            p.write_text(txt + "\n")
            out = dio.read_csv(str(p))
            if fmt in ("%r", "%.9g", "%.17g"):
                # enough digits to round-trip float32 exactly
                np.testing.assert_array_equal(out, vals, err_msg=fmt)
            else:
                np.testing.assert_allclose(out, vals, rtol=1e-5,
                                           err_msg=fmt)


@needs_hypothesis
class TestWindowedStreamProperties:
    """Adversarial window-boundary coverage for the windowed streaming
    session (round 5: the session went from whole-file-resident to a
    moving window; every refill/compact/carry-over cycle is new code).
    DMLT_STREAM_WINDOW_BYTES shrinks the window to a few tens of bytes
    so tiny files exercise MANY windows, lines split across refills,
    blank lines at region starts, and missing trailing newlines."""

    @settings(max_examples=40, deadline=None)
    @given(
        seed=st.integers(0, 2**31 - 1),
        n_rows=st.integers(1, 40),
        n_cols=st.integers(1, 5),
        block_rows=st.integers(1, 7),
        window=st.integers(16, 200),
        trailing=st.booleans(),
        blanks=st.booleans(),
    )
    def test_stream_matches_whole_file_parse(
            self, seed, n_rows, n_cols, block_rows, window, trailing,
            blanks):
        import os
        import tempfile
        rng = np.random.RandomState(seed % (2**31 - 1))
        rows = rng.normal(size=(n_rows, n_cols)) * 10.0 ** rng.randint(
            -3, 4, size=(n_rows, n_cols))
        lines = [",".join(f"{v:.6g}" for v in r) for r in rows]
        if blanks:
            # blank lines anywhere (including the very start and between
            # window boundaries) must be skipped, as the whole-file
            # parser does
            out = []
            for ln in lines:
                if rng.rand() < 0.3:
                    out.append("")
                out.append(ln)
            if rng.rand() < 0.5:
                out.append("")
            lines = out
        text = "\n".join(lines)
        if trailing:
            text += "\n"
        with tempfile.NamedTemporaryFile(
                "w", suffix=".csv", delete=False) as f:
            f.write(text)
            p = f.name
        saved = os.environ.get("DMLT_STREAM_WINDOW_BYTES")
        os.environ["DMLT_STREAM_WINDOW_BYTES"] = str(window)
        try:
            got = [b.copy() for b in dio.stream_csv_blocks(p, block_rows)]
        finally:
            if saved is None:
                os.environ.pop("DMLT_STREAM_WINDOW_BYTES", None)
            else:
                os.environ["DMLT_STREAM_WINDOW_BYTES"] = saved
        stream = (np.vstack(got) if got
                  else np.zeros((0, n_cols), np.float32))
        whole = dio.read_csv(p)
        os.unlink(p)
        assert stream.shape == whole.shape, (stream.shape, whole.shape)
        np.testing.assert_array_equal(stream, whole)
        assert all(b.shape[0] <= block_rows for b in got)

    @settings(max_examples=15, deadline=None)
    @given(seed=st.integers(0, 2**31 - 1), window=st.integers(16, 120))
    def test_malformed_line_prefix_across_windows(self, seed, window):
        import os
        import tempfile
        """The deterministic-prefix error contract must hold at ANY
        window size: every full block before the first malformed line
        is delivered, then the error raises."""
        rng = np.random.RandomState(seed % (2**31 - 1))
        n = int(rng.randint(4, 30))
        bad = int(rng.randint(0, n))
        lines = [f"{i}.0,{i * 2}.0" for i in range(n)]
        lines[bad] = "not,numeric_at_all"
        with tempfile.NamedTemporaryFile(
                "w", suffix=".csv", delete=False) as f:
            f.write("\n".join(lines) + "\n")
            p = f.name
        saved = os.environ.get("DMLT_STREAM_WINDOW_BYTES")
        os.environ["DMLT_STREAM_WINDOW_BYTES"] = str(window)
        got = []
        try:
            with pytest.raises(OSError):
                for b in dio.stream_csv_blocks(p, 2):
                    got.append(b.copy())
        finally:
            if saved is None:
                os.environ.pop("DMLT_STREAM_WINDOW_BYTES", None)
            else:
                os.environ["DMLT_STREAM_WINDOW_BYTES"] = saved
            os.unlink(p)
        assert len(got) == bad // 2  # full blocks strictly before the bad row
        if got:
            np.testing.assert_array_equal(
                np.vstack(got)[:, 0],
                np.arange(bad // 2 * 2, dtype=np.float32))


class TestStreamEdgeCases:
    """ISSUE-3 satellite: reader edge cases x prefetch permutations —
    the stream contract must be depth-invariant and degenerate-safe."""

    def test_csv_empty_file(self, tmp_path):
        p = tmp_path / "empty.csv"
        p.write_text("")
        assert dio.csv_dims(str(p)) == (0, 0)
        assert list(dio.stream_csv_blocks(str(p), 10)) == []

    def test_csv_header_only(self, tmp_path):
        p = tmp_path / "h.csv"
        p.write_text("a,b\n")
        assert list(
            dio.stream_csv_blocks(str(p), 10, has_header=True)
        ) == []

    def test_csv_block_rows_exceed_n_rows(self, csv_file):
        p, X = csv_file
        blocks = list(dio.stream_csv_blocks(p, X.shape[0] * 10))
        assert len(blocks) == 1 and blocks[0].shape == X.shape
        np.testing.assert_allclose(blocks[0], X, rtol=1e-5)

    def test_csv_last_partial_block(self, csv_file):
        p, X = csv_file  # 537 rows: 2x250 + 37
        blocks = list(dio.stream_csv_blocks(p, 250))
        assert [b.shape[0] for b in blocks] == [250, 250, 37]
        np.testing.assert_allclose(np.vstack(blocks), X, rtol=1e-5)

    @pytest.mark.parametrize("prefetch", [1, 2, 4])
    def test_csv_prefetch_permutations_bit_identical(self, csv_file,
                                                     prefetch):
        """The native session's prefetch worker must never reorder or
        alter blocks: every depth is bit-identical to serial-ish depth 1
        at every block boundary (including the partial tail)."""
        p, X = csv_file
        base = [b.copy() for b in dio.stream_csv_blocks(p, 100, prefetch=1)]
        got = [b.copy() for b in dio.stream_csv_blocks(
            p, 100, prefetch=prefetch)]
        assert len(base) == len(got)
        for a, b in zip(base, got):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("depth", [0, 1, 3])
    def test_csv_pipeline_depth_permutations(self, csv_file, depth):
        """The PYTHON-level prefetch pipeline over the reader: same
        blocks, same order, at every DASK_ML_TPU_PREFETCH_DEPTH."""
        from dask_ml_tpu.pipeline import prefetch_blocks

        p, X = csv_file
        got = [
            b.copy() for b in prefetch_blocks(
                dio.stream_csv_blocks(p, 100), depth=depth)
        ]
        assert [b.shape[0] for b in got] == [100] * 5 + [37]
        np.testing.assert_allclose(np.vstack(got), X, rtol=1e-5)

    def test_binary_stream_roundtrip(self, tmp_path, rng):
        X = rng.normal(size=(257, 8)).astype(np.float32)
        p = tmp_path / "x.bin"
        X.tofile(p)
        blocks = list(dio.stream_binary_blocks(str(p), 100, 8))
        assert [b.shape[0] for b in blocks] == [100, 100, 57]
        np.testing.assert_array_equal(np.vstack(blocks), X)

    def test_binary_empty_file(self, tmp_path):
        p = tmp_path / "empty.bin"
        p.write_bytes(b"")
        assert list(dio.stream_binary_blocks(str(p), 10, 4)) == []

    def test_binary_block_rows_exceed_n_rows(self, tmp_path, rng):
        X = rng.normal(size=(7, 3)).astype(np.float32)
        p = tmp_path / "small.bin"
        X.tofile(p)
        blocks = list(dio.stream_binary_blocks(str(p), 1000, 3))
        assert len(blocks) == 1
        np.testing.assert_array_equal(blocks[0], X)

    def test_binary_trailing_partial_row_ignored(self, tmp_path):
        # 10 floats at n_features=4: 2 complete rows + 2 stray values
        np.arange(10, dtype=np.float32).tofile(tmp_path / "part.bin")
        blocks = list(
            dio.stream_binary_blocks(str(tmp_path / "part.bin"), 10, 4)
        )
        assert [b.shape for b in blocks] == [(2, 4)]
        np.testing.assert_array_equal(
            np.vstack(blocks), np.arange(8, dtype=np.float32).reshape(2, 4)
        )

    def test_binary_missing_file_raises(self):
        with pytest.raises(OSError):
            list(dio.stream_binary_blocks("/nonexistent/x.bin", 10, 4))

    @pytest.mark.parametrize("depth", [0, 2])
    def test_binary_pipeline_depth_bit_identical(self, tmp_path, rng,
                                                 depth):
        from dask_ml_tpu.pipeline import prefetch_blocks

        X = rng.normal(size=(530, 6)).astype(np.float32)
        p = tmp_path / "s.bin"
        X.tofile(p)
        got = [
            b.copy() for b in prefetch_blocks(
                dio.stream_binary_blocks(str(p), 128, 6), depth=depth)
        ]
        assert [b.shape[0] for b in got] == [128, 128, 128, 128, 18]
        np.testing.assert_array_equal(np.vstack(got), X)

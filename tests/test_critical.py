"""graftpath tests (ISSUE 15 tentpole): the causal critical-path
engine, its joins, and the acceptance criteria.

Covers: the interval-algebra layering on synthetic timelines (category
times sum to the wall EXACTLY, priority order is causal);
``run_report()["critical_path"]`` present with a non-"unknown" verdict
for a depth-2 streamed SGD fit, a concurrent Hyperband search, and a
serve closed-loop run; the per-request serve split pinned
(queue+window+device+fetch == request_s) under an armed sanitizer with
zero steady compiles; the data plane's reorder-queue wait counting as
FED (not idle) under graftscope; the ``data.*`` / ``search.round_s``
families scraping through ``/metrics`` as valid Prometheus text; the
flight-recorder dump showing OPEN device intervals; Perfetto flow
events linking host dispatch spans to device-lane slices; and the perf
ratchet's v3 overlap-efficiency floor + bottleneck pin semantics.
"""

import json
import re
import time
import urllib.request

import numpy as np
import pytest

from dask_ml_tpu import diagnostics, obs
from dask_ml_tpu.obs import critical, flight, perf, scope
from dask_ml_tpu.obs.spans import SpanRecord
from dask_ml_tpu.pipeline import stream_partial_fit


@pytest.fixture(autouse=True)
def _clean_books():
    if not obs.enabled():
        obs.enable()
    diagnostics.reset()
    yield
    obs.serve.stop()
    diagnostics.reset()


class _Leaf:
    def __init__(self, ready=False):
        self._ready = ready

    def is_ready(self):
        return self._ready


def _sgd_blocks(n_blocks=8, rows=16384, dim=32, parse_s=0.001, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(rows, dim)).astype(np.float32)
    w = rng.normal(size=dim)
    y = (X @ w > 0).astype(np.int32)
    for _ in range(n_blocks):
        if parse_s:
            time.sleep(parse_s)
        yield X, y


def _rec(name, t0, t1, span_id, parent_id=1, thread="t"):
    return SpanRecord("span", span_id, parent_id, name, t0, t1, thread,
                      {})


# -- the engine on synthetic timelines -----------------------------------

class TestIntervalAlgebra:
    def test_union_merges_and_sorts(self):
        u = critical._union([(5, 7), (1, 2), (1.5, 3), (7, 7)])
        assert u == [(1, 3), (5, 7)]
        assert critical._length(u) == pytest.approx(4.0)

    def test_overlap_two_pointer(self):
        xs = [(0, 4), (6, 9)]
        ys = [(2, 7), (8, 12)]
        assert critical._overlap(xs, ys) == pytest.approx(
            2 + 1 + 1)  # [2,4] + [6,7] + [8,9]

    def test_resolvers_strict_parse(self, monkeypatch):
        monkeypatch.setenv(critical.CRITICAL_TOL_ENV, "nope")
        with pytest.raises(ValueError, match="must be a number"):
            critical.resolve_tolerance()
        monkeypatch.setenv(critical.CRITICAL_TOL_ENV, "1.5")
        with pytest.raises(ValueError, match="in \\(0, 1\\)"):
            critical.resolve_tolerance()
        monkeypatch.setenv(critical.CRITICAL_TOL_ENV, "0.2")
        assert critical.resolve_tolerance() == 0.2
        assert critical.resolve_dominance(0.5) == 0.5


class TestSyntheticCriticalPath:
    def test_priority_layering_sums_to_wall_exactly(self):
        """Synthetic window [0, 10]: device [0,4] under a consumer
        compute span [0,4], a worker parse span [3,6] (1s hidden under
        the concurrent compute), stage [6,7], a stall [7,9] with no
        producer work over it, nothing in [9,10]."""
        root = _rec("pipeline.stream", 0.0, 10.0, 1, None)
        records = [
            root,
            _rec("pipeline.compute", 0.0, 4.0, 5, thread="consumer"),
            _rec("pipeline.parse", 3.0, 6.0, 2, thread="worker"),
            _rec("pipeline.stage", 6.0, 7.0, 3, thread="worker"),
            _rec("pipeline.stall", 7.0, 9.0, 4, thread="consumer"),
        ]
        device = [{"program": "p", "t0": 0.0, "t1": 4.0, "seq": 0}]
        cp = critical.critical_path(root, records=records,
                                    device=device, publish=False)
        cats = cp["categories"]
        assert cats["device"] == pytest.approx(4.0)
        assert cats["parse"] == pytest.approx(2.0)   # [4,6]: 1s hidden
        assert cats["stage"] == pytest.approx(1.0)
        assert cats["queue_wait"] == pytest.approx(2.0)
        assert cats["dispatch"] == pytest.approx(0.0)  # under device
        assert cats["idle_gap"] == pytest.approx(1.0)
        assert sum(cats.values()) == pytest.approx(cp["wall_s"])
        assert cp["within_tolerance"]
        assert cp["verdict"]["class"] == "device-bound"
        # worker host time [3,7] = 4s; [3,4] ran under the consumer's
        # concurrent compute span → 1s hidden
        assert cp["overlap_efficiency"] == pytest.approx(1.0 / 4.0)
        assert cp["plane"] == "fit"

    def test_depth0_single_thread_measures_zero_overlap(self):
        """Serial layout (everything on one thread): no overlap, even
        when a slack-extended device interval laps the next parse."""
        root = _rec("pipeline.stream", 0.0, 10.0, 1, None)
        records = [
            root,
            _rec("pipeline.compute", 0.0, 4.0, 2, thread="main"),
            _rec("pipeline.parse", 4.0, 6.0, 3, thread="main"),
        ]
        # device interval closed LATE (detection slack): laps the parse
        device = [{"program": "p", "t0": 0.0, "t1": 5.0, "seq": 0}]
        cp = critical.critical_path(root, records=records,
                                    device=device, publish=False)
        assert cp["overlap_efficiency"] == pytest.approx(0.0)

    def test_stall_covered_by_producer_work_attributes_to_cause(self):
        """A consumer stall overlapped by the worker's concurrent parse
        attributes to PARSE (the cause), not queue_wait."""
        root = _rec("pipeline.stream", 0.0, 10.0, 1, None)
        records = [
            root,
            _rec("pipeline.parse", 0.0, 8.0, 2, thread="worker"),
            _rec("pipeline.stall", 1.0, 7.0, 3, thread="consumer"),
        ]
        cp = critical.critical_path(root, records=records, device=[],
                                    publish=False)
        assert cp["categories"]["parse"] == pytest.approx(8.0)
        assert cp["categories"]["queue_wait"] == pytest.approx(0.0)
        assert cp["verdict"]["class"] == "parse-bound"

    def test_reader_truth_outranks_reorder_wait(self):
        """The worker's pipeline.parse wraps a reorder WAIT; the reader
        threads' data.parse is the concurrent truth — reader work
        claims its time, the uncovered wait is queue_wait, and the
        wrapper's residue stays parse."""
        root = _rec("pipeline.stream", 0.0, 10.0, 1, None)
        records = [
            root,
            # worker "parse" wrapping the whole pull (mostly waiting)
            _rec("pipeline.parse", 0.0, 10.0, 2, thread="worker"),
            # the wait itself, and the readers' real work over part
            _rec("data.queue_wait", 0.0, 8.0, 3, thread="worker"),
            _rec("data.parse", 0.0, 5.0, 4, thread="reader"),
        ]
        cp = critical.critical_path(root, records=records, device=[],
                                    publish=False)
        assert cp["categories"]["parse"] == pytest.approx(
            5.0 + 2.0)  # reader truth + wrapper residue [8,10]
        assert cp["categories"]["queue_wait"] == pytest.approx(3.0)
        assert cp["verdict"]["class"] == "parse-bound"

    def test_idle_dominant_refuses_verdict(self):
        root = _rec("pipeline.stream", 0.0, 10.0, 1, None)
        records = [root, _rec("pipeline.parse", 0.0, 1.0, 2)]
        cp = critical.critical_path(root, records=records, device=[],
                                    publish=False)
        assert cp["shares"]["idle_gap"] > 0.5
        assert cp["verdict"]["class"] == "unknown"
        assert "idle_gap" in cp["verdict"]["reason"]

    def test_container_spans_are_not_host_work(self):
        """A search.round container covering the window must not read
        as dispatch; an inner search.unit does."""
        root = _rec("search.fit", 0.0, 10.0, 1, None)
        records = [
            root,
            _rec("search.round", 0.0, 10.0, 2),
            _rec("search.unit", 0.0, 6.0, 3),
        ]
        cp = critical.critical_path(root, records=records, device=[],
                                    publish=False)
        assert cp["categories"]["dispatch"] == pytest.approx(6.0)
        assert cp["categories"]["idle_gap"] == pytest.approx(4.0)
        assert cp["plane"] == "search"

    def test_no_root_no_serve_is_explicit_unknown(self):
        obs.clear_spans()
        cp = critical.critical_path(publish=False)
        assert cp["plane"] is None
        assert cp["verdict"]["class"] == "unknown"

    def test_publish_lands_gauges_and_device_report_join(self):
        root = _rec("pipeline.stream", 0.0, 10.0, 1, None)
        device = [{"program": "p", "t0": 0.0, "t1": 9.0, "seq": 0}]
        cp = critical.critical_path(root, records=[root],
                                    device=device)
        assert cp["verdict"]["class"] == "device-bound"
        reg = obs.registry()
        assert reg.gauge("critical.bottleneck", "fit").value == \
            float(critical.BOTTLENECK_CLASSES.index("device-bound"))
        dev = scope.device_report()
        assert dev["critical"]["fit"]["verdict"] == "device-bound"
        # …and the gauge scrapes as valid Prometheus text
        text = obs.prometheus_text()
        assert "# TYPE critical_bottleneck gauge" in text
        assert 'critical_bottleneck{tag="fit"} 1.0' in text


# -- acceptance: the three planes ----------------------------------------

class TestRunReportCriticalPath:
    def test_depth2_streamed_fit_has_verdict(self):
        from dask_ml_tpu.linear_model import SGDClassifier

        model = SGDClassifier(random_state=0)
        stream_partial_fit(model, _sgd_blocks(4), depth=2,
                           fit_kwargs={"classes": np.array([0, 1])})
        diagnostics.reset()  # scope to the measured fit
        stream_partial_fit(model, _sgd_blocks(6), depth=2,
                           fit_kwargs={"classes": np.array([0, 1])})
        cp = diagnostics.run_report()["critical_path"]
        assert cp["plane"] == "fit"
        cats = cp["categories"]
        assert sum(cats.values()) == pytest.approx(
            cp["wall_s"], rel=cp["tolerance"])
        assert cp["within_tolerance"]
        assert cp["verdict"]["class"] != "unknown"
        assert cp["overlap_efficiency"] is not None
        # depth 2 with a sleeping parse: real hidden host time
        assert cp["overlap_efficiency"] > 0.1
        assert cp["evidence"]["top_spans"]

    @pytest.mark.slow
    def test_concurrent_hyperband_search_has_verdict(self):
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.model_selection import HyperbandSearchCV

        rng = np.random.RandomState(3)
        X = rng.normal(size=(20_000, 16)).astype(np.float32)
        y = (X @ rng.normal(size=16) > 0).astype(np.int32)
        hb = HyperbandSearchCV(
            SGDClassifier(random_state=0),
            {"loss": ["log_loss", "hinge"],
             "alpha": [1e-4, 1e-3, 1e-2]},
            max_iter=9, random_state=0, test_size=0.25)
        hb.fit(X, y, classes=np.array([0, 1]))
        cp = diagnostics.run_report()["critical_path"]
        assert cp["plane"] == "search"
        assert cp["root"] == "search.fit"
        assert sum(cp["categories"].values()) == pytest.approx(
            cp["wall_s"], rel=cp["tolerance"])
        assert cp["verdict"]["class"] != "unknown"

    def test_serve_closed_loop_has_verdict(self):
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.serve import ModelServer

        rng = np.random.RandomState(5)
        X = rng.normal(size=(256, 8)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        model = SGDClassifier(random_state=0)
        model.partial_fit(X, y, classes=np.array([0, 1]))
        diagnostics.reset()  # no fit root: the serve fallback path
        with ModelServer(label="t_cp", window_s=0.0) as srv:
            srv.load("m", model)
            for i in range(30):
                srv.predict("m", X[i % 64:i % 64 + 1])
        cp = diagnostics.run_report()["critical_path"]
        assert cp["plane"] == "serve"
        assert cp["requests"] >= 30
        assert cp["within_tolerance"]
        assert cp["verdict"]["class"] != "unknown"
        assert set(cp["categories"]) == {"queue", "window", "device",
                                         "fetch"}


class TestServePerRequestSplit:
    def test_split_pinned_under_armed_sanitizer(self, sanitizer):
        """Acceptance criterion: queue+window+device+fetch ==
        request_s (same-clock contiguous stamps, so the identity is
        exact, not approximate) under an armed sanitizer with zero
        steady compiles."""
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.serve import ModelServer

        rng = np.random.RandomState(11)
        X = rng.normal(size=(512, 16)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        model = SGDClassifier(random_state=0)
        model.partial_fit(X, y, classes=np.array([0, 1]))
        reg = obs.registry()
        with ModelServer(label="t_split", window_s=0.0) as srv:
            srv.load("m", model)
            srv.predict("m", X[:1])  # request path hot
            reg.reset(prefix="serve.request_s")
            reg.reset(prefix="serve.req_")
            with sanitizer.steady():
                for i in range(40):
                    srv.predict("m", X[i:i + 1])
        rep = sanitizer.report()
        assert rep["totals"]["steady_compiles"] == 0, rep["violations"]
        total = sum(
            reg.histogram(f"serve.req_{leg}_s", "m").sum
            for leg in ("queue", "window", "device", "fetch"))
        req = reg.histogram("serve.request_s", "m")
        assert req.count == 40
        assert reg.histogram("serve.req_queue_s", "m").count == 40
        assert total == pytest.approx(req.sum, rel=1e-6)
        sc = obs.serve_critical(publish=False)
        assert sc["within_tolerance"] and sc["coverage"] == \
            pytest.approx(1.0, abs=1e-3)

    def test_slowest_request_exemplar_in_flight_recorder(self):
        from dask_ml_tpu.linear_model import SGDClassifier
        from dask_ml_tpu.serve import ModelServer

        rng = np.random.RandomState(2)
        X = rng.normal(size=(64, 8)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        model = SGDClassifier(random_state=0)
        model.partial_fit(X, y, classes=np.array([0, 1]))
        with ModelServer(label="t_ex", window_s=0.0) as srv:
            srv.load("m", model)
            for i in range(10):
                srv.predict("m", X[i:i + 1])
        events = [e for e in flight.tail()
                  if e["name"] == "serve.slow_request"]
        assert events, "no slow-request exemplar recorded"
        ex = events[-1]["attrs"]
        # the exemplar carries the trace id and the full split
        assert ex["request"] >= 1 and ex["model"] == "m"
        parts = (ex["queue_ms"] + ex["window_ms"] + ex["device_ms"]
                 + ex["fetch_ms"])
        # each leg is rounded to a microsecond in the exemplar: the
        # identity holds to the rounding, not exactly
        assert parts == pytest.approx(ex["request_ms"], abs=0.005)


# -- the data plane (satellites 2 and 4) ---------------------------------

def _tiny_dataset(tmp_path, rows=4096, dim=8, shards=2,
                  block_rows=256):
    from dask_ml_tpu import data as _data

    rng = np.random.RandomState(0)
    X = rng.normal(size=(rows, dim)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    d = str(tmp_path / "ds")
    _data.write_dataset(d, X, y, shards=shards, block_rows=block_rows)
    return d


class TestDataPlaneHonesty:
    def test_reorder_queue_wait_counts_as_fed_not_idle(self, tmp_path):
        """Satellite: the honesty contract asserted for search
        queue-wait, applied to the data plane — while the consumer
        waits on the reorder queue behind slow readers, an in-flight
        device program keeps the graftscope lane BUSY (enqueue→ready):
        the wait reads as fed, never as device idle."""
        from dask_ml_tpu import data as _data

        d = _tiny_dataset(tmp_path)
        leaf = _Leaf(ready=False)
        cur = scope.cursor()
        scope.track("prog.during_ingest", time.perf_counter(), [leaf])
        ds = _data.ShardedDataset(d, key=0, readers=2,
                                  fetch_latency_s=0.005,
                                  label="fed_test")
        n = sum(xb.shape[0] for xb, yb in ds.iter_blocks(epoch=0))
        assert n == 4096
        leaf._ready = True
        assert scope.settle(5.0)
        dev = scope.device_report(since=cur)
        # ONE interval spanning the whole (slow, wait-heavy) stream:
        # zero idle, utilization 1.0 — queue wait counted as FED
        assert dev["dispatches"] == 1
        assert dev["utilization"] == pytest.approx(1.0)
        assert dev["idle_s"] == pytest.approx(0.0, abs=1e-6)
        assert dev["idle_gaps"] == []
        # …and the wait itself was measured on its own books
        qw = obs.registry().histogram("data.queue_wait_s", "fed_test")
        assert qw.count >= 1 and qw.sum > 0.0

    def test_data_families_and_search_round_scrape_via_endpoint(
            self, tmp_path):
        """Satellite: the data.* reader/reorder metrics and the
        search.round_s histogram export through a live /metrics
        endpoint as valid Prometheus text."""
        from dask_ml_tpu import data as _data
        from dask_ml_tpu.obs import serve as obs_serve

        d = _tiny_dataset(tmp_path)
        ds = _data.ShardedDataset(d, key=0, readers=2,
                                  fetch_latency_s=0.002,
                                  label="scrape_test")
        list(ds.iter_blocks(epoch=0))
        obs.registry().histogram("search.round_s").record(0.05)
        srv = obs_serve.start(port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=10) as resp:
            assert resp.status == 200
            text = resp.read().decode()
        # Prometheus validity: every line is a TYPE comment or a
        # sample with a legal name, optional labels, numeric value
        sample = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
            r'(\{[a-zA-Z0-9_]+="(\\.|[^"\\])*"'
            r'(,[a-zA-Z0-9_]+="(\\.|[^"\\])*")*\})? '
            r"(NaN|[-+0-9.e]+)$")
        for line in text.strip().splitlines():
            if line.startswith("# TYPE "):
                assert re.match(
                    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                    r"(counter|gauge|summary)$", line), line
            else:
                assert sample.match(line), line
        assert "# TYPE data_blocks counter" in text
        assert 'data_blocks{tag="scrape_test"} 16.0' in text
        assert 'data_rows{tag="scrape_test"} 4096.0' in text
        assert "# TYPE data_queue_wait_s summary" in text
        assert "# TYPE search_round_s summary" in text
        assert re.search(r'search_round_s\{quantile="0\.5"\}', text)
        assert "search_round_s_count 1" in text


# -- flight recorder + perfetto (satellite 3 + tentpole joins) -----------

class TestForensicJoins:
    def test_flight_dump_shows_open_device_interval(self):
        leaf = _Leaf(ready=False)
        scope.track("prog.hung", time.perf_counter(), [leaf])
        try:
            text = flight.post_mortem("unit test")
            assert "open device intervals:" in text
            assert "prog.hung: in flight" in text
        finally:
            leaf._ready = True
            scope.settle(5.0)
        # once closed, the dump says so explicitly
        assert "open device intervals: (none)" in \
            flight.post_mortem("after")

    def test_perfetto_flow_events_link_compute_to_device_lane(self):
        from dask_ml_tpu.linear_model import SGDClassifier

        model = SGDClassifier(random_state=0)
        stream_partial_fit(model, _sgd_blocks(4), depth=2,
                           fit_kwargs={"classes": np.array([0, 1])})
        scope.settle(5.0)
        trace = obs.perfetto_trace()
        flows = [e for e in trace["traceEvents"]
                 if e.get("cat") == "graftpath"]
        starts = [e for e in flows if e["ph"] == "s"]
        ends = [e for e in flows if e["ph"] == "f"]
        assert starts and ends
        assert {e["id"] for e in starts} == {e["id"] for e in ends}
        # the finish end sits on the device lane, the start on a host
        # thread's lane
        assert all(e["tid"] == 0 for e in ends)
        assert all(e["tid"] != 0 for e in starts)
        # every start lies inside a pipeline.compute slice
        computes = [(e["ts"], e["ts"] + e["dur"], e["tid"])
                    for e in trace["traceEvents"]
                    if e.get("name") == "pipeline.compute"
                    and e.get("ph") == "X"]
        for s in starts:
            assert any(t0 <= s["ts"] <= t1 and tid == s["tid"]
                       for t0, t1, tid in computes)


# -- perf ratchet v3 (satellite 6 semantics) -----------------------------

def _m(**kw):
    base = {"blocks": 10, "p50_block_s": 0.002, "p99_block_s": 0.01,
            "utilization": 0.8, "stall_fraction": 0.1, "wall_s": 0.5,
            "device_busy_s": 0.4, "programs": {},
            "overlap_efficiency": 0.6,
            "bottleneck": {"class": "device-bound", "share": 0.7}}
    base.update(kw)
    return base


def _snap(**workloads):
    return {"version": 3, "workloads": workloads}


class TestPerfV3Gates:
    def test_overlap_floor_regression(self):
        delta = perf.compare(_snap(w=_m()),
                             {"w": _m(overlap_efficiency=0.1)})
        assert any("overlap_efficiency" in r
                   for r in delta["regressions"])

    def test_overlap_within_floor_is_clean(self):
        delta = perf.compare(_snap(w=_m()),
                             {"w": _m(overlap_efficiency=0.35)})
        assert not any("overlap_efficiency" in r
                       for r in delta["regressions"])

    def test_tiny_committed_overlap_cannot_floor(self):
        delta = perf.compare(_snap(w=_m(overlap_efficiency=0.05)),
                             {"w": _m(overlap_efficiency=0.0)})
        assert not any("overlap_efficiency" in r
                       for r in delta["regressions"])

    def test_confident_bottleneck_flip_is_regression(self):
        delta = perf.compare(
            _snap(w=_m()),
            {"w": _m(bottleneck={"class": "dispatcher-bound",
                                 "share": 0.95})})
        assert any("bottleneck verdict flipped" in r
                   for r in delta["regressions"])

    def test_unconfident_wobble_does_not_pin(self):
        # measured share below the pin threshold: a 40/35 split on a
        # loaded box is not a verdict flip
        delta = perf.compare(
            _snap(w=_m()),
            {"w": _m(bottleneck={"class": "parse-bound",
                                 "share": 0.4})})
        assert not any("bottleneck" in r for r in delta["regressions"])
        # …and an unconfident BASELINE cannot pin either
        delta = perf.compare(
            _snap(w=_m(bottleneck={"class": "device-bound",
                                   "share": 0.4})),
            {"w": _m(bottleneck={"class": "parse-bound",
                                 "share": 0.9})})
        assert not any("bottleneck" in r for r in delta["regressions"])

    def test_v2_snapshot_skips_graftpath_gates(self):
        old = _m()
        old.pop("overlap_efficiency")
        old.pop("bottleneck")
        delta = perf.compare(
            {"version": 2, "workloads": {"w": old}},
            {"w": _m(overlap_efficiency=0.0,
                     bottleneck={"class": "queue-bound",
                                 "share": 0.99})})
        assert not any("overlap" in r or "bottleneck" in r
                       for r in delta["regressions"])

    def test_committed_baseline_is_v3_with_columns(self):
        snap = perf.load(perf.default_path())
        assert snap["version"] == 3
        for name, m in snap["workloads"].items():
            assert "overlap_efficiency" in m, name
            assert m["bottleneck"]["class"] != "unknown", name

"""Fault matrix for the resilience runtime (ISSUE 1 acceptance gate).

For every long-running estimator: a fit KILLED at an arbitrary iteration
and resumed from its ``FitCheckpoint`` must produce fitted attributes
numerically close (rtol <= 1e-5) to an uninterrupted fit; a TRANSIENT
ingest fault is absorbed by ``retry`` with backoff while a PERSISTENT
fault propagates loudly — with accurate ``FaultStats`` books either way.

Everything here is tier-1-safe on the 8-device CPU mesh: tiny data, few
iterations, zero-length backoffs.
"""

import os

import numpy as np
import pytest

from dask_ml_tpu.resilience import (
    FaultInjected,
    FitCheckpoint,
    PreemptionWatcher,
    TrainingPreempted,
    fault_plan,
)
from dask_ml_tpu.resilience.retry import (
    Deadline,
    DeadlineExceeded,
    FaultStats,
    fault_stats,
    retry,
)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_fault_stats():
    # diagnostics.reset() is the one-call isolation idiom: fault stats,
    # pipeline stats, metrics registry, span rings, flight recorder
    from dask_ml_tpu import diagnostics

    diagnostics.reset()
    yield
    diagnostics.reset()


@pytest.fixture
def X(rng):
    x = rng.normal(size=(192, 6)).astype(np.float32)
    x[:96] += 4.0  # two separable blobs for the clusterers
    return x


@pytest.fixture
def y_cls(X, rng):
    return (X @ rng.normal(size=X.shape[1]) > 0).astype(np.float32)


@pytest.fixture
def y_reg(X, rng):
    return (X @ rng.normal(size=X.shape[1])).astype(np.float32)


# ---------------------------------------------------------------------------
# retry / Deadline / FaultStats primitives
# ---------------------------------------------------------------------------
class TestRetryPrimitives:
    def test_exponential_backoff_schedule(self):
        delays, calls = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 4:
                raise OSError("transient")
            return "ok"

        out = retry(flaky, retries=3, backoff=0.1, factor=2.0, jitter=0.0,
                    sleep=delays.append)
        assert out == "ok"
        np.testing.assert_allclose(delays, [0.1, 0.2, 0.4])

    def test_jitter_multiplies_up_to_fraction(self):
        delays = []

        def boom():
            raise OSError("x")

        with pytest.raises(OSError):
            retry(boom, retries=3, backoff=1.0, factor=1.0, jitter=0.5,
                  sleep=delays.append)
        assert len(delays) == 3
        assert all(1.0 <= d <= 1.5 for d in delays)

    def test_stats_invariant_faults_eq_retries_plus_failures(self):
        stats = FaultStats()

        def boom():
            raise ValueError("persistent")

        with pytest.raises(ValueError):
            retry(boom, retries=2, backoff=0.0, jitter=0.0, stats=stats,
                  tag="t")
        s = stats.snapshot()
        assert s["faults"]["t"] == 3
        assert s["retries"]["t"] == 2
        assert s["failures"]["t"] == 1
        assert s["faults"]["t"] == s["retries"]["t"] + s["failures"]["t"]

    def test_non_retryable_propagates_immediately_uncounted(self):
        stats = FaultStats()
        calls = []

        def boom():
            calls.append(1)
            raise TypeError("a bug, not a fault")

        with pytest.raises(TypeError):
            retry(boom, retries=5, backoff=0.0, retryable=(OSError,),
                  stats=stats)
        assert len(calls) == 1
        assert stats.total("faults") == 0

    def test_on_error_hook_sees_every_fault(self):
        seen = []

        def boom():
            raise OSError(f"fault {len(seen)}")

        with pytest.raises(OSError):
            retry(boom, retries=2, backoff=0.0, jitter=0.0,
                  on_error=lambda e, k: seen.append(k))
        assert seen == [0, 1, 2]

    def test_deadline_stops_retry_loop(self):
        """An expired deadline stops retrying even with retry budget left;
        the LAST FAULT propagates (the deadline is a budget, not a fault),
        and the propagated failure is on the books."""
        calls = []

        def boom():
            calls.append(1)
            raise OSError("x")

        with pytest.raises(OSError):
            retry(boom, retries=10_000, backoff=0.05, factor=1.0,
                  jitter=0.0, deadline=Deadline(0.2), tag="dl")
        assert len(calls) < 100  # the deadline cut the 10k-retry budget
        s = fault_stats().snapshot()
        assert s["failures"]["dl"] == 1
        # the books stay exact even on the deadline path
        assert s["faults"]["dl"] == s["retries"]["dl"] + s["failures"]["dl"]

    def test_deadline_expired_before_first_attempt(self):
        import time

        dl = Deadline(0.01)
        time.sleep(0.02)
        with pytest.raises(DeadlineExceeded):
            retry(lambda: "never runs", deadline=dl)

    def test_deadline_exceeded_inside_fn_never_absorbed(self):
        def boom():
            raise DeadlineExceeded("budget blown inside the unit")

        with pytest.raises(DeadlineExceeded):
            retry(boom, retries=5, backoff=0.0)  # retryable=Exception

    def test_zero_retries_single_attempt_still_counted(self):
        def boom():
            raise OSError("x")

        with pytest.raises(OSError):
            retry(boom, retries=0, tag="once")
        s = fault_stats().snapshot()
        assert s["faults"]["once"] == 1 and s["failures"]["once"] == 1


# ---------------------------------------------------------------------------
# ingest layer: transient absorbed, persistent loud, books accurate
# ---------------------------------------------------------------------------
@pytest.fixture
def csv_path(tmp_path, rng):
    p = tmp_path / "data.csv"
    arr = rng.normal(size=(40, 3)).astype(np.float32)
    np.savetxt(p, arr, delimiter=",", fmt="%.6f")
    return str(p), arr


class TestIngestFaults:
    def test_transient_ingest_fault_absorbed(self, csv_path):
        from dask_ml_tpu.io import read_csv

        path, arr = csv_path
        with fault_plan() as plan:
            plan.inject("ingest", at_call=1)
            out = read_csv(path, retries=2, retry_backoff=0.0)
        np.testing.assert_allclose(out, arr, rtol=1e-4)
        s = fault_stats().snapshot()
        assert s["faults"]["ingest"] == 1
        assert s["retries"]["ingest"] == 1
        assert "ingest" not in s["failures"]

    def test_persistent_ingest_fault_propagates(self, csv_path):
        from dask_ml_tpu.io import read_csv

        path, _ = csv_path
        with fault_plan() as plan:
            plan.persistent("ingest")
            with pytest.raises(FaultInjected, match="ingest"):
                read_csv(path, retries=2, retry_backoff=0.0)
        s = fault_stats().snapshot()
        assert s["faults"]["ingest"] == 3      # initial + 2 re-attempts
        assert s["retries"]["ingest"] == 2
        assert s["failures"]["ingest"] == 1

    def test_stream_blocks_retry_never_skips_rows(self, csv_path):
        from dask_ml_tpu.io import stream_csv_blocks

        path, arr = csv_path
        with fault_plan() as plan:
            plan.inject("ingest", at_call=2)  # fault fetching block 2
            blocks = list(
                stream_csv_blocks(path, 16, retries=1, retry_backoff=0.0)
            )
        np.testing.assert_allclose(np.vstack(blocks), arr, rtol=1e-4)
        assert fault_stats().snapshot()["retries"]["ingest"] == 1

    def test_stream_blocks_no_retry_budget_propagates(self, csv_path):
        from dask_ml_tpu.io import stream_csv_blocks

        path, _ = csv_path
        with fault_plan() as plan:
            plan.inject("ingest", at_call=1)
            with pytest.raises(FaultInjected):
                list(stream_csv_blocks(path, 16))  # retries=0 default


# ---------------------------------------------------------------------------
# the kill/resume estimator matrix
# ---------------------------------------------------------------------------
def _factories():
    from dask_ml_tpu.cluster import KMeans, MiniBatchKMeans
    from dask_ml_tpu.decomposition import IncrementalPCA
    from dask_ml_tpu.linear_model import (
        LinearRegression,
        LogisticRegression,
        SGDClassifier,
        SGDRegressor,
    )

    return {
        # name -> (factory(ckpt), fit(est, X, y_cls, y_reg), fitted attr)
        "kmeans": (
            lambda c: KMeans(n_clusters=2, init="random", random_state=0,
                             max_iter=8, tol=0.0, fit_checkpoint=c),
            lambda e, X, yc, yr: e.fit(X),
            "cluster_centers_",
        ),
        "minibatch-kmeans": (
            lambda c: MiniBatchKMeans(n_clusters=2, random_state=0,
                                      max_iter=6, batch_size=64,
                                      fit_checkpoint=c),
            lambda e, X, yc, yr: e.fit(X),
            "cluster_centers_",
        ),
        "sgd-classifier": (
            lambda c: SGDClassifier(random_state=0, max_iter=8, tol=None,
                                    fit_checkpoint=c),
            lambda e, X, yc, yr: e.fit(X, yc),
            "coef_",
        ),
        "sgd-regressor": (
            lambda c: SGDRegressor(random_state=0, max_iter=8, tol=None,
                                   fit_checkpoint=c),
            lambda e, X, yc, yr: e.fit(X, yr),
            "coef_",
        ),
        "glm-logistic": (
            lambda c: LogisticRegression(solver="gradient_descent",
                                         max_iter=24,
                                         fit_checkpoint=FitCheckpoint(
                                             c.path, every_n_iters=6)),
            lambda e, X, yc, yr: e.fit(X, yc),
            "coef_",
        ),
        "glm-linear": (
            lambda c: LinearRegression(solver="lbfgs", max_iter=24,
                                       fit_checkpoint=FitCheckpoint(
                                           c.path, every_n_iters=6)),
            lambda e, X, yc, yr: e.fit(X, yr),
            "coef_",
        ),
        "incremental-pca": (
            lambda c: IncrementalPCA(n_components=2, batch_size=48,
                                     fit_checkpoint=c),
            lambda e, X, yc, yr: e.fit(X),
            "components_",
        ),
    }


@pytest.mark.parametrize("name", sorted(_factories()))
@pytest.mark.parametrize("kill_at", [2, 3])
def test_kill_resume_matches_uninterrupted(name, kill_at, tmp_path, X,
                                           y_cls, y_reg):
    """A fit killed at step-boundary ``kill_at`` and resumed from its
    snapshot converges to the SAME fitted attributes as an uninterrupted
    (identically-configured) fit."""
    make, fit, attr = _factories()[name]

    clean = make(FitCheckpoint(str(tmp_path / "clean.pkl"),
                               every_n_iters=1))
    fit(clean, X, y_cls, y_reg)
    ref = np.asarray(getattr(clean, attr))

    path = str(tmp_path / "killed.pkl")
    est = make(FitCheckpoint(path, every_n_iters=1))
    with fault_plan() as plan:
        plan.inject("step", at_call=kill_at)
        with pytest.raises(FaultInjected):
            fit(est, X, y_cls, y_reg)
    assert os.path.exists(path), "no snapshot survived the kill"

    resumed = make(FitCheckpoint(path, every_n_iters=1))
    fit(resumed, X, y_cls, y_reg)
    np.testing.assert_allclose(
        np.asarray(getattr(resumed, attr)), ref, rtol=1e-5, atol=1e-6
    )
    assert not os.path.exists(path), "completed fit must clear its snapshot"


def test_search_kill_resume_matches_uninterrupted(tmp_path, rng):
    """The adaptive-search row of the matrix: IncrementalSearchCV killed
    mid-search resumes from its round-granular SearchCheckpoint and ranks
    the identical models."""
    from dask_ml_tpu.model_selection import IncrementalSearchCV
    from test_fault_injection import POINT, PlanModel

    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)

    def search(path):
        return IncrementalSearchCV(
            PlanModel(), {"slope": [1.0, 2.0, 3.0]},
            n_initial_parameters=3, max_iter=4, random_state=0,
            checkpoint=path,
        )

    clean = search(str(tmp_path / "clean.pkl")).fit(X, y)

    path = str(tmp_path / "killed.pkl")
    with fault_plan() as plan:
        # persistent from call 5 on: the unit's single retry hits it
        # again, so the search dies after round 1 is checkpointed
        plan.inject(POINT, at_call=range(5, 500), times=None)
        with pytest.raises(FaultInjected):
            search(path).fit(X, y)
    assert os.path.exists(path)

    resumed = search(path).fit(X, y)
    assert resumed.best_params_ == clean.best_params_
    assert resumed.best_score_ == clean.best_score_
    assert {m: r[-1]["partial_fit_calls"]
            for m, r in resumed.model_history_.items()} == {
        m: r[-1]["partial_fit_calls"]
        for m, r in clean.model_history_.items()
    }


# ---------------------------------------------------------------------------
# checkpoint-write crash window + fingerprint policy
# ---------------------------------------------------------------------------
class TestCheckpointWriteCrash:
    def test_crash_mid_write_keeps_previous_snapshot(self, tmp_path, X):
        """The checkpoint-write injection point fires BETWEEN the tmp
        write and the atomic rename — the exact window the tmp+rename
        protocol defends: the previous snapshot must survive, and the fit
        must be resumable from it."""
        from dask_ml_tpu.linear_model import SGDRegressor

        path = str(tmp_path / "ck.pkl")
        yr = np.asarray(X @ np.ones(X.shape[1]), np.float32)

        def make():
            return SGDRegressor(random_state=0, max_iter=6, tol=None,
                                fit_checkpoint=FitCheckpoint(
                                    path, every_n_iters=1))

        clean = make()
        clean.fit(X, yr)
        ref = np.asarray(clean.coef_)

        est = make()
        with fault_plan() as plan:
            plan.inject("checkpoint-write", at_call=3)
            with pytest.raises(FaultInjected):
                est.fit(X, yr)
        # epoch-2 snapshot (written at checkpoint-write call 2) survives
        assert os.path.exists(path)
        snap = FitCheckpoint(path).load_if_matches(make())
        assert snap is not None and snap[0] == 2

        resumed = make()
        resumed.fit(X, yr)
        np.testing.assert_allclose(np.asarray(resumed.coef_), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_fingerprint_mismatch_starts_fresh_keeps_foreign_file(
            self, tmp_path, X):
        from dask_ml_tpu.cluster import KMeans

        path = str(tmp_path / "ck.pkl")
        a = KMeans(n_clusters=2, init="random", random_state=0, max_iter=3,
                   tol=0.0,
                   fit_checkpoint=FitCheckpoint(path, every_n_iters=1,
                                                keep_on_complete=True))
        a.fit(X)
        assert os.path.exists(path)
        foreign_bytes = open(path, "rb").read()

        # differently-configured fit against the same path: the snapshot
        # must be IGNORED (fresh trajectory), not consumed or deleted
        b = KMeans(n_clusters=3, init="random", random_state=0, max_iter=3,
                   tol=0.0,
                   fit_checkpoint=FitCheckpoint(path, every_n_iters=1,
                                                keep_on_complete=True))
        assert b.fit_checkpoint.load_if_matches(b) is None
        assert open(path, "rb").read() == foreign_bytes


# ---------------------------------------------------------------------------
# preemption: signal -> boundary stop -> final snapshot -> resume
# ---------------------------------------------------------------------------
class TestPreemption:
    def test_trigger_checkpoints_and_stops_then_resumes(self, tmp_path, X,
                                                        y_reg):
        from dask_ml_tpu.linear_model import SGDRegressor

        path = str(tmp_path / "pre.pkl")

        def make():
            return SGDRegressor(random_state=0, max_iter=8, tol=None,
                                fit_checkpoint=FitCheckpoint(
                                    path, every_n_iters=100))

        clean = make()
        clean.fit(X, y_reg)
        ref = np.asarray(clean.coef_)

        est = make()
        with PreemptionWatcher() as w:
            with fault_plan() as plan:
                # the "signal" lands mid-epoch-3; the stop must land at
                # the epoch-3 BOUNDARY with a final snapshot even though
                # the cadence (every 100) never fired on its own
                plan.on_call("step", w.trigger, at_call=3)
                with pytest.raises(TrainingPreempted) as ei:
                    est.fit(X, y_reg)
        assert ei.value.iteration == 3
        assert ei.value.checkpoint_path == path
        assert os.path.exists(path)

        resumed = make()
        resumed.fit(X, y_reg)
        np.testing.assert_allclose(np.asarray(resumed.coef_), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_real_sigterm_sets_flag_without_raising(self):
        import signal

        with PreemptionWatcher() as w:
            assert not w.requested
            signal.raise_signal(signal.SIGTERM)
            assert w.requested  # flag only — no exception mid-collective

    def test_no_checkpoint_still_stops_cleanly(self, X):
        from dask_ml_tpu.cluster import KMeans

        est = KMeans(n_clusters=2, init="random", random_state=0,
                     max_iter=8, tol=0.0)  # NO fit_checkpoint
        with PreemptionWatcher() as w:
            with fault_plan() as plan:
                plan.on_call("step", w.trigger, at_call=1)
                with pytest.raises(TrainingPreempted) as ei:
                    est.fit(X)
        assert ei.value.checkpoint_path is None

    def test_uninstall_restores_handlers(self):
        import signal

        prev = signal.getsignal(signal.SIGTERM)
        with PreemptionWatcher():
            assert signal.getsignal(signal.SIGTERM) != prev
        assert signal.getsignal(signal.SIGTERM) == prev


# ---------------------------------------------------------------------------
# collective-layer injection point
# ---------------------------------------------------------------------------
class TestCollectivePoint:
    def test_shard_rows_faults_on_schedule(self, rng):
        from dask_ml_tpu.core.sharded import shard_rows, unshard

        x = rng.normal(size=(16, 4)).astype(np.float32)
        with fault_plan() as plan:
            plan.inject("collective", at_call=2)
            s = shard_rows(x)  # call 1: fine
            with pytest.raises(FaultInjected, match="collective"):
                unshard(s)  # call 2: the injected transport fault
            np.testing.assert_allclose(unshard(s), x)  # call 3: fine


# ---------------------------------------------------------------------------
# FitCheckpoint policy
# ---------------------------------------------------------------------------
class TestFitCheckpointPolicy:
    def test_complete_forgets_last_save_iteration(self, tmp_path):
        """A FitCheckpoint reused across fits must not skip the final
        preemption snapshot because an EARLIER fit saved at the same
        iteration count (check_preemption dedups on _last_save_iter)."""
        from dask_ml_tpu.resilience.preemption import (
            PreemptionWatcher, TrainingPreempted, check_preemption,
        )

        from dask_ml_tpu.cluster import KMeans

        ck = FitCheckpoint(str(tmp_path / "x"), every_s=3600.0)
        est = KMeans(n_clusters=2, init="random", random_state=0)
        ck.save(est, {"w": 4.0}, iteration=4)
        ck.complete()  # fit A finished: snapshot deleted, iter forgotten
        assert not ck.exists()
        with PreemptionWatcher() as w:
            w.trigger()
            with pytest.raises(TrainingPreempted) as ei:
                check_preemption(ck, est, {"w": 7.0}, iteration=4)
        # the final snapshot was WRITTEN, not skipped as a duplicate
        assert ck.exists() and ei.value.checkpoint_path == ck.path
        assert ck.load_if_matches(est)[1]["w"] == 7.0

    def test_cadence_validation(self, tmp_path):
        with pytest.raises(ValueError):
            FitCheckpoint(str(tmp_path / "x"), every_n_iters=0)
        with pytest.raises(ValueError):
            FitCheckpoint(str(tmp_path / "x"), every_s=0.0)

    def test_due_iteration_cadence(self, tmp_path):
        ck = FitCheckpoint(str(tmp_path / "x"), every_n_iters=3)
        assert [i for i in range(1, 10) if ck.due(i)] == [3, 6, 9]

    def test_due_time_cadence_fires_then_rearms(self, tmp_path):
        ck = FitCheckpoint(str(tmp_path / "x"), every_s=10_000.0)
        # cadence anchors at construction: the first boundary is NOT due
        assert not ck.due(1)
        ck._last_save_t -= 20_000.0  # pretend every_s elapsed
        assert ck.due(2)
        ck._last_save_t = __import__("time").monotonic()  # a save re-arms
        assert not ck.due(3)

    def test_default_cadence_every_boundary(self, tmp_path):
        ck = FitCheckpoint(str(tmp_path / "x"))
        assert ck.every_n_iters == 1 and all(ck.due(i) for i in (1, 2, 3))

"""Fault injection for the dynamic search plane.

The reference inherits resilience from distributed: tasks of a dead worker
are resubmitted and lineage recomputes their inputs; a handful of its tests
kill workers mid-search (SURVEY.md §5 failure detection).  The analogue
here is process-local: a training unit that raises is retried ONCE from a
deep-copied round-start snapshot (exact-state recovery —
``model_selection/_incremental.py :: run_unit``, riding the shared
``resilience.retry`` primitive), persistent faults propagate, and
round-granular checkpoints (tests/test_checkpoint.py) cover whole-process
death.

Faults are scheduled DECLARATIVELY through ``resilience.testing``: the
fake model's ``partial_fit`` is an injection SITE and a ``FaultPlan``
owns the schedule — the plan's call counter coordinates across model
clones and search threads, replacing the class-level counters these
tests used to hand-roll per fake estimator.
"""

import numpy as np
import pytest
from sklearn.base import BaseEstimator

from dask_ml_tpu.model_selection import GridSearchCV, IncrementalSearchCV
from dask_ml_tpu.resilience import FaultInjected, FaultPlan, fault_plan, maybe_fault
from dask_ml_tpu.resilience.retry import fault_stats

pytestmark = pytest.mark.faults

#: the search-plane injection point (a caller-private point name; the
#: canonical runtime points are ingest/step/checkpoint-write/collective)
POINT = "search-step"


class PlanModel(BaseEstimator):
    """Linear-score fake model whose ``partial_fit`` is an injection site:
    the active :class:`FaultPlan` decides which (globally-numbered) call
    faults.  Deterministic score keeps search results comparable."""

    def __init__(self, slope=1.0):
        self.slope = slope

    def partial_fit(self, X, y, **kw):
        maybe_fault(POINT)
        self.n_calls_ = getattr(self, "n_calls_", 0) + 1
        return self

    def score(self, X, y):
        return self.slope * getattr(self, "n_calls_", 0)


class FailingFit(BaseEstimator):
    """For GridSearchCV: fit raises for a poisoned parameter value."""

    def __init__(self, c=1.0):
        self.c = c

    def fit(self, X, y):
        if self.c < 0:
            raise ValueError("injected candidate failure")
        self.fitted_ = True
        return self

    def score(self, X, y):
        return float(self.c)


@pytest.fixture
def xy(rng):
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    return X, y


@pytest.fixture(autouse=True)
def _clean_fault_stats():
    # diagnostics.reset() is the one-call isolation idiom: fault stats,
    # pipeline stats, metrics registry, span rings, flight recorder
    from dask_ml_tpu import diagnostics

    diagnostics.reset()
    yield
    diagnostics.reset()


class TestIncrementalFaultRecovery:
    def _search(self, **kw):
        kw.setdefault("n_initial_parameters", 3)
        kw.setdefault("max_iter", 4)
        kw.setdefault("random_state", 0)
        return IncrementalSearchCV(
            PlanModel(), {"slope": [1.0, 2.0, 3.0]}, **kw
        )

    def test_transient_fault_recovers(self, xy):
        X, y = xy
        with fault_plan() as plan:
            plan.inject(POINT, at_call=5)
            search = self._search().fit(X, y)
        assert plan.fired[POINT] == 1
        assert search.fit_failures_ == 1
        # the search still trained every model to budget and ranked them
        assert search.best_score_ == max(
            r["score"] for r in search.history_
        )
        # the retry rode the shared primitive: observable in fault_stats
        s = fault_stats().snapshot()
        assert s["faults"].get("search-unit") == 1
        assert s["retries"].get("search-unit") == 1
        assert "search-unit" not in s["failures"]

    def test_recovery_is_exact_state(self, xy):
        """A retried unit restarts from its round-start snapshot, so the
        final fitted state matches an entirely fault-free run."""
        X, y = xy
        clean = self._search().fit(X, y)
        with fault_plan() as plan:
            plan.inject(POINT, at_call=4)
            faulty = self._search().fit(X, y)
        assert faulty.fit_failures_ == 1
        assert clean.best_params_ == faulty.best_params_
        assert clean.best_score_ == faulty.best_score_
        # every model saw the same number of effective partial_fit calls
        clean_calls = {
            m: recs[-1]["partial_fit_calls"]
            for m, recs in clean.model_history_.items()
        }
        faulty_calls = {
            m: recs[-1]["partial_fit_calls"]
            for m, recs in faulty.model_history_.items()
        }
        assert clean_calls == faulty_calls

    def test_no_fault_counts_zero(self, xy):
        X, y = xy
        with fault_plan() as plan:  # an EMPTY plan: counts, never fires
            search = self._search().fit(X, y)
        assert search.fit_failures_ == 0
        assert plan.fired[POINT] == 0
        assert plan.calls[POINT] > 0
        assert fault_stats().total("faults") == 0

    def test_persistent_fault_raises(self, xy):
        X, y = xy
        search = IncrementalSearchCV(
            PlanModel(), {"slope": [1.0, 2.0]},
            n_initial_parameters=2, max_iter=2, random_state=0,
        )
        with fault_plan() as plan:
            plan.persistent(POINT)
            with pytest.raises(FaultInjected, match=POINT):
                search.fit(X, y)
        # the unit's single retry hit the persistent fault again: the
        # second failure propagated (loud), and the books say so
        s = fault_stats().snapshot()
        assert s["failures"].get("search-unit", 0) >= 1

    def test_scheduled_exception_type_propagates(self, xy):
        """A plan can inject ANY exception type — the search's retry
        treats it like any transient unit fault."""
        X, y = xy
        with fault_plan() as plan:
            plan.inject(POINT, at_call=3, exc=OSError("disk vanished"))
            search = self._search().fit(X, y)
        assert search.fit_failures_ == 1


class TestGridSearchErrorScore:
    def test_error_score_nan_keeps_good_candidates(self, xy):
        X, y = xy
        search = GridSearchCV(
            FailingFit(), {"c": [-1.0, 1.0, 2.0]}, cv=3,
            error_score=np.nan,
        ).fit(X, y)
        scores = search.cv_results_["mean_test_score"]
        bad = search.cv_results_["param_c"].index(-1.0)
        assert np.isnan(scores[bad])
        assert search.best_params_ == {"c": 2.0}

    def test_error_score_raise_propagates(self, xy):
        X, y = xy
        with pytest.raises(ValueError, match="injected candidate failure"):
            GridSearchCV(
                FailingFit(), {"c": [-1.0, 1.0]}, cv=3, error_score="raise"
            ).fit(X, y)


class TestHyperbandFaultRollup:
    def test_bracket_failures_surface_on_hyperband(self, xy):
        from dask_ml_tpu.model_selection import HyperbandSearchCV

        X, y = xy

        def hb():
            return HyperbandSearchCV(
                PlanModel(), {"slope": [1.0, 2.0, 3.0]},
                max_iter=4, random_state=0,
            )

        with fault_plan() as plan:
            plan.inject(POINT, at_call=6)
            faulty = hb().fit(X, y)
        assert faulty.fit_failures_ == 1
        clean = hb().fit(X, y)
        assert clean.fit_failures_ == 0
        assert clean.best_params_ == faulty.best_params_


class TestFaultPlanRegistry:
    """The harness itself: schedules, probes, accounting."""

    def test_at_call_list_and_times(self):
        plan = FaultPlan()
        plan.inject("p", at_call=(2, 4), times=2)
        with fault_plan(plan):
            for i in range(1, 6):
                if i in (2, 4):
                    with pytest.raises(FaultInjected):
                        maybe_fault("p")
                else:
                    maybe_fault("p")
        assert plan.calls["p"] == 5
        assert plan.fired["p"] == 2

    def test_probe_side_effect_without_raise(self):
        hits = []
        with fault_plan() as plan:
            plan.on_call("p", lambda: hits.append(plan.calls["p"]),
                         at_call=3)
            for _ in range(4):
                maybe_fault("p")
        assert hits == [3]

    def test_no_active_plan_is_noop(self):
        maybe_fault("anything")  # must not raise, must not record

    def test_plans_nest_and_restore(self):
        with fault_plan() as outer:
            with fault_plan() as inner:
                maybe_fault("p")
            maybe_fault("p")
            assert inner.calls["p"] == 1
            assert outer.calls["p"] == 1

"""Fault injection for the dynamic search plane.

The reference inherits resilience from distributed: tasks of a dead worker
are resubmitted and lineage recomputes their inputs; a handful of its tests
kill workers mid-search (SURVEY.md §5 failure detection).  The analogue
here is process-local: a training unit that raises is retried ONCE from a
deep-copied round-start snapshot (exact-state recovery —
``model_selection/_incremental.py :: run_unit``), persistent faults
propagate, and round-granular checkpoints (tests/test_checkpoint.py) cover
whole-process death.  These tests inject faults at the partial_fit level
and assert recovery semantics, determinism, and failure accounting.
"""

import threading

import numpy as np
import pytest
from sklearn.base import BaseEstimator

from dask_ml_tpu.model_selection import IncrementalSearchCV, GridSearchCV


class FlakyOnce(BaseEstimator):
    """Linear-score fake model whose partial_fit raises once, globally
    coordinated: call number ``fail_at`` (1-based, across ALL instances)
    raises RuntimeError, every other call succeeds.  Deterministic score
    keeps search results comparable across runs."""

    # class-level so all clones share the fault schedule
    _calls = 0
    _failed = False
    _lock = threading.Lock()
    fail_at = None

    def __init__(self, slope=1.0, fail_marker=0):
        self.slope = slope
        self.fail_marker = fail_marker

    @classmethod
    def reset(cls, fail_at=None):
        cls._calls = 0
        cls._failed = False
        cls.fail_at = fail_at

    def partial_fit(self, X, y, **kw):
        cls = type(self)
        with cls._lock:
            cls._calls += 1
            should_fail = (
                cls.fail_at is not None
                and cls._calls == cls.fail_at
                and not cls._failed
            )
            if should_fail:
                cls._failed = True
        if should_fail:
            raise RuntimeError("injected fault")
        self.n_calls_ = getattr(self, "n_calls_", 0) + 1
        return self

    def score(self, X, y):
        return self.slope * getattr(self, "n_calls_", 0)


class AlwaysFails(BaseEstimator):
    def __init__(self, dummy=0):
        self.dummy = dummy

    def partial_fit(self, X, y, **kw):
        raise RuntimeError("persistent injected fault")

    def score(self, X, y):  # pragma: no cover
        return 0.0


class FailingFit(BaseEstimator):
    """For GridSearchCV: fit raises for a poisoned parameter value."""

    def __init__(self, c=1.0):
        self.c = c

    def fit(self, X, y):
        if self.c < 0:
            raise ValueError("injected candidate failure")
        self.fitted_ = True
        return self

    def score(self, X, y):
        return float(self.c)


@pytest.fixture
def xy(rng):
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    return X, y


class TestIncrementalFaultRecovery:
    def _search(self, **kw):
        kw.setdefault("n_initial_parameters", 3)
        kw.setdefault("max_iter", 4)
        kw.setdefault("random_state", 0)
        return IncrementalSearchCV(
            FlakyOnce(), {"slope": [1.0, 2.0, 3.0]}, **kw
        )

    def test_transient_fault_recovers(self, xy):
        X, y = xy
        FlakyOnce.reset(fail_at=5)
        search = self._search().fit(X, y)
        assert search.fit_failures_ == 1
        # the search still trained every model to budget and ranked them
        assert search.best_score_ == max(
            r["score"] for r in search.history_
        )

    def test_recovery_is_exact_state(self, xy):
        """A retried unit restarts from its round-start snapshot, so the
        final fitted state matches an entirely fault-free run."""
        X, y = xy
        FlakyOnce.reset(fail_at=None)
        clean = self._search().fit(X, y)
        FlakyOnce.reset(fail_at=4)
        faulty = self._search().fit(X, y)
        assert faulty.fit_failures_ == 1
        assert clean.best_params_ == faulty.best_params_
        assert clean.best_score_ == faulty.best_score_
        # every model saw the same number of effective partial_fit calls
        clean_calls = {
            m: recs[-1]["partial_fit_calls"]
            for m, recs in clean.model_history_.items()
        }
        faulty_calls = {
            m: recs[-1]["partial_fit_calls"]
            for m, recs in faulty.model_history_.items()
        }
        assert clean_calls == faulty_calls

    def test_no_fault_counts_zero(self, xy):
        X, y = xy
        FlakyOnce.reset(fail_at=None)
        search = self._search().fit(X, y)
        assert search.fit_failures_ == 0

    def test_persistent_fault_raises(self, xy):
        X, y = xy
        search = IncrementalSearchCV(
            AlwaysFails(), {"dummy": [0, 1]},
            n_initial_parameters=2, max_iter=2, random_state=0,
        )
        with pytest.raises(RuntimeError, match="persistent injected fault"):
            search.fit(X, y)


class TestGridSearchErrorScore:
    def test_error_score_nan_keeps_good_candidates(self, xy):
        X, y = xy
        search = GridSearchCV(
            FailingFit(), {"c": [-1.0, 1.0, 2.0]}, cv=3,
            error_score=np.nan,
        ).fit(X, y)
        scores = search.cv_results_["mean_test_score"]
        bad = search.cv_results_["param_c"].index(-1.0)
        assert np.isnan(scores[bad])
        assert search.best_params_ == {"c": 2.0}

    def test_error_score_raise_propagates(self, xy):
        X, y = xy
        with pytest.raises(ValueError, match="injected candidate failure"):
            GridSearchCV(
                FailingFit(), {"c": [-1.0, 1.0]}, cv=3, error_score="raise"
            ).fit(X, y)


class TestHyperbandFaultRollup:
    def test_bracket_failures_surface_on_hyperband(self, xy):
        from dask_ml_tpu.model_selection import HyperbandSearchCV

        X, y = xy
        FlakyOnce.reset(fail_at=6)
        hb = HyperbandSearchCV(
            FlakyOnce(), {"slope": [1.0, 2.0, 3.0]},
            max_iter=4, random_state=0,
        ).fit(X, y)
        assert hb.fit_failures_ == 1
        FlakyOnce.reset(fail_at=None)
        clean = HyperbandSearchCV(
            FlakyOnce(), {"slope": [1.0, 2.0, 3.0]},
            max_iter=4, random_state=0,
        ).fit(X, y)
        assert clean.fit_failures_ == 0
        assert clean.best_params_ == hb.best_params_

"""True parallelism in the search planes (VERDICT round-1 item 6): the
thread-pool fan-out must produce real wall-clock overlap (>1.5x with 4
workers), identical results to serial, and a compute-once prefix cache."""

import threading
import time

import numpy as np
import pytest

import jax

from sklearn.base import BaseEstimator

from dask_ml_tpu.model_selection import GridSearchCV, IncrementalSearchCV


class SleepyClassifier(BaseEstimator):
    """GIL-releasing slow fit (time.sleep releases the GIL like sklearn's C
    kernels do), deterministic score."""

    def __init__(self, delay=0.05, quality=0.5):
        self.delay = delay
        self.quality = quality

    def fit(self, X, y=None, **kwargs):
        time.sleep(self.delay)
        self.fitted_ = True
        return self

    def partial_fit(self, X, y=None, **kwargs):
        time.sleep(self.delay)
        self.fitted_ = True
        return self

    def score(self, X, y=None):
        return self.quality

    def predict(self, X):
        return np.zeros(len(X))


class TestGridSearchParallel:
    def _grid(self, n_jobs):
        return GridSearchCV(
            SleepyClassifier(delay=0.05),
            {"quality": [0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8]},
            cv=2,
            n_jobs=n_jobs,
            refit=False,
        )

    def test_four_workers_speedup(self, rng):
        X = rng.normal(size=(40, 3))
        y = (X[:, 0] > 0).astype(int)
        t0 = time.perf_counter()
        self._grid(1).fit(X, y)
        serial = time.perf_counter() - t0
        t0 = time.perf_counter()
        self._grid(4).fit(X, y)
        par = time.perf_counter() - t0
        assert serial / par > 1.5, (serial, par)

    def test_parallel_results_match_serial(self, rng):
        X = rng.normal(size=(40, 3))
        y = (X[:, 0] > 0).astype(int)
        a = self._grid(1).fit(X, y)
        b = self._grid(4).fit(X, y)
        assert a.best_params_ == b.best_params_
        np.testing.assert_allclose(
            a.cv_results_["mean_test_score"], b.cv_results_["mean_test_score"]
        )
        assert a.cv_results_["rank_test_score"] == b.cv_results_["rank_test_score"]

    def test_error_score_raise_propagates(self, rng):
        class Exploder(BaseEstimator):
            def __init__(self, boom=True):
                self.boom = boom

            def fit(self, X, y=None):
                raise RuntimeError("boom")

            def score(self, X, y=None):  # pragma: no cover
                return 0.0

        X = rng.normal(size=(20, 2))
        search = GridSearchCV(Exploder(), {"boom": [True, False]}, cv=2,
                              n_jobs=4, refit=False)
        with pytest.raises(RuntimeError, match="boom"):
            search.fit(X, np.zeros(20))

    def test_prefix_cache_compute_once_under_threads(self, rng):
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        fit_counts = {"n": 0}
        lock = threading.Lock()

        class CountingScaler(StandardScaler):
            def fit(self, X, y=None, sample_weight=None):
                with lock:
                    fit_counts["n"] += 1
                time.sleep(0.02)  # widen the race window
                return super().fit(X, y)

        X = rng.normal(size=(60, 3))
        y = (X[:, 0] > 0).astype(int)
        pipe = Pipeline([
            ("sc", CountingScaler()),
            ("clf", SleepyClassifier(delay=0.01)),
        ])
        search = GridSearchCV(
            pipe,
            {"clf__quality": [0.1, 0.3, 0.5, 0.7]},
            cv=3, n_jobs=4, refit=False,
        )
        search.fit(X, y)
        # one scaler fit per FOLD (3), never per candidate x fold (12)
        assert fit_counts["n"] == 3, fit_counts


class TestIncrementalParallel:
    def test_models_overlap_in_wall_clock(self, rng):
        X = rng.normal(size=(60, 3))
        y = (X[:, 0] > 0).astype(int)
        n_models = 6
        search = IncrementalSearchCV(
            SleepyClassifier(delay=0.08),
            {"quality": np.linspace(0.1, 0.9, n_models)},
            n_initial_parameters=n_models,
            max_iter=2,
            random_state=0,
        )
        t0 = time.perf_counter()
        search.fit(X, y)
        wall = time.perf_counter() - t0
        # serial lower bound: n_models * max_iter * (delay per call)
        serial_floor = n_models * 2 * 0.08
        assert wall < serial_floor / 1.5, (wall, serial_floor)
        assert search.best_score_ == pytest.approx(0.9)


class TestMeshPropagation:
    def test_caller_mesh_reaches_worker_threads(self, rng):
        # thread-local mesh overrides must survive the executor hop
        from dask_ml_tpu.core.mesh import device_mesh, get_mesh, use_mesh

        seen = []

        class MeshSpy(BaseEstimator):
            def fit(self, X, y=None):
                seen.append(get_mesh().shape)
                self.fitted_ = True
                return self

            def partial_fit(self, X, y=None, **kw):
                seen.append(get_mesh().shape)
                self.fitted_ = True
                return self

            def score(self, X, y=None):
                return 0.5

        X = rng.normal(size=(40, 3))
        y = (X[:, 0] > 0).astype(int)
        from conftest import require_devices_divisible

        mesh = device_mesh(require_devices_divisible(4), model_axis=4)
        with use_mesh(mesh):
            GridSearchCV(MeshSpy(), {}, cv=2, n_jobs=4, refit=False).fit(X, y)
            IncrementalSearchCV(
                MeshSpy(), {}, n_initial_parameters="grid", max_iter=1,
            ).fit(X, y)
        assert seen, "no fits ran"
        for shape in seen:
            assert dict(shape) == {"data": len(jax.devices()) // 4,
                                   "model": 4}, shape


class MutatingScaler(BaseEstimator):
    """A transformer that scales its input IN PLACE (the sklearn
    ``copy=False`` hazard class): under a shared fold cache, one
    candidate's fit would poison every later candidate's view of the
    same fold slice."""

    def fit(self, X, y=None):
        return self

    def transform(self, X):
        X *= 2.0  # in-place: mutates whatever array object it was given
        return X

    def fit_transform(self, X, y=None):
        return self.fit(X).transform(X)


class TestFoldCacheMutationSafety:
    """VERDICT r5 target: the refcounted fold cache under concurrent
    n_jobs mutation.  Host numpy fold slices must be fresh per task
    (mutable), so an in-place pipeline step cannot corrupt siblings;
    results must be identical serial vs 4-way concurrent."""

    def _grid(self, n_jobs):
        from sklearn.pipeline import Pipeline
        from sklearn.linear_model import LogisticRegression as SkLR

        return GridSearchCV(
            Pipeline([("mut", MutatingScaler()),
                      ("clf", SkLR(max_iter=50))]),
            {"clf__C": [0.01, 0.1, 1.0, 10.0, 100.0]},
            cv=3, n_jobs=n_jobs, refit=False,
            cache_cv=False,  # the mutating step must not be prefix-cached
        )

    def test_inplace_step_concurrent_matches_serial(self, rng):
        X = rng.normal(size=(90, 4)).astype(np.float64)
        y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(int)
        Xa, Xb = X.copy(), X.copy()
        a = self._grid(1).fit(Xa, y)
        b = self._grid(4).fit(Xb, y)
        np.testing.assert_allclose(
            a.cv_results_["mean_test_score"],
            b.cv_results_["mean_test_score"],
        )
        # the ORIGINAL arrays must also be untouched: fold slices are
        # copies, never views into the caller's X
        np.testing.assert_array_equal(Xa, X)
        np.testing.assert_array_equal(Xb, X)

    def test_inplace_step_with_prefix_cache_is_safe(self, rng):
        """cache_cv=True shares fitted-prefix OUTPUTS across candidates;
        a later in-place final step mutating the cached transformed
        array would poison siblings.  Concurrent scores must still match
        serial."""
        from sklearn.pipeline import Pipeline
        from sklearn.linear_model import LogisticRegression as SkLR

        class MutatingLR(SkLR):
            def fit(self, X, y, **kw):
                X *= 1.0 + float(self.C)  # in-place, C-dependent
                return super().fit(X, y, **kw)

        def grid(n_jobs):
            return GridSearchCV(
                Pipeline([("mut", MutatingScaler()),
                          ("clf", MutatingLR(max_iter=50))]),
                {"clf__C": [0.01, 1.0, 100.0]},
                cv=2, n_jobs=n_jobs, refit=False, cache_cv=True,
            )

        X = rng.normal(size=(60, 4)).astype(np.float64)
        y = (X[:, 0] > 0).astype(int)
        a = grid(1).fit(X.copy(), y)
        b = grid(4).fit(X.copy(), y)
        np.testing.assert_allclose(
            a.cv_results_["mean_test_score"],
            b.cv_results_["mean_test_score"],
        )

"""graftfleet: replicated serving with a health-aware router
(dask_ml_tpu/serve/fleet.py + router.py, design.md §22).

Covers the PR 19 acceptance criteria: consistent placement (hot
replication, cold rendezvous partitioning under per-replica budgets
with counted spill), readiness-gated routing (a warming replica never
sees traffic), budgeted retry with full-jitter backoff, tail hedging
(first-response-wins with the loser's spend counted), replica death →
budgeted respawn while survivors absorb, brownout shedding by priority
class when the fleet budget is gone (never blackout), rolling deploys
behind the drain barrier with the autopilot held, the per-replica
graftpath verdicts, and the seeded-fault self-test's exit contract
(sighted 0 / blind 1).  The chaos-drill versions of these scenarios
ratchet in resilience/drills.py; this file owns the unit-level policy
checks that need no baseline.
"""

import threading
import time

import numpy as np
import pytest

from dask_ml_tpu.control import pilot as _pilot
from dask_ml_tpu.linear_model import SGDClassifier
from dask_ml_tpu.obs.metrics import registry as _registry
from dask_ml_tpu.resilience.elastic import FaultBudget
from dask_ml_tpu.serve import (
    RequestRejected,
    Router,
    ServeFleet,
    full_jitter_backoff,
    rendezvous,
)
from dask_ml_tpu.serve import config as _cfg


def _fitted_clf(seed=0, d=8, n=512):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    clf = SGDClassifier(random_state=seed)
    clf.partial_fit(X, y, classes=np.arange(2))
    return clf, X


def _mini_fleet(n=2, **kw):
    kw.setdefault("window_s", 0.0)
    kw.setdefault("hedge_ms", 0.0)
    kw.setdefault("budget", FaultBudget(16, 60.0, name="t_fleet"))
    return ServeFleet(replicas=n, label="t_fleet", **kw)


class _FakeRep:
    def __init__(self, index, ready=True, qsize=0):
        self.index = index
        self._ready = ready
        self._qsize = qsize

    def ready(self):
        return self._ready

    def qsize(self):
        return self._qsize


class TestRouterPolicy:
    def test_rendezvous_is_consistent_under_membership_change(self):
        ids = [0, 1, 2, 3]
        ranked = rendezvous("some-model", ids, k=4)
        assert sorted(ranked) == ids
        # removing a NON-chosen replica must not move the model
        loser = ranked[-1]
        assert rendezvous("some-model", [i for i in ids if i != loser],
                          k=1) == ranked[:1]
        # same name, same ids → same answer every time
        assert rendezvous("some-model", ids, k=2) == ranked[:2]

    def test_hot_replicates_cold_partitions(self):
        reps = [_FakeRep(i) for i in range(3)]
        r = Router(reps)
        assert r.place("hot-model", hot=True) == (0, 1, 2)
        cold = r.place("cold-model")
        assert len(cold) == 1
        # idempotent re-place: deploys refresh in place, never migrate
        assert r.place("cold-model") == cold

    def test_cold_placement_respects_budget_and_counts_spill(self):
        reps = [_FakeRep(i) for i in range(2)]
        r = Router(reps, budget_bytes=100)
        spill0 = _registry().counter("fleet.placement_spill").value
        first = r.place("model-a", nbytes=80)
        second = r.place("model-b", nbytes=80)
        # the second cold model cannot share the first's replica budget
        assert first != second
        assert _registry().counter("fleet.placement_spill").value == spill0
        third = r.place("model-c", nbytes=80)  # fits nowhere: spills
        assert len(third) == 1
        assert _registry().counter(
            "fleet.placement_spill").value == spill0 + 1

    def test_candidates_gate_on_readiness_and_partition(self):
        reps = [_FakeRep(0, qsize=5), _FakeRep(1, qsize=1),
                _FakeRep(2, ready=False)]
        r = Router(reps)
        r.place("m", hot=True)
        # warming replica excluded; least-loaded first
        assert [c.index for c in r.candidates("m")] == [1, 0]
        r.partition(0, duration_s=30.0)
        assert [c.index for c in r.candidates("m")] == [1]
        assert r.is_partitioned(0) is True
        r._partition_until[0] = 0.0  # force-expire: heals, re-admits
        assert r.is_partitioned(0) is False
        assert [c.index for c in r.candidates("m")] == [1, 0]

    def test_blind_router_skips_every_gate(self):
        reps = [_FakeRep(0, ready=False, qsize=9), _FakeRep(1)]
        r = Router(reps, blind=True)
        r.place("m", hot=True)
        r.partition(0, duration_s=30.0)
        # raw placement order: no readiness, no partition, no reorder
        assert [c.index for c in r.candidates("m")] == [0, 1]

    def test_full_jitter_backoff_bounds(self):
        import random

        rng = random.Random(7)
        for attempt in range(8):
            cap = min(0.25, 0.01 * 2 ** attempt)
            for _ in range(20):
                d = full_jitter_backoff(attempt, rng=rng)
                assert 0.0 <= d <= cap


class TestFleetServing:
    def test_fleet_predictions_match_direct(self):
        clf, X = _fitted_clf()
        with _mini_fleet(2) as fleet:
            assert fleet.load("m", clf, hot=True) == (0, 1)
            for rows in (1, 3, 16):
                np.testing.assert_array_equal(
                    fleet.predict("m", X[:rows]),
                    np.asarray(clf.predict(X[:rows])))

    def test_unknown_model_and_priorities(self):
        clf, X = _fitted_clf()
        with _mini_fleet(2) as fleet:
            fleet.load("m", clf)
            with pytest.raises(RequestRejected) as ei:
                fleet.submit("nope", X[:1])
            assert ei.value.reason == "unknown_model"
            with pytest.raises(ValueError):
                fleet.submit("m", X[:1], priority="vip")

    def test_replica_death_respawns_within_budget(self):
        clf, X = _fitted_clf()
        reg = _registry()
        respawn0 = reg.counter("fleet.respawn").value
        with _mini_fleet(2, replica_fault_attempts=0) as fleet:
            fleet.load("m", clf, hot=True)
            fleet.predict("m", X[:1])
            victim = fleet._replicas[0]
            victim.server.kill()
            fleet.predict("m", X[:1])  # tick the victim's loop awake
            for _ in range(500):
                if victim.state() == "dead":
                    break
                time.sleep(0.01)
            # survivors absorb while the routing sweep respawns
            for i in range(4):
                np.testing.assert_array_equal(
                    fleet.predict("m", X[i:i + 2], timeout=30.0),
                    np.asarray(clf.predict(X[i:i + 2])))
            assert reg.counter("fleet.respawn").value >= respawn0 + 1
            # the fresh slot warms and re-enters the candidate set
            for _ in range(1000):
                if len(fleet._router.candidates("m")) == 2:
                    break
                time.sleep(0.01)
            assert len(fleet._router.candidates("m")) == 2

    def test_hedge_beats_a_stalled_replica(self):
        clf, X = _fitted_clf()
        reg = _registry()
        won0 = reg.counter("fleet.hedge", "won").value
        launched0 = reg.counter("fleet.hedge", "launched").value
        with _mini_fleet(2, hedge_ms=20.0) as fleet:
            fleet.load("m", clf, hot=True)
            fleet.predict("m", X[:1])  # warm both paths
            slow = fleet._router.candidates("m")[0]
            slow.server._test_dispatch_delay_s = 0.4
            t0 = time.monotonic()
            got = fleet.predict("m", X[:4], timeout=30.0)
            dt = time.monotonic() - t0
            slow.server._test_dispatch_delay_s = 0.0
            np.testing.assert_array_equal(
                got, np.asarray(clf.predict(X[:4])))
            assert reg.counter("fleet.hedge",
                               "launched").value >= launched0 + 1
            assert reg.counter("fleet.hedge", "won").value >= won0 + 1
            assert dt < 0.4, "the hedge answer must beat the straggler"

    def test_brownout_sheds_lowest_class_first_and_clears(self):
        clf, X = _fitted_clf()
        with _mini_fleet(2, replica_fault_attempts=0,
                         budget=FaultBudget(0, 60.0,
                                            name="t_brownout")) as fleet:
            fleet.load("m", clf, hot=True)
            fleet.predict("m", X[:1])
            victim = fleet._replicas[0]
            victim.server.kill()
            fleet.predict("m", X[:1])
            for _ in range(500):
                if victim.state() == "dead":
                    break
                time.sleep(0.01)
            # the respawn attempt hits the exhausted FLEET budget →
            # brownout: low sheds, high keeps serving on the survivor
            np.testing.assert_array_equal(
                fleet.predict("m", X[:2], priority="high"),
                np.asarray(clf.predict(X[:2])))
            assert fleet._shed_level >= 1
            with pytest.raises(RequestRejected) as ei:
                fleet.submit("m", X[:1], priority="low")
            assert ei.value.reason == "brownout"
            assert _registry().family(
                "fleet.rejected").get("brownout", 0) >= 1
            # manual recovery (a fresh slot outside the dead budget):
            # all replicas ready again → the next submit clears shed
            from dask_ml_tpu.serve.fleet import Replica
            # close the corpse first: a replaced-but-unclosed server
            # would leak its dead supervised unit + not-ready probe
            # into the process-global healthz/readyz books
            fleet._replicas[0].server.close(timeout=1.0)
            fleet._replicas[0] = Replica(0, fleet._spawn_server(0))
            fleet._replicas[0].server.load("m", clf)
            fleet._router._replicas[0] = fleet._replicas[0]
            fleet.predict("m", X[:1], priority="high")
            assert fleet._shed_level == 0
            fleet.predict("m", X[:1], priority="low")  # re-admitted

    def test_slo_miss_counted_per_model(self):
        clf, X = _fitted_clf()
        reg = _registry()
        with _mini_fleet(2) as fleet:
            fleet.load("m", clf, hot=True, slo_ms=0.0001)
            miss0 = reg.counter("fleet.slo_miss", "m").value
            fleet.predict("m", X[:4])
            assert reg.counter("fleet.slo_miss", "m").value >= miss0 + 1


class TestRollingDeploy:
    def test_refresh_under_traffic_rejections_confined_to_draining(self):
        clf_a, X = _fitted_clf(seed=0)
        clf_b, _ = _fitted_clf(seed=3)
        twin_a = np.asarray(clf_a.predict(X[:8]))
        twin_b = np.asarray(clf_b.predict(X[:8]))
        reg = _registry()
        reject0 = dict(reg.family("serve.rejected"))
        stop = threading.Event()
        served, holds_seen = [], []

        with _mini_fleet(2, retries=3) as fleet:
            fleet.load("m", clf_a, hot=True)

            def _traffic():
                while not stop.is_set():
                    try:
                        served.append(np.asarray(
                            fleet.predict("m", X[:8], timeout=30.0)))
                    except BaseException as exc:  # noqa: BLE001
                        served.append(exc)
                    if _pilot.active_holds():
                        holds_seen.extend(_pilot.active_holds())

            t = threading.Thread(target=_traffic, name="t_deploy_tfc")
            t.start()
            try:
                out = fleet.rolling_refresh("m", clf_b, timeout=30.0)
            finally:
                stop.set()
                t.join(timeout=30.0)
            assert not t.is_alive()
            assert set(out) == {"r0", "r1"}
            assert all(v["ready"] for v in out.values())
            # the controller was held for the whole walk
            assert "fleet_drain" in holds_seen
            assert not _pilot.active_holds()  # and released after
            # every served answer is EXACTLY old or new — never a blend
            for r in served:
                assert isinstance(r, np.ndarray), r
                assert (np.array_equal(r, twin_a)
                        or np.array_equal(r, twin_b))
            # fleet-level replay confined any rejection to `draining`
            delta = {k: v - reject0.get(k, 0)
                     for k, v in reg.family("serve.rejected").items()
                     if v - reject0.get(k, 0)}
            assert set(delta) <= {"draining"}
            np.testing.assert_array_equal(
                fleet.predict("m", X[:8]), twin_b)

    def test_refresh_unplaced_model_raises(self):
        with _mini_fleet(2) as fleet:
            with pytest.raises(KeyError):
                fleet.rolling_refresh("ghost", object())


class TestWarmupAndObservability:
    def test_warm_from_drives_per_host_shards(self, tmp_path):
        from dask_ml_tpu import data as _data

        clf, X = _fitted_clf(d=4)
        rng = np.random.RandomState(5)
        Xd = rng.normal(size=(512, 4)).astype(np.float32)
        yd = (Xd[:, 0] > 0).astype(np.int32)
        _data.write_dataset(str(tmp_path), Xd, yd, shards=4,
                            block_rows=256)
        with _mini_fleet(2) as fleet:
            fleet.load("m", clf, hot=True)
            warmed = fleet.warm_from(str(tmp_path), rows=16)
            assert warmed.get("r0/m") == 16
            assert warmed.get("r1/m") == 16

    def test_report_aggregates_replica_scrapes(self):
        clf, X = _fitted_clf()
        with _mini_fleet(2) as fleet:
            fleet.load("m", clf, hot=True)
            fleet.predict("m", X[:2])
            rep = fleet.report()
            assert set(rep["replicas"]) == {"r0", "r1"}
            assert all(r["state"] == "ready"
                       for r in rep["replicas"].values())
            assert rep["router"]["placement"] == {"m": [0, 1]}
            assert any(k.startswith("fleet.replica_state")
                       for k in rep["metrics"])
            assert rep["priorities"] == ["low", "normal", "high"]

    def test_per_replica_critical_verdicts(self):
        from dask_ml_tpu.obs.critical import serve_critical

        clf, X = _fitted_clf()
        reg = _registry()
        reg.reset(prefix="serve.req_")
        reg.reset(prefix="serve.request_s")
        with _mini_fleet(2) as fleet:
            fleet.load("m", clf, hot=True)
            for i in range(8):
                fleet.predict("m", X[i:i + 2])
            tagged = [serve_critical(tag=f"r{i}", publish=False)
                      for i in range(2)]
            assert any(v is not None for v in tagged)
            for v in tagged:
                if v is not None:
                    assert v["plane"].startswith("serve:r")
                    assert v["requests"] >= 1
            # an unknown tag is silence, not an invented story
            assert serve_critical(tag="r9", publish=False) is None


class TestSelfTestContract:
    def test_sighted_exits_zero(self, monkeypatch):
        from dask_ml_tpu.serve import fleet as fleet_mod

        monkeypatch.delenv(_cfg.FLEET_INJECT_ENV, raising=False)
        assert fleet_mod.self_test(verbose=False) == 0

    def test_blind_router_exits_one(self, monkeypatch):
        from dask_ml_tpu.serve import fleet as fleet_mod

        monkeypatch.setenv(_cfg.FLEET_INJECT_ENV, "replica-kill")
        assert fleet_mod.self_test(verbose=False) == 1


class TestFleetKnobs:
    def test_strict_parse_rejects_typos(self, monkeypatch):
        monkeypatch.setenv(_cfg.FLEET_REPLICAS_ENV, "two")
        with pytest.raises(ValueError):
            _cfg.resolve_fleet_replicas()
        monkeypatch.delenv(_cfg.FLEET_REPLICAS_ENV)
        monkeypatch.setenv(_cfg.FLEET_INJECT_ENV, "replica-maim")
        with pytest.raises(ValueError):
            _cfg.resolve_fleet_inject()

    def test_priorities_parse_and_validate(self, monkeypatch):
        monkeypatch.setenv(_cfg.FLEET_PRIORITIES_ENV, "bulk, rt")
        assert _cfg.resolve_fleet_priorities() == ("bulk", "rt")
        monkeypatch.setenv(_cfg.FLEET_PRIORITIES_ENV, "a,a")
        with pytest.raises(ValueError):
            _cfg.resolve_fleet_priorities()

    def test_explicit_args_pin_over_env(self, monkeypatch):
        monkeypatch.setenv(_cfg.FLEET_REPLICAS_ENV, "7")
        assert _cfg.resolve_fleet_replicas(3) == 3
        assert _cfg.resolve_fleet_replicas() == 7
        assert _cfg.resolve_hedge_s(250.0) == pytest.approx(0.25)
        assert _cfg.resolve_fleet_retries(0) == 0


class TestRejectClassification:
    """Every rejection reason lands in the flight recorder with an
    explicit retry classification — 'unclassified' is the graftcontract
    drift signal (contract-orphan-producer), never a shipped state."""

    def _last_reject(self):
        from dask_ml_tpu.obs import flight

        evs = [e for e in flight.tail() if e["name"] == "fleet.reject"]
        assert evs, "no fleet.reject flight event recorded"
        return evs[-1]["attrs"]

    def test_retryable_reason_tags_retryable(self):
        from dask_ml_tpu.serve import fleet as fleet_mod

        with _mini_fleet(1) as fleet:
            fleet._count_reject("queue_full", "m")
            assert self._last_reject()["retry"] == "retryable"
            for reason in fleet_mod._RETRYABLE:
                fleet._count_reject(reason, "m")
                assert self._last_reject() == {
                    "model": "m", "reason": reason, "retry": "retryable"}

    def test_terminal_reason_tags_terminal(self):
        from dask_ml_tpu.serve import fleet as fleet_mod

        with _mini_fleet(1) as fleet:
            for reason in fleet_mod._NON_RETRYABLE:
                fleet._count_reject(reason, "m")
                assert self._last_reject() == {
                    "model": "m", "reason": reason, "retry": "terminal"}

    def test_unknown_reason_is_loud_not_defaulted(self):
        # an unrostered reason must scream 'unclassified' in the books
        # (and graftcontract rejects it at lint time before it ships)
        with _mini_fleet(1) as fleet:
            fleet._count_reject("mystery", "m")
            assert self._last_reject()["retry"] == "unclassified"

    def test_real_rejection_carries_classification(self):
        clf, X = _fitted_clf()
        with _mini_fleet(1) as fleet:
            fleet.load("m", clf)
            with pytest.raises(RequestRejected) as ei:
                fleet.predict("nope", X[:1])
            assert ei.value.reason == "unknown_model"
            attrs = self._last_reject()
            assert attrs["reason"] == "unknown_model"
            assert attrs["retry"] == "retryable"

#!/usr/bin/env bash
# Pre-commit check: graftlint (the repo's JAX/SPMD-aware static analyzer)
# plus a bytecode-compile sweep.  Fast (no tests, no jax programs) — run
# it before every commit; tier-1 runs the same gate via
# tests/test_graftlint.py.
#
# Usage: tools/lint.sh [extra graftlint args, e.g. --format json]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftlint =="
JAX_PLATFORMS=cpu python -m dask_ml_tpu.analysis dask_ml_tpu "$@"

echo "== compileall =="
python -m compileall -q dask_ml_tpu
echo "lint OK"

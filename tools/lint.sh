#!/usr/bin/env bash
# Pre-commit check: graftlint (the repo's JAX/SPMD-aware static analyzer)
# plus a bytecode-compile sweep.  Fast (no tests, no jax programs; a warm
# whole-project cache makes the re-run near-free) — run it before every
# commit; tier-1 runs the same gate via tests/test_graftlint.py.
#
# Default run is the RATCHET: compares against the committed baseline
# (tools/graftlint_baseline.json) and fails on NEW findings, on STALE
# baseline entries, and on unused suppressions — exit 1.  Exit 2 means
# the analyzer itself failed (bad args / crash), which must never be
# confused with a clean run.
#
# Usage:
#   tools/lint.sh                 # ratchet gate (text output)
#   tools/lint.sh --json          # same, JSON output (CI trending)
#   tools/lint.sh --rebaseline    # refresh the committed baseline after
#                                 # intentional changes, then re-gate
#   tools/lint.sh [extra graftlint args]   # passed through
set -euo pipefail
cd "$(dirname "$0")/.."

BASELINE=tools/graftlint_baseline.json
MODE=gate
EXTRA=()
for a in "$@"; do
  case "$a" in
    --json) EXTRA+=(--format json) ;;
    --rebaseline) MODE=rebaseline ;;
    *) EXTRA+=("$a") ;;
  esac
done

if [[ "$MODE" == rebaseline ]]; then
  echo "== graftlint (rebaseline) =="
  JAX_PLATFORMS=cpu python -m dask_ml_tpu.analysis dask_ml_tpu \
    --write-baseline "$BASELINE"
fi

echo "== graftlint (ratchet vs $BASELINE) =="
JAX_PLATFORMS=cpu python -m dask_ml_tpu.analysis dask_ml_tpu \
  --baseline "$BASELINE" ${EXTRA[@]+"${EXTRA[@]}"}

echo "== compileall =="
python -m compileall -q dask_ml_tpu
echo "lint OK"
